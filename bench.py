"""Benchmark: HIGGS-class GBDT training throughput on one chip.

Mirrors the reference's headline experiment (docs/Experiments.rst:104-113:
LightGBM CPU trains HIGGS — 11M rows x 28 features, 500 iterations,
num_leaves=255 — in 238.5 s on a 2x E5-2670v3 box; the GPU docs recommend
max_bin=63 for device runs, docs/GPU-Performance.rst:111-127). HIGGS
itself cannot be downloaded here (no egress), so an equally-sized
synthetic binary task with the same shape parameters is used and the
result is normalized to row-iterations/second for comparison against the
published reference wall-clock.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline > 1.0 means faster than the reference's published HIGGS
CPU number (its strongest in-repo headline baseline).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

# reference headline: 11M rows x 500 iters in 238.5 s  (Experiments.rst)
BASELINE_ROWS = 11_000_000
BASELINE_ITERS = 500
BASELINE_SECONDS = 238.5
BASELINE_ROW_ITERS_PER_S = BASELINE_ROWS * BASELINE_ITERS / BASELINE_SECONDS


def make_higgs_like(n_rows: int, n_features: int = 28, seed: int = 7):
    """Synthetic HIGGS-shaped task: 28 continuous features, nonlinear
    decision boundary, balanced classes."""
    r = np.random.default_rng(seed)
    X = r.normal(size=(n_rows, n_features)).astype(np.float32)
    logit = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] - 0.3 * X[:, 3] * X[:, 4]
             + 0.2 * np.abs(X[:, 5]) + 0.1 * X[:, 6])
    y = (logit + 0.5 * r.normal(size=n_rows) > 0).astype(np.float32)
    return X, y


HOLDOUT_ROWS = 500_000


def _lrb_probe_batch(rows: int) -> np.ndarray:
    """A plausible LRB feature batch (inter-arrival gaps, log2 size,
    log2 available bytes, cost) for the live-scoring thread — the
    predictions' values don't matter, the serving path they exercise
    does."""
    from lightgbm_tpu.lrb import HISTFEATURES, NUM_FEATURES
    r = np.random.default_rng(3)
    X = np.zeros((rows, NUM_FEATURES), np.float64)
    X[:, :8] = r.integers(1, 500, size=(rows, 8)).astype(np.float64)
    X[:, HISTFEATURES] = np.round(
        100.0 * np.log2(r.integers(64, 16384, rows)))
    X[:, HISTFEATURES + 1] = round(100.0 * np.log2(1 << 16))
    X[:, HISTFEATURES + 2] = 1.0
    return X


def lrb_stream_bench(args) -> dict:
    """The streaming retrain-while-serve bench (ROADMAP item 3): the
    SAME synthetic multi-window trace through the LRB loop twice in
    one process — sequential then pipelined — at an LRB-realistic
    request RATE, with a scorer thread firing ``predict_live``
    micro-batches against the published model the whole time.

    The feeder paces requests with a minimum inter-arrival gap (a
    bounded-buffer upstream: a retrain stall pushes every later
    arrival out — backpressure, not an infinite burst buffer), with
    the rate auto-calibrated from an untimed warm pass so one window
    of requests spans ~2.5x the window's warm training wall
    (``--lrb-rate`` overrides; 0 = closed-loop, no pacing). Under
    that load the comparison is structural, not scheduling luck: the
    sequential loop stalls the stream for every window's whole
    derive+train+evaluate wall, the pipelined loop absorbs training
    into the stream's idle gaps — so pipelined sustains the offered
    rate and wins end-to-end wall by ~the total training time.

    Reported: end-to-end wall for both modes, sustained trace
    requests/s (N / wall), serve p50/p99 split by whether a trainer
    thread was mid-window when the probe fired (the during-retrain
    tail is the number this workload exists to bound), and the
    model-staleness lag."""
    import io
    import threading
    import time as _time

    from lightgbm_tpu import lrb
    from lightgbm_tpu.obs import registry as obs_registry

    windows = args.lrb_windows
    rows = args.lrb_window_rows
    sample = min(args.lrb_sample, rows)
    iters = args.lrb_iters
    if args.quick:
        windows, rows = min(windows, 6), min(rows, 1024)
        sample, iters = min(sample, 256), min(iters, 8)
    reqs = list(lrb.synthetic_trace(windows * rows,
                                    max(rows // 8, 50)))
    base = {"num_iterations": iters, "verbose": "-1"}
    probe = _lrb_probe_batch(args.lrb_serve_batch)

    # untimed full-trace warm pass: pays the one-off per-geometry
    # step/predict compiles (every window can land in its own shape
    # bucket) so neither timed mode carries a cold tail the other
    # skipped, AND yields the warm per-window training wall the
    # request rate is calibrated from
    warm = lrb.LrbDriver(1 << 16, rows, sample, 0.5, 1,
                         result_file=io.StringIO(),
                         extra_params={**base, "tpu_lrb_pipeline": 0},
                         serve_batch=args.lrb_serve_batch)
    for seq, oid, size, cost in reqs:
        warm.process_request(seq, oid, size, cost)
    warm.predict_live(probe)
    train_walls = [r["train_s"] for r in warm.results
                   if "train_s" in r]
    warm.close()
    rate = args.lrb_rate
    if rate < 0:        # auto: one window of arrivals ~ 2.5x train
        t_win = 2.5 * (np.median(train_walls) if train_walls else 0.5)
        rate = rows / max(t_win, 1e-3)
    # pacing in bursts of 16 keeps sleep syscalls off the per-request
    # path; a stall rebases the clock (bounded buffer: missed arrival
    # slots are lost, not replayed as an instant burst)
    gap16 = 16.0 / rate if rate > 0 else 0.0

    def run(mode):
        drv = lrb.LrbDriver(1 << 16, rows, sample, 0.5, 1,
                            result_file=io.StringIO(),
                            extra_params={**base,
                                          "tpu_lrb_pipeline": mode},
                            serve_batch=args.lrb_serve_batch)
        stop = threading.Event()
        reg = obs_registry.MetricsRegistry()
        hist_d = obs_registry.latency_histogram("serve_during", reg)
        hist_b = obs_registry.latency_histogram("serve_between", reg)

        def score_loop():
            while not stop.is_set():
                in_flight = drv.training_in_flight()
                t0 = _time.monotonic()
                out = drv.predict_live(probe)
                dt = _time.monotonic() - t0
                if out is None:         # no model published yet
                    _time.sleep(0.002)
                    continue
                (hist_d if in_flight else hist_b).observe(dt)
                _time.sleep(0.002)      # a bounded probe rate

        th = threading.Thread(target=score_loop, name="lrb-scorer",
                              daemon=True)
        th.start()
        t0 = _time.monotonic()
        nxt = t0
        for i, (seq, oid, size, cost) in enumerate(reqs):
            if gap16 and i % 16 == 0:
                nxt += gap16
                delay = nxt - _time.monotonic()
                if delay > 0:
                    _time.sleep(delay)
                else:
                    nxt = _time.monotonic()
            drv.process_request(seq, oid, size, cost)
        drv.drain()
        wall = _time.monotonic() - t0
        stop.set()
        th.join(timeout=10)
        res = drv.results
        degraded = drv.degraded_windows()
        drv.close()
        return res, wall, hist_d, hist_b, degraded

    res_s, wall_s, _, _, deg_s = run(0)
    res_p, wall_p, hist_d, hist_b, deg_p = run(1)
    n_s = n_p = len(reqs)

    parity_keys = ("eval_rows", "fp_rate", "fn_rate", "train_rows",
                   "staleness_windows", "degraded", "degrade_reason")
    mismatches = sum(1 for a, b in zip(res_s, res_p)
                     for k in parity_keys if a.get(k) != b.get(k))
    stale = [r.get("staleness_windows", 0) for r in res_p] or [0]

    def q_ms(hist, q):
        v = hist.percentile(q)
        return None if v is None else round(1e3 * v, 3)

    stream = {
        "windows": windows, "window_rows": rows,
        "sample_rows": sample, "iters": iters,
        "offered_requests_per_s": round(rate, 1),
        "wall_sequential_s": round(wall_s, 3),
        "wall_pipelined_s": round(wall_p, 3),
        "speedup": round(wall_s / max(wall_p, 1e-9), 3),
        "requests_per_s": round(n_p / max(wall_p, 1e-9), 1),
        "requests_per_s_sequential": round(n_s / max(wall_s, 1e-9), 1),
        "serve_p50_during_retrain_ms": q_ms(hist_d, 0.5),
        "serve_p99_during_retrain_ms": q_ms(hist_d, 0.99),
        "serve_p50_between_ms": q_ms(hist_b, 0.5),
        "serve_p99_between_ms": q_ms(hist_b, 0.99),
        "requests_during_retrain": hist_d.count,
        "staleness_p99_windows": round(
            float(np.percentile(stale, 99)), 3),
        "overlap_s_total": round(
            sum(r.get("overlap_s", 0.0) for r in res_p), 3),
        "degraded_windows": deg_p,
        "degraded_windows_sequential": deg_s,
        "result_parity_mismatches": mismatches,
    }
    print(f"# lrb-stream: {windows} windows x {rows} rows — wall "
          f"seq {wall_s:.2f}s vs pipe {wall_p:.2f}s "
          f"(speedup {stream['speedup']:.2f}x), "
          f"{stream['requests_per_s']:.0f} requests/s, p99 during "
          f"retrain {stream['serve_p99_during_retrain_ms']} ms "
          f"({hist_d.count} reqs mid-retrain), staleness p99 "
          f"{stream['staleness_p99_windows']} windows",
          file=sys.stderr)
    return stream


FLEET_FEATURES = 16


def _fleet_model_str(rows: int, iters: int) -> str:
    """Train one small binary booster through the capi surface and
    return its model text — the artifact every fleet tenant is
    registered from. Same text, same tree geometry: the predict
    registry compiles ONE program and serves all K tenants off it."""
    from lightgbm_tpu import capi
    rng = np.random.default_rng(17)
    X = rng.normal(size=(rows, FLEET_FEATURES))
    y = (X[:, 0] + 0.5 * X[:, 1]
         + 0.25 * rng.normal(size=rows) > 0).astype(np.float64)
    ds = capi.LGBM_DatasetCreateFromMat(X)
    capi.LGBM_DatasetSetField(ds, "label", y)
    booster = capi.LGBM_BoosterCreate(
        ds, {"objective": "binary", "num_leaves": 31, "verbose": "-1"})
    for _ in range(iters):
        capi.LGBM_BoosterUpdateOneIter(booster)
    return capi.LGBM_BoosterSaveModelToString(booster)


def fleet_bench(args) -> dict:
    """The multi-tenant coalesced-serving bench (serve/): one
    ScoringDaemon, K same-geometry tenants registered from ONE model
    text, scored over real localhost HTTP in two phases —

      sequential   each tenant's requests issued one at a time, one
                   tenant after another: no concurrency, so the
                   coalescer never merges anything (the K-separate-
                   processes fleet this subsystem replaces)
      coalesced    K paced client threads offered ~2x the sequential
                   phase's per-tenant rate (the lrb-stream feeder's
                   burst-paced clock-rebase loop), so requests from
                   different tenants genuinely overlap and the
                   dispatcher drains them as shared device batches

    Reported: aggregate requests/s for both phases, per-tenant client
    p50/p99, the coalesced-batch-rows histogram, the predict-registry
    hit rate across registration + serving (K-1 of K registrations
    reuse the first tenant's compiled program), shed/queue-reject
    counters, and the daemon's admission budget state."""
    import threading
    import time as _time

    from lightgbm_tpu.obs import registry as obs_registry
    from lightgbm_tpu.ops import predict_cache
    from lightgbm_tpu.serve import FleetClient, ScoringDaemon, ShedError
    from lightgbm_tpu.serve import coalescer as serve_coalescer

    tenants = max(args.fleet_tenants, 1)
    reqs = max(args.fleet_requests, 8)
    rows = max(args.fleet_rows, 1)
    streams = max(args.fleet_streams, 1)
    if args.quick:
        reqs = min(reqs, 80)
    names = [f"tenant_{i:02d}" for i in range(tenants)]
    # a deliberately non-trivial forest: per-batch predict dispatch is
    # the cost coalescing amortizes, so a toy model would measure only
    # fixed HTTP overhead (not clamped under --quick for the same
    # reason)
    model_str = _fleet_model_str(rows=2048, iters=args.fleet_iters)
    X = np.random.default_rng(29).normal(size=(rows, FLEET_FEATURES))

    before = predict_cache.stats()
    retries0 = obs_registry.counter("retry/retries").value
    daemon = ScoringDaemon(port=0, coalesce_us=args.fleet_coalesce_us,
                           slo_p99_ms=args.fleet_slo_p99_ms).start()
    try:
        client = FleetClient(daemon.url)
        for t in names:
            client.register(t, model_str, warm_rows=rows)
        # one warm request per tenant over the wire so neither timed
        # phase carries a first-request cost the other skipped
        for t in names:
            client.predict(t, X)

        # phase 1: uncoalesced sequential streams
        t0 = _time.monotonic()
        for t in names:
            for _ in range(reqs):
                client.predict(t, X)
        wall_seq = _time.monotonic() - t0
        seq_rps = tenants * reqs / max(wall_seq, 1e-9)

        # phase 2: K tenants x M concurrent paced streams, offered 2x
        # the sequential per-tenant rate in aggregate — sustained only
        # if coalescing actually buys overlapping requests a shared
        # device batch. M > 1 puts several same-tenant requests in
        # flight at once, so the dispatcher gets real merges (one
        # synchronous stream per tenant would cap every coalesced
        # batch at a single request).
        per_stream = max(reqs // streams, 1)
        per_rate = 2.0 * seq_rps / tenants
        gap8 = 8.0 * streams / per_rate if per_rate > 0 else 0.0
        lat = {t: [] for t in names}
        shed = {t: 0 for t in names}
        errors = []
        agg_hist = obs_registry.latency_histogram(
            "fleet/client_latency_s")

        def stream(t):
            c = FleetClient(daemon.url)
            nxt = _time.monotonic()
            for i in range(per_stream):
                if gap8 and i % 8 == 0:
                    nxt += gap8
                    delay = nxt - _time.monotonic()
                    if delay > 0:
                        _time.sleep(delay)
                    else:
                        nxt = _time.monotonic()
                s = _time.monotonic()
                try:
                    c.predict(t, X)
                except ShedError:
                    shed[t] += 1
                    continue
                except Exception as e:  # noqa: BLE001 — a failed
                    # request is a result (errors gate below), not a
                    # bench abort
                    errors.append(f"{t}: {e}")
                    continue
                dt = _time.monotonic() - s
                lat[t].append(dt)       # list.append: thread-safe
                agg_hist.observe(dt)

        threads = [threading.Thread(target=stream, args=(t,),
                                    name=f"fleet-{t}-{j}", daemon=True)
                   for t in names for j in range(streams)]
        t0 = _time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = _time.monotonic() - t0
        done = sum(len(v) for v in lat.values())
        rps = done / max(wall, 1e-9)

        cache = predict_cache.stats()
        lookups = ((cache["hits"] - before["hits"])
                   + (cache["misses"] - before["misses"]))
        hit_rate = ((cache["hits"] - before["hits"]) / lookups
                    if lookups else None)
        batch_hist = obs_registry.histogram(
            "fleet/coalesced_batch_rows",
            serve_coalescer.ROW_BUCKETS).snapshot()
        stats = daemon.stats()

        def q_ms(vals, q):
            return (round(1e3 * float(np.percentile(vals, q)), 3)
                    if vals else None)

        out = {
            "tenants": tenants,
            "requests_per_tenant": per_stream * streams,
            "rows_per_request": rows, "streams_per_tenant": streams,
            "coalesce_us": args.fleet_coalesce_us,
            "requests_per_s": round(rps, 1),
            "requests_per_s_sequential": round(seq_rps, 1),
            "coalescing_speedup": round(rps / max(seq_rps, 1e-9), 3),
            "offered_per_tenant_requests_per_s": round(per_rate, 1),
            "per_tenant": {
                t: {"requests": len(lat[t]),
                    "p50_ms": q_ms(lat[t], 50),
                    "p99_ms": q_ms(lat[t], 99),
                    "shed": shed[t]}
                for t in names},
            "registry_hit_rate": (round(hit_rate, 4)
                                  if hit_rate is not None else None),
            "registry_lookups": lookups,
            "coalesced_batch_rows": {
                "batches": batch_hist["count"],
                "mean": (round(batch_hist["sum"]
                               / batch_hist["count"], 2)
                         if batch_hist["count"] else None),
                "p50": batch_hist["p50"], "p99": batch_hist["p99"],
                "buckets": batch_hist["buckets"]},
            "shed_total": stats["shed_total"],
            "queue_rejects": stats["queue_rejects"],
            "requests_total": stats["requests_total"],
            "client_retries": (obs_registry.counter(
                "retry/retries").value - retries0),
            "errors": len(errors),
            "slo_admission": daemon.slo_report(),
        }
    finally:
        daemon.stop()
    if errors:
        print(f"# fleet: {len(errors)} failed requests, first: "
              f"{errors[0]}", file=sys.stderr)
    worst = max((v["p99_ms"] or 0.0)
                for v in out["per_tenant"].values())
    print(f"# fleet: {tenants} tenants x {reqs} requests — "
          f"{out['requests_per_s']:.0f} requests/s coalesced vs "
          f"{out['requests_per_s_sequential']:.0f} sequential "
          f"({out['coalescing_speedup']:.2f}x), worst-tenant p99 "
          f"{worst} ms, mean batch "
          f"{out['coalesced_batch_rows']['mean']} rows, registry hit "
          f"rate {out['registry_hit_rate']}, shed {out['shed_total']}",
          file=sys.stderr)
    return out


def make_ctr_sparse(n_rows: int, n_features: int, density: float,
                    seed: int = 11):
    """Synthetic CTR-shaped sparse task: ~density*F active hashed
    features per row with small integer-ish values (one-hot-with-
    counts, the ad-click shape), labels from a sparse linear logit.
    O(nnz) generation — the dense matrix never exists here either."""
    from lightgbm_tpu.io.sparse import SparseMatrix
    rng = np.random.default_rng(seed)
    k = max(1, int(round(n_features * density)))
    rows = np.repeat(np.arange(n_rows, dtype=np.int64), k)
    cols = rng.integers(0, n_features, size=n_rows * k)
    key = rows * n_features + cols
    _, first = np.unique(key, return_index=True)   # drop dup cells
    rows, cols = rows[first], cols[first]
    vals = rng.integers(1, 16, size=len(rows)).astype(np.float64)
    indptr = np.concatenate(
        [[0], np.cumsum(np.bincount(rows, minlength=n_rows))])
    w = rng.normal(size=n_features)
    logits = np.zeros(n_rows)
    np.add.at(logits, rows, w[cols] * np.log1p(vals))
    y = (logits + 0.5 * rng.normal(size=n_rows) > 0).astype(np.float32)
    sm = SparseMatrix(vals, cols.astype(np.int64),
                      indptr.astype(np.int64), (n_rows, n_features))
    return sm, y


def sparse_route_run(args) -> dict:
    """ONE route of the sparse bench, run in its own process so each
    route's ru_maxrss watermark is its own (--sparse-route {dense,csr}):
    the SAME synthetic CSR workload trained through the dense-densified
    path or the CSR-native route, reporting wall, throughput, host peak
    RSS and a tree-section hash (the parent asserts cross-route
    parity)."""
    import hashlib
    import resource

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    sm, y = make_ctr_sparse(args.sparse_rows, args.sparse_features,
                            args.sparse_density)
    t0 = time.time()
    cfg = Config().set({
        "objective": "binary", "max_bin": args.max_bin,
        "num_leaves": min(args.leaves, 63), "min_data_in_leaf": 20,
        "learning_rate": 0.1, "tpu_stop_check_interval": 10_000,
        "tpu_quantized_hist": not args.no_quant,
        "tpu_ingest": 0 if args.no_ingest else -1,
    })
    X = sm.to_dense() if args.sparse_route == "dense" else sm
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, [])
    ingest_s = time.time() - t0
    t0 = time.time()
    for _ in range(args.sparse_iters):
        g.train_one_iter()
    float(np.asarray(g._scores[0, :1])[0])      # drain the queue
    train_s = time.time() - t0
    # model parity across routes: the tree sections only (the
    # parameters: block echoes per-route knobs)
    trees = g.model_to_string().split("\nparameters:\n")[0]
    rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return {
        "route": args.sparse_route,
        "rows": args.sparse_rows, "features": args.sparse_features,
        "nnz": sm.nnz, "density": round(sm.density, 5),
        "iters": args.sparse_iters,
        "ingest_s": round(ingest_s, 3),
        "train_s": round(train_s, 3),
        "rows_per_s": round(
            args.sparse_rows * args.sparse_iters / max(train_s, 1e-9),
            1),
        "peak_rss_mb": round(rss_kb / 1024.0, 1),
        "sparse_hist_tier": bool(g._grower_cfg.sparse_hist),
        "model_sha1": hashlib.sha1(trees.encode()).hexdigest(),
    }


def sparse_bench(args) -> dict:
    """The sparse CTR workload bench (--sparse): the same CSR matrix
    trained dense-densified vs CSR-native, each route in a fresh
    subprocess so 'peak host RSS' is per-route truth (ru_maxrss is a
    process-lifetime high-water mark). Appends both routes + the RSS
    ratio to the JSON line; refuses silently-diverged models."""
    import subprocess

    if args.quick:
        args.sparse_rows = min(args.sparse_rows, 20_000)
        args.sparse_iters = min(args.sparse_iters, 8)
    routes = {}
    for route in ("dense", "csr"):
        cmd = [sys.executable, __file__, "--sparse-route", route,
               "--sparse-rows", str(args.sparse_rows),
               "--sparse-features", str(args.sparse_features),
               "--sparse-density", str(args.sparse_density),
               "--sparse-iters", str(args.sparse_iters),
               "--max-bin", str(args.max_bin),
               "--leaves", str(args.leaves)]
        if args.no_quant:
            cmd.append("--no-quant")
        if args.no_ingest:
            cmd.append("--no-ingest")
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            print(proc.stderr[-2000:], file=sys.stderr)
            raise RuntimeError(f"sparse route {route!r} failed "
                               f"(exit {proc.returncode})")
        routes[route] = json.loads(proc.stdout.strip().splitlines()[-1])
        print(f"# sparse {route}: {routes[route]['rows_per_s']:.0f} "
              f"rows/s, peak RSS {routes[route]['peak_rss_mb']:.0f} MB "
              f"(ingest {routes[route]['ingest_s']:.2f}s, train "
              f"{routes[route]['train_s']:.2f}s)", file=sys.stderr)
    parity = (routes["dense"]["model_sha1"]
              == routes["csr"]["model_sha1"])
    if not parity:
        print("# WARNING: sparse routes trained DIFFERENT models",
              file=sys.stderr)
    out = {
        "rows": args.sparse_rows, "features": args.sparse_features,
        "density": routes["csr"]["density"],
        "nnz": routes["csr"]["nnz"], "iters": args.sparse_iters,
        "routes": {k: {kk: vv for kk, vv in v.items()
                       if kk not in ("rows", "features", "nnz",
                                     "density", "iters")}
                   for k, v in routes.items()},
        "peak_rss_ratio": round(
            routes["dense"]["peak_rss_mb"]
            / max(routes["csr"]["peak_rss_mb"], 1e-9), 3),
        "model_parity": parity,
    }
    print(f"# sparse bench: dense {routes['dense']['peak_rss_mb']:.0f}"
          f" MB vs csr {routes['csr']['peak_rss_mb']:.0f} MB peak RSS "
          f"({out['peak_rss_ratio']:.2f}x), model parity {parity}",
          file=sys.stderr)
    return out


def make_rank_stream(path: str, n_rows: int, n_features: int,
                     qsize: int, seed: int = 13) -> int:
    """Write a synthetic ranking dataset straight to disk in bounded
    blocks (label + features CSV with a .query sidecar of fixed-size
    queries) — the WRITER never holds the full matrix, so the loader
    under test owns the whole RSS story. Graded 0..3 relevance from a
    per-query-shifted linear score (the learning-to-rank shape).
    Returns the written row count (whole queries only)."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=n_features)
    n_rows -= n_rows % qsize
    block = max(65_536 // qsize, 1) * qsize
    with open(path, "w") as fh:
        for r0 in range(0, n_rows, block):
            k = min(block, n_rows - r0)
            X = rng.normal(size=(k, n_features))
            qoff = rng.normal(size=k // qsize).repeat(qsize)
            s = X @ w + qoff + rng.normal(size=k)
            lab = np.clip(np.floor((s - s.mean())
                          / max(float(s.std()), 1e-9) + 2.0), 0, 3)
            np.savetxt(fh, np.column_stack([lab, X]), delimiter=",",
                       fmt="%.6g")
    with open(path + ".query", "w") as fh:
        for _ in range(n_rows // qsize):
            fh.write(f"{qsize}\n")
    return n_rows


def rank_route_run(args) -> dict:
    """ONE route of the ranking bench, run in its own process so each
    route's ru_maxrss watermark is its own (--rank-route
    {memory,ooc}): the SAME on-disk ranking file loaded through the
    in-memory one-round loader or the out-of-core streaming route
    (tpu_out_of_core=1), then lambdarank trained plain AND under
    hashed GOSS, with a same-geometry retrain to surface the
    step-cache hit rate. The parent asserts cross-route model
    parity (OOC is bit-identical by construction)."""
    import hashlib
    import resource

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.loader import DatasetLoader
    from lightgbm_tpu.metrics import create_metrics
    from lightgbm_tpu.models.boosting import create_boosting
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.obs import registry as obs_registry
    from lightgbm_tpu.ops import step_cache

    base = {
        "objective": "lambdarank", "max_bin": args.max_bin,
        "num_leaves": min(args.leaves, 63), "min_data_in_leaf": 20,
        "learning_rate": 0.1, "tpu_stop_check_interval": 10_000,
        "tpu_quantized_hist": not args.no_quant,
        "tpu_ingest": 0 if args.no_ingest else -1,
    }
    if args.rank_route == "ooc":
        base["tpu_out_of_core"] = 1
    cfg = Config().set(base)
    t0 = time.time()
    ds = DatasetLoader(cfg).load_from_file(args.rank_file)
    ingest_s = time.time() - t0

    def fit(goss: bool):
        obj = create_objective("lambdarank", cfg)
        obj.init(ds.metadata, ds.num_data)
        mets = create_metrics(["ndcg"], cfg, ds.metadata,
                              ds.num_data)
        g = create_boosting("goss") if goss else GBDT()
        g.init(cfg, ds, obj, mets)
        t1 = time.time()
        for _ in range(args.rank_iters):
            g.train_one_iter()
        float(np.asarray(g._scores[0, :1])[0])     # drain the queue
        wall = time.time() - t1
        evals = {e[0]: round(float(e[1]), 5)
                 for e in g.get_eval_at(0)}
        trees = g.model_to_string().split("\nparameters:\n")[0]
        return wall, evals, hashlib.sha1(trees.encode()).hexdigest()

    train_s, ndcg, sha = fit(False)
    goss_s, ndcg_goss, _ = fit(True)
    # same-geometry retrains: both objective families must now ride
    # the registry (hit rate 1.0 = the windows-2+ zero-compile story)
    s0 = step_cache.stats()
    fit(False)
    fit(True)
    s1 = step_cache.stats()
    hits = s1["hits"] - s0["hits"]
    misses = s1["misses"] - s0["misses"]
    rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return {
        "route": args.rank_route,
        "rows": ds.num_data,
        "queries": int(ds.metadata.num_queries),
        "iters": args.rank_iters,
        "ingest_s": round(ingest_s, 3),
        "train_s": round(train_s, 3),
        "train_goss_s": round(goss_s, 3),
        "rows_per_s": round(
            ds.num_data * args.rank_iters / max(train_s, 1e-9), 1),
        "ndcg": ndcg,
        "ndcg_goss": ndcg_goss,
        "retrain_step_cache": {
            "hits": hits, "misses": misses,
            "hit_rate": round(hits / max(hits + misses, 1), 3)},
        "ooc_blocks": obs_registry.counter("ooc/blocks").value,
        "peak_rss_mb": round(rss_kb / 1024.0, 1),
        "model_sha1": sha,
    }


def rank_bench(args) -> dict:
    """The ranking workload bench (--rank): one on-disk lambdarank
    dataset loaded in-memory vs out-of-core, each route in a fresh
    subprocess so 'peak host RSS' is per-route truth (the --sparse
    methodology). Reports NDCG (plain + hashed GOSS), rows/s, the
    OOC peak-RSS ratio and the same-geometry retrain step-cache hit
    rate; refuses silently-diverged models (OOC promises BIT parity)."""
    import os
    import subprocess
    import tempfile

    if args.quick:
        args.rank_rows = min(args.rank_rows, 20_000)
        args.rank_iters = min(args.rank_iters, 8)
    routes = {}
    with tempfile.TemporaryDirectory(prefix="rank_bench_") as td:
        path = os.path.join(td, "rank.csv")
        t0 = time.time()
        n = make_rank_stream(path, args.rank_rows, args.rank_features,
                             args.rank_qsize)
        print(f"# rank data: {n} rows ({args.rank_qsize}-row queries) "
              f"written in {time.time()-t0:.1f}s", file=sys.stderr)
        for route in ("memory", "ooc"):
            cmd = [sys.executable, __file__, "--rank-route", route,
                   "--rank-file", path,
                   "--rank-rows", str(n),
                   "--rank-features", str(args.rank_features),
                   "--rank-qsize", str(args.rank_qsize),
                   "--rank-iters", str(args.rank_iters),
                   "--max-bin", str(args.max_bin),
                   "--leaves", str(args.leaves)]
            if args.no_quant:
                cmd.append("--no-quant")
            if args.no_ingest:
                cmd.append("--no-ingest")
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                print(proc.stderr[-2000:], file=sys.stderr)
                raise RuntimeError(f"rank route {route!r} failed "
                                   f"(exit {proc.returncode})")
            routes[route] = json.loads(
                proc.stdout.strip().splitlines()[-1])
            r = routes[route]
            print(f"# rank {route}: {r['rows_per_s']:.0f} rows/s, "
                  f"peak RSS {r['peak_rss_mb']:.0f} MB (ingest "
                  f"{r['ingest_s']:.2f}s, train {r['train_s']:.2f}s, "
                  f"retrain hit rate "
                  f"{r['retrain_step_cache']['hit_rate']:.0%})",
                  file=sys.stderr)
    parity = (routes["memory"]["model_sha1"]
              == routes["ooc"]["model_sha1"])
    if not parity:
        print("# WARNING: rank routes trained DIFFERENT models",
              file=sys.stderr)
    out = {
        "rows": n, "features": args.rank_features,
        "qsize": args.rank_qsize, "iters": args.rank_iters,
        "routes": {k: {kk: vv for kk, vv in v.items()
                       if kk not in ("rows", "iters")}
                   for k, v in routes.items()},
        "peak_rss_ratio": round(
            routes["memory"]["peak_rss_mb"]
            / max(routes["ooc"]["peak_rss_mb"], 1e-9), 3),
        "step_cache_hit_rate":
            routes["ooc"]["retrain_step_cache"]["hit_rate"],
        "model_parity": parity,
    }
    print(f"# rank bench: memory "
          f"{routes['memory']['peak_rss_mb']:.0f} MB vs ooc "
          f"{routes['ooc']['peak_rss_mb']:.0f} MB peak RSS "
          f"({out['peak_rss_ratio']:.2f}x), model parity {parity}",
          file=sys.stderr)
    return out


# default SLO specs per bench mode (obs/slo.py grammar): generous
# ceilings — the section exists to put budget/burn/p99.9 numbers in
# the artifact (gated for SHAPE by tools/check_bench_regression.py),
# not to fail a shared-host run on scheduling noise. --slo overrides.
DEFAULT_SLO_TRAIN = "predict_p99_ms<5000;degraded_window_rate<0.5"
DEFAULT_SLO_STREAM = ("serve_p99_ms<5000;staleness_windows<=8;"
                      "degraded_window_rate<0.5")
# fleet bench: client-observed wire latency (generic hist form,
# threshold in seconds) + a ceiling on how much of the offered load
# admission control may shed before the artifact flags itself
DEFAULT_SLO_FLEET = ("hist:fleet/client_latency_s:p99 < 5;"
                     "ratio:fleet/shed_total|fleet/requests_total"
                     " <= 0.5")


def slo_section(spec: str) -> dict:
    """Evaluate ``spec`` against the run's live registry state and
    return the bench JSON's ``slo`` section: overall compliance,
    remaining error budget, burn rate, the p99.9 serving tails
    (obs/registry.py quantiles now reach past p99), and one compact
    row per objective. Installed as the process-global engine so a
    live exporter's /slo endpoint reports the same budgets."""
    from lightgbm_tpu.obs import registry as obs_registry
    from lightgbm_tpu.obs import slo as obs_slo
    # one idempotence rule: a running engine with the same spec text
    # keeps its burn/latch state, anything else is replaced
    # (obs/slo.py ensure_from_config)
    eng = obs_slo.ensure_from_config({"tpu_slo": spec})
    rep = eng.report(fresh=True)

    def p999_ms(name):
        # bounded-cardinality: called with two literal names
        v = obs_registry.latency_histogram(name).percentile(0.999)
        return None if v is None else round(1e3 * v, 3)

    return {
        "spec": spec,
        "ok": rep.get("ok"),
        "violating": rep.get("violating", 0),
        "budget_remaining_min": rep.get("budget_remaining_min"),
        "burn_rate_max": rep.get("burn_rate_max"),
        "predict_p999_ms": p999_ms("predict/latency_s"),
        "serve_p999_ms": p999_ms("lrb/serve_latency_s"),
        "objectives": [
            {k: r[k] for k in ("name", "ok", "current", "threshold",
                               "budget_remaining", "burn_rate")}
            for r in rep.get("specs", [])],
    }


def _auc(y, s):
    """Holdout AUC through the engine's own metric implementation."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.metrics import create_metrics
    (m,) = create_metrics(["auc"], Config(), Metadata(label=y), len(y))
    return float(m.eval(np.asarray(s, np.float64), None)[0][1])


# ---------------------------------------------------------------------------
# Reference-parity harness (bench.py --parity)
# ---------------------------------------------------------------------------

# the reference's own GPU-vs-CPU quality bar: test AUC within ~4e-4 of
# the CPU engine at 63 bins (docs/GPU-Performance.rst) — the ceiling
# the measured-parity gate asserts when reference LightGBM is present
PARITY_AUC_TOL = 4e-4


def _metric_tag() -> str:
    """Device-kind suffix every headline metric string carries.
    tools/check_bench_regression.py compares runs by metric-string
    equality, so the stamp makes a CPU number structurally incomparable
    with a GPU or TPU trajectory — the checker refuses instead of
    ratioing across backends."""
    from lightgbm_tpu.ops import autotune
    return f" [{autotune.device_kind()}]"


def _import_reference_lightgbm():
    """The reference engine, if this host can import it: the
    ``lightgbm`` PyPI package, else the fork's python-package under
    /root/reference. Returns (module, skip_reason) — exactly one is
    None. The skip reason records the device kind and every import
    path attempted, so a parity skip in a cross-backend sweep log is
    self-explaining."""
    attempted = ["lightgbm (sys.path)"]
    try:
        import lightgbm as ref
        return ref, None
    except ImportError as e:
        first = str(e)
    from lightgbm_tpu.ops import autotune
    dk = autotune.device_kind()
    ref_pkg = "/root/reference/python-package"
    if os.path.isdir(ref_pkg):
        attempted.append(ref_pkg)
        sys.path.insert(0, ref_pkg)
        try:
            import lightgbm as ref
            return ref, None
        except Exception as e:  # noqa: BLE001 — a fork without a built
            # lib_lightgbm.so raises OSError from its loader
            return None, (f"reference fork at {ref_pkg} not importable:"
                          f" {e} [device_kind={dk}; attempted: "
                          f"{', '.join(attempted)}]")
        finally:
            sys.path.remove(ref_pkg)
    attempted.append(f"{ref_pkg} (absent)")
    return (None,
            f"lightgbm not importable ({first}) and no fork at "
            f"{ref_pkg} [device_kind={dk}; attempted: "
            f"{', '.join(attempted)}]")


def _train_reference(args, X, y, X_test, y_test):
    """Train reference LightGBM CPU on the SAME synthetic data and
    measure {wall, auc}. Returns (stats dict, None) or
    (None, skip_reason)."""
    ref, reason = _import_reference_lightgbm()
    if ref is None:
        return None, reason
    params = {
        "objective": "binary", "metric": "auc",
        "num_leaves": args.leaves, "max_bin": args.max_bin,
        "learning_rate": 0.1, "min_data_in_leaf": 20,
        "verbose": -1,
    }
    # hand float32 over as-is — the reference bins float32 natively,
    # and a float64 copy of the 11M-row matrix would add ~2.5 GB of
    # peak RSS to a process already holding the engine's state
    t0 = time.time()
    dtrain = ref.Dataset(X, label=y)
    booster = ref.train(params, dtrain, num_boost_round=args.iters)
    wall = time.time() - t0
    pred = booster.predict(X_test, raw_score=True)
    return {
        # end-to-end wall: the reference's Dataset is lazy, so binning
        # happens inside train() — this wall covers bin + train, the
        # same span the engine tiers' wall_s covers (dataset construct
        # + all iterations incl. compile); vs_measured compares the
        # two LIKE walls, never a steady-state rate against an
        # all-inclusive one
        "ref_wall_s": round(wall, 2),
        "row_iters_per_s": round(args.rows * args.iters / max(wall, 1e-9)
                                 / 1e6, 4),
        "auc_ref": round(_auc(y_test, pred), 6),
        "version": getattr(ref, "__version__", "unknown"),
    }, None


def _train_tpu_tier(args, X, y, X_test, y_test, tier: str) -> dict:
    """Train ONE tier of this engine on the same data and measure
    {tpu_wall, steady row-iters/s, holdout AUC}. ``tier``: "exact" =
    the f32-grade hi/lo histogram path (autotuned variant), "proxy" =
    int8 quantization + count-proxy (the headline tier)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    cfg = Config().set({
        "objective": "binary", "metric": "auc",
        "num_leaves": args.leaves, "max_bin": args.max_bin,
        "learning_rate": 0.1, "min_data_in_leaf": 20,
        "tpu_stop_check_interval": 10_000,
        "tpu_quantized_hist": tier == "proxy",
        "tpu_ingest": 0 if args.no_ingest else -1,
    })
    # wall_s spans dataset construction through the last iteration's
    # readback — the SAME span the reference's lazy Dataset + train()
    # wall covers, so vs_measured is a like-for-like wall ratio
    t_all = time.time()
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, [])

    def sync():
        return float(np.asarray(g._scores[0, :1])[0])

    t1 = time.time()
    g.train_one_iter()
    sync()
    compile_s = time.time() - t1
    t0 = time.time()
    for _ in range(args.iters - 1):
        g.train_one_iter()
    sync()
    train_s = time.time() - t0
    wall = time.time() - t_all
    parts = []
    for r0 in range(0, len(X_test), 20_000):
        parts.append(np.asarray(g.predict_raw(X_test[r0:r0 + 20_000])))
    auc = _auc(y_test, np.concatenate(parts))
    out = {
        "wall_s": round(wall, 2),
        "compile_s": round(compile_s, 2),
        "train_s": round(train_s, 2),
        # steady-state rate (post-compile iterations): the regression
        # tool's exact-tier floor gates THIS; vs_measured uses wall_s
        "row_iters_per_s": round(
            args.rows * (args.iters - 1) / max(train_s, 1e-9) / 1e6, 4),
        "auc_tpu": round(auc, 6),
    }
    if tier == "exact":
        out["exact_variant"] = g._grower_cfg.exact_variant
        out["wave_size"] = g._grower_cfg.wave_size
    return out


def parity_bench(args, data=None) -> dict:
    """The measured reference-parity harness (--parity): BOTH of this
    engine's tiers (exact hi/lo and int8 count-proxy) AND reference
    LightGBM CPU trained on the SAME synthetic HIGGS-shaped data,
    recording {auc_ref, auc_tpu, ref_wall, tpu_wall} so the perf
    ledger's ``vs_measured`` stands on a measured run instead of the
    published number — and asserting the reference's own quality bar
    (|auc_ref - auc_tpu| <= 4e-4 at 63 bins, GPU-Performance.rst).
    When reference LightGBM cannot be imported the ref fields are null
    and ``skip_reason`` records why — a recorded skip, not a silent
    pass. ``data`` reuses the standard bench's already-generated
    (X, y, X_test, y_test)."""
    from lightgbm_tpu.ops import autotune

    if data is not None:
        X, y, X_test, y_test = data
    else:
        X, y = make_higgs_like(args.rows + HOLDOUT_ROWS)
        X_test, y_test = X[args.rows:], y[args.rows:]
        X, y = X[:args.rows], y[:args.rows]

    tiers = {}
    for tier in ("exact", "proxy"):
        tiers[tier] = _train_tpu_tier(args, X, y, X_test, y_test, tier)
        print(f"# parity {tier}: {tiers[tier]['train_s']:.1f}s train, "
              f"{tiers[tier]['row_iters_per_s']:.3f} M row-iters/s, "
              f"AUC {tiers[tier]['auc_tpu']:.5f}", file=sys.stderr)
    ref, skip = _train_reference(args, X, y, X_test, y_test)
    if ref is not None:
        print(f"# parity ref: {ref['ref_wall_s']:.1f}s wall, AUC "
              f"{ref['auc_ref']:.5f}", file=sys.stderr)
    else:
        print(f"# parity ref: SKIPPED — {skip}", file=sys.stderr)

    ok = True
    for tier, t in tiers.items():
        if ref is not None:
            t["ref_wall_s"] = ref["ref_wall_s"]
            t["auc_ref"] = ref["auc_ref"]
            t["auc_delta"] = round(abs(t["auc_tpu"] - ref["auc_ref"]), 6)
            # like-for-like wall ratio: BOTH walls span dataset
            # construction through the last trained iteration (the
            # reference's Dataset is lazy — its wall includes binning)
            t["vs_measured"] = round(
                ref["ref_wall_s"] / max(t["wall_s"], 1e-9), 3)
            if t["auc_delta"] > args.parity_auc_tol:
                ok = False
        else:
            t["ref_wall_s"] = t["auc_ref"] = None
            t["auc_delta"] = t["vs_measured"] = None
    return {
        "rows": args.rows, "iters": args.iters, "leaves": args.leaves,
        "max_bin": args.max_bin,
        "device_kind": autotune.device_kind(),
        "ref_available": ref is not None,
        "skip_reason": skip,
        "ref": ref,
        "auc_tol": args.parity_auc_tol,
        "tiers": tiers,
        "ok": ok,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=11_000_000)
    ap.add_argument("--iters", type=int, default=500)
    ap.add_argument("--leaves", type=int, default=255)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--quick", action="store_true",
                    help="tiny smoke run (64k rows, 20 iters)")
    ap.add_argument("--no-quant", action="store_true",
                    help="disable int8 histogram quantization "
                         "(f32-grade hi/lo accumulation instead)")
    ap.add_argument("--no-ingest", action="store_true",
                    help="disable the streamed device ingest path "
                         "(host binner + bulk upload instead)")
    ap.add_argument("--learner", default="serial",
                    choices=["serial", "data", "voting"],
                    help="tree learner: 'data' shards rows over every "
                         "visible chip and psums wave histograms over "
                         "ICI — the multi-chip path for the v5e-8 "
                         "north-star target (falls back to serial on "
                         "one device)")
    ap.add_argument("--retrain", type=int, default=0, metavar="K",
                    help="after the timed run, train K fresh boosters "
                         "back-to-back on the same data (the lrb.py "
                         "sliding-window pattern) and report warm vs "
                         "cold compile time + step-cache hit rate in "
                         "the JSON output")
    ap.add_argument("--serve", action="store_true",
                    help="after training, run the serving-latency "
                         "bench: p50/p95/p99 per-request latency and "
                         "sustained rows/s at 1/64/4096-row batches "
                         "through the geometry-keyed predict registry "
                         "(ops/predict_cache.py), reported under "
                         "'serve' in the JSON line")
    ap.add_argument("--serve-seconds", type=float, default=2.0,
                    help="measurement budget per serve batch size "
                         "(default 2.0s, after 2 warmup requests)")
    ap.add_argument("--run-report", default="",
                    help="write the run-report artifact here "
                         "(tpu_run_report; .jsonl for line-delimited). "
                         "The JSON line's phase breakdown comes from "
                         "this report's phase table either way.")
    ap.add_argument("--lrb-stream", action="store_true",
                    help="run ONLY the streaming retrain-while-serve "
                         "bench (lrb.py pipelined vs sequential on a "
                         "synthetic multi-window trace, with a live "
                         "scorer thread) and emit its JSON line — "
                         "unit requests/s, details under 'lrb_stream'")
    ap.add_argument("--no-lrb-stream", action="store_true",
                    help="skip the compact lrb-stream section the "
                         "standard bench appends to its JSON/report")
    ap.add_argument("--lrb-windows", type=int, default=8)
    ap.add_argument("--lrb-window-rows", type=int, default=4096)
    ap.add_argument("--lrb-sample", type=int, default=512)
    ap.add_argument("--lrb-iters", type=int, default=10)
    ap.add_argument("--lrb-serve-batch", type=int, default=32)
    ap.add_argument("--fleet", action="store_true",
                    help="run ONLY the multi-tenant coalesced-serving "
                         "bench (serve/): one scoring daemon, K "
                         "same-geometry tenants over localhost HTTP, "
                         "sequential uncoalesced streams vs K paced "
                         "concurrent streams; emits a standalone JSON "
                         "line (unit requests/s, details under "
                         "'fleet')")
    ap.add_argument("--fleet-tenants", type=int, default=4)
    ap.add_argument("--fleet-requests", type=int, default=300,
                    help="requests per tenant per phase (default 300;"
                         " --quick clamps to 80)")
    ap.add_argument("--fleet-rows", type=int, default=4,
                    help="rows per request (default 4 — the "
                         "small-batch shape coalescing exists for)")
    ap.add_argument("--fleet-iters", type=int, default=150,
                    help="boosting rounds for the shared fleet model "
                         "(default 150 — big enough that per-batch "
                         "predict dispatch, the cost coalescing "
                         "amortizes, dominates fixed HTTP overhead)")
    ap.add_argument("--fleet-streams", type=int, default=2,
                    help="concurrent client streams per tenant in the "
                         "coalesced phase (default 2: several "
                         "same-tenant requests in flight is what "
                         "makes per-tick merging visible)")
    ap.add_argument("--fleet-coalesce-us", type=int, default=2000,
                    help="coalescer max-wait (tpu_fleet_coalesce_us)")
    ap.add_argument("--fleet-slo-p99-ms", type=float, default=250.0,
                    help="per-tenant p99 admission threshold for the "
                         "bench daemon (tpu_fleet_slo_p99_ms); 0 "
                         "disables shedding")
    ap.add_argument("--slo", default="",
                    help="SLO spec string (obs/slo.py grammar) for the "
                         "JSON line's 'slo' section — budget remaining, "
                         "burn rate, p99.9 tails; default: a generous "
                         "built-in set per bench mode")
    ap.add_argument("--lrb-rate", type=float, default=-1.0,
                    help="offered request rate (requests/s) for the "
                         "lrb-stream feeder; -1 = auto-calibrate so "
                         "one window of arrivals spans ~2.5x the warm "
                         "training wall; 0 = closed loop (no pacing)")
    ap.add_argument("--sparse", action="store_true",
                    help="run ONLY the sparse CTR workload bench: the "
                         "same synthetic CSR matrix trained "
                         "dense-densified vs CSR-native (io/sparse.py)"
                         ", each route in its own subprocess so host "
                         "peak RSS is per-route; emits a standalone "
                         "JSON line (unit rows/s, details under "
                         "'sparse')")
    ap.add_argument("--sparse-route", default="",
                    choices=["", "dense", "csr"],
                    help="(internal) run ONE sparse-bench route in "
                         "this process and print its JSON")
    ap.add_argument("--sparse-rows", type=int, default=200_000)
    ap.add_argument("--sparse-features", type=int, default=256)
    ap.add_argument("--sparse-density", type=float, default=0.01,
                    help="fraction of explicit cells in the synthetic "
                         "CTR workload (default ~1%%)")
    ap.add_argument("--sparse-iters", type=int, default=30)
    ap.add_argument("--rank", action="store_true",
                    help="run ONLY the ranking workload bench: one "
                         "on-disk lambdarank dataset loaded in-memory "
                         "vs out-of-core (tpu_out_of_core=1), each "
                         "route in its own subprocess for a clean "
                         "peak-RSS watermark; NDCG (plain + hashed "
                         "GOSS), rows/s, OOC RSS ratio and the "
                         "same-geometry retrain step-cache hit rate "
                         "(JSON details under 'rank')")
    ap.add_argument("--rank-route", default="",
                    choices=["", "memory", "ooc"],
                    help="(internal) run ONE rank-bench route in this "
                         "process and print its JSON")
    ap.add_argument("--rank-file", default="",
                    help="(internal) pre-written ranking CSV for "
                         "--rank-route")
    ap.add_argument("--rank-rows", type=int, default=200_000)
    ap.add_argument("--rank-features", type=int, default=16)
    ap.add_argument("--rank-qsize", type=int, default=50,
                    help="rows per synthetic query (default 50)")
    ap.add_argument("--rank-iters", type=int, default=30)
    ap.add_argument("--parity", action="store_true",
                    help="append the measured reference-parity "
                         "harness to the standard bench: train BOTH "
                         "tiers of this engine (exact hi/lo and int8 "
                         "count-proxy) and reference LightGBM CPU on "
                         "the same synthetic data, record {auc_ref, "
                         "auc_tpu, ref_wall, tpu_wall} per tier under "
                         "'parity' in the JSON line (plus a "
                         "'vs_measured' sibling of vs_baseline), and "
                         "assert |auc_ref - auc_tpu| <= "
                         "--parity-auc-tol (exit 1 on a miss, after "
                         "the JSON is emitted); a missing reference "
                         "records a skip reason instead")
    ap.add_argument("--parity-auc-tol", type=float,
                    default=PARITY_AUC_TOL,
                    help="measured AUC-parity ceiling vs reference "
                         "LightGBM (default 4e-4, the reference's own "
                         "GPU-vs-CPU bar at 63 bins)")
    args = ap.parse_args()
    if args.slo:
        # refuse a malformed spec NOW, not after an hours-long run
        # when slo_section() would crash before the JSON line is
        # emitted (the config.py tpu_slo validation rule)
        from lightgbm_tpu.obs.slo import parse_specs
        try:
            parse_specs(args.slo)
        except ValueError as e:
            ap.error(str(e))
    if args.quick:
        args.rows, args.iters, args.leaves = 65_536, 20, 63

    if args.sparse_route:
        print(json.dumps(sparse_route_run(args)))
        return

    if args.rank_route:
        print(json.dumps(rank_route_run(args)))
        return

    if args.rank:
        rank = rank_bench(args)
        print(json.dumps({
            "rank": rank,
            "metric": (f"lambdarank ranking training "
                       f"({rank['rows']} rows x "
                       f"{rank['features']} feat, "
                       f"{rank['qsize']}-row queries, "
                       f"{rank['iters']} iters, out-of-core)"
                       + _metric_tag()),
            "value": rank["routes"]["ooc"]["rows_per_s"],
            "unit": "rows/s",
        }))
        return

    if args.sparse:
        sparse = sparse_bench(args)
        print(json.dumps({
            "sparse": sparse,
            "metric": (f"sparse CTR GBDT training "
                       f"({sparse['rows']} rows x "
                       f"{sparse['features']} feat, density "
                       f"{sparse['density']:g}, "
                       f"{sparse['iters']} iters)" + _metric_tag()),
            "value": sparse["routes"]["csr"]["rows_per_s"],
            "unit": "rows/s",
        }))
        return

    if args.fleet:
        from lightgbm_tpu.ops import autotune as _autotune
        _autotune.ensure_compile_cache()
        fleet = fleet_bench(args)
        print(json.dumps({
            "fleet": fleet,
            "slo": slo_section(args.slo or DEFAULT_SLO_FLEET),
            "metric": ("fleet coalesced serving "
                       f"({fleet['tenants']} tenants x "
                       f"{fleet['requests_per_tenant']} requests, "
                       f"{fleet['rows_per_request']}-row requests)"
                       + _metric_tag()),
            "value": fleet["requests_per_s"],
            "unit": "requests/s",
        }))
        return

    if args.lrb_stream:
        from lightgbm_tpu.ops import autotune as _autotune
        _autotune.ensure_compile_cache()
        stream = lrb_stream_bench(args)
        print(json.dumps({
            "lrb_stream": stream,
            "slo": slo_section(args.slo or DEFAULT_SLO_STREAM),
            "metric": ("LRB streaming retrain-while-serve "
                       f"({stream['windows']} windows x "
                       f"{stream['window_rows']} rows, sample "
                       f"{stream['sample_rows']}, "
                       f"{stream['iters']} iters)" + _metric_tag()),
            "value": stream["requests_per_s"],
            "unit": "requests/s",
        }))
        return

    # persistent compile cache: the grower/predict kernels compile once
    # per machine instead of once per process (~30-60 s saved per run);
    # shares the autotuner's cache-dir scheme (ops/autotune.py)
    from lightgbm_tpu.ops import autotune
    autotune.ensure_compile_cache()

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import TpuDataset, Metadata
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.metrics import create_metrics

    # run recorder (obs/recorder.py): per-iteration wall times, HBM and
    # transfer-byte samples; the phase table it snapshots at finish()
    # IS the JSON line's phase breakdown (no hand-rolled sub-phase
    # bookkeeping here)
    from lightgbm_tpu.obs.recorder import RunRecorder
    from lightgbm_tpu.utils import timing
    recorder = RunRecorder(
        path=args.run_report,
        meta={"driver": "bench", "rows": args.rows, "iters": args.iters,
              "leaves": args.leaves, "max_bin": args.max_bin,
              "learner": args.learner,
              "quantized": not args.no_quant,
              "ingest": "host" if args.no_ingest else "auto"}).start()

    t0 = time.time()
    # +holdout: the reference's headline quality number is TEST-set AUC
    # (docs/Experiments.rst:125-127); the timed training uses args.rows
    X, y = make_higgs_like(args.rows + HOLDOUT_ROWS)
    X_test, y_test = X[args.rows:], y[args.rows:]
    X, y = X[:args.rows], y[:args.rows]
    timing.add("bench/datagen", time.time() - t0)
    print(f"# data gen: {time.time()-t0:.1f}s", file=sys.stderr)

    cfg = Config().set({
        "objective": "binary", "metric": "auc",
        "num_leaves": args.leaves, "max_bin": args.max_bin,
        "learning_rate": 0.1, "min_data_in_leaf": 20,
        # run every iteration on device; no periodic host sync inside
        "tpu_stop_check_interval": 10_000,
        # int8 gradient quantization: exact int32 histogram sums of
        # stochastically-rounded int8 g/h at 2x MXU rate (the train-AUC
        # printed below shows quality parity with the f32 path)
        "tpu_quantized_hist": not args.no_quant,
        "tree_learner": args.learner,
        # streamed device ingest (io/ingest.py): -1 auto-enables on a
        # real TPU; --no-ingest pins the host binner for A/B runs
        "tpu_ingest": 0 if args.no_ingest else -1,
        "tpu_run_report": args.run_report,
    })
    t0 = time.time()
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    mets = create_metrics(["auc"], cfg, ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, mets)      # kernel autotuning happens here
    binning_init_s = time.time() - t0
    tune_s = timing.seconds("autotune")
    # ingest sub-phases (timing.phase accumulators, device-synced at
    # phase exit), reported DISJOINT: find_bins = sampled boundary
    # search; device_xfer = host->device transfer issue (chunked
    # device_put on the streamed path — nested inside the bin_matrix
    # phase, so it is subtracted back out — plus the bulk [F, N]
    # upload on the host path); bin_matrix = the value->bin mapping
    # itself (device kernel time on the streamed path)
    find_bins_s = timing.seconds("binning/find_bins")
    ingest_xfer_s = timing.seconds("binning/device_xfer")
    bin_matrix_s = max(
        timing.seconds("binning/bin_matrix") - ingest_xfer_s, 0.0)
    device_xfer_s = ingest_xfer_s + timing.seconds("init/upload_bins")
    print(f"# binning+init: {binning_init_s:.1f}s "
          f"(find_bins {find_bins_s:.1f}s, bin_matrix {bin_matrix_s:.1f}s, "
          f"device_xfer {device_xfer_s:.1f}s, "
          f"kernel autotune: {tune_s:.1f}s)", file=sys.stderr)

    import numpy as _np

    def sync():
        # force completion with a real device->host readback:
        # block_until_ready has been observed to return early on the
        # tunneled backend, which would stop the clock with hundreds of
        # iterations still queued
        return float(_np.asarray(g._scores[0, :1])[0])

    # one warm-up iteration compiles the grower (a warm persistent
    # compile cache + tuning cache make this step mostly iter0)
    t0 = time.time()
    with recorder.iteration(1):
        g.train_one_iter()
        sync()
    compile_s = time.time() - t0
    timing.add("bench/compile_iter0", compile_s)
    print(f"# compile+iter0: {compile_s:.1f}s", file=sys.stderr)

    t0 = time.time()
    for i in range(args.iters - 1):
        # per-iteration spans are dispatch-issue time (jax async); the
        # sync below attributes queued device time to the run total
        with recorder.iteration(i + 2):
            g.train_one_iter()
    sync()
    train_s = time.time() - t0
    timing.add("bench/train", train_s)
    (_, auc, _), = g.get_eval_at(0)
    # holdout predict in serving-shaped batches: each batch's wall
    # (dispatch + device->host materialize) feeds the log-bucketed
    # predict/latency_s instrument (obs/registry.py latency_histogram),
    # so the JSON line reports p50/p95/p99 — the measurement bed the
    # bench --serve path will stand on. The first batch carries the
    # forest kernel's tune+compile (the cold-start tail, reported as
    # max/p99, not hidden).
    from lightgbm_tpu.obs import registry as obs_registry
    # divides HOLDOUT_ROWS exactly: every batch is one jit shape, so
    # the cold compile really is only in batch 1 (a ragged tail batch
    # would pay a second compile and fake a latency outlier)
    pred_batch = 20_000
    lat = obs_registry.latency_histogram("predict/latency_s")
    t0 = time.time()
    parts = []
    for r0 in range(0, len(X_test), pred_batch):
        tb = time.time()
        parts.append(np.asarray(g.predict_raw(X_test[r0:r0 + pred_batch])))
        lat.observe(time.time() - tb)
    test_raw = np.concatenate(parts)
    test_auc = _auc(y_test, test_raw)
    pred_s = time.time() - t0
    timing.add("bench/predict_holdout", pred_s)
    lat_q = lat.quantiles()
    print(f"# {args.iters} iters in {train_s:.1f}s  train-AUC={auc:.5f}  "
          f"test-AUC={test_auc:.5f}  "
          f"(holdout predict {HOLDOUT_ROWS} rows x "
          f"{len(g.records) or len(g.models)} trees: {pred_s:.1f}s; "
          f"{pred_batch}-row batch latency "
          + " ".join(f"{k}={1e3 * v:.1f}ms" for k, v in lat_q.items()
                     if v is not None)
          + ")", file=sys.stderr)

    # phase breakdown: the tuning win (tune ~0 on a warm tuning cache)
    # and the compile-cache win (compile+iter0 collapses to iter0 on a
    # warm XLA cache) are both visible here. Re-read the accumulator:
    # the forest kernel tunes during the first predict, after the
    # init-time snapshot above.
    tune_s = timing.seconds("autotune")
    print(f"# phase breakdown: tune={tune_s:.1f}s "
          f"compile+iter0={compile_s:.1f}s train={train_s:.1f}s",
          file=sys.stderr)

    row_iters_per_s = args.rows * (args.iters - 1) / max(train_s, 1e-9)
    # the run report's phase table IS the emitted breakdown: every
    # timing.phase the run touched (binning/find_bins, binning/
    # bin_matrix, binning/device_xfer, init/upload_bins, autotune/*,
    # train/step_dispatch, ...) plus the bench/* spans added above —
    # no hand-maintained sub-phase arithmetic to drift
    # per-iteration psum payloads (data learner): the same accounting
    # gbdt.train records into its run report, through the same public
    # helpers (one stacked leaf download drives both)
    leaves, waves = (g.leaves_and_waves() if g.num_devices > 1
                     else ([], []))
    comm = g.record_comm_bytes(recorder, waves) if waves else None
    # None (JSON null) when accounting is unavailable (serial/voting):
    # a literal 0 would read as "zero cross-chip bytes"
    comm_per_iter = round(float(np.mean(comm))) if comm else None

    # --retrain K: the lrb.py per-window pattern — K FRESH boosters on
    # the same data. With the compiled-step registry warm from the run
    # above, each retrain's first step should dispatch in ~0s (a cache
    # hit) instead of re-paying the cold compile.
    from lightgbm_tpu.ops import step_cache
    retrain = None
    if args.retrain > 0:
        warm_first = []
        s0 = step_cache.stats()
        t_retrain = time.time()
        for r in range(args.retrain):
            gr_ = GBDT()
            gr_.init(cfg, ds, obj, mets)
            t0 = time.time()
            gr_.train_one_iter()
            sync_r = float(_np.asarray(gr_._scores[0, :1])[0])  # noqa: F841
            warm_first.append(time.time() - t0)
            for _ in range(4):
                gr_.train_one_iter()
            float(_np.asarray(gr_._scores[0, :1])[0])
        s1 = step_cache.stats()
        hits, misses = s1["hits"] - s0["hits"], s1["misses"] - s0["misses"]
        retrain = {
            "boosters": args.retrain,
            "cold_compile_s": round(compile_s, 3),
            "warm_first_step_s": round(float(np.mean(warm_first)), 3),
            "hits": hits, "misses": misses,
            "hit_rate": round(hits / max(hits + misses, 1), 3),
            "total_s": round(time.time() - t_retrain, 2),
        }
        print(f"# retrain x{args.retrain}: warm first-step "
              f"{retrain['warm_first_step_s']:.3f}s vs cold compile "
              f"{compile_s:.1f}s, step-cache hit rate "
              f"{retrain['hit_rate']:.0%}", file=sys.stderr)

    # --serve: the online-inference half of the ledger. Per-request
    # wall (dispatch + device->host materialize) at serving-shaped
    # batch sizes, through the SAME public predict entry a model
    # server would call — micro-batches pad to pow2 serve buckets and
    # dispatch through the geometry-keyed predict registry, so every
    # batch size 1..bucket rides one warm compiled program.
    from lightgbm_tpu.obs import reqlog as obs_reqlog
    from lightgbm_tpu.ops import predict_cache
    serve = None
    if args.serve:
        serve = {"batches": {}}
        pc0 = predict_cache.stats()
        for b in (1, 64, 4096):
            # bounded-cardinality: b in (1, 64, 4096)
            hist = obs_registry.latency_histogram(
                f"serve/latency_s_b{b}")
            n_test = len(X_test)
            for _ in range(2):          # warmup: compile + registry
                g.predict_raw(X_test[:b])
            reqs = rows = 0
            t0 = time.time()
            t_end = t0 + args.serve_seconds
            while time.time() < t_end:
                r0 = (reqs * b) % max(n_test - b, 1)
                # request-scoped (obs/reqlog.py): each serve request
                # gets a monotonic id carried through the predict
                # stack (spans tagged, serve bucket noted) and ONE
                # wide event — the same identity a model server's
                # stream would carry
                rid = obs_reqlog.next_request_id()
                tb = time.time()
                with obs_reqlog.request(rid) as rctx:
                    g.predict_raw(X_test[r0:r0 + b])
                dt = time.time() - tb
                hist.observe(dt)
                obs_reqlog.record(
                    "request", req_id=rid, path="bench/serve", rows=b,
                    latency_ms=round(1e3 * dt, 3),
                    serve_bucket=rctx.bucket)
                reqs += 1
                rows += b
            wall = time.time() - t0
            q = hist.quantiles((0.5, 0.95, 0.99))
            serve["batches"][str(b)] = {
                "requests": reqs,
                "rows_per_s": round(rows / max(wall, 1e-9), 1),
                **{f"{k}_ms": (None if v is None
                               else round(1e3 * v, 3))
                   for k, v in q.items()},
            }
            print(f"# serve b={b}: {reqs} reqs, "
                  f"{serve['batches'][str(b)]['rows_per_s']:.0f} "
                  "rows/s, "
                  + " ".join(f"{k}={1e3 * v:.2f}ms"
                             for k, v in q.items() if v is not None),
                  file=sys.stderr)
        pc1 = predict_cache.stats()
        serve["predict_cache"] = {
            k: pc1[k] - pc0[k] for k in ("hits", "misses", "stacks",
                                         "extends")}

    # compact streaming retrain-while-serve section (bench hygiene:
    # the trajectory point captures requests/s + during-retrain p99 +
    # staleness, so BENCH_r0x diffs show the serving story too)
    stream = None
    if not args.no_lrb_stream:
        stream = lrb_stream_bench(args)
        recorder.meta["lrb_stream"] = stream

    # --parity: the measured reference-parity harness — both tiers of
    # this engine and reference LightGBM CPU on the SAME data, so the
    # trajectory carries separate exact-tier / proxy-tier throughput
    # lines and vs_measured stands on a measured reference run
    parity = None
    if args.parity:
        parity = parity_bench(args, data=(X, y, X_test, y_test))
        recorder.meta["parity"] = parity

    # SLO/error-budget section: evaluated over the run's own predict/
    # serve histograms (p99.9 now rides the quantile readout); the
    # regression tool validates the section's shape
    slo = slo_section(args.slo or DEFAULT_SLO_TRAIN)
    recorder.meta["slo"] = slo

    recorder.meta["step_cache"] = step_cache.stats()
    recorder.meta["predict_cache"] = predict_cache.stats()
    report = recorder.finish(
        leaves_per_iteration=leaves or None,
        waves_per_iteration=waves or None,
        extra={
        "train_s": round(train_s, 2), "compile_s": round(compile_s, 2),
        "mesh_devices": g.num_devices,
        "comm_bytes_per_iter": comm_per_iter,
        "train_auc": round(float(auc), 5),
        "test_auc": round(float(test_auc), 5)})
    result = {
        "phases": {name: round(rec["total_s"], 2)
                   for name, rec in report["phases"].items()},
        "counters": {k: v for k, v in report["counters"].items()
                     if k.startswith(("ingest/", "transfer/", "comm/"))},
        "ingest": "host" if args.no_ingest else "auto",
        "chips": g.num_devices,
        "comm_bytes_per_iter": comm_per_iter,
        "step_cache": step_cache.stats(),
        "predict_cache": predict_cache.stats(),
        "serve": serve,
        "retrain": retrain,
        "lrb_stream": stream,
        "slo": slo,
        "parity": parity,
        "device_kind": autotune.device_kind(),
        "train_auc": round(float(auc), 5),
        "test_auc": round(float(test_auc), 5),
        # quantiles from the log-bucketed histogram, not a sample list:
        # the same instrument a live exporter scrape sees
        "predict_latency": {
            "batch_rows": pred_batch,
            "batches": lat.count,
            "mean_ms": round(1e3 * lat.sum / max(lat.count, 1), 3),
            **{f"{k}_ms": (None if v is None else round(1e3 * v, 3))
               for k, v in lat_q.items()},
        },
        "metric": ("HIGGS-class GBDT training throughput "
                   f"({args.rows} rows x 28 feat, {args.leaves} leaves, "
                   f"{args.max_bin} bins, {args.iters} iters, "
                   f"{g.num_devices}"
                   " chip(s))" + _metric_tag()),
        "value": round(row_iters_per_s / 1e6, 3),
        "unit": "M row-iters/s",
        "vs_baseline": round(row_iters_per_s / BASELINE_ROW_ITERS_PER_S, 3),
        # the measured sibling: the parity harness's like-for-like
        # wall ratio for the tier this headline ran (proxy unless
        # --no-quant) — ref wall / engine wall, both spanning dataset
        # construction through the last iteration. Null (with
        # parity.skip_reason recorded) when the reference is
        # unavailable or --parity was not requested.
        "vs_measured": (
            parity["tiers"]["exact" if args.no_quant
                            else "proxy"]["vs_measured"]
            if parity else None),
    }
    print(json.dumps(result))
    if parity is not None and not parity["ok"]:
        print(f"# PARITY FAILURE: AUC delta vs measured reference "
              f"exceeds {args.parity_auc_tol:g}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
