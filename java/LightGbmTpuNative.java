/**
 * In-process JVM binding for lightgbm_tpu over the linkable C ABI.
 *
 * The reference ships SWIG glue (reference: swig/lightgbmlib.i,
 * CMakeLists.txt:185-214) so JVM callers (mmlspark) can drive the C API
 * (include/LightGBM/c_api.h) per-row with no process boundary. Here the
 * same boundary is `native/c_api_embed.cpp` — a .so that embeds the
 * CPython/JAX engine behind the identical LGBM_* entry points — and the
 * JVM side binds it with the Panama FFI (java.lang.foreign, JDK 22+):
 * no JNI glue code, no SWIG generation step, direct downcalls.
 *
 * Surface mirrors the SWIG module's working set: dataset create (dense
 * matrix / file), SetField, booster create / load / train / predict /
 * save / eval, and frees. Parameter-string entry points use the
 * plain-C `...C` variants (the fork's header passes std::unordered_map
 * by value, which no FFI can call; the C variants take upstream
 * LightGBM's "key=value ..." string form).
 *
 * Per-row online prediction — the reason an in-process binding exists —
 * is {@link Booster#predictRow(double[])}: one downcall, no spawn, no
 * serialization. The CLI-subprocess wrapper (LightGbmTpu.java) remains
 * as the zero-dependency fallback.
 *
 * Build the native library once (see tests/test_c_abi.py):
 *   g++ -O2 -shared -fPIC native/c_api_embed.cpp -o liblightgbm_tpu.so \
 *       $(python3-config --includes) $(python3-config --ldflags --embed)
 */
import java.lang.foreign.Arena;
import java.lang.foreign.FunctionDescriptor;
import java.lang.foreign.Linker;
import java.lang.foreign.MemorySegment;
import java.lang.foreign.SymbolLookup;
import java.lang.foreign.ValueLayout;
import java.lang.invoke.MethodHandle;
import java.nio.file.Path;

public final class LightGbmTpuNative implements AutoCloseable {

    // c_api.h data-type tags
    public static final int C_API_DTYPE_FLOAT32 = 0;
    public static final int C_API_DTYPE_FLOAT64 = 1;
    public static final int C_API_DTYPE_INT32 = 2;
    // c_api.h predict-type tags
    public static final int C_API_PREDICT_NORMAL = 0;
    public static final int C_API_PREDICT_RAW_SCORE = 1;
    public static final int C_API_PREDICT_LEAF_INDEX = 2;
    public static final int C_API_PREDICT_CONTRIB = 3;

    private final Arena arena = Arena.ofShared();
    private final Linker linker = Linker.nativeLinker();
    private final SymbolLookup lib;

    private final MethodHandle getLastError;
    private final MethodHandle datasetCreateFromMat;
    private final MethodHandle datasetCreateFromFile;
    private final MethodHandle datasetSetField;
    private final MethodHandle datasetGetNumData;
    private final MethodHandle datasetGetNumFeature;
    private final MethodHandle datasetFree;
    private final MethodHandle boosterCreate;
    private final MethodHandle boosterCreateFromModelfile;
    private final MethodHandle boosterFree;
    private final MethodHandle boosterAddValidData;
    private final MethodHandle boosterUpdateOneIter;
    private final MethodHandle boosterGetEval;
    private final MethodHandle boosterCalcNumPredict;
    private final MethodHandle boosterPredictForMat;
    private final MethodHandle boosterSaveModel;

    public LightGbmTpuNative(Path sharedLibrary) {
        lib = SymbolLookup.libraryLookup(sharedLibrary, arena);
        var I = ValueLayout.JAVA_INT;
        var L = ValueLayout.JAVA_LONG;
        var P = ValueLayout.ADDRESS;
        getLastError = down("LGBM_GetLastError",
                FunctionDescriptor.of(P));
        datasetCreateFromMat = down("LGBM_DatasetCreateFromMatC",
                FunctionDescriptor.of(I, P, I, I, I, I, P, P, P));
        datasetCreateFromFile = down("LGBM_DatasetCreateFromFile",
                FunctionDescriptor.of(I, P, P, P, P));
        datasetSetField = down("LGBM_DatasetSetField",
                FunctionDescriptor.of(I, P, P, P, I, I));
        datasetGetNumData = down("LGBM_DatasetGetNumData",
                FunctionDescriptor.of(I, P, P));
        datasetGetNumFeature = down("LGBM_DatasetGetNumFeature",
                FunctionDescriptor.of(I, P, P));
        datasetFree = down("LGBM_DatasetFree",
                FunctionDescriptor.of(I, P));
        boosterCreate = down("LGBM_BoosterCreateC",
                FunctionDescriptor.of(I, P, P, P));
        boosterCreateFromModelfile = down("LGBM_BoosterCreateFromModelfile",
                FunctionDescriptor.of(I, P, P, P));
        boosterFree = down("LGBM_BoosterFree",
                FunctionDescriptor.of(I, P));
        boosterAddValidData = down("LGBM_BoosterAddValidData",
                FunctionDescriptor.of(I, P, P));
        boosterUpdateOneIter = down("LGBM_BoosterUpdateOneIter",
                FunctionDescriptor.of(I, P, P));
        boosterGetEval = down("LGBM_BoosterGetEval",
                FunctionDescriptor.of(I, P, I, P, P));
        boosterCalcNumPredict = down("LGBM_BoosterCalcNumPredict",
                FunctionDescriptor.of(I, P, I, I, I, P));
        boosterPredictForMat = down("LGBM_BoosterPredictForMatC",
                FunctionDescriptor.of(I, P, P, I, I, I, I, I, I, P, P, P));
        boosterSaveModel = down("LGBM_BoosterSaveModel",
                FunctionDescriptor.of(I, P, I, I, P));
    }

    private MethodHandle down(String name, FunctionDescriptor desc) {
        return linker.downcallHandle(
                lib.find(name).orElseThrow(
                        () -> new UnsatisfiedLinkError(name)), desc);
    }

    private void check(int rc) {
        if (rc != 0) {
            String msg = "unknown";
            try {
                MemorySegment p = (MemorySegment) getLastError.invoke();
                msg = p.reinterpret(4096).getString(0);
            } catch (Throwable ignored) {
            }
            throw new RuntimeException("lightgbm_tpu: " + msg);
        }
    }

    @Override
    public void close() {
        arena.close();
    }

    // ---- Dataset -------------------------------------------------------

    public final class Dataset implements AutoCloseable {
        final MemorySegment handle;

        private Dataset(MemorySegment handle) {
            this.handle = handle;
        }

        public void setLabel(float[] label) {
            try (Arena a = Arena.ofConfined()) {
                MemorySegment buf = a.allocateFrom(
                        ValueLayout.JAVA_FLOAT, label);
                check((int) datasetSetField.invoke(
                        handle, a.allocateFrom("label"), buf,
                        label.length, C_API_DTYPE_FLOAT32));
            } catch (RuntimeException e) {
                throw e;
            } catch (Throwable t) {
                throw new RuntimeException(t);
            }
        }

        public int numData() {
            return getInt(datasetGetNumData);
        }

        public int numFeature() {
            return getInt(datasetGetNumFeature);
        }

        private int getInt(MethodHandle h) {
            try (Arena a = Arena.ofConfined()) {
                MemorySegment out = a.allocate(ValueLayout.JAVA_INT);
                check((int) h.invoke(handle, out));
                return out.get(ValueLayout.JAVA_INT, 0);
            } catch (RuntimeException e) {
                throw e;
            } catch (Throwable t) {
                throw new RuntimeException(t);
            }
        }

        @Override
        public void close() {
            try {
                datasetFree.invoke(handle);
            } catch (Throwable ignored) {
            }
        }
    }

    /** Row-major dense double matrix -> Dataset. */
    public Dataset datasetFromMat(double[] data, int nrow, int ncol,
                                  String params) {
        try (Arena a = Arena.ofConfined()) {
            MemorySegment buf = a.allocateFrom(
                    ValueLayout.JAVA_DOUBLE, data);
            MemorySegment out = a.allocate(ValueLayout.ADDRESS);
            check((int) datasetCreateFromMat.invoke(
                    buf, C_API_DTYPE_FLOAT64, nrow, ncol, 1,
                    a.allocateFrom(params == null ? "" : params),
                    MemorySegment.NULL, out));
            return new Dataset(out.get(ValueLayout.ADDRESS, 0));
        } catch (RuntimeException e) {
            throw e;
        } catch (Throwable t) {
            throw new RuntimeException(t);
        }
    }

    public Dataset datasetFromFile(Path file, String params) {
        try (Arena a = Arena.ofConfined()) {
            MemorySegment out = a.allocate(ValueLayout.ADDRESS);
            check((int) datasetCreateFromFile.invoke(
                    a.allocateFrom(file.toString()),
                    a.allocateFrom(params == null ? "" : params),
                    MemorySegment.NULL, out));
            return new Dataset(out.get(ValueLayout.ADDRESS, 0));
        } catch (RuntimeException e) {
            throw e;
        } catch (Throwable t) {
            throw new RuntimeException(t);
        }
    }

    // ---- Booster -------------------------------------------------------

    public final class Booster implements AutoCloseable {
        final MemorySegment handle;
        private final int numFeatures;

        private Booster(MemorySegment handle, int numFeatures) {
            this.handle = handle;
            this.numFeatures = numFeatures;
        }

        /** One boosting round; true = no further splits possible. */
        public boolean updateOneIter() {
            try (Arena a = Arena.ofConfined()) {
                MemorySegment fin = a.allocate(ValueLayout.JAVA_INT);
                check((int) boosterUpdateOneIter.invoke(handle, fin));
                return fin.get(ValueLayout.JAVA_INT, 0) != 0;
            } catch (RuntimeException e) {
                throw e;
            } catch (Throwable t) {
                throw new RuntimeException(t);
            }
        }

        /** Metric values for data_idx (0 = train, 1+ = valid sets). */
        public double[] getEval(int dataIdx) {
            try (Arena a = Arena.ofConfined()) {
                MemorySegment len = a.allocate(ValueLayout.JAVA_INT);
                MemorySegment out = a.allocate(
                        ValueLayout.JAVA_DOUBLE, 64);
                check((int) boosterGetEval.invoke(
                        handle, dataIdx, len, out));
                int n = len.get(ValueLayout.JAVA_INT, 0);
                return out.asSlice(0, 8L * n)
                        .toArray(ValueLayout.JAVA_DOUBLE);
            } catch (RuntimeException e) {
                throw e;
            } catch (Throwable t) {
                throw new RuntimeException(t);
            }
        }

        /** Batch predict; predictType is a C_API_PREDICT_* tag. */
        public double[] predict(double[] rowMajor, int nrow,
                                int predictType) {
            int ncol = numFeatures;
            try (Arena a = Arena.ofConfined()) {
                MemorySegment nout = a.allocate(ValueLayout.JAVA_LONG);
                check((int) boosterCalcNumPredict.invoke(
                        handle, nrow, predictType, -1, nout));
                long n = nout.get(ValueLayout.JAVA_LONG, 0);
                MemorySegment buf = a.allocateFrom(
                        ValueLayout.JAVA_DOUBLE, rowMajor);
                MemorySegment res = a.allocate(
                        ValueLayout.JAVA_DOUBLE, n);
                MemorySegment olen = a.allocate(ValueLayout.JAVA_LONG);
                check((int) boosterPredictForMat.invoke(
                        handle, buf, C_API_DTYPE_FLOAT64, nrow, ncol,
                        1, predictType, -1, a.allocateFrom(""), olen,
                        res));
                return res.toArray(ValueLayout.JAVA_DOUBLE);
            } catch (RuntimeException e) {
                throw e;
            } catch (Throwable t) {
                throw new RuntimeException(t);
            }
        }

        /** Per-row online prediction — one in-process downcall. */
        public double predictRow(double[] features) {
            return predict(features, 1, C_API_PREDICT_NORMAL)[0];
        }

        public void saveModel(Path file) {
            try (Arena a = Arena.ofConfined()) {
                check((int) boosterSaveModel.invoke(
                        handle, 0, -1,
                        a.allocateFrom(file.toString())));
            } catch (RuntimeException e) {
                throw e;
            } catch (Throwable t) {
                throw new RuntimeException(t);
            }
        }

        @Override
        public void close() {
            try {
                boosterFree.invoke(handle);
            } catch (Throwable ignored) {
            }
        }
    }

    public Booster boosterCreate(Dataset train, String params) {
        try (Arena a = Arena.ofConfined()) {
            MemorySegment out = a.allocate(ValueLayout.ADDRESS);
            check((int) boosterCreate.invoke(
                    train.handle,
                    a.allocateFrom(params == null ? "" : params), out));
            return new Booster(out.get(ValueLayout.ADDRESS, 0),
                    train.numFeature());
        } catch (RuntimeException e) {
            throw e;
        } catch (Throwable t) {
            throw new RuntimeException(t);
        }
    }

    public Booster boosterFromModelfile(Path model, int numFeatures) {
        try (Arena a = Arena.ofConfined()) {
            MemorySegment iters = a.allocate(ValueLayout.JAVA_INT);
            MemorySegment out = a.allocate(ValueLayout.ADDRESS);
            check((int) boosterCreateFromModelfile.invoke(
                    a.allocateFrom(model.toString()), iters, out));
            return new Booster(out.get(ValueLayout.ADDRESS, 0),
                    numFeatures);
        } catch (RuntimeException e) {
            throw e;
        } catch (Throwable t) {
            throw new RuntimeException(t);
        }
    }

    /** Smoke entry point for the JDK-gated test: train a tiny model
     *  in-process, per-row predict, save, reload, re-predict. */
    public static void main(String[] args) throws Exception {
        Path so = Path.of(args[0]);
        Path modelOut = Path.of(args[1]);
        try (LightGbmTpuNative lgb = new LightGbmTpuNative(so)) {
            int n = 400, f = 4;
            double[] x = new double[n * f];
            float[] y = new float[n];
            java.util.Random r = new java.util.Random(7);
            for (int i = 0; i < n; i++) {
                for (int j = 0; j < f; j++) {
                    x[i * f + j] = r.nextGaussian();
                }
                y[i] = (x[i * f] + 0.5 * x[i * f + 1] > 0) ? 1f : 0f;
            }
            String params = "objective=binary num_leaves=15 max_bin=63 "
                    + "metric=auc verbose=-1";
            try (var ds = lgb.datasetFromMat(x, n, f, params)) {
                ds.setLabel(y);
                try (var b = lgb.boosterCreate(ds, params)) {
                    for (int it = 0; it < 10; it++) {
                        if (b.updateOneIter()) break;
                    }
                    double auc = b.getEval(0)[0];
                    double p0 = b.predictRow(
                            new double[] {2.0, 1.0, 0.0, 0.0});
                    double p1 = b.predictRow(
                            new double[] {-2.0, -1.0, 0.0, 0.0});
                    b.saveModel(modelOut);
                    try (var b2 = lgb.boosterFromModelfile(modelOut, f)) {
                        double q0 = b2.predictRow(
                                new double[] {2.0, 1.0, 0.0, 0.0});
                        if (Math.abs(q0 - p0) > 1e-6) {
                            throw new AssertionError("reload mismatch");
                        }
                    }
                    System.out.printf(
                            "JAVA_FFM_OK auc=%.4f p_pos=%.4f p_neg=%.4f%n",
                            auc, p0, p1);
                    if (!(auc > 0.9) || !(p0 > p1)) {
                        throw new AssertionError("quality check failed");
                    }
                }
            }
        }
    }
}
