/**
 * JVM binding for lightgbm_tpu.
 *
 * The reference exposes its engine to the JVM through a 100-line SWIG
 * interface over the C API (reference: swig/lightgbmlib.i,
 * CMakeLists.txt:185-214) — a thin marshalling layer for mmlspark.
 * Here the engine is a Python/XLA runtime, so the equivalent thin
 * boundary is the framework's config-file CLI (python -m lightgbm_tpu),
 * which accepts exactly the reference CLI's config keys: the JVM side
 * marshals parameters and matrices to files, the TPU side does all the
 * work, and models cross the boundary in the LightGBM v2 text format
 * both engines read and write.
 */
import java.io.BufferedWriter;
import java.io.File;
import java.io.IOException;
import java.nio.charset.StandardCharsets;
import java.nio.file.Files;
import java.nio.file.Path;
import java.util.ArrayList;
import java.util.List;
import java.util.Map;

public final class LightGbmTpu {

    private String python = "python3";

    public LightGbmTpu() {}

    public LightGbmTpu(String pythonExecutable) {
        this.python = pythonExecutable;
    }

    /** Train from a data file; returns the model file path. */
    public Path train(Path trainData, Path validData,
                      Map<String, String> params, Path outputModel)
            throws IOException, InterruptedException {
        List<String> argv = baseArgv();
        argv.add("task=train");
        argv.add("data=" + trainData);
        if (validData != null) argv.add("valid_data=" + validData);
        for (Map.Entry<String, String> e : params.entrySet()) {
            argv.add(e.getKey() + "=" + e.getValue());
        }
        argv.add("output_model=" + outputModel);
        run(argv);
        return outputModel;
    }

    /** Train on an in-memory dense matrix. */
    public Path train(double[][] features, double[] labels,
                      Map<String, String> params, Path outputModel)
            throws IOException, InterruptedException {
        Path data = writeMatrix(features, labels);
        try {
            return train(data, null, params, outputModel);
        } finally {
            Files.deleteIfExists(data);
        }
    }

    /** Predict rows of a data file with a saved model. */
    public double[] predict(Path model, Path data,
                            Map<String, String> params)
            throws IOException, InterruptedException {
        Path out = Files.createTempFile("lgbtpu_pred", ".txt");
        List<String> argv = baseArgv();
        argv.add("task=predict");
        argv.add("input_model=" + model);
        argv.add("data=" + data);
        argv.add("output_result=" + out);
        if (params != null) {
            for (Map.Entry<String, String> e : params.entrySet()) {
                argv.add(e.getKey() + "=" + e.getValue());
            }
        }
        run(argv);
        List<String> lines = Files.readAllLines(out,
                StandardCharsets.UTF_8);
        Files.deleteIfExists(out);
        double[] preds = new double[lines.size()];
        for (int i = 0; i < lines.size(); i++) {
            // multiclass rows are tab-separated; keep the max prob here
            String[] toks = lines.get(i).trim().split("\\s+");
            double best = Double.NEGATIVE_INFINITY;
            for (String t : toks) {
                best = Math.max(best, Double.parseDouble(t));
            }
            preds[i] = toks.length == 1
                    ? Double.parseDouble(toks[0]) : best;
        }
        return preds;
    }

    /** Predict an in-memory matrix. */
    public double[] predict(Path model, double[][] features)
            throws IOException, InterruptedException {
        Path data = writeMatrix(features, null);
        try {
            return predict(model, data, null);
        } finally {
            Files.deleteIfExists(data);
        }
    }

    private List<String> baseArgv() {
        List<String> argv = new ArrayList<>();
        argv.add(python);
        argv.add("-m");
        argv.add("lightgbm_tpu");
        return argv;
    }

    private static Path writeMatrix(double[][] x, double[] y)
            throws IOException {
        Path f = Files.createTempFile("lgbtpu_data", ".csv");
        try (BufferedWriter w = Files.newBufferedWriter(f,
                StandardCharsets.UTF_8)) {
            StringBuilder sb = new StringBuilder();
            for (int i = 0; i < x.length; i++) {
                sb.setLength(0);
                sb.append(y == null ? 0.0 : y[i]);
                for (double v : x[i]) sb.append(',').append(v);
                sb.append('\n');
                w.write(sb.toString());
            }
        }
        return f;
    }

    private static void run(List<String> argv)
            throws IOException, InterruptedException {
        ProcessBuilder pb = new ProcessBuilder(argv);
        pb.redirectErrorStream(true);
        pb.redirectOutput(ProcessBuilder.Redirect.INHERIT);
        Process p = pb.start();
        int rc = p.waitFor();
        if (rc != 0) {
            throw new IOException("lightgbm_tpu exited with " + rc
                    + " for: " + String.join(" ", argv));
        }
    }

    public static void main(String[] args) throws Exception {
        // smoke test: train + predict on a tiny synthetic problem
        double[][] x = new double[400][4];
        double[] y = new double[400];
        java.util.Random r = new java.util.Random(7);
        for (int i = 0; i < 400; i++) {
            for (int j = 0; j < 4; j++) x[i][j] = r.nextGaussian();
            y[i] = (x[i][0] + 0.5 * x[i][1] > 0) ? 1 : 0;
        }
        LightGbmTpu lgb = new LightGbmTpu();
        Path model = Files.createTempFile("lgbtpu_model", ".txt");
        Map<String, String> params = Map.of(
                "objective", "binary", "num_leaves", "15",
                "num_trees", "20", "min_data_in_leaf", "5");
        lgb.train(x, y, params, model);
        double[] p = lgb.predict(model, x);
        int correct = 0;
        for (int i = 0; i < 400; i++) {
            if ((p[i] > 0.5 ? 1 : 0) == (int) y[i]) correct++;
        }
        System.out.println("accuracy=" + (correct / 400.0));
        Files.deleteIfExists(model);
        if (correct < 360) throw new AssertionError("quality too low");
    }
}
