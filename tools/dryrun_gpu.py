#!/usr/bin/env python
"""GPU-backend dry run — the Triton twin of ``dryrun_multichip``.

Two phases, each recorded in the one-line verdict so the artifact
cannot drift from the test suite:

1. **Interpret-mode parity slice** (any backend): runs
   ``pytest -m gpu_tier`` in a fresh CPU-pinned subprocess — the
   bit-parity certificates of both GPU histogram kernels and the GPU
   forest kernel against their XLA oracles, the device-kind autotune
   arms, and the per-backend step-cache keying. "OK" here means the
   kernels are bit-correct wherever Pallas-Triton can lower.
2. **Native GPU smoke** (only when ``backend_kind() == "gpu"``): a
   real timed training run asserting the pallas-gpu route actually
   engaged (WaveGrowerConfig.route on the live booster), that a
   same-geometry retrain is a pure compiled-step registry hit, and
   that the persistent XLA compile cache (tpu_compile_cache auto-on
   for GPU) populated its directory. Skipped with the reason printed
   — device kind and the capability that gated it — on hosts without
   a GPU, mirroring bench.py --parity's recorded-skip contract.

Run from the repo root: ``python tools/dryrun_gpu.py``.
Exit 0 = every phase that could run passed; skips are not failures.
"""
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parity_slice() -> str:
    """pytest -m gpu_tier in a CPU-pinned subprocess (fresh jax: the
    parent may have initialized a GPU backend, the slice must not)."""
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "-m",
         "gpu_tier", "-p", "no:cacheprovider"],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=1800)
    tail = (proc.stdout or "").strip().splitlines()[-1:] or ["(no out)"]
    if proc.returncode != 0:
        raise SystemExit(
            f"pytest -m gpu_tier failed (rc={proc.returncode}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    return tail[0]


def _gpu_smoke() -> str:
    """Timed native smoke: route engagement + registry hit + compile
    cache population. Caller guarantees backend_kind() == 'gpu'."""
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.metrics import create_metrics
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.ops import autotune, step_cache

    cache_dir = tempfile.mkdtemp(prefix="lgbm_tpu_gpu_cache_")
    autotune.ensure_compile_cache(cache_dir)   # auto-on for GPU

    r = np.random.default_rng(0)
    X = r.normal(size=(4096, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)

    def train():
        cfg = Config().set({"objective": "binary", "num_leaves": 15,
                            "max_bin": 63, "min_data_in_leaf": 5})
        ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
        obj = create_objective("binary", cfg)
        obj.init(ds.metadata, ds.num_data)
        mets = create_metrics(["auc"], cfg, ds.metadata, ds.num_data)
        g = GBDT()
        g.init(cfg, ds, obj, mets)
        for _ in range(5):
            g.train_one_iter()
        return g

    s0 = step_cache.stats()
    t0 = time.perf_counter()
    g1 = train()
    t1 = time.perf_counter()
    assert g1._grower_cfg.route == "pallas-gpu", (
        f"pallas-gpu route did not engage on a GPU backend "
        f"(route={g1._grower_cfg.route!r})")
    g2 = train()
    t2 = time.perf_counter()
    s2 = step_cache.stats()
    d = {k: s2[k] - s0[k] for k in ("hits", "misses")}
    assert d["hits"] >= 1, f"retrain must hit the step registry ({d})"
    cached = sum(len(fs) for _, _, fs in os.walk(cache_dir))
    assert cached > 0, (
        "persistent compile cache stayed empty on GPU — "
        "ensure_compile_cache policy regressed")
    assert np.allclose(np.asarray(g1.predict_raw(X[:256])),
                       np.asarray(g2.predict_raw(X[:256])))
    return (f"run1={t1 - t0:.2f}s run2={t2 - t1:.2f}s "
            f"registry(hits={d['hits']},misses={d['misses']}) "
            f"compile_cache_files={cached}")


def dryrun_gpu() -> None:
    try:
        from lightgbm_tpu.ops import autotune
    except ImportError:                # invoked from outside the repo
        sys.path.insert(0, REPO)
        from lightgbm_tpu.ops import autotune

    if not autotune.gpu_pallas_supported():
        print("dryrun_gpu: SKIP — jax.experimental.pallas.triton not "
              "importable; the pallas-gpu route is gated off and the "
              "parity slice has nothing to certify "
              f"[device_kind={autotune.device_kind()}]")
        return

    parity = _parity_slice()

    from lightgbm_tpu.utils.device import backend_kind
    if backend_kind() == "gpu":
        smoke = _gpu_smoke()
        print(f"dryrun_gpu: OK — parity slice: {parity}; "
              f"native smoke [{autotune.device_kind()}]: {smoke}")
    else:
        print(f"dryrun_gpu: OK — parity slice: {parity}; native smoke "
              f"SKIP — no GPU visible "
              f"[device_kind={autotune.device_kind()}]; interpret-mode "
              "bit-parity is the certificate that transfers")


if __name__ == "__main__":
    dryrun_gpu()
