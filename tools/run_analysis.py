#!/usr/bin/env python
"""Run the repo's static-analysis checkers (lightgbm_tpu/analysis/).

Checks the package, tools/ and bench.py against the repo's own
invariants: jit-capture discipline, guarded-by lock discipline, knob /
metric / artifact contracts. Stdlib-only and import-free of the code
under analysis (pure AST) — runs anywhere in ~seconds, no jax.

Exit codes (the check_bench_regression.py convention):
  0  clean (all findings baselined or none)
  1  findings (including STALE baseline entries — the file only
     shrinks toward zero)
  2  usage error (bad arguments, unreadable/forbidden baseline)

Baseline: ``tools/analysis_baseline.json`` — every entry is a
``finding key`` plus a one-line justification. jit_capture and
lock_discipline findings are REFUSED there: deliberate exemptions for
those live inline next to the code (``# jit-capture: ok(...) —
reason``, ``# unguarded-ok: reason``).

  python tools/run_analysis.py                # human-readable
  python tools/run_analysis.py --json         # machine-readable
  python tools/run_analysis.py --update-baseline   # rewrite baseline
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys
import types
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _load_analysis():
    """Import lightgbm_tpu.analysis WITHOUT executing the package
    __init__ (which imports the full engine, jax included): register
    a path-only stub for ``lightgbm_tpu`` when the real package is
    not already loaded, then import the analysis subpackage normally.
    Inside a process that has the real package (the pytest wrapper),
    this is a plain import."""
    if "lightgbm_tpu" not in sys.modules:
        stub = types.ModuleType("lightgbm_tpu")
        stub.__path__ = [os.path.join(_REPO, "lightgbm_tpu")]
        sys.modules["lightgbm_tpu"] = stub
    return (importlib.import_module("lightgbm_tpu.analysis." + name)
            for name in ("core", "jit_capture", "lock_discipline",
                         "contracts"))


_core, jit_capture, lock_discipline, contracts = _load_analysis()
Baseline = _core.Baseline
Finding = _core.Finding
UsageError = _core.UsageError
iter_sources = _core.iter_sources

DEFAULT_BASELINE = os.path.join(_REPO, "tools", "analysis_baseline.json")


def run_checkers(root: str) -> List[Finding]:
    sources = iter_sources(root)
    info = contracts.build_repo_info(sources, root)
    findings: List[Finding] = []
    findings += jit_capture.check(sources, info.config_fields)
    findings += lock_discipline.check(sources)
    findings += contracts.check(sources, info)
    findings.sort(key=lambda f: (f.path, f.line, f.key))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="repo-native static analysis (exit 0 clean / "
                    "1 findings / 2 usage error)")
    ap.add_argument("--root", default=_REPO,
                    help="repo root to scan (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: "
                         "tools/analysis_baseline.json under --root)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(jit_capture/lock_discipline never written; "
                         "new entries get a TODO justification to fill)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    root = os.path.abspath(args.root)
    if not os.path.isdir(os.path.join(root, "lightgbm_tpu")):
        print(f"error: {root} does not look like the repo root "
              "(no lightgbm_tpu/ package)", file=sys.stderr)
        return 2
    baseline_path = args.baseline or os.path.join(
        root, "tools", "analysis_baseline.json")

    try:
        baseline = Baseline.load(baseline_path)
        findings = run_checkers(root)
    except UsageError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except SyntaxError as e:
        print(f"error: unparsable source: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        doc = baseline.dump(findings)
        with open(baseline_path, "w") as fh:   # atomic-ok: dev tool,
            json.dump(doc, fh, indent=2)       # no concurrent reader
            fh.write("\n")
        print(f"baseline written: {baseline_path} "
              f"({len(doc['entries'])} entries)")
        # fall through with the FRESH baseline: the run must report
        # (and exit on) only what is NOT baselineable — not the
        # findings it just wrote
        try:
            baseline = Baseline.load(baseline_path)
        except UsageError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    kept, suppressed, stale = baseline.apply(findings)
    stale_findings = [
        Finding("baseline", "stale-entry",
                os.path.relpath(baseline_path, root), 1,
                f"baseline entry no longer matches any finding — "
                f"remove it: {k}", k)
        for k in sorted(stale)]
    report = kept + stale_findings

    if args.json:
        print(json.dumps({
            "schema": "lightgbm-tpu/analysis v1",
            "root": root,
            "findings": [f.to_json() for f in report],
            "suppressed_by_baseline": suppressed,
            "stale_baseline_keys": sorted(stale),
            "clean": not report,
        }, indent=2))
    else:
        for f in report:
            print(f.render())
        print(f"analysis: {len(report)} finding(s), "
              f"{suppressed} baselined, {len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'}")
    return 1 if report else 0


if __name__ == "__main__":
    sys.exit(main())
