"""Bench-regression gate: compare a fresh bench JSON against the
repo's BENCH_r0x trajectory.

The perf ledger lives in-repo as BENCH_r01..r0N snapshots (each the
driver's wrapper around one ``python bench.py`` run); until now a
throughput or quality regression only surfaced when a reviewer eyeballed
the numbers. This tool makes the comparison mechanical:

- **throughput**: the fresh run's ``value`` (M row-iters/s) must be
  within ``--throughput-tol`` (default 20%, measurement noise on shared
  hosts) of the LATEST trajectory point;
- **quality**: the fresh run's test AUC must be no more than
  ``--auc-tol`` (default 2e-3) below the latest baseline's (parsed from
  the wrapper's stderr tail when the JSON predates the in-line field);
- **serving latency**: the fresh run's ``predict_latency`` p50/p99 must
  be within ``--latency-tol`` (default 50% — per-request walls on
  shared hosts are far noisier than throughput) of the latest baseline
  that CARRIES the quantiles; trajectory points predating the field are
  skipped, never treated as a zero-latency baseline;
- **measured parity** (``bench.py --parity``): the ``parity`` section
  carries both tiers' measured walls/AUCs against reference LightGBM
  CPU on the same data — the exact-semantics tier's throughput gates
  like the headline (floor, ``--throughput-tol``, against the latest
  trajectory point CARRYING a comparable parity section), and when the
  reference was importable the per-tier AUC delta must stay under the
  recorded ceiling (the reference's own ~4e-4 GPU-vs-CPU bar); a run
  where the reference was unavailable must RECORD its skip reason;
- **fleet serving** (``bench.py --fleet``): the ``fleet`` section
  (unit ``requests/s``, like the lrb-stream line — the section key
  disambiguates) gates aggregate coalesced requests/s as a floor
  (``--throughput-tol``) and the WORST tenant's client p99 as a
  ceiling (``--latency-tol``) against the latest trajectory point
  carrying a comparable fleet shape (tenants x requests x rows x
  streams); per-tenant quantiles, shed counts and the registry hit
  rate are shape-validated;
- **SLO section**: a fresh run carrying an ``slo`` section (obs/slo.py
  budget report: remaining error budget, burn rate, p99.9 tails) has
  its SHAPE validated — budget fields numeric-or-null, per-objective
  budget state present; values are reported as notes, never gated
  (compliance on a shared host is an operator signal, not a perf
  regression);
- **comparability**: the bench ``metric`` string embeds the workload
  shape (rows x features, leaves, bins, iters, chips) AND the device
  kind (bench.py ``_metric_tag`` — a trailing ``[cpu]`` / ``[TPU v4]``
  / GPU-name stamp) — a quick run is refused against a full-size
  baseline, and a CPU number against a GPU or TPU trajectory (exit 2),
  instead of "passing" a meaningless comparison. Baseline selection
  filters on metric equality, so the walk-back skips trajectory
  points recorded on a different backend and gates against the newest
  same-shape same-device point (``--schema-only`` skips the trajectory
  and just validates the fresh artifact's shape, including the
  predict-latency quantiles).

Standalone:  ``python tools/check_bench_regression.py fresh.json``
(exit 0 pass / 1 regression / 2 schema-or-usage error); also importable
— tests/test_bench_regression.py drives ``compare``/``check_schema``
directly and a slow-marked test runs the real ``bench.py --quick``
through ``--schema-only``.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

DEFAULT_THROUGHPUT_TOL = 0.20
DEFAULT_AUC_TOL = 2e-3
DEFAULT_LATENCY_TOL = 0.50
# model-staleness lag is integral windows: an absolute slack reads
# better than a percentage of a number that is usually 0
DEFAULT_STALENESS_SLACK = 1.0

# the wrapper's stderr tail carries the AUC line for trajectory points
# that predate the in-JSON train_auc/test_auc fields
_TAIL_AUC_RE = re.compile(
    r"train-AUC=(?P<train>[0-9.]+)\s+test-AUC=(?P<test>[0-9.]+)")


def load_bench(doc) -> dict:
    """Normalize a bench artifact — either the raw JSON line bench.py
    prints, or a BENCH_r0x wrapper ({"parsed": ..., "tail": ...}) — to
    one flat dict with metric/value/unit and (when recoverable)
    train_auc/test_auc."""
    if isinstance(doc, str):
        with open(doc) as fh:
            doc = json.load(fh)
    out = dict(doc.get("parsed") or doc)
    # the wrapper-level baseline flag must survive normalization:
    # "baseline": false marks a ledger-only point (e.g. a quick-shape
    # parity snapshot) that must never become the trajectory floor
    if "baseline" in doc:
        out["baseline"] = doc["baseline"]
    tail = doc.get("tail", "")
    if tail and ("test_auc" not in out or out.get("test_auc") is None):
        m = _TAIL_AUC_RE.search(tail)
        if m:
            out.setdefault("train_auc", float(m.group("train")))
            out["test_auc"] = float(m.group("test"))
    return out


def trajectory(baseline_dir: str) -> List[str]:
    """BENCH_r*.json paths in trajectory order — NUMERIC run index
    (lexicographic order would park r100 before r11 forever)."""

    def run_index(path):
        m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
        return (int(m.group(1)) if m else 0, path)

    return sorted(glob.glob(os.path.join(baseline_dir, "BENCH_r*.json")),
                  key=run_index)


def check_schema(fresh: dict) -> List[str]:
    """Shape problems in a (normalized) fresh bench artifact — the
    HIGGS-class training line (unit ``M row-iters/s``), the standalone
    ``bench.py --lrb-stream`` line (unit ``requests/s``, details under
    ``lrb_stream``), the ``bench.py --sparse`` line (unit ``rows/s``,
    dense-vs-CSR routes under ``sparse``) or the ``bench.py --rank``
    line (also unit ``rows/s`` — the two share the unit, so the
    section key disambiguates: memory-vs-OOC routes under ``rank``);
    a training line may also CARRY an ``lrb_stream`` section (the
    appended compact stream bench). The ``bench.py --fleet`` line
    shares the requests/s unit with the stream line; the ``fleet``
    section key disambiguates."""
    problems = []
    fleet_only = (fresh.get("unit") == "requests/s"
                  and fresh.get("fleet") is not None)
    stream_only = (fresh.get("unit") == "requests/s"
                   and not fleet_only)
    rank_only = (fresh.get("unit") == "rows/s"
                 and isinstance(fresh.get("rank"), (dict, list, str)))
    sparse_only = fresh.get("unit") == "rows/s" and not rank_only
    if not isinstance(fresh.get("value"), (int, float)):
        problems.append("missing numeric 'value' "
                        + ("(requests/s)" if stream_only or fleet_only
                           else "(rows/s)" if sparse_only or rank_only
                           else "(M row-iters/s)"))
    if fleet_only:
        pass                      # shape gated below with the section
    elif stream_only:
        if not isinstance(fresh.get("lrb_stream"), dict):
            problems.append("unit requests/s but no 'lrb_stream' "
                            "object")
    elif rank_only:
        pass                      # shape gated below with the section
    elif sparse_only:
        if not isinstance(fresh.get("sparse"), dict):
            problems.append("unit rows/s but no 'sparse' object")
    elif fresh.get("unit") != "M row-iters/s":
        problems.append(f"unexpected unit {fresh.get('unit')!r}")
    if not isinstance(fresh.get("metric"), str):
        problems.append("missing 'metric' workload descriptor")
    ls = fresh.get("lrb_stream")
    if ls is not None:
        if not isinstance(ls, dict):
            problems.append(
                f"lrb_stream is {type(ls).__name__}, not a dict")
        else:
            for k in ("requests_per_s", "staleness_p99_windows"):
                if not isinstance(ls.get(k), (int, float)):
                    problems.append(f"lrb_stream.{k} missing/null")
            # during-retrain quantiles may legitimately be null (a
            # fast trainer can finish between scorer requests) but
            # must not be a wrong type
            p99d = ls.get("serve_p99_during_retrain_ms")
            if p99d is not None and not isinstance(p99d, (int, float)):
                problems.append(
                    "lrb_stream.serve_p99_during_retrain_ms is "
                    f"{type(p99d).__name__}, not numeric/null")
    problems += _check_fleet_schema(fresh.get("fleet"))
    sp = fresh.get("sparse")
    if sp is not None:
        if not isinstance(sp, dict):
            problems.append(
                f"sparse is {type(sp).__name__}, not a dict")
        else:
            routes = sp.get("routes")
            if not isinstance(routes, dict):
                problems.append("sparse.routes missing/not a dict")
            else:
                for rname in ("dense", "csr"):
                    r = routes.get(rname)
                    if not isinstance(r, dict):
                        problems.append(
                            f"sparse.routes.{rname} missing/not a dict")
                        continue
                    for k in ("rows_per_s", "peak_rss_mb"):
                        if not isinstance(r.get(k), (int, float)):
                            problems.append(
                                f"sparse.routes.{rname}.{k} "
                                "missing/null")
            for k in ("density", "nnz"):
                if not isinstance(sp.get(k), (int, float)):
                    problems.append(f"sparse.{k} missing/null")
            # a silently-diverged model across routes is a correctness
            # bug, not a perf number — fail the artifact's shape check
            if sp.get("model_parity") is False:
                problems.append("sparse.model_parity is false: the "
                                "dense and CSR routes trained "
                                "different models")
    rk = fresh.get("rank")
    if rk is not None:
        if not isinstance(rk, dict):
            problems.append(f"rank is {type(rk).__name__}, not a dict")
        else:
            routes = rk.get("routes")
            if not isinstance(routes, dict):
                problems.append("rank.routes missing/not a dict")
            else:
                for rname in ("memory", "ooc"):
                    r = routes.get(rname)
                    if not isinstance(r, dict):
                        problems.append(
                            f"rank.routes.{rname} missing/not a dict")
                        continue
                    for k in ("rows_per_s", "peak_rss_mb"):
                        if not isinstance(r.get(k), (int, float)):
                            problems.append(
                                f"rank.routes.{rname}.{k} missing/null")
                    nd = r.get("ndcg")
                    if not (isinstance(nd, dict) and nd
                            and all(isinstance(v, (int, float))
                                    for v in nd.values())):
                        problems.append(
                            f"rank.routes.{rname}.ndcg missing/not a "
                            "non-empty dict of numbers")
            for k in ("peak_rss_ratio", "step_cache_hit_rate"):
                if not isinstance(rk.get(k), (int, float)):
                    problems.append(f"rank.{k} missing/null")
            # OOC's whole promise is BIT parity with the in-memory
            # loader — diverged models are a correctness bug, not a
            # perf number
            if rk.get("model_parity") is False:
                problems.append("rank.model_parity is false: the "
                                "in-memory and out-of-core routes "
                                "trained different models")
    lat = fresh.get("predict_latency")
    if lat is not None:
        if not isinstance(lat, dict):
            problems.append(
                f"predict_latency is {type(lat).__name__}, not a dict")
        else:
            for q in ("p50_ms", "p95_ms", "p99_ms"):
                if not isinstance(lat.get(q), (int, float)):
                    problems.append(f"predict_latency.{q} missing/null")
    problems += _check_slo_schema(fresh.get("slo"))
    problems += _check_parity_schema(fresh.get("parity"))
    return problems


def _check_fleet_schema(fl) -> List[str]:
    """Shape problems in the ``fleet`` section (bench.py --fleet):
    both phases' aggregate rates, the per-tenant client quantiles,
    the shed/queue counters and the registry hit rate must be present
    and numeric — an artifact that silently lost the multi-tenant
    evidence must not pass as "nothing to gate". The admission budget
    state rides along as ``slo_admission`` but is an operator signal,
    not a schema requirement (a daemon with shedding disabled has
    none)."""
    if fl is None:
        return []
    if not isinstance(fl, dict):
        return [f"fleet is {type(fl).__name__}, not a dict"]
    problems = []
    for k in ("tenants", "requests_per_tenant", "rows_per_request",
              "requests_per_s", "requests_per_s_sequential",
              "shed_total", "queue_rejects"):
        if not _num(fl.get(k)):
            problems.append(f"fleet.{k} missing/null")
    # one compiled program across same-geometry tenants is the whole
    # point — the rate may legitimately be null only when there were
    # no registry lookups at all
    hit = fl.get("registry_hit_rate")
    if hit is None:
        if _num(fl.get("registry_lookups")) and fl["registry_lookups"]:
            problems.append("fleet.registry_hit_rate null with "
                            "nonzero registry_lookups")
    elif not _num(hit):
        problems.append(f"fleet.registry_hit_rate is "
                        f"{type(hit).__name__}, not numeric/null")
    pt = fl.get("per_tenant")
    if not (isinstance(pt, dict) and pt):
        problems.append("fleet.per_tenant missing/not a non-empty "
                        "dict")
        pt = {}
    for t, row in sorted(pt.items()):
        if not isinstance(row, dict):
            problems.append(f"fleet.per_tenant.{t} is "
                            f"{type(row).__name__}, not a dict")
            continue
        for k in ("p50_ms", "p99_ms", "shed"):
            if not _num(row.get(k)):
                problems.append(f"fleet.per_tenant.{t}.{k} "
                                "missing/null")
    cb = fl.get("coalesced_batch_rows")
    if not isinstance(cb, dict):
        problems.append("fleet.coalesced_batch_rows missing/not a "
                        "dict")
    elif not _num(cb.get("batches")):
        problems.append("fleet.coalesced_batch_rows.batches "
                        "missing/null")
    return problems


def _check_parity_schema(parity) -> List[str]:
    """Shape problems in the ``parity`` section (bench.py --parity):
    both tiers must carry their measured numbers, and a run without
    the reference must carry its skip reason — an artifact that
    silently lost the measurement must not pass as "nothing to
    check"."""
    if parity is None:
        return []
    if not isinstance(parity, dict):
        return [f"parity is {type(parity).__name__}, not a dict"]
    problems = []
    tiers = parity.get("tiers")
    if not isinstance(tiers, dict):
        problems.append("parity.tiers missing/not a dict")
        tiers = {}
    for tname in ("exact", "proxy"):
        t = tiers.get(tname)
        if not isinstance(t, dict):
            problems.append(f"parity.tiers.{tname} missing/not a dict")
            continue
        for k in ("wall_s", "row_iters_per_s", "auc_tpu"):
            v = t.get(k)
            if not (isinstance(v, (int, float))
                    and not isinstance(v, bool)):
                problems.append(f"parity.tiers.{tname}.{k} "
                                "missing/not numeric")
    avail = parity.get("ref_available")
    if not isinstance(avail, bool):
        problems.append("parity.ref_available missing/not a bool")
    elif avail:
        for tname in ("exact", "proxy"):
            t = tiers.get(tname)
            if isinstance(t, dict):
                for k in ("auc_ref", "auc_delta", "ref_wall_s"):
                    v = t.get(k)
                    if not (isinstance(v, (int, float))
                            and not isinstance(v, bool)):
                        problems.append(
                            f"parity.tiers.{tname}.{k} missing/not "
                            "numeric (reference was available)")
    else:
        if not (isinstance(parity.get("skip_reason"), str)
                and parity["skip_reason"]):
            problems.append("parity.skip_reason missing/empty with "
                            "ref_available false — a skipped reference "
                            "run must record why")
    if not isinstance(parity.get("ok"), bool):
        problems.append("parity.ok missing/not a bool")
    if not isinstance(parity.get("auc_tol"), (int, float)):
        problems.append("parity.auc_tol missing/not numeric")
    return problems


def _check_slo_schema(slo) -> List[str]:
    """Shape problems in the bench ``slo`` section (obs/slo.py budget
    report): the budget fields must be numeric (or null where a tail
    legitimately has no events yet) and the per-objective rows must
    carry their budget state — an artifact that LOST the budget math
    must not pass as "no SLOs configured". Values are NOT gated:
    compliance on a shared host is an operator signal, not a perf
    regression."""
    if slo is None:
        return []
    if not isinstance(slo, dict):
        return [f"slo is {type(slo).__name__}, not a dict"]
    problems = []
    if not isinstance(slo.get("spec"), str):
        problems.append("slo.spec missing/not a string")
    if not isinstance(slo.get("ok"), bool):
        problems.append("slo.ok missing/not a bool")
    for k in ("budget_remaining_min", "burn_rate_max",
              "predict_p999_ms", "serve_p999_ms"):
        v = slo.get(k)
        if v is not None and not (isinstance(v, (int, float))
                                  and not isinstance(v, bool)):
            problems.append(
                f"slo.{k} is {type(v).__name__}, not numeric/null")
    objs = slo.get("objectives")
    if not isinstance(objs, list):
        problems.append("slo.objectives missing/not a list")
        return problems
    for i, o in enumerate(objs):
        if not isinstance(o, dict):
            problems.append(f"slo.objectives[{i}] is "
                            f"{type(o).__name__}, not a dict")
            continue
        if not isinstance(o.get("name"), str):
            problems.append(f"slo.objectives[{i}].name missing")
        for k in ("budget_remaining", "burn_rate"):
            v = o.get(k)
            if not (isinstance(v, (int, float))
                    and not isinstance(v, bool)):
                problems.append(
                    f"slo.objectives[{i}].{k} missing/not numeric")
    return problems


def field_notes(doc: dict) -> List[str]:
    """Informational notes for the fault-tolerance fields newer bench
    JSONs may carry (``degraded_windows``, ``checkpoint`` meta) —
    REPORTED, never a crash or a gate: a degraded serving window is an
    operator signal, not a perf regression, and an old tool version
    must keep working against new artifacts."""
    notes = []
    dw = doc.get("degraded_windows")
    if dw is not None:
        if isinstance(dw, (int, float)) and not isinstance(dw, bool):
            if dw:
                notes.append(f"{int(dw)} degraded window(s) reported "
                             f"by this run")
        else:
            notes.append(f"degraded_windows present but "
                         f"{type(dw).__name__}, not numeric — ignored")
    ck = doc.get("checkpoint")
    if ck is not None:
        if isinstance(ck, dict):
            keys = ", ".join(f"{k}={ck[k]}" for k in sorted(ck)[:4])
            notes.append(f"checkpoint meta present ({keys})")
        else:
            notes.append(f"checkpoint meta present but "
                         f"{type(ck).__name__}, not an object — ignored")
    slo = doc.get("slo")
    if isinstance(slo, dict) and slo.get("ok") is False:
        # an operator signal, not a perf gate (shared-host runs
        # violate latency SLOs on scheduling noise alone)
        bad = [o.get("name") for o in slo.get("objectives", [])
               if isinstance(o, dict) and o.get("ok") is False]
        notes.append(
            f"SLO violations reported by this run: "
            f"{', '.join(str(b) for b in bad) or 'unknown'} "
            f"(budget_remaining_min={slo.get('budget_remaining_min')})")
    return notes


def compare(fresh: dict, baseline: dict,
            throughput_tol: float = DEFAULT_THROUGHPUT_TOL,
            auc_tol: float = DEFAULT_AUC_TOL,
            latency_tol: float = DEFAULT_LATENCY_TOL,
            staleness_slack: float = DEFAULT_STALENESS_SLACK
            ) -> List[str]:
    """Regression problems of ``fresh`` vs one ``baseline`` point
    (both normalized); empty list == pass. Refuses cross-workload
    comparisons (the metric strings embed the shape)."""
    if fresh.get("metric") != baseline.get("metric"):
        return [f"not comparable: workload {fresh.get('metric')!r} "
                f"vs baseline {baseline.get('metric')!r}"]
    problems = []
    bv, fv = baseline.get("value"), fresh.get("value")
    if isinstance(bv, (int, float)) and isinstance(fv, (int, float)):
        floor = (1.0 - throughput_tol) * bv
        if fv < floor:
            problems.append(
                f"throughput regression: {fv:g} M row-iters/s < "
                f"{floor:g} (baseline {bv:g} - {throughput_tol:.0%})")
    ba, fa = baseline.get("test_auc"), fresh.get("test_auc")
    if isinstance(ba, (int, float)) and isinstance(fa, (int, float)):
        if fa < ba - auc_tol:
            problems.append(
                f"quality regression: test AUC {fa:.5f} < baseline "
                f"{ba:.5f} - {auc_tol:g}")
    elif isinstance(ba, (int, float)):
        problems.append("fresh run carries no test_auc to compare")
    problems += _compare_latency(fresh, baseline, latency_tol)
    problems += _compare_lrb_stream(fresh, baseline, throughput_tol,
                                    staleness_slack)
    problems += _compare_fleet(fresh, baseline, throughput_tol,
                               latency_tol)
    problems += _compare_parity(fresh, baseline, throughput_tol)
    problems += _compare_rank(fresh, baseline, auc_tol, latency_tol)
    return problems


def _compare_rank(fresh: dict, baseline: dict, auc_tol: float,
                  latency_tol: float) -> List[str]:
    """Rank-bench gate (``rank`` section): NDCG is a quality floor
    (``--auc-tol``, like test AUC — ranking quality must not silently
    decay) and the OOC route's peak RSS is a ceiling
    (``--latency-tol`` fractional slack — RSS creep back toward the
    in-memory watermark is exactly the regression out-of-core ingest
    exists to prevent). The headline rows/s floor is the generic
    ``value`` gate; the metric string embeds the workload shape, so
    cross-shape comparisons were already refused upstream. Only fires
    when the BASELINE carries the section; a fresh run that LOST it
    against a carrier is itself a problem."""
    br = baseline.get("rank")
    if not isinstance(br, dict):
        return []
    fr = fresh.get("rank")
    if not isinstance(fr, dict):
        return ["fresh run carries no rank section to compare"]
    problems = []
    bo = (br.get("routes") or {}).get("ooc") or {}
    fo = (fr.get("routes") or {}).get("ooc") or {}
    bnd = bo.get("ndcg") if isinstance(bo.get("ndcg"), dict) else {}
    fnd = fo.get("ndcg") if isinstance(fo.get("ndcg"), dict) else {}
    for k in sorted(bnd):
        bq = bnd[k]
        if not isinstance(bq, (int, float)):
            continue
        fq = fnd.get(k)
        if not isinstance(fq, (int, float)):
            problems.append(f"fresh run carries no rank ooc {k} "
                            "to compare")
        elif fq < bq - auc_tol:
            problems.append(
                f"ranking-quality regression: ooc {k} {fq:.5f} < "
                f"baseline {bq:.5f} - {auc_tol:g}")
    brss = bo.get("peak_rss_mb")
    if isinstance(brss, (int, float)):
        frss = fo.get("peak_rss_mb")
        if not isinstance(frss, (int, float)):
            problems.append("fresh run carries no rank ooc "
                            "peak_rss_mb to compare")
        else:
            ceil = (1.0 + latency_tol) * brss
            if frss > ceil:
                problems.append(
                    f"out-of-core RSS regression: ooc peak "
                    f"{frss:g} MB > {ceil:g} (baseline {brss:g} + "
                    f"{latency_tol:.0%})")
    return problems


def parity_quality_problems(fresh: dict) -> List[str]:
    """Fresh-run-only parity assertions (no baseline needed): when the
    reference engine WAS measured, every tier's AUC must be inside the
    run's recorded ceiling and the run's own ``ok`` verdict must hold —
    a measured quality miss is a regression even on the very first
    trajectory point that carries the section."""
    parity = fresh.get("parity")
    if not isinstance(parity, dict):
        return []
    problems = []
    if parity.get("ok") is False:
        problems.append("parity.ok is false: the run's own measured "
                        "AUC-parity assertion failed")
    if parity.get("ref_available") is not True:
        return problems
    tol = parity.get("auc_tol")
    if not isinstance(tol, (int, float)):
        return problems
    for tname, t in (parity.get("tiers") or {}).items():
        if not isinstance(t, dict):
            continue
        d = t.get("auc_delta")
        if isinstance(d, (int, float)) and d > tol:
            problems.append(
                f"measured-parity regression: {tname} tier AUC delta "
                f"{d:g} vs reference exceeds the {tol:g} ceiling")
    return problems


def _parity_comparable(fresh: dict, baseline: dict) -> bool:
    """True when the baseline's parity block can gate this fresh run:
    it exists and its workload shape (rows/iters/leaves/bins +
    device kind) matches — an exact-tier floor measured on a different
    shape or device gates nothing."""
    bp = baseline.get("parity")
    if not isinstance(bp, dict):
        return False
    fp = fresh.get("parity")
    if not isinstance(fp, dict):
        return True          # lost-section check still applies
    keys = ("rows", "iters", "leaves", "max_bin", "device_kind")
    return all(bp.get(k) == fp.get(k) for k in keys)


def _compare_parity(fresh: dict, baseline: dict,
                    throughput_tol: float) -> List[str]:
    """Measured-parity gate: the EXACT-semantics tier's throughput is
    a floor (like the headline value, ``--throughput-tol``) against
    the latest baseline carrying a comparable parity section — the
    whole point of the section is that the exact tier's speed stops
    being invisible behind the proxy-tier headline. Only fires when
    the baseline carries it; a fresh run that LOST the section against
    a carrier is itself a problem."""
    bp = baseline.get("parity")
    if not isinstance(bp, dict):
        return []
    if not _parity_comparable(fresh, baseline):
        return []
    fp_raw = fresh.get("parity")
    if not isinstance(fp_raw, dict):
        return ["fresh run carries no parity section to compare"]
    problems = []
    bt = ((bp.get("tiers") or {}).get("exact") or {})
    brate = bt.get("row_iters_per_s")
    if isinstance(brate, (int, float)):
        ft = ((fp_raw.get("tiers") or {}).get("exact") or {})
        frate = ft.get("row_iters_per_s")
        if not isinstance(frate, (int, float)):
            problems.append("fresh run carries no parity.tiers.exact."
                            "row_iters_per_s to compare")
        else:
            floor = (1.0 - throughput_tol) * brate
            if frate < floor:
                problems.append(
                    f"exact-tier throughput regression: {frate:g} "
                    f"M row-iters/s < {floor:g} (baseline {brate:g} - "
                    f"{throughput_tol:.0%})")
    return problems


def _fleet_shape(fl: dict) -> tuple:
    """The fleet workload shape — requests/s over 2 tenants is not a
    comparable floor for 8, nor 1-row requests for 64-row ones."""
    return tuple(fl.get(k) for k in ("tenants", "requests_per_tenant",
                                     "rows_per_request",
                                     "streams_per_tenant"))


def _fleet_comparable(fresh: dict, baseline: dict) -> bool:
    """True when the baseline's fleet block can gate this fresh run:
    it exists and matches the fresh run's fleet shape (the metric
    string embeds tenants x requests x rows, but streams_per_tenant
    only lives in the section)."""
    bf = baseline.get("fleet")
    if not isinstance(bf, dict):
        return False
    ff = fresh.get("fleet")
    if not isinstance(ff, dict):
        return True         # lost-section check still applies
    return _fleet_shape(ff) == _fleet_shape(bf)


def _fleet_worst_p99(fl: dict):
    pt = fl.get("per_tenant")
    if not isinstance(pt, dict):
        return None
    vals = [row.get("p99_ms") for row in pt.values()
            if isinstance(row, dict) and _num(row.get("p99_ms"))]
    return max(vals) if vals else None


def _compare_fleet(fresh: dict, baseline: dict, throughput_tol: float,
                   latency_tol: float) -> List[str]:
    """Fleet-serving gate (``fleet`` section): aggregate coalesced
    requests/s is a floor (``--throughput-tol``, like every
    throughput) and the WORST tenant's client p99 is a ceiling
    (``--latency-tol`` — multi-tenant isolation means no tenant's
    tail may quietly rot behind a healthy aggregate). Only fires when
    the BASELINE carries a comparable fleet shape; a fresh run that
    LOST the section against a carrier is itself a problem."""
    bf = baseline.get("fleet")
    if not isinstance(bf, dict):
        return []
    if not _fleet_comparable(fresh, baseline):
        return []
    ff_raw = fresh.get("fleet")
    ff = ff_raw if isinstance(ff_raw, dict) else {}
    problems = []
    brps = bf.get("requests_per_s")
    if _num(brps):
        frps = ff.get("requests_per_s")
        if not _num(frps):
            problems.append("fresh run carries no "
                            "fleet.requests_per_s to compare")
        else:
            floor = (1.0 - throughput_tol) * brps
            if frps < floor:
                problems.append(
                    f"fleet-throughput regression: {frps:g} "
                    f"requests/s < {floor:g} (baseline {brps:g} - "
                    f"{throughput_tol:.0%})")
    bp99 = _fleet_worst_p99(bf)
    if _num(bp99):
        fp99 = _fleet_worst_p99(ff)
        if not _num(fp99):
            problems.append("fresh run carries no fleet per-tenant "
                            "p99_ms to compare")
        else:
            ceil = (1.0 + latency_tol) * bp99
            if fp99 > ceil:
                problems.append(
                    f"fleet-latency regression: worst-tenant p99 "
                    f"{fp99:g} ms > {ceil:g} (baseline {bp99:g} + "
                    f"{latency_tol:.0%})")
    return problems


def _stream_shape(stream: dict) -> tuple:
    """The lrb-stream workload shape (the training-line metric string
    does not embed it, so comparability must be checked here)."""
    return tuple(stream.get(k) for k in ("windows", "window_rows",
                                         "sample_rows", "iters"))


def _stream_comparable(fresh: dict, baseline: dict) -> bool:
    """True when the baseline's lrb_stream block can gate this fresh
    run: it exists, and either predates the shape fields or matches
    the fresh run's stream shape."""
    bs = baseline.get("lrb_stream")
    if not isinstance(bs, dict):
        return False
    fs = fresh.get("lrb_stream")
    if not isinstance(fs, dict):
        return True         # lost-section check still applies
    return (not any(v is not None for v in _stream_shape(bs))
            or _stream_shape(fs) == _stream_shape(bs))


def _compare_lrb_stream(fresh: dict, baseline: dict,
                        throughput_tol: float,
                        staleness_slack: float) -> List[str]:
    """Streaming retrain-while-serve gate (``lrb_stream``): sustained
    requests/s (floor, like throughput) and model-staleness p99 lag
    (ceiling, absolute window slack). Only fires when the BASELINE
    carries the fields — trajectory points predating the stream bench
    gate nothing; a fresh run that LOST them against a baseline that
    has them is itself a problem. A baseline whose stream SHAPE
    (windows x rows, sample, iters) differs gates nothing either:
    requests/s measured on a 4x-larger window is not a comparable
    floor (the same different-workload rule the metric string enforces
    for the training line)."""
    bs = baseline.get("lrb_stream")
    if not isinstance(bs, dict):
        return []
    if not _stream_comparable(fresh, baseline):
        return []
    fs_raw = fresh.get("lrb_stream")
    fs = fs_raw if isinstance(fs_raw, dict) else {}
    problems = []
    brps = bs.get("requests_per_s")
    if isinstance(brps, (int, float)):
        frps = fs.get("requests_per_s")
        if not isinstance(frps, (int, float)):
            problems.append("fresh run carries no "
                            "lrb_stream.requests_per_s to compare")
        else:
            floor = (1.0 - throughput_tol) * brps
            if frps < floor:
                problems.append(
                    f"serving-throughput regression: {frps:g} "
                    f"requests/s < {floor:g} (baseline {brps:g} - "
                    f"{throughput_tol:.0%})")
    bst = bs.get("staleness_p99_windows")
    if isinstance(bst, (int, float)):
        fst = fs.get("staleness_p99_windows")
        if not isinstance(fst, (int, float)):
            problems.append("fresh run carries no "
                            "lrb_stream.staleness_p99_windows to "
                            "compare")
        elif fst > bst + staleness_slack:
            problems.append(
                f"staleness regression: p99 lag {fst:g} windows > "
                f"baseline {bst:g} + {staleness_slack:g}")
    return problems


def _compare_latency(fresh: dict, baseline: dict,
                     latency_tol: float) -> List[str]:
    """predict_latency p50/p99 gate. Only fires when the BASELINE
    carries numeric quantiles (points predating the field gate
    nothing); a fresh run that LOST the field against a baseline that
    has it is itself a problem — the serving ledger must not silently
    disappear."""
    blat = baseline.get("predict_latency")
    if not isinstance(blat, dict):
        return []
    flat = fresh.get("predict_latency")
    problems = []
    for q in ("p50_ms", "p99_ms"):
        bq = blat.get(q)
        if not isinstance(bq, (int, float)):
            continue
        fq = (flat or {}).get(q) if isinstance(flat, dict) else None
        if not isinstance(fq, (int, float)):
            problems.append(
                f"fresh run carries no predict_latency.{q} to compare")
            continue
        ceil = (1.0 + latency_tol) * bq
        if fq > ceil:
            problems.append(
                f"latency regression: predict {q} {fq:g} ms > "
                f"{ceil:g} (baseline {bq:g} + {latency_tol:.0%})")
    return problems


MULTICHIP_DRILL_SCHEMA = "lightgbm-tpu/multichip-drill"


def check_multichip_drill(doc: dict) -> tuple:
    """(schema_problems, regressions, notes) for an elastic-drill
    artifact (parallel/elastic.py run_drill -> MULTICHIP_r06+). The
    shape carries the drill's whole verdict, so the gate is absolute —
    no trajectory walk-back: ``model_parity=false`` (the resumed model
    diverged from the uninterrupted run) fails the artifact, as does a
    survivor that never named the dead rank or hung past its exit."""
    schema: List[str] = []
    regressions: List[str] = []
    notes: List[str] = []
    if doc.get("version") != 1:
        return ([f"multichip-drill version {doc.get('version')!r}, "
                 f"this checker wants 1"], [], [])
    ws = doc.get("world_sizes")
    if not (isinstance(ws, dict)
            and isinstance(ws.get("train"), int)
            and isinstance(ws.get("resume"), int)):
        schema.append("world_sizes must carry int train/resume")
        ws = {}
    elif not (ws["train"] > ws["resume"] >= 1):
        schema.append(f"world_sizes train={ws['train']} must exceed "
                      f"resume={ws['resume']} >= 1 (the drill proves a "
                      f"SHRINKING mesh)")
    parity = doc.get("model_parity")
    if not isinstance(parity, bool):
        schema.append("model_parity flag missing or non-boolean — the "
                      "drill's verdict must be recorded")
    elif not parity:
        regressions.append(
            "model_parity=false: the resumed model diverged from the "
            "uninterrupted run — elastic resume is broken")
    kill = doc.get("kill")
    if not isinstance(kill, dict):
        schema.append("kill section missing")
    else:
        named = kill.get("survivor_named_ranks")
        if not (isinstance(named, list) and named
                and all(isinstance(r, int) for r in named)):
            regressions.append(
                "kill.survivor_named_ranks empty: the survivor never "
                "named the dead rank (the no-hang guarantee demands "
                "one actionable line)")
        code = kill.get("survivor_exit_code")
        if not isinstance(code, int):
            schema.append("kill.survivor_exit_code missing")
        elif code != 17:    # cluster.EXIT_PEER_LOST
            regressions.append(
                f"kill.survivor_exit_code={code}: expected "
                f"EXIT_PEER_LOST (17) — a -9 means the survivor HUNG "
                f"and was killed at the launcher timeout; any other "
                f"code means it crashed instead of exiting cleanly")
    res = doc.get("resume")
    if not isinstance(res, dict) \
            or not isinstance(res.get("from_iteration"), int):
        schema.append("resume.from_iteration missing — the artifact "
                      "must record which checkpoint carried the run")
    rows = doc.get("per_host_ingest_rows")
    train_w = ws.get("train") if isinstance(ws, dict) else None
    if not isinstance(rows, list) or (
            isinstance(train_w, int) and len(rows) != train_w):
        schema.append(f"per_host_ingest_rows must list one entry per "
                      f"training host (got {rows!r} for "
                      f"{train_w} hosts)")
    else:
        if any(not isinstance(r, (int, float)) or r <= 0
               for r in rows):
            regressions.append(
                f"per_host_ingest_rows {rows}: every host must have "
                f"ingested rows — a zero means a rank trained without "
                f"its data shard")
        else:
            n = (doc.get("workload") or {}).get("n")
            if isinstance(n, int) and sum(rows) < n:
                regressions.append(
                    f"per_host_ingest_rows sum {sum(rows)} < workload "
                    f"n {n}: rows were dropped on the way in")
            notes.append(f"per-host ingest rows: {rows}")
    for k in ("train_auc", "resumed_auc"):
        v = doc.get(k)
        if v is not None and not isinstance(v, (int, float)):
            schema.append(f"{k} must be numeric or null")
        elif v is not None:
            notes.append(f"{k}={v:.4f}")
    walls = doc.get("wall_s")
    if isinstance(walls, dict):
        notes.append("walls: " + ", ".join(
            f"{k}={v}s" for k, v in walls.items()))
    _check_cluster_obs(doc, schema, notes)
    _check_incident(doc, schema, notes)
    return schema, regressions, notes


def _check_cluster_obs(doc: dict, schema: List[str],
                       notes: List[str]) -> None:
    """Shape-validate the optional ``cluster_obs`` rollup section
    (parallel/elastic.py _cluster_obs_section — rank 0's final
    cluster/* merge). Observability evidence, NEVER a perf gate: a
    malformed shape is a schema problem, a missing rollup or missing
    rank digest is a note."""
    cobs = doc.get("cluster_obs")
    if cobs is None:
        notes.append("cluster_obs rollup absent (rank-0 export not "
                     "captured)")
        return
    if not isinstance(cobs, dict):
        schema.append("cluster_obs must be an object when present")
        return
    counters = cobs.get("counters")
    if not (isinstance(counters, dict) and counters
            and all(isinstance(k, str) and k.startswith("cluster/")
                    for k in counters)):
        schema.append("cluster_obs.counters must be a non-empty "
                      "cluster/*-keyed map")
    w, rr = cobs.get("world"), cobs.get("ranks_reporting")
    if not (_num(w) and _num(rr)):
        schema.append("cluster_obs.world/ranks_reporting must be "
                      "numeric")
    elif rr < w:
        notes.append(f"cluster_obs: only {int(rr)}/{int(w)} ranks' "
                     f"digests made the final rollup")
    else:
        notes.append(f"cluster_obs: {int(rr)}/{int(w)} ranks "
                     f"reporting, {len(counters) if isinstance(counters, dict) else 0} "
                     f"cluster counters")


def _check_incident(doc: dict, schema: List[str],
                    notes: List[str]) -> None:
    """Shape-validate the optional ``incident`` summary section
    (parallel/elastic.py _incident_section). Same discipline as
    cluster_obs: shape errors are schema problems, absent evidence is
    a note — never a perf regression."""
    inc = doc.get("incident")
    if inc is None:
        notes.append("incident bundle absent")
        return
    if not isinstance(inc, dict):
        schema.append("incident must be an object when present")
        return
    if (inc.get("schema") != "lightgbm-tpu/incident"
            or inc.get("version") != 1):
        schema.append(f"incident schema/version "
                      f"{inc.get('schema')!r}/{inc.get('version')!r}: "
                      f"want lightgbm-tpu/incident v1")
    dead = inc.get("dead_ranks")
    if not (isinstance(dead, list)
            and all(isinstance(r, int) for r in dead)):
        schema.append("incident.dead_ranks must be a list of ints")
        dead = []
    have = inc.get("ranks_with_dumps")
    if not isinstance(have, list):
        schema.append("incident.ranks_with_dumps must be a list")
        have = []
    missing = [r for r in dead if r not in have]
    if missing:
        notes.append(f"incident: no flight dump recovered from dead "
                     f"rank(s) {missing}")
    notes.append(f"incident: dead_ranks={dead}, dumps from ranks "
                 f"{have}, digests from ranks "
                 f"{inc.get('digest_ranks')}")


MULTICHIP_SCALING_SCHEMA = "lightgbm-tpu/multichip-scaling"


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def check_multichip_scaling(doc: dict) -> tuple:
    """(schema_problems, regressions, notes) for a scaling-curve
    artifact (parallel/elastic.py run_scaling_artifact ->
    MULTICHIP_r07+): measured throughput per world size plus the
    autoscale drill verdict. Like the elastic drill, the shape carries
    the whole verdict — no trajectory walk-back: ``model_parity=false``
    anywhere (across scaling points, or between the autoscaled run and
    its uninterrupted baseline) fails the artifact."""
    schema: List[str] = []
    regressions: List[str] = []
    notes: List[str] = []
    if doc.get("version") != 1:
        return ([f"multichip-scaling version {doc.get('version')!r}, "
                 f"this checker wants 1"], [], [])
    pts = doc.get("points")
    if not (isinstance(pts, list) and pts):
        schema.append("points must be a non-empty list")
        pts = []
    worlds: List[int] = []
    for i, p in enumerate(pts):
        if not isinstance(p, dict):
            schema.append(f"points[{i}] is {type(p).__name__}, "
                          f"not an object")
            continue
        w = p.get("world")
        if not (isinstance(w, int) and not isinstance(w, bool)
                and w >= 1):
            schema.append(f"points[{i}].world missing/not a "
                          f"positive int")
        else:
            worlds.append(w)
        tp = p.get("throughput_rows_per_s")
        if not _num(tp) or tp <= 0:
            schema.append(f"points[{i}].throughput_rows_per_s "
                          f"missing/not positive")
        # DCN accounting: numeric where the point HAS a collective
        # (world > 1), null where it legitimately has none (world 1,
        # serial fallback) — but never a wrong type
        for k in ("comm_bytes_per_iter", "psum_stall_s",
                  "ckpt_hidden_s"):
            v = p.get(k)
            if v is not None and not _num(v):
                schema.append(f"points[{i}].{k} is "
                              f"{type(v).__name__}, not numeric/null")
        if not isinstance(p.get("model_sha"), str):
            schema.append(f"points[{i}].model_sha missing — parity "
                          f"across worlds must be auditable")
    if worlds and (worlds != sorted(worlds)
                   or len(set(worlds)) != len(worlds)):
        schema.append(f"points must be strictly increasing in world "
                      f"size (got {worlds})")
    parity = doc.get("model_parity")
    if not isinstance(parity, bool):
        schema.append("model_parity flag missing or non-boolean — "
                      "the curve's verdict must be recorded")
    elif not parity:
        regressions.append(
            "model_parity=false: the scaling points trained different "
            "models — the mesh-size invariance the whole curve rests "
            "on is broken")
    ck = doc.get("checkpoint")
    if ck is not None:
        if not isinstance(ck, dict):
            schema.append(f"checkpoint is {type(ck).__name__}, "
                          f"not an object")
        else:
            h = ck.get("hidden_s")
            if h is not None and not _num(h):
                schema.append("checkpoint.hidden_s is "
                              f"{type(h).__name__}, not numeric/null")
            elif h is not None:
                notes.append(f"checkpoint seconds hidden by the "
                             f"background writer: {h}")
    auto = doc.get("autoscale")
    if not isinstance(auto, dict):
        schema.append("autoscale section missing — the artifact must "
                      "carry the grow-then-shrink drill verdict")
    else:
        ap = auto.get("model_parity")
        if not isinstance(ap, bool):
            schema.append("autoscale.model_parity missing or "
                          "non-boolean")
        elif not ap:
            regressions.append(
                "autoscale.model_parity=false: the grow-then-shrink "
                "run diverged from the uninterrupted baseline — "
                "elastic autoscale is broken")
        rt = auto.get("reshard_total")
        if not (isinstance(rt, int) and not isinstance(rt, bool)):
            schema.append("autoscale.reshard_total missing/not an int")
        elif rt < 1:
            regressions.append(
                "autoscale.reshard_total=0: the drill never "
                "re-sharded — the autoscale path was not exercised")
        aw = auto.get("worlds")
        if not (isinstance(aw, list) and len(aw) >= 2
                and all(isinstance(w, int) and not isinstance(w, bool)
                        for w in aw)):
            schema.append("autoscale.worlds must list the world-size "
                          "sequence (>= 2 int entries)")
        else:
            notes.append("autoscale worlds: "
                         + " -> ".join(str(w) for w in aw))
    for p in pts:
        if isinstance(p, dict) and _num(p.get("throughput_rows_per_s")):
            notes.append(
                f"world {p.get('world')}: "
                f"{p['throughput_rows_per_s']:g} rows/s, "
                f"comm {p.get('comm_bytes_per_iter')} B/iter, "
                f"stall {p.get('psum_stall_s')} s, "
                f"wire {p.get('wire')!r}")
    if "cluster_obs" in doc:
        _check_cluster_obs(doc, schema, notes)
    if "incident" in doc:
        _check_incident(doc, schema, notes)
    return schema, regressions, notes


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate a fresh bench JSON against the BENCH_r0x "
                    "trajectory.")
    ap.add_argument("fresh", help="fresh bench JSON (bench.py output "
                                  "line saved to a file, or a BENCH_r0x"
                                  "-style wrapper)")
    ap.add_argument("--baseline-dir",
                    default=os.path.join(os.path.dirname(__file__),
                                         os.pardir),
                    help="directory holding BENCH_r*.json (default: "
                         "repo root)")
    ap.add_argument("--throughput-tol", type=float,
                    default=DEFAULT_THROUGHPUT_TOL,
                    help="allowed fractional throughput drop vs the "
                         "latest baseline (default 0.20)")
    ap.add_argument("--auc-tol", type=float, default=DEFAULT_AUC_TOL,
                    help="allowed absolute test-AUC drop (default 2e-3)")
    ap.add_argument("--latency-tol", type=float,
                    default=DEFAULT_LATENCY_TOL,
                    help="allowed fractional predict-latency p50/p99 "
                         "increase vs the latest baseline carrying the "
                         "quantiles (default 0.50 — per-request walls "
                         "are noisier than throughput)")
    ap.add_argument("--staleness-slack", type=float,
                    default=DEFAULT_STALENESS_SLACK,
                    help="allowed absolute increase of the lrb-stream "
                         "model-staleness p99 lag in windows vs the "
                         "latest baseline carrying it (default 1.0)")
    ap.add_argument("--schema-only", action="store_true",
                    help="validate the fresh artifact's shape only "
                         "(quick runs are not comparable to the "
                         "full-size trajectory)")
    args = ap.parse_args(argv)

    try:
        fresh = load_bench(args.fresh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read {args.fresh}: {e}", file=sys.stderr)
        return 2
    if fresh.get("schema") == MULTICHIP_SCALING_SCHEMA:
        # scaling-curve artifact (MULTICHIP_r07+): self-contained
        # verdict, no trajectory comparison
        schema, regressions, notes = check_multichip_scaling(fresh)
        for p in schema:
            print(f"SCHEMA: {p}", file=sys.stderr)
        if schema:
            return 2
        for note in notes:
            print(f"NOTE: {note}")
        for p in regressions:
            print(f"REGRESSION (scaling): {p}", file=sys.stderr)
        if regressions:
            return 1
        worlds = [p["world"] for p in fresh["points"]]
        print(f"pass: multichip scaling curve over worlds {worlds}, "
              f"model parity bit-identical, autoscale reshards="
              f"{fresh['autoscale']['reshard_total']}")
        return 0
    if fresh.get("schema") == MULTICHIP_DRILL_SCHEMA:
        # elastic-drill artifact (MULTICHIP_r06+): self-contained
        # verdict, no trajectory comparison
        schema, regressions, notes = check_multichip_drill(fresh)
        for p in schema:
            print(f"SCHEMA: {p}", file=sys.stderr)
        if schema:
            return 2
        for note in notes:
            print(f"NOTE: {note}")
        for p in regressions:
            print(f"REGRESSION (drill): {p}", file=sys.stderr)
        if regressions:
            return 1
        ws = fresh["world_sizes"]
        print(f"pass: elastic drill {ws['train']}->{ws['resume']} "
              f"processes, resume from iteration "
              f"{fresh['resume']['from_iteration']}, model parity "
              f"bit-identical")
        return 0
    problems = check_schema(fresh)
    if problems:
        for p in problems:
            print(f"SCHEMA: {p}", file=sys.stderr)
        return 2
    for note in field_notes(fresh):
        print(f"NOTE: {note}")
    # fresh-only measured-parity assertions: a run that measured the
    # reference and missed the AUC ceiling fails regardless of the
    # trajectory (there is nothing to walk back to — the miss is a
    # fact of this run). Checked BEFORE the --schema-only early
    # return: quick-shape parity runs are metric-refused against the
    # full-size trajectory, so schema-only is exactly the mode that
    # validates them — it must not wave a recorded quality miss
    # through.
    quality = parity_quality_problems(fresh)
    if quality:
        for p in quality:
            print(f"REGRESSION (self): {p}", file=sys.stderr)
        return 1
    if args.schema_only:
        print(f"schema ok: {args.fresh} "
              f"({fresh['value']:g} {fresh['unit']})")
        return 0

    points = trajectory(args.baseline_dir)
    if not points:
        print(f"no BENCH_r*.json under {args.baseline_dir}",
              file=sys.stderr)
        return 2
    # shape-aware baseline selection: gate against the NEWEST
    # eligible point whose metric string (the workload shape) matches
    # the fresh run's. Points flagged "baseline": false are
    # ledger-only (a quick-shape parity snapshot must not become the
    # headline floor, nor silently absorb a full-size comparison).
    # No same-shape eligible point = the refusal path: compare()
    # against the newest eligible point returns "not comparable",
    # exit 2 — a quick run is refused against a full-size trajectory
    # instead of "passing" a meaningless comparison.
    loaded = [(p, load_bench(p)) for p in points]
    eligible = [(p, d) for p, d in loaded
                if d.get("baseline") is not False]
    if not eligible:
        print(f"no eligible baseline (every BENCH_r*.json under "
              f"{args.baseline_dir} is flagged \"baseline\": false)",
              file=sys.stderr)
        return 2
    matching = [(p, d) for p, d in eligible
                if d.get("metric") == fresh.get("metric")]
    base_path, baseline = (matching or eligible)[-1]
    baseline_name = os.path.basename(base_path)
    problems = compare(fresh, baseline, args.throughput_tol,
                       args.auc_tol, args.latency_tol,
                       args.staleness_slack)
    # the lrb-stream fields gate against the LATEST point CARRYING
    # them comparably: when the newest point predates the stream
    # bench (or carries a different stream shape), walk back for a
    # same-workload comparable point — including when the FRESH run
    # lost the section (the walk-back is exactly what catches that
    # against an older carrier; cross-workload refusal above still
    # wins — a refused comparison never reaches here)
    if not problems and not _stream_comparable(fresh, baseline):
        for p, cand in reversed(matching[:-1]):
            if _stream_comparable(fresh, cand):
                got = _compare_lrb_stream(fresh, cand,
                                          args.throughput_tol,
                                          args.staleness_slack)
                if got:
                    problems = got
                    baseline_name = os.path.basename(p)
                break
    # same walk-back for the fleet section: gate against the latest
    # same-workload point CARRYING a comparable fleet shape
    if not problems and not _fleet_comparable(fresh, baseline):
        for p, cand in reversed(matching[:-1]):
            if _fleet_comparable(fresh, cand):
                got = _compare_fleet(fresh, cand,
                                     args.throughput_tol,
                                     args.latency_tol)
                if got:
                    problems = got
                    baseline_name = os.path.basename(p)
                break
    # same walk-back for the parity section: gate the exact-tier floor
    # against the latest same-workload point CARRYING a comparable
    # parity block (newer points that predate it gate nothing)
    if not problems and not _parity_comparable(fresh, baseline):
        for p, cand in reversed(matching[:-1]):
            if _parity_comparable(fresh, cand):
                got = _compare_parity(fresh, cand,
                                      args.throughput_tol)
                if got:
                    problems = got
                    baseline_name = os.path.basename(p)
                break
    if problems:
        for p in problems:
            print(f"REGRESSION vs {baseline_name}: {p}",
                  file=sys.stderr)
        return 1 if not problems[0].startswith("not comparable") else 2
    print(f"pass: {fresh['value']:g} {fresh['unit']} vs "
          f"{baseline['value']:g} in {baseline_name} "
          f"(tol {args.throughput_tol:.0%}), test AUC "
          f"{fresh.get('test_auc')} vs {baseline.get('test_auc')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
