"""Bench-regression gate: compare a fresh bench JSON against the
repo's BENCH_r0x trajectory.

The perf ledger lives in-repo as BENCH_r01..r0N snapshots (each the
driver's wrapper around one ``python bench.py`` run); until now a
throughput or quality regression only surfaced when a reviewer eyeballed
the numbers. This tool makes the comparison mechanical:

- **throughput**: the fresh run's ``value`` (M row-iters/s) must be
  within ``--throughput-tol`` (default 20%, measurement noise on shared
  hosts) of the LATEST trajectory point;
- **quality**: the fresh run's test AUC must be no more than
  ``--auc-tol`` (default 2e-3) below the latest baseline's (parsed from
  the wrapper's stderr tail when the JSON predates the in-line field);
- **serving latency**: the fresh run's ``predict_latency`` p50/p99 must
  be within ``--latency-tol`` (default 50% — per-request walls on
  shared hosts are far noisier than throughput) of the latest baseline
  that CARRIES the quantiles; trajectory points predating the field are
  skipped, never treated as a zero-latency baseline;
- **comparability**: the bench ``metric`` string embeds the workload
  shape (rows x features, leaves, bins, iters, chips) — a quick run is
  refused against a full-size baseline instead of "passing" a
  meaningless comparison (``--schema-only`` skips the trajectory and
  just validates the fresh artifact's shape, including the
  predict-latency quantiles).

Standalone:  ``python tools/check_bench_regression.py fresh.json``
(exit 0 pass / 1 regression / 2 schema-or-usage error); also importable
— tests/test_bench_regression.py drives ``compare``/``check_schema``
directly and a slow-marked test runs the real ``bench.py --quick``
through ``--schema-only``.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

DEFAULT_THROUGHPUT_TOL = 0.20
DEFAULT_AUC_TOL = 2e-3
DEFAULT_LATENCY_TOL = 0.50

# the wrapper's stderr tail carries the AUC line for trajectory points
# that predate the in-JSON train_auc/test_auc fields
_TAIL_AUC_RE = re.compile(
    r"train-AUC=(?P<train>[0-9.]+)\s+test-AUC=(?P<test>[0-9.]+)")


def load_bench(doc) -> dict:
    """Normalize a bench artifact — either the raw JSON line bench.py
    prints, or a BENCH_r0x wrapper ({"parsed": ..., "tail": ...}) — to
    one flat dict with metric/value/unit and (when recoverable)
    train_auc/test_auc."""
    if isinstance(doc, str):
        with open(doc) as fh:
            doc = json.load(fh)
    out = dict(doc.get("parsed") or doc)
    tail = doc.get("tail", "")
    if tail and ("test_auc" not in out or out.get("test_auc") is None):
        m = _TAIL_AUC_RE.search(tail)
        if m:
            out.setdefault("train_auc", float(m.group("train")))
            out["test_auc"] = float(m.group("test"))
    return out


def trajectory(baseline_dir: str) -> List[str]:
    """BENCH_r*.json paths in trajectory order — NUMERIC run index
    (lexicographic order would park r100 before r11 forever)."""

    def run_index(path):
        m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
        return (int(m.group(1)) if m else 0, path)

    return sorted(glob.glob(os.path.join(baseline_dir, "BENCH_r*.json")),
                  key=run_index)


def check_schema(fresh: dict) -> List[str]:
    """Shape problems in a (normalized) fresh bench artifact."""
    problems = []
    if not isinstance(fresh.get("value"), (int, float)):
        problems.append("missing numeric 'value' (M row-iters/s)")
    if fresh.get("unit") != "M row-iters/s":
        problems.append(f"unexpected unit {fresh.get('unit')!r}")
    if not isinstance(fresh.get("metric"), str):
        problems.append("missing 'metric' workload descriptor")
    lat = fresh.get("predict_latency")
    if lat is not None:
        if not isinstance(lat, dict):
            problems.append(
                f"predict_latency is {type(lat).__name__}, not a dict")
        else:
            for q in ("p50_ms", "p95_ms", "p99_ms"):
                if not isinstance(lat.get(q), (int, float)):
                    problems.append(f"predict_latency.{q} missing/null")
    return problems


def field_notes(doc: dict) -> List[str]:
    """Informational notes for the fault-tolerance fields newer bench
    JSONs may carry (``degraded_windows``, ``checkpoint`` meta) —
    REPORTED, never a crash or a gate: a degraded serving window is an
    operator signal, not a perf regression, and an old tool version
    must keep working against new artifacts."""
    notes = []
    dw = doc.get("degraded_windows")
    if dw is not None:
        if isinstance(dw, (int, float)) and not isinstance(dw, bool):
            if dw:
                notes.append(f"{int(dw)} degraded window(s) reported "
                             f"by this run")
        else:
            notes.append(f"degraded_windows present but "
                         f"{type(dw).__name__}, not numeric — ignored")
    ck = doc.get("checkpoint")
    if ck is not None:
        if isinstance(ck, dict):
            keys = ", ".join(f"{k}={ck[k]}" for k in sorted(ck)[:4])
            notes.append(f"checkpoint meta present ({keys})")
        else:
            notes.append(f"checkpoint meta present but "
                         f"{type(ck).__name__}, not an object — ignored")
    return notes


def compare(fresh: dict, baseline: dict,
            throughput_tol: float = DEFAULT_THROUGHPUT_TOL,
            auc_tol: float = DEFAULT_AUC_TOL,
            latency_tol: float = DEFAULT_LATENCY_TOL) -> List[str]:
    """Regression problems of ``fresh`` vs one ``baseline`` point
    (both normalized); empty list == pass. Refuses cross-workload
    comparisons (the metric strings embed the shape)."""
    if fresh.get("metric") != baseline.get("metric"):
        return [f"not comparable: workload {fresh.get('metric')!r} "
                f"vs baseline {baseline.get('metric')!r}"]
    problems = []
    bv, fv = baseline.get("value"), fresh.get("value")
    if isinstance(bv, (int, float)) and isinstance(fv, (int, float)):
        floor = (1.0 - throughput_tol) * bv
        if fv < floor:
            problems.append(
                f"throughput regression: {fv:g} M row-iters/s < "
                f"{floor:g} (baseline {bv:g} - {throughput_tol:.0%})")
    ba, fa = baseline.get("test_auc"), fresh.get("test_auc")
    if isinstance(ba, (int, float)) and isinstance(fa, (int, float)):
        if fa < ba - auc_tol:
            problems.append(
                f"quality regression: test AUC {fa:.5f} < baseline "
                f"{ba:.5f} - {auc_tol:g}")
    elif isinstance(ba, (int, float)):
        problems.append("fresh run carries no test_auc to compare")
    problems += _compare_latency(fresh, baseline, latency_tol)
    return problems


def _compare_latency(fresh: dict, baseline: dict,
                     latency_tol: float) -> List[str]:
    """predict_latency p50/p99 gate. Only fires when the BASELINE
    carries numeric quantiles (points predating the field gate
    nothing); a fresh run that LOST the field against a baseline that
    has it is itself a problem — the serving ledger must not silently
    disappear."""
    blat = baseline.get("predict_latency")
    if not isinstance(blat, dict):
        return []
    flat = fresh.get("predict_latency")
    problems = []
    for q in ("p50_ms", "p99_ms"):
        bq = blat.get(q)
        if not isinstance(bq, (int, float)):
            continue
        fq = (flat or {}).get(q) if isinstance(flat, dict) else None
        if not isinstance(fq, (int, float)):
            problems.append(
                f"fresh run carries no predict_latency.{q} to compare")
            continue
        ceil = (1.0 + latency_tol) * bq
        if fq > ceil:
            problems.append(
                f"latency regression: predict {q} {fq:g} ms > "
                f"{ceil:g} (baseline {bq:g} + {latency_tol:.0%})")
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate a fresh bench JSON against the BENCH_r0x "
                    "trajectory.")
    ap.add_argument("fresh", help="fresh bench JSON (bench.py output "
                                  "line saved to a file, or a BENCH_r0x"
                                  "-style wrapper)")
    ap.add_argument("--baseline-dir",
                    default=os.path.join(os.path.dirname(__file__),
                                         os.pardir),
                    help="directory holding BENCH_r*.json (default: "
                         "repo root)")
    ap.add_argument("--throughput-tol", type=float,
                    default=DEFAULT_THROUGHPUT_TOL,
                    help="allowed fractional throughput drop vs the "
                         "latest baseline (default 0.20)")
    ap.add_argument("--auc-tol", type=float, default=DEFAULT_AUC_TOL,
                    help="allowed absolute test-AUC drop (default 2e-3)")
    ap.add_argument("--latency-tol", type=float,
                    default=DEFAULT_LATENCY_TOL,
                    help="allowed fractional predict-latency p50/p99 "
                         "increase vs the latest baseline carrying the "
                         "quantiles (default 0.50 — per-request walls "
                         "are noisier than throughput)")
    ap.add_argument("--schema-only", action="store_true",
                    help="validate the fresh artifact's shape only "
                         "(quick runs are not comparable to the "
                         "full-size trajectory)")
    args = ap.parse_args(argv)

    try:
        fresh = load_bench(args.fresh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"cannot read {args.fresh}: {e}", file=sys.stderr)
        return 2
    problems = check_schema(fresh)
    if problems:
        for p in problems:
            print(f"SCHEMA: {p}", file=sys.stderr)
        return 2
    for note in field_notes(fresh):
        print(f"NOTE: {note}")
    if args.schema_only:
        print(f"schema ok: {args.fresh} "
              f"({fresh['value']:g} {fresh['unit']})")
        return 0

    points = trajectory(args.baseline_dir)
    if not points:
        print(f"no BENCH_r*.json under {args.baseline_dir}",
              file=sys.stderr)
        return 2
    baseline = load_bench(points[-1])
    problems = compare(fresh, baseline, args.throughput_tol,
                       args.auc_tol, args.latency_tol)
    if problems:
        for p in problems:
            print(f"REGRESSION vs {os.path.basename(points[-1])}: {p}",
                  file=sys.stderr)
        return 1 if not problems[0].startswith("not comparable") else 2
    print(f"pass: {fresh['value']:g} {fresh['unit']} vs "
          f"{baseline['value']:g} in {os.path.basename(points[-1])} "
          f"(tol {args.throughput_tol:.0%}), test AUC "
          f"{fresh.get('test_auc')} vs {baseline.get('test_auc')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
