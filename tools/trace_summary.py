"""Render a trace / flight-dump / request-log file for humans: a
per-thread span table and the top-N slow requests.

The black box half of the observability stack writes three machine
artifacts — Chrome trace-event JSON (obs/trace.py, ``tpu_trace``),
flight-recorder postmortem bundles (obs/flight.py), and the
request-log JSONL (obs/reqlog.py, ``tpu_reqlog``). This tool is the
human side: point it at ANY of the three (the format is sniffed from
the content, never the file name) and it prints

- a **per-thread span table** — thread name, span name, call count,
  total/mean/max milliseconds, sorted hottest-first — the "what was
  every thread doing" answer without loading Perfetto;
- the **top-N slow requests** — from request wide events when the
  input carries them (reqlog files, flight dumps), else from
  ``serve/request``-class spans whose args carry ``req_id`` — with
  window / rows / serve bucket / model generation where known;
- for flight dumps: the trigger history and the dump's reason line.

**Cross-rank merge** (``--merge FILE...``): N per-rank artifacts —
trace files, flight dumps, or ONE incident bundle
(obs/incident.py, which embeds every rank's flight dump) — render on
one aligned timeline. Each file's event ``ts`` values are microseconds
since that PROCESS's tracer epoch; the merge aligns them with the
clock-alignment rule (Design.md §6e): a trace file's wall anchor is
``otherData.started_unix``, a flight dump's is ``created_unix -
max(ts)/1e6``, and every event shifts by ``(anchor - min anchor)``.
The merged span table and instant timeline carry a rank column.

Standalone: ``python tools/trace_summary.py FILE [--top N]`` or
``python tools/trace_summary.py --merge FILE [FILE...]``
(exit 0 ok / 2 unreadable-or-unrecognized). Importable — the unit
tests drive ``load_artifact``/``span_table``/``top_requests``/
``render``/``merge_entries``/``render_merged`` directly.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

# span names that represent one serving request dispatch (the spans
# fallback for top-N when no request wide events are present)
REQUEST_SPAN_NAMES = ("serve/request", "predict/stacked")


def load_artifact(path: str) -> Tuple[str, dict]:
    """Sniff and load one artifact -> (kind, normalized doc) where
    kind is "trace" | "flight" | "reqlog" and doc always carries
    ``events`` (span/instant dicts) and ``records`` (wide events).
    Raises ValueError for unrecognized content."""
    with open(path) as fh:
        doc = None
        try:
            doc = json.load(fh)
        except json.JSONDecodeError:
            fh.seek(0)          # not ONE document: try JSONL below
        if doc is not None:
            if isinstance(doc, dict) and "traceEvents" in doc:
                return "trace", {"events": doc["traceEvents"],
                                 "records": [],
                                 "meta": doc.get("otherData", {})}
            if (isinstance(doc, dict)
                    and doc.get("schema") == "lightgbm-tpu/flight"):
                return "flight", {"events": doc.get("spans", []),
                                  "records": doc.get("reqlog", []),
                                  "meta": {
                                      "reason": doc.get("reason"),
                                      "context": doc.get("context"),
                                      "identity": doc.get("identity"),
                                      "created_unix": doc.get(
                                          "created_unix"),
                                      "triggers": doc.get("triggers",
                                                          []),
                                      "log_lines": doc.get("log_lines",
                                                           [])}}
            if (isinstance(doc, dict)
                    and doc.get("schema") == "lightgbm-tpu/incident"):
                # the distributed incident bundle embeds every rank's
                # flight dumps; expose them for the merge path
                bundles = []
                for r, dumps in (doc.get("ranks") or {}).items():
                    for d in dumps:
                        b = d.get("bundle") or {}
                        bundles.append((int(r), d.get("path", ""), b))
                return "incident", {"events": [], "records": [],
                                    "bundles": bundles,
                                    "meta": {
                                        "reason": doc.get("reason"),
                                        "dead_ranks": doc.get(
                                            "dead_ranks", []),
                                        "identity": doc.get("identity"),
                                        "created_unix": doc.get(
                                            "created_unix"),
                                        "digest_ranks": sorted(
                                            (doc.get("digests")
                                             or {}).keys())}}
            raise ValueError(f"{path}: JSON but neither a trace "
                             f"(traceEvents) nor a flight dump / "
                             f"incident bundle (schema="
                             f"lightgbm-tpu/flight|incident)")
        # JSONL: a request log (one wide event per line, optional
        # header record) — skip unparseable lines like lrb.py's
        # trace reader does
        records = []
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("kind") != "header":
                records.append(rec)
        if not records:
            raise ValueError(f"{path}: no recognizable records "
                             f"(want trace JSON, a flight dump, or "
                             f"reqlog JSONL)")
        return "reqlog", {"events": [], "records": records, "meta": {}}


def span_table(events: List[dict]) -> List[dict]:
    """Aggregate complete-events per (thread, span name) -> rows
    sorted by total duration desc. Thread names come from the ph:"M"
    thread_name metadata when present, else the numeric tid."""
    names = {}
    for ev in events:
        if (ev.get("ph") == "M" and ev.get("name") == "thread_name"
                and isinstance(ev.get("args"), dict)):
            names[ev.get("tid")] = ev["args"].get("name")
    agg = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        tid = ev.get("tid")
        key = (tid, ev.get("name"))
        row = agg.get(key)
        dur_ms = float(ev.get("dur", 0.0)) / 1000.0
        if row is None:
            agg[key] = {"thread": names.get(tid) or f"tid {tid}",
                        "span": ev.get("name"), "count": 1,
                        "total_ms": dur_ms, "max_ms": dur_ms}
        else:
            row["count"] += 1
            row["total_ms"] += dur_ms
            row["max_ms"] = max(row["max_ms"], dur_ms)
    rows = sorted(agg.values(), key=lambda r: -r["total_ms"])
    for r in rows:
        r["mean_ms"] = r["total_ms"] / r["count"]
    return rows


def top_requests(doc: dict, n: int = 10) -> List[dict]:
    """The N slowest requests: from request wide events when present
    (latency_ms, plus window/rows/bucket/model identity), else from
    request-class spans carrying args.req_id (dur -> latency)."""
    recs = [r for r in doc.get("records", [])
            if r.get("kind") == "request"
            and isinstance(r.get("latency_ms"), (int, float))]
    if recs:
        rows = [{k: r.get(k) for k in
                 ("req_id", "latency_ms", "path", "window", "rows",
                  "serve_bucket", "model_window", "staleness_windows")
                 if r.get(k) is not None} for r in recs]
        return sorted(rows, key=lambda r: -r["latency_ms"])[:n]
    rows = []
    for ev in doc.get("events", []):
        args = ev.get("args")
        if (ev.get("ph") == "X" and isinstance(args, dict)
                and "req_id" in args
                and ev.get("name") in REQUEST_SPAN_NAMES):
            row = {"req_id": args["req_id"],
                   "latency_ms": round(float(ev.get("dur", 0.0))
                                       / 1000.0, 3)}
            for k in ("window", "rows"):
                if k in args:
                    row[k] = args[k]
            rows.append(row)
    return sorted(rows, key=lambda r: -r["latency_ms"])[:n]


def _fmt_table(rows: List[dict], columns: List[Tuple[str, str]]) -> str:
    """Plain aligned text table: columns = [(key, heading)]."""
    def cell(r, k):
        v = r.get(k)
        if isinstance(v, float):
            return f"{v:.3f}"
        return "" if v is None else str(v)

    widths = [max(len(h), *(len(cell(r, k)) for r in rows))
              if rows else len(h) for k, h in columns]
    out = ["  ".join(h.ljust(w) for (_, h), w in zip(columns, widths))]
    out.append("  ".join("-" * w for w in widths))
    for r in rows:
        out.append("  ".join(cell(r, k).ljust(w)
                             for (k, _), w in zip(columns, widths)))
    return "\n".join(out)


def render(kind: str, doc: dict, top: int = 10) -> str:
    """The full human rendering of one loaded artifact."""
    parts = []
    meta = doc.get("meta", {})
    if kind == "flight":
        parts.append(f"flight dump: reason={meta.get('reason')} "
                     f"context={json.dumps(meta.get('context', {}))}")
        trigs = meta.get("triggers", [])
        if trigs:
            parts.append("triggers:")
            for t in trigs[-top:]:
                parts.append(f"  ts={t.get('ts')} {t.get('reason')}"
                             + (f" {json.dumps(t['context'])}"
                                if t.get("context") else ""))
        parts.append("")
    elif kind == "trace" and meta.get("dropped_events"):
        parts.append(f"(ring dropped {meta['dropped_events']} older "
                     f"events)")
        parts.append("")
    spans = span_table(doc.get("events", []))
    if spans:
        parts.append(f"per-thread span table ({len(spans)} rows, "
                     f"hottest first):")
        parts.append(_fmt_table(spans, [
            ("thread", "thread"), ("span", "span"),
            ("count", "count"), ("total_ms", "total_ms"),
            ("mean_ms", "mean_ms"), ("max_ms", "max_ms")]))
        parts.append("")
    reqs = top_requests(doc, top)
    if reqs:
        parts.append(f"top {len(reqs)} slow requests:")
        parts.append(_fmt_table(reqs, [
            ("req_id", "req_id"), ("latency_ms", "latency_ms"),
            ("path", "path"), ("window", "window"), ("rows", "rows"),
            ("serve_bucket", "bucket"),
            ("model_window", "model_win"),
            ("staleness_windows", "stale")]))
        parts.append("")
    windows = [r for r in doc.get("records", [])
               if r.get("kind") in ("window", "degraded_window")]
    if windows:
        parts.append(f"window records ({len(windows)}):")
        parts.append(_fmt_table(windows[-top:], [
            ("window", "window"), ("kind", "kind"),
            ("train_s", "train_s"), ("window_wall_s", "wall_s"),
            ("fp_rate", "fp"), ("fn_rate", "fn"),
            ("degrade_label", "degrade"),
            ("staleness_windows", "stale")]))
        parts.append("")
    if not spans and not reqs and not windows:
        parts.append("(no spans, requests or windows in this artifact)")
    return "\n".join(parts).rstrip() + "\n"


# -- cross-rank merge ---------------------------------------------------------


def _anchor_unix(kind: str, doc: dict) -> float:
    """One artifact's wall-clock anchor: the unix time its event
    ``ts=0`` corresponds to (the Design.md §6e clock-alignment rule).
    Trace files record it directly (``otherData.started_unix``); a
    flight dump's newest span landed ~at ``created_unix``, so its
    epoch is estimated as ``created_unix - max(ts)/1e6``. 0.0 when
    the artifact carries no wall clock (events then merge unshifted)."""
    meta = doc.get("meta") or {}
    if kind == "trace":
        su = meta.get("started_unix")
        if isinstance(su, (int, float)):
            return float(su)
    cu = meta.get("created_unix")
    if isinstance(cu, (int, float)):
        mx = max((float(e.get("ts", 0) or 0)
                  for e in doc.get("events", [])), default=0.0)
        return float(cu) - mx / 1e6
    return 0.0


def _rank_of_doc(doc: dict):
    """The rank an artifact belongs to: its identity stamp, else the
    first event arg that carries one, else None."""
    ident = (doc.get("meta") or {}).get("identity")
    if isinstance(ident, dict) and "machine_rank" in ident:
        return ident["machine_rank"]
    for ev in doc.get("events", []):
        a = ev.get("args")
        if isinstance(a, dict) and "rank" in a:
            return a["rank"]
    return None


def merge_entries(loaded: List[Tuple[str, str, dict]]) -> dict:
    """[(path, kind, doc)] -> one merged doc whose events carry
    ``rank`` in args and ``ts`` on a COMMON timeline (µs since the
    earliest anchor across the inputs). An incident bundle expands to
    its embedded per-rank flight dumps before merging."""
    flat: List[Tuple[str, str, dict, object]] = []
    for path, kind, doc in loaded:
        if kind == "incident":
            for r, bpath, bundle in doc.get("bundles", []):
                _k, bdoc = "flight", {
                    "events": bundle.get("spans", []),
                    "records": bundle.get("reqlog", []),
                    "meta": {"identity": bundle.get("identity"),
                             "created_unix": bundle.get("created_unix"),
                             "reason": bundle.get("reason")}}
                flat.append((bpath or f"{path}[rank {r}]", "flight",
                             bdoc, r))
        else:
            flat.append((path, kind, doc, _rank_of_doc(doc)))
    anchors = [_anchor_unix(k, d) for _p, k, d, _r in flat]
    known = [a for a in anchors if a > 0]
    t0 = min(known) if known else 0.0
    events: List[dict] = []
    records: List[dict] = []
    sources = []
    for (path, kind, doc, r), anchor in zip(flat, anchors):
        shift_us = (anchor - t0) * 1e6 if anchor > 0 else 0.0
        for ev in doc.get("events", []):
            ev = dict(ev)
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = float(ev["ts"]) + shift_us
            if r is not None:
                args = dict(ev.get("args") or {})
                args.setdefault("rank", r)
                ev["args"] = args
            events.append(ev)
        records.extend(doc.get("records", []))
        sources.append({"path": path, "kind": kind, "rank": r,
                        "anchor_unix": round(anchor, 3) if anchor
                        else None,
                        "events": len(doc.get("events", []))})
    events.sort(key=lambda e: float(e.get("ts", 0) or 0))
    return {"events": events, "records": records,
            "meta": {"sources": sources, "t0_unix": round(t0, 3)}}


def render_merged(merged: dict, top: int = 10) -> str:
    """The cross-rank rendering: sources, a span table keyed by
    (rank, thread, span), and the aligned instant timeline."""
    parts = []
    parts.append(f"merged timeline over "
                 f"{len(merged['meta']['sources'])} artifact(s), "
                 f"t0={merged['meta']['t0_unix']}:")
    for s in merged["meta"]["sources"]:
        parts.append(f"  rank={s['rank']} kind={s['kind']} "
                     f"events={s['events']} "
                     f"anchor={s['anchor_unix']} {s['path']}")
    parts.append("")
    # per-(rank, thread) span table: reuse span_table per rank so the
    # thread-name metadata of one rank never relabels another's tids
    by_rank = {}
    for ev in merged.get("events", []):
        r = (ev.get("args") or {}).get("rank")
        by_rank.setdefault(r, []).append(ev)
    rows = []
    for r in sorted(by_rank, key=lambda x: (x is None, x)):
        for row in span_table(by_rank[r]):
            row = dict(row)
            row["rank"] = r
            rows.append(row)
    rows.sort(key=lambda r: -r["total_ms"])
    if rows:
        parts.append(f"cross-rank span table ({len(rows)} rows, "
                     f"hottest first):")
        parts.append(_fmt_table(rows, [
            ("rank", "rank"), ("thread", "thread"), ("span", "span"),
            ("count", "count"), ("total_ms", "total_ms"),
            ("mean_ms", "mean_ms"), ("max_ms", "max_ms")]))
        parts.append("")
    instants = [ev for ev in merged.get("events", [])
                if ev.get("ph") in ("i", "I")]
    if instants:
        parts.append(f"aligned instants ({len(instants)}; newest "
                     f"{min(len(instants), max(top, 1) * 2)}):")
        irows = []
        for ev in instants[-max(top, 1) * 2:]:
            args = dict(ev.get("args") or {})
            r = args.pop("rank", None)
            irows.append({
                "t_s": round(float(ev.get("ts", 0) or 0) / 1e6, 3),
                "rank": r, "name": ev.get("name"),
                "args": json.dumps(args, sort_keys=True) if args
                else ""})
        parts.append(_fmt_table(irows, [
            ("t_s", "t_s"), ("rank", "rank"), ("name", "name"),
            ("args", "args")]))
        parts.append("")
    if not rows and not instants:
        parts.append("(no spans or instants across the inputs)")
    return "\n".join(parts).rstrip() + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a trace / flight dump / request log: "
                    "per-thread span table + top-N slow requests. "
                    "--merge renders N per-rank artifacts (or one "
                    "incident bundle) on one aligned timeline.")
    ap.add_argument("paths", nargs="+",
                    help="trace JSON (tpu_trace), flight dump "
                         "(flight_*.json), incident bundle "
                         "(incident_*.json) or reqlog JSONL "
                         "(tpu_reqlog) — format is sniffed")
    ap.add_argument("--merge", action="store_true",
                    help="merge all inputs onto one rank-aware "
                         "aligned timeline")
    ap.add_argument("--top", type=int, default=10,
                    help="slow requests / tail rows shown (default 10)")
    args = ap.parse_args(argv)
    loaded = []
    for path in args.paths:
        try:
            kind, doc = load_artifact(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"cannot summarize {path}: {e}", file=sys.stderr)
            return 2
        loaded.append((path, kind, doc))
    if args.merge or len(loaded) > 1 or loaded[0][1] == "incident":
        merged = merge_entries(loaded)
        print(f"# merged: {', '.join(p for p, _k, _d in loaded)}")
        print(render_merged(merged, top=max(args.top, 1)))
        return 0
    path, kind, doc = loaded[0]
    print(f"# {path}: {kind} artifact")
    print(render(kind, doc, top=max(args.top, 1)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
