// Native text parser for lightgbm_tpu.
//
// TPU-native counterpart of the reference's C++ parser/TextReader stack
// (reference: src/io/parser.cpp, include/LightGBM/utils/text_reader.h):
// the JAX compute path needs no native code, but the IO runtime around
// it follows the reference in being C++ — row-major tokenization of
// CSV/TSV/LibSVM into a dense float64 matrix at memory bandwidth
// instead of Python string speed. Loaded via ctypes
// (lightgbm_tpu/io/native.py); the pure-Python parser remains the
// fallback and the semantic oracle.
//
// Build: g++ -O3 -shared -fPIC -o _fast_parser.so fast_parser.cpp
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <cctype>
#include <thread>
#include <vector>
#include <string>
#include <locale.h>

namespace {

struct Lines {
  std::vector<const char*> begin;
  std::vector<const char*> end;
  std::string storage;
};

// read the file and index data lines (skip blanks and '#' comments,
// optionally the header line)
bool load_lines(const char* path, int skip_header, Lines* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->storage.resize(sz);
  if (sz > 0 && std::fread(&out->storage[0], 1, sz, f) != (size_t)sz) {
    std::fclose(f);
    return false;
  }
  std::fclose(f);
  const char* p = out->storage.data();
  const char* endp = p + sz;
  bool header_skipped = skip_header == 0;
  while (p < endp) {
    const char* eol = (const char*)memchr(p, '\n', endp - p);
    if (!eol) eol = endp;
    const char* e = eol;
    while (e > p && (e[-1] == '\r' || e[-1] == ' ')) --e;
    const char* b = p;
    while (b < e && (*b == ' ' || *b == '\t')) ++b;
    if (b < e && *b != '#') {
      if (!header_skipped) {
        header_skipped = true;
      } else {
        out->begin.push_back(p);
        out->end.push_back(e);
      }
    } else if (b < e) {
      // comment line: never a header
    } else if (!header_skipped && b < e) {
      header_skipped = true;
    }
    p = eol + 1;
  }
  return true;
}

inline bool is_na_token(const char* b, const char* e) {
  size_t n = e - b;
  if (n == 0) return true;
  auto eq = [&](const char* s) {
    if (std::strlen(s) != n) return false;
    for (size_t i = 0; i < n; ++i)
      if (std::tolower(b[i]) != s[i]) return false;
    return true;
  };
  return eq("na") || eq("nan") || eq("null") || eq("none") || eq("?");
}

// locale-independent strtod: a host app setting LC_NUMERIC must not
// change how training data parses (the reference's Atof is likewise
// locale-free)
inline double c_strtod(const char* b, char** endp) {
  static locale_t c_loc = newlocale(LC_NUMERIC_MASK, "C", (locale_t)0);
  return strtod_l(b, endp, c_loc);
}

inline double tok_to_double(const char* b, const char* e) {
  if (is_na_token(b, e)) return NAN;
  return c_strtod(b, nullptr);
}

int count_cols(const char* b, const char* e, char delim) {
  int cols = 1;
  for (const char* p = b; p < e; ++p)
    if (*p == delim) ++cols;
  return cols;
}

}  // namespace

extern "C" {

// First pass: rows, columns, detected format (0 tsv, 1 csv, 2 libsvm).
// For libsvm, out_cols is max feature index + 1 over the whole file
// (caller may widen it with the label handling).
int lgbm_tpu_parse_count(const char* path, int skip_header,
                         int64_t* out_rows, int32_t* out_cols,
                         int32_t* out_format) {
  Lines ln;
  if (!load_lines(path, skip_header, &ln)) return 1;
  *out_rows = (int64_t)ln.begin.size();
  if (ln.begin.empty()) { *out_cols = 0; *out_format = 0; return 0; }
  const char* b = ln.begin[0];
  const char* e = ln.end[0];
  int colon = 0, tab = 0, comma = 0;
  for (const char* p = b; p < e; ++p) {
    colon += *p == ':';
    tab += *p == '\t';
    comma += *p == ',';
  }
  if (colon > 0) {
    *out_format = 2;
    int32_t maxidx = -1;
    for (size_t i = 0; i < ln.begin.size(); ++i) {
      for (const char* p = ln.begin[i]; p < ln.end[i]; ++p) {
        if (*p == ':') {
          const char* q = p;
          while (q > ln.begin[i] && q[-1] >= '0' && q[-1] <= '9') --q;
          int32_t idx = (int32_t)std::strtol(q, nullptr, 10);
          if (idx > maxidx) maxidx = idx;
        }
      }
    }
    *out_cols = maxidx + 1;
  } else if (tab > 0) {
    *out_format = 0;
    *out_cols = count_cols(b, e, '\t');
  } else if (comma > 0) {
    *out_format = 1;
    *out_cols = count_cols(b, e, ',');
  } else {
    *out_format = 0;
    *out_cols = 1;
  }
  return 0;
}

// Second pass: fill values [rows, cols] row-major and labels [rows].
// label_idx < 0 = no label column. cols counts FEATURE columns only.
int lgbm_tpu_parse_fill(const char* path, int skip_header,
                        int32_t label_idx, int32_t format,
                        double* values, float* labels,
                        int64_t rows, int32_t cols) {
  Lines ln;
  if (!load_lines(path, skip_header, &ln)) return 1;
  if ((int64_t)ln.begin.size() != rows) return 2;
  char delim = format == 1 ? ',' : '\t';
  if (format == 2) {
    std::memset(values, 0, sizeof(double) * rows * cols);
    for (int64_t i = 0; i < rows; ++i) {
      const char* p = ln.begin[i];
      const char* e = ln.end[i];
      bool first = true;
      while (p < e) {
        while (p < e && (*p == ' ' || *p == '\t')) ++p;
        const char* t = p;
        while (p < e && *p != ' ' && *p != '\t') ++p;
        if (t == p) break;
        const char* c = (const char*)memchr(t, ':', p - t);
        if (!c) {
          if (first && label_idx >= 0) labels[i] = (float)tok_to_double(t, p);
        } else {
          long idx = std::strtol(t, nullptr, 10);
          if (idx >= 0 && idx < cols)
            values[i * cols + idx] = c_strtod(c + 1, nullptr);
        }
        first = false;
      }
    }
    return 0;
  }
  int32_t expect_cols = cols + (label_idx >= 0 ? 1 : 0);
  for (int64_t i = 0; i < rows; ++i) {
    const char* p = ln.begin[i];
    const char* e = ln.end[i];
    int32_t col = 0, feat = 0;
    while (p <= e) {
      const char* t = p;
      while (p < e && *p != delim) ++p;
      if (col == label_idx) {
        if (labels) labels[i] = (float)tok_to_double(t, p);
      } else if (feat < cols) {
        values[i * cols + feat] = tok_to_double(t, p);
        ++feat;
      }
      ++col;
      if (p >= e) break;
      ++p;  // skip delimiter
    }
    // ragged rows (more or fewer columns than the first line): refuse
    // so the caller falls back to the python parser's pad-and-warn
    if (col != expect_cols) return 3;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Bulk value->bin mapping (BinMapper::ValueToBin over whole columns).
//
// numpy's per-column searchsorted pays a float64 copy plus ~95ns/value
// of branchy interpreter-driven binary search — ~45s for the 11M x 28
// HIGGS shape. Here: threads over columns, cache-resident bounds,
// std::lower_bound on doubles (the reference's comparison domain, so
// bins are bit-identical for both f32 and f64 inputs).
//
// X: row-major [n, ncol_total], float64 (xdtype=0) or float32 (1).
// col_idx[f]: source column of used feature f.  bounds/bnd_off:
// concatenated per-feature upper-bound arrays.  r_len[f]: searchsorted
// range (num_bin-1, minus 1 more when NaN has its own bin).
// nan_bin[f]: bin for NaN values, or -1 to map NaN like 0.0
// (MissingType::None/Zero — value_to_bin parity, io/binning.py).
// out: row-major [n, f_used] uint8.
extern "C" int lgbm_tpu_bin_columns(
    const void* X, int64_t n, int32_t ncol_total, int32_t xdtype,
    const int32_t* col_idx, int32_t f_used,
    const double* bounds, const int64_t* bnd_off,
    const int32_t* r_len, const int32_t* nan_bin,
    uint8_t* out, int32_t nthreads) {
  if (n <= 0 || f_used <= 0) return 0;
  auto run_col = [&](int32_t f) {
    const double* b = bounds + bnd_off[f];
    const int32_t r = r_len[f];
    const int32_t nb = nan_bin[f];
    const int64_t src = col_idx[f];
    uint8_t* o = out + f;
    if (xdtype == 1) {
      const float* xp = (const float*)X + src;
      for (int64_t i = 0; i < n; ++i) {
        double v = (double)xp[i * ncol_total];
        if (std::isnan(v)) {
          if (nb >= 0) { o[i * f_used] = (uint8_t)nb; continue; }
          v = 0.0;
        }
        o[i * f_used] =
            (uint8_t)(std::lower_bound(b, b + r, v) - b);
      }
    } else {
      const double* xp = (const double*)X + src;
      for (int64_t i = 0; i < n; ++i) {
        double v = xp[i * ncol_total];
        if (std::isnan(v)) {
          if (nb >= 0) { o[i * f_used] = (uint8_t)nb; continue; }
          v = 0.0;
        }
        o[i * f_used] =
            (uint8_t)(std::lower_bound(b, b + r, v) - b);
      }
    }
  };
  if (nthreads <= 1 || f_used == 1) {
    for (int32_t f = 0; f < f_used; ++f) run_col(f);
    return 0;
  }
  std::vector<std::thread> pool;
  std::atomic<int32_t> next(0);
  int32_t nt = nthreads < f_used ? nthreads : f_used;
  for (int32_t t = 0; t < nt; ++t) {
    pool.emplace_back([&]() {
      for (;;) {
        int32_t f = next.fetch_add(1);
        if (f >= f_used) return;
        run_col(f);
      }
    });
  }
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"
