// Linkable C ABI for lightgbm_tpu — an embedded-CPython shim.
//
// The reference exposes its engine as `extern "C"` entry points in
// src/c_api.cpp (1568 LoC, include/LightGBM/c_api.h) that foreign
// runtimes (the fork's src/test.cpp, SWIG, mmlspark) link against.
// Here the engine is the Python/JAX package, so this .so hosts a
// CPython interpreter and forwards each export to
// lightgbm_tpu/c_embed.py, which wraps the caller's raw buffers
// zero-copy with numpy and calls the same capi.py shim the Python
// package uses. Signatures mirror the fork's c_api.h exactly —
// including its C++ `std::unordered_map` parameter forms — so
// src/test.cpp-style drivers compile and link unchanged.
//
// Build (see tests/test_c_abi.py):
//   g++ -O2 -shared -fPIC c_api_embed.cpp -o liblightgbm_tpu.so \
//       $(python3-config --includes) $(python3-config --ldflags --embed)
//
// The embedding process must be able to `import lightgbm_tpu`
// (PYTHONPATH or installed package).

#include <Python.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <mutex>
#include <string>
#include <unordered_map>

#define LIGHTGBM_C_EXPORT extern "C" __attribute__((visibility("default")))

typedef void* DatasetHandle;
typedef void* BoosterHandle;

namespace {

std::mutex g_init_mutex;
// lightgbm_tpu.c_embed module; atomic so the lock-free fast path is a
// well-defined acquire read against the GIL-held publishing store
std::atomic<PyObject*> g_glue{nullptr};
thread_local std::string g_last_error = "everything is fine";

bool ensure_python() {
  // fast path: a stale null just takes the slow path
  if (g_glue.load(std::memory_order_acquire) != nullptr) return true;
  {
    // interpreter bootstrap only — do NOT hold this mutex while
    // acquiring the GIL, or a GIL-holding caller racing first-time
    // init deadlocks (lock-order inversion)
    std::lock_guard<std::mutex> lock(g_init_mutex);
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the init-time GIL or every later PyGILState_Ensure
      // from another thread (thread-pool consumers) deadlocks
      PyEval_SaveThread();
    }
  }
  PyGILState_STATE st = PyGILState_Ensure();
  if (g_glue.load(std::memory_order_relaxed) == nullptr) {
    // re-check under the GIL (it serializes importers)
    PyObject* mod = PyImport_ImportModule("lightgbm_tpu.c_embed");
    if (mod == nullptr) {
      PyObject *t, *v, *tb;
      PyErr_Fetch(&t, &v, &tb);
      PyObject* s = v ? PyObject_Str(v) : nullptr;
      const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
      if (msg == nullptr) {
        PyErr_Clear();           // AsUTF8 can fail on odd messages
        msg = "unknown";
      }
      g_last_error =
          std::string("cannot import lightgbm_tpu.c_embed: ") + msg;
      Py_XDECREF(s); Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
    } else {
      g_glue.store(mod, std::memory_order_release);
    }
  }
  PyGILState_Release(st);
  return g_glue.load(std::memory_order_acquire) != nullptr;
}

void capture_error() {
  PyObject *t, *v, *tb;
  PyErr_Fetch(&t, &v, &tb);
  PyObject* s = v ? PyObject_Str(v) : nullptr;
  const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (msg == nullptr) {
    PyErr_Clear();               // AsUTF8 can fail on odd messages
    msg = "unknown python error";
  }
  g_last_error = msg;
  Py_XDECREF(s); Py_XDECREF(t); Py_XDECREF(v); Py_XDECREF(tb);
}

std::string join_params(
    const std::unordered_map<std::string, std::string>& m) {
  std::string out;
  for (const auto& kv : m) {
    if (!out.empty()) out += ' ';
    out += kv.first + "=" + kv.second;
  }
  return out;
}

// Call glue.<fn>(args...) with a Py_BuildValue format; returns the
// result object (new ref) or nullptr (error captured).
PyObject* call(const char* fn, const char* fmt, ...) {
  if (!ensure_python()) return nullptr;
  PyGILState_STATE st = PyGILState_Ensure();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject* out = nullptr;
  if (args != nullptr) {
    PyObject* f = PyObject_GetAttrString(
        g_glue.load(std::memory_order_acquire), fn);
    if (f != nullptr) {
      out = PyObject_CallObject(f, args);
      Py_DECREF(f);
    }
    Py_DECREF(args);
  }
  if (out == nullptr) capture_error();
  PyGILState_Release(st);
  return out;
}

int call_void(const char* fn, const char* fmt, ...) {
  if (!ensure_python()) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject* out = nullptr;
  if (args != nullptr) {
    PyObject* f = PyObject_GetAttrString(
        g_glue.load(std::memory_order_acquire), fn);
    if (f != nullptr) {
      out = PyObject_CallObject(f, args);
      Py_DECREF(f);
    }
    Py_DECREF(args);
  }
  int rc = 0;
  if (out == nullptr) {
    capture_error();
    rc = -1;
  }
  Py_XDECREF(out);
  PyGILState_Release(st);
  return rc;
}

// Result -> C long (handles, lengths); -1 + error on failure.
long long as_ll(PyObject* obj) {
  if (obj == nullptr) return -1;
  PyGILState_STATE st = PyGILState_Ensure();
  long long v = PyLong_AsLongLong(obj);
  if (PyErr_Occurred()) { capture_error(); v = -1; }
  Py_DECREF(obj);
  PyGILState_Release(st);
  return v;
}

}  // namespace

LIGHTGBM_C_EXPORT const char* LGBM_GetLastError() {
  return g_last_error.c_str();
}

// --- Dataset ---------------------------------------------------------------

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromCSR(
    const void* indptr, int indptr_type, const int32_t* indices,
    const void* data, int data_type, int64_t nindptr, int64_t nelem,
    int64_t num_col,
    const std::unordered_map<std::string, std::string> parameters,
    const DatasetHandle reference, DatasetHandle* out) {
  long long h = as_ll(call(
      "dataset_from_csr", "(KiKKiLLLsK)",
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col,
      join_params(parameters).c_str(),
      (unsigned long long)(uintptr_t)reference));
  if (h < 0) return -1;
  *out = (DatasetHandle)(uintptr_t)h;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromMat(
    const void* data, int data_type, int32_t nrow, int32_t ncol,
    int is_row_major,
    const std::unordered_map<std::string, std::string> parameters,
    const DatasetHandle reference, DatasetHandle* out) {
  long long h = as_ll(call(
      "dataset_from_mat", "(KiiiisK)",
      (unsigned long long)(uintptr_t)data, data_type, (int)nrow,
      (int)ncol, is_row_major, join_params(parameters).c_str(),
      (unsigned long long)(uintptr_t)reference));
  if (h < 0) return -1;
  *out = (DatasetHandle)(uintptr_t)h;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromFile(
    const char* filename, const char* parameters,
    const DatasetHandle reference, DatasetHandle* out) {
  long long h = as_ll(call(
      "dataset_from_file", "(ssK)", filename, parameters,
      (unsigned long long)(uintptr_t)reference));
  if (h < 0) return -1;
  *out = (DatasetHandle)(uintptr_t)h;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetSetField(
    DatasetHandle handle, const char* field_name, const void* field_data,
    int num_element, int type) {
  return call_void("dataset_set_field", "(KsKii)",
                   (unsigned long long)(uintptr_t)handle, field_name,
                   (unsigned long long)(uintptr_t)field_data,
                   num_element, type);
}

LIGHTGBM_C_EXPORT int LGBM_DatasetGetNumData(DatasetHandle handle,
                                             int* out) {
  long long v = as_ll(call("dataset_num_data", "(K)",
                           (unsigned long long)(uintptr_t)handle));
  if (v < 0) return -1;
  *out = (int)v;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetGetNumFeature(DatasetHandle handle,
                                                int* out) {
  long long v = as_ll(call("dataset_num_feature", "(K)",
                           (unsigned long long)(uintptr_t)handle));
  if (v < 0) return -1;
  *out = (int)v;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_DatasetFree(DatasetHandle handle) {
  return call_void("free_handle", "(K)",
                   (unsigned long long)(uintptr_t)handle);
}

// --- Booster ---------------------------------------------------------------

LIGHTGBM_C_EXPORT int LGBM_BoosterCreate(
    const DatasetHandle train_data,
    std::unordered_map<std::string, std::string> parameters,
    BoosterHandle* out) {
  long long h = as_ll(call(
      "booster_create", "(Ks)",
      (unsigned long long)(uintptr_t)train_data,
      join_params(parameters).c_str()));
  if (h < 0) return -1;
  *out = (BoosterHandle)(uintptr_t)h;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterCreateFromModelfile(
    const char* filename, int* out_num_iterations, BoosterHandle* out) {
  long long h = as_ll(call(
      "booster_from_modelfile", "(sK)", filename,
      (unsigned long long)(uintptr_t)out_num_iterations));
  if (h < 0) return -1;
  *out = (BoosterHandle)(uintptr_t)h;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterFree(BoosterHandle handle) {
  return call_void("free_handle", "(K)",
                   (unsigned long long)(uintptr_t)handle);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterMerge(BoosterHandle handle,
                                        BoosterHandle other_handle) {
  return call_void("booster_merge", "(KK)",
                   (unsigned long long)(uintptr_t)handle,
                   (unsigned long long)(uintptr_t)other_handle);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterAddValidData(
    BoosterHandle handle, const DatasetHandle valid_data) {
  return call_void("booster_add_valid", "(KK)",
                   (unsigned long long)(uintptr_t)handle,
                   (unsigned long long)(uintptr_t)valid_data);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterUpdateOneIter(BoosterHandle handle,
                                                int* is_finished) {
  return call_void("booster_update", "(KK)",
                   (unsigned long long)(uintptr_t)handle,
                   (unsigned long long)(uintptr_t)is_finished);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterRefit(BoosterHandle handle,
                                        const int32_t* leaf_preds,
                                        int32_t nrow, int32_t ncol) {
  return call_void("booster_refit", "(KKii)",
                   (unsigned long long)(uintptr_t)handle,
                   (unsigned long long)(uintptr_t)leaf_preds,
                   (int)nrow, (int)ncol);
}

LIGHTGBM_C_EXPORT int LGBM_BoosterCalcNumPredict(
    BoosterHandle handle, int num_row, int predict_type,
    int num_iteration, int64_t* out_len) {
  long long v = as_ll(call("booster_calc_num_predict", "(Kiii)",
                           (unsigned long long)(uintptr_t)handle,
                           num_row, predict_type, num_iteration));
  if (v < 0) return -1;
  *out_len = (int64_t)v;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterGetEval(BoosterHandle handle,
                                          int data_idx, int* out_len,
                                          double* out_results) {
  long long v = as_ll(call("booster_get_eval", "(KiK)",
                           (unsigned long long)(uintptr_t)handle,
                           data_idx,
                           (unsigned long long)(uintptr_t)out_results));
  if (v < 0) return -1;
  *out_len = (int)v;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForCSR(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int num_iteration,
    std::unordered_map<std::string, std::string> parameter,
    int64_t* out_len, double* out_result) {
  long long v = as_ll(call(
      "booster_predict_csr", "(KKiKKiLLLiisK)",
      (unsigned long long)(uintptr_t)handle,
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col,
      predict_type, num_iteration, join_params(parameter).c_str(),
      (unsigned long long)(uintptr_t)out_result));
  if (v < 0) return -1;
  *out_len = (int64_t)v;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForMat(
    BoosterHandle handle, const void* data, int data_type, int32_t nrow,
    int32_t ncol, int is_row_major, int predict_type, int num_iteration,
    std::unordered_map<std::string, std::string> parameter,
    int64_t* out_len, double* out_result) {
  long long v = as_ll(call(
      "booster_predict_mat", "(KKiiiiiisK)",
      (unsigned long long)(uintptr_t)handle,
      (unsigned long long)(uintptr_t)data, data_type, (int)nrow,
      (int)ncol, is_row_major, predict_type, num_iteration,
      join_params(parameter).c_str(),
      (unsigned long long)(uintptr_t)out_result));
  if (v < 0) return -1;
  *out_len = (int64_t)v;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterSaveModel(BoosterHandle handle,
                                            int start_iteration,
                                            int num_iteration,
                                            const char* filename) {
  return call_void("booster_save_model", "(Kiis)",
                   (unsigned long long)(uintptr_t)handle,
                   start_iteration, num_iteration, filename);
}

// ---------------------------------------------------------------------------
// Plain-C parameter forms.
//
// The fork's c_api.h passes parameters as C++ std::unordered_map BY
// VALUE in four entry points — fine for a C++ translation unit that
// includes the header, but uncallable through a pure-C FFI (JNI
// RegisterNatives, Java's Panama FFM, ctypes, dlsym users). These
// variants take the upstream LightGBM convention instead — a single
// "key=value key2=value2" C string — and are what
// java/LightGbmTpuNative.java binds to. Same handles, same glue.
// ---------------------------------------------------------------------------

LIGHTGBM_C_EXPORT int LGBM_DatasetCreateFromMatC(
    const void* data, int data_type, int32_t nrow, int32_t ncol,
    int is_row_major, const char* parameters,
    const DatasetHandle reference, DatasetHandle* out) {
  long long h = as_ll(call(
      "dataset_from_mat", "(KiiiisK)",
      (unsigned long long)(uintptr_t)data, data_type, (int)nrow,
      (int)ncol, is_row_major, parameters ? parameters : "",
      (unsigned long long)(uintptr_t)reference));
  if (h < 0) return -1;
  *out = (DatasetHandle)(uintptr_t)h;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterCreateC(
    const DatasetHandle train_data, const char* parameters,
    BoosterHandle* out) {
  long long h = as_ll(call(
      "booster_create", "(Ks)",
      (unsigned long long)(uintptr_t)train_data,
      parameters ? parameters : ""));
  if (h < 0) return -1;
  *out = (BoosterHandle)(uintptr_t)h;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForMatC(
    BoosterHandle handle, const void* data, int data_type, int32_t nrow,
    int32_t ncol, int is_row_major, int predict_type, int num_iteration,
    const char* parameter, int64_t* out_len, double* out_result) {
  long long v = as_ll(call(
      "booster_predict_mat", "(KKiiiiiisK)",
      (unsigned long long)(uintptr_t)handle,
      (unsigned long long)(uintptr_t)data, data_type, (int)nrow,
      (int)ncol, is_row_major, predict_type, num_iteration,
      parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result));
  if (v < 0) return -1;
  *out_len = (int64_t)v;
  return 0;
}

LIGHTGBM_C_EXPORT int LGBM_BoosterPredictForCSRC(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  long long v = as_ll(call(
      "booster_predict_csr", "(KKiKKiLLLiisK)",
      (unsigned long long)(uintptr_t)handle,
      (unsigned long long)(uintptr_t)indptr, indptr_type,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, data_type,
      (long long)nindptr, (long long)nelem, (long long)num_col,
      predict_type, num_iteration, parameter ? parameter : "",
      (unsigned long long)(uintptr_t)out_result));
  if (v < 0) return -1;
  *out_len = (int64_t)v;
  return 0;
}
