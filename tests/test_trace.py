"""Cross-thread span tracer (lightgbm_tpu/obs/trace.py): trace-event
JSON schema, ring-buffer bounds, multi-thread hammer, timing.phase and
step-cache integration, watchdog instants, and the end-to-end LRB
two-window trace (spans from the ingest worker AND the main thread in
one Perfetto-loadable file).

Run with ``pytest -m obs``.
"""
import json
import threading

import pytest

from lightgbm_tpu.obs import trace
from lightgbm_tpu.obs.trace import Tracer

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    """Every test leaves the process-global tracer uninstalled."""
    trace.stop()
    yield
    trace.stop()


# -- schema round-trip -------------------------------------------------------

def _valid_event(ev):
    assert ev["ph"] in ("X", "i", "M"), ev
    assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
    if ev["ph"] == "X":
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
    elif ev["ph"] == "i":
        assert isinstance(ev["ts"], (int, float))
        assert ev["s"] in ("t", "p", "g")
    else:            # metadata: thread/process name + rank labels
        assert ev["name"] in ("thread_name", "process_name",
                              "process_labels")
        if ev["name"] == "process_labels":
            assert "labels" in ev["args"]    # Chrome labels record
        else:
            assert "name" in ev["args"]


def test_trace_event_schema_roundtrip(tmp_path):
    """Spans + instants -> write -> parse: every event satisfies the
    Chrome trace-event contract (valid ph/ts/pid/tid) and the document
    is the Perfetto-loadable traceEvents form."""
    path = str(tmp_path / "t.json")
    tr = Tracer(path)
    with tr.span("outer", cat="window", args={"window": 1}):
        with tr.span("inner", cat="iteration", args={"it": 3}):
            pass
    tr.instant("marker", cat="event", args={"why": "test"})
    assert tr.write() == path
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list)
    assert doc["otherData"]["schema"] == "lightgbm-tpu/trace"
    assert doc["otherData"]["dropped_events"] == 0
    for ev in doc["traceEvents"]:
        _valid_event(ev)
    spans = {e["name"]: e for e in doc["traceEvents"]
             if e["ph"] == "X"}
    assert set(spans) == {"outer", "inner"}
    # nesting: inner lies within outer on the same thread
    o, i = spans["outer"], spans["inner"]
    assert o["tid"] == i["tid"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-3
    assert i["args"] == {"it": 3}
    inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(inst) == 1 and inst[0]["name"] == "marker"
    # thread-name metadata present for the recording thread
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in doc["traceEvents"])


def test_trace_write_idempotent_and_atomic(tmp_path):
    """write() replaces the file with the ring's current contents —
    callable after every window of a live loop."""
    path = str(tmp_path / "t.json")
    tr = Tracer(path)
    with tr.span("a"):
        pass
    tr.write()
    first = json.load(open(path))
    with tr.span("b"):
        pass
    tr.write()
    second = json.load(open(path))
    n_first = sum(e["ph"] == "X" for e in first["traceEvents"])
    n_second = sum(e["ph"] == "X" for e in second["traceEvents"])
    assert (n_first, n_second) == (1, 2)


# -- ring buffer -------------------------------------------------------------

def test_ring_buffer_bounds_and_dropped_count(tmp_path):
    """The buffer keeps the most recent ``capacity`` events and counts
    what it evicted (capacity floors at MIN_BUFFER_EVENTS)."""
    tr = Tracer(str(tmp_path / "t.json"), capacity=10)
    assert tr.capacity == trace.MIN_BUFFER_EVENTS
    n = tr.capacity + 100
    for i in range(n):
        tr.instant(f"e{i}")
    assert tr.event_count() == tr.capacity
    assert tr.dropped_events == 100
    doc = tr.trace_document()
    assert doc["otherData"]["dropped_events"] == 100
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "i"]
    assert names[0] == "e100" and names[-1] == f"e{n - 1}"


def test_multithread_span_hammer(tmp_path):
    """N threads record spans + instants concurrently (the ingest
    worker / exporter / main-thread mix): no exceptions, no lost
    events below capacity, one tid row per thread."""
    tr = Tracer(str(tmp_path / "t.json"), capacity=100_000)
    N, M = 8, 500
    errs = []

    def work(k):
        try:
            for i in range(M):
                with tr.span(f"w{k}", cat="hammer", args={"i": i}):
                    pass
        except Exception as e:              # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=work, args=(k,), name=f"ham-{k}")
               for k in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert tr.event_count() == N * M
    assert tr.dropped_events == 0
    doc = tr.trace_document()
    span_tids = {e["tid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(span_tids) == N
    # every hammer thread got a thread_name metadata record
    named = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {f"ham-{k}" for k in range(N)} <= named


# -- module-global API -------------------------------------------------------

def test_write_failure_warns_once_and_returns_none(tmp_path):
    """An unwritable tpu_trace path is not a silent no-trace run: the
    first failed flush warns, later ones stay quiet, training-side
    callers just see None."""
    from lightgbm_tpu.utils import log
    bad_parent = tmp_path / "f"
    bad_parent.write_text("")               # file where a dir is needed
    trace.configure(str(bad_parent / "sub" / "t.json"))
    trace._write_warned = False
    lines = []
    prev_level = log.get_level()
    log.set_level(log.LogLevel.INFO)        # earlier tests may pin FATAL
    log.set_callback(lines.append)
    try:
        assert trace.write() is None
        assert trace.write() is None
    finally:
        log.set_callback(None)
        log.set_level(prev_level)
        trace._write_warned = False
    assert sum("could not write trace" in ln for ln in lines) == 1


def test_global_tracer_off_is_noop(tmp_path):
    assert not trace.enabled()
    with trace.span("ignored"):
        pass
    trace.instant("ignored")
    assert trace.write() is None


def test_configure_and_ensure_from_config(tmp_path):
    path = str(tmp_path / "t.json")
    assert trace.ensure_from_config({"no_trace_here": 1}) is None
    tr = trace.ensure_from_config({"tpu_trace": path,
                                   "tpu_trace_buffer": "2048"})
    assert tr is not None and tr.capacity == 2048
    assert trace.enabled()
    # same path: idempotent (buffer survives)
    with trace.span("kept"):
        pass
    assert trace.ensure_from_config({"tpu_trace": path}) is tr
    assert tr.event_count() == 1
    # Config-object flavor
    from lightgbm_tpu.config import Config
    cfg = Config().set({"tpu_trace": path})
    assert trace.ensure_from_config(cfg) is tr
    assert trace.write() == path
    assert json.load(open(path))["traceEvents"]


def test_configure_same_path_grows_buffer(tmp_path):
    """A later config naming the same path with a LARGER
    tpu_trace_buffer grows the ring in place (events kept); a smaller
    or default capacity never shrinks it mid-run."""
    path = str(tmp_path / "t.json")
    tr = trace.configure(path, capacity=2048)
    with trace.span("kept"):
        pass
    assert trace.configure(path, capacity=8192) is tr
    assert tr.capacity == 8192
    assert tr.event_count() == 1
    trace.configure(path)                   # default (65536 > 8192): grows
    assert tr.capacity == trace.DEFAULT_BUFFER_EVENTS
    assert trace.configure(path, capacity=1024) is tr
    assert tr.capacity == trace.DEFAULT_BUFFER_EVENTS  # never shrinks


def test_configure_retarget_flushes_old_buffer(tmp_path):
    """Re-targeting the global tracer to a new path first flushes the
    old buffer to its own file — post-flush spans (a predict after
    train's finish()) are never silently dropped."""
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    trace.configure(a)
    with trace.span("late-span"):
        pass
    tr_b = trace.configure(b)
    assert tr_b.path == b
    doc = json.load(open(a))
    assert [e["name"] for e in doc["traceEvents"]
            if e["ph"] == "X"] == ["late-span"]


def test_atomic_write_failure_leaves_no_debris(tmp_path):
    """utils/fileio.atomic_write: a failing write keeps the original
    file intact and removes the temp file."""
    import os

    from lightgbm_tpu.utils.fileio import atomic_write
    path = str(tmp_path / "f.json")
    with atomic_write(path) as fh:
        fh.write("good")
    with pytest.raises(RuntimeError):
        with atomic_write(path) as fh:
            fh.write("partial")
            raise RuntimeError("boom")
    assert open(path).read() == "good"
    assert os.listdir(tmp_path) == ["f.json"]


def test_timing_phase_emits_trace_span(tmp_path):
    """Every timing.phase block is also a span on the active trace —
    same name, recorded on the calling thread."""
    from lightgbm_tpu.utils import timing
    tr = trace.configure(str(tmp_path / "t.json"))
    with timing.phase("unit/traced_phase"):
        pass
    doc = tr.trace_document()
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [s["name"] for s in spans] == ["unit/traced_phase"]
    assert spans[0]["cat"] == "phase"
    timing.reset()


def test_step_cache_events_and_watchdog_instant(tmp_path):
    """Step-cache hits/misses and watchdog firings land as trace
    events exactly where they happened."""
    from lightgbm_tpu.obs.recorder import RunRecorder
    from lightgbm_tpu.obs.registry import MetricsRegistry
    from lightgbm_tpu.ops import step_cache
    tr = trace.configure(str(tmp_path / "t.json"))
    key = ("trace-test-key",)
    step_cache.get_step(key, lambda: (lambda *a: a))
    step_cache.get_step(key, lambda: (lambda *a: a))
    rec = RunRecorder(watchdog_factor=3.0,
                      registry=MetricsRegistry()).start()
    for it in range(1, 10):
        rec.observe_iteration(it, 0.01)
    rec.observe_iteration(10, 0.5)          # 50x the trailing median
    rec.finish()
    names = [e["name"] for e in tr.trace_document()["traceEvents"]
             if e["ph"] == "i"]
    assert "step_cache/miss" in names
    assert "step_cache/hit" in names
    wd = [e for e in tr.trace_document()["traceEvents"]
          if e["ph"] == "i" and e["name"] == "watchdog/slow_iteration"]
    assert wd and wd[0]["args"]["it"] == 10


# -- end-to-end: the acceptance run ------------------------------------------

def test_lrb_two_window_trace_end_to_end(tmp_path):
    """A single lrb run with tpu_trace set produces ONE
    Perfetto-loadable trace containing spans from >= 2 threads (main +
    ingest prefetch worker) and >= 3 span kinds (window, iteration,
    ingest chunk), plus per-window derive/train/evaluate walls in the
    results."""
    import io

    from lightgbm_tpu.lrb import LrbDriver, synthetic_trace
    path = str(tmp_path / "lrb_trace.json")
    out = io.StringIO()
    drv = LrbDriver(cache_size=1 << 16, window_size=256,
                    sample_size=128, cutoff=0.5, sampling=1,
                    result_file=out,
                    extra_params={"tpu_trace": path,
                                  "num_iterations": 8,
                                  # force the device-ingest pipeline so
                                  # the prefetch worker thread records
                                  "tpu_ingest": 1})
    for seq, oid, size, cost in synthetic_trace(512, n_objects=60):
        drv.process_request(seq, oid, size, cost)
    assert len(drv.results) == 2
    # per-window phase table: derive/train/evaluate wall seconds
    r2 = drv.results[1]
    assert r2["derive_s"] >= 0 and r2["train_s"] > 0
    assert r2["evaluate_s"] >= 0          # window 2 scored window 1's model
    assert r2["window_wall_s"] >= r2["train_s"]
    q = drv.window_wall_quantiles()
    assert q and q["p50"] > 0 and q["p99"] >= q["p50"]

    # the trace was flushed DURING the run (after each window)
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    for ev in evs:
        _valid_event(ev)
    spans = [e for e in evs if e["ph"] == "X"]
    cats = {e["cat"] for e in spans}
    assert {"window", "iteration", "ingest"} <= cats, cats
    assert len({e["tid"] for e in spans}) >= 2, \
        "expected spans from main + ingest worker threads"
    names = {e["name"] for e in spans}
    assert {"window", "lrb/derive", "lrb/train", "iteration",
            "ingest/prep_chunk", "ingest/chunk"} <= names, names
    # the ingest worker's spans are on a different tid than the window
    win_tids = {e["tid"] for e in spans if e["name"] == "window"}
    prep_tids = {e["tid"] for e in spans
                 if e["name"] == "ingest/prep_chunk"}
    assert prep_tids and not (prep_tids & win_tids)
