"""Refit + prediction-early-stop regression tests.

Reference: src/boosting/gbdt.cpp:265-289 RefitTree /
serial_tree_learner.cpp:223-253 FitByExistingTree;
src/boosting/prediction_early_stop.cpp:20-84.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=500, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


class TestRefit:
    def test_refit_improves_on_shifted_data(self):
        X, y = _data()
        bst = lgb.train({"objective": "binary", "verbose": -1,
                         "min_data_in_leaf": 5}, lgb.Dataset(X, y), 10,
                        verbose_eval=False)
        X2 = X + 0.15
        y2 = (X2[:, 0] + 0.5 * X2[:, 1] > 0).astype(np.float64)

        def ll(yy, p):
            p = np.clip(p, 1e-12, 1 - 1e-12)
            return float(-np.mean(yy * np.log(p)
                                  + (1 - yy) * np.log(1 - p)))
        r = bst.refit(X2, y2, decay_rate=0.5)
        assert r.num_trees() == bst.num_trees()
        assert ll(y2, r.predict(X2)) < ll(y2, bst.predict(X2))

    def test_decay_one_is_identity(self):
        """decay_rate=1 keeps every leaf output
        (FitByExistingTree blend, serial_tree_learner.cpp:243)."""
        X, y = _data()
        bst = lgb.train({"objective": "binary", "verbose": -1,
                         "min_data_in_leaf": 5}, lgb.Dataset(X, y), 5,
                        verbose_eval=False)
        same = bst.refit(X, y, decay_rate=1.0)
        np.testing.assert_allclose(same.predict(X, raw_score=True),
                                   bst.predict(X, raw_score=True),
                                   atol=2e-4)

    def test_cli_refit_task(self, tmp_path):
        import os
        from lightgbm_tpu.application import Application
        X, y = _data(300, 5)
        data = str(tmp_path / "t.tsv")
        with open(data, "w") as fh:
            for i in range(len(y)):
                fh.write("\t".join([f"{y[i]:g}"]
                                   + [f"{v:.5f}" for v in X[i]]) + "\n")
        model = str(tmp_path / "m.txt")
        refit_out = str(tmp_path / "m2.txt")
        cwd = os.getcwd()
        os.chdir(tmp_path)
        try:
            Application([f"data={data}", "objective=binary",
                         "num_trees=4", "verbose=-1",
                         "min_data_in_leaf=5",
                         f"output_model={model}"]).run()
            Application(["task=refit", f"data={data}",
                         "objective=binary", "verbose=-1",
                         f"input_model={model}",
                         f"output_model={refit_out}"]).run()
        finally:
            os.chdir(cwd)
        assert "Tree=3" in open(refit_out).read()


class TestForcedSplits:
    def test_forced_prefix_then_gain_growth(self, tmp_path):
        """forcedsplits_filename forces the first splits of every tree
        (ForceSplits, serial_tree_learner.cpp:546-701)."""
        import json
        rng = np.random.default_rng(0)
        X = rng.normal(size=(600, 6))
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
        spec = {"feature": 3, "threshold": 0.2,
                "left": {"feature": 4, "threshold": -0.1},
                "right": {"feature": 4, "threshold": -0.1}}
        path = str(tmp_path / "forced.json")
        with open(path, "w") as fh:
            json.dump(spec, fh)
        bst = lgb.train({"objective": "binary", "verbose": -1,
                         "min_data_in_leaf": 5, "num_leaves": 15,
                         "forcedsplits_filename": path},
                        lgb.Dataset(X, y), 5, verbose_eval=False,
                        keep_training_booster=True)
        bst._gbdt._ensure_host_trees()
        for t in bst._gbdt.models:
            assert t.split_feature[0] == 3          # forced root
            assert t.split_feature[1] == 4          # forced child
            assert t.split_feature[2] == 4          # forced child
        # gain-driven growth continues and still learns the signal
        assert ((bst.predict(X) > 0.5) == y).mean() > 0.9
        # round-trips through the model format
        loaded = lgb.Booster(model_str=bst.model_to_string())
        np.testing.assert_allclose(loaded.predict(X), bst.predict(X),
                                   atol=1e-5)


class TestPredEarlyStop:
    def test_binary_sign_preserved(self):
        X, y = _data()
        bst = lgb.train({"objective": "binary", "verbose": -1,
                         "min_data_in_leaf": 5}, lgb.Dataset(X, y), 40,
                        verbose_eval=False)
        exact = bst.predict(X, raw_score=True)
        es = bst.predict(X, raw_score=True, pred_early_stop=True,
                         pred_early_stop_freq=5,
                         pred_early_stop_margin=4.0)
        assert ((exact > 0) == (es > 0)).all()
        # some rows actually stopped early (values differ)
        assert (exact != es).any()
        # a huge margin means no early stop at all
        no_stop = bst.predict(X, raw_score=True, pred_early_stop=True,
                              pred_early_stop_freq=5,
                              pred_early_stop_margin=1e9)
        np.testing.assert_allclose(no_stop, exact, atol=1e-5)

    def test_multiclass_argmax_preserved(self):
        rng = np.random.default_rng(1)
        y = rng.integers(0, 3, 400).astype(np.float64)
        X = rng.normal(size=(400, 5))
        X[:, 0] += 2 * y
        bst = lgb.train({"objective": "multiclass", "num_class": 3,
                         "verbose": -1, "min_data_in_leaf": 5},
                        lgb.Dataset(X, y), 25, verbose_eval=False)
        exact = bst.predict(X, raw_score=True)
        es = bst.predict(X, raw_score=True, pred_early_stop=True,
                         pred_early_stop_freq=3,
                         pred_early_stop_margin=3.0)
        assert (exact.argmax(1) == es.argmax(1)).mean() > 0.99

    def test_regression_rejects_early_stop(self):
        X, y = _data()
        bst = lgb.train({"objective": "regression", "verbose": -1},
                        lgb.Dataset(X, y), 10, verbose_eval=False)
        exact = bst.predict(X, raw_score=True)
        ignored = bst.predict(X, raw_score=True, pred_early_stop=True)
        np.testing.assert_allclose(ignored, exact, atol=1e-5)
