"""Split-search correctness: find_best_split vs a numpy brute force that
follows FeatureHistogram::FindBestThresholdNumerical semantics
(reference: src/treelearner/feature_histogram.hpp:84-110,506-653)."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.split import (
    FeatureMeta, SplitParams, find_best_split, KEPSILON,
    MISSING_NONE, MISSING_ZERO, MISSING_NAN)


def brute_force_best(hist, sum_g, sum_h, num_data, meta, hp):
    """Scan both directions per feature exactly like the reference."""
    F, B, _ = hist.shape
    sum_h = sum_h + 2 * KEPSILON

    def thr_l1(s, l1):
        return np.sign(s) * max(abs(s) - l1, 0.0)

    def out(g, h):
        r = -thr_l1(g, hp.lambda_l1) / (h + hp.lambda_l2)
        if hp.max_delta_step > 0:
            r = np.clip(r, -hp.max_delta_step, hp.max_delta_step)
        return r

    def gain_given(g, h, o):
        return -(2 * thr_l1(g, hp.lambda_l1) * o
                 + (h + hp.lambda_l2) * o * o)

    def split_gain(lg, lh, rg, rh):
        return (gain_given(lg, lh, out(lg, lh))
                + gain_given(rg, rh, out(rg, rh)))

    parent_gain = gain_given(sum_g, sum_h, out(sum_g, sum_h))
    min_shift = parent_gain + hp.min_gain_to_split
    best = (-np.inf, -1, 0, False)
    for f in range(F):
        nb = int(meta.num_bin[f])
        mt = int(meta.missing_type[f])
        db = int(meta.default_bin[f])
        two_scan = nb > 2 and mt != MISSING_NONE
        use_na = two_scan and mt == MISSING_NAN
        skip_db = two_scan and mt == MISSING_ZERO
        g = hist[f, :, 0].astype(np.float64)
        h = hist[f, :, 1].astype(np.float64)
        c = hist[f, :, 2].astype(np.float64)

        # dir = -1 (default left): accumulate right from top
        hi = nb - 2 if use_na else nb - 1   # skip NaN bin
        for dirn in (-1, 1) if two_scan else (1,):
            lg = lh = lc = 0.0
            if dirn == -1:
                rg = rh = rc = 0.0
                ts = []
                for b in range(hi, 0, -1):
                    if skip_db and b == db:
                        continue
                    rg += g[b]; rh += h[b]; rc += c[b]
                    t = b - 1
                    lg2 = sum_g - rg
                    lh2 = sum_h - rh
                    lc2 = num_data - rc
                    ts.append((t, lg2, lh2, lc2, rg, rh + KEPSILON, rc))
                cands = ts
            else:
                cands = []
                lg = lh = lc = 0.0
                top = nb - 1
                end = nb - 2
                for b in range(0, end + 1):
                    if skip_db and b == db:
                        continue
                    if use_na and b == nb - 1:
                        continue
                    lg += g[b]; lh += h[b]; lc += c[b]
                    if two_scan and b > end - 1 and use_na:
                        break
                    cands.append((b, lg, lh + KEPSILON, lc,
                                  sum_g - lg - (0.0),
                                  sum_h - lh - KEPSILON, num_data - lc))
            for (t, lg_, lh_, lc_, rg_, rh_, rc_) in cands:
                if (lc_ < hp.min_data_in_leaf or rc_ < hp.min_data_in_leaf
                        or lh_ < hp.min_sum_hessian_in_leaf
                        or rh_ < hp.min_sum_hessian_in_leaf):
                    continue
                sg = split_gain(lg_, lh_, rg_, rh_)
                if sg <= min_shift:
                    continue
                if sg > best[0] + 1e-12:
                    best = (sg, f, t, dirn == -1)
    if best[1] < 0:
        return None
    return (best[0] - min_shift, best[1], best[2])


def _random_case(rng, F=5, B=16, missing=MISSING_NONE):
    hist = np.zeros((F, B, 3), np.float32)
    num_bin = np.full(F, B, np.int32)
    for f in range(F):
        nb = rng.integers(3, B + 1)
        num_bin[f] = nb
        cnt = rng.integers(1, 50, size=nb).astype(np.float32)
        g = rng.normal(size=nb).astype(np.float32) * cnt
        h = (rng.uniform(0.1, 1.0, size=nb) * cnt).astype(np.float32)
        hist[f, :nb, 0] = g
        hist[f, :nb, 1] = h
        hist[f, :nb, 2] = cnt
    sum_g = hist[0, :, 0].sum()
    sum_h = hist[0, :, 1].sum()
    cnt0 = hist[0, :, 2].sum()
    # make all features consistent: same totals
    for f in range(1, F):
        s = hist[f, :, 2].sum()
        hist[f] *= 0
        nb = num_bin[f]
        # redistribute feature 0's rows
        alloc = rng.multinomial(int(cnt0), np.ones(nb) / nb)
        hist[f, :nb, 2] = alloc
        hist[f, :nb, 0] = sum_g / max(cnt0, 1) * alloc
        hist[f, :nb, 1] = sum_h / max(cnt0, 1) * alloc
    meta = FeatureMeta(
        num_bin=num_bin,
        missing_type=np.full(F, missing, np.int32),
        default_bin=np.zeros(F, np.int32),
        monotone=np.zeros(F, np.int32),
        penalty=np.ones(F, np.float32))
    return hist, sum_g, sum_h, cnt0, meta


@pytest.mark.parametrize("missing", [MISSING_NONE, MISSING_NAN])
@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_matches_bruteforce(seed, missing):
    rng = np.random.default_rng(seed)
    hp = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=1e-3)
    hist, sg, sh, nd, meta = _random_case(rng, missing=missing)
    res = find_best_split(jnp.asarray(hist), sg, sh, nd,
                          jnp.ones(hist.shape[0], bool), meta, hp)
    bf = brute_force_best(hist, float(sg), float(sh), float(nd), meta, hp)
    got_gain = float(res.gain)
    if bf is None:
        assert not np.isfinite(got_gain) or got_gain <= 0
    else:
        assert np.isfinite(got_gain)
        assert got_gain == pytest.approx(bf[0], rel=2e-4, abs=1e-4)


def test_l1_l2_regularization():
    rng = np.random.default_rng(9)
    hist, sg, sh, nd, meta = _random_case(rng)
    hp = SplitParams(lambda_l1=0.5, lambda_l2=2.0, min_data_in_leaf=1)
    res = find_best_split(jnp.asarray(hist), sg, sh, nd,
                          jnp.ones(hist.shape[0], bool), meta, hp)
    bf = brute_force_best(hist, float(sg), float(sh), float(nd), meta, hp)
    if bf is not None:
        assert float(res.gain) == pytest.approx(bf[0], rel=2e-4, abs=1e-4)


def test_min_data_in_leaf_blocks_small_splits():
    hist = np.zeros((1, 4, 3), np.float32)
    hist[0, :, 2] = [5, 5, 5, 100]
    hist[0, :, 0] = [-10, -10, -10, 30]
    hist[0, :, 1] = [5, 5, 5, 100]
    meta = FeatureMeta(np.array([4], np.int32), np.array([0], np.int32),
                       np.zeros(1, np.int32), np.zeros(1, np.int32),
                       np.ones(1, np.float32))
    hp = SplitParams(min_data_in_leaf=50)
    res = find_best_split(jnp.asarray(hist), 0.0, 115.0, 115.0,
                          jnp.ones(1, bool), meta, hp)
    assert not np.isfinite(float(res.gain))


def test_feature_mask_respected():
    rng = np.random.default_rng(5)
    hist, sg, sh, nd, meta = _random_case(rng)
    hp = SplitParams(min_data_in_leaf=1)
    fmask = np.zeros(hist.shape[0], bool)
    fmask[2] = True
    res = find_best_split(jnp.asarray(hist), sg, sh, nd,
                          jnp.asarray(fmask), meta, hp)
    if np.isfinite(float(res.gain)):
        assert int(res.feature) == 2
