"""CLI application + text loader tests.

Covers the reference Application/Parser/DatasetLoader behaviors
(reference: src/application/application.cpp:64-281, src/io/parser.cpp,
src/io/dataset_loader.cpp:161-499): config files, train/predict tasks,
TSV/CSV/LibSVM autodetect, sidecar weight/query files, header columns.
"""
import os

import numpy as np
import pytest

from lightgbm_tpu.application import Application, parse_config_file
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.loader import DatasetLoader
from lightgbm_tpu.io.parser import detect_format, parse_file


def _write_tsv(path, X, y):
    with open(path, "w") as fh:
        for i in range(len(y)):
            fh.write("\t".join(
                [f"{y[i]:g}"] + [f"{v:.6f}" for v in X[i]]) + "\n")


def _make_data(n=300, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


class TestParser:
    def test_detect_format(self):
        assert detect_format(["1\t2\t3", "4\t5\t6"]) == "tsv"
        assert detect_format(["1,2,3", "4,5,6"]) == "csv"
        assert detect_format(["1 0:2 3:4", "0 1:1"]) == "libsvm"

    def test_parse_tsv(self, tmp_path):
        X, y = _make_data(50)
        p = str(tmp_path / "d.tsv")
        _write_tsv(p, X, y)
        parsed, names = parse_file(p, label_idx=0)
        assert parsed.num_data == 50
        assert parsed.num_columns == 6
        np.testing.assert_allclose(parsed.label, y, atol=1e-6)
        np.testing.assert_allclose(parsed.values, X, atol=1e-5)

    def test_parse_csv_header(self, tmp_path):
        X, y = _make_data(30)
        p = str(tmp_path / "d.csv")
        with open(p, "w") as fh:
            fh.write("target," + ",".join(
                f"x{i}" for i in range(X.shape[1])) + "\n")
            for i in range(len(y)):
                fh.write(",".join(
                    [f"{y[i]:g}"] + [f"{v:.5f}" for v in X[i]]) + "\n")
        parsed, names = parse_file(p, header=True, label_idx=0)
        assert names == [f"x{i}" for i in range(X.shape[1])]
        assert parsed.num_columns == X.shape[1]

    def test_parse_libsvm(self, tmp_path):
        p = str(tmp_path / "d.svm")
        with open(p, "w") as fh:
            fh.write("1 0:0.5 2:1.5\n0 1:2.0\n1 0:1.0 1:1.0 2:1.0\n")
        parsed, _ = parse_file(p, label_idx=0)
        assert parsed.values.shape == (3, 3)
        np.testing.assert_allclose(parsed.label, [1, 0, 1])
        assert parsed.values[1, 1] == 2.0
        assert parsed.values[1, 0] == 0.0

    def test_label_inference_for_prediction(self, tmp_path):
        # rows with exactly num_features columns -> no label column
        p = str(tmp_path / "d.tsv")
        with open(p, "w") as fh:
            fh.write("0.1\t0.2\t0.3\n0.4\t0.5\t0.6\n")
        parsed, _ = parse_file(p, label_idx=0, num_features_hint=3)
        assert parsed.label is None
        assert parsed.num_columns == 3


class TestLoader:
    def test_sidecar_weight_query(self, tmp_path):
        X, y = _make_data(60)
        data = str(tmp_path / "train.txt")
        _write_tsv(data, X, y)
        with open(data + ".weight", "w") as fh:
            for i in range(60):
                fh.write(f"{1.0 + (i % 3)}\n")
        with open(data + ".query", "w") as fh:
            fh.write("30\n30\n")
        cfg = Config()
        ds = DatasetLoader(cfg).load_from_file(data)
        assert ds.metadata.weights is not None
        assert ds.metadata.weights[1] == pytest.approx(2.0)
        assert ds.metadata.num_queries == 2

    def test_ignore_and_weight_column(self, tmp_path):
        X, y = _make_data(80)
        data = str(tmp_path / "t.csv")
        with open(data, "w") as fh:
            fh.write("label,w,a,b,c,d,e,f\n")
            for i in range(80):
                fh.write(",".join(
                    [f"{y[i]:g}", f"{1 + i % 2}"]
                    + [f"{v:.5f}" for v in X[i]]) + "\n")
        cfg = Config()
        cfg.set({"header": True, "label_column": "name:label",
                 "weight_column": "name:w", "ignore_column": "name:a"})
        ds = DatasetLoader(cfg).load_from_file(data)
        assert ds.metadata.weights[1] == pytest.approx(2.0)
        # 6 X columns minus the ignored one
        assert ds.num_total_features == 5

    def test_binary_cache(self, tmp_path, monkeypatch):
        X, y = _make_data(50)
        data = str(tmp_path / "train.txt")
        _write_tsv(data, X, y)
        cfg = Config()
        cfg.save_binary = True
        ds = DatasetLoader(cfg).load_from_file(data)
        assert os.path.exists(data + ".bin")
        ds2 = DatasetLoader(Config()).load_from_file(data + ".bin")
        np.testing.assert_array_equal(ds.bins, ds2.bins)


class TestApplication:
    def _write_conf(self, tmp_path, X, y, Xv, yv, extra=""):
        train = str(tmp_path / "train.txt")
        valid = str(tmp_path / "valid.txt")
        _write_tsv(train, X, y)
        _write_tsv(valid, Xv, yv)
        conf = str(tmp_path / "train.conf")
        with open(conf, "w") as fh:
            fh.write(f"""
task = train
objective = binary
metric = binary_logloss,auc   # two metrics
is_training_metric = true
data = train.txt
valid_data = valid.txt
num_trees = 5
learning_rate = 0.1
num_leaves = 15
min_data_in_leaf = 5
metric_freq = 5
output_model = {tmp_path}/model.txt
{extra}
""")
        return conf

    def test_train_and_predict_tasks(self, tmp_path, capsys):
        X, y = _make_data(200)
        Xv, yv = _make_data(80, seed=1)
        conf = self._write_conf(tmp_path, X, y, Xv, yv)
        cwd = os.getcwd()
        os.chdir(tmp_path)       # data paths resolve relative to config
        try:
            Application([f"config={conf}"]).run()
            model = str(tmp_path / "model.txt")
            assert os.path.exists(model)
            text = open(model).read()
            assert text.startswith("tree")
            assert "Tree=4" in text
            # predict task
            out = str(tmp_path / "preds.txt")
            Application([
                "task=predict", f"data={tmp_path}/valid.txt",
                f"input_model={model}", f"output_result={out}",
            ]).run()
            preds = np.loadtxt(out)
            assert preds.shape == (80,)
            assert preds.min() >= 0 and preds.max() <= 1
            auc_input = preds[yv > 0].mean() > preds[yv == 0].mean()
            assert auc_input
        finally:
            os.chdir(cwd)

    def test_cli_continue_training(self, tmp_path):
        X, y = _make_data(200)
        Xv, yv = _make_data(80, seed=1)
        conf = self._write_conf(tmp_path, X, y, Xv, yv)
        cwd = os.getcwd()
        os.chdir(tmp_path)
        try:
            Application([f"config={conf}"]).run()
            model = str(tmp_path / "model.txt")
            out2 = str(tmp_path / "model2.txt")
            Application([f"config={conf}", f"input_model={model}",
                         f"output_model={out2}", "num_trees=8"]).run()
            text = open(out2).read()
            # reference semantics (gbdt.cpp:248): num_trees counts
            # ADDITIONAL rounds on top of the loaded model: 5 + 8
            assert "Tree=12" in text
            assert "Tree=13" not in text
        finally:
            os.chdir(cwd)

    def test_convert_model_task(self, tmp_path):
        X, y = _make_data(150)
        Xv, yv = _make_data(50, seed=2)
        conf = self._write_conf(tmp_path, X, y, Xv, yv)
        cwd = os.getcwd()
        os.chdir(tmp_path)
        try:
            Application([f"config={conf}"]).run()
            cpp = str(tmp_path / "model.cpp")
            Application([
                "task=convert_model",
                f"input_model={tmp_path}/model.txt",
                f"convert_model={cpp}"]).run()
            code = open(cpp).read()
            assert "double PredictTree0" in code
            assert "PredictRaw" in code
        finally:
            os.chdir(cwd)

    @pytest.mark.parametrize("example", [
        "binary_classification", "regression", "multiclass_classification",
        "lambdarank", "parallel_learning"])
    def test_reference_example_configs(self, tmp_path, example):
        """All five reference example configs train end-to-end
        (the north-star's 'via CLI' wording; application.cpp flow)."""
        conf = f"/root/reference/examples/{example}/train.conf"
        if not os.path.exists(conf):
            pytest.skip("reference examples not mounted")
        out = str(tmp_path / "model.txt")
        cwd = os.getcwd()
        os.chdir(tmp_path)
        try:
            Application([f"config={conf}", "num_trees=2", "verbose=-1",
                         f"output_model={out}"]).run()
        finally:
            os.chdir(cwd)
        text = open(out).read()
        assert text.startswith("tree")
        assert "Tree=" in text

    def test_parse_config_file(self, tmp_path):
        conf = str(tmp_path / "c.conf")
        with open(conf, "w") as fh:
            fh.write("# comment\nnum_trees = 7\nmetric = auc # tail\n")
        kv = parse_config_file(conf)
        assert kv["num_trees"] == "7"
        assert kv["metric"] == "auc"


class TestOwnExamples:
    """This repo's own self-contained examples/ (generated data)."""

    @pytest.mark.parametrize("example", [
        "binary_classification", "regression",
        "multiclass_classification", "lambdarank", "parallel_learning"])
    def test_own_example_configs(self, tmp_path, example):
        import subprocess
        import sys as _sys
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        exdir = os.path.join(repo, "examples", example)
        train_file = {
            "binary_classification": "binary.train",
            "regression": "regression.train",
            "multiclass_classification": "multiclass.train",
            "lambdarank": "rank.train",
            "parallel_learning": "binary.train",
        }[example]
        data = os.path.join(exdir, train_file)
        if not os.path.exists(data):
            subprocess.run(
                [_sys.executable,
                 os.path.join(repo, "examples", "generate_data.py")],
                check=True, capture_output=True)
        conf = os.path.join(exdir, "train.conf")
        out = str(tmp_path / "model.txt")
        cwd = os.getcwd()
        os.chdir(tmp_path)
        try:
            Application([f"config={conf}", "num_trees=2", "verbose=-1",
                         f"output_model={out}"]).run()
        finally:
            os.chdir(cwd)
        text = open(out).read()
        assert text.startswith("tree") and "Tree=" in text


class TestPipelinedTrainLoop:
    """GBDT.train's one-iteration-lookahead evaluation must reproduce
    the synchronous path's early-stopping behavior exactly: same metric
    values per iteration, same stop iteration, same kept model."""

    def _fit(self, sync, tmp_path, tag):
        import numpy as np
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.io.dataset import TpuDataset, Metadata
        from lightgbm_tpu.metrics import create_metrics
        from lightgbm_tpu.models.gbdt import GBDT
        from lightgbm_tpu.objectives import create_objective
        r = np.random.default_rng(3)
        X = r.normal(size=(900, 5))
        # noisy labels so validation loss bottoms out and the stop FIRES
        y = ((X[:, 0] + 0.4 * X[:, 1] + 1.2 * r.normal(size=900))
             > 0).astype(np.float32)
        Xv = r.normal(size=(400, 5))
        yv = ((Xv[:, 0] + 0.4 * Xv[:, 1] + 1.2 * r.normal(size=400))
              > 0).astype(np.float32)
        cfg = Config().set({
            "objective": "binary", "metric": "binary_logloss",
            "num_leaves": 31, "max_bin": 63, "num_iterations": 40,
            "early_stopping_round": 3, "metric_freq": 1,
            "min_data_in_leaf": 5})
        ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
        obj = create_objective("binary", cfg)
        obj.init(ds.metadata, ds.num_data)
        g = GBDT()
        g.init(cfg, ds, obj, [])
        vd = TpuDataset(cfg).construct_from_matrix(
            Xv, Metadata(label=yv), reference=ds)
        vm = create_metrics(["binary_logloss"], cfg, vd.metadata,
                            vd.num_data)
        g.add_valid_data(vd, vm, "v")
        if sync:
            g._eval_dispatch = lambda it: None   # force sync fallback
        out = tmp_path / f"{tag}.txt"
        g.train(output_model=str(out))
        return g, out.read_text()

    def test_pipelined_matches_sync_early_stopping(self, tmp_path):
        gs, ms = self._fit(True, tmp_path, "sync")
        gp, mp = self._fit(False, tmp_path, "pipe")
        # the stop must actually FIRE (otherwise the lookahead drop
        # bookkeeping this test exists for is never exercised)
        assert len(gs.records) < 40, "early stopping did not trigger"
        # identical kept model (stop at the same iteration, same trees)
        assert len(gp.records) == len(gs.records)
        assert mp == ms
        # and identical best-iteration bookkeeping
        assert gp._best_iter == gs._best_iter
