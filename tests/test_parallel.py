"""Distributed tree learner tests on the 8-device virtual CPU mesh —
the in-process multi-worker coverage the reference never had
(SURVEY.md §4.4: the reference's parallel learners are only exercised
manually via examples/parallel_learning)."""
import numpy as np
import pytest

from lightgbm_tpu.utils.device import get_devices

from conftest import fit_gbdt, make_binary, make_regression

pytestmark = pytest.mark.skipif(
    len(get_devices()) < 2, reason="needs multi-device mesh")


def _auc(g):
    return dict((n, v) for n, v, _ in g.get_eval_at(0))["auc"]


@pytest.fixture(scope="module")
def serial_binary():
    X, y = make_binary()
    g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc"},
                 num_round=15)
    return g, X, y


class TestDataParallel:
    def test_matches_serial(self, serial_binary):
        gs, X, y = serial_binary
        gd = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                             "tree_learner": "data"}, num_round=15)
        assert gd._learner_mode == "data"
        # identical data + deterministic splits -> identical models
        np.testing.assert_allclose(
            gd.predict_raw(X[:200]), gs.predict_raw(X[:200]),
            rtol=1e-4, atol=1e-4)

    def test_quality(self):
        X, y = make_binary(1282)  # deliberately not divisible by 8
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "tree_learner": "data"}, num_round=15)
        assert _auc(g) > 0.97


class TestFeatureParallel:
    def test_matches_serial(self, serial_binary):
        gs, X, y = serial_binary
        gf = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                             "tree_learner": "feature"}, num_round=15)
        assert gf._learner_mode == "feature"
        np.testing.assert_allclose(
            gf.predict_raw(X[:200]), gs.predict_raw(X[:200]),
            rtol=1e-4, atol=1e-4)


class TestVotingParallel:
    def test_quality(self):
        # voting is an approximation (top-k election) — assert quality,
        # not exact equality with serial
        X, y = make_binary()
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "tree_learner": "voting", "top_k": 5},
                     num_round=15)
        assert g._learner_mode == "voting"
        assert _auc(g) > 0.95

    def test_elects_signal_features(self):
        X, y = make_binary()
        g = fit_gbdt(X, y, {"objective": "binary",
                            "tree_learner": "voting", "top_k": 3},
                     num_round=15)
        imp = g.feature_importance("split")
        assert imp[:4].sum() > imp[4:].sum()


class TestRegressionParallel:
    def test_data_parallel_l2(self):
        X, y = make_regression()
        g = fit_gbdt(X, y, {"objective": "regression", "metric": "l2",
                            "tree_learner": "data"}, num_round=20)
        (_, l2, _), = g.get_eval_at(0)
        assert l2 < 0.4 * np.var(y)

    def test_data_parallel_l1_odd_rows(self):
        # regression: padded mask + leaf renewal with n % devices != 0
        r = np.random.default_rng(11)
        X = r.normal(size=(1283, 6))
        y = (2 * X[:, 0] + 0.1 * r.normal(size=1283)).astype(np.float32)
        g = fit_gbdt(X, y, {"objective": "regression_l1", "metric": "l1",
                            "tree_learner": "data"}, num_round=8)
        (_, l1, _), = g.get_eval_at(0)
        assert l1 < np.mean(np.abs(y - np.median(y)))


class TestSerialFallback:
    def test_single_machine_requested(self):
        X, y = make_binary(640)
        g = fit_gbdt(X, y, {"objective": "binary",
                            "tree_learner": "data", "num_machines": 1},
                     num_round=3)
        # num_machines=1 -> mesh over all local devices still engages
        assert g._learner_mode == "data"


class TestFusedDistributed:
    """The fused partition+histogram kernel under shard_map (the real
    multi-chip path: per-shard Pallas pass + histogram psum), forced
    into interpret mode on the CPU mesh."""

    def test_data_parallel_fused_matches_serial(self):
        from lightgbm_tpu.ops.split import SplitParams
        from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                                  make_wave_grower)
        from lightgbm_tpu.parallel.learners import (
            make_data_parallel_grower, make_mesh)
        import jax.numpy as jnp

        r = np.random.default_rng(3)
        N, F, B = 1024, 8, 63
        bins = r.integers(0, B, (N, F)).astype(np.uint8)
        bins_t = jnp.asarray(np.ascontiguousarray(bins.T))
        grad = jnp.asarray(r.normal(size=N).astype(np.float32))
        hess = jnp.full(N, 0.25, jnp.float32)
        mask = jnp.ones(N, jnp.float32)
        fmask = jnp.ones(F, bool)
        from lightgbm_tpu.ops.split import FeatureMeta
        meta = FeatureMeta(
            num_bin=np.full(F, B, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        cfg = WaveGrowerConfig(num_leaves=15, num_bins=B, wave_size=8,
                               fused=True, chunk=128,
                               hp=SplitParams(min_data_in_leaf=5))
        serial = make_wave_grower(cfg, meta)
        rec_s, leaf_s = serial(bins_t, grad, hess, mask, fmask)

        mesh = make_mesh()
        dp = make_data_parallel_grower(cfg, meta, mesh)
        rec_d, leaf_d = dp(bins_t, grad, hess, mask, fmask)
        assert int(rec_d.num_leaves) == int(rec_s.num_leaves)
        np.testing.assert_array_equal(np.asarray(rec_d.split_feature),
                                      np.asarray(rec_s.split_feature))
        np.testing.assert_allclose(np.asarray(rec_d.leaf_output),
                                   np.asarray(rec_s.leaf_output),
                                   atol=1e-4)
        np.testing.assert_array_equal(np.asarray(leaf_d),
                                      np.asarray(leaf_s))

    def test_voting_fused_quality(self):
        X, y = make_binary(2048)
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "tree_learner": "voting", "top_k": 5},
                     num_round=15)
        # 256 rows/shard with a 5-feature vote is the reference's
        # "small data per machine" regime — approximation costs a hair
        assert _auc(g) > 0.96


class TestCollectiveInjection:
    """The external-collective seam (network.cpp:41-54,
    LGBM_NetworkInitWithFunctions): injected wrappers observe/replace
    the learners' collectives."""

    def test_counting_reducer_observes_psum_sites(self):
        from lightgbm_tpu import capi
        calls = {"rs": 0, "ag": 0}

        def counting_reduce(x, default):
            calls["rs"] += 1
            return default(x)

        def counting_allgather(x, default):
            calls["ag"] += 1
            return default(x)

        capi.LGBM_NetworkInitWithFunctions(
            8, 0, reduce_scatter_fn=counting_reduce,
            allgather_fn=counting_allgather)
        try:
            X, y = make_binary(640)
            g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                                "tree_learner": "data"}, num_round=3)
            assert g._learner_mode == "data"
            assert calls["rs"] > 0          # psum sites traced through
        finally:
            capi.LGBM_NetworkFree()
        from lightgbm_tpu.parallel.learners import _collective_overrides
        assert not _collective_overrides   # NetworkFree cleared the seam

    def test_replacing_reducer_changes_result(self):
        """A replacing override (ignores the default collective) must
        actually flow into the compiled program: scaling every reduction
        by 1 device-count leaves a single-shard... instead verify a
        broken reducer (identity, no psum) degrades data-parallel into
        shard-local training — trees differ from the proper run."""
        from lightgbm_tpu import capi
        X, y = make_binary(640)
        proper = fit_gbdt(X, y, {"objective": "binary",
                                 "tree_learner": "data"}, num_round=3)
        capi.LGBM_NetworkInitWithFunctions(
            8, 0, reduce_scatter_fn=lambda x, default: x)
        try:
            broken = fit_gbdt(X, y, {"objective": "binary",
                                     "tree_learner": "data"},
                              num_round=3)
        finally:
            capi.LGBM_NetworkFree()
        a = proper.predict_raw(X[:100])
        b = broken.predict_raw(X[:100])
        assert np.abs(a - b).max() > 1e-6


class TestDataParallelQuantized:
    """int8 quantized histograms + count-proxy under the data-parallel
    learner: global pmax quantization scales keep the proxy's count
    bounds valid on the psummed histogram and identical on every shard
    (shard-local scales would silently diverge the replicated tree)."""

    def test_quant_proxy_trains_and_counts_exact(self):
        X, y = make_binary(1282)
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "tree_learner": "data",
                            "tpu_quantized_hist": True}, num_round=12)
        assert g._learner_mode == "data"
        assert g._grower_cfg.count_proxy
        assert _auc(g) > 0.97
        # per-leaf counts are partition-mask exact: recount from the
        # training-data leaf assignments of the last tree
        g._ensure_host_trees()
        rec = g.records[-1]
        nl = int(np.asarray(rec.num_leaves))
        leaves = g.models[-1].predict_leaf_index(X)
        recount = np.bincount(leaves, minlength=nl)[:nl]
        np.testing.assert_array_equal(
            np.asarray(rec.leaf_count)[:nl], recount)

    def test_quant_exact_counts_mode(self):
        X, y = make_binary(1282)
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "tree_learner": "data",
                            "tpu_quantized_hist": True,
                            "tpu_count_proxy": 0}, num_round=12)
        assert not g._grower_cfg.count_proxy
        assert _auc(g) > 0.97


class TestFeatureParallelQuantized:
    def test_quant_matches_serial_quant(self):
        """Quantized histograms compose with the feature-parallel
        learner: every device holds all rows, so scales and the
        stochastic-rounding stream are identical and the feature-sliced
        int8 histograms agree with the serial quantized run exactly."""
        X, y = make_binary()
        # tpu_count_proxy=0: serial would otherwise auto-enable the
        # count-proxy gate (feature mode keeps exact counts), and the
        # two gates can prune differently near min_data boundaries
        gs = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                             "tpu_quantized_hist": True,
                             "tpu_count_proxy": 0}, num_round=12)
        gf = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                             "tree_learner": "feature",
                             "tpu_quantized_hist": True}, num_round=12)
        assert gf._learner_mode == "feature"
        assert gf._grower_cfg.precision == "int8"
        np.testing.assert_allclose(
            gf.predict_raw(X[:200]), gs.predict_raw(X[:200]),
            rtol=1e-4, atol=1e-4)
        assert _auc(gf) > 0.97


class TestScaleReadiness:
    """Compiled-artifact evidence that the data-parallel path is
    multi-chip ready: the lowered program must reduce wave histograms
    with XLA all-reduce collectives (riding ICI on real hardware), and
    the per-step collective payload must match the W x F x B x C
    histogram block the design doc projects scaling from."""

    def test_data_parallel_hlo_contains_histogram_allreduce(self):
        import jax
        import jax.numpy as jnp
        from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
        from lightgbm_tpu.ops.wave_grower import WaveGrowerConfig
        from lightgbm_tpu.parallel.learners import (
            make_data_parallel_grower, make_mesh)
        F, n, B, W = 4, 1024, 16, 8
        meta = FeatureMeta(
            num_bin=np.full(F, B, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        cfg = WaveGrowerConfig(num_leaves=15, num_bins=B, wave_size=W,
                               hp=SplitParams(min_data_in_leaf=1),
                               precision="default")
        mesh = make_mesh()
        grow = make_data_parallel_grower(cfg, meta, mesh)
        r = np.random.default_rng(0)
        args = (jnp.asarray(r.integers(0, B, (F, n)), jnp.uint8),
                jnp.asarray(r.normal(size=n), jnp.float32),
                jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
                jnp.ones(F, bool))
        hlo = grow.lower(*args).compile().as_text()
        # the wave-histogram psum lowers to all-reduce over the mesh
        assert "all-reduce" in hlo, "no collective in data-parallel HLO"
        # and the payload includes the full [W, F, B, 3] f32 histogram
        # block (917 KB/wave at the HIGGS bench shape, projected in
        # README's scaling table)
        import re as _re
        shapes = _re.findall(r"all-reduce\.?\d*\s*=\s*\(?([^)=]*)", hlo)
        assert any(f"{W},{F},{B}" in s.replace(" ", "")
                   for s in shapes) or "f32[8,4,16" in hlo.replace(
                       " ", ""), "histogram block not in any all-reduce"

    def test_data_parallel_keeps_fused_kernel_per_shard(self):
        """The fused partition+histogram Pallas kernel must stay live
        INSIDE the shard_map (each chip runs the single-chip kernel on
        its rows; only histograms cross the interconnect)."""
        import jax
        import jax.numpy as jnp
        from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
        from lightgbm_tpu.ops.wave_grower import WaveGrowerConfig
        from lightgbm_tpu.parallel.learners import (
            make_data_parallel_grower, make_mesh)
        F, n, B, W = 4, 1024, 16, 8
        meta = FeatureMeta(
            num_bin=np.full(F, B, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        # fused=True + use_pallas left None: on the CPU test backend the
        # kernel lowers through interpret mode, which still names the
        # custom call in the jaxpr
        cfg = WaveGrowerConfig(num_leaves=15, num_bins=B, wave_size=W,
                               hp=SplitParams(min_data_in_leaf=1),
                               precision="default", fused=True,
                               chunk=256)
        mesh = make_mesh()
        grow = make_data_parallel_grower(cfg, meta, mesh)
        r = np.random.default_rng(0)
        args = (jnp.asarray(r.integers(0, B, (F, n)), jnp.uint8),
                jnp.asarray(r.normal(size=n), jnp.float32),
                jnp.ones(n, jnp.float32), jnp.ones(n, jnp.float32),
                jnp.ones(F, bool))
        jaxpr = str(jax.make_jaxpr(lambda *a: grow(*a))(*args))
        assert "shard_map" in jaxpr or "psum" in jaxpr
        rec, leaf = grow(*args)       # executes on the 8-device mesh
        assert int(rec.num_leaves) > 1
