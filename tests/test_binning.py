"""BinMapper semantics tests (reference: src/io/bin.cpp:74-208
GreedyFindBin / FindBinWithZeroAsOneBin, bin.h:452-488 ValueToBin)."""
import numpy as np
import pytest

from lightgbm_tpu.io.binning import BinMapper, BinType, MissingType
from lightgbm_tpu.io.dataset import TpuDataset, Metadata
from lightgbm_tpu.config import Config


def _fit_mapper(values, max_bin=255, **kw):
    values = np.asarray(values, np.float64)
    nz = values[(np.abs(values) > 1e-35) | np.isnan(values)]
    m = BinMapper()
    m.find_bin(nz, len(values), max_bin, kw.pop("min_data_in_bin", 3),
               kw.pop("filter_cnt", 0), kw.pop("bin_type", BinType.NUMERICAL),
               kw.pop("use_missing", True), kw.pop("zero_as_missing", False))
    return m


class TestNumerical:
    def test_monotone_bounds(self):
        r = np.random.default_rng(0)
        v = r.normal(size=5000)
        m = _fit_mapper(v, max_bin=63)
        assert 2 <= m.num_bin <= 63
        # value_to_bin must be monotone in value
        xs = np.sort(r.normal(size=1000))
        bins = m.value_to_bin(xs)
        assert np.all(np.diff(bins) >= 0)

    def test_roundtrip_ordering(self):
        r = np.random.default_rng(1)
        v = r.uniform(-10, 10, size=2000)
        m = _fit_mapper(v, max_bin=31)
        for b in range(1, m.num_bin - 1):
            lo = m.bin_to_value(b - 1)
            hi = m.bin_to_value(b)
            assert lo <= hi

    def test_few_distinct_values_exact_bins(self):
        v = np.array([1.0, 2.0, 3.0] * 100)
        m = _fit_mapper(v, max_bin=255)
        b1 = m.value_to_bin(np.array([1.0]))[0]
        b2 = m.value_to_bin(np.array([2.0]))[0]
        b3 = m.value_to_bin(np.array([3.0]))[0]
        assert len({int(b1), int(b2), int(b3)}) == 3

    def test_nan_goes_to_last_bin(self):
        r = np.random.default_rng(2)
        v = r.normal(size=1000)
        v[::10] = np.nan
        m = _fit_mapper(v)
        assert m.missing_type == MissingType.NAN
        nb = m.value_to_bin(np.array([np.nan]))[0]
        assert nb == m.num_bin - 1

    def test_zero_as_missing(self):
        r = np.random.default_rng(3)
        v = r.normal(size=1000)
        v[::5] = 0.0
        m = _fit_mapper(v, zero_as_missing=True)
        assert m.missing_type == MissingType.ZERO

    def test_trivial_constant_feature(self):
        v = np.full(100, 3.14)
        m = _fit_mapper(v)
        assert m.is_trivial or m.num_bin <= 2


class TestCategorical:
    def test_categories_to_distinct_bins(self):
        r = np.random.default_rng(4)
        v = r.integers(0, 10, size=2000).astype(np.float64)
        m = _fit_mapper(v, bin_type=BinType.CATEGORICAL)
        bins = m.value_to_bin(np.arange(10, dtype=np.float64))
        # the most frequent categories must all get distinct bins
        assert len(set(int(b) for b in bins)) >= 9

    def test_unseen_category_to_last_bin(self):
        # reference ValueToBin (bin.h:482-487): unseen/negative -> num_bin-1
        v = np.array([1.0, 2.0, 3.0] * 50)
        m = _fit_mapper(v, bin_type=BinType.CATEGORICAL)
        assert int(m.value_to_bin(np.array([99.0]))[0]) == m.num_bin - 1
        assert int(m.value_to_bin(np.array([-5.0]))[0]) == m.num_bin - 1


class TestDataset:
    def test_trivial_features_excluded(self):
        r = np.random.default_rng(5)
        X = r.normal(size=(500, 5))
        X[:, 3] = 7.0  # constant
        cfg = Config().set({"objective": "regression"})
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=r.normal(size=500)))
        assert ds.num_features == 4
        assert 3 not in set(ds.used_feature_map.tolist())
        infos = ds.feature_infos()
        assert infos[3] == "none"

    def test_valid_reuses_mappers(self):
        r = np.random.default_rng(6)
        X = r.normal(size=(500, 4))
        cfg = Config().set({"objective": "regression"})
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=r.normal(size=500)))
        Xv = r.normal(size=(100, 4))
        vd = ds.create_valid(Xv, Metadata(label=r.normal(size=100)))
        assert vd.mappers is ds.mappers
        assert vd.num_data == 100

    def test_binary_cache_roundtrip(self, tmp_path):
        r = np.random.default_rng(7)
        X = r.normal(size=(300, 4))
        y = r.normal(size=300)
        cfg = Config().set({"objective": "regression"})
        ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
        fn = str(tmp_path / "data.bin")
        ds.save_binary(fn)
        assert TpuDataset.is_binary_file(fn)
        ds2 = TpuDataset.load_binary(fn, cfg)
        np.testing.assert_array_equal(ds.bins, ds2.bins)
        np.testing.assert_array_equal(ds.metadata.label, ds2.metadata.label)

    def test_max_bin_respected(self):
        r = np.random.default_rng(8)
        X = r.normal(size=(2000, 3))
        cfg = Config().set({"objective": "regression", "max_bin": 15})
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=r.normal(size=2000)))
        assert all(m.num_bin <= 15 for m in ds.mappers)


class TestNibblePackedCache:
    def test_binary_cache_roundtrip_with_4bit_columns(self, tmp_path):
        """Columns with <= 16 bins nibble-pack in the binary cache
        (Dense4bitsBin storage tier) and round-trip bit-exactly."""
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.io.dataset import Metadata, TpuDataset

        r = np.random.default_rng(7)
        n = 1001                           # odd: exercises the tail row
        X = np.column_stack([
            r.integers(0, 3, n),           # few bins -> packed
            r.normal(size=n),              # many bins -> unpacked
            r.integers(0, 5, n),           # packed
        ]).astype(np.float64)
        cfg = Config().set({"objective": "binary", "max_bin": 63,
                            "min_data_in_leaf": 1, "min_data_in_bin": 1})
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=r.uniform(size=n).astype(np.float32)))
        packed_repr, packed_cols = ds._pack_nibble_columns()
        assert len(packed_cols) == 2       # the two low-cardinality cols
        f = tmp_path / "c.bin"
        ds.save_binary(str(f))
        loaded = TpuDataset.load_binary(str(f), cfg)
        np.testing.assert_array_equal(loaded.bins, ds.bins)
        np.testing.assert_array_equal(loaded.metadata.label,
                                      ds.metadata.label)
