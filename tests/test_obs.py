"""Observability subsystem (lightgbm_tpu/obs/): registry semantics,
thread-safety under a hammer, run-report round-trip + versioning, the
slow-iteration watchdog, profiler smoke, end-to-end run reports from
both training drivers, and the phase-attribution lint.

Run with ``pytest -m obs``.
"""
import json
import os
import re
import threading

import pytest

from conftest import TEST_PARAMS, make_binary, make_regression

from lightgbm_tpu.obs.recorder import (RUN_REPORT_SCHEMA,
                                       RUN_REPORT_VERSION, RunRecorder,
                                       load_run_report)
from lightgbm_tpu.obs.registry import MetricsRegistry
from lightgbm_tpu.utils import log, timing

pytestmark = pytest.mark.obs

PKG = os.path.join(os.path.dirname(__file__), os.pardir, "lightgbm_tpu")


@pytest.fixture(autouse=True)
def _info_log_level():
    """Pin the global log level: earlier suite tests pass verbose=-1,
    which flips the process-wide level to FATAL and would swallow the
    info/warning lines these tests capture."""
    prev = log.get_level()
    log.set_level(log.LogLevel.INFO)
    yield
    log.set_level(prev)


# -- registry semantics ------------------------------------------------------

def test_counter_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("c")
    c.add()
    c.add(41)
    assert c.value == 42
    assert reg.counter("c") is c           # get-or-create returns same
    g = reg.gauge("g")
    assert g.value is None
    g.set(7)
    g.set(3.5)
    assert g.value == 3.5
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 42
    assert snap["gauges"]["g"] == 3.5


def test_histogram_buckets_and_percentiles():
    reg = MetricsRegistry()
    h = reg.histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 2.0, 3.0, 5.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(10.5)
    # ranks: p25 -> first bucket (<=1), p50 -> <=2, p75 -> <=4,
    # p100 -> overflow reports the observed max
    assert h.percentile(0.25) == 1.0
    assert h.percentile(0.5) == 2.0
    assert h.percentile(0.75) == 4.0
    assert h.percentile(1.0) == 5.0
    snap = h.snapshot()
    assert snap["count"] == 4 and snap["overflow"] == 1
    assert snap["min"] == 0.5 and snap["max"] == 5.0
    empty = reg.histogram("empty")
    assert empty.percentile(0.5) is None


def test_timer_total_count_max():
    reg = MetricsRegistry()
    t = reg.timer("t")
    t.add(0.25)
    t.add(1.0)
    t.add(0.5)
    assert t.count == 3
    assert t.total == pytest.approx(1.75)
    assert t.max == 1.0
    assert reg.snapshot()["phases"]["t"]["calls"] == 3


def test_timing_feeds_registry_and_report_order():
    """timing.add/phase store in the obs registry; report() sorts by
    total DESC and shows a max column."""
    timing.reset()
    timing.add("small", 0.001)
    timing.add("big", 2.0)
    timing.add("big", 1.0)
    with timing.phase("phased"):
        pass
    from lightgbm_tpu.obs import registry as obs
    items = {n: (tot, cnt) for n, tot, cnt, _ in
             obs.default_registry().timer_items()}
    assert items["big"][1] == 2 and items["phased"][1] == 1
    rep = timing.report()
    lines = rep.splitlines()
    assert lines[0].split()[0] == "big"     # dominant phase first
    assert "ms max" in lines[0]
    assert timing.seconds("big") == pytest.approx(3.0)
    timing.reset()
    assert timing.report() == ""


# -- thread-safety hammer ----------------------------------------------------

def test_registry_hammer_thread_safety():
    """N threads x M mutations on shared instruments (the ingest
    prefetch worker records from off-thread while the main thread
    accumulates phases): totals must be exact, no lost updates."""
    reg = MetricsRegistry()
    N, M = 8, 2000
    errs = []

    def work():
        try:
            c = reg.counter("bytes")
            t = reg.timer("phase")
            h = reg.histogram("lat")
            for i in range(M):
                c.add(3)
                t.add(0.001)
                h.observe(0.002)
                reg.gauge("hbm").set(i)
        except Exception as e:              # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=work) for _ in range(N)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    assert reg.counter("bytes").value == 3 * N * M
    t = reg.timer("phase")
    assert t.count == N * M
    # every addition is the same fp op under the lock -> deterministic
    ref = 0.0
    for _ in range(N * M):
        ref += 0.001
    assert t.total == ref
    assert reg.histogram("lat").count == N * M


def test_timing_module_hammer_thread_safety():
    """The module-level timing API (the one the ingest worker calls)
    under the same hammer — the historical race was here."""
    timing.reset()
    N, M = 8, 1000

    def work():
        for _ in range(M):
            timing.add("hammer/add", 0.0001)
            with timing.phase("hammer/phase"):
                pass

    threads = [threading.Thread(target=work) for _ in range(N)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    from lightgbm_tpu.obs import registry as obs
    items = {n: cnt for n, _, cnt, _ in
             obs.default_registry().timer_items()}
    assert items["hammer/add"] == N * M
    assert items["hammer/phase"] == N * M
    timing.reset()


# -- run report --------------------------------------------------------------

def _small_report(path):
    reg = MetricsRegistry()
    reg.counter("ingest/h2d_bytes").add(1234)
    rec = RunRecorder(path=path, meta={"driver": "test"},
                      registry=reg).start()
    rec.observe_iteration(1, 0.01)
    rec.observe_iteration(2, 0.02)
    rec.record_eval(2, "training", "l2", 0.5)
    return rec.finish(leaves_per_iteration=[[7], [9]],
                      waves_per_iteration=[1, 1],
                      extra={"note": "x"})


@pytest.mark.parametrize("name", ["run.json", "run.jsonl"])
def test_run_report_roundtrip(tmp_path, name):
    path = str(tmp_path / name)
    built = _small_report(path)
    assert built["schema"] == RUN_REPORT_SCHEMA
    loaded = load_run_report(path)
    assert loaded["version"] == RUN_REPORT_VERSION
    assert loaded["meta"]["driver"] == "test"
    its = loaded["iterations"]
    assert [r["it"] for r in its] == [1, 2]
    assert its[0]["wall_s"] == pytest.approx(0.01)
    assert its[0]["leaves"] == [7] and its[1]["waves"] == 1
    assert its[1]["evals"]["training"]["l2"] == 0.5
    assert loaded["counters"]["ingest/h2d_bytes"] == 1234
    assert "train/iteration_s" in loaded["histograms"]
    assert loaded["extra"]["note"] == "x"


def test_run_report_version_refused(tmp_path):
    path = str(tmp_path / "run.json")
    _small_report(path)
    with open(path) as fh:
        doc = json.load(fh)
    doc["version"] = RUN_REPORT_VERSION + 1
    with open(path, "w") as fh:
        json.dump(doc, fh)
    with pytest.raises(ValueError, match="version"):
        load_run_report(path)
    with open(path, "w") as fh:
        json.dump({"schema": "something-else", "version": 1}, fh)
    with pytest.raises(ValueError, match="schema"):
        load_run_report(path)


def test_recorder_finish_idempotent(tmp_path):
    rec = RunRecorder(path=str(tmp_path / "r.json"),
                      registry=MetricsRegistry()).start()
    rec.observe_iteration(1, 0.01)
    first = rec.finish()
    assert first["iterations"]
    assert rec.finish() == {}               # second call is a no-op


# -- watchdog ----------------------------------------------------------------

def test_watchdog_triggers_on_slow_iteration():
    reg = MetricsRegistry()
    rec = RunRecorder(watchdog_factor=3.0, registry=reg).start()
    lines = []
    log.set_callback(lines.append)
    try:
        for it in range(1, 10):             # arm the trailing median
            rec.observe_iteration(it, 0.01)
        assert not any("slow iteration" in ln for ln in lines)
        rec.observe_iteration(10, 0.2)      # 20x the median
    finally:
        log.set_callback(None)
        rec.finish()
    hits = [ln for ln in lines if "slow iteration 10" in ln]
    assert hits and "phase table" in hits[0]
    assert reg.counter("watchdog/slow_iterations").value == 1


def test_watchdog_sync_spans_judged_separately():
    """Periodic drain iterations (kind="sync") legitimately absorb the
    queued dispatch backlog; they must be compared against other sync
    spans, not the issue-only iteration median — otherwise every drain
    interval would false-positive on an async backend."""
    reg = MetricsRegistry()
    rec = RunRecorder(watchdog_factor=3.0, registry=reg).start()
    lines = []
    log.set_callback(lines.append)
    try:
        for it in range(1, 41):
            if it % 8 == 0:             # the drain: 50x the issue time
                rec.observe_iteration(it, 0.5, kind="sync")
            else:
                rec.observe_iteration(it, 0.01)
    finally:
        log.set_callback(None)
        report = rec.finish()
    assert not any("slow iteration" in ln for ln in lines)
    assert report["iterations"][7]["sync"] is True
    assert "sync" not in report["iterations"][0]


def test_watchdog_disabled_at_zero_factor():
    rec = RunRecorder(watchdog_factor=0.0,
                      registry=MetricsRegistry()).start()
    lines = []
    log.set_callback(lines.append)
    try:
        for it in range(1, 10):
            rec.observe_iteration(it, 0.01)
        rec.observe_iteration(10, 5.0)
    finally:
        log.set_callback(None)
        rec.finish()
    assert not any("slow iteration" in ln for ln in lines)


# -- structured log prefix ---------------------------------------------------

def test_log_run_context_prefix():
    lines = []
    log.set_callback(lines.append)
    try:
        log.info("bare")
        log.set_run_context(lambda: (12.34, 140))
        log.info("prefixed")
        log.set_run_context(lambda: (1.0, None))
        log.info("no-iter")
        log.set_run_context(None)
        log.info("bare again")
    finally:
        log.set_run_context(None)
        log.set_callback(None)
    assert lines[0] == "[LightGBM-TPU] [Info] bare\n"
    assert lines[1] == "[LightGBM-TPU] [Info] [t+12.3s it=140] prefixed\n"
    assert lines[2] == "[LightGBM-TPU] [Info] [t+1.0s] no-iter\n"
    assert lines[3] == "[LightGBM-TPU] [Info] bare again\n"


def test_set_callback_thread_safe_under_writes():
    """set_callback flips while worker threads log: no exceptions, and
    every line lands in exactly one sink or stderr."""
    stop = threading.Event()
    errs = []

    def writer():
        try:
            while not stop.is_set():
                # debug under the default INFO level: the line is
                # filtered after the locked state read, so the race is
                # exercised without spamming stderr between flips
                log.debug("hammer line")
        except Exception as e:              # noqa: BLE001
            errs.append(e)

    sink = []
    threads = [threading.Thread(target=writer) for _ in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(200):
            log.set_callback(sink.append)
            log.set_callback(None)
    finally:
        stop.set()
        for th in threads:
            th.join()
        log.set_callback(None)
    assert not errs


# -- profiler ----------------------------------------------------------------

def test_profile_window_smoke(tmp_path):
    """tpu_profile_dir on the CPU backend produces a trace directory
    with capture files (skip where the profiler is unavailable)."""
    from lightgbm_tpu.obs import profiler as prof
    if not prof.profiler_available():
        pytest.skip("jax.profiler unavailable")
    import jax.numpy as jnp
    d = tmp_path / "trace"
    pw = prof.ProfileWindow(str(d), iters=2)
    for i in range(1, 5):
        pw.iter_begin(i)
        jnp.sum(jnp.arange(256)).block_until_ready()
        pw.iter_end(i)
    pw.close()
    if not pw.enabled:
        pytest.skip("start_trace failed on this backend")
    files = [p for p in d.rglob("*") if p.is_file()]
    assert files, "profiler produced no trace files"


def test_profile_window_iters_bracketing(monkeypatch, tmp_path):
    """iters=N starts at iteration 2 and stops after N iterations;
    iters=0 spans the whole run until close()."""
    from lightgbm_tpu.obs import profiler as prof
    calls = []
    monkeypatch.setattr(prof, "profiler_available", lambda: True)

    class FakeProfiler:
        @staticmethod
        def start_trace(d):
            calls.append(("start", d))

        @staticmethod
        def stop_trace():
            calls.append(("stop", None))

    import jax
    monkeypatch.setattr(jax, "profiler", FakeProfiler)
    pw = prof.ProfileWindow(str(tmp_path), iters=2)
    for i in range(1, 6):
        pw.iter_begin(i)
        pw.iter_end(i)
    pw.close()
    assert [c[0] for c in calls] == ["start", "stop"]
    calls.clear()
    pw = prof.ProfileWindow(str(tmp_path), iters=0)
    pw.iter_begin(1)
    pw.iter_end(1)
    assert [c[0] for c in calls] == ["start"]   # open until close
    pw.close()
    assert [c[0] for c in calls] == ["start", "stop"]


# -- end-to-end run reports --------------------------------------------------

def _fit_with_report(path, n_iter=8):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.metrics import create_metrics
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    X, y = make_regression(n=640)
    cfg = Config().set({**TEST_PARAMS, "objective": "regression",
                        "metric": "l2", "num_iterations": n_iter,
                        "is_provide_training_metric": True,
                        "tpu_run_report": path})
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    obj = create_objective("regression", cfg)
    obj.init(ds.metadata, ds.num_data)
    mets = create_metrics(["l2"], cfg, ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, mets)
    g.train()
    return g


def test_gbdt_train_writes_run_report(tmp_path):
    """The acceptance-shaped run: a CPU-backend training with
    tpu_run_report set produces a parseable report with per-iteration
    timings, the phase table, and >= 3 ingest/transfer counters."""
    path = str(tmp_path / "run.json")
    g = _fit_with_report(path, n_iter=8)
    rep = load_run_report(path)
    its = rep["iterations"]
    assert 1 <= len(its) <= 8
    assert all(r["wall_s"] > 0 for r in its)
    # leaves filled from ONE stacked download at finish; waves derived
    assert all(len(r["leaves"]) == 1 and r["leaves"][0] >= 1
               for r in its)
    assert all(r["waves"] >= 1 for r in its)
    # eval values captured per iteration
    assert its[0]["evals"]["training"]["l2"] > 0
    # phase table present, sorted by total desc
    totals = [v["total_s"] for v in rep["phases"].values()]
    assert totals == sorted(totals, reverse=True)
    assert "train/step_dispatch" in rep["phases"]
    assert rep["phases"]["train/step_dispatch"]["calls"] >= len(its)
    # >= 3 ingest/transfer counters (host binner + bulk upload + syncs)
    xfer = {k: v for k, v in rep["counters"].items()
            if k.startswith(("ingest/", "transfer/"))}
    assert len(xfer) >= 3, xfer
    assert rep["meta"]["driver"] == "gbdt.train"
    assert rep["extra"]["trained_iterations"] == g.iter_
    # the run prefix was uninstalled at finish
    lines = []
    log.set_callback(lines.append)
    try:
        log.info("post-run")
    finally:
        log.set_callback(None)
    assert "[t+" not in lines[0]


def test_engine_train_writes_run_report(tmp_path):
    """python-API path: engine.train with tpu_run_report spans
    iterations via the internal callback and writes the report."""
    from lightgbm_tpu.basic import Dataset
    from lightgbm_tpu.engine import train

    X, y = make_binary(n=640)
    path = str(tmp_path / "engine_run.jsonl")
    params = {**TEST_PARAMS, "objective": "binary", "metric": "auc",
              "tpu_run_report": path}
    d = Dataset(X, label=y)
    # valid = train set: exercises eval recording without compiling a
    # second valid-passenger grower shape (keeps tier-1 fast)
    bst = train(params, d, num_boost_round=4, valid_sets=[d],
                verbose_eval=False)
    assert bst.current_iteration() >= 1
    rep = load_run_report(path)
    assert rep["meta"]["driver"] == "engine.train"
    assert len(rep["iterations"]) >= 1
    assert all(r["wall_s"] > 0 for r in rep["iterations"])
    # the valid set's metric flowed through evaluation_result_list
    ev = rep["iterations"][0].get("evals", {})
    assert any("auc" in m for ds_m in ev.values() for m in ds_m)


# -- phase-attribution lint --------------------------------------------------

# phases that measure dispatch-issue time BY DESIGN (documented in
# models/gbdt.py: the fused step is async; its device time is drained
# by train/queue_drain and the pipelined eval materialization)
_WATCH_ALLOWLIST = {"train/step_dispatch"}
# a block "synchronizes itself" when it materializes to host or runs
# the self-syncing measure harness
_SYNC_TOKENS = (".watch(", "np.asarray", "timing.measure", "measure(")
_DISPATCH_TOKENS = ("jnp.", "jax.")


def _phase_blocks(path):
    """Yield (phase_name, block_text) for every `with timing.phase(...)`
    in a source file (block = following lines with deeper indent)."""
    src = open(path).read().splitlines()
    pat = re.compile(r"with timing\.phase\(\s*f?[\"']([^\"']+)[\"']")
    for i, ln in enumerate(src):
        m = pat.search(ln)
        if not m:
            continue
        indent = len(ln) - len(ln.lstrip())
        body = [ln]
        for nxt in src[i + 1:]:
            if nxt.strip() and (len(nxt) - len(nxt.lstrip())) <= indent:
                break
            body.append(nxt)
        yield m.group(1), "\n".join(body)


def test_phase_blocks_register_watch():
    """Every timing.phase block in ops/ and models/ that dispatches jax
    work must .watch(...) its output (or synchronize explicitly) so
    device time is attributed to the phase that issued it — otherwise
    it silently lands in whichever later phase first syncs."""
    offenders = []
    for sub in ("ops", "models"):
        root = os.path.join(PKG, sub)
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            for name, block in _phase_blocks(path):
                dispatches = any(t in block for t in _DISPATCH_TOKENS)
                synced = any(t in block for t in _SYNC_TOKENS)
                if (dispatches and not synced
                        and name not in _WATCH_ALLOWLIST):
                    offenders.append(f"{sub}/{fn}: {name}")
    assert not offenders, (
        "timing.phase blocks dispatch jax work without .watch()/sync "
        f"(device time will be misattributed): {offenders}")


# -- log-bucketed latency quantiles ------------------------------------------

def test_log_buckets_shape():
    from lightgbm_tpu.obs.registry import LATENCY_BUCKETS_S, log_buckets
    b = log_buckets(1e-3, 1.0, per_decade=10)
    assert b[0] == pytest.approx(1e-3)
    assert b[-1] >= 1.0
    assert len(b) == 31                     # 3 decades x 10 + 1
    ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
    assert all(r == pytest.approx(10 ** 0.1, rel=1e-9) for r in ratios)
    # the preset spans predict-dispatch to window-wall magnitudes
    assert LATENCY_BUCKETS_S[0] == pytest.approx(1e-6)
    assert LATENCY_BUCKETS_S[-1] >= 60.0


def test_quantiles_vs_numpy_percentile():
    """Interpolated histogram quantiles track numpy.percentile within
    one log-bucket's resolution on a realistic latency mixture."""
    import numpy as np

    from lightgbm_tpu.obs.registry import (MetricsRegistry,
                                           latency_histogram)
    rng = np.random.default_rng(3)
    # bimodal: fast path ~2ms + slow tail ~80ms (the serving shape)
    fast = rng.lognormal(np.log(2e-3), 0.25, size=4000)
    slow = rng.lognormal(np.log(8e-2), 0.3, size=250)
    samples = np.concatenate([fast, slow])
    reg = MetricsRegistry()
    h = latency_histogram("lat", reg)
    for v in samples:
        h.observe(float(v))
    bucket_ratio = 10 ** (1 / 12)           # adjacent bound spacing
    for q in (0.5, 0.9, 0.95, 0.99):
        est = h.percentile(q)
        ref = float(np.percentile(samples, 100 * q))
        assert ref / bucket_ratio <= est <= ref * bucket_ratio, \
            f"q={q}: est {est:g} vs numpy {ref:g}"
    # interpolation stays inside the observed range
    assert h.percentile(1.0) == pytest.approx(samples.max())
    snap = h.snapshot()
    assert snap["p95"] is not None and snap["p50"] < snap["p95"]


def test_quantiles_exact_degenerate_cases():
    from lightgbm_tpu.obs.registry import MetricsRegistry
    reg = MetricsRegistry()
    h = reg.histogram("one", buckets=(1.0, 2.0))
    h.observe(1.5)
    # a single sample reports itself regardless of bucket width
    assert h.percentile(0.5) == pytest.approx(1.5)
    const = reg.histogram("const", buckets=(1.0, 2.0))
    for _ in range(100):
        const.observe(1.5)
    for q in (0.01, 0.5, 0.99):
        assert const.percentile(q) == pytest.approx(1.5)
    assert reg.histogram("one").quantiles() == {
        "p50": pytest.approx(1.5), "p95": pytest.approx(1.5),
        "p99": pytest.approx(1.5), "p999": pytest.approx(1.5)}


# -- live metrics exporter ---------------------------------------------------

def _prom_lines_ok(text):
    """Every non-comment line is `name{labels} value` with a legal
    Prometheus metric name."""
    pat = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? \S+$")
    lines = [ln for ln in text.splitlines() if ln and
             not ln.startswith("#")]
    assert lines, "no samples rendered"
    for ln in lines:
        assert pat.match(ln), f"bad exposition line: {ln!r}"
    return lines


def test_prometheus_text_rendering():
    from lightgbm_tpu.obs.export import prometheus_text
    from lightgbm_tpu.obs.registry import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("ingest/h2d_bytes").add(1234)
    reg.gauge("device/hbm_bytes_in_use").set(5e8)
    reg.timer("train/step_dispatch").add(0.25)
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = prometheus_text(reg.snapshot())
    lines = _prom_lines_ok(text)
    joined = "\n".join(lines)
    assert "lgbm_tpu_ingest_h2d_bytes_total 1234" in joined
    assert "lgbm_tpu_device_hbm_bytes_in_use 500000000" in joined
    assert "lgbm_tpu_train_step_dispatch_seconds_total 0.25" in joined
    assert "lgbm_tpu_train_step_dispatch_calls_total 1" in joined
    # histogram: cumulative buckets + +Inf == count
    assert 'lgbm_tpu_lat_bucket{le="0.1"} 1' in joined
    assert 'lgbm_tpu_lat_bucket{le="1"} 2' in joined
    assert 'lgbm_tpu_lat_bucket{le="+Inf"} 3' in joined
    assert "lgbm_tpu_lat_count 3" in joined


def test_exporter_writes_during_run(tmp_path):
    """The exporter snapshots the registry DURING a run: .prom is
    replaced and .jsonl appended on the interval while counters are
    still moving — not a finish-time artifact."""
    import time as _t

    from lightgbm_tpu.obs.export import MetricsExporter
    from lightgbm_tpu.obs.registry import MetricsRegistry
    reg = MetricsRegistry()
    base = str(tmp_path / "live")
    ex = MetricsExporter(base_path=base, interval_s=0.05,
                         registry=reg).start()
    try:
        deadline = _t.monotonic() + 5.0
        while ex.snapshots_written < 3 and _t.monotonic() < deadline:
            reg.counter("work/items").add(1)
            _t.sleep(0.01)
        assert ex.snapshots_written >= 3
        # files exist and parse WHILE the run is still going
        text = open(ex.prom_path).read()
        _prom_lines_ok(text)
        assert "lgbm_tpu_work_items_total" in text
        rows = [json.loads(ln) for ln in open(ex.jsonl_path)]
        assert len(rows) >= 2
        assert rows[0]["ts"] <= rows[-1]["ts"]
        assert rows[-1]["counters"]["work/items"] >= 1
        # time series is append-only: later rows never lose counts
        counts = [r["counters"].get("work/items", 0) for r in rows]
        assert counts == sorted(counts)
    finally:
        ex.stop()
    # suffix stripping: pointing the knob at the .jsonl works too
    ex2 = MetricsExporter(base_path=base + ".jsonl", interval_s=5,
                          registry=reg)
    assert ex2.base_path == base


def test_exporter_http_endpoint(tmp_path):
    """GET /metrics over the stdlib server scrapes a live registry;
    /metrics.json returns the raw snapshot; others 404."""
    import urllib.error
    import urllib.request

    from lightgbm_tpu.obs.export import MetricsExporter
    from lightgbm_tpu.obs.registry import MetricsRegistry
    reg = MetricsRegistry()
    reg.counter("serve/requests").add(7)
    ex = MetricsExporter(base_path=str(tmp_path / "m"), interval_s=60,
                         port=0, registry=reg).start()
    try:
        port = ex.http_port
        assert port
        url = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
            assert r.status == 200
            body = r.read().decode()
        _prom_lines_ok(body)
        assert "lgbm_tpu_serve_requests_total 7" in body
        with urllib.request.urlopen(f"{url}/metrics.json",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["counters"]["serve/requests"] == 7
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{url}/other", timeout=10)
    finally:
        ex.stop()
    # the port is released after stop
    assert ex.http_port is None


def test_exporter_ensure_from_config_and_shutdown(tmp_path):
    from lightgbm_tpu.obs import export as obs_export
    obs_export.shutdown()
    try:
        assert obs_export.ensure_from_config({}) is None
        ex = obs_export.ensure_from_config(
            {"tpu_metrics_export": str(tmp_path / "g"),
             "tpu_metrics_interval_s": "30"})
        assert ex is not None and ex.interval_s == 30.0
        # later boosters JOIN the running exporter
        assert obs_export.ensure_from_config(
            {"tpu_metrics_export": str(tmp_path / "g")}) is ex
        assert os.path.exists(ex.prom_path)   # immediate first snapshot
    finally:
        obs_export.shutdown()
    assert obs_export.global_exporter() is None


def test_exporter_survives_port_in_use(tmp_path):
    """A taken (or bogus) HTTP port degrades to file-only export with
    a warning — never an exception out of GBDT init."""
    from lightgbm_tpu.obs.export import MetricsExporter
    from lightgbm_tpu.obs.registry import MetricsRegistry
    reg = MetricsRegistry()
    ex1 = MetricsExporter(base_path=str(tmp_path / "a"), interval_s=60,
                          port=0, registry=reg).start()
    lines = []
    log.set_callback(lines.append)
    try:
        ex2 = MetricsExporter(base_path=str(tmp_path / "b"),
                              interval_s=60, port=ex1.http_port,
                              registry=reg).start()
        assert ex2.http_port is None        # no server, no crash
        assert os.path.exists(ex2.prom_path)  # files still flow
        ex2.stop()
        ex3 = MetricsExporter(base_path=str(tmp_path / "c"),
                              interval_s=60, port=70000,
                              registry=reg).start()
        assert ex3.http_port is None
        ex3.stop()
    finally:
        log.set_callback(None)
        ex1.stop()
    assert sum("metrics HTTP endpoint" in ln for ln in lines) == 2


def test_exporter_unwritable_path_warns_once(tmp_path):
    """An unwritable export destination logs ONE diagnostic and keeps
    the run alive (snapshots keep silently retrying)."""
    from lightgbm_tpu.obs.export import MetricsExporter
    from lightgbm_tpu.obs.registry import MetricsRegistry
    bad = str(tmp_path / "f")
    (tmp_path / "f").write_text("")         # file where a DIR is needed
    lines = []
    log.set_callback(lines.append)
    try:
        ex = MetricsExporter(base_path=bad + "/sub/base",
                             interval_s=60,
                             registry=MetricsRegistry()).start()
        ex._write_once()                     # second failure: no spam
        ex.stop(final_snapshot=True)         # third: still quiet
    finally:
        log.set_callback(None)
    assert sum("metrics export" in ln and "failing" in ln
               for ln in lines) == 1


def test_exporter_config_mismatch_warns(tmp_path):
    from lightgbm_tpu.obs import export as obs_export
    obs_export.shutdown()
    lines = []
    log.set_callback(lines.append)
    try:
        ex = obs_export.ensure_from_config(
            {"tpu_metrics_export": str(tmp_path / "a")})
        assert obs_export.ensure_from_config(
            {"tpu_metrics_export": str(tmp_path / "b")}) is ex
    finally:
        log.set_callback(None)
        obs_export.shutdown()
    assert any("ignored for this process" in ln for ln in lines)


def test_lrb_window_wall_quantiles_per_driver():
    """A second driver's quantile summary must not inherit an earlier
    run's windows (the process-global instrument stays cumulative for
    the exporter; the summary is per-run)."""
    import io

    from lightgbm_tpu.lrb import LrbDriver
    d1 = LrbDriver(cache_size=1 << 16, window_size=256,
                   sample_size=128, cutoff=0.5, sampling=1,
                   result_file=io.StringIO())
    d1._wall_hist.observe(42.0)             # stand-in for a slow run
    assert d1.window_wall_quantiles()["p99"] == pytest.approx(42.0)
    d2 = LrbDriver(cache_size=1 << 16, window_size=256,
                   sample_size=128, cutoff=0.5, sampling=1,
                   result_file=io.StringIO())
    assert d2.window_wall_quantiles() is None


def test_training_run_feeds_live_exporter(tmp_path):
    """The config-wired path: a training run with tpu_metrics_export
    set starts the process-global exporter from GBDT.init and registry
    snapshots land on disk while the run proceeds."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.obs import export as obs_export
    from lightgbm_tpu.objectives import create_objective

    obs_export.shutdown()
    try:
        X, y = make_regression(n=640)
        cfg = Config().set({**TEST_PARAMS, "objective": "regression",
                            "num_iterations": 3,
                            "tpu_metrics_export": str(tmp_path / "live"),
                            "tpu_metrics_interval_s": 0.05})
        ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
        obj = create_objective("regression", cfg)
        obj.init(ds.metadata, ds.num_data)
        g = GBDT()
        g.init(cfg, ds, obj, ())
        ex = obs_export.global_exporter()
        assert ex is not None, "GBDT.init did not start the exporter"
        g.train()
        assert ex.snapshots_written >= 1
        rows = [json.loads(ln) for ln in open(ex.jsonl_path)]
        assert rows and rows[-1]["counters"]
        _prom_lines_ok(open(ex.prom_path).read())
    finally:
        obs_export.shutdown()


# -- report <-> trace cross-link ---------------------------------------------

def test_run_report_meta_gains_trace_path(tmp_path):
    """A training run with BOTH tpu_run_report and tpu_trace set
    cross-links them: the report's meta carries trace_path and the
    trace file exists with iteration spans by the time finish()
    returns."""
    from lightgbm_tpu.obs import trace
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective

    report_path = str(tmp_path / "run.json")
    trace_path = str(tmp_path / "run_trace.json")
    trace.stop()
    try:
        X, y = make_regression(n=640)
        cfg = Config().set({**TEST_PARAMS, "objective": "regression",
                            "num_iterations": 3,
                            "tpu_run_report": report_path,
                            "tpu_trace": trace_path})
        ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
        obj = create_objective("regression", cfg)
        obj.init(ds.metadata, ds.num_data)
        g = GBDT()
        g.init(cfg, ds, obj, ())
        g.train()
        rep = load_run_report(report_path)
        assert rep["meta"]["trace_path"] == trace_path
        doc = json.load(open(trace_path))
        names = {e["name"] for e in doc["traceEvents"]
                 if e["ph"] == "X"}
        assert "iteration" in names
        assert "train/step_dispatch" in names
    finally:
        trace.stop()


def test_obs_marker_registered():
    """`pytest -m obs` must select this suite: the marker is declared
    in pyproject (unknown markers would warn and select nothing)."""
    with open(os.path.join(PKG, os.pardir, "pyproject.toml")) as fh:
        doc = fh.read()
    assert re.search(r'^\s*"obs:', doc, re.M), \
        "pytest marker 'obs' missing from pyproject.toml"
