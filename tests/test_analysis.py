"""Static-analysis suite (pytest -m analysis): the repo's own
invariants, machine-checked.

Covers the three checkers (jit-capture, lock-discipline, contracts)
with positive/negative synthetic fixtures per rule, the two
HISTORICAL bug shapes (PR 5 closure recapture, PR 7 captured device
arrays) re-introduced in miniature under tests/fixtures/analysis/,
the baseline add/expire round-trip, the runtime lock-order detector
(deliberate A->B / B->A cycle), and the tier-1 wrapper: the repo
itself must analyze CLEAN with empty jit-capture and lock-discipline
baselines.
"""
import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from lightgbm_tpu.analysis import (contracts, jit_capture,  # noqa: E402
                                   lock_discipline, lockorder)
from lightgbm_tpu.analysis.core import (Baseline, Finding,  # noqa: E402
                                        NO_BASELINE_CHECKERS,
                                        SourceFile, UsageError)

pytestmark = pytest.mark.analysis

FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")


def _sf(text, rel="synthetic.py"):
    return SourceFile(rel, rel, text)


def _sf_file(name):
    path = os.path.join(FIXTURES, name)
    with open(path) as fh:
        return SourceFile(path, f"fixtures/{name}", fh.read())


def _jit(sources, fields=frozenset()):
    return jit_capture.check(
        sources if isinstance(sources, list) else [sources],
        set(fields))


# ---------------------------------------------------------------------------
# jit-capture: synthetic rule fixtures
# ---------------------------------------------------------------------------

def test_jit_capture_flags_array_capture():
    fs = _jit(_sf("""
import jax, numpy as np
def outer(y):
    labels = np.asarray(y)
    def step(bins):
        return bins * labels
    return jax.jit(step)
"""))
    assert len(fs) == 1 and fs[0].rule == "nonstatic-capture"
    assert "labels" in fs[0].message


def test_jit_capture_static_kinds_pass():
    # ints, bools, tuples of ints, config scalars, arithmetic,
    # identity tests, module globals: all allowlisted static kinds
    fs = _jit(_sf("""
import jax
HELPER = 3
def outer(cfg, n: int, flags: tuple, fn=None):
    k = n * 2 + 1
    lr = cfg.learning_rate
    offs = tuple(int(o) for o in cfg.whatever_list)
    has_fn = fn is not None
    def step(x):
        return x * k * lr + HELPER, offs, has_fn, flags, n
    return jax.jit(step)
"""), fields={"learning_rate"})
    assert fs == [], [f.render() for f in fs]


def test_jit_capture_module_level_decorators_pass():
    fs = _jit(_sf("""
import jax, functools
@jax.jit
def a(x):
    return x + 1
@functools.partial(jax.jit, static_argnames=("n",))
def b(x, n):
    return x * n
"""))
    assert fs == []


def test_jit_capture_named_waiver_with_reason():
    src = """
import jax, numpy as np
def outer(y):
    tbl = np.asarray(y)
    def chunk(x):
        return x + tbl
    # jit-capture: ok(tbl) — per-instance kernel constant
    return jax.jit(chunk)
"""
    assert _jit(_sf(src)) == []
    # a waiver WITHOUT a reason is no waiver
    src_noreason = src.replace(" — per-instance kernel constant", "")
    fs = _jit(_sf(src_noreason))
    assert len(fs) == 1


def test_jit_capture_wildcard_ok_for_plain_jit_only():
    plain = """
import jax, numpy as np
def outer(y):
    tbl = np.asarray(y)
    def chunk(x):
        return x + tbl
    # jit-capture: ok(*) — instance kernel, tables are constants
    return jax.jit(chunk)
"""
    assert _jit(_sf(plain)) == []
    registered = """
import jax, numpy as np
from x import step_cache
def outer(y, n: int):
    tbl = np.asarray(y)
    def builder():
        def step(x):
            return x + tbl
        return jax.jit(step)
    # jit-capture: ok(*) — should NOT be honored for the registry
    return step_cache.get_step(("k", n), builder)
"""
    fs = _jit(_sf(registered))
    assert len(fs) == 1 and "tbl" in fs[0].message
    assert "named waivers only" in fs[0].message


def test_jit_capture_key_covered_names_pass():
    fs = _jit(_sf("""
from x import predict_cache
def outer(self, n):
    bucket = self._bucket_for(n)        # not provably static...
    def build():
        def run(part):
            return part[:bucket]
        return run
    key = ("scan", bucket)              # ...but it IS the key
    return predict_cache.get(key, build)
"""))
    assert fs == [], [f.render() for f in fs]


def test_jit_capture_keyword_forms_not_a_bypass():
    # keyword-form registration/jit must be audited like positional
    kw_registry = _jit(_sf("""
import jax, numpy as np
from x import predict_cache
def outer(self, n: int):
    dev = self._device_arrays()
    def build():
        def run(part):
            return part + dev[0]
        return run
    return predict_cache.get(key=("k", n), builder=build)
"""))
    assert [f.detail.rsplit(":", 1)[-1] for f in kw_registry] == ["dev"]
    kw_jit = _jit(_sf("""
import jax, numpy as np
def outer(y):
    tbl = np.asarray(y)
    def step(x):
        return x + tbl
    return jax.jit(fun=step)
"""))
    assert len(kw_jit) == 1 and "tbl" in kw_jit[0].message
    # a registration with NO locatable builder must not pass silently
    no_builder = _jit(_sf("""
from x import step_cache
def outer(n: int, weird):
    return step_cache.get_step(("k", n), *weird)
"""))
    assert [f.rule for f in no_builder] == ["unresolvable-builder"]


def test_jit_capture_unresolvable_needs_waiver():
    fs = _jit(_sf("""
import jax
def outer(factory):
    sharded = factory()
    return jax.jit(sharded)
"""))
    assert len(fs) == 1 and fs[0].rule == "unresolvable"


def test_jit_capture_nested_closure_flagged():
    fs = _jit(_sf("""
import jax, numpy as np
def outer(y):
    tbl = np.asarray(y)
    def helper(x):
        return x + tbl
    def step(x):
        return helper(x)
    return jax.jit(step)
"""))
    assert len(fs) == 1 and "helper" in fs[0].message


def test_jit_capture_conditional_builders_both_audited():
    # two same-named defs: BOTH are possible runtime bindings
    fs = _jit(_sf("""
import jax, numpy as np
def outer(y, flag):
    bad = np.asarray(y)
    if flag:
        def step(x):
            return x
    else:
        def step(x):
            return x + bad
    return jax.jit(step)
"""))
    assert len(fs) == 1 and "bad" in fs[0].message


# ---------------------------------------------------------------------------
# jit-capture: the two historical bug shapes, in miniature
# ---------------------------------------------------------------------------

def test_pr5_closure_recapture_fixture_flagged():
    fs = _jit(_sf_file("pr5_closure_recapture_bug.py"))
    assert len(fs) == 1, [f.render() for f in fs]
    f = fs[0]
    assert f.rule == "nonstatic-capture" and "labels" in f.message
    assert "registered" in f.message        # registry-strict, no ok(*)


def test_pr5_closure_recapture_fixed_form_passes():
    assert _jit(_sf_file("pr5_closure_recapture_fixed.py")) == []


def test_pr7_captured_device_arrays_fixture_flagged():
    fs = _jit(_sf_file("pr7_captured_device_arrays_bug.py"))
    names = {f.detail.rsplit(":", 1)[-1] for f in fs}
    assert names == {"dev", "aux"}, [f.render() for f in fs]
    assert all(f.rule == "nonstatic-capture" for f in fs)


def test_pr7_captured_device_arrays_fixed_form_passes():
    assert _jit(_sf_file("pr7_captured_device_arrays_fixed.py")) == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_SRC = """
import threading
_lock = threading.Lock()
_reg = {}                         # guarded-by: _lock

class Server:
    def __init__(self):
        self._mu = threading.Lock()
        self._state = None        # guarded-by: _mu
        self._state = "init-write-is-exempt"

    def good(self):
        with self._mu:
            self._state = 1

    def helper_form(self):
        with self._guard():
            self._state = 2

    def bad(self):
        self._state = 3

    def bad_item(self):
        self._state["k"] = 4

    def bad_mutator(self):
        _reg.update(x=1)

    def waived(self):
        self._state = 5           # unguarded-ok: single-threaded CLI path

def module_good(k, v):
    with _lock:
        _reg[k] = v
"""


def test_lock_discipline_rules():
    fs = lock_discipline.check([_sf(LOCK_SRC)])
    details = sorted(f.detail for f in fs)
    # helper_form holds the WRONG lock (_guard() vs the declared _mu),
    # so it is flagged alongside the three bare writes; the __init__
    # write and the unguarded-ok waiver are exempt
    assert details == ["Server.bad:_state", "Server.bad_item:_state",
                       "Server.bad_mutator:_reg",
                       "Server.helper_form:_state"], \
        [f.render() for f in fs]


def test_lock_discipline_own_line_annotation():
    # the annotation may sit on its own comment line ABOVE a (long)
    # declaration, not just trail it — both forms must collect
    fs = lock_discipline.check([_sf("""
import threading, collections
class Ring:
    def __init__(self):
        self._mu = threading.Lock()
        # guarded-by: _mu
        self._slots: "collections.OrderedDict[int, tuple]" = \\
            collections.OrderedDict()
    def good(self, k, v):
        with self._mu:
            self._slots[k] = v
    def bad(self, k, v):
        self._slots[k] = v
""")])
    assert [f.detail for f in fs] == ["Ring.bad:_slots"], \
        [f.render() for f in fs]


def test_lock_discipline_helper_call_spec():
    fs = lock_discipline.check([_sf("""
import threading
class A:
    def __init__(self):
        self._cache = None        # guarded-by: _guard()
    def good(self):
        with self._guard():
            self._cache = 1
    def bad(self):
        with self._other():
            self._cache = 2
""")])
    assert [f.detail for f in fs] == ["A.bad:_cache"]


def test_lock_discipline_local_shadow_not_flagged():
    # a plain local that shadows an annotated module global can never
    # touch the global — only `global`-declared rebinds and
    # item/mutator writes reach it
    fs = lock_discipline.check([_sf("""
import threading
_lock = threading.Lock()
_steps = {}                       # guarded-by: _lock

def innocent():
    _steps = {"local": "temp"}    # new local, not the global
    return _steps

def guilty_rebind():
    global _steps
    _steps = {}

def guilty_item(k, v):
    _steps[k] = v
""")])
    assert sorted(f.detail for f in fs) == \
        ["guilty_item:_steps", "guilty_rebind:_steps"], \
        [f.render() for f in fs]


def test_lock_discipline_guarded_function_annotation():
    fs = lock_discipline.check([_sf("""
import threading
class A:
    def __init__(self):
        self._lk = threading.Lock()
        self._pending = None      # guarded-by: _lk

    # guarded-by: _lk
    def _drain_locked(self):
        self._pending = None      # body counts as guarded

    def good(self):
        with self._lk:
            self._drain_locked()

    def bad(self):
        self._drain_locked()      # call without the lock
""")])
    assert [f.rule for f in fs] == ["unguarded-call"], \
        [f.render() for f in fs]
    assert "bad" in fs[0].detail


# ---------------------------------------------------------------------------
# contracts
# ---------------------------------------------------------------------------

def _info(**kw):
    info = contracts.RepoInfo()
    info.config_fields = set(kw.get("fields", {"tpu_known"}))
    info.volatile_knobs = set(kw.get("volatile", ()))
    info.documented_knobs = set(
        kw.get("documented", info.config_fields))
    info.validated_knobs = set(kw.get("validated", ()))
    return info


def test_contracts_undeclared_knob():
    sf = _sf("""
def f(cfg, params):
    a = cfg.tpu_known
    b = params.get("tpu_unknown", 0)
    return a, b
""", rel="lightgbm_tpu/models/x.py")
    fs = contracts.check_knobs([sf], _info())
    assert [f.rule for f in fs] == ["undeclared-knob"]
    assert "tpu_unknown" in fs[0].message


def test_contracts_knob_function_attr_not_a_read():
    # autotune.tpu_compiler_params() is a FUNCTION, not a knob
    sf = _sf("""
def f(autotune):
    return autotune.tpu_compiler_params()
""", rel="lightgbm_tpu/ops/x.py")
    assert contracts.check_knobs([sf], _info()) == []


def test_contracts_telemetry_knob_classification():
    # a knob read ONLY from obs/ must be VOLATILE
    sf = _sf("def f(c):\n    return c.tpu_known\n",
             rel="lightgbm_tpu/obs/x.py")
    fs = contracts.check_knobs([sf], _info())
    assert [f.rule for f in fs] == ["unclassified-telemetry-knob"]
    assert contracts.check_knobs([sf], _info(
        volatile={"tpu_known"})) == []
    # a stale VOLATILE entry (renamed knob) is flagged
    fs = contracts.check_knobs([sf], _info(
        volatile={"tpu_known", "tpu_renamed_away"}))
    assert [f.rule for f in fs] == ["stale-volatile-entry"]


def test_contracts_metric_rules():
    sf = _sf("""
def f(obs, label):
    obs.counter("good/name").add(1)
    obs.counter("Bad-Name").add(1)
    obs.counter(f"dyn/{label}").add(1)
    # bounded-cardinality: label comes from a closed enum
    obs.counter(f"dyn2/{label}").add(1)
""", rel="lightgbm_tpu/obs/x.py")
    fs = contracts.check_metrics([sf])
    rules = sorted(f.rule for f in fs)
    assert rules == ["metric-cardinality", "metric-name"], \
        [f.render() for f in fs]


def test_contracts_artifact_rules():
    sf = _sf("""
def f(path):
    with open(path) as fh:              # read: fine
        fh.read()
    with open(path, "a") as fh:         # append stream: fine
        fh.write("x")
    with open(path, "w") as fh:         # torn-file hazard
        fh.write("x")
    # atomic-ok: crash-only debug dump, no concurrent reader
    with open(path, "w") as fh:
        fh.write("x")
""", rel="lightgbm_tpu/obs/x.py")
    fs = contracts.check_artifacts([sf])
    assert len(fs) == 1 and fs[0].rule == "non-atomic-write"
    # outside the obs/utils/tools scope: not this linter's business
    sf2 = _sf("def f(p):\n    open(p, 'w').write('x')\n",
              rel="lightgbm_tpu/models/x.py")
    assert contracts.check_artifacts([sf2]) == []


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------

def _finding(checker="contracts", rule="r", detail="d"):
    return Finding(checker, rule, "a.py", 3, "msg", detail)


def test_baseline_add_expire_roundtrip(tmp_path):
    f1, f2 = _finding(detail="one"), _finding(detail="two")
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({
        "version": 1,
        "entries": [{"key": f1.key, "justification": "known"},
                    {"key": "contracts:r:a.py:gone",
                     "justification": "stale"}]}))
    b = Baseline.load(str(path))
    kept, suppressed, stale = b.apply([f1, f2])
    assert kept == [f2] and suppressed == 1
    assert stale == ["contracts:r:a.py:gone"]


def test_baseline_refuses_no_baseline_checkers(tmp_path):
    for checker in NO_BASELINE_CHECKERS:
        path = tmp_path / f"{checker}.json"
        path.write_text(json.dumps({
            "version": 1,
            "entries": [{"key": f"{checker}:r:a.py:d",
                         "justification": "nope"}]}))
        with pytest.raises(UsageError):
            Baseline.load(str(path))


def test_baseline_refuses_bad_documents(tmp_path):
    p = tmp_path / "b.json"
    p.write_text("{not json")
    with pytest.raises(UsageError):
        Baseline.load(str(p))
    p.write_text(json.dumps({"version": 99, "entries": []}))
    with pytest.raises(UsageError):
        Baseline.load(str(p))
    p.write_text(json.dumps({
        "version": 1, "entries": [{"key": "c:r:a:d",
                                   "justification": "   "}]}))
    with pytest.raises(UsageError):
        Baseline.load(str(p))


# ---------------------------------------------------------------------------
# lock-order detector
# ---------------------------------------------------------------------------

def test_lockorder_cycle_detected():
    with lockorder.detecting(patch_globals=False) as mon:
        a = lockorder.named_lock("A")
        b = lockorder.named_lock("B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t2 = threading.Thread(target=ba)
        t1.start(); t1.join()
        t2.start(); t2.join()
    assert mon.cycles() == [["A", "B", "A"]]
    with pytest.raises(lockorder.LockOrderError) as ei:
        mon.assert_acyclic()
    assert "A -> B" in str(ei.value) and "B -> A" in str(ei.value)
    g = mon.graph()
    assert g["schema"].startswith("lightgbm-tpu/lock-order")
    assert {(e["from"], e["to"]) for e in g["edges"]} == \
        {("A", "B"), ("B", "A")}


def test_lockorder_acyclic_and_reentrant():
    with lockorder.detecting(patch_globals=False) as mon:
        a = lockorder.named_rlock("A")
        b = lockorder.named_lock("B")
        with a:
            with a:                      # reentrant: no self-edge
                with b:
                    pass
    assert mon.cycles() == []
    mon.assert_acyclic()
    assert {(e["from"], e["to"]) for e in mon.graph()["edges"]} == \
        {("A", "B")}


def test_lockorder_off_by_default_is_free():
    assert not lockorder.enabled()
    lk = lockorder.named_lock("X")
    assert isinstance(lk, type(threading.Lock()))   # plain stdlib lock
    rlk = lockorder.named_rlock("X")
    assert isinstance(rlk, type(threading.RLock()))


def test_lockorder_patch_table_restores():
    from lightgbm_tpu.ops import step_cache
    orig = step_cache._lock
    with lockorder.detecting() as mon:
        assert step_cache._lock is not orig
        with step_cache._lock:
            pass
    assert step_cache._lock is orig
    assert "step_cache._lock" in mon.lock_names()


# ---------------------------------------------------------------------------
# the tier-1 wrapper: the repo itself analyzes clean
# ---------------------------------------------------------------------------

def test_repo_analyzes_clean_with_empty_critical_baselines():
    """THE acceptance gate: run the full analysis over this checkout
    in-process — zero unbaselined findings, and the baseline file
    contains no jit-capture / lock-discipline entries (those two
    bug classes have no exemption channel but inline waivers)."""
    import run_analysis
    findings = run_analysis.run_checkers(REPO)
    baseline = Baseline.load(
        os.path.join(REPO, "tools", "analysis_baseline.json"))
    kept, _suppressed, stale = baseline.apply(findings)
    assert kept == [], "\n".join(f.render() for f in kept)
    assert stale == [], stale
    # Baseline.load already refuses jit_capture/lock_discipline
    # entries; assert the live findings for those checkers are zero
    # BEFORE baselining too (the empty-baseline criterion)
    critical = [f for f in findings
                if f.checker in NO_BASELINE_CHECKERS]
    assert critical == [], "\n".join(f.render() for f in critical)


def test_driver_exit_codes_and_json():
    """tools/run_analysis.py speaks the check_bench_regression.py
    protocol: exit 0 clean / 2 usage error, --json parses."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "run_analysis.py"), "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["clean"] is True and doc["findings"] == []
    # usage error: a root that is not the repo
    out2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "run_analysis.py"),
         "--root", "/tmp"],
        capture_output=True, text=True, env=env, timeout=60)
    assert out2.returncode == 2


def test_driver_update_baseline_applies_fresh_file(tmp_path):
    """--update-baseline must exit on the FRESH baseline it just
    wrote, not the stale in-memory one (a CI step keyed on the exit
    code would otherwise go red on a successful update)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    bl = tmp_path / "baseline.json"     # starts absent
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "run_analysis.py"),
         "--baseline", str(bl), "--update-baseline"],
        capture_output=True, text=True, env=env, timeout=120)
    assert bl.exists()
    doc = json.loads(bl.read_text())
    assert doc["version"] == 1 and len(doc["entries"]) >= 1
    assert out.returncode == 0, out.stdout + out.stderr
