"""Streamed device ingest (io/ingest.py): bit-exact parity against the
host binner, pipeline routing, and determinism.

The host ``BinMapper.value_to_bin`` / ``TpuDataset.bin_rows`` path is
the semantic oracle; every test forces ``tpu_ingest=1`` so the device
kernels run on the CPU backend (the same code path a real TPU takes
under the default ``tpu_ingest=-1`` auto gate).
"""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata, TpuDataset

pytestmark = pytest.mark.ingest


def _mk(params, ingest, chunk=0):
    full = {"objective": "regression", "max_bin": 63,
            "min_data_in_leaf": 20, "tpu_ingest": ingest,
            "tpu_ingest_chunk_rows": chunk}
    full.update(params)
    return Config().set(full)


def _pair(X, y, params=None, categorical=(), chunk=257):
    """Construct the same dataset through the host binner and the
    device pipeline; returns (host_ds, dev_ds)."""
    params = params or {}
    ds0 = TpuDataset(_mk(params, 0)).construct_from_matrix(
        np.asarray(X), Metadata(label=y), categorical=categorical)
    ds1 = TpuDataset(_mk(params, 1, chunk)).construct_from_matrix(
        np.asarray(X), Metadata(label=y), categorical=categorical)
    return ds0, ds1


def _dev_bins(ds):
    assert ds.bins_t_dev is not None, "device ingest did not engage"
    return np.ascontiguousarray(np.asarray(ds.bins_t_dev).T)


def _nasty_matrix(n=1601, seed=0):
    """Every BinMapper edge case in one matrix: plain continuous, NaN
    columns, zero-heavy columns, the negative-zero / kZeroThreshold
    crossing, a categorical column and a nibble-tier (<=16 bins)
    column."""
    r = np.random.default_rng(seed)
    zero_cross = np.concatenate([
        [-0.0, 0.0, 1e-36, -1e-36, 5e-324, -5e-324, 1e-35, -1e-35,
         np.nextafter(1e-35, 1), np.nextafter(-1e-35, -1)],
        r.normal(size=n - 10) * 1e-30])
    return np.column_stack([
        r.normal(size=n),
        np.where(r.uniform(size=n) < 0.15, np.nan, r.normal(size=n)),
        np.where(r.uniform(size=n) < 0.5, 0.0, r.normal(size=n)),
        r.integers(0, 9, n).astype(np.float64),      # categorical
        zero_cross,
        r.integers(0, 3, n).astype(np.float64),      # <=16-bin tier
    ])


class TestBinningParity:
    @pytest.mark.parametrize("dtype", [np.float64, np.float32])
    def test_nasty_matrix_bit_identical(self, dtype):
        X = _nasty_matrix().astype(dtype)
        y = np.zeros(len(X), np.float32)
        ds0, ds1 = _pair(X, y, categorical=[3])
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))

    @pytest.mark.parametrize("dtype", [np.float64, np.float32])
    def test_zero_as_missing(self, dtype):
        X = _nasty_matrix(seed=1).astype(dtype)
        y = np.zeros(len(X), np.float32)
        ds0, ds1 = _pair(X, y, params={"zero_as_missing": True})
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))

    def test_int32_tier(self):
        r = np.random.default_rng(2)
        X = r.normal(size=(1500, 3))
        y = np.zeros(1500, np.float32)
        ds0, ds1 = _pair(X, y, params={"max_bin": 500,
                                       "min_data_in_bin": 1})
        assert ds1.bins_t_dev.dtype == np.int32
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))

    def test_values_at_bin_boundaries(self):
        """Adversarial: values placed exactly AT each bound and one
        ulp either side — the cases a rounded comparison would get
        wrong."""
        r = np.random.default_rng(3)
        base = r.normal(size=1200)
        ds = TpuDataset(_mk({}, 0)).construct_from_matrix(
            base[:, None], Metadata(label=np.zeros(1200, np.float32)))
        b = ds.mappers[0].bin_upper_bound[:-1]
        adv = np.concatenate([b, np.nextafter(b, -np.inf),
                              np.nextafter(b, np.inf), base])
        y = np.zeros(len(adv), np.float32)
        ds0, ds1 = _pair(adv[:, None], y)
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))

    def test_unseen_and_negative_categories(self):
        n = 1200
        r = np.random.default_rng(4)
        col = r.integers(0, 5, n).astype(np.float64)
        col[::7] = 99.0          # unseen at sample time? (still seen)
        col[::11] = np.nan
        X = np.column_stack([col, r.normal(size=n)])
        y = np.zeros(n, np.float32)
        ds0, ds1 = _pair(X, y, categorical=[0])
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))

    def test_multi_chunk_tail(self):
        """Chunking must be invisible: odd row count, chunk smaller
        than the matrix, tail chunk partially filled."""
        r = np.random.default_rng(5)
        X = r.normal(size=(999, 4)).astype(np.float32)
        y = np.zeros(999, np.float32)
        ds0, ds1 = _pair(X, y, chunk=123)
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))


class TestSampledBoundaries:
    def test_sampled_boundaries_deterministic(self):
        """bin_construct_sample_cnt smaller than N: two constructions
        with the same data_random_seed must produce identical
        boundaries (the reference's deterministic sampled
        ConstructFromSampleData)."""
        r = np.random.default_rng(6)
        X = r.normal(size=(8000, 3))
        y = np.zeros(8000, np.float32)
        p = {"bin_construct_sample_cnt": 1500}
        a = TpuDataset(_mk(p, 0)).construct_from_matrix(
            X, Metadata(label=y))
        b = TpuDataset(_mk(p, 0)).construct_from_matrix(
            X, Metadata(label=y))
        for ma, mb in zip(a.mappers, b.mappers):
            np.testing.assert_array_equal(ma.bin_upper_bound,
                                          mb.bin_upper_bound)
            assert ma.num_bin == mb.num_bin

    def test_sampled_vs_full_same_mapping_contract(self):
        """Sampled and full boundary search agree when the budget
        covers every row — and the streamed path bins IDENTICALLY for
        either mapper set (boundaries in, bins out)."""
        r = np.random.default_rng(7)
        X = r.normal(size=(2500, 3))
        y = np.zeros(2500, np.float32)
        full = TpuDataset(_mk({"bin_construct_sample_cnt": 2500}, 0)) \
            .construct_from_matrix(X, Metadata(label=y))
        samp = TpuDataset(_mk({"bin_construct_sample_cnt": 2500}, 1)) \
            .construct_from_matrix(X, Metadata(label=y))
        for ma, mb in zip(full.mappers, samp.mappers):
            np.testing.assert_array_equal(ma.bin_upper_bound,
                                          mb.bin_upper_bound)
        np.testing.assert_array_equal(full.bins, _dev_bins(samp))


class TestPipelineRoutes:
    def test_training_same_trees(self):
        """Fixed-seed end-to-end run: tpu_ingest on/off grow identical
        trees (the acceptance bar for the streamed path)."""
        import sys, os
        sys.path.insert(0, os.path.dirname(__file__))
        from conftest import fit_gbdt, make_binary
        # default shapes on purpose: the grower compiled for other
        # tests' (1280-row, TEST_PARAMS) datasets is reused in-process
        X, y = make_binary()

        def trees(model_string):
            return model_string.split("parameters:")[0]

        g0 = fit_gbdt(X, y, {"objective": "binary", "tpu_ingest": 0},
                      num_round=8)
        g1 = fit_gbdt(X, y, {"objective": "binary", "tpu_ingest": 1,
                             "tpu_ingest_chunk_rows": 300},
                      num_round=8)
        assert trees(g0.model_to_string()) == trees(g1.model_to_string())

    def test_create_valid_streams_and_never_rederives(self, monkeypatch):
        """create_valid must take the streamed path AND never re-derive
        mappers — find_column_mappers is poisoned while the valid set
        is constructed."""
        import lightgbm_tpu.io.dataset as dsmod
        r = np.random.default_rng(8)
        X = r.normal(size=(1000, 4))
        y = np.zeros(1000, np.float32)
        ds = TpuDataset(_mk({}, 1, 300)).construct_from_matrix(
            X, Metadata(label=y))
        host_ref = TpuDataset(_mk({}, 0)).construct_from_matrix(
            X, Metadata(label=y))

        def boom(*a, **k):
            raise AssertionError("create_valid re-derived mappers")

        monkeypatch.setattr(dsmod, "find_column_mappers", boom)
        Xv = r.normal(size=(500, 4))
        vd = ds.create_valid(Xv, Metadata(label=np.zeros(500, np.float32)))
        assert vd.mappers is ds.mappers
        assert vd.bins_t_dev is not None
        vd_host = host_ref.create_valid(
            Xv, Metadata(label=np.zeros(500, np.float32)))
        np.testing.assert_array_equal(vd_host.bins, _dev_bins(vd))

    def test_efb_data_falls_back_identically(self):
        """Data EFB actually bundles must take the host path and end
        bit-identical to tpu_ingest=0 (bundling decision and bundled
        matrix included)."""
        r = np.random.default_rng(9)
        n = 2000
        which = r.integers(0, 3, n)
        X = np.zeros((n, 4))
        for j in range(3):
            X[which == j, j] = r.uniform(1, 5, (which == j).sum())
        X[:, 3] = r.normal(size=n)
        y = np.zeros(n, np.float32)
        ds0, ds1 = _pair(X, y, params={"max_bin": 31})
        assert ds1.bins_t_dev is None          # host fallback
        assert ds0.bundles == ds1.bundles and ds0.bundles is not None
        np.testing.assert_array_equal(ds0.bundled_bins, ds1.bundled_bins)
        np.testing.assert_array_equal(ds0.bins, ds1.bins)

    def test_two_round_loader_streams(self, tmp_path):
        r = np.random.default_rng(10)
        n = 1100
        X = r.normal(size=(n, 4))
        X[::9, 1] = np.nan
        y = (X[:, 0] > 0).astype(int)
        path = str(tmp_path / "d.csv")
        with open(path, "w") as fh:
            for i in range(n):
                fh.write(",".join([str(y[i])]
                                  + [repr(float(v)) for v in X[i]])
                         + "\n")
        from lightgbm_tpu.io.loader import DatasetLoader

        def load(ingest, ref=None):
            cfg = _mk({"objective": "binary", "two_round": True},
                      ingest, 300 if ingest else 0)
            return DatasetLoader(cfg).load_from_file(path, reference=ref)

        ds0, ds1 = load(0), load(1)
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))
        np.testing.assert_array_equal(ds0.metadata.label,
                                      ds1.metadata.label)
        v0, v1 = load(0, ref=ds0), load(1, ref=ds1)
        assert v1.mappers is ds1.mappers
        np.testing.assert_array_equal(v0.bins, _dev_bins(v1))

    def test_save_binary_roundtrip_from_device(self, tmp_path):
        """save_binary on a device-ingested set downloads once and
        round-trips bit-exactly (nibble packing included)."""
        X = _nasty_matrix(n=1001, seed=11)
        y = np.zeros(1001, np.float32)
        ds0, ds1 = _pair(X, y, categorical=[3])
        fn = str(tmp_path / "d.bin")
        ds1.save_binary(fn)
        loaded = TpuDataset.load_binary(fn, _mk({}, 0))
        np.testing.assert_array_equal(ds0.bins, loaded.bins)


class TestKeyOrder:
    def test_sortable_keys_match_float_order(self):
        """The uint32 key planes order exactly like float comparisons
        (NaN-free, -0.0 normalized)."""
        from lightgbm_tpu.io.ingest import _key32_host, _keys64_host
        r = np.random.default_rng(12)
        v = np.concatenate([
            r.normal(size=500) * 10.0 ** r.integers(-300, 300, 500),
            [0.0, 5e-324, -5e-324, np.inf, -np.inf, 1e-35, -1e-35]])
        v = v + 0.0                      # -0.0 -> +0.0, as the binner
        order = np.argsort(v, kind="stable")
        h, lo = _keys64_host(v)
        key_order = np.argsort(h.astype(np.uint64) << np.uint64(32)
                               | lo.astype(np.uint64), kind="stable")
        np.testing.assert_array_equal(np.sort(v), v[key_order])
        np.testing.assert_array_equal(v[order], v[key_order])
        with np.errstate(over="ignore"):    # huge f64 -> f32 inf is fine
            v32 = (v.astype(np.float32) + np.float32(0.0))
        k32 = _key32_host(v32)
        np.testing.assert_array_equal(np.sort(v32), v32[np.argsort(k32)])

    def test_floor32_is_largest_f32_below(self):
        from lightgbm_tpu.io.ingest import _floor32
        r = np.random.default_rng(13)
        b = r.normal(size=1000) * 10.0 ** r.integers(-30, 30, 1000)
        f = _floor32(b)
        assert (f.astype(np.float64) <= b).all()
        up = np.nextafter(f, np.float32(np.inf))
        assert (up.astype(np.float64) > b).all()


@pytest.mark.slow
class TestIngestThroughput:
    def test_large_ingest_matches_host(self):
        """HIGGS-shaped slab (scaled down): the streamed pipeline over
        many chunks stays bit-identical and produces a usable
        dataset."""
        r = np.random.default_rng(14)
        X = r.normal(size=(400_000, 28)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        ds0, ds1 = _pair(X, y, chunk=1 << 16)
        np.testing.assert_array_equal(ds0.bins, _dev_bins(ds1))
