"""Kernel autotuner + shared VMEM geometry (ops/autotune.py).

Covers the tuning-cache lifecycle (round-trip, version invalidation,
stale candidate sets), the VMEM-budget predicate that gates candidate
tiles, the single-source-of-truth property (the forest kernel's
BlockSpecs and _pallas_tc's byte estimate derive from the same shape
function), and tuned-vs-default numerical parity on both Pallas hot
paths.
"""
import json

import numpy as np
import pytest

from conftest import TEST_PARAMS, fit_gbdt, make_binary

from lightgbm_tpu.ops import autotune
from lightgbm_tpu.ops.autotune import (Autotuner, TuningCache,
                                       TUNING_CACHE_VERSION)


def _counting_measure(times):
    """measure() stub: returns scripted seconds, counts invocations."""
    calls = []

    def measure(cand):
        calls.append(cand)
        return times[json.dumps(cand, sort_keys=True)]

    return measure, calls


CANDS = [{"chunk": 4096}, {"chunk": 8192}, {"chunk": 16384}]
TIMES = {json.dumps(c, sort_keys=True): t
         for c, t in zip(CANDS, (3e-3, 1e-3, 2e-3))}
KEY = {"F": 28, "B": 64, "tier": "int8", "device": "test"}


class TestTuningCache:
    def test_roundtrip_no_retiming(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        measure, calls = _counting_measure(TIMES)
        t = Autotuner("on", path)
        choice = t.best("fused_hist", KEY, CANDS, measure)
        assert choice == {"chunk": 8192}          # fastest candidate
        assert len(calls) == len(CANDS)
        # a FRESH tuner (new process analog) serves the persisted
        # winner without timing anything
        measure2, calls2 = _counting_measure(TIMES)
        t2 = Autotuner("on", path)
        assert t2.best("fused_hist", KEY, CANDS, measure2) == choice
        assert calls2 == []
        # the file records the winner and the per-candidate timings
        with open(path) as fh:
            d = json.load(fh)
        assert d["version"] == TUNING_CACHE_VERSION
        (entry,) = d["entries"].values()
        assert entry["choice"] == {"chunk": 8192}
        assert len(entry["timings_ms"]) == len(CANDS)

    def test_version_mismatch_invalidates(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        key = TuningCache.key_string("fused_hist", KEY)
        with open(path, "w") as fh:
            json.dump({"version": TUNING_CACHE_VERSION + 999,
                       "entries": {key: {"choice": {"chunk": 4096}}}},
                      fh)
        measure, calls = _counting_measure(TIMES)
        choice = Autotuner("on", path).best("fused_hist", KEY, CANDS,
                                            measure)
        # the stale-version entry was ignored: re-timed, new winner
        assert choice == {"chunk": 8192}
        assert len(calls) == len(CANDS)

    def test_corrupt_cache_file_is_ignored(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        with open(path, "w") as fh:
            fh.write("{not json")
        measure, calls = _counting_measure(TIMES)
        assert Autotuner("on", path).best(
            "fused_hist", KEY, CANDS, measure) == {"chunk": 8192}
        assert len(calls) == len(CANDS)

    def test_stale_candidate_set_retunes(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        t = Autotuner("on", path)
        key = TuningCache.key_string("fused_hist", KEY)
        # cached choice no longer in the candidate set (e.g. a
        # tightened VMEM budget dropped it) -> re-tune
        t.cache.put(key, {"choice": {"chunk": 65536}, "timings_ms": {}})
        measure, calls = _counting_measure(TIMES)
        assert t.best("fused_hist", KEY, CANDS, measure) == \
            {"chunk": 8192}
        assert len(calls) == len(CANDS)

    def test_mode_off_returns_default_without_timing(self, tmp_path):
        measure, calls = _counting_measure(TIMES)
        t = Autotuner("off", str(tmp_path / "t.json"))
        assert t.best("fused_hist", KEY, CANDS, measure,
                      default={"chunk": 16384}) == {"chunk": 16384}
        assert calls == []

    def test_failing_candidates_are_skipped(self, tmp_path):
        def measure(cand):
            if cand["chunk"] == 8192:
                raise RuntimeError("Mosaic rejected this tiling")
            return TIMES[json.dumps(cand, sort_keys=True)]

        t = Autotuner("on", str(tmp_path / "t.json"))
        # 8192 (the true fastest) fails -> next best wins, not a crash
        assert t.best("fused_hist", KEY, CANDS, measure) == \
            {"chunk": 16384}


class TestVmemPredicate:
    def test_hist_candidates_respect_budget(self):
        # a bench-shaped problem admits large chunks...
        small = autotune.hist_chunk_candidates(
            F=28, B=64, W=64, fused=True, int8=True, count_proxy=True)
        assert {"chunk": 16384} in small
        # ...a wide/deep-bin problem must shed the big tiles
        wide = autotune.hist_chunk_candidates(
            F=256, B=256, W=24, fused=True)
        assert wide and all(c["chunk"] < 32768 for c in wide)
        geom = autotune.hist_geometry(F=256, B=256, W=24)
        for c in wide:
            assert autotune.fits_vmem(autotune.hist_vmem_bytes(
                chunk=c["chunk"], geom=geom, W=24, fused=True))
        assert not autotune.fits_vmem(autotune.hist_vmem_bytes(
            chunk=32768, geom=geom, W=24, fused=True))
        # a shape whose VMEM accumulator alone exceeds the budget has
        # no feasible tile at all (the kernel cannot run there)
        assert autotune.hist_chunk_candidates(
            F=4096, B=256, W=24, fused=True) == []

    def test_int8_overflow_guard_filters_chunks(self):
        # n just under the int32 histogram guard: padding a 16M-row
        # dataset up to a big chunk multiple must not cross 2^31/127
        n = 2 ** 31 // 127 - 1000
        cands = autotune.hist_chunk_candidates(
            F=28, B=64, W=64, fused=True, int8=True, count_proxy=True,
            n_rows=n)
        for c in cands:
            assert 127 * (n + (-n) % c["chunk"]) < 2 ** 31

    def test_forest_guard_derives_from_shared_shapes(self):
        """_pallas_tc's byte estimate IS autotune.forest_vmem_bytes —
        priced from the same forest_block_shapes the kernel's
        BlockSpecs are built from (no independent hand-maintained byte
        formula)."""
        from lightgbm_tpu.ops.stacked_predict import (StackedModel,
                                                      _PALLAS_VMEM_BUDGET)
        assert _PALLAS_VMEM_BUDGET == autotune.PALLAS_VMEM_BUDGET_BYTES

        sm = StackedModel.__new__(StackedModel)
        sm._S, sm._L, sm._Wtot = 1023, 1024, 8192
        tc = sm._pallas_tc()
        assert tc is not None
        est = autotune.forest_vmem_bytes(
            F=0, Wtot=8192, TC=tc, Sp=1024, Lp=1024, K=1, row_tile=2048)
        assert est <= autotune.PALLAS_VMEM_BUDGET_BYTES
        # the next power of two does NOT fit — tc is the guard's answer
        assert autotune.forest_vmem_bytes(
            F=0, Wtot=8192, TC=tc * 2, Sp=1024, Lp=1024, K=1,
            row_tile=2048) > autotune.PALLAS_VMEM_BUDGET_BYTES
        # block shapes match what forest_predict_pallas hands BlockSpec
        blk = autotune.forest_block_shapes(
            F=28, Wtot=8192, TC=tc, Sp=1024, Lp=1024, K=1,
            row_tile=2048)
        assert blk["codes"] == (28, 2048)
        assert blk["W"] == (1, 8192, tc * 1024)
        assert blk["P"] == (1, tc, 1024, 1024)
        assert blk["acc"] == (2048, 1)

    def test_hist_kernel_uses_shared_geometry(self):
        """The wave kernels' accumulator shape comes from
        autotune.hist_geometry — the same numbers hist_vmem_bytes
        prices."""
        import jax.numpy as jnp
        from lightgbm_tpu.ops.hist_wave import wave_histogram_pallas
        g = autotune.hist_geometry(F=5, B=64, W=8)
        assert g["Bp"] == 64 and g["group_sz"] == 2
        assert g["groups"] == 3 and g["gb_pad"] == 128
        rng = np.random.default_rng(0)
        bins = jnp.asarray(rng.integers(0, 64, (5, 512)).astype(np.uint8))
        out = wave_histogram_pallas(
            bins, jnp.ones(512), jnp.ones(512),
            jnp.zeros(512, jnp.int32),
            jnp.zeros(1, jnp.int32), num_bins=64, chunk=256,
            interpret=True)
        assert out.shape == (1, 5, 64, 3)


class TestDefaultsOffTpu:
    def test_tune_hist_chunk_returns_tier_default_on_cpu(self, tmp_path,
                                                         monkeypatch):
        # conftest pins the cpu backend: no timing may happen, and the
        # measured per-tier defaults come back untouched
        autotune.configure("on", str(tmp_path / "t.json"))
        try:
            assert autotune.tune_hist_chunk(
                fused=True, F=28, B=64, W=24) == \
                autotune.DEFAULT_HIST_CHUNK
            assert autotune.tune_hist_chunk(
                fused=True, F=28, B=64, W=64, precision="int8",
                count_proxy=True) == autotune.DEFAULT_HIST_CHUNK_INT8
            assert not (tmp_path / "t.json").exists()
        finally:
            autotune.configure("on", None)

    def test_config_knob_validation(self):
        from lightgbm_tpu.config import Config
        cfg = Config().set({"tpu_autotune": "bogus"})
        assert cfg.tpu_autotune == "on"
        cfg = Config().set({"tpu_autotune": "exhaustive",
                            "tpu_tuning_cache": "/tmp/x.json"})
        assert cfg.tpu_autotune == "exhaustive"
        assert cfg.tpu_tuning_cache == "/tmp/x.json"

    def test_overlap_knob_validation(self):
        """(PR16) the three overlap knobs are tri-state -1/0/1 and
        clamp anything else back to auto."""
        from lightgbm_tpu.config import Config
        cfg = Config()
        assert (cfg.tpu_psum_wire, cfg.tpu_async_psum,
                cfg.tpu_ckpt_async) == (-1, -1, -1)
        cfg = Config().set({"tpu_psum_wire": 0, "tpu_async_psum": 1,
                            "tpu_ckpt_async": 0})
        assert (cfg.tpu_psum_wire, cfg.tpu_async_psum,
                cfg.tpu_ckpt_async) == (0, 1, 0)
        cfg = Config().set({"tpu_psum_wire": 7, "tpu_async_psum": -3,
                            "tpu_ckpt_async": "2"})
        assert (cfg.tpu_psum_wire, cfg.tpu_async_psum,
                cfg.tpu_ckpt_async) == (-1, -1, -1)


class TestPsumWire:
    """(PR16) the packed-wire and async-psum tuner arms: pure bound
    checks / analytic defaults off-TPU, so fully deterministic here."""

    def test_wire_bound_selects_narrowest_safe(self):
        # 127*N < 2^7 only for N=1; 127*N < 2^15 up to N=258
        assert autotune.tune_psum_wire(n_rows_global=1) == "int8"
        assert autotune.tune_psum_wire(n_rows_global=200) == "int16"
        assert autotune.tune_psum_wire(n_rows_global=258) == "int16"
        assert autotune.tune_psum_wire(n_rows_global=259) == "int32"
        assert autotune.tune_psum_wire(n_rows_global=4096) == "int32"

    def test_wire_requested_zero_is_legacy(self):
        assert autotune.tune_psum_wire(
            n_rows_global=1, requested=0) == "int32"

    def test_wire_force_narrow_refuses_on_wrap_bound(self):
        """tpu_psum_wire=1 cannot override the overflow proof: the
        refusal falls back to int32 and says why."""
        from lightgbm_tpu.utils import log as tpulog
        lines = []
        tpulog.add_sink(lines.append)
        try:
            got = autotune.tune_psum_wire(n_rows_global=4096,
                                          requested=1)
        finally:
            tpulog.remove_sink(lines.append)
        assert got == "int32"
        assert any("wrap bound" in ln for ln in lines)

    def _mesh(self, n):
        from lightgbm_tpu.parallel.learners import make_mesh
        from lightgbm_tpu.utils.device import get_devices
        return make_mesh(min(n, len(get_devices())))

    def test_async_arm_decisions(self):
        mesh2 = self._mesh(2)
        kw = dict(mesh=mesh2, W=8, F=4, B=64, channels=3)
        # requested sync / async win outright
        assert autotune.tune_hist_psum_async(requested=0, **kw) == 1
        assert autotune.tune_hist_psum_async(requested=1, **kw) == 2
        # auto: analytic default (async) off-TPU on a real mesh
        assert autotune.tune_hist_psum_async(requested=-1, **kw) == 2
        # single feature column: nothing to split
        assert autotune.tune_hist_psum_async(
            mesh=mesh2, W=8, F=1, B=64, channels=3, requested=1) == 1

    def test_async_arm_single_device_mesh_stays_sync(self):
        mesh1 = self._mesh(1)
        assert autotune.tune_hist_psum_async(
            mesh=mesh1, W=8, F=4, B=64, channels=3, requested=-1) == 1


class TestTunedParity:
    """A tuned tile choice may never change results beyond documented
    tolerance: the histogram kernels accumulate per-chunk partial sums
    (f32 reassociation across chunk sizes -> tolerance; int8 tier is
    exact int32), and the forest kernel's per-row scores are
    independent of the row blocking (bit-for-bit)."""

    def _hist_args(self, n=1536, F=6, B=64, W=8, seed=3):
        import jax.numpy as jnp
        rng = np.random.default_rng(seed)
        bins = jnp.asarray(rng.integers(0, B, (F, n)).astype(np.uint8))
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        h = jnp.asarray(np.abs(rng.normal(size=n)).astype(np.float32))
        leaf = jnp.asarray(rng.integers(0, 2, n).astype(np.int32))
        wl = jnp.asarray(np.array([0, 1] + [-1] * (W - 2), np.int32))
        return bins, g, h, leaf, wl

    def test_wave_hist_chunk_parity(self):
        from lightgbm_tpu.ops.hist_wave import (wave_histogram_pallas,
                                                wave_histogram_xla)
        args = self._hist_args()
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        for chunk in (256, 512, 1536):
            out = np.asarray(wave_histogram_pallas(
                *args, num_bins=64, chunk=chunk, interpret=True))
            np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-5)

    def test_wave_hist_chunk_parity_int8_exact(self):
        import jax.numpy as jnp
        from lightgbm_tpu.ops.hist_wave import (wave_histogram_pallas,
                                                wave_histogram_xla)
        bins, _, _, leaf, wl = self._hist_args()
        rng = np.random.default_rng(9)
        n = bins.shape[1]
        gq = jnp.asarray(rng.integers(-127, 128, n).astype(np.float32))
        hq = jnp.asarray(rng.integers(0, 128, n).astype(np.float32))
        ref = np.asarray(wave_histogram_xla(
            bins, gq, hq, leaf, wl, num_bins=64))
        outs = [np.asarray(wave_histogram_pallas(
            bins, gq, hq, leaf, wl, num_bins=64, chunk=c,
            interpret=True, precision="int8", gh_scale=(1.0, 1.0)))
            for c in (256, 768)]
        # int32 accumulation: bit-for-bit across tile choices AND
        # exactly equal to the oracle's integer-float sums
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], ref)

    def test_fused_chunk_parity(self):
        import jax.numpy as jnp
        from lightgbm_tpu.ops.hist_wave import (
            fused_partition_histogram_pallas)
        bins, g, h, leaf, _ = self._hist_args(W=4)
        n = bins.shape[1]
        mask = jnp.ones(n, jnp.float32)
        W = 4
        tbl = np.zeros((18, W), np.int32)
        tbl[0] = [0, 1, -1, -1]          # parents
        tbl[1] = [2, 3, -1, -1]          # new ids
        tbl[2] = [0, 1, 0, 0]            # features
        tbl[3] = [31, 40, 0, 0]          # bins
        tbl[7] = 64                      # num_bin
        tbl[8] = [2, 3, -1, -1]          # smaller child
        tbl_d = jnp.asarray(tbl)
        outs = []
        for chunk in (256, 768):
            leaf_o, hist = fused_partition_histogram_pallas(
                bins, g, h, mask, leaf, tbl_d, num_bins=64,
                chunk=chunk, interpret=True)
            outs.append((np.asarray(leaf_o), np.asarray(hist)))
        # the partition is integer logic: identical at any tile
        np.testing.assert_array_equal(outs[0][0], outs[1][0])
        np.testing.assert_allclose(outs[0][1], outs[1][1],
                                   atol=1e-4, rtol=1e-5)

    def test_forest_row_tile_parity_bit_for_bit(self):
        from lightgbm_tpu.ops.stacked_predict import (
            forest_predict_pallas)
        import jax.numpy as jnp
        X, y = make_binary(n=1000, f=6, seed=21)
        g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                     num_round=10)
        g._ensure_host_trees()
        from lightgbm_tpu.ops.stacked_predict import StackedModel
        sm = StackedModel(g.models, g.max_feature_idx + 1, 1)
        assert sm.ok
        tc = sm._pallas_tc()
        dev = sm._device_arrays_pallas(0, sm.num_trees, tc)
        Xt = np.random.default_rng(4).normal(size=(700, 6))
        codes = jnp.asarray(np.ascontiguousarray(sm._bin_rows(Xt).T))
        offs = tuple(int(o) for o in sm._offsets)
        outs = [np.asarray(forest_predict_pallas(
            codes, *dev, offsets=offs, row_tile=rt, interpret=True))
            for rt in (256, 512, 1024)]
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])


def test_measure_median_with_sync():
    """timing.measure: median-of-k wall seconds, device-synced."""
    import jax.numpy as jnp

    from lightgbm_tpu.utils import timing

    def fn():
        return jnp.arange(1024.0).sum()

    t = timing.measure(fn, repeats=3, warmup=1)
    assert 0.0 < t < 10.0


def test_ensure_compile_cache_cpu_backend_leaves_config_alone(
        monkeypatch):
    """The persistent compile cache auto-wires only for the TPU
    backend (this image's jax 0.4.x CPU backend flakily segfaults
    deserializing warm entries); on the CPU test backend the jax
    config must come through untouched. The once-guard is reset so the
    gate itself is exercised (earlier tests' GBDT.init already tripped
    it, which would make this assertion vacuous)."""
    import jax

    from lightgbm_tpu.ops import autotune as at
    monkeypatch.setattr(at, "_compile_cache_done", False)
    before = jax.config.jax_compilation_cache_dir
    at.ensure_compile_cache()
    assert jax.config.jax_compilation_cache_dir == before
