"""Pipelined retrain-while-serve LRB loop (lrb.py) + its two perf
layers: vectorized derive/OPT bit-parity against the scalar reference
transliterations, pipelined-vs-sequential result parity, paced-stream
wall win, serving-during-retrain liveness, degrade/swap-suppression,
the trainer-thread fault drills, and the device-resident ingest chunk
ring's h2d ledger (io/ingest.py ChunkRing).
"""
import io
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from lightgbm_tpu import lrb
from lightgbm_tpu.obs import registry as obs

pytestmark = pytest.mark.lrb

FAST = {"num_iterations": 4, "verbose": -1}


def _driver(mode, window=300, sample=150, extra=None, **kw):
    params = dict(FAST)
    params["tpu_lrb_pipeline"] = mode
    params.update(extra or {})
    return lrb.LrbDriver(1 << 16, window, sample, 0.5, 1,
                         result_file=io.StringIO(),
                         extra_params=params, **kw)


def _feed(drv, n, objects=60):
    for seq, oid, size, cost in lrb.synthetic_trace(n, objects):
        drv.process_request(seq, oid, size, cost)


def _fill_window(drv, n, n_ids=8, seed=0, big_sizes=False):
    """An adversarial window: heavy id repeats (>50 occurrences, the
    gap-deque cap), same id at different sizes (insert-size vs
    current-size eviction credit), label runs (insert/evict run-start
    propagation), and optionally sizes that drive cache_avail <= 0."""
    rng = np.random.default_rng(seed)
    w = drv.window
    hi = (1 << 22) if big_sizes else 5000
    for i in range(n):
        w.ids.append(int(rng.integers(0, n_ids)))
        w.sizes.append(int(rng.integers(1, hi)))
        w.costs.append(float(rng.random()))
        w.has_next.append(bool(rng.random() < 0.6))
        w.volume.append(int(rng.integers(0, 1 << 20)))
        w.byte_sum += w.sizes[-1]


# -- vectorized hot loops: bit-parity vs the scalar oracles ------------------

def test_vectorized_opt_bit_parity():
    drv = _driver(0)
    _fill_window(drv, 400)
    drv._calculate_opt_scalar()
    want = (drv.window.to_cache.copy(), drv._opt_hits,
            drv._opt_byte_hits)
    drv._calculate_opt()
    np.testing.assert_array_equal(drv.window.to_cache, want[0])
    assert (drv._opt_hits, drv._opt_byte_hits) == want[1:]


def test_vectorized_opt_budget_cutoff():
    """The scalar loop admits while the running volume is <= budget
    and BREAKS past it — the vectorized exclusive-cumsum mask must
    land on exactly the same boundary item."""
    drv = _driver(0, window=4, sample=4)
    drv.cache_size = 10                   # budget = 10 * 4 = 40
    w = drv.window
    for vol, size in ((15, 3), (25, 5), (1, 7), (999, 9)):
        w.ids.append(1)
        w.sizes.append(size)
        w.costs.append(1.0)
        w.has_next.append(True)
        w.volume.append(vol)
        w.byte_sum += size
    drv._calculate_opt_scalar()
    want = drv.window.to_cache.copy()
    drv._calculate_opt()
    np.testing.assert_array_equal(drv.window.to_cache, want)
    # items 15+25+1 admitted (cum-before 0/15/40 <= 40), 999 cut off
    assert list(drv.window.to_cache) == [True, True, True, False]


@pytest.mark.parametrize("sampling", [0, 1, 2])
@pytest.mark.parametrize("big_sizes", [False, True])
def test_vectorized_derive_bit_parity(sampling, big_sizes):
    drv = _driver(0, window=400, sample=170)
    if big_sizes:
        drv.cache_size = 1 << 20          # avail goes <= 0 mid-window
    _fill_window(drv, 400, big_sizes=big_sizes)
    drv._calculate_opt()
    drv.rng = np.random.default_rng(42)
    l_s, x_s = drv._derive_features_scalar(sampling)
    drv.rng = np.random.default_rng(42)
    l_v, x_v = drv._derive_features(sampling)
    np.testing.assert_array_equal(l_s, l_v)
    assert x_s.shape == x_v.shape
    np.testing.assert_array_equal(x_s, x_v)


def test_vectorized_derive_empty_and_single():
    drv = _driver(0)
    labels, X = drv._derive_features(0)
    assert labels.shape == (0,) and X.shape == (0, lrb.NUM_FEATURES)
    _fill_window(drv, 1)
    drv._calculate_opt()
    l_s, x_s = drv._derive_features_scalar(0)
    l_v, x_v = drv._derive_features(0)
    np.testing.assert_array_equal(l_s, l_v)
    np.testing.assert_array_equal(x_s, x_v)


# -- pipelined vs sequential: field-for-field parity -------------------------

PARITY_KEYS = ("window", "eval_rows", "fp_rate", "fn_rate",
               "train_rows", "opt_obj_hit_ratio", "opt_byte_hit_ratio",
               "staleness_windows", "degraded", "degrade_reason")


def _run_modes(n=1800, window=300, sample=150, extra=None):
    out = {}
    for mode in (1, 0):
        drv = _driver(mode, window, sample, extra=extra)
        _feed(drv, n)
        res = drv.results                 # drains the pipeline
        out[mode] = (drv, res)
        drv.close()
    return out


def test_pipelined_matches_sequential():
    swaps0 = obs.counter("lrb/model_swaps").value
    runs = _run_modes()
    drv_p, res_p = runs[1]
    drv_s, res_s = runs[0]
    assert len(res_p) == len(res_s) == 6
    for a, b in zip(res_s, res_p):
        for k in PARITY_KEYS:
            assert a.get(k) == b.get(k), (k, a.get(k), b.get(k))
    # swap-at-boundary: the pipelined run published exactly one model
    # per successfully trained window, and only those
    trained = sum(1 for r in res_p if not r.get("degraded"))
    assert obs.counter("lrb/model_swaps").value - swaps0 == trained
    # every pipelined window carries the overlap instrument
    assert all("overlap_s" in r for r in res_p)
    # the serve histogram is PER-REQUEST: one observation per scored
    # row, not one per micro-batch
    assert drv_p._serve_hist.count == sum(r.get("eval_rows", 0)
                                          for r in res_p)
    assert drv_p._serve_batch_hist.count < drv_p._serve_hist.count


def test_pipelined_beats_sequential_wall_at_rate():
    """The acceptance run: a >= 6-window synthetic trace offered at an
    LRB-realistic rate (bounded-buffer pacing, calibrated from a warm
    pass). The sequential loop stalls the stream for every window's
    train+evaluate wall; the pipelined loop absorbs both into the
    stream's idle gaps — a structural, not statistical, wall win."""
    import time
    n, window, sample = 3072, 512, 256
    extra = {"num_iterations": 6}
    reqs = list(lrb.synthetic_trace(n, 80))

    warm = _driver(0, window, sample, extra=extra)
    for r in reqs:
        warm.process_request(*r)
    train_walls = [r["train_s"] for r in warm.results if "train_s" in r]
    warm.close()
    gap16 = 16.0 * 2.5 * float(np.median(train_walls)) / window

    def paced(mode):
        drv = _driver(mode, window, sample, extra=extra)
        t0 = time.monotonic()
        nxt = t0
        for i, r in enumerate(reqs):
            if i % 16 == 0:
                nxt += gap16
                delay = nxt - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                else:
                    nxt = time.monotonic()
            drv.process_request(*r)
        drv.drain()
        wall = time.monotonic() - t0
        res = drv.results
        drv.close()
        return res, wall

    res_s, wall_s = paced(0)
    res_p, wall_p = paced(1)
    for a, b in zip(res_s, res_p):
        for k in PARITY_KEYS:
            assert a.get(k) == b.get(k), (k, a.get(k), b.get(k))
    assert sum(r.get("overlap_s", 0) for r in res_p) > 0
    assert wall_p < wall_s, \
        f"pipelined {wall_p:.2f}s did not beat sequential {wall_s:.2f}s"


# -- serving-during-retrain liveness -----------------------------------------

def test_serving_stays_live_during_retrain(lock_order):
    """predict_live returns while the trainer thread provably holds a
    window (parked on the test gate), serving the previous model.
    Runs under the lock-order detector: the swap/join/serving-lock
    acquisition graph of a mid-window serve must stay acyclic."""
    reqs = list(lrb.synthetic_trace(600, 60))
    drv = _driver(1)
    for r in reqs[:300]:
        drv.process_request(*r)           # window 1 trains + publishes
    drv.drain()
    assert drv.booster is not None
    gate = threading.Event()
    drv._train_gate = gate
    for r in reqs[300:]:
        drv.process_request(*r)
    # window 2's boundary submitted its training; the trainer is
    # parked on the gate — training is in flight RIGHT NOW
    assert drv._train_started.wait(timeout=30)
    assert drv.training_in_flight()
    probe = np.zeros((8, lrb.NUM_FEATURES))
    out = drv.predict_live(probe)
    assert out is not None and np.asarray(out).shape == (8,)
    assert drv.training_in_flight(), \
        "the serve call must not have waited the trainer out"
    gate.set()
    drv._train_gate = None
    res = drv.results
    assert len(res) == 2 and not res[1].get("degraded")
    drv.close()


def test_concurrent_drain_joins_once():
    """results/booster drain from any thread; concurrent drains must
    not both run the join body (double-counted staleness, duplicate
    result lines)."""
    import time
    reqs = list(lrb.synthetic_trace(600, 60))
    out = io.StringIO()
    params = dict(FAST)
    params["tpu_lrb_pipeline"] = 1
    drv = lrb.LrbDriver(1 << 16, 300, 150, 0.5, 1, result_file=out,
                        extra_params=params)
    for r in reqs[:300]:
        drv.process_request(*r)
    drv.drain()
    gate = threading.Event()
    drv._train_gate = gate
    for r in reqs[300:]:
        drv.process_request(*r)           # window 2 parked on the gate
    assert drv._train_started.wait(timeout=30)
    got = []
    readers = [threading.Thread(target=lambda: got.append(
        len(drv.results))) for _ in range(4)]
    for t in readers:
        t.start()
    time.sleep(0.2)
    gate.set()
    drv._train_gate = None
    for t in readers:
        t.join(timeout=30)
    assert got == [2, 2, 2, 2]
    assert out.getvalue().count("window 2:") == 1
    assert len(drv.results) == 2
    drv.close()


def test_chunk_ring_bypassed_when_matrix_exceeds_capacity():
    """A matrix wider than the ring's slot capacity must take the
    plain path (every slot would be evicted before reuse — pure
    overhead) with identical bins and an empty ring."""
    from lightgbm_tpu import capi
    from lightgbm_tpu.io.ingest import ChunkRing
    rng = np.random.default_rng(9)
    X = rng.normal(size=(1000, 4))
    params = {"tpu_ingest": 1, "tpu_ingest_chunk_rows": 64,
              "max_bin": 15, "verbose": -1}       # 16 chunks > cap 8
    ring = ChunkRing()
    ds_r = capi.LGBM_DatasetCreateFromMat(X, parameters=params,
                                          ring=ring)
    got = np.asarray(ds_r.construct().bins_t_dev)
    ds_p = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
    want = np.asarray(ds_p.construct().bins_t_dev)
    np.testing.assert_array_equal(got, want)
    assert not ring._slots, "bypass must not pin resident chunks"


# -- degrade: swap suppression + fault drills --------------------------------

def test_degraded_window_suppresses_swap():
    from lightgbm_tpu.utils import faults
    swaps0 = obs.counter("lrb/model_swaps").value
    faults.configure("lrb.window_train@2")
    try:
        drv = _driver(1)
        _feed(drv, 900)
        res = drv.results
    finally:
        faults.clear()
    assert [r.get("degraded") for r in res] == [None, True, None]
    assert "InjectedFault" in res[1]["degrade_reason"]
    assert [r["staleness_windows"] for r in res] == [0, 1, 0]
    # windows 1 and 3 published; window 2's swap never happened
    assert obs.counter("lrb/model_swaps").value - swaps0 == 2
    # ... and the loop kept serving window 1's model through window 3
    assert res[2].get("eval_rows", 0) > 0
    assert drv.booster is not None
    drv.close()


def test_every_window_failing_degrades_not_deadlocks():
    """The raise drill on EVERY window: the trainer thread dies clean
    each time, nothing ever publishes, the loop completes the whole
    trace degraded — no deadlock, no exception."""
    from lightgbm_tpu.utils import faults
    faults.configure("lrb.window_train@1+")
    try:
        drv = _driver(1)
        _feed(drv, 900)
        res = drv.results
    finally:
        faults.clear()
    assert len(res) == 3
    assert all(r.get("degraded") for r in res)
    assert drv.booster is None
    assert [r["staleness_windows"] for r in res] == [0, 0, 0]
    drv.close()


_KILL_CHILD = """
import io, sys
from lightgbm_tpu import lrb
d = lrb.LrbDriver(1 << 16, 300, 150, 0.5, 1, result_file=io.StringIO(),
                  extra_params={"num_iterations": 2,
                                "tpu_lrb_pipeline": 1})
for seq, oid, size, cost in lrb.synthetic_trace(900, 60):
    d.process_request(seq, oid, size, cost)
d.drain()
print("SURVIVED-THE-DRILL")
"""


def test_kill_drill_trainer_thread_dies_clean():
    """``lrb.window_train@1:kill`` SIGKILLs from the TRAINER thread:
    the process must die promptly (no deadlocked join, no survivor
    output) — the crash drill the degrade path cannot absorb."""
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "LGBM_TPU_FAULTS": "lrb.window_train@1:kill"})
    assert proc.returncode == -9, (proc.returncode, proc.stderr[-500:])
    assert "SURVIVED-THE-DRILL" not in proc.stdout


# -- device-resident ingest chunk ring ---------------------------------------

def test_chunk_ring_bit_identical_fewer_h2d():
    """Ingest-level: two same-geometry constructions through one ring
    — the second window's smaller matrix reuses the resident slot
    (stale rows beyond its live region must read as pad), bins are
    bit-identical to ring-less ingest, and the h2d ledger shrinks."""
    from lightgbm_tpu import capi
    from lightgbm_tpu.io.ingest import ChunkRing
    rng = np.random.default_rng(5)
    params = {"tpu_ingest": 1, "max_bin": 63, "verbose": -1}
    X1 = rng.normal(size=(500, 12))
    X2 = rng.normal(size=(200, 12))       # smaller: stale-tail case
    ring = ChunkRing()

    def bins(X, ring=None):
        h0 = obs.counter("ingest/h2d_bytes").value
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params,
                                            ring=ring)
        out = np.asarray(ds.construct().bins_t_dev)
        return out, obs.counter("ingest/h2d_bytes").value - h0

    want1, h_plain1 = bins(X1)
    want2, h_plain2 = bins(X2)
    got1, h_ring1 = bins(X1, ring)
    got2, h_ring2 = bins(X2, ring)
    np.testing.assert_array_equal(got1, want1)
    np.testing.assert_array_equal(got2, want2)
    assert h_ring1 < h_plain1 and h_ring2 < h_plain2
    assert obs.counter("ingest/ring_saved_bytes").value > 0


def test_lrb_ring_fewer_h2d_bytes_per_window():
    """Driver-level acceptance: the windowed loop with tpu_lrb_ring
    ships fewer h2d bytes per window than full re-ingest, with
    bit-identical training results (fp/fn parity)."""
    def run(ring):
        drv = _driver(1, extra={"num_iterations": 3, "tpu_ingest": 1,
                                "tpu_lrb_ring": ring})
        h0 = obs.counter("ingest/h2d_bytes").value
        _feed(drv, 900)
        res = drv.results
        drv.close()
        return res, obs.counter("ingest/h2d_bytes").value - h0

    res_plain, h_plain = run(0)
    res_ring, h_ring = run(1)
    assert h_ring < h_plain / 4, (h_ring, h_plain)
    for a, b in zip(res_plain, res_ring):
        for k in ("fp_rate", "fn_rate", "train_rows", "degraded"):
            assert a.get(k) == b.get(k), (k, a.get(k), b.get(k))


# -- serve-latency accounting + registry -------------------------------------

def test_observe_n_per_request_normalization():
    reg = obs.MetricsRegistry()
    h = obs.latency_histogram("t", reg)
    h.observe_n(0.010, 64)                # one 64-row micro-batch
    h.observe(2.0)                        # one slow single request
    assert h.count == 65
    assert h.sum == pytest.approx(0.010 * 64 + 2.0)
    # p50 ranks REQUESTS: the 64 fast requests dominate the median
    assert h.percentile(0.5) < 0.05
    assert h.percentile(0.99) > 1.0
    h.observe_n(5.0, 0)                   # n=0 is a no-op
    assert h.count == 65


def test_main_result_file_context_managed_and_flushed(tmp_path):
    """lrb.main() with a resultFile: the handle is context-managed
    (closed on exit) and every window's line plus the summary reaches
    disk."""
    trace_path = tmp_path / "trace.txt"
    lines = [f"{seq} {oid} {size} {cost}"
             for seq, oid, size, cost in lrb.synthetic_trace(600, 60)]
    trace_path.write_text("\n".join(lines) + "\n")
    out_path = tmp_path / "result.txt"
    lrb.main([str(trace_path), str(1 << 16), "300", "150", "0.5", "1",
              str(out_path)])
    text = out_path.read_text()
    assert "window 1:" in text and "window 2:" in text
    assert "window_wall" in text
    assert "serve_latency" in text        # per-request quantiles line
