"""Test configuration: force an 8-device virtual CPU platform so that
multi-chip sharding (data/feature/voting parallel learners) is exercised
in-process — fixing the reference's distributed-test gap (SURVEY.md §4.4:
the reference has no multi-node test at all).

Must run before jax is imported anywhere.
"""
import os

# The axon TPU plugin on this image registers itself regardless of
# JAX_PLATFORMS, so jax.devices() returns the (single, tunneled) TPU.
# Tests run on the true CPU backend with 8 virtual devices instead:
# LGBM_TPU_PLATFORM routes the framework's device selection
# (lightgbm_tpu/utils/device.py) and jax_default_device keeps all test
# computation off the tunnel.
os.environ["LGBM_TPU_PLATFORM"] = "cpu"
# jax < 0.5 has no jax_num_cpu_devices config option and needs the XLA
# flag set BEFORE jax imports; jax >= 0.5 wants the config option and
# rejects having both. Pick ONE mechanism by version, read without
# importing jax (the flag must precede the import).
from importlib import metadata as _md  # noqa: E402

try:
    _legacy_jax = tuple(
        int(x) for x in _md.version("jax").split(".")[:2]) < (0, 5)
except Exception:                       # unparseable dev version
    _legacy_jax = False
if _legacy_jax and "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=8 "
        + os.environ.get("XLA_FLAGS", ""))

import jax  # noqa: E402

if not _legacy_jax:
    jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_default_device",
                  jax.local_devices(backend="cpu")[0])
# Persistent compile cache: distinct grower shapes compile once per
# machine, not once per pytest run. Disabled on jax 0.4.x: its CPU
# cache-deserialization path flakily segfaults/aborts when serving a
# warm entry (~1/3 of warm runs in this image), killing the whole
# pytest process; recompiling is slower but deterministic. Set
# LGBM_TPU_TEST_COMPILE_CACHE=1 to opt back in on a fixed jax.
if os.environ.get("LGBM_TPU_TEST_COMPILE_CACHE"):
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/lgbm_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
# NOTE: jax_disable_most_optimizations was evaluated for the
# compile-bound suite (compiles are ~60% of a typical engine-test
# slice even with the cross-booster step cache) and rejected: it
# halves compile time but de-optimizes the RUNTIME code so badly that
# iteration-heavy tests (DART replay, CV) dominate — the full suite
# got slower, not faster.

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Small standard shapes keep XLA compile time per distinct grower shape
# bounded; every test that can share a shape should use these.
TEST_PARAMS = {"num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 20}


def fit_gbdt(X, y, params, num_round=30, weight=None, group=None,
             valid=None):
    """Train a GBDT the low-level way (shared by many tests)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import TpuDataset, Metadata
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.metrics import create_metrics

    full = dict(TEST_PARAMS)
    full.update(params)
    cfg = Config().set(full)
    md = Metadata(label=y, weight=weight, group=group)
    ds = TpuDataset(cfg).construct_from_matrix(
        X, md, categorical=cfg.categorical_feature)
    obj = create_objective(cfg.objective, cfg)
    if obj is not None:
        obj.init(ds.metadata, ds.num_data)
    metrics = create_metrics(cfg.metric or [cfg.objective], cfg,
                             ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, metrics)
    if valid is not None:
        Xv, yv = valid
        vd = ds.create_valid(Xv, Metadata(label=yv))
        vm = create_metrics(cfg.metric or [cfg.objective], cfg,
                            vd.metadata, vd.num_data)
        g.add_valid_data(vd, vm)
    for _ in range(num_round):
        if g.train_one_iter():
            break
    g.finish_training()
    return g


@pytest.fixture(scope="session", autouse=True)
def _step_cache_suite_guard():
    """Regression guard for the compiled-step registry
    (ops/step_cache.py): a full suite run trains hundreds of boosters
    in one process, many with identical geometry — if the registry
    records plenty of misses but not a single hit, a closure
    re-capture regression has silently put every booster back on its
    own compile (the ~19 min PR-4 wall-clock). Small selections that
    train only a handful of boosters stay under the miss threshold and
    are exempt."""
    yield
    from lightgbm_tpu.ops import step_cache
    s = step_cache.stats()
    if s["enabled"] and s["misses"] > 20:
        assert s["hits"] > 0, (
            "step cache recorded %(misses)d compiles and ZERO hits "
            "across the suite — cross-booster step reuse has regressed "
            "(every booster is re-compiling its fused step)" % s)


@pytest.fixture
def lock_order():
    """Run a thread-hammer test with the runtime lock-order detector
    armed (lightgbm_tpu/analysis/lockorder.py): locks created inside
    the test via the named-lock factories are tracked, the known
    module-level locks are swapped for the window, and the test fails
    if the recorded acquisition graph has a cycle — "no deadlock yet"
    becomes a checked property of exactly the interleavings the
    hammer generates. Production pays nothing: detection is off
    everywhere else."""
    from lightgbm_tpu.analysis import lockorder
    with lockorder.detecting() as mon:
        yield mon
    mon.assert_acyclic()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


def make_binary(n=1280, f=10, seed=0):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, f))
    logit = X[:, 0] + 0.5 * X[:, 1] * X[:, 2] - 0.25 * X[:, 3]
    y = (logit + 0.1 * r.normal(size=n) > 0).astype(np.float32)
    return X, y


def make_regression(n=1280, f=10, seed=1):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, f))
    y = (2.0 * X[:, 0] + X[:, 1] ** 2 + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * r.normal(size=n)).astype(np.float32)
    return X, y


def make_multiclass(n=1280, f=10, k=4, seed=2):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, f))
    centers = r.normal(size=(k, f)) * 2.0
    d = ((X[:, None, :] - centers[None]) ** 2).sum(-1)
    y = np.argmin(d + 0.5 * r.normal(size=(n, k)), axis=1).astype(np.float32)
    return X, y


def rank_auc(y, scores):
    """Hand-rolled Mann-Whitney AUC (no sklearn in the image)."""
    import numpy as np
    order = np.argsort(scores)
    ranks = np.empty(len(scores), dtype=np.float64)
    ranks[order] = np.arange(len(scores))
    pos = np.asarray(y) > 0.5
    npos, nneg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - npos * (npos - 1) / 2) / max(
        npos * nneg, 1)
