"""End-to-end sharded multi-chip training (pytest -m multichip).

Runs on the 8-device virtual CPU mesh (conftest): the same code path a
real v5e-8 takes, minus the Pallas kernels (interpret/XLA fallbacks).
Three properties anchor the distributed design (Design.md §7):

(a) sharded ingest is BIT-exact against single-device ingest — the
    round-robin chunk pipeline assembles the row-sharded [F, N] under
    the mesh's NamedSharding from the identical chunk kernel;
(b) data-parallel training with fully sharded iteration state (bins,
    scores, grad/hess, bagging mask) keeps same-seed serial parity —
    sharding is layout, never semantics;
(c) quantized training with the int32 quantized-histogram psum
    reproduces the single-chip quantized trees — the rounding hash is
    keyed by GLOBAL row index and the scales are global, so the wire
    format (int vs f32) and the shard count drop out of the model.
"""
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata, TpuDataset
from lightgbm_tpu.utils.device import get_devices

from conftest import fit_gbdt, make_binary

pytestmark = [
    pytest.mark.multichip,
    pytest.mark.skipif(len(get_devices()) < 2,
                       reason="needs multi-device mesh"),
]


@pytest.fixture(scope="module")
def serial_baseline():
    """One serial same-seed reference booster (every booster pays a
    full XLA compile on this backend, so the ingest-parity and
    sharded-state-parity tests share their serial half)."""
    X, y = make_binary(1280)
    g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc"},
                 num_round=5)
    return X, y, g


def _nasty_matrix(n=1601, seed=0):
    """The ingest parity matrix (tests/test_ingest.py): continuous,
    NaN, zero-heavy, the -0.0/kZeroThreshold crossing, categorical."""
    r = np.random.default_rng(seed)
    zero_cross = np.concatenate([
        [-0.0, 0.0, 1e-36, -1e-36, 5e-324, -5e-324, 1e-35, -1e-35,
         np.nextafter(1e-35, 1), np.nextafter(-1e-35, -1)],
        r.normal(size=n - 10) * 1e-30])
    return np.column_stack([
        r.normal(size=n),
        np.where(r.uniform(size=n) < 0.15, np.nan, r.normal(size=n)),
        np.where(r.uniform(size=n) < 0.5, 0.0, r.normal(size=n)),
        r.integers(0, 9, n).astype(np.float64),      # categorical
        zero_cross,
    ])


def _ingest_ds(X, y, learner, categorical=(), chunk=97):
    cfg = Config().set({"objective": "regression", "max_bin": 63,
                        "min_data_in_leaf": 20, "tpu_ingest": 1,
                        "tpu_ingest_chunk_rows": chunk,
                        "tree_learner": learner})
    return TpuDataset(cfg).construct_from_matrix(
        np.asarray(X), Metadata(label=y), categorical=categorical)


class TestShardedIngest:
    """(a) row-sharded assembly under NamedSharding, bit-exact."""

    @pytest.mark.parametrize("dtype", [np.float64, np.float32])
    def test_nasty_matrix_bit_identical(self, dtype):
        X = _nasty_matrix().astype(dtype)
        y = np.zeros(len(X), np.float32)
        ds1 = _ingest_ds(X, y, "serial", categorical=[3])
        ds8 = _ingest_ds(X, y, "data", categorical=[3])
        assert ds1.bins_t_dev is not None and ds8.bins_t_dev is not None
        # the sharded matrix really is distributed over the mesh
        assert len(ds8.bins_t_dev.sharding.device_set) > 1
        n = ds1.num_data
        np.testing.assert_array_equal(
            np.asarray(ds1.bins_t_dev),
            np.asarray(ds8.bins_t_dev)[:, :n])
        # shard-equalizing pad columns are zero bins (what row padding
        # writes); shards are chunk-aligned to the largest power-of-two
        # unit u with n >= 4*D*u so the grower adopts the padding
        # (io/ingest.py bin_matrix_sharded)
        D = len(ds8.bins_t_dev.sharding.device_set)
        from lightgbm_tpu.ops.autotune import MAX_HIST_CHUNK
        u = 1
        while u * 2 <= MAX_HIST_CHUNK and n >= 4 * D * (u * 2):
            u *= 2
        S = -(-max(-(-n // D), 1) // u) * u
        assert ds8.bins_t_dev_pad == D * S - n
        assert (np.asarray(ds8.bins_t_dev)[:, n:] == 0).all()

    def test_matches_host_binner(self):
        X = _nasty_matrix(seed=3)
        y = np.zeros(len(X), np.float32)
        cfg = Config().set({"objective": "regression", "max_bin": 63,
                            "min_data_in_leaf": 20, "tpu_ingest": 0,
                            "tree_learner": "data"})
        host = TpuDataset(cfg).construct_from_matrix(
            X.copy(), Metadata(label=y), categorical=[3])
        dev = _ingest_ds(X, y, "data", categorical=[3])
        np.testing.assert_array_equal(
            host.bins,
            np.ascontiguousarray(
                np.asarray(dev.bins_t_dev)[:, :host.num_data].T))

    def test_sharded_ingest_trains_serial_parity(self, serial_baseline):
        """The sharded-ingest bins feed the sharded grower directly
        (no single-device staging) and the trees still match a fully
        host-binned serial run. (The baseline's tpu_ingest=-1 resolves
        to the host binner off-TPU — the same path as tpu_ingest=0.)"""
        X, y, gs = serial_baseline
        gd = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                             "tree_learner": "data",
                             "tpu_ingest": 1}, num_round=5)
        assert gd._learner_mode == "data"
        assert gd.train_data.bins_t_dev is not None
        np.testing.assert_allclose(
            gd.predict_raw(X[:200]), gs.predict_raw(X[:200]),
            rtol=1e-5, atol=1e-5)


class TestShardedState:
    """(b) fully sharded iteration state keeps serial parity."""

    def test_five_iteration_serial_parity(self, serial_baseline):
        # 1280 % 8 == 0: scores shard too (the production-layout path)
        X, y, gs = serial_baseline
        gd = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                             "tree_learner": "data"}, num_round=5)
        assert gd._learner_mode == "data"
        # the state really lives sharded on the mesh
        assert len(gd._bins_dev.sharding.device_set) > 1
        assert len(gd._scores.sharding.device_set) > 1
        assert len(gd._full_mask_dev.sharding.device_set) > 1
        np.testing.assert_allclose(
            gd.predict_raw(X[:300]), gs.predict_raw(X[:300]),
            rtol=1e-5, atol=1e-5)
        gd._ensure_host_trees()
        gs._ensure_host_trees()
        for td, ts in zip(gd.models, gs.models):
            assert td.split_feature == ts.split_feature

    def test_uneven_rows_with_bagging_and_valid(self):
        """Odd row count (scores stay unsharded), bagging mask and a
        passenger valid set — the whole iteration surface."""
        X, y = make_binary(1283, seed=5)
        Xv, yv = make_binary(257, seed=6)
        params = {"objective": "binary", "metric": "auc",
                  "bagging_fraction": 0.7, "bagging_freq": 1}
        gs = fit_gbdt(X, y, params, num_round=5, valid=(Xv, yv))
        gd = fit_gbdt(X, y, dict(params, tree_learner="data"),
                      num_round=5, valid=(Xv, yv))
        np.testing.assert_allclose(
            gd.predict_raw(X[:200]), gs.predict_raw(X[:200]),
            rtol=1e-5, atol=1e-5)
        # valid scores advanced identically through the passenger rows
        np.testing.assert_allclose(
            np.asarray(gd._valid_scores[0]),
            np.asarray(gs._valid_scores[0]), rtol=1e-5, atol=1e-5)


class TestQuantizedPsum:
    """(c) the int32 quantized-histogram reduction matches single-chip
    quantized training (and the f32 wire matches it too)."""

    def test_matches_single_chip_quantized(self):
        X, y = make_binary(1282, seed=7)
        base = {"objective": "binary", "metric": "auc",
                "tpu_quantized_hist": True}
        gs = fit_gbdt(X, y, base, num_round=6)
        gd = fit_gbdt(X, y, dict(base, tree_learner="data",
                                 tpu_quantized_psum=1), num_round=6)
        assert gd._grower_cfg.precision == "int8"
        assert gd._grower_cfg.quant_psum
        np.testing.assert_allclose(
            gd.predict_raw(X[:300]), gs.predict_raw(X[:300]),
            rtol=1e-5, atol=1e-5)
        gd._ensure_host_trees()
        gs._ensure_host_trees()
        for td, ts in zip(gd.models, gs.models):
            assert td.split_feature == ts.split_feature

    def test_f32_wire_is_near_but_not_exact(self):
        """The pre-compression wire: psumming per-shard DEQUANTIZED
        sums rounds (D multiplies + D-1 f32 adds where the int wire
        does one exact int sum), so parity is approximate — the
        quality bar holds but bit-parity is exactly what the int32
        wire buys."""
        X, y = make_binary(1282, seed=7)
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "tpu_quantized_hist": True,
                            "tree_learner": "data",
                            "tpu_quantized_psum": 0}, num_round=6)
        assert not g._grower_cfg.quant_psum
        auc = dict((n, v) for n, v, _ in g.get_eval_at(0))["auc"]
        assert auc > 0.95

    def test_quant_psum_requires_default_seams(self):
        from lightgbm_tpu.ops.split import SplitParams
        from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                                  make_wave_grower)
        from lightgbm_tpu.ops.split import FeatureMeta
        F = 2
        meta = FeatureMeta(
            num_bin=np.full(F, 8, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        cfg = WaveGrowerConfig(num_leaves=7, num_bins=8,
                               precision="int8", quant_psum=True,
                               hp=SplitParams())
        with pytest.raises(ValueError, match="quant_psum"):
            make_wave_grower(
                cfg, meta,
                hist_fn=lambda *a, gh_scale=None: None)
        bad = cfg._replace(precision="default")
        with pytest.raises(ValueError, match="int8"):
            make_wave_grower(bad, meta)

    def test_packed_wire_bit_parity(self):
        """(PR16) the narrow psum wire: with few enough global rows the
        127*N wrap bound proves an int16 (even int8) payload cannot
        overflow, so cast -> narrow psum -> widen is EXACT and the
        model must match the int32 wire byte for byte."""
        from lightgbm_tpu.parallel.elastic import _strip_volatile
        X, y = make_binary(256, seed=11)
        base = {"objective": "binary", "metric": "auc",
                "tpu_quantized_hist": True, "tree_learner": "data",
                "tpu_quantized_psum": 1, "min_data_in_leaf": 5}
        g32 = fit_gbdt(X, y, dict(base, tpu_psum_wire=0), num_round=5)
        gnw = fit_gbdt(X, y, dict(base, tpu_psum_wire=-1), num_round=5)
        assert g32.wire_encoding() == "int32"
        assert gnw.wire_encoding() in ("int8", "int16")
        assert _strip_volatile(gnw.model_to_string()) \
            == _strip_volatile(g32.model_to_string())

    def test_async_slot_psum_bit_parity(self):
        """(PR16) the double-buffered slot collective splits the psum
        along the feature axis — pure scheduling freedom, elementwise
        across shards, so the model is bit-identical to the monolithic
        (sync) collective."""
        from lightgbm_tpu.parallel.elastic import _strip_volatile
        X, y = make_binary(1282, seed=7)
        base = {"objective": "binary", "metric": "auc",
                "tpu_quantized_hist": True, "tree_learner": "data",
                "tpu_quantized_psum": 1}
        gsync = fit_gbdt(X, y, dict(base, tpu_async_psum=0),
                         num_round=5)
        gasync = fit_gbdt(X, y, dict(base, tpu_async_psum=1),
                          num_round=5)
        assert gsync._grower_cfg.psum_slots == 1
        assert gasync._grower_cfg.psum_slots == 2
        assert _strip_volatile(gasync.model_to_string()) \
            == _strip_volatile(gsync.model_to_string())


class TestReporting:
    """Mesh size + comm bytes surface through the public API and the
    run report (bench.py consumes exactly these)."""

    def test_num_devices_and_run_report(self, tmp_path):
        import json
        path = str(tmp_path / "run.json")
        X, y = make_binary(1280, seed=9)
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "tree_learner": "data",
                            "num_iterations": 4,
                            "tpu_run_report": path}, num_round=0)
        assert g.num_devices == len(get_devices())
        g.train()
        report = json.load(open(path))
        assert report["meta"]["mesh_devices"] == g.num_devices
        iters = [r for r in report["iterations"] if "comm_bytes" in r]
        assert iters, "no per-iteration comm bytes recorded"
        gcfg = g._grower_cfg
        per_pass = gcfg.wave_size * g.train_data.num_features \
            * gcfg.num_bins * 3 * 4
        for r in iters:
            assert r["comm_bytes"] % per_pass == 0
            assert r["comm_bytes"] >= per_pass
        # the registry is process-cumulative, so >= the run's own total
        assert report["counters"]["comm/psum_bytes"] >= sum(
            r["comm_bytes"] for r in iters)

    def test_booster_num_devices(self):
        import lightgbm_tpu as lgb
        X, y = make_binary(640, seed=10)
        bst = lgb.train({"objective": "binary", "num_leaves": 7,
                         "max_bin": 31, "tree_learner": "data",
                         "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=2)
        assert bst.num_devices == len(get_devices())


class TestConfigFallback:
    def test_unknown_tree_learner_warns_to_serial(self):
        cfg = Config().set({"tree_learner": "bogus"})
        assert cfg.tree_learner == "serial"
