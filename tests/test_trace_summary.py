"""tools/trace_summary.py — the human side of the black box: format
sniffing across trace/flight/reqlog artifacts, the per-thread span
table math, top-N slow-request selection (wide events first, spans
fallback), and the CLI's exit codes.

Run with ``pytest -m obs``.
"""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                "tools"))
import trace_summary  # noqa: E402

pytestmark = pytest.mark.obs


def _trace_doc():
    return {
        "traceEvents": [
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 7,
             "args": {"name": "lrb-trainer"}},
            {"name": "lrb/train", "cat": "window", "ph": "X",
             "ts": 0.0, "dur": 4000.0, "pid": 1, "tid": 7},
            {"name": "lrb/train", "cat": "window", "ph": "X",
             "ts": 5000.0, "dur": 2000.0, "pid": 1, "tid": 7},
            {"name": "serve/request", "cat": "serve", "ph": "X",
             "ts": 100.0, "dur": 1500.0, "pid": 1, "tid": 9,
             "args": {"req_id": 3, "window": 2, "rows": 64}},
            {"name": "serve/request", "cat": "serve", "ph": "X",
             "ts": 2000.0, "dur": 500.0, "pid": 1, "tid": 9,
             "args": {"req_id": 4, "window": 2, "rows": 64}},
            {"name": "watchdog/slow_iteration", "ph": "i", "s": "t",
             "ts": 50.0, "pid": 1, "tid": 9},
        ],
        "otherData": {"schema": "lightgbm-tpu/trace", "version": 1,
                      "dropped_events": 2},
    }


def test_load_and_summarize_trace(tmp_path):
    p = tmp_path / "trace.json"
    p.write_text(json.dumps(_trace_doc()))
    kind, doc = trace_summary.load_artifact(str(p))
    assert kind == "trace"
    rows = trace_summary.span_table(doc["events"])
    # hottest-first; metadata names resolved; instants excluded
    assert rows[0]["span"] == "lrb/train"
    assert rows[0]["thread"] == "lrb-trainer"
    assert rows[0]["count"] == 2
    assert rows[0]["total_ms"] == pytest.approx(6.0)
    assert rows[0]["max_ms"] == pytest.approx(4.0)
    assert rows[0]["mean_ms"] == pytest.approx(3.0)
    assert rows[1]["span"] == "serve/request"
    assert rows[1]["thread"] == "tid 9"        # no metadata for tid 9
    assert len(rows) == 2
    # spans FALLBACK for top requests (no wide events in a trace)
    reqs = trace_summary.top_requests(doc, 5)
    assert [r["req_id"] for r in reqs] == [3, 4]   # latency desc
    assert reqs[0]["latency_ms"] == pytest.approx(1.5)
    assert reqs[0]["window"] == 2
    out = trace_summary.render(kind, doc)
    assert "dropped 2 older events" in out
    assert "lrb-trainer" in out and "req_id" in out


def test_load_and_summarize_reqlog(tmp_path):
    p = tmp_path / "req.jsonl"
    lines = [
        {"kind": "header", "schema": "lightgbm-tpu/reqlog",
         "version": 1},
        {"kind": "request", "req_id": 1, "latency_ms": 5.0,
         "path": "lrb/serve", "window": 1, "rows": 64,
         "serve_bucket": 64, "model_window": 0},
        {"kind": "request", "req_id": 2, "latency_ms": 50.0,
         "path": "lrb/serve", "window": 2, "rows": 64,
         "serve_bucket": 64, "model_window": 1},
        {"kind": "request", "req_id": 3, "latency_ms": 1.0,
         "path": "lrb/live", "window": 2, "rows": 8},
        {"kind": "window", "window": 1, "train_s": 2.0,
         "window_wall_s": 2.5, "fp_rate": 0.1, "fn_rate": 0.0},
        {"kind": "degraded_window", "window": 2,
         "label": "budget", "degrade_label": "budget"},
    ]
    p.write_text("".join(json.dumps(ln) + "\n" for ln in lines)
                 + "not json\n")                # skipped, not fatal
    kind, doc = trace_summary.load_artifact(str(p))
    assert kind == "reqlog"
    assert len(doc["records"]) == 5            # header + garbage gone
    reqs = trace_summary.top_requests(doc, 2)  # top-N honors N
    assert [r["req_id"] for r in reqs] == [2, 1]
    out = trace_summary.render(kind, doc)
    assert "top 2 slow requests" not in out    # default top=10
    assert "window records (2)" in out
    assert "budget" in out


def test_load_and_summarize_flight_dump(tmp_path):
    doc = {
        "schema": "lightgbm-tpu/flight", "version": 1,
        "created_unix": 1.0, "pid": 42, "reason": "degraded_window",
        "context": {"window": 2, "label": "budget"},
        "triggers": [{"ts": 1.0, "reason": "degraded_window"}],
        "spans": _trace_doc()["traceEvents"],
        "log_lines": ["[LightGBM-TPU] [Warning] w"],
        "reqlog": [{"kind": "request", "req_id": 9,
                    "latency_ms": 3.25, "window": 2, "rows": 16}],
        "metrics": {"current": {"counters": {}}, "recent": []},
        "slo": None,
    }
    p = tmp_path / "flight_p42_001_degraded_window.json"
    p.write_text(json.dumps(doc))
    kind, loaded = trace_summary.load_artifact(str(p))
    assert kind == "flight"
    out = trace_summary.render(kind, loaded)
    assert "reason=degraded_window" in out
    assert "triggers:" in out
    # wide events win over the spans fallback when both are present
    reqs = trace_summary.top_requests(loaded, 5)
    assert [r["req_id"] for r in reqs] == [9]
    assert "lrb-trainer" in out                # span table still there


def test_cli_exit_codes(tmp_path, capsys):
    good = tmp_path / "trace.json"
    good.write_text(json.dumps(_trace_doc()))
    assert trace_summary.main([str(good), "--top", "3"]) == 0
    assert "trace artifact" in capsys.readouterr().out
    bad = tmp_path / "noise.txt"
    bad.write_text("definitely not an artifact\n")
    assert trace_summary.main([str(bad)]) == 2
    assert trace_summary.main([str(tmp_path / "missing.json")]) == 2
    empty_json = tmp_path / "other.json"
    empty_json.write_text(json.dumps({"some": "dict"}))
    assert trace_summary.main([str(empty_json)]) == 2


def test_real_artifacts_round_trip(tmp_path):
    """A trace written by the real Tracer and a reqlog written by the
    real RequestLog summarize without special-casing."""
    from lightgbm_tpu.obs import registry as obs_registry
    from lightgbm_tpu.obs import reqlog as rl
    from lightgbm_tpu.obs import trace as tr
    t = tr.Tracer(str(tmp_path / "t.json"))
    with t.span("serve/request", cat="serve",
                args={"req_id": 1, "rows": 4}):
        pass
    t.write()
    kind, doc = trace_summary.load_artifact(str(tmp_path / "t.json"))
    assert kind == "trace"
    assert trace_summary.span_table(doc["events"])
    log = rl.RequestLog(str(tmp_path / "r.jsonl"),
                        registry=obs_registry.MetricsRegistry())
    log.record("request", req_id=1, latency_ms=2.0, rows=4)
    log.record("window", window=1, window_wall_s=0.5)
    log.close()
    kind, doc = trace_summary.load_artifact(str(tmp_path / "r.jsonl"))
    assert kind == "reqlog"
    assert trace_summary.top_requests(doc, 5)[0]["req_id"] == 1
