"""Fixed form of the PR-5 closure-recapture miniature: the label
array rides as a TRACED ARGUMENT (the aux-pytree seam of
objectives/objective.py), so the registered program is pure in its
geometry key and any booster's call supplies its own arrays. The
jit-capture checker must pass this file clean."""
import jax

from lightgbm_tpu.ops import step_cache


def make_step(self, y, num_leaves: int):
    n = int(y.shape[0])

    def builder():
        def step(bins, scores, labels):
            # labels is an argument: each caller binds its own array
            grad = scores - labels
            return bins, scores - 0.1 * grad

        return jax.jit(step)

    key = ("mini_step", n, num_leaves)
    return step_cache.get_step(key, builder)
