"""PR-5 closure-recapture bug, in miniature (DO NOT FIX — this file
is a regression fixture for the jit-capture checker).

The historical shape: the fused training step was a pure function of
a geometry key, but a refactor silently re-captured per-booster state
— here the label array — into the function registered process-wide.
Two boosters with the same geometry then share ONE compiled program
with the FIRST booster's labels baked in as a trace constant: the
second booster trains on the wrong data, bit-exactly wrong, and the
only runtime symptom is the conftest hit-rate assertion this checker
replaces as the sole defense.

tests/test_analysis.py asserts the jit-capture checker FLAGS the
``labels`` capture below (and that the _fixed twin passes).
"""
import jax
import numpy as np

from lightgbm_tpu.ops import step_cache


def make_step(self, y, num_leaves: int):
    labels = np.asarray(y, np.float32)   # per-booster array
    n = int(y.shape[0])

    def builder():
        def step(bins, scores):
            # BUG: ``labels`` is a closure capture — it bakes into the
            # shared compiled program as a constant; a registry hit
            # from a same-geometry booster serves THESE labels
            grad = scores - labels
            return bins, scores - 0.1 * grad

        return jax.jit(step)

    key = ("mini_step", n, num_leaves)
    return step_cache.get_step(key, builder)
