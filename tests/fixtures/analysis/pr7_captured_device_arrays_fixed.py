"""Fixed form of the PR-7 miniature: the model's device stacks ride
as ARGUMENTS of the registered wrapper (the actual PR-7 fix in
ops/stacked_predict.py), so a registry hit runs the warm compiled
program on the CALLING model's arrays. The jit-capture checker must
pass this file clean."""
import jax.numpy as jnp

from lightgbm_tpu.ops import predict_cache


def _forest_eval(part, W, P, aux):
    return jnp.einsum("rs,wsl->rl", part, W)[:, :1] + P[0, 0, 0]


class MiniStacked:
    def predict(self, rows, S: int, L: int, K: int):
        dev = self._device_arrays()          # THIS model's stacks
        aux = (jnp.asarray(self._edges),)

        def build():
            def run(part, dv, ax):
                # stacks/edge tables are arguments, not closure state
                return _forest_eval(part, dv[0], dv[1], ax)

            return run

        key = ("mini_predict", S, L, K)
        fn = predict_cache.get(key, build)
        return fn(rows, dev, aux)

    def _device_arrays(self):
        return (jnp.zeros((2, 4, 4)), jnp.zeros((1, 1, 1)))
