"""PR-7 captured-device-array bug, in miniature (DO NOT FIX — this
file is a regression fixture for the jit-capture checker).

The historical shape: a predict-registry wrapper closed over the
first model's device stacks. The registry key covered the GEOMETRY
(shapes, offsets, class count), so a retrained same-geometry model
hit the warm entry — and the warm program served the FIRST model's
arrays. Caught back then by the serving parity suite after the fact;
the jit-capture checker flags it at analysis time.

tests/test_analysis.py asserts the checker FLAGS the ``dev``/``aux``
captures below (and that the _fixed twin passes).
"""
import jax.numpy as jnp

from lightgbm_tpu.ops import predict_cache


def _forest_eval(part, W, P, aux):
    return jnp.einsum("rs,wsl->rl", part, W)[:, :1] + P[0, 0, 0]


class MiniStacked:
    def predict(self, rows, S: int, L: int, K: int):
        dev = self._device_arrays()          # THIS model's stacks
        aux = (jnp.asarray(self._edges),)

        def build():
            def run(part):
                # BUG: dev/aux are closure captures — a registry hit
                # from a retrained same-geometry model runs the warm
                # program on the FIRST model's device arrays
                return _forest_eval(part, dev[0], dev[1], aux)

            return run

        key = ("mini_predict", S, L, K)
        fn = predict_cache.get(key, build)
        return fn(rows)

    def _device_arrays(self):
        return (jnp.zeros((2, 4, 4)), jnp.zeros((1, 1, 1)))
