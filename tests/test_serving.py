"""Serving path (ops/predict_cache.py + the StackedModel serving
refactor): geometry-keyed predict registry, pow2 serve buckets, and
incremental forest stacking.

The contract under test: online micro-batches (1..4096 rows) are
BIT-equal to one full-batch predict (pad rows are independent and
sliced off), a retrained same-geometry model hits a warm registry
entry instead of re-tracing, appending trees re-stacks only the new
chunk, and a predict() racing a retrain never sees a half-built
predictor (the thread-safety satellite).

``pytest -m serving``.
"""
import threading

import numpy as np
import pytest

from conftest import (TEST_PARAMS, fit_gbdt, make_binary,
                      make_multiclass)

from lightgbm_tpu.ops import predict_cache

pytestmark = pytest.mark.serving


# -- serve bucket policy (pure units) ----------------------------------------

def test_serve_bucket_rows_policy():
    # auto: pow2, floor 16
    assert predict_cache.serve_bucket_rows(1, -1) == 16
    assert predict_cache.serve_bucket_rows(16, -1) == 16
    assert predict_cache.serve_bucket_rows(17, -1) == 32
    assert predict_cache.serve_bucket_rows(4096, -1) == 4096
    assert predict_cache.serve_bucket_rows(4097, -1) == 8192
    # above 16k: pow2/16 steps (pad capped at ~1/8, 8 buckets/octave)
    assert predict_cache.serve_bucket_rows(1 << 14, -1) == 1 << 14
    b = predict_cache.serve_bucket_rows(20000, -1)
    assert b >= 20000 and b % 1024 == 0 and b - 20000 < 20000 / 8
    # exact shapes
    assert predict_cache.serve_bucket_rows(37, 0) == 37
    # multiple-of-N
    assert predict_cache.serve_bucket_rows(37, 50) == 50
    assert predict_cache.serve_bucket_rows(120, 50) == 150


# -- micro-batch bit-parity vs full batch ------------------------------------

def _microbatch(g, X, sizes, **kw):
    """Concatenated predict_raw over a stream of odd batch sizes."""
    parts, r0 = [], 0
    i = 0
    while r0 < len(X):
        b = sizes[i % len(sizes)]
        parts.append(np.atleast_1d(g.predict_raw(X[r0:r0 + b], **kw)))
        r0 += b
        i += 1
    return np.concatenate(parts, axis=0)


def test_microbatch_bit_equal_binary():
    X, y = make_binary(n=1500, f=6, seed=3)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=15)
    Xt = np.random.default_rng(1).normal(size=(700, 6))
    Xt[::13, 2] = np.nan
    full = g.predict_raw(Xt)
    got = _microbatch(g, Xt, (1, 3, 64, 117, 256))
    np.testing.assert_array_equal(got, full)


def test_microbatch_bit_equal_multiclass():
    X, y = make_multiclass(n=1200, f=5, k=3, seed=5)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="multiclass",
                            num_class=3), num_round=8)
    Xt = np.random.default_rng(2).normal(size=(500, 5))
    full = g.predict_raw(Xt)
    got = _microbatch(g, Xt, (2, 65, 130))
    np.testing.assert_array_equal(got, full)


def test_microbatch_bit_equal_pred_leaf():
    X, y = make_binary(n=1200, f=6, seed=7)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=12)
    Xt = np.random.default_rng(3).normal(size=(400, 6))
    full = g.predict_leaf_index(Xt)
    parts = [g.predict_leaf_index(Xt[r0:r0 + 37])
             for r0 in range(0, 400, 37)]
    np.testing.assert_array_equal(np.concatenate(parts, axis=0), full)


def test_xla_scan_and_pallas_buckets_bit_equal():
    """Bucketed (serve policy -1) vs unbucketed (policy 0) predict is
    bit-identical on BOTH device paths — the XLA scan fallback and the
    fused Pallas forest kernel (interpret mode off-TPU)."""
    from lightgbm_tpu.ops.stacked_predict import StackedModel
    X, y = make_binary(n=1200, f=6, seed=11)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=10)
    g._ensure_host_trees()
    F = g.max_feature_idx + 1
    bucketed = StackedModel(g.models, F, 1, serve_bucket=-1)
    exact = StackedModel(g.models, F, 1, serve_bucket=0)
    Xt = np.random.default_rng(4).normal(size=(137, 6))
    Xt[::9, 1] = np.nan
    np.testing.assert_array_equal(bucketed.predict(Xt),
                                  exact.predict(Xt))
    np.testing.assert_array_equal(
        bucketed.predict(Xt, use_pallas=True),
        exact.predict(Xt, use_pallas=True))
    np.testing.assert_array_equal(
        bucketed.predict(Xt, pred_leaf=True),
        exact.predict(Xt, pred_leaf=True))


# -- registry: cross-model reuse ---------------------------------------------

def test_registry_hits_after_same_geometry_retrain():
    """The lrb shape: a FRESH booster on same-shaped data lands on the
    same predict geometry — its dispatch is a registry HIT (warm
    compiled program), and each model pays exactly one full stack."""
    params = dict(TEST_PARAMS, objective="binary")
    X, y = make_binary(n=1500, f=6, seed=13)
    Xt = np.random.default_rng(5).normal(size=(64, 6))

    g1 = fit_gbdt(X, y, params, num_round=10)
    g1.predict_raw(Xt)                       # builds + registers
    s0 = predict_cache.stats()
    # retrain: fresh booster, same data shape -> same geometry
    X2, y2 = make_binary(n=1500, f=6, seed=14)
    g2 = fit_gbdt(X2, y2, params, num_round=10)
    g2.predict_raw(Xt)
    s1 = predict_cache.stats()
    assert s1["hits"] - s0["hits"] >= 1, \
        "same-geometry retrain must hit the warm predict registry"
    assert s1["misses"] == s0["misses"], \
        "same-geometry retrain must not mint a new dispatch"
    assert s1["stacks"] - s0["stacks"] == 1      # g2's one full stack
    # same model, same batch bucket again: memoized per-instance, no
    # new registry traffic at all
    g2.predict_raw(Xt[:32])                  # same 16..64 bucket? 32->32
    s2 = predict_cache.stats()
    assert s2["stacks"] == s1["stacks"]


def test_registry_disabled_still_correct():
    """tpu_predict_cache=0: no registry bookkeeping, identical
    results."""
    params = dict(TEST_PARAMS, objective="binary",
                  tpu_predict_cache=0)
    X, y = make_binary(n=1200, f=6, seed=17)
    g = fit_gbdt(X, y, params, num_round=8)
    Xt = np.random.default_rng(6).normal(size=(100, 6))
    s0 = predict_cache.stats()
    full = g.predict_raw(Xt)
    got = _microbatch(g, Xt, (7, 33))
    np.testing.assert_array_equal(got, full)
    assert predict_cache.stats()["hits"] == s0["hits"]
    assert predict_cache.stats()["misses"] == s0["misses"]


# -- incremental forest stacking ---------------------------------------------

def test_extend_on_continued_training_bit_equal():
    """predict -> train more -> predict re-stacks ONLY the appended
    chunk (extends counter), and the extended predictor is bit-equal
    to a from-scratch stack of the full ensemble."""
    from lightgbm_tpu.ops.stacked_predict import StackedModel
    X, y = make_binary(n=1500, f=6, seed=19)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=10)
    Xt = np.random.default_rng(7).normal(size=(300, 6))
    first = g.predict_raw(Xt)
    assert first.shape == (300,)
    s0 = predict_cache.stats()
    for _ in range(5):
        g.train_one_iter()
    got = g.predict_raw(Xt)
    s1 = predict_cache.stats()
    assert s1["extends"] - s0["extends"] == 1, \
        "continued training must extend, not re-stack"
    assert s1["stacks"] == s0["stacks"]
    g._ensure_host_trees()
    fresh = StackedModel(g.models, g.max_feature_idx + 1, 1)
    np.testing.assert_array_equal(
        got, fresh.predict(np.ascontiguousarray(Xt))[0])


def test_rollback_reuses_stacks_then_rebuilds_cleanly():
    """Rollback keeps the cached stacks (predict slices by ntree);
    training past a rollback must NOT extend over stale positions."""
    X, y = make_binary(n=1500, f=6, seed=23)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=12)
    Xt = np.random.default_rng(8).normal(size=(200, 6))
    g.predict_raw(Xt)
    s0 = predict_cache.stats()
    g.rollback_one_iter()
    want_11 = g.predict_raw(Xt)              # 11 trees, reused stacks
    s1 = predict_cache.stats()
    assert s1["stacks"] == s0["stacks"]
    assert s1["extends"] == s0["extends"]
    # grow past the rollback point: positions diverge from the stacked
    # ref -> full rebuild, and the result reflects the NEW trees
    g.train_one_iter()
    got = g.predict_raw(Xt)
    assert got.shape == want_11.shape
    from lightgbm_tpu.ops.stacked_predict import StackedModel
    g._ensure_host_trees()
    fresh = StackedModel(g.models, g.max_feature_idx + 1, 1)
    np.testing.assert_array_equal(
        got, fresh.predict(np.ascontiguousarray(Xt))[0])


def test_set_leaf_value_invalidates_stacked():
    """In-place leaf edits keep tree identity — the stacked predictor
    must be dropped explicitly, or serving would use stale leaves."""
    from lightgbm_tpu import capi
    X, y = make_binary(n=800, f=5, seed=29)
    params = "objective=binary num_leaves=15 min_data_in_leaf=20"
    ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
    capi.LGBM_DatasetSetField(ds, "label", y)
    bst = capi.LGBM_BoosterCreate(ds, params)
    for _ in range(6):
        capi.LGBM_BoosterUpdateOneIter(bst)
    Xt = X[:64]
    before = np.asarray(capi.LGBM_BoosterPredictForMat(
        bst, Xt, predict_type=capi.C_API_PREDICT_RAW_SCORE))
    old = capi.LGBM_BoosterGetLeafValue(bst, 0, 0)
    capi.LGBM_BoosterSetLeafValue(bst, 0, 0, old + 5.0)
    after = np.asarray(capi.LGBM_BoosterPredictForMat(
        bst, Xt, predict_type=capi.C_API_PREDICT_RAW_SCORE))
    leaf0 = np.asarray(capi.LGBM_BoosterPredictForMat(
        bst, Xt, predict_type=capi.C_API_PREDICT_LEAF_INDEX))[:, 0]
    hit = leaf0 == 0
    assert hit.any() and not hit.all()
    np.testing.assert_allclose(after[hit], before[hit] + 5.0,
                               atol=1e-5)
    np.testing.assert_allclose(after[~hit], before[~hit], atol=1e-6)


# -- thread safety: predict while retraining ---------------------------------

def test_predict_during_training_is_safe(lock_order):
    """Concurrent predict() calls while the booster trains more trees:
    no crash, no half-built predictor, every result equals a clean
    predict at SOME consistent tree count (prefix snapshots). Runs
    under the lock-order detector (conftest.lock_order): the serving
    lock vs registry/obs lock acquisition graph must stay acyclic."""
    X, y = make_binary(n=1200, f=6, seed=31)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=8)
    Xt = np.ascontiguousarray(
        np.random.default_rng(9).normal(size=(64, 6)))
    g.predict_raw(Xt)                        # warm build
    errors = []
    results = []
    stop = threading.Event()

    def serve():
        try:
            while not stop.is_set():
                results.append(g.predict_raw(Xt))
        except Exception as e:               # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=serve) for _ in range(3)]
    for t in threads:
        t.start()
    for _ in range(8):
        g.train_one_iter()
    stop.set()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert results
    # every observed result matches a clean single-threaded predict at
    # one of the tree counts that existed during the run
    valid = {n: g.predict_raw(Xt, num_iteration=n)
             for n in range(8, 17)}
    for r in results:
        assert any(np.array_equal(r, v) for v in valid.values())
