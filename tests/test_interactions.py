"""Config-interaction smoke matrix: boosting variants x sampling x
quantization x constraints must train, predict finitely, and
round-trip through the text format. Guards against cross-feature
regressions no single-feature test sees.
"""
import numpy as np
import pytest


COMBOS = [
    {"boosting": "dart", "bagging_fraction": 0.7, "bagging_freq": 2},
    {"boosting": "goss", "tpu_quantized_hist": True},
    {"boosting": "rf", "bagging_fraction": 0.6, "bagging_freq": 1,
     "feature_fraction": 0.7},
    {"tpu_quantized_hist": True, "feature_fraction": 0.6,
     "bagging_fraction": 0.5, "bagging_freq": 3},
    {"objective": "regression_l1", "tpu_quantized_hist": True},
    {"objective": "quantile", "alpha": 0.7, "lambda_l1": 0.5},
    {"tpu_quantized_hist": True,
     "monotone_constraints": "1,0,-1,0,0,0,0,0"},
    {"tpu_use_dp": False, "max_depth": 4, "min_gain_to_split": 0.1},
    {"objective": "poisson", "tpu_quantized_hist": True},
    {"tpu_quantized_hist": True, "enable_bundle": True},
]


@pytest.fixture(scope="module")
def xy():
    r = np.random.default_rng(0)
    X = r.normal(size=(600, 8))
    X[::9, 3] = np.nan                # missing values in the mix
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    yr = X[:, 0] + 0.2 * r.normal(size=600)
    return X, y, yr


@pytest.mark.parametrize("extra", COMBOS,
                         ids=[f"combo{i}" for i in range(len(COMBOS))])
def test_interaction_smoke(xy, extra):
    import lightgbm_tpu as lgb
    X, y, yr = xy
    params = {"num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
              "verbose": -1, "objective": "binary", **extra}
    label = yr if params["objective"] in (
        "regression_l1", "quantile", "poisson") else y
    if params["objective"] == "poisson":
        label = np.abs(label)
    ds = lgb.Dataset(X, label=label)
    bst = lgb.train(params, ds, 8)
    p = np.asarray(bst.predict(X))
    assert np.isfinite(p).all()
    s = bst._gbdt.model_to_string()
    b2 = lgb.Booster(model_str=s)
    p2 = np.asarray(b2.predict(X, raw_score=True))
    assert np.isfinite(p2).all()
