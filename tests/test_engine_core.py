"""End-to-end quality tests on the core GBDT engine — the model of the
reference's tests/python_package_test/test_engine.py (trains real models,
asserts metric thresholds). Shared fixtures keep the number of distinct
XLA compiles (and thus CPU test time) bounded."""
import numpy as np
import pytest

from lightgbm_tpu.models.gbdt import GBDT

from conftest import fit_gbdt, make_binary, make_regression, make_multiclass


@pytest.fixture(scope="module")
def binary_model():
    X, y = make_binary()
    g = fit_gbdt(X, y, {"objective": "binary",
                        "metric": "auc,binary_logloss"}, num_round=30)
    return g, X, y


@pytest.fixture(scope="module")
def regression_model():
    X, y = make_regression()
    g = fit_gbdt(X, y, {"objective": "regression", "metric": "l2"},
                 num_round=40)
    return g, X, y


@pytest.fixture(scope="module")
def multiclass_model():
    X, y = make_multiclass()
    g = fit_gbdt(X, y, {"objective": "multiclass", "num_class": 4,
                        "metric": "multi_error,multi_logloss"},
                 num_round=20)
    return g, X, y


class TestBinary:
    def test_auc(self, binary_model):
        g, X, y = binary_model
        evals = dict((n, v) for n, v, _ in g.get_eval_at(0))
        assert evals["auc"] > 0.97

    def test_logloss(self, binary_model):
        g, X, y = binary_model
        evals = dict((n, v) for n, v, _ in g.get_eval_at(0))
        assert evals["binary_logloss"] < 0.35

    def test_prediction_matches_internal_score(self, binary_model):
        g, X, y = binary_model
        p = g.predict_raw(X)
        internal = np.asarray(g.train_scores()[0])
        np.testing.assert_allclose(p, internal, rtol=1e-4, atol=1e-5)

    def test_predict_probability_range(self, binary_model):
        g, X, _ = binary_model
        p = g.predict(X[:100])
        assert np.all((p >= 0) & (p <= 1))

    def test_valid_auc_generalizes(self):
        X, y = make_binary()
        Xv, yv = make_binary(640, seed=7)
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc"},
                     num_round=20, valid=(Xv, yv))
        (_, auc, _), = g.get_eval_at(1)
        assert auc > 0.93


class TestRegression:
    def test_l2(self, regression_model):
        g, X, y = regression_model
        (_, l2, _), = g.get_eval_at(0)
        assert l2 < 0.35 * np.var(y)

    def test_l1_objective(self):
        X, y = make_regression()
        g = fit_gbdt(X, y, {"objective": "regression_l1", "metric": "l1"},
                     num_round=40)
        (_, l1, _), = g.get_eval_at(0)
        assert l1 < 0.7 * np.mean(np.abs(y - y.mean()))

    def test_quantile(self):
        X, y = make_regression()
        g = fit_gbdt(X, y, {"objective": "quantile", "alpha": 0.9},
                     num_round=30)
        p = g.predict(X)
        frac = np.mean(y <= p)
        assert 0.78 < frac <= 1.0

    @pytest.mark.parametrize("objective", ["huber", "fair", "poisson"])
    def test_other_objectives_run(self, objective):
        X, y = make_regression()
        if objective == "poisson":
            y = y - y.min() + 0.5
        g = fit_gbdt(X, y, {"objective": objective}, num_round=5)
        assert len(g.models) == 5


class TestMulticlass:
    def test_softmax_error(self, multiclass_model):
        g, X, y = multiclass_model
        evals = dict((n, v) for n, v, _ in g.get_eval_at(0))
        assert evals["multi_error"] < 0.12

    def test_predict_shape_and_simplex(self, multiclass_model):
        g, X, _ = multiclass_model
        p = g.predict(X[:50])
        assert p.shape == (50, 4)
        np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-5)

    def test_ova(self):
        X, y = make_multiclass()
        g = fit_gbdt(X, y, {"objective": "multiclassova", "num_class": 4,
                            "metric": "multi_error"}, num_round=15)
        (_, err, _), = g.get_eval_at(0)
        assert err < 0.15


class TestWeightsAndSampling:
    def test_weighted_binary(self):
        X, y = make_binary()
        w = np.where(y > 0, 2.0, 1.0).astype(np.float32)
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc"},
                     num_round=20, weight=w)
        (_, auc, _), = g.get_eval_at(0)
        assert auc > 0.95

    def test_bagging(self):
        X, y = make_binary()
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "bagging_fraction": 0.5, "bagging_freq": 1},
                     num_round=20)
        (_, auc, _), = g.get_eval_at(0)
        assert auc > 0.94

    def test_feature_fraction(self):
        X, y = make_binary()
        g = fit_gbdt(X, y, {"objective": "binary", "metric": "auc",
                            "feature_fraction": 0.5}, num_round=20)
        (_, auc, _), = g.get_eval_at(0)
        assert auc > 0.94


class TestModelIO:
    def test_text_roundtrip_exact(self, binary_model):
        g, X, _ = binary_model
        s = g.model_to_string()
        g2 = GBDT().load_model_from_string(s)
        # the reference's own codegen test asserts 5-decimal equality
        # (tests/cpp_test/test.py); device f32 vs host f64 accumulation
        np.testing.assert_allclose(
            g.predict_raw(X), g2.predict_raw(X), rtol=0, atol=1e-5)

    def test_reference_format_header(self, binary_model):
        g, _, _ = binary_model
        s = g.model_to_string()
        lines = s.splitlines()
        assert lines[0] == "tree"
        assert any(l.startswith("version=v2") for l in lines)
        assert any(l.startswith("num_class=1") for l in lines)
        assert any(l.startswith("feature_infos=") for l in lines)
        assert any(l.startswith("tree_sizes=") for l in lines)
        assert "end of trees" in s
        assert "end of parameters" in s

    def test_multiclass_roundtrip(self, multiclass_model):
        g, X, _ = multiclass_model
        g2 = GBDT().load_model_from_string(g.model_to_string())
        np.testing.assert_allclose(
            g.predict_raw(X[:100]), g2.predict_raw(X[:100]), atol=1e-5)

    def test_json_dump(self, binary_model):
        g, _, _ = binary_model
        d = g.dump_model()
        assert d["num_class"] == 1
        assert len(d["tree_info"]) == len(g.models)
        t0 = d["tree_info"][0]["tree_structure"]
        assert "split_feature" in t0 or "leaf_value" in t0

    def test_num_iteration_clamp(self, binary_model):
        g, X, _ = binary_model
        p5 = g.predict_raw(X[:50], num_iteration=5)
        pall = g.predict_raw(X[:50])
        assert not np.allclose(p5, pall)


class TestRollback:
    def test_rollback_one_iter(self):
        X, y = make_binary()
        g = fit_gbdt(X, y, {"objective": "binary"}, num_round=5)
        p5 = g.predict_raw(X)
        g.train_one_iter()
        g.rollback_one_iter()
        np.testing.assert_allclose(g.predict_raw(X), p5, atol=1e-5)
        assert len(g.models) == 5


class TestMonotone:
    def test_monotone_constraints_hold(self):
        r = np.random.default_rng(3)
        n = 1280
        X = r.uniform(-2, 2, size=(n, 3))
        y = (X[:, 0] + 0.3 * np.sin(3 * X[:, 1])
             + 0.05 * r.normal(size=n)).astype(np.float32)
        g = fit_gbdt(X, y, {"objective": "regression",
                            "monotone_constraints": [1, 0, 0]},
                     num_round=25)
        base = np.zeros((200, 3))
        base[:, 0] = np.linspace(-2, 2, 200)
        p = g.predict(base)
        assert np.all(np.diff(p) >= -1e-6)


class TestFeatureImportance:
    def test_importance_finds_signal(self, binary_model):
        g, _, _ = binary_model
        imp = g.feature_importance("split")
        # features 0-3 carry signal, 4+ are noise
        assert imp[:4].sum() > imp[4:].sum()

    def test_gain_importance(self, binary_model):
        g, _, _ = binary_model
        imp = g.feature_importance("gain")
        assert imp.sum() > 0


def test_device_type_routing():
    """Explicit device_type routes the framework's device selection
    (the reference's CPU/GPU switch); the operator env pin is never
    touched, unknown values fatal, tpu clears a prior cpu routing."""
    import os
    import pytest as _pytest
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils import device
    from lightgbm_tpu.utils.log import LightGBMError
    before = os.environ.get("LGBM_TPU_PLATFORM")
    try:
        Config().set({"device_type": "cpu"})
        assert device._config_platform == "cpu"
        assert os.environ.get("LGBM_TPU_PLATFORM") == before
        Config().set({"device_type": "tpu"})
        assert device._config_platform is None
        with _pytest.raises(LightGBMError):
            Config().set({"device_type": "banana"})
    finally:
        device.set_config_platform(None)


def test_cv_accepts_test_index_folds():
    """cv(folds=[test_idx, ...]) — the reference R package's custom
    folds semantics: bare test-index arrays whose train side is the
    complement, normalized AFTER the dataset is constructed with the
    merged params."""
    import lightgbm_tpu as lgb
    X, y = make_binary(n=900, f=5, seed=31)
    ds = lgb.Dataset(X, label=y)
    folds = [np.arange(0, 300), np.arange(300, 600),
             np.arange(600, 900)]
    res = lgb.cv({"objective": "binary", "metric": "auc",
                  "num_leaves": 15, "verbose": -1}, ds,
                 num_boost_round=8, folds=folds, verbose_eval=False)
    assert "auc-mean" in res and len(res["auc-mean"]) == 8
    assert res["auc-mean"][-1] > 0.9
    # pair form still works
    ds2 = lgb.Dataset(X, label=y)
    pairs = [(np.arange(300, 900), np.arange(0, 300))]
    res2 = lgb.cv({"objective": "binary", "metric": "auc",
                   "num_leaves": 15, "verbose": -1}, ds2,
                  num_boost_round=5, folds=pairs, verbose_eval=False)
    assert len(res2["auc-mean"]) == 5
