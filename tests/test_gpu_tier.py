"""GPU (Pallas-Triton) backend suite (pytest -m gpu_tier).

Three layers lock the GPU port down, all runnable on any backend:

1. **Kernel bit-parity** — the Pallas-GPU histogram kernels
   (wave_histogram_pallas_gpu, fused_partition_histogram_pallas_gpu)
   in interpret mode must reproduce the XLA oracles BIT-FOR-BIT across
   the awkward-numerics grid (-0.0 gradients, out-of-bag rows,
   categorical bitsets, quantized int8 tier, count-proxy two-channel,
   odd-feature packed4 nibbles), and the fused forest traversal
   (forest_predict_pallas_gpu) must match the TPU Pallas kernel's
   interpret-mode bits at the same row tile plus the host traversal
   within fp32 tolerance.
2. **Device-kind autotune arms** — tune_hist_route's capability
   ladder, the shared-memory candidate guard
   (gpu_hist_chunk_candidates / gpu_hist_smem_bytes / fits_smem), and
   tune_hist_chunk's GPU arm driven by an injected fake timer
   (selection + cache-hit semantics without a physical GPU).
3. **Per-backend step-cache keying** — WaveGrowerConfig.route rides
   the compiled-step geometry key: a forced pallas-gpu training run
   (interpret mode) compiles its OWN step, trains bit-identical trees
   to the fused-XLA route, and a same-geometry retrain is a pure
   registry hit.

The whole module skips cleanly (with the capability named in the
reason) when this jax cannot lower Pallas-Triton — the same gate
tune_hist_route uses for the pallas-gpu rung.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from conftest import TEST_PARAMS, fit_gbdt, make_binary, make_multiclass
from lightgbm_tpu.ops import autotune, step_cache
from lightgbm_tpu.ops import stacked_predict as sp
from lightgbm_tpu.ops.hist_wave import (
    TBL_CATW, TBL_ISCAT, fused_partition_histogram_pallas_gpu,
    fused_partition_histogram_xla, wave_histogram,
    wave_histogram_pallas_gpu, wave_histogram_xla)
from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
from lightgbm_tpu.ops.stacked_predict import StackedModel
from lightgbm_tpu.ops.wave_grower import WaveGrowerConfig, make_wave_grower

pytestmark = [
    pytest.mark.gpu_tier,
    pytest.mark.skipif(
        not autotune.gpu_pallas_supported(),
        reason="jax.experimental.pallas.triton not importable — the "
               "pallas-gpu route is gated off on this install, so the "
               "interpret-mode parity suite has nothing to certify"),
]


def _jx(*arrs):
    return tuple(jnp.asarray(a) for a in arrs)


def _kernel_problem(kind, N=777, F=6, B=63, n_leaves=5, seed=3):
    """(bins_t, g, h, leaf) with the grid's awkward numerics (the
    exact-tier suite's fixture, shared shape)."""
    r = np.random.default_rng(seed)
    bins_t = r.integers(0, B, (F, N)).astype(np.uint8)
    g = r.normal(size=N).astype(np.float32)
    h = r.uniform(0.2, 1.0, N).astype(np.float32)
    leaf = r.integers(-1, n_leaves, N).astype(np.int32)
    if kind == "neg_zero":
        g[::7] = -0.0
        g[1::7] = 0.0
    elif kind == "zero_hess":
        h[::5] = 0.0
    elif kind == "bag_heavy":
        leaf[r.random(N) < 0.6] = -1
    return bins_t, g, h, leaf


KERNEL_KINDS = ["plain", "neg_zero", "zero_hess", "bag_heavy"]


def _pack4(bins_t):
    """Two 4-bit bins per byte, feature 2p in the LOW nibble of byte
    row p (the _feature_row / _gpu_unpack_row layout); an odd feature
    count leaves the last high nibble zero."""
    F, N = bins_t.shape
    p = np.zeros(((F + 1) // 2, N), np.uint8)
    for f in range(F):
        p[f // 2] |= bins_t[f] << (4 * (f % 2))
    return p


# ---------------------------------------------------------------------------
# 1a. wave histogram kernel bit-parity
# ---------------------------------------------------------------------------

class TestWaveGpuKernel:
    @pytest.mark.parametrize("kind", KERNEL_KINDS)
    def test_bitwise_vs_xla_oracle(self, kind):
        """f32 channels INCLUDED: the per-row ascending atomic order
        is the oracle's combined-scatter order, so interpret mode is
        bit-equal, not merely close."""
        bins_t, g, h, leaf = _kernel_problem(kind)
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = _jx(bins_t, g, h, leaf, wl)
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        got = np.asarray(wave_histogram_pallas_gpu(
            *args, num_bins=64, chunk=256, interpret=True))
        np.testing.assert_array_equal(got, ref)

    def test_variants_are_layout_free(self):
        """Every hilo layout lowers to the same layout-free GPU kernel
        (no 128-lane budget to ration) — identical bits across the
        variant knob, which exists for interface parity only."""
        bins_t, g, h, leaf = _kernel_problem("plain")
        wl = np.array([0, 1, 2, 3, 4], np.int32)
        args = _jx(bins_t, g, h, leaf, wl)
        outs = [np.asarray(wave_histogram_pallas_gpu(
            *args, num_bins=64, chunk=256, interpret=True, variant=v))
            for v in ("hilo5", "hilo4", "hilo3")]
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])

    def test_int8_tier_raw_wire_and_dequant(self):
        """Quantized tier: int32 accumulation of integer-valued g/h is
        exact; dequant=False hands back the quantized-psum wire format
        and gh_scale dequantizes exactly like the oracle's f32 sums."""
        bins_t, _, _, leaf = _kernel_problem("bag_heavy")
        r = np.random.default_rng(9)
        N = bins_t.shape[1]
        gq = r.integers(-127, 128, N).astype(np.float32)
        hq = r.integers(0, 128, N).astype(np.float32)
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = _jx(bins_t, gq, hq, leaf, wl)
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        raw = np.asarray(wave_histogram_pallas_gpu(
            *args, num_bins=64, chunk=256, interpret=True,
            precision="int8", dequant=False))
        assert raw.dtype == np.int32
        np.testing.assert_array_equal(raw.astype(np.float32), ref)
        deq = np.asarray(wave_histogram_pallas_gpu(
            *args, num_bins=64, chunk=256, interpret=True,
            precision="int8", gh_scale=(0.5, 0.25)))
        np.testing.assert_array_equal(
            deq, ref * np.array([0.5, 0.25, 1.0], np.float32))

    def test_count_proxy_two_channel(self):
        """count_proxy drops the count plane: [W, F, B, 2] of exactly
        the oracle's g/h channels, dequantized by the 2-vector."""
        bins_t, _, _, leaf = _kernel_problem("plain")
        r = np.random.default_rng(10)
        N = bins_t.shape[1]
        gq = r.integers(-127, 128, N).astype(np.float32)
        hq = r.integers(0, 128, N).astype(np.float32)
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = _jx(bins_t, gq, hq, leaf, wl)
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        got = np.asarray(wave_histogram_pallas_gpu(
            *args, num_bins=64, chunk=256, interpret=True,
            precision="int8", count_proxy=True, gh_scale=(0.5, 2.0)))
        assert got.shape == ref[..., :2].shape
        np.testing.assert_array_equal(
            got, ref[..., :2] * np.array([0.5, 2.0], np.float32))

    def test_packed4_odd_feature_count(self):
        """4-bit nibble tier with an ODD logical feature count — the
        dangling high nibble must not leak into the histogram."""
        bins_t, g, h, leaf = _kernel_problem("plain", F=5, B=16)
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        ref = np.asarray(wave_histogram_xla(
            *_jx(bins_t, g, h, leaf, wl), num_bins=16))
        got = np.asarray(wave_histogram_pallas_gpu(
            *_jx(_pack4(bins_t), g, h, leaf, wl), num_bins=16,
            chunk=256, interpret=True, packed4=True, num_features=5))
        np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# 1b. fused partition+histogram kernel bit-parity
# ---------------------------------------------------------------------------

def _fused_case(categorical=False, B=64, seed=0):
    """One wave of 4 live splits (+4 inactive slots) with bagging,
    -0.0 gradients, missing-type metadata; optionally slot 0 becomes a
    categorical bitset split."""
    r = np.random.default_rng(seed)
    N, F, W = 999, 5, 8
    bins_t = r.integers(0, B - 1, (F, N)).astype(np.uint8)
    g = r.normal(size=N).astype(np.float32)
    g[::9] = -0.0
    h = r.uniform(0.1, 1, N).astype(np.float32)
    mask = (r.uniform(size=N) > 0.3).astype(np.float32)
    leaf = r.integers(0, 4, N).astype(np.int32)
    wl = np.array([0, 1, 2, 3, -1, -1, -1, -1], np.int32)
    new_ids = np.array([4, 5, 6, 7, -1, -1, -1, -1], np.int32)
    feat = r.integers(0, F, W).astype(np.int32)
    tbin = r.integers(0, B - 4, W).astype(np.int32)
    dleft = r.integers(0, 2, W).astype(bool)
    meta = FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.array([0, 1, 2, 0, 1], np.int32),
        default_bin=np.array([0, 3, 0, 0, 5], np.int32),
        monotone=np.zeros(F, np.int32),
        penalty=np.ones(F, np.float32))
    iscat = np.zeros(W, bool)
    catw = np.zeros((W, 8), np.int32)
    if categorical:
        iscat[0] = True
        bits = r.integers(0, 2, B, dtype=np.int64)
        for b in np.nonzero(bits)[0]:
            catw[0, b // 32] |= 1 << (b % 32)
    tbl = np.zeros((18, W), np.int32)
    tbl[0], tbl[1], tbl[2], tbl[3] = wl, new_ids, feat, tbin
    tbl[4] = dleft.astype(np.int32)
    tbl[5] = meta.missing_type[feat]
    tbl[6] = meta.default_bin[feat]
    tbl[7] = meta.num_bin[feat]
    tbl[8] = new_ids                # small = right child
    tbl[TBL_ISCAT] = iscat.astype(np.int32)
    for q in range(8):
        tbl[TBL_CATW + q] = catw[:, q]
    oracle_args = (wl, new_ids, feat, tbin, dleft, iscat, catw,
                   new_ids, meta.missing_type[np.maximum(feat, 0)],
                   meta.default_bin[np.maximum(feat, 0)],
                   meta.num_bin[np.maximum(feat, 0)])
    return bins_t, g, h, mask, leaf, tbl, oracle_args, B


class TestFusedGpuKernel:
    @pytest.mark.parametrize("categorical", [False, True])
    def test_bitwise_vs_xla_oracle(self, categorical):
        (bins_t, g, h, mask, leaf, tbl, oargs, B) = _fused_case(
            categorical)
        gm, hm = g * mask, h * mask
        lr, hr = fused_partition_histogram_xla(
            *_jx(bins_t, gm, hm, mask, leaf, *oargs), num_bins=B)
        lg, hg = fused_partition_histogram_pallas_gpu(
            *_jx(bins_t, gm, hm, mask, leaf, tbl), num_bins=B,
            chunk=256, interpret=True, any_cat=categorical)
        np.testing.assert_array_equal(np.asarray(lg), np.asarray(lr))
        np.testing.assert_array_equal(np.asarray(hg), np.asarray(hr))

    def test_int8_count_proxy_with_exact_counts(self):
        """Quantized proxy tier: 2-channel dequantized histogram plus
        the EXACT in-bag moved-row counts the partition mask implies."""
        (bins_t, _, _, mask, leaf, tbl, oargs, B) = _fused_case()
        r = np.random.default_rng(5)
        N = bins_t.shape[1]
        gq = (r.integers(-127, 128, N) * mask).astype(np.float32)
        hq = (r.integers(0, 128, N) * mask).astype(np.float32)
        lr, hr, cr = fused_partition_histogram_xla(
            *_jx(bins_t, gq, hq, mask, leaf, *oargs), num_bins=B,
            count_proxy=True)
        lg, hg, cg = fused_partition_histogram_pallas_gpu(
            *_jx(bins_t, gq, hq, mask, leaf, tbl), num_bins=B,
            chunk=256, interpret=True, precision="int8",
            count_proxy=True, gh_scale=(0.5, 0.25))
        np.testing.assert_array_equal(np.asarray(lg), np.asarray(lr))
        np.testing.assert_array_equal(
            np.asarray(hg),
            np.asarray(hr)[..., :2] * np.array([0.5, 0.25], np.float32))
        np.testing.assert_array_equal(np.asarray(cg), np.asarray(cr))

    def test_packed4_odd_feature_count(self):
        (bins_t, g, h, mask, leaf, tbl, oargs, _) = _fused_case(B=16)
        gm, hm = g * mask, h * mask
        lr, hr = fused_partition_histogram_xla(
            *_jx(bins_t, gm, hm, mask, leaf, *oargs), num_bins=16)
        lg, hg = fused_partition_histogram_pallas_gpu(
            *_jx(_pack4(bins_t), gm, hm, mask, leaf, tbl), num_bins=16,
            chunk=256, interpret=True, packed4=True, num_features=5)
        np.testing.assert_array_equal(np.asarray(lg), np.asarray(lr))
        np.testing.assert_array_equal(np.asarray(hg), np.asarray(hr))

    def test_dispatcher_pins_gpu_route_off_device(self):
        """wave_histogram(route='pallas-gpu') on a CPU backend runs the
        GPU kernel in interpret mode — the dryrun/parity entry point."""
        bins_t, g, h, leaf = _kernel_problem("plain")
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = _jx(bins_t, g, h, leaf, wl)
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        got = np.asarray(wave_histogram(
            *args, num_bins=64, route="pallas-gpu"))
        np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# 1c. fused forest traversal bit-parity
# ---------------------------------------------------------------------------

def _stacked(g):
    g._ensure_host_trees()
    sm = StackedModel(g.models, g.max_feature_idx + 1,
                      g.num_tree_per_iteration)
    assert sm.ok
    return sm


def _host_raw(g, X):
    g._ensure_host_trees()
    k = g.num_tree_per_iteration
    out = np.zeros((k, X.shape[0]))
    for t, m in enumerate(g.models):
        out[t % k] += m.predict(X)
    return out


def _pallas_stacks(sm):
    dev = sm._device_arrays_pallas(0, sm.num_trees, sm._pallas_tc())
    return dev, tuple(int(o) for o in sm._offsets)


class TestForestGpuKernel:
    ROW_TILE = 512

    def test_binary_with_nans_bitwise_vs_tpu_interpret(self):
        """Same row tile, same step order, exact integer decision
        algebra: the GPU forest kernel's interpret bits equal the TPU
        Pallas kernel's interpret bits, and both track the host
        traversal within fp32 tolerance."""
        X, y = make_binary(n=1200, f=6, seed=47)
        g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                     num_round=13)
        sm = _stacked(g)
        Xt = np.random.default_rng(11).normal(size=(700, 6))
        Xt[::9, 1] = np.nan
        codes = jnp.asarray(np.ascontiguousarray(sm._bin_rows(Xt).T))
        dev, offs = _pallas_stacks(sm)
        a = sp.forest_predict_pallas(
            codes, *dev, offsets=offs, row_tile=self.ROW_TILE,
            interpret=True)
        b = sp.forest_predict_pallas_gpu(
            codes, *dev, offsets=offs, row_tile=self.ROW_TILE,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(b)[:700].T,
                                   _host_raw(g, Xt), atol=1e-5)

    def test_multiclass_and_from_x_devbin(self):
        r = np.random.default_rng(51)
        X = r.normal(size=(1100, 5)).astype(np.float32).astype(
            np.float64)
        y = ((np.abs(X[:, 0]) + X[:, 1] > 1).astype(int)
             + (X[:, 2] > 0)).astype(np.float32)
        g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="multiclass",
                                num_class=3), num_round=6)
        sm = _stacked(g)
        Xt = r.normal(size=(500, 5))
        codes = jnp.asarray(np.ascontiguousarray(sm._bin_rows(Xt).T))
        dev, offs = _pallas_stacks(sm)
        a = sp.forest_predict_pallas(
            codes, *dev, offsets=offs, row_tile=self.ROW_TILE,
            interpret=True)
        b = sp.forest_predict_pallas_gpu(
            codes, *dev, offsets=offs, row_tile=self.ROW_TILE,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # device-binning twin: float rows in, same bits out
        assert sm._dev_bin_ok
        aux = (jnp.asarray(sm._E_f32), jnp.asarray(sm._off32),
               jnp.asarray(sm._nan_slot))
        xf = jnp.asarray(Xt.astype(np.float32))
        c = sp.forest_predict_from_x(
            xf, *aux, *dev, offsets=offs, row_tile=self.ROW_TILE,
            interpret=True)
        d = sp.forest_predict_from_x_gpu(
            xf, *aux, *dev, offsets=offs, row_tile=self.ROW_TILE,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(c), np.asarray(d))


# ---------------------------------------------------------------------------
# 2. device-kind autotune arms
# ---------------------------------------------------------------------------

class TestGpuAutotuneArms:
    @pytest.fixture
    def fresh_tuner(self, tmp_path):
        autotune.configure("on", str(tmp_path / "tuning.json"))
        yield
        autotune.configure("on", None)

    def test_tune_hist_route_capability_ladder(self):
        assert autotune.tune_hist_route(backend="tpu") == "pallas-tpu"
        assert autotune.tune_hist_route(backend="gpu") == "pallas-gpu"
        assert autotune.tune_hist_route(backend="cpu") == "fused-xla"
        assert autotune.tune_hist_route(
            backend="cpu", fused_eligible=False) == "two-pass"
        # config override beats capability, both directions
        assert autotune.tune_hist_route(
            backend="gpu", use_pallas=False) == "fused-xla"
        assert autotune.tune_hist_route(
            backend="cpu", use_pallas=True) == "pallas-tpu"

    def test_candidates_respect_smem_budget(self):
        geom = autotune.hist_geometry(F=8, B=64, W=8, F_rows=8)
        cands = autotune.gpu_hist_chunk_candidates(F=8, B=64, W=8,
                                                   fused=False)
        assert cands, "small geometry must admit at least one tile"
        chunks = [c["chunk"] for c in cands]
        assert chunks == sorted(chunks, reverse=True), "largest-first"
        for c in chunks:
            assert autotune.fits_smem(autotune.gpu_hist_smem_bytes(
                chunk=c, geom=geom, fused=False))
        # pricing is monotone in the tile, and the fused kernel's
        # extra per-row operands (mask, leaf in/out, table) cost more
        b1 = autotune.gpu_hist_smem_bytes(chunk=512, geom=geom,
                                          fused=False)
        b2 = autotune.gpu_hist_smem_bytes(chunk=1024, geom=geom,
                                          fused=False)
        bf = autotune.gpu_hist_smem_bytes(chunk=512, geom=geom,
                                          fused=True)
        assert b1 < b2 and bf > b1

    def test_candidates_cap_at_n_rows(self):
        chunks = [c["chunk"] for c in autotune.gpu_hist_chunk_candidates(
            F=8, B=64, W=8, fused=False, n_rows=300)]
        assert chunks == [256]

    def test_exhaustive_superset(self):
        norm = {c["chunk"] for c in autotune.gpu_hist_chunk_candidates(
            F=8, B=64, W=8, fused=False)}
        exh = {c["chunk"] for c in autotune.gpu_hist_chunk_candidates(
            F=8, B=64, W=8, fused=False, exhaustive=True)}
        assert norm <= exh and len(exh) > len(norm)

    def test_gpu_arm_fake_timer_selection_and_cache(self, fresh_tuner):
        """The GPU arm engages off-TPU whenever a timer is injected:
        the fastest shared-memory-feasible tile wins, and the second
        encounter of the (kernel, geometry, device) key times nothing."""
        calls = []

        def fake(cand):
            calls.append(cand["chunk"])
            return {256: 2.0, 512: 0.5, 1024: 1.0, 2048: 3.0}.get(
                cand["chunk"], 9.0)

        got = autotune.tune_hist_chunk(fused=False, F=8, B=64, W=8,
                                       _measure=fake)
        assert got == 512
        assert set(calls) == {c["chunk"] for c in
                              autotune.gpu_hist_chunk_candidates(
                                  F=8, B=64, W=8, fused=False)}
        calls.clear()
        again = autotune.tune_hist_chunk(fused=False, F=8, B=64, W=8,
                                         _measure=fake)
        assert again == 512
        assert calls == [], "second encounter must be a cache hit"
        # the fused kernel tunes under its own name — same geometry,
        # fresh timing run, no collision with the wave decision
        fused_choice = autotune.tune_hist_chunk(fused=True, F=8, B=64,
                                                W=8, _measure=fake)
        assert calls, "fused arm must not reuse the wave cache entry"
        assert fused_choice == 512

    def test_cpu_backend_without_timer_keeps_default(self, fresh_tuner):
        assert autotune.tune_hist_chunk(
            fused=False, F=8, B=64, W=8) == autotune.DEFAULT_HIST_CHUNK

    def test_sparse_tier_ceiling_is_lower_on_gpu(self):
        """On the gpu route the sparse tier forfeits the fused Pallas
        kernel, so auto demands a sparser matrix than elsewhere."""
        kw = dict(requested=-1, nnz=1000, F=8, B=64, W=8, quant=True)
        mid = (autotune.SPARSE_TIER_MAX_DENSITY
               + autotune.SPARSE_TIER_MAX_DENSITY_GPU) / 2
        assert autotune.tune_hist_tier(density=mid, backend="cpu", **kw)
        assert not autotune.tune_hist_tier(density=mid, backend="gpu",
                                           **kw)
        assert autotune.tune_hist_tier(
            density=autotune.SPARSE_TIER_MAX_DENSITY_GPU / 2,
            backend="gpu", **kw)


# ---------------------------------------------------------------------------
# 3. per-backend step-cache keying
# ---------------------------------------------------------------------------

def _trees(g):
    return g.model_to_string().split("parameters:")[0]


def _stats_delta(fn):
    s0 = step_cache.stats()
    out = fn()
    s1 = step_cache.stats()
    return out, {k: s1[k] - s0[k] for k in ("hits", "misses")}


class TestStepCacheKeying:
    def test_route_field_separates_config_identity(self):
        kw = dict(num_leaves=15, num_bins=63, wave_size=8,
                  hp=SplitParams())
        a = WaveGrowerConfig(**kw, route="fused-xla")
        b = WaveGrowerConfig(**kw, route="pallas-gpu")
        assert a != b and hash(a) != hash(b)

    def test_bogus_route_rejected(self):
        meta = FeatureMeta(
            num_bin=np.full(4, 63, np.int32),
            missing_type=np.zeros(4, np.int32),
            default_bin=np.zeros(4, np.int32),
            monotone=np.zeros(4, np.int32),
            penalty=np.ones(4, np.float32))
        cfg = WaveGrowerConfig(num_leaves=15, num_bins=63, wave_size=8,
                               hp=SplitParams(), route="pallas-rocm")
        with pytest.raises(ValueError, match="route"):
            make_wave_grower(cfg, meta)

    def test_gpu_route_trains_bit_identical_and_keys_apart(
            self, monkeypatch):
        """Force the pallas-gpu route on this CPU host (interpret
        mode): the model is BIT-identical to the fused-XLA route's, the
        first GPU-route booster compiles its own step (registry miss —
        the route rides the geometry key), and a same-geometry
        GPU-route retrain is a pure registry hit."""
        X, y = make_binary(640, seed=21)
        params = dict(TEST_PARAMS, objective="binary")
        g_cpu, _ = _stats_delta(
            lambda: fit_gbdt(X, y, params, num_round=4))
        monkeypatch.setattr(autotune, "tune_hist_route",
                            lambda **kw: "pallas-gpu")
        g_gpu1, d1 = _stats_delta(
            lambda: fit_gbdt(X, y, params, num_round=4))
        g_gpu2, d2 = _stats_delta(
            lambda: fit_gbdt(X, y, params, num_round=4))
        assert _trees(g_gpu1) == _trees(g_cpu), \
            "pallas-gpu interpret route must reproduce the fused-XLA " \
            "route's trees bit-for-bit"
        assert d1["misses"] >= 1, \
            "the GPU route must compile its own step program"
        assert d2["misses"] == 0 and d2["hits"] >= 1, \
            "same-geometry GPU-route retrain must be a registry hit"
        assert _trees(g_gpu2) == _trees(g_gpu1)
