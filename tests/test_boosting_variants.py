"""GOSS / DART / RF boosting-variant tests.

Ports of the reference variant coverage (reference:
tests/python_package_test/test_engine.py:50-74 test_rf, :311-337
test_multiclass_rf, :719-752 test_mape_rf/test_mape_dart) on small
synthetics.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _binary_data(n=500, f=10, seed=42):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.5 + X[:, 1] - 0.5 * X[:, 2]
    y = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _logloss(y, p):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


class TestRF:
    def test_rf_binary(self):
        # test_engine.py:50-74
        X, y = _binary_data()
        params = {"boosting_type": "rf", "objective": "binary",
                  "bagging_freq": 1, "bagging_fraction": 0.5,
                  "feature_fraction": 0.5, "num_leaves": 31,
                  "metric": "binary_logloss", "verbose": -1}
        evals_result = {}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=30,
                        valid_sets=lgb.Dataset(X, y, reference=None),
                        verbose_eval=False, evals_result=evals_result)
        # RF raw scores are averaged leaf means of the 0/1 label, so the
        # sigmoid compresses predictions into [0.5, 0.73] (this fork's
        # rf.hpp has no binary leaf renewal) — judge separation, not
        # absolute logloss
        pred = gbm.predict(X)
        raw = gbm.predict(X, raw_score=True)
        assert raw[y > 0].mean() - raw[y == 0].mean() > 0.25
        thr = np.median(pred)
        assert ((pred > thr) == y).mean() > 0.85
        assert 0.0 <= pred.min() and pred.max() <= 1.0
        # model file carries the average_output marker
        assert "average_output" in gbm.model_to_string()

    def test_rf_prediction_matches_training_score(self):
        X, y = _binary_data(n=300)
        params = {"boosting_type": "rf", "objective": "binary",
                  "bagging_freq": 1, "bagging_fraction": 0.6,
                  "feature_fraction": 0.7, "verbose": -1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=10,
                        verbose_eval=False)
        raw = gbm.predict(X, raw_score=True)
        train_scores = np.asarray(gbm._gbdt.train_scores())[0]
        np.testing.assert_allclose(raw, train_scores, atol=1e-4)

    def test_rf_multiclass(self):
        # test_engine.py:311-337
        rng = np.random.default_rng(0)
        y = rng.integers(0, 3, 400).astype(np.float64)
        X = rng.normal(size=(400, 6))
        X[:, 0] += 2 * y
        params = {"boosting_type": "rf", "objective": "multiclass",
                  "num_class": 3, "bagging_freq": 1,
                  "bagging_fraction": 0.6, "feature_fraction": 0.6,
                  "verbose": -1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=20,
                        verbose_eval=False)
        pred = gbm.predict(X)
        assert (pred.argmax(axis=1) == y).mean() > 0.8


class TestGOSS:
    def test_goss_binary(self):
        X, y = _binary_data(n=1000)
        params = {"boosting_type": "goss", "objective": "binary",
                  "metric": "binary_logloss", "top_rate": 0.2,
                  "other_rate": 0.1, "learning_rate": 0.1,
                  "verbose": -1}
        evals_result = {}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=40,
                        valid_sets=lgb.Dataset(X, y, reference=None),
                        verbose_eval=False, evals_result=evals_result)
        ll = evals_result["valid_0"]["binary_logloss"]
        assert ll[-1] < 0.3
        assert ll[-1] < ll[0]
        assert ((gbm.predict(X) > 0.5) == y).mean() > 0.9

    def test_goss_sampling_activates(self):
        """After warmup, trees must see only ~(top_rate+other_rate) of
        the rows — guards against the sampler silently no-op'ing."""
        X, y = _binary_data(n=2000)
        params = {"boosting_type": "goss", "objective": "binary",
                  "top_rate": 0.1, "other_rate": 0.1,
                  "learning_rate": 0.5, "verbose": -1}   # warmup = 2
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=6,
                        verbose_eval=False, keep_training_booster=True)
        g = gbm._gbdt
        first = float(np.asarray(g.records[0].leaf_count).sum())
        last = float(np.asarray(g.records[-1].leaf_count).sum())
        assert first == 2000          # warmup tree sees everything
        assert 250 < last < 650      # ~0.2 * n afterwards

    def test_goss_rejects_bagging(self):
        X, y = _binary_data(n=100)
        params = {"boosting_type": "goss", "objective": "binary",
                  "bagging_freq": 1, "bagging_fraction": 0.5,
                  "verbose": -1}
        with pytest.raises(lgb.LightGBMError):
            lgb.train(params, lgb.Dataset(X, y), num_boost_round=2,
                      verbose_eval=False)

    def test_mape_goss(self):
        # GOSS composes with the leaf-renewal objectives
        rng = np.random.default_rng(3)
        X = rng.normal(size=(600, 5))
        y = np.abs(X[:, 0] * 3 + 10 + 0.2 * rng.normal(size=600))
        params = {"boosting_type": "goss", "objective": "mape",
                  "verbose": -1, "learning_rate": 0.2}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=30,
                        verbose_eval=False)
        mape = np.mean(np.abs(y - gbm.predict(X)) / np.maximum(y, 1))
        assert mape < 0.3

    def test_goss_hash_mask_is_width_invariant(self):
        """The hashed sampler draws from the GLOBAL row index, not the
        array position: the same rows are kept no matter how far the
        row axis is padded (the property that makes the sample
        identical under step-cache bucketing AND row sharding, where
        the legacy positional PRNG draw changes with the width)."""
        import jax.numpy as jnp
        X, y = _binary_data(n=600)
        gbm = lgb.train({"boosting_type": "goss", "objective": "binary",
                         "verbose": -1}, lgb.Dataset(X, y),
                        num_boost_round=1, verbose_eval=False,
                        keep_training_booster=True)
        hook = gbm._gbdt._sample_hook
        rng = np.random.default_rng(0)
        n = 600
        g = rng.normal(size=(1, n)).astype(np.float32)
        h = np.ones((1, n), np.float32)
        key = jnp.asarray([0, 123], jnp.uint32)

        def run(width):
            gp = np.zeros((1, width), np.float32)
            gp[:, :n] = g
            hp = np.zeros((1, width), np.float32)
            hp[:, :n] = h
            rv = np.zeros(width, bool)
            rv[:n] = True
            go, ho, m = hook(jnp.asarray(gp), jnp.asarray(hp),
                             jnp.ones(width, jnp.float32), key,
                             jnp.asarray(rv))
            return (np.asarray(go)[:, :n], np.asarray(ho)[:, :n],
                    np.asarray(m)[:n])
        a, b = run(1024), run(2048)
        for x, z in zip(a, b):
            np.testing.assert_array_equal(x, z)
        # and the sample is live: some rows dropped, some amplified
        assert 0 < float(a[2].sum()) < n
        np.testing.assert_array_equal(
            np.asarray(a[0] != 0).any(), True)

    def test_goss_hash_matches_legacy_quality(self):
        """tpu_goss_hash=0 keeps the positional-PRNG sampler as a
        repro oracle; the hashed default must reach the same quality
        (AUC equivalence, not bit parity — the draws differ)."""
        from conftest import rank_auc
        X, y = _binary_data(n=1500, seed=9)
        out = {}
        for name, hashed in (("hash", -1), ("legacy", 0)):
            gbm = lgb.train(
                {"boosting_type": "goss", "objective": "binary",
                 "top_rate": 0.2, "other_rate": 0.1,
                 "learning_rate": 0.1, "verbose": -1,
                 "tpu_goss_hash": hashed},
                lgb.Dataset(X, y), num_boost_round=40,
                verbose_eval=False)
            out[name] = rank_auc(y, gbm.predict(X))
        assert out["hash"] > 0.9
        assert abs(out["hash"] - out["legacy"]) < 0.02

    def test_goss_hash_data_parallel(self):
        """Hashed GOSS composes with the row-sharding data learner:
        sampling activates post-warmup and the booster stays
        registry-eligible."""
        X, y = _binary_data(n=2000)
        params = {"boosting_type": "goss", "objective": "binary",
                  "top_rate": 0.1, "other_rate": 0.1,
                  "learning_rate": 0.5, "verbose": -1,   # warmup = 2
                  "tree_learner": "data"}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=6,
                        verbose_eval=False, keep_training_booster=True)
        g = gbm._gbdt
        assert g._cache_eligible
        first = float(np.asarray(g.records[0].leaf_count).sum())
        last = float(np.asarray(g.records[-1].leaf_count).sum())
        assert first == 2000
        assert 250 < last < 650


class TestDART:
    def test_dart_binary(self):
        X, y = _binary_data(n=600)
        params = {"boosting_type": "dart", "objective": "binary",
                  "metric": "binary_logloss", "drop_rate": 0.3,
                  "skip_drop": 0.3, "verbose": -1}
        evals_result = {}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=40,
                        valid_sets=lgb.Dataset(X, y, reference=None),
                        verbose_eval=False, evals_result=evals_result)
        ll = evals_result["valid_0"]["binary_logloss"]
        assert ll[-1] < 0.4
        assert ((gbm.predict(X) > 0.5) == y).mean() > 0.9

    def test_dart_scores_consistent_with_model(self):
        """After training, replaying the serialized model must equal the
        maintained train scores (the normalization bookkeeping)."""
        X, y = _binary_data(n=300)
        params = {"boosting_type": "dart", "objective": "binary",
                  "drop_rate": 0.5, "skip_drop": 0.0, "verbose": -1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=15,
                        verbose_eval=False, keep_training_booster=True)
        raw = gbm.predict(X, raw_score=True)
        train_scores = np.asarray(gbm._gbdt.train_scores())[0]
        np.testing.assert_allclose(raw, train_scores, rtol=1e-4,
                                   atol=1e-4)

    def test_mape_dart(self):
        # test_engine.py:736-752
        rng = np.random.default_rng(3)
        X = rng.normal(size=(600, 5))
        y = np.abs(X[:, 0] * 3 + 10 + 0.2 * rng.normal(size=600))
        params = {"boosting_type": "dart", "objective": "mape",
                  "verbose": -1, "learning_rate": 0.2}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=30,
                        verbose_eval=False)
        mape = np.mean(np.abs(y - gbm.predict(X)) / np.maximum(y, 1))
        assert mape < 0.35

    def test_dart_serialization_roundtrip(self):
        X, y = _binary_data(n=200)
        params = {"boosting_type": "dart", "objective": "binary",
                  "drop_rate": 0.4, "skip_drop": 0.2, "verbose": -1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=10,
                        verbose_eval=False)
        loaded = lgb.Booster(model_str=gbm.model_to_string())
        np.testing.assert_allclose(loaded.predict(X), gbm.predict(X),
                                   atol=1e-5)
