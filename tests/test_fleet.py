"""Serving fleet (lightgbm_tpu/serve/): coalescer bit-parity against
direct predict (in-process AND over the HTTP wire), cross-tenant
compiled-program reuse through the predict registry, versioned warm
swap under load, the SLO admission-control shed drill (latency fault
burns one tenant's p99 budget -> 429 pre-breach while neighbors keep
serving), bounded-queue backpressure, and daemon lifecycle.

``pytest -m fleet``.
"""
import threading
import time
import urllib.error

import numpy as np
import pytest

from lightgbm_tpu import capi
from lightgbm_tpu.ops import predict_cache
from lightgbm_tpu.serve import (Coalescer, FleetClient, QueueFull,
                                ScoringDaemon, ShedError,
                                TenantRegistry)
from lightgbm_tpu.serve import client as serve_client
from lightgbm_tpu.obs import registry as obs
from lightgbm_tpu.utils import faults

pytestmark = pytest.mark.fleet

_PARAMS = ("objective=binary num_leaves=15 max_bin=63 "
           "min_data_in_leaf=5 verbose=-1")


def _train_model_str(params=_PARAMS, n=300, f=6, iters=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
    capi.LGBM_DatasetSetField(ds, "label", y)
    bst = capi.LGBM_BoosterCreate(ds, params)
    for _ in range(iters):
        if capi.LGBM_BoosterUpdateOneIter(bst):
            break
    return capi.LGBM_BoosterSaveModelToString(bst)


@pytest.fixture(scope="module")
def binary_model():
    return _train_model_str(seed=0)


@pytest.fixture(scope="module")
def binary_model_v2():
    # same geometry knobs, different data: a distinguishable version
    return _train_model_str(seed=9)


@pytest.fixture(scope="module")
def multiclass_model():
    params = ("objective=multiclass num_class=3 num_leaves=15 "
              "max_bin=63 min_data_in_leaf=5 verbose=-1")
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 6))
    y = (np.abs(X[:, 0]) + X[:, 1] > 0.8).astype(np.float32) \
        + (X[:, 2] > 0.5)
    ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
    capi.LGBM_DatasetSetField(ds, "label", y.astype(np.float32))
    bst = capi.LGBM_BoosterCreate(ds, params)
    for _ in range(6):
        capi.LGBM_BoosterUpdateOneIter(bst)
    return capi.LGBM_BoosterSaveModelToString(bst)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


@pytest.fixture
def make_daemon():
    """Daemon factory that guarantees stop() even on assertion
    failure (the listener and dispatcher are process-global
    resources)."""
    made = []

    def _make(**kw):
        d = ScoringDaemon(port=0, **kw).start()
        made.append(d)
        return d

    yield _make
    for d in made:
        d.stop()


def _direct(model_str, X):
    """What an uncoalesced caller would get: the exact serving call
    on a freshly loaded handle."""
    h = capi.LGBM_BoosterLoadModelFromString(model_str)
    return np.asarray(capi.LGBM_BoosterPredictForMat(
        h, X, predict_type=capi.C_API_PREDICT_NORMAL))


# -- tenant registry units ---------------------------------------------------

def test_tenant_name_validation():
    assert TenantRegistry.validate_name("tenant_07") == "tenant_07"
    for bad in ("", "UPPER", "has-dash", "a" * 65, "sp ace"):
        with pytest.raises(ValueError, match="tenant name"):
            TenantRegistry.validate_name(bad)


def test_registry_swap_and_drop(binary_model):
    reg = TenantRegistry(warm_rows=4)
    assert reg.register("t", binary_model) == 1
    h1, v1 = reg.get("t")
    assert v1 == 1
    assert reg.register("t", binary_model) == 2   # swap bumps version
    _, v2 = reg.get("t")
    assert v2 == 2
    assert reg.stats()["tenants"]["t"]["version"] == 2
    assert reg.drop("t") and not reg.drop("t")
    with pytest.raises(KeyError):
        reg.get("t")


# -- coalescer bit-parity ----------------------------------------------------

def test_coalesced_parity_concurrent_odd_batches(
        make_daemon, binary_model, multiclass_model):
    """Many concurrent small requests (1-row, odd sizes, two tenants
    with DIFFERENT model shapes) coalesced into shared device batches
    return exactly the bytes each request would have gotten alone."""
    d = make_daemon(coalesce_us=3000, warm_rows=16)
    d.register_tenant("bin", binary_model)
    d.register_tenant("multi", multiclass_model)
    rng = np.random.default_rng(11)
    Xt = rng.normal(size=(120, 6))
    Xt[::7, 3] = np.nan                       # missing values ride too
    want = {"bin": _direct(binary_model, Xt),
            "multi": _direct(multiclass_model, Xt)}
    # odd slice ladder incl. 1-row requests
    sizes = (1, 3, 5, 17, 94)
    jobs = []
    for tenant in ("bin", "multi"):
        r0 = 0
        for i in range(999):
            b = sizes[i % len(sizes)]
            if r0 >= len(Xt):
                break
            jobs.append((tenant, r0, min(b, len(Xt) - r0)))
            r0 += b
    out = {}
    errs = []

    def worker(tenant, r0, b):
        try:
            preds, version = d.predict(tenant, Xt[r0:r0 + b])
            out[(tenant, r0)] = (np.asarray(preds), version)
        except Exception as e:                # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=j) for j in jobs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs
    assert len(out) == len(jobs)
    for (tenant, r0), (preds, version) in out.items():
        b = preds.shape[0]
        np.testing.assert_array_equal(preds, want[tenant][r0:r0 + b])
        assert version == 1
    # the point of the exercise: at least one real multi-request batch
    snap = obs.histogram("fleet/coalesced_batch_rows").snapshot()
    assert snap["count"] > 0


def test_http_roundtrip_bit_parity(make_daemon, binary_model):
    """Predictions over the JSON wire equal in-process predict to the
    last bit (float64 shortest-round-trip repr)."""
    d = make_daemon(coalesce_us=0)
    client = FleetClient(d.url)
    assert client.register("wire", binary_model, warm_rows=8) == 1
    rng = np.random.default_rng(5)
    for rows in (1, 7, 33):
        Xb = rng.normal(size=(rows, 6))
        got, version = client.predict_versioned("wire", Xb)
        assert version == 1
        np.testing.assert_array_equal(got, _direct(binary_model, Xb))
    assert "wire" in client.health()["tenants"]
    assert client.tenants()["tenants"]["tenants"]["wire"][
        "version"] == 1


# -- cross-tenant compiled-program reuse -------------------------------------

def test_same_geometry_tenants_share_compiled_program(
        make_daemon, binary_model):
    """N same-geometry tenants, one compiled program: every
    registration after the first warms against a predict-registry HIT
    (no re-trace), which is the --fleet acceptance bar of hit rate
    >= 3/4 at K=4."""
    if not predict_cache.enabled():
        pytest.skip("predict registry disabled in this environment")
    d = make_daemon(coalesce_us=0, warm_rows=16)
    before = predict_cache.stats()
    for i in range(4):
        d.register_tenant(f"tenant_{i:02d}", binary_model)
    after = predict_cache.stats()
    lookups = (after["hits"] + after["misses"]
               - before["hits"] - before["misses"])
    hits = after["hits"] - before["hits"]
    misses = after["misses"] - before["misses"]
    assert lookups >= 4, "warmup never reached the predict registry"
    # only the FIRST tenant may compile; 3 of 4 must reuse
    assert hits / lookups >= 0.75, (hits, misses)
    # steady serving is memoized: scoring all tenants adds no misses
    rng = np.random.default_rng(2)
    Xb = rng.normal(size=(8, 6))
    mid = predict_cache.stats()
    for i in range(4):
        preds, _ = d.predict(f"tenant_{i:02d}", Xb)
        np.testing.assert_array_equal(preds, _direct(binary_model, Xb))
    assert predict_cache.stats()["misses"] == mid["misses"]


# -- versioned warm swap under load ------------------------------------------

def test_swap_under_load_every_response_is_some_clean_version(
        make_daemon, binary_model, binary_model_v2):
    """Hammer one tenant while models swap underneath: every response
    must bit-equal a clean predict at the version it claims — never a
    torn read, and in-flight requests finish on the old model."""
    d = make_daemon(coalesce_us=0, warm_rows=8)
    d.register_tenant("swap", binary_model)
    rng = np.random.default_rng(7)
    Xb = rng.normal(size=(6, 6))
    want = {1: _direct(binary_model, Xb),
            2: _direct(binary_model_v2, Xb),
            3: _direct(binary_model, Xb)}
    stop = threading.Event()
    got, errs = [], []

    def hammer():
        while not stop.is_set():
            try:
                preds, version = d.predict("swap", Xb)
                got.append((version, np.asarray(preds)))
            except Exception as e:            # noqa: BLE001
                errs.append(e)
                return

    def wait_seen(version, deadline_s=30.0):
        # publish timing is load-dependent: wait until the hammer
        # actually OBSERVES the version instead of sleeping blind
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if errs or any(v == version for v, _ in list(got)):
                return
            time.sleep(0.002)
        raise AssertionError(f"version {version} never served")

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    assert d.register_tenant("swap", binary_model_v2) == 2
    wait_seen(2)
    assert d.register_tenant("swap", binary_model) == 3
    wait_seen(3)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errs and got
    for version, preds in got:
        np.testing.assert_array_equal(preds, want[version])
    assert obs.counter("fleet/model_swaps").value >= 2


# -- SLO admission control: the shed drill -----------------------------------

def test_shed_drill_pre_breach_and_neighbor_isolation(
        make_daemon, binary_model):
    """Inject a latency fault into ONE tenant's predict path: its p99
    budget burns, admission sheds it with 429 BEFORE the budget is
    exhausted (pre-breach, by the snapshotted remaining budget), the
    neighbor tenant keeps serving, and the probe trickle keeps the
    shed tenant's recovery possible."""
    d = make_daemon(coalesce_us=0, slo_p99_ms=50.0, shed_budget=0.5,
                    slo_eval_gap_s=0.0, slo_min_events=100,
                    shed_probe_every=16)
    d.register_tenant("alpha", binary_model)
    d.register_tenant("beta", binary_model)
    x1 = np.zeros((1, 6))
    shed0 = obs.counter("fleet/shed_total").value
    # prefill: a healthy latency history for both tenants (also takes
    # the engine past its min_events warming floor)
    for _ in range(400):
        d.predict("alpha", x1)
        d.predict("beta", x1)
    assert d.shed_check("alpha") is None      # healthy: admitted
    # now alpha's every predict stalls 80ms — past the 50ms objective
    faults.configure("fleet.predict.alpha@1+:sleep80")
    shed_at = None
    for i in range(12):
        try:
            d.predict("alpha", x1)
        except ShedError as e:
            shed_at = i
            assert e.tenant == "alpha" and e.retry_after_s > 0
            break
    assert shed_at is not None, "admission never shed the slow tenant"
    report = d.slo_report()
    state = report["shedding"]["alpha"]
    # the drill's proof that admission acted PRE-breach: budget
    # remained when shedding began, and it was not exhausted
    assert state["budget_remaining_at_shed"] > 0
    assert state["exhausted_at_shed"] is False
    # while shed, requests are refused fast (modulo the probe trickle)
    sheds = 0
    for _ in range(20):
        try:
            d.predict("alpha", x1)
        except ShedError:
            sheds += 1
    assert sheds >= 15
    assert obs.counter("fleet/shed_total").value - shed0 >= 15
    assert obs.counter("fleet/shed/alpha").value >= 15
    # neighbor isolation: beta's budget is untouched, it still serves
    preds, _ = d.predict("beta", x1)
    np.testing.assert_array_equal(preds, _direct(binary_model, x1))
    assert "beta" not in d.slo_report()["shedding"]
    # the wire surface agrees: HTTP 429 + Retry-After -> ShedError
    client = FleetClient(d.url)
    with pytest.raises(ShedError) as ei:
        for _ in range(3):                    # skip a probe admit
            client.predict("alpha", x1)
    assert ei.value.retry_after_s > 0


# -- backpressure ------------------------------------------------------------

def test_bounded_queue_refuses_then_drains(binary_model):
    reg = TenantRegistry(warm_rows=4)
    reg.register("t", binary_model)
    rejects0 = obs.counter("fleet/queue_rejects").value
    co = Coalescer(reg, max_wait_us=0, max_queue=2)
    # dispatcher not started: submissions pile into the bounded buffer
    f1 = co.submit("t", np.zeros((1, 6)))
    f2 = co.submit("t", np.zeros((1, 6)))
    with pytest.raises(QueueFull) as ei:
        co.submit("t", np.zeros((1, 6)))
    assert ei.value.retry_after_s > 0
    assert obs.counter("fleet/queue_rejects").value == rejects0 + 1
    # starting the dispatcher drains what was queued
    co.start()
    preds, version = f1.result(timeout=30)
    assert version == 1 and preds.shape[0] == 1
    f2.result(timeout=30)
    co.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        co.submit("t", np.zeros((1, 6)))


# -- daemon lifecycle + client classification --------------------------------

def test_daemon_lifecycle_and_from_config(binary_model):
    d = ScoringDaemon.from_config(
        {"tpu_fleet_coalesce_us": 123, "tpu_fleet_slo_p99_ms": 10.0,
         "tpu_fleet_shed_budget": 0.4})
    assert d.coalescer._wait_s == pytest.approx(123 / 1e6)
    assert d._slo_p99_ms == 10.0 and d._shed_budget == 0.4
    d.start()
    assert d.start() is d                     # idempotent start
    port = d.http_port
    assert port > 0                           # ephemeral bind resolved
    assert d.url.endswith(f":{port}")
    client = FleetClient(d.url)
    assert client.health()["ok"] is True
    # unknown tenant is a caller bug: 404, fail fast (never retried)
    with pytest.raises(urllib.error.HTTPError) as ei:
        client.predict("nobody", np.zeros((1, 6)))
    assert ei.value.code == 404
    d.stop()
    d.stop()                                  # idempotent stop
    with pytest.raises(RuntimeError, match="stopped"):
        d.predict("nobody", np.zeros((1, 6)))


def test_client_transient_classification():
    """429 is admission (never retried); 503 is backpressure
    (retried); 404 is a caller bug (fail fast); socket-level failures
    are transient."""
    assert serve_client._classify(ShedError("t", 0.5)) is False
    assert serve_client._classify(
        urllib.error.HTTPError("u", 503, "busy", None, None)) is True
    assert serve_client._classify(
        urllib.error.HTTPError("u", 502, "bad gw", None, None)) is True
    assert serve_client._classify(
        urllib.error.HTTPError("u", 404, "nope", None, None)) is False
    assert serve_client._classify(
        urllib.error.URLError(ConnectionRefusedError(
            "Connection refused"))) is True
    assert serve_client._classify(
        ConnectionResetError("Connection reset by peer")) is True
    assert serve_client._classify(
        RuntimeError("Remote end closed connection without "
                     "response")) is True
    assert serve_client._classify(ValueError("bad rows")) is False
