"""Golden-file interop with the REAL reference engine.

tests/data/golden_model.txt + golden_{X,y,pred,raw}.bin were produced
by the reference C++ engine itself (built from /root/reference, driven
through its C API; generator preserved below in the docstring of
``_golden_inputs``). These tests prove byte-level model-format interop:
parse -> predict -> re-serialize round-trips a reference-produced model
and training continues from it (SURVEY §7 step-5 commitment).
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

DATA = os.path.join(os.path.dirname(__file__), "data")


def _golden_inputs():
    """The generator replicated the C++ LCG exactly:
    s = s*6364136223846793005 + 1442695040888963407; u = (s>>11)/2^53;
    z_j = (2u-1)+(2u'-1); logit = 1.5 z0 + z1 - 0.5 z2 + 0.3 noise;
    X[i, 3] = NaN every 17th row.
    Inputs are stored as raw float64/float32 dumps, so no replication is
    actually needed — just read them back.
    """
    n, f = 500, 8
    X = np.fromfile(os.path.join(DATA, "golden_X.bin"),
                    np.float64).reshape(n, f)
    y = np.fromfile(os.path.join(DATA, "golden_y.bin"), np.float32)
    return X, y


class TestGoldenModel:
    def test_load_and_predict_matches_reference(self):
        X, _ = _golden_inputs()
        ref_pred = np.fromfile(os.path.join(DATA, "golden_pred.bin"),
                               np.float64)
        ref_raw = np.fromfile(os.path.join(DATA, "golden_raw.bin"),
                              np.float64)
        bst = lgb.Booster(model_file=os.path.join(DATA,
                                                  "golden_model.txt"))
        raw = bst.predict(X, raw_score=True)
        pred = bst.predict(X)
        # the reference's own codegen test uses a 1e-5 bar
        np.testing.assert_allclose(raw, ref_raw, atol=1e-5)
        np.testing.assert_allclose(pred, ref_pred, atol=1e-5)

    def test_reserialize_roundtrip(self):
        X, _ = _golden_inputs()
        path = os.path.join(DATA, "golden_model.txt")
        bst = lgb.Booster(model_file=path)
        re_str = bst.model_to_string()
        again = lgb.Booster(model_str=re_str)
        np.testing.assert_allclose(again.predict(X), bst.predict(X),
                                   atol=1e-7)
        # header fields preserved
        orig = open(path).read()
        for key in ("num_class=1", "max_feature_idx=7",
                    "objective=binary sigmoid:1"):
            assert key in re_str and key in orig

    def test_continue_training_from_reference_model(self):
        X, y = _golden_inputs()
        ref_raw = np.fromfile(os.path.join(DATA, "golden_raw.bin"),
                              np.float64)
        evals = {}
        gbm = lgb.train(
            {"objective": "binary", "metric": "binary_logloss",
             "num_leaves": 15, "min_data_in_leaf": 5, "verbose": -1},
            lgb.Dataset(X, y, free_raw_data=False), num_boost_round=10,
            valid_sets=lgb.Dataset(X, y, reference=None,
                                   free_raw_data=False),
            init_model=os.path.join(DATA, "golden_model.txt"),
            verbose_eval=False, evals_result=evals)
        # continued predictions = reference raw + new trees' raw
        total = ref_raw + gbm.predict(X, raw_score=True)
        ll = evals["valid_0"]["binary_logloss"]
        p = 1.0 / (1.0 + np.exp(-total))
        eps = 1e-15
        manual_ll = -np.mean(y * np.log(p + eps)
                             + (1 - y) * np.log(1 - p + eps))
        assert ll[-1] == pytest.approx(manual_ll, abs=1e-3)
        assert ll[-1] < ll[0]

    def test_feature_importance_from_loaded(self):
        bst = lgb.Booster(model_file=os.path.join(DATA,
                                                  "golden_model.txt"))
        imp = bst.feature_importance("split")
        assert imp.sum() > 0
        assert len(imp) == 8
