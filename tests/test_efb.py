"""Exclusive Feature Bundling tests.

Reference: src/io/dataset.cpp:66-210 FindGroups/FastFeatureBundling;
the VERDICT acceptance bar: a sparse synthetic shrinks the HBM bins
tensor >= 4x with unchanged quality.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.efb import bundle_bins, find_bundles


def _sparse_problem(n=2000, blocks=40, seed=0):
    """One-hot-ish exclusive block + one dense feature."""
    rng = np.random.default_rng(seed)
    group = rng.integers(0, blocks, n)
    X = np.zeros((n, blocks + 1))
    X[np.arange(n), group] = rng.uniform(1, 5, n)
    X[:, blocks] = rng.normal(size=n)
    y = ((group % 7 < 3).astype(float) * 2 - 1
         + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _tiefree_sparse_problem(n=2000, blocks=24, seed=5):
    """Exclusive one-hot block (bundles) + two dense continuous
    features with well-separated smooth signal. Unlike
    _sparse_problem's modular-arithmetic label (which produces EXACT
    gain ties whose winner depends on summation order), every
    candidate split's gain here is a distinct continuous value, so the
    data-parallel psum's f32 reassociation cannot flip an election —
    near-exact serial/parallel parity is expected."""
    rng = np.random.default_rng(seed)
    group = rng.integers(0, blocks, n)
    X = np.zeros((n, blocks + 2))
    X[np.arange(n), group] = rng.uniform(1, 5, n)
    X[:, blocks] = rng.normal(size=n)
    X[:, blocks + 1] = rng.normal(size=n)
    w = rng.uniform(0.5, 2.0, blocks) * np.where(
        rng.random(blocks) < 0.5, -1, 1)
    logit = (w[group] * 0.8 + 1.7 * X[:, blocks]
             - 0.9 * X[:, blocks + 1] + 0.25 * rng.normal(size=n))
    y = (logit > 0).astype(np.float64)
    return X, y


class TestBundling:
    def test_find_bundles_merges_exclusive(self):
        rng = np.random.default_rng(1)
        n = 1000
        bins = np.zeros((n, 4), np.uint8)
        active = rng.integers(0, 3, n)
        for j in range(3):                 # 3 mutually exclusive
            bins[active == j, j] = rng.integers(1, 10, (active == j).sum())
        bins[:, 3] = rng.integers(0, 10, n)   # dense: conflicts with all
        db = np.zeros(4, np.int32)
        nb = np.full(4, 10, np.int32)
        bundles = find_bundles(bins, db, nb, max_conflict_rate=0.0)
        sizes = sorted(len(b) for b in bundles)
        assert sizes == [1, 3]

    def test_bundle_roundtrip_encoding(self):
        rng = np.random.default_rng(2)
        n = 500
        bins = np.zeros((n, 3), np.uint8)
        active = rng.integers(0, 3, n)
        for j in range(3):
            bins[active == j, j] = rng.integers(1, 8, (active == j).sum())
        db = np.zeros(3, np.int32)
        nb = np.full(3, 8, np.int32)
        bundles = [[0, 1, 2]]
        out, mb, mo, width = bundle_bins(bins, bundles, db, nb)
        assert out.shape == (n, 1)
        assert width == 1 + 3 * 8
        # decode: in-range -> col - offset else default
        for j in range(3):
            col = out[:, 0].astype(np.int64)
            dec = np.where((col >= mo[j]) & (col < mo[j] + nb[j]),
                           col - mo[j], db[j])
            np.testing.assert_array_equal(dec, bins[:, j])

    def test_training_with_efb_matches_unbundled(self):
        X, y = _sparse_problem()
        params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
                  "min_data_in_leaf": 5}
        b_on = lgb.train(dict(params, enable_bundle=True),
                         lgb.Dataset(X, y,
                                     params={"enable_bundle": True}),
                         15, verbose_eval=False,
                         keep_training_booster=True)
        b_off = lgb.train(dict(params, enable_bundle=False),
                          lgb.Dataset(X, y,
                                      params={"enable_bundle": False}),
                          15, verbose_eval=False)
        td = b_on._gbdt.train_data
        assert td.bundles is not None
        # HBM tensor shrank >= 4x (VERDICT bar)
        assert td.num_features / len(td.bundles) >= 4
        assert b_on._gbdt._bins_dev.shape[0] == len(td.bundles)
        acc_on = ((b_on.predict(X) > 0.5) == y).mean()
        acc_off = ((b_off.predict(X) > 0.5) == y).mean()
        assert acc_on >= acc_off - 0.005
        assert acc_on > 0.97
        # serialized models predict identically after reload
        loaded = lgb.Booster(model_str=b_on.model_to_string())
        np.testing.assert_allclose(loaded.predict(X), b_on.predict(X),
                                   atol=1e-5)

    def test_valid_sets_share_bundles(self):
        X, y = _sparse_problem()
        Xv, yv = _sparse_problem(seed=9)
        ev = {}
        train = lgb.Dataset(X, y, params={"enable_bundle": True})
        lgb.train({"objective": "binary", "metric": "auc",
                   "verbose": -1, "num_leaves": 15,
                   "min_data_in_leaf": 5, "enable_bundle": True},
                  train, 10, valid_sets=lgb.Dataset(Xv, yv,
                                                    reference=train),
                  verbose_eval=False, evals_result=ev)
        assert ev["valid_0"]["auc"][-1] > 0.97


class TestBundleComposition:
    """EFB composing with the quantized histogram path and the
    row-sharded distributed learners (the reference's GPU path bundles
    dense groups and offloads, gpu_tree_learner.cpp:325-357)."""

    def _train(self, X, y, **extra):
        params = {"objective": "binary", "verbose": -1,
                  "num_leaves": 15, "min_data_in_leaf": 5,
                  "enable_bundle": True, **extra}
        return lgb.train(params,
                         lgb.Dataset(X, y, params=params), 12,
                         verbose_eval=False,
                         keep_training_booster=True)

    def test_quantized_hist_with_bundles(self):
        """Bundling composes with int8 quantized histograms and costs
        no quality vs the unbundled quantized run. (Bit-exact parity is
        not the bar: the default-bin complement `total - rest` sums
        dequantized floats in a different order than the direct member
        histogram, so near-tie splits may flip — same as the
        reference's own EFB.)"""
        X, y = _sparse_problem()
        b = self._train(X, y, tpu_quantized_hist=True)
        g = b._gbdt
        assert g._use_bundles
        assert g._grower_cfg.precision == "int8"
        b_ref = lgb.train(
            {"objective": "binary", "verbose": -1, "num_leaves": 15,
             "min_data_in_leaf": 5, "enable_bundle": False,
             "tpu_quantized_hist": True},
            lgb.Dataset(X, y, params={"enable_bundle": False}), 12,
            verbose_eval=False)
        acc_b = ((b.predict(X) > 0.5) == y).mean()
        acc_u = ((b_ref.predict(X) > 0.5) == y).mean()
        assert acc_b >= acc_u - 0.005

    @pytest.mark.skipif(
        len(__import__("lightgbm_tpu.utils.device",
                       fromlist=["get_devices"]).get_devices()) < 2,
        reason="needs mesh")
    def test_data_parallel_with_bundles_matches_serial(self):
        """Quality parity, not bitwise: the 8-shard psum reassociates
        the expanded bundle histograms' f32 sums, and this sparse
        problem has exact gain TIES (one observed flip: same feature,
        different bin, equal gain) whose winner depends on summation
        order — one early flip then decorrelates every later tree.
        The reference's own parallel learners have the same property
        (its feature-histogram sums reassociate across machines)."""
        X, y = _sparse_problem()
        b_ser = self._train(X, y)
        b_par = self._train(X, y, tree_learner="data")
        g = b_par._gbdt
        assert g._use_bundles and g._learner_mode == "data"
        # the first splits agree (the tie sits deeper in the tree)
        gs, gp = b_ser._gbdt, b_par._gbdt
        gs._ensure_host_trees(); gp._ensure_host_trees()
        assert (gs.models[0].split_feature[0]
                == gp.models[0].split_feature[0])
        acc_s = ((b_ser.predict(X) > 0.5) == y).mean()
        acc_p = ((b_par.predict(X) > 0.5) == y).mean()
        assert acc_p >= acc_s - 0.01 and acc_p > 0.95

    @pytest.mark.skipif(
        len(__import__("lightgbm_tpu.utils.device",
                       fromlist=["get_devices"]).get_devices()) < 2,
        reason="needs mesh")
    def test_data_parallel_efb_split_sequences_match_serial(self):
        """Beyond the first-split check: on a TIE-FREE problem the
        full per-tree split_feature sequences of the data-parallel
        bundled learner match the serial bundled learner exactly —
        the 8-shard psum over expanded bundle histograms reassociates
        f32 sums, but with every gain a distinct continuous value that
        reassociation cannot change any election. (The looser
        test_data_parallel_with_bundles_matches_serial keeps covering
        the tie-carrying problem, where only quality parity holds.)"""
        X, y = _tiefree_sparse_problem()
        b_ser = self._train(X, y)
        b_par = self._train(X, y, tree_learner="data")
        gs, gp = b_ser._gbdt, b_par._gbdt
        assert gp._use_bundles and gp._learner_mode == "data"
        gs._ensure_host_trees(); gp._ensure_host_trees()
        assert len(gs.models) == len(gp.models) > 0
        for t, (ts, tp) in enumerate(zip(gs.models, gp.models)):
            assert list(ts.split_feature) == list(tp.split_feature), \
                f"tree {t} split sequence diverged"

    @pytest.mark.skipif(
        len(__import__("lightgbm_tpu.utils.device",
                       fromlist=["get_devices"]).get_devices()) < 2,
        reason="needs mesh")
    def test_voting_and_quant_data_with_bundles(self):
        X, y = _sparse_problem()
        bv = self._train(X, y, tree_learner="voting", top_k=5)
        assert bv._gbdt._use_bundles
        # 250 rows/shard with a 5-feature vote over a sparse problem is
        # deep in PV-Tree's approximation regime; the election outcome
        # sits near a tie and wobbles with backend numerics
        assert ((bv.predict(X) > 0.5) == y).mean() > 0.93
        bq = self._train(X, y, tree_learner="data",
                         tpu_quantized_hist=True)
        assert bq._gbdt._use_bundles
        assert bq._gbdt._grower_cfg.precision == "int8"
        # same marginal regime as the voting case above (tiny shards,
        # stochastic int8 rounding with global pmax scales)
        assert ((bq.predict(X) > 0.5) == y).mean() > 0.93

    @pytest.mark.skipif(
        len(__import__("lightgbm_tpu.utils.device",
                       fromlist=["get_devices"]).get_devices()) < 2,
        reason="needs mesh")
    def test_feature_parallel_with_bundles(self):
        """EFB composes with the feature-parallel learner: devices
        slice BUNDLE columns, expand their slice to member histograms
        (zeros elsewhere — zero histograms cannot win the election),
        and the global best rides the usual all_gather+argmax. Same
        data, same determinism: must match the serial bundled model."""
        X, y = _sparse_problem()
        b_ser = self._train(X, y)
        b_fp = self._train(X, y, tree_learner="feature")
        g = b_fp._gbdt
        assert g._use_bundles and g._learner_mode == "feature"
        # first split agrees; full quality parity (exact gain ties can
        # flip with the local/global evaluation order, like the data-
        # parallel case above)
        gs, gf = b_ser._gbdt, b_fp._gbdt
        gs._ensure_host_trees(); gf._ensure_host_trees()
        assert (gs.models[0].split_feature[0]
                == gf.models[0].split_feature[0])
        acc_s = ((b_ser.predict(X) > 0.5) == y).mean()
        acc_f = ((b_fp.predict(X) > 0.5) == y).mean()
        assert acc_f >= acc_s - 0.01 and acc_f > 0.95
