"""Cluster-scope observability (obs/identity.py, obs/clusterobs.py,
obs/incident.py — Design.md §6e): rank-aware telemetry, the per-rank
metrics digest -> rank-0 ``cluster/*`` rollup pipeline, distributed
incident bundles, and the cross-rank merged timeline.

Unit layer (pytest -m obs): identity/path policy, digest wire
round-trip, rollup merge correctness — summed counters and merged
histograms whose quantiles track numpy over the UNION of per-rank
samples — the KV key discipline over a fake client, incident
sweep/build/resweep, the trace_summary clock-alignment merge, and the
drill-artifact section validators.

Process layer (pytest -m multihost): 2 REAL jax.distributed processes
export rank-suffixed artifacts with no path collision, rank 0's export
carries the ``cluster/*`` rollup whose merged iteration histogram
counts every rank's iterations, the per-rank trace files merge onto
one timeline, and a SIGKILL drill leaves ONE incident bundle naming
the dead rank with both ranks' flight dumps embedded.
"""
import json
import os
import sys

import numpy as np
import pytest

from lightgbm_tpu.obs import clusterobs, identity, incident
from lightgbm_tpu.obs.registry import MetricsRegistry
from lightgbm_tpu.utils import log

pytestmark = pytest.mark.obs

REPO = os.path.join(os.path.dirname(__file__), os.pardir)
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _restore_identity():
    """Every test leaves the process single-rank again (identity is a
    process-global; a leaked world>1 would rank-suffix every later
    test's artifact paths)."""
    yield
    identity.set_topology(0, 1)
    log.set_rank_tag("")
    clusterobs.reset()


# ---------------------------------------------------------------------------
# identity + path policy
# ---------------------------------------------------------------------------

def test_rank_suffixed_single_process_is_byte_identical():
    assert identity.rank_suffixed("metrics.prom") == "metrics.prom"
    assert identity.rank_suffixed("") == ""
    assert identity.log_tag() == ""
    assert not identity.is_multiprocess()


def test_rank_suffixed_inserts_before_extension():
    identity.set_topology(1, 2)
    assert identity.rank_suffixed("metrics.prom") == "metrics.r1.prom"
    assert identity.rank_suffixed("/a/b/trace.json") == \
        "/a/b/trace.r1.json"
    assert identity.rank_suffixed("report") == "report.r1"
    # explicit rank overrides the ambient one (the exporter suffixes
    # its base once, before splitting into .prom/.jsonl)
    assert identity.rank_suffixed("m.jsonl", rank_n=0) == "m.r0.jsonl"
    assert identity.log_tag() == "r1"


def test_topology_and_incarnation_stamp_every_surface():
    identity.set_topology(1, 4)
    ident = identity.identity()
    assert ident["machine_rank"] == 1 and ident["world"] == 4
    before = identity.incarnation()
    new = identity.bump_incarnation("unit re-shard")
    assert new == before + 1
    assert identity.identity()["incarnation"] == new
    # the digest built AFTER the bump carries the new incarnation
    d = clusterobs.build_digest(MetricsRegistry())
    assert d["identity"] == identity.identity()


def test_log_prefix_carries_rank_tag(capsys):
    prev = log.get_level()
    log.set_level(log.LogLevel.INFO)
    try:
        log.set_rank_tag("r1")
        log.info("cluster hello")
        err = capsys.readouterr().err
        assert "[r1]" in err and "cluster hello" in err
        log.set_rank_tag("")
        log.info("solo hello")
        err = capsys.readouterr().err
        assert "[r1]" not in err and "solo hello" in err
    finally:
        log.set_level(prev)


def test_trace_events_stamp_rank_only_multiprocess():
    from lightgbm_tpu.obs import trace as obs_trace
    ev = {"ph": "i", "name": "x", "ts": 1.0, "args": {}}
    obs_trace._stamp_rank(ev)
    assert "rank" not in (ev.get("args") or {})      # world == 1
    identity.set_topology(1, 2)
    obs_trace._stamp_rank(ev)
    assert ev["args"]["rank"] == 1


# ---------------------------------------------------------------------------
# digest wire + rollup merge
# ---------------------------------------------------------------------------

_BUCKETS = tuple(round(0.05 * i, 2) for i in range(1, 41))  # 0.05..2.0


def _digest_for_rank(rank_n, samples, stall, extra=10.0):
    reg = MetricsRegistry()
    reg.counter("comm/psum_stall_s").add(stall)
    reg.counter("train/trees_total").add(extra)
    reg.gauge("ckpt/queue_depth").set(rank_n)
    h = reg.histogram("train/iteration_s", _BUCKETS)
    for s in samples:
        h.observe(float(s))
    d = clusterobs.build_digest(reg)
    d["identity"] = {"machine_rank": rank_n, "world": 2,
                     "incarnation": 0}
    return d


def test_digest_build_and_wire_roundtrip():
    d = _digest_for_rank(0, [0.1, 0.2], stall=1.5)
    assert d["schema"] == clusterobs.DIGEST_SCHEMA
    assert d["version"] == clusterobs.DIGEST_VERSION
    assert d["counters"]["comm/psum_stall_s"] == 1.5
    assert d["hists"]["train/iteration_s"]["c"][1] == 1   # 0.1 bucket
    back = clusterobs.digest_from_wire(clusterobs.digest_to_wire(d))
    assert back == d
    # malformed wire never raises, it reads as "no digest"
    assert clusterobs.digest_from_wire("{truncated") is None
    assert clusterobs.digest_from_wire(json.dumps({"schema": "x"})) \
        is None
    assert clusterobs.digest_from_wire(json.dumps(
        {"schema": clusterobs.DIGEST_SCHEMA, "version": 99})) is None


def test_merge_sums_counters_and_quantiles_track_union():
    """The tentpole invariant: ``cluster/<h>`` quantiles interpolate
    over the TRUE union distribution (elementwise bucket-count sums),
    not an average of per-rank quantiles."""
    r = np.random.default_rng(7)
    s0 = r.uniform(0.05, 0.9, 400)
    s1 = r.uniform(0.6, 1.8, 600)          # rank 1 is the straggler
    digests = {0: _digest_for_rank(0, s0, stall=1.5),
               1: _digest_for_rank(1, s1, stall=4.0)}
    agg = clusterobs.merge_digests(digests, world_n=2)
    snap = agg.snapshot()
    assert snap["gauges"]["cluster/world"] == 2
    assert snap["gauges"]["cluster/ranks_reporting"] == 2
    assert snap["counters"]["cluster/comm/psum_stall_s"] == 5.5
    assert snap["counters"]["cluster/train/trees_total"] == 20.0
    h = agg.histogram("cluster/train/iteration_s", _BUCKETS)
    union = np.concatenate([s0, s1])
    assert h.snapshot()["count"] == len(union)
    assert h.snapshot()["sum"] == pytest.approx(union.sum(), rel=1e-6)
    for q in (0.5, 0.9, 0.99):
        est = h.percentile(q)
        true = float(np.quantile(union, q))
        # within one 0.05 bucket of numpy over the union
        assert abs(est - true) <= 0.051, (q, est, true)
    # straggler attribution names rank 1 on both families
    assert snap["gauges"]["cluster/psum_stall_max_rank"] == 1
    assert snap["gauges"]["cluster/slowest_iter_rank"] == 1
    assert snap["gauges"]["cluster/psum_stall_s/r0"] == 1.5
    assert snap["gauges"]["cluster/psum_stall_s/r1"] == 4.0
    m0 = snap["gauges"]["cluster/iter_wall_mean_s/r0"]
    m1 = snap["gauges"]["cluster/iter_wall_mean_s/r1"]
    assert m0 == pytest.approx(s0.mean(), rel=1e-6)
    assert m1 == pytest.approx(s1.mean(), rel=1e-6)


def test_merge_skips_mismatched_bucket_bounds():
    d0 = _digest_for_rank(0, [0.1, 0.2, 0.3], stall=0.0)
    d1 = _digest_for_rank(1, [0.4], stall=0.0)
    d1["hists"]["train/iteration_s"]["b"] = [1.0, 2.0]   # version skew
    d1["hists"]["train/iteration_s"]["c"] = [1, 0, 0]
    agg = clusterobs.merge_digests({0: d0, 1: d1}, world_n=2)
    h = agg.histogram("cluster/train/iteration_s", _BUCKETS)
    assert h.snapshot()["count"] == 3      # rank 1's skewed hist out
    assert clusterobs.missing_ranks({0: d0}, 3) == [1, 2]


class _FakeKV:
    """The coordination-service KV surface the digest publisher uses
    (jax coordination client: key_value_set/delete/dir_get)."""

    def __init__(self):
        self.kv = {}

    def key_value_set(self, k, v):
        self.kv[k] = v

    def key_value_delete(self, k):
        self.kv.pop(k, None)

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in sorted(self.kv.items())
                if k.startswith(prefix)]


def test_publish_read_kv_roundtrip_keeps_one_digest_per_rank():
    clusterobs.reset()
    client = _FakeKV()
    assert clusterobs.publish_digest(client, 0)
    assert clusterobs.publish_digest(client, 0)
    # seq-in-key discipline: the previous seq is deleted, one digest
    # per rank remains in the directory
    keys = [k for k in client.kv if k.startswith("lgbm_tpu/obs/0/")]
    assert keys == ["lgbm_tpu/obs/0/1"]
    # a second rank + one junk value (truncated write) alongside
    d1 = _digest_for_rank(1, [0.2], stall=0.5)
    client.key_value_set("lgbm_tpu/obs/1/7",
                         clusterobs.digest_to_wire(d1))
    client.key_value_set("lgbm_tpu/obs/2/0", "{torn")
    got = clusterobs.read_digests(client)
    assert sorted(got) == [0, 1]
    assert got[1] == d1
    assert got[0]["schema"] == clusterobs.DIGEST_SCHEMA


def test_enablement_knob_off_stops_publish():
    clusterobs.configure_from_config({"tpu_cluster_obs": 0})
    try:
        assert not clusterobs.enabled()
        assert clusterobs.publish_now() is False
        clusterobs.configure_from_config({"tpu_cluster_obs": -1})
        assert clusterobs.enabled()
        clusterobs.configure_from_config({"tpu_cluster_obs": 7})
        assert clusterobs.enabled()            # garbage reads as auto
    finally:
        clusterobs.configure_from_config({"tpu_cluster_obs": -1})


# ---------------------------------------------------------------------------
# incident bundles
# ---------------------------------------------------------------------------

def _flight_doc(rank_n, created, spans=()):
    return {"schema": "lightgbm-tpu/flight", "version": 1,
            "created_unix": created, "pid": 100 + rank_n,
            "identity": {"machine_rank": rank_n, "world": 2,
                         "incarnation": 0},
            "reason": "unit", "context": {}, "triggers": [],
            "spans": list(spans), "log_lines": [], "reqlog": [],
            "metrics": {}, "slo": None}


def _write_flight(directory, name, doc):
    with open(os.path.join(directory, name), "w") as fh:
        json.dump(doc, fh)


def test_incident_sweep_build_resweep(tmp_path):
    d = str(tmp_path)
    _write_flight(d, "flight_r0_p100_001_a.json", _flight_doc(0, 10.0))
    _write_flight(d, "flight_r0_p100_002_b.json", _flight_doc(0, 12.0))
    _write_flight(d, "flight_r1_p101_001_a.json", _flight_doc(1, 11.0))
    # a legacy pre-rank-tag dump attributes to rank 0 by filename rule
    legacy = _flight_doc(0, 9.0)
    legacy.pop("identity")
    _write_flight(d, "flight_p77_001_old.json", legacy)
    with open(os.path.join(d, "flight_r9_p9_001_bad.json"), "w") as fh:
        fh.write("{torn write")               # skipped, never raises
    swept = incident.sweep_flight_dumps(d)
    assert sorted(swept) == [0, 1]
    assert [b["bundle"]["created_unix"] for b in swept[0]] == \
        [9.0, 10.0, 12.0]                      # oldest first

    # the final KV digest snapshot rides into the bundle
    with clusterobs._lock:
        clusterobs._last_digests.update(
            {0: _digest_for_rank(0, [0.1], stall=0.0),
             1: _digest_for_rank(1, [0.2], stall=0.0)})
    path = incident.write_incident("peer_lost", d, dead_ranks=[1],
                                   context={"kill_iteration": 3})
    assert path and os.path.basename(path) == "incident_peer_lost.json"
    doc = incident.load_incident(path)
    assert doc["schema"] == incident.INCIDENT_SCHEMA
    assert doc["version"] == incident.INCIDENT_VERSION
    assert doc["dead_ranks"] == [1]
    assert doc["ranks_with_dumps"] == [0, 1]
    assert len(doc["ranks"]["0"]) == 3 and len(doc["ranks"]["1"]) == 1
    assert sorted(doc["digests"]) == ["0", "1"]

    # the victim's late dump flushes AFTER assembly: resweep picks it
    # up while keeping the (now unreachable) KV digests
    _write_flight(d, "flight_r1_p101_002_late.json",
                  _flight_doc(1, 13.0))
    doc2 = incident.resweep(path, d)
    assert len(doc2["ranks"]["1"]) == 2
    assert sorted(doc2["digests"]) == ["0", "1"]
    assert incident.load_incident(path)["ranks_with_dumps"] == [0, 1]

    # versioned-artifact discipline: a foreign schema is refused
    with open(os.path.join(d, "not_incident.json"), "w") as fh:
        json.dump({"schema": "x"}, fh)
    with pytest.raises(ValueError, match="not an incident"):
        incident.load_incident(os.path.join(d, "not_incident.json"))


# ---------------------------------------------------------------------------
# cross-rank merged timeline (tools/trace_summary.py --merge)
# ---------------------------------------------------------------------------

def _trace_doc(rank_n, started_unix):
    return {"traceEvents": [
        {"ph": "X", "name": "train/iter", "ts": 1000.0, "dur": 500.0,
         "pid": 1, "tid": 1, "args": {}},
        {"ph": "i", "name": "mark/it", "ts": 2000.0, "pid": 1,
         "tid": 1, "args": {"it": 1}},
    ], "otherData": {"started_unix": started_unix,
                     "identity": {"machine_rank": rank_n, "world": 2,
                                  "incarnation": 0}}}


def test_merge_aligns_clocks_and_stamps_ranks(tmp_path):
    import trace_summary as ts
    p0 = str(tmp_path / "trace.r0.json")
    p1 = str(tmp_path / "trace.r1.json")
    with open(p0, "w") as fh:
        json.dump(_trace_doc(0, 100.0), fh)
    with open(p1, "w") as fh:
        json.dump(_trace_doc(1, 102.0), fh)    # started 2s later
    loaded = []
    for p in (p0, p1):
        kind, doc = ts.load_artifact(p)
        loaded.append((p, kind, doc))
    merged = ts.merge_entries(loaded)
    assert merged["meta"]["t0_unix"] == 100.0
    ranks = {(ev.get("args") or {}).get("rank")
             for ev in merged["events"]}
    assert ranks == {0, 1}
    by_rank_instant = {
        (ev["args"]["rank"]): ev["ts"] for ev in merged["events"]
        if ev["ph"] == "i"}
    # rank 1's events shift by the 2s anchor gap onto rank 0's clock
    assert by_rank_instant[1] - by_rank_instant[0] == \
        pytest.approx(2e6)
    assert merged["events"] == sorted(
        merged["events"], key=lambda e: e["ts"])
    out = ts.render_merged(merged)
    assert "rank" in out and "train/iter" in out


def test_merge_expands_incident_bundles(tmp_path):
    import trace_summary as ts
    d = str(tmp_path)
    spans0 = [{"ph": "X", "name": "iter", "ts": 500.0, "dur": 100.0,
               "pid": 100, "tid": 1, "args": {}}]
    spans1 = [{"ph": "X", "name": "iter", "ts": 600.0, "dur": 150.0,
               "pid": 101, "tid": 1, "args": {}}]
    _write_flight(d, "flight_r0_p100_001_a.json",
                  _flight_doc(0, 50.0, spans0))
    _write_flight(d, "flight_r1_p101_001_a.json",
                  _flight_doc(1, 50.1, spans1))
    path = incident.write_incident("drill", d, dead_ranks=[1])
    kind, doc = ts.load_artifact(path)
    assert kind == "incident"
    assert doc["meta"]["dead_ranks"] == [1]
    assert len(doc["bundles"]) == 2
    merged = ts.merge_entries([(path, kind, doc)])
    ranks = {(ev.get("args") or {}).get("rank")
             for ev in merged["events"]}
    assert ranks == {0, 1}
    assert len(merged["meta"]["sources"]) == 2
    out = ts.render_merged(merged)
    assert "iter" in out


# ---------------------------------------------------------------------------
# drill-artifact section validators (tools/check_bench_regression.py)
# ---------------------------------------------------------------------------

def test_artifact_validators_accept_and_note():
    import check_bench_regression as cbr
    schema, notes = [], []
    cbr._check_cluster_obs({"cluster_obs": {
        "export": "m.r0.jsonl", "world": 2, "ranks_reporting": 2,
        "counters": {"cluster/train/trees_total": 20}}}, schema, notes)
    cbr._check_incident({"incident": {
        "path": "i.json", "schema": "lightgbm-tpu/incident",
        "version": 1, "dead_ranks": [1], "ranks_with_dumps": [0, 1],
        "digest_ranks": [0, 1]}}, schema, notes)
    assert schema == []
    assert any("2/2 ranks" in n for n in notes)
    assert any("dead_ranks=[1]" in n for n in notes)

    # absent sections are notes (evidence missing), never gates
    schema, notes = [], []
    cbr._check_cluster_obs({}, schema, notes)
    cbr._check_incident({}, schema, notes)
    assert schema == [] and len(notes) == 2

    # malformed shapes ARE schema problems; a dead rank with no
    # recovered dump is a note
    schema, notes = [], []
    cbr._check_cluster_obs({"cluster_obs": {"counters": {},
                                            "world": "x"}},
                           schema, notes)
    cbr._check_incident({"incident": {
        "schema": "lightgbm-tpu/incident", "version": 1,
        "dead_ranks": [1], "ranks_with_dumps": [0]}}, schema, notes)
    assert any("cluster/*-keyed" in s for s in schema)
    assert any("numeric" in s for s in schema)
    assert any("no flight dump recovered" in n for n in notes)


# ---------------------------------------------------------------------------
# real processes: rank-suffixed exports, cluster rollup, incident drill
# ---------------------------------------------------------------------------

_SKIP_SPAWN = bool(os.environ.get("LGBM_TPU_SKIP_MULTIHOST"))


@pytest.mark.multihost
@pytest.mark.skipif(_SKIP_SPAWN, reason="LGBM_TPU_SKIP_MULTIHOST set")
def test_two_process_rollup_and_rank_suffixed_artifacts(tmp_path):
    """2 REAL ranks: export/trace paths rank-suffix (no collision),
    rank 0's export folds the ``cluster/*`` rollup built from both
    ranks' digests, rank 1 publishes but never merges, and the two
    trace files merge onto one aligned timeline."""
    from lightgbm_tpu.parallel import elastic
    import trace_summary as ts
    iters = 3
    elastic.run_two_process(
        str(tmp_path), n=768, iterations=iters,
        extra_params={"tpu_metrics_export": str(tmp_path / "metrics"),
                      "tpu_trace": str(tmp_path / "trace.json")})
    # satellite 1: the PR-6 collision fix — one file per rank, no
    # unsuffixed path ever written
    for name in ("metrics.r0.jsonl", "metrics.r1.jsonl",
                 "metrics.r0.prom", "metrics.r1.prom",
                 "trace.r0.json", "trace.r1.json"):
        assert (tmp_path / name).exists(), name
    assert not (tmp_path / "metrics.jsonl").exists()
    assert not (tmp_path / "trace.json").exists()

    def last_snap(name):
        lines = (tmp_path / name).read_text().strip().splitlines()
        return json.loads(lines[-1])

    snap0 = last_snap("metrics.r0.jsonl")
    assert snap0["identity"]["machine_rank"] == 0
    assert snap0["identity"]["world"] == 2
    assert snap0["gauges"]["cluster/world"] == 2
    assert snap0["gauges"]["cluster/ranks_reporting"] == 2
    # the acceptance invariant: the merged iteration histogram counts
    # EVERY rank's iterations — summed per-rank digests, nothing lost
    ch = snap0["histograms"]["cluster/train/iteration_s"]
    assert ch["count"] == 2 * iters
    for r in (0, 1):
        assert f"cluster/iter_wall_mean_s/r{r}" in snap0["gauges"]
    assert snap0["gauges"]["cluster/slowest_iter_rank"] in (0, 1)
    # rank 1 stamps identity but holds no rollup (publishers never
    # merge); its prom export carries the identity info-gauge
    snap1 = last_snap("metrics.r1.jsonl")
    assert snap1["identity"]["machine_rank"] == 1
    assert not any(k.startswith("cluster/")
                   for k in snap1["gauges"]) and \
        not any(k.startswith("cluster/") for k in snap1["counters"])
    prom1 = (tmp_path / "metrics.r1.prom").read_text()
    assert 'lgbm_tpu_identity_info{machine_rank="1"' in prom1

    # per-rank traces merge: both ranks on one timeline
    loaded = []
    for r in (0, 1):
        p = str(tmp_path / f"trace.r{r}.json")
        kind, doc = ts.load_artifact(p)
        assert kind == "trace"
        assert doc["meta"]["identity"]["machine_rank"] == r
        loaded.append((p, kind, doc))
    merged = ts.merge_entries(loaded)
    ranks = {(ev.get("args") or {}).get("rank")
             for ev in merged["events"]}
    assert {0, 1} <= ranks


@pytest.mark.multihost
@pytest.mark.skipif(_SKIP_SPAWN, reason="LGBM_TPU_SKIP_MULTIHOST set")
def test_kill_drill_leaves_one_incident_bundle(tmp_path):
    """SIGKILL rank 1 mid-training: the survivor assembles ONE
    incident bundle naming the dead rank; after a post-exit resweep it
    embeds BOTH ranks' flight dumps (the victim dumped to the shared
    dir just before its SIGKILL)."""
    from lightgbm_tpu.parallel import cluster, elastic
    spec = {
        "seed": 0, "n": 512, "f": 6,
        "params": {"num_iterations": 6,
                   "tpu_collective_timeout_s": 15.0},
        "out": str(tmp_path / "result.json"),
        "checkpoint_dir": str(tmp_path / "ckpt"),
    }
    spec_path = str(tmp_path / "spec.json")
    with open(spec_path, "w") as fh:
        json.dump(spec, fh)
    procs = elastic.launch_workers(
        spec_path, 2, log_dir=str(tmp_path), fault_rank=1,
        faults="train.iter@3:kill")
    assert procs[1].wait(timeout=240) == -9
    assert procs[0].wait(timeout=60) == cluster.EXIT_PEER_LOST
    surv = json.loads((tmp_path / "result.json.rank0").read_text())
    assert surv["dead_ranks"] == [1]
    ipath = surv.get("incident")
    assert ipath and os.path.exists(ipath), surv
    # flight dumps are rank-tagged into the ONE shared directory
    names = os.listdir(tmp_path)
    assert any(n.startswith("flight_r0_") for n in names), names
    assert any(n.startswith("flight_r1_") for n in names), names
    doc = incident.resweep(ipath, str(tmp_path))
    assert doc["dead_ranks"] == [1]
    assert doc["ranks_with_dumps"] == [0, 1]
    victim = doc["ranks"]["1"][0]["bundle"]
    assert victim["identity"]["machine_rank"] == 1
    # the merged timeline renders straight off the incident bundle
    import trace_summary as ts
    kind, idoc = ts.load_artifact(ipath)
    assert kind == "incident"
    out = ts.render_merged(ts.merge_entries([(ipath, kind, idoc)]))
    assert "rank" in out
