"""Stacked whole-ensemble device prediction (ops/stacked_predict.py).

The reference predicts by per-row tree walks (tree.h:212-266,
gbdt_prediction.cpp:9-30); the TPU path lowers the whole ensemble to
one-hot MXU matmuls. These tests pin exact agreement with the host
traversal across every decision semantic: missing values, default
directions, zero-as-missing, categorical bitsets, multiclass, loaded
models, and tree-range slicing.
"""
import numpy as np
import pytest

from conftest import TEST_PARAMS, fit_gbdt, make_binary


def _stacked(g):
    from lightgbm_tpu.ops.stacked_predict import StackedModel
    g._ensure_host_trees()
    sm = StackedModel(g.models, g.max_feature_idx + 1,
                      g.num_tree_per_iteration)
    assert sm.ok
    return sm


def _host_raw(g, X, first=0, ntree=None):
    g._ensure_host_trees()
    ntree = len(g.models) if ntree is None else ntree
    k = g.num_tree_per_iteration
    out = np.zeros((k, X.shape[0]))
    for t in range(first, ntree):
        out[t % k] += g.models[t].predict(X)
    return out


def test_binary_parity_with_nan():
    X, y = make_binary(n=1500, f=6, seed=3)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=15)
    Xt = np.random.default_rng(1).normal(size=(700, 6))
    Xt[::13, 2] = np.nan
    Xt[::7, 0] = np.nan
    sm = _stacked(g)
    np.testing.assert_allclose(sm.predict(Xt), _host_raw(g, Xt),
                               atol=1e-5)


def test_multiclass_parity():
    r = np.random.default_rng(5)
    X = r.normal(size=(1200, 5))
    y = (np.abs(X[:, 0]) + X[:, 1] > 1).astype(int) + (X[:, 2] > 0)
    g = fit_gbdt(X, y.astype(np.float32),
                 dict(TEST_PARAMS, objective="multiclass", num_class=3),
                 num_round=8)
    Xt = r.normal(size=(400, 5))
    sm = _stacked(g)
    np.testing.assert_allclose(sm.predict(Xt), _host_raw(g, Xt),
                               atol=1e-5)


def test_categorical_parity():
    r = np.random.default_rng(11)
    n = 2000
    X = np.zeros((n, 4))
    X[:, 0] = r.integers(0, 12, n)          # categorical
    X[:, 1] = r.normal(size=n)
    X[:, 2] = r.integers(0, 5, n)           # categorical
    X[:, 3] = r.normal(size=n)
    y = ((np.isin(X[:, 0], [1, 3, 7]) ^ (X[:, 1] > 0))
         | (X[:, 2] == 2)).astype(np.float32)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary",
                            categorical_feature="0,2"), num_round=12)
    Xt = np.zeros((500, 4))
    Xt[:, 0] = r.integers(0, 15, 500)       # incl. unseen categories
    Xt[:, 1] = r.normal(size=500)
    Xt[:, 2] = r.integers(0, 7, 500)
    Xt[:, 3] = r.normal(size=500)
    Xt[::9, 0] = np.nan                     # missing categorical
    sm = _stacked(g)
    np.testing.assert_allclose(sm.predict(Xt), _host_raw(g, Xt),
                               atol=1e-5)


def test_zero_as_missing_parity():
    X, y = make_binary(n=1500, f=5, seed=7)
    X[::3, 1] = 0.0
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary",
                            zero_as_missing=True), num_round=10)
    Xt = np.random.default_rng(2).normal(size=(600, 5))
    Xt[::4, 1] = 0.0
    Xt[::5, 3] = np.nan
    sm = _stacked(g)
    np.testing.assert_allclose(sm.predict(Xt), _host_raw(g, Xt),
                               atol=1e-5)


def test_pred_leaf_and_range():
    X, y = make_binary(n=1200, f=6, seed=13)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=14)
    Xt = np.random.default_rng(4).normal(size=(300, 6))
    sm = _stacked(g)
    leaves = sm.predict(Xt, pred_leaf=True)
    want = np.stack([t.predict_leaf_index(Xt) for t in g.models], axis=1)
    np.testing.assert_array_equal(leaves, want)
    np.testing.assert_allclose(sm.predict(Xt, first=3, ntree=11),
                               _host_raw(g, Xt, 3, 11), atol=1e-5)


def test_loaded_model_uses_stacked_path(tmp_path):
    """The motivating case: a model loaded from file (no train_data)
    predicts through the stacked device path, not a per-row host walk."""
    X, y = make_binary(n=1500, f=6, seed=17)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=12)
    f = tmp_path / "m.txt"
    g.save_model_to_file(str(f))

    from lightgbm_tpu.basic import Booster
    bst = Booster(model_file=str(f))
    Xt = np.random.default_rng(6).normal(size=(800, 6))
    got = bst.predict(Xt, raw_score=True)
    sm = bst._gbdt._stacked_model()
    assert sm is not None and sm.ok
    np.testing.assert_allclose(got, _host_raw(bst._gbdt, Xt)[0],
                               atol=1e-5)


def test_gbdt_predict_raw_routes_stacked():
    """predict_raw on a trained booster matches the host path bit-for-
    tree semantics through the public entry point."""
    X, y = make_binary(n=1500, f=6, seed=19)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=10)
    Xt = np.random.default_rng(8).normal(size=(512, 6))
    got = g.predict_raw(Xt)
    np.testing.assert_allclose(got, _host_raw(g, Xt)[0], atol=1e-5)


def test_device_binning_path_matches_host_binning():
    """f32-exact rows take the on-device binning path (edges rounded
    down to f32); it must agree exactly with the host f64 searchsorted
    path, NaNs included."""
    X, y = make_binary(n=1500, f=6, seed=23)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=10)
    sm = _stacked(g)
    assert sm._dev_bin_ok
    Xt = np.random.default_rng(9).normal(
        size=(600, 6)).astype(np.float32).astype(np.float64)
    Xt[::11, 1] = np.nan
    from lightgbm_tpu.ops import stacked_predict as sp
    assert sp._f32_exact(Xt, Xt.astype(np.float32))
    got = sm.predict(Xt)                      # device-binned
    # force the host-binned path by perturbing exactness detection
    Xh = Xt.copy(); Xh[0, 0] = 0.1            # 0.1 not f32-exact
    want = sm.predict(Xh)
    np.testing.assert_allclose(got[:, 1:], want[:, 1:], atol=1e-6)
    np.testing.assert_allclose(got, _host_raw(g, Xt), atol=1e-5)


def test_forest_pallas_kernel_parity():
    """The fused forest kernel (one dispatch: one-hot build + two int8
    MXU dots + match/value reduction in VMEM) agrees with the host
    traversal — run in Pallas interpret mode off-TPU."""
    X, y = make_binary(n=1200, f=6, seed=47)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=13)
    sm = _stacked(g)
    Xt = np.random.default_rng(11).normal(size=(700, 6))
    Xt[::9, 1] = np.nan
    out = sm.predict(Xt, use_pallas=True)
    np.testing.assert_allclose(out, _host_raw(g, Xt), atol=1e-5)
    out2 = sm.predict(Xt, first=2, ntree=9, use_pallas=True)
    np.testing.assert_allclose(out2, _host_raw(g, Xt, 2, 9), atol=1e-5)


def test_forest_pallas_multiclass_and_devbin():
    r = np.random.default_rng(51)
    X = r.normal(size=(1100, 5)).astype(np.float32).astype(np.float64)
    y = ((np.abs(X[:, 0]) + X[:, 1] > 1).astype(int)
         + (X[:, 2] > 0)).astype(np.float32)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="multiclass",
                            num_class=3), num_round=6)
    sm = _stacked(g)
    Xt = r.normal(size=(500, 5)).astype(np.float32).astype(np.float64)
    from lightgbm_tpu.ops import stacked_predict as sp
    assert sm._dev_bin_ok and sp._f32_exact(Xt, Xt.astype(np.float32))
    out = sm.predict(Xt, use_pallas=True)   # device-binned codes path
    np.testing.assert_allclose(out, _host_raw(g, Xt), atol=1e-5)


def test_huge_threshold_edges_warning_free():
    """Thresholds near +-DBL_MAX must not overflow the f32 edge cast
    (clip-then-cast) and device/host paths must agree on values around
    the huge split point."""
    import warnings
    r = np.random.default_rng(77)
    X = r.normal(size=(1200, 3))
    X[:400, 0] = 1e300          # forces a split threshold ~5e299
    X[400:800, 0] = -1e300
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float32)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary"),
                 num_round=8)
    with warnings.catch_warnings():
        warnings.simplefilter("error")      # any RuntimeWarning fails
        sm = _stacked(g)
        Xt = r.normal(size=(300, 3))
        Xt[::3, 0] = 1e300
        Xt[1::3, 0] = -1e300
        got = sm.predict(Xt)
    np.testing.assert_allclose(got, _host_raw(g, Xt), atol=1e-5)


def test_pallas_vmem_guard_scales_tree_chunk():
    """_pallas_tc sizes the fused kernel's tree chunk from the ACTUAL
    block bytes: bench-shaped models keep TC=16, a num_leaves=1024 x
    Wtot=8192 model (which passes a naive Wtot-only gate but needs
    ~134 MB at TC=8) drops to a TC that fits, and an absurdly wide
    model returns None (scan-path fallback instead of a Mosaic OOM)."""
    from lightgbm_tpu.ops.stacked_predict import (StackedModel,
                                                  _PALLAS_VMEM_BUDGET)

    def shape(S, L, Wtot):
        sm = StackedModel.__new__(StackedModel)
        sm._S, sm._L, sm._Wtot = S, L, Wtot
        return sm

    assert shape(254, 255, 2016)._pallas_tc() == 16     # bench shape
    tc = shape(1023, 1024, 8192)._pallas_tc()           # ADVICE case
    assert tc is not None and tc <= 2
    Sp = Lp = 1024
    est = (2 * 8192 * tc * Sp + 2 * tc * Sp * Lp
           + 2048 * tc * Sp * 4 + 2048 * tc * Sp
           + 2048 * 8192 + 2048 * Lp * 4)
    assert est <= _PALLAS_VMEM_BUDGET
    assert shape(1023, 1024, 120_000)._pallas_tc() is None
