"""two_round=true loading: streaming two-pass ingestion must produce
the same binned dataset as the in-memory path when the bin sample
covers every row, and a usable one when it doesn't.

Reference semantics: dataset_loader.cpp LoadFromFile two_round branch
(sample from file, then re-read and push rows straight to bins).
"""
import numpy as np
import pytest

from conftest import TEST_PARAMS, make_binary


def _cfg(**kw):
    from lightgbm_tpu.config import Config
    full = dict(TEST_PARAMS)
    full.update({"objective": "binary", "metric": "auc"})
    full.update(kw)
    return Config().set(full)


def _write_csv(path, X, y, extra_cols=None):
    cols = [y] + ([] if extra_cols is None else extra_cols) + [X]
    np.savetxt(path, np.column_stack(cols), delimiter=",", fmt="%.7g")


def test_two_round_matches_one_pass(tmp_path):
    from lightgbm_tpu.io.loader import DatasetLoader

    X, y = make_binary(n=1000, f=6, seed=21)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    ds1 = DatasetLoader(_cfg()).load_from_file(str(f))
    ds2 = DatasetLoader(_cfg(two_round=True)).load_from_file(str(f))
    assert ds2.num_data == ds1.num_data
    assert [m.feature_info() for m in ds2.mappers] == \
        [m.feature_info() for m in ds1.mappers]
    np.testing.assert_array_equal(ds1.bins, ds2.bins)
    np.testing.assert_array_equal(ds1.metadata.label, ds2.metadata.label)


def test_two_round_small_chunks(tmp_path):
    """Chunked pass-2 (many flushes) assembles the same bins."""
    from lightgbm_tpu.io.loader import DatasetLoader

    X, y = make_binary(n=700, f=5, seed=23)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    ldr = DatasetLoader(_cfg(two_round=True))
    ds_small = ldr._load_two_round(str(f), chunk_rows=64)
    ds_big = ldr._load_two_round(str(f), chunk_rows=1 << 18)
    np.testing.assert_array_equal(ds_small.bins, ds_big.bins)
    np.testing.assert_array_equal(ds_small.metadata.label,
                                  ds_big.metadata.label)


def test_two_round_sampled_bins_train(tmp_path):
    """Sample smaller than the file: training still reaches the same
    quality ballpark as full-sample binning."""
    from conftest import fit_gbdt
    from lightgbm_tpu.io.loader import DatasetLoader
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.metrics import create_metrics

    X, y = make_binary(n=3000, f=6, seed=25)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    cfg = _cfg(two_round=True, bin_construct_sample_cnt=500)
    ds = DatasetLoader(cfg).load_from_file(str(f))
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    mets = create_metrics(["auc"], cfg, ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, mets)
    for _ in range(25):
        g.train_one_iter()
    (_, auc, _), = g.get_eval_at(0)
    g2 = fit_gbdt(X, y, {"objective": "binary", "metric": "auc"},
                  num_round=25)
    (_, auc_full, _), = g2.get_eval_at(0)
    assert auc == pytest.approx(auc_full, abs=0.02)


def test_two_round_weight_and_query_columns(tmp_path):
    """In-file weight/query columns resolve and split out per chunk."""
    from lightgbm_tpu.io.loader import DatasetLoader

    r = np.random.default_rng(3)
    X, y = make_binary(n=400, f=4, seed=27)
    w = r.uniform(0.5, 2.0, size=400).astype(np.float32)
    qid = np.repeat(np.arange(40), 10).astype(np.float64)
    f = tmp_path / "t.tsv"
    np.savetxt(f, np.column_stack([y, w, qid, X]), delimiter="\t",
               fmt="%.7g")
    cfg = _cfg(two_round=True, weight_column="0", group_column="1")
    ds = DatasetLoader(cfg)._load_two_round(str(f), chunk_rows=64)
    assert ds.num_total_features == 4
    np.testing.assert_allclose(ds.metadata.weights, w, rtol=1e-5)
    assert ds.metadata.num_queries == 40
    assert ds.metadata.query_boundaries[-1] == 400


def test_two_round_libsvm_rare_tail_feature(tmp_path):
    """A feature that only appears outside the bin sample still gets a
    column (the pass-1 scan tracks the file-wide max libsvm index)."""
    from lightgbm_tpu.io.loader import DatasetLoader

    r = np.random.default_rng(31)
    f = tmp_path / "rare.svm"
    with open(f, "w") as fh:
        for i in range(2000):
            y = int(r.uniform() > 0.5)
            feats = [f"0:{r.normal():.5g}", f"1:{r.normal():.5g}"]
            if i >= 1995:                       # rare tail feature
                feats.append(f"6:{r.normal():.5g}")
            fh.write(f"{y} {' '.join(feats)}\n")
    cfg = _cfg(two_round=True, bin_construct_sample_cnt=200)
    ds = DatasetLoader(cfg)._load_two_round(str(f), chunk_rows=256)
    ds_ref = DatasetLoader(_cfg()).load_from_file(str(f))
    assert ds.num_total_features == ds_ref.num_total_features == 7


def test_two_round_libsvm(tmp_path):
    from lightgbm_tpu.io.loader import DatasetLoader

    X, y = make_binary(n=300, f=5, seed=29)
    f = tmp_path / "t.svm"
    with open(f, "w") as fh:
        for i in range(300):
            feats = " ".join(f"{j}:{X[i, j]:.6g}" for j in range(5)
                             if abs(X[i, j]) > 0.05)
            fh.write(f"{y[i]:.0f} {feats}\n")
    ds1 = DatasetLoader(_cfg()).load_from_file(str(f))
    ds2 = DatasetLoader(_cfg(two_round=True))._load_two_round(
        str(f), chunk_rows=37)
    np.testing.assert_array_equal(ds1.bins, ds2.bins)
    np.testing.assert_array_equal(ds1.metadata.label, ds2.metadata.label)


def test_two_round_libsvm_nonascending_errors(tmp_path):
    """Non-ascending feature indices break the pass-1 last-pair column
    scan; pass 2 must fail loudly instead of silently truncating."""
    from lightgbm_tpu.io.loader import DatasetLoader
    from lightgbm_tpu.utils.log import LightGBMError

    f = tmp_path / "bad.svm"
    with open(f, "w") as fh:
        for i in range(500):
            fh.write(f"{i % 2} 0:1.0 1:2.0\n")
        fh.write("1 5:3.0 2:1.0\n")            # max index NOT last
    # small sample cap so the malformed line stays OUT of the pass-1
    # reservoir (otherwise its columns are discovered by the sample)
    cfg = _cfg(two_round=True, bin_construct_sample_cnt=20)
    with pytest.raises(LightGBMError, match="not ascending"):
        DatasetLoader(cfg)._load_two_round(str(f), chunk_rows=16)
