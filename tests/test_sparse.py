"""CSR-native sparse engine (io/sparse.py + SparseDeviceBinner +
wave_histogram_sparse): bit-parity against the densified path.

The densified dense-matrix route is the semantic oracle everywhere: the
CSR route must produce the SAME bin mappers (identical rng sample), the
SAME bin matrix (implicit cells = value_to_bin(0.0)), and therefore the
SAME trained model text and predictions — for numerical, categorical
and EFB-bundled features (the acceptance bar of ROADMAP item 5). The
sparse histogram TIER is additionally proven bit-equal to the dense
tier under quantized (integer, order-free) accumulation, and the O(nnz)
promise is asserted directly: a 1%-density workload trains without any
dense [N, F] materialization.
"""
import threading

import numpy as np
import pytest

from conftest import TEST_PARAMS, fit_gbdt
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata, TpuDataset, \
    find_column_mappers
from lightgbm_tpu.io.sparse import (SparseMatrix, bin_entries,
                                    find_column_mappers_sparse,
                                    host_bins_from_sparse,
                                    route_sparse, warn_dense_cliff,
                                    zero_bins)

pytestmark = pytest.mark.sparse

sp_sparse = pytest.importorskip("scipy.sparse")


def _sparse_task(n=1500, f=18, density=0.05, seed=0, cat_col=None,
                 nan_frac=0.0, tiny_col=None):
    """(dense X, SparseMatrix, y): a sparse matrix with the BinMapper
    edge cases on demand — categorical column, NaN entries, values
    straddling ±kZeroThreshold."""
    r = np.random.default_rng(seed)
    mask = r.uniform(size=(n, f)) < density
    X = np.where(mask, r.normal(size=(n, f)) * 2, 0.0)
    if cat_col is not None:
        X[:, cat_col] = np.where(mask[:, cat_col],
                                 r.integers(0, 7, n).astype(float), 0.0)
    if tiny_col is not None:
        X[:, tiny_col] = np.where(
            mask[:, tiny_col],
            np.sign(r.normal(size=n)) * 10.0 ** r.uniform(-37, -33, n),
            0.0)
    if nan_frac:
        X[(r.uniform(size=(n, f)) < nan_frac) & mask] = np.nan
    y = (np.nansum(X[:, : min(6, f)], axis=1)
         + 0.3 * r.normal(size=n) > 0).astype(np.float32)
    sm = SparseMatrix.from_scipy(sp_sparse.csr_matrix(X))
    return X, sm, y


def _trees(g):
    """Model text minus the parameters: block (config knobs like
    tpu_sparse legitimately differ across compared routes)."""
    s = g.model_to_string() if hasattr(g, "model_to_string") else g
    return s.split("\nparameters:\n")[0]


# ---------------------------------------------------------------------------
# Representation + binning parity
# ---------------------------------------------------------------------------

class TestRepresentation:
    def test_mappers_bit_identical(self):
        X, sm, _ = _sparse_task(n=2000, f=14, cat_col=3, nan_frac=0.02,
                                tiny_col=5)
        cfg = Config().set(dict(TEST_PARAMS))
        m0 = find_column_mappers(X, cfg, categorical=[3])
        m1 = find_column_mappers_sparse(sm, cfg, categorical=[3])
        assert len(m0) == len(m1)
        for a, b in zip(m0, m1):
            assert repr(a.to_dict()) == repr(b.to_dict())

    @pytest.mark.parametrize("zam", [False, True])
    def test_host_bins_cell_for_cell(self, zam):
        X, sm, y = _sparse_task(n=1600, f=12, cat_col=2, nan_frac=0.02,
                                tiny_col=7, seed=3)
        cfg = Config().set(dict(TEST_PARAMS, zero_as_missing=zam,
                                enable_bundle=False))
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=y), categorical=[2])
        hb = host_bins_from_sparse(sm, ds.mappers, ds.used_feature_map,
                                   ds.bin_dtype())
        np.testing.assert_array_equal(hb, ds.bins)
        # explicit zeros / sub-threshold values land on the zero bin
        zb = zero_bins(ds.mappers)
        codes, feat, rows = bin_entries(sm, ds.mappers,
                                        ds.used_feature_map)
        rebuilt = np.empty_like(hb)
        rebuilt[:] = zb[None, :].astype(hb.dtype)
        rebuilt[rows, feat] = codes.astype(hb.dtype)
        np.testing.assert_array_equal(rebuilt, hb)

    def test_csc_and_duplicate_semantics(self):
        X, sm, _ = _sparse_task(n=400, f=6, seed=9)
        csc = sp_sparse.csc_matrix(X)
        sm2 = SparseMatrix.from_csc(csc.indptr, csc.indices, csc.data,
                                    *X.shape)
        np.testing.assert_array_equal(sm2.to_dense(), X)
        # duplicate (row, col) in raw CSR planes: LAST wins (the old
        # densify assignment's semantics)
        smd = SparseMatrix.from_csr([0, 2], [1, 1], [5.0, 7.0], 3)
        assert smd.nnz == 1 and smd.to_dense()[0, 1] == 7.0


# ---------------------------------------------------------------------------
# End-to-end route parity (model text + predictions)
# ---------------------------------------------------------------------------

def _capi_train(handle_factory, params, rounds=12):
    from lightgbm_tpu import capi
    h = handle_factory(params)
    b = capi.LGBM_BoosterCreate(h, params)
    for _ in range(rounds):
        capi.LGBM_BoosterUpdateOneIter(b)
    return b


class TestRouteParity:
    PARAMS = ("objective=binary max_bin=63 num_leaves=15 "
              "min_data_in_leaf=20 num_iterations=12")

    def _roundtrip(self, make_sparse_handle, X, y):
        from lightgbm_tpu import capi

        def dense_handle(params):
            h = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
            capi.LGBM_DatasetSetField(h, "label", y)
            return h

        def sparse_handle(params):
            h = make_sparse_handle(params)
            capi.LGBM_DatasetSetField(h, "label", y)
            return h

        bd = _capi_train(dense_handle, self.PARAMS)
        bs = _capi_train(sparse_handle, self.PARAMS)
        sd = capi.LGBM_BoosterSaveModelToString(bd)
        ss = capi.LGBM_BoosterSaveModelToString(bs)
        assert sd == ss, "CSR-native model text differs from densified"
        pd = capi.LGBM_BoosterPredictForMat(bd, X[:300])
        csr = sp_sparse.csr_matrix(X[:300])
        ps = capi.LGBM_BoosterPredictForCSR(
            bs, csr.indptr, 0, csr.indices, csr.data, 0,
            len(csr.indptr), csr.nnz, X.shape[1])
        np.testing.assert_array_equal(np.asarray(pd), np.asarray(ps))

    def test_csr_roundtrip(self):
        from lightgbm_tpu import capi
        X, _, y = _sparse_task(n=1800, f=16, seed=1)
        csr = sp_sparse.csr_matrix(X)

        def mk(params):
            return capi.LGBM_DatasetCreateFromCSR(
                csr.indptr, 0, csr.indices, csr.data, 0,
                len(csr.indptr), csr.nnz, X.shape[1],
                parameters=params)

        self._roundtrip(mk, X, y)

    def test_csc_roundtrip(self):
        from lightgbm_tpu import capi
        X, _, y = _sparse_task(n=1500, f=12, seed=2)
        csc = sp_sparse.csc_matrix(X)

        def mk(params):
            return capi.LGBM_DatasetCreateFromCSC(
                csc.indptr, 0, csc.indices, csc.data, 0,
                len(csc.indptr), csc.nnz, X.shape[0],
                parameters=params)

        self._roundtrip(mk, X, y)

    def test_scipy_dataset_parity(self):
        import lightgbm_tpu as lgb
        X, _, y = _sparse_task(n=1500, f=14, seed=4)
        params = dict(TEST_PARAMS, objective="binary", verbosity=-1)
        bd = lgb.train(params, lgb.Dataset(X.copy(), label=y),
                       num_boost_round=10)
        bs = lgb.train(params,
                       lgb.Dataset(sp_sparse.csr_matrix(X), label=y),
                       num_boost_round=10)
        assert bd.model_to_string() == bs.model_to_string()
        np.testing.assert_array_equal(
            bd.predict(X[:200]),
            bs.predict(sp_sparse.csr_matrix(X[:200])))

    def test_categorical_parity(self):
        X, sm, y = _sparse_task(n=1800, f=12, cat_col=4, seed=5)
        params = dict(TEST_PARAMS, objective="binary")
        gd = fit_gbdt(X, y, params, num_round=10)
        # fit_gbdt passes categorical through construct: do it directly
        cfg = Config().set(dict(TEST_PARAMS, objective="binary"))

        def train(Xin):
            from lightgbm_tpu.metrics import create_metrics
            from lightgbm_tpu.models.gbdt import GBDT
            from lightgbm_tpu.objectives import create_objective
            ds = TpuDataset(cfg.copy()).construct_from_matrix(
                Xin, Metadata(label=y), categorical=[4])
            obj = create_objective("binary", cfg)
            obj.init(ds.metadata, ds.num_data)
            g = GBDT()
            g.init(cfg.copy(), ds, obj, [])
            for _ in range(10):
                g.train_one_iter()
            return g

        g0, g1 = train(X.copy()), train(sm)
        assert g0.model_to_string() == g1.model_to_string()
        np.testing.assert_array_equal(g0.predict_raw(X[:200]),
                                      g1.predict_raw(X[:200]))
        del gd

    def test_efb_on_sparse_parity(self):
        # mutually exclusive columns bundle; the sparse route must take
        # the host-bins path and produce the identical bundled dataset
        r = np.random.default_rng(7)
        n = 1500
        owner = r.integers(0, 6, n)
        X = np.zeros((n, 6))
        X[np.arange(n), owner] = r.normal(size=n) + 3.0
        y = (X.sum(1) + 0.2 * r.normal(size=n) > 3.0).astype(np.float32)
        sm = SparseMatrix.from_scipy(sp_sparse.csr_matrix(X))
        params = dict(TEST_PARAMS, objective="binary")
        g0 = fit_gbdt(X.copy(), y, params, num_round=10)
        g1 = fit_gbdt(sm, y, params, num_round=10)
        assert g0.train_data.bundles is not None
        assert g1.train_data.bundles is not None
        assert g0.train_data.bundles == g1.train_data.bundles
        assert g0.model_to_string() == g1.model_to_string()

    def test_valid_set_sparse(self):
        X, sm, y = _sparse_task(n=1200, f=10, seed=6)
        Xv, smv, yv = _sparse_task(n=400, f=10, seed=16)
        params = dict(TEST_PARAMS, objective="binary", metric="auc")
        g0 = fit_gbdt(X.copy(), y, params, num_round=8,
                      valid=(Xv.copy(), yv))
        g1 = fit_gbdt(X.copy(), y, params, num_round=8, valid=(smv, yv))
        e0 = g0.get_eval_at(1)
        e1 = g1.get_eval_at(1)
        assert e0 == e1


# ---------------------------------------------------------------------------
# Streamed sparse device ingest
# ---------------------------------------------------------------------------

class TestDeviceIngest:
    def test_device_bins_bit_identical(self):
        X, sm, y = _sparse_task(n=2100, f=10, cat_col=4, nan_frac=0.02,
                                tiny_col=6, seed=8)
        base = dict(TEST_PARAMS, enable_bundle=False)
        ds0 = TpuDataset(Config().set(dict(base, tpu_ingest=0))) \
            .construct_from_matrix(sm, Metadata(label=y),
                                   categorical=[4])
        ds1 = TpuDataset(Config().set(dict(
            base, tpu_ingest=1, tpu_ingest_chunk_rows=257,
            tpu_sparse=1))).construct_from_matrix(
            sm, Metadata(label=y), categorical=[4])
        assert ds1.bins_t_dev is not None, "sparse device ingest off"
        np.testing.assert_array_equal(
            ds0.bins, np.ascontiguousarray(np.asarray(ds1.bins_t_dev).T))
        # the retained coordinate planes rebuild the same matrix
        codes, feat, rows = [np.asarray(a) for a in ds1.sparse_coords]
        keep = feat < len(ds1.mappers)
        rb = np.empty_like(ds0.bins)
        rb[:] = zero_bins(ds1.mappers)[None, :].astype(rb.dtype)
        rb[rows[keep], feat[keep]] = codes[keep].astype(rb.dtype)
        np.testing.assert_array_equal(rb, ds0.bins)

    def test_training_parity_ingest_on_off(self):
        X, sm, y = _sparse_task(n=1600, f=12, seed=10)
        params = dict(TEST_PARAMS, objective="binary",
                      enable_bundle=False)
        g0 = fit_gbdt(sm, y, dict(params, tpu_ingest=0), num_round=8)
        g1 = fit_gbdt(sm, y, dict(params, tpu_ingest=1,
                                  tpu_ingest_chunk_rows=300),
                      num_round=8)
        assert _trees(g0) == _trees(g1)


# ---------------------------------------------------------------------------
# Sparse histogram tier
# ---------------------------------------------------------------------------

class TestSparseHistTier:
    def test_wave_histogram_sparse_vs_dense_oracle(self):
        import jax.numpy as jnp

        from lightgbm_tpu.ops.hist_wave import (wave_histogram_sparse,
                                                wave_histogram_xla)
        r = np.random.default_rng(2)
        N, F, B, W = 700, 6, 16, 5
        zb = r.integers(0, B, F).astype(np.int32)
        bins = np.empty((N, F), np.int32)
        bins[:] = zb[None, :]
        mask = r.uniform(size=(N, F)) < 0.1
        rows, feats = np.nonzero(mask)
        codes = r.integers(0, B, mask.sum()).astype(np.int32)
        bins[rows, feats] = codes
        leaf = r.integers(-1, 7, N).astype(np.int32)    # -1 = oob
        wl = np.array([0, 3, 5, -1, 2], np.int32)
        pad = 37                                        # sentinels
        sp = (jnp.asarray(np.concatenate([codes,
                                          np.zeros(pad, np.int32)])),
              jnp.asarray(np.concatenate([feats.astype(np.int32),
                                          np.full(pad, F, np.int32)])),
              jnp.asarray(np.concatenate([rows.astype(np.int32),
                                          np.zeros(pad, np.int32)])),
              jnp.asarray(zb))
        gi = r.integers(-127, 128, N).astype(np.float32)
        hi = r.integers(0, 128, N).astype(np.float32)
        dense = wave_histogram_xla(
            jnp.asarray(bins.T), jnp.asarray(gi), jnp.asarray(hi),
            jnp.asarray(leaf), jnp.asarray(wl), num_bins=B)
        sparse = wave_histogram_sparse(
            sp, jnp.asarray(gi), jnp.asarray(hi), jnp.asarray(leaf),
            jnp.asarray(wl), num_bins=B, num_features=F)
        # integer-valued accumulation: BIT-equal
        np.testing.assert_array_equal(np.asarray(dense),
                                      np.asarray(sparse))
        # dequantization multiplies identically to the dense path
        sc = (0.031, 0.017)
        s2 = wave_histogram_sparse(
            sp, jnp.asarray(gi), jnp.asarray(hi), jnp.asarray(leaf),
            jnp.asarray(wl), num_bins=B, num_features=F, gh_scale=sc)
        np.testing.assert_array_equal(
            np.asarray(dense) * np.array([sc[0], sc[1], 1.0],
                                         np.float32),
            np.asarray(s2))
        # f32 gradients: equal up to completion reassociation
        gf = r.normal(size=N).astype(np.float32)
        hf = r.uniform(0.1, 1, N).astype(np.float32)
        df = wave_histogram_xla(
            jnp.asarray(bins.T), jnp.asarray(gf), jnp.asarray(hf),
            jnp.asarray(leaf), jnp.asarray(wl), num_bins=B)
        sf = wave_histogram_sparse(
            sp, jnp.asarray(gf), jnp.asarray(hf), jnp.asarray(leaf),
            jnp.asarray(wl), num_bins=B, num_features=F)
        np.testing.assert_allclose(np.asarray(df), np.asarray(sf),
                                   rtol=1e-5, atol=1e-4)

    def _tier_pair(self, sm, y, tpu_sparse, rounds=8, **extra):
        params = dict(TEST_PARAMS, objective="binary",
                      enable_bundle=False, tpu_quantized_hist=True,
                      tpu_count_proxy=0, tpu_sparse=tpu_sparse)
        params.update(extra)
        return fit_gbdt(sm, y, params, num_round=rounds)

    def test_quantized_bit_parity(self):
        # integer accumulation is order-free: the sparse tier's trees
        # are BIT-equal to the dense tier's on the same CSR input
        X, sm, y = _sparse_task(n=2200, f=20, cat_col=5, seed=11,
                                density=0.03)
        g0 = self._tier_pair(sm, y, 0)
        g1 = self._tier_pair(sm, y, 1)
        assert not g0._grower_cfg.sparse_hist
        assert g1._grower_cfg.sparse_hist
        assert _trees(g0) == _trees(g1)
        np.testing.assert_array_equal(g0.predict_raw(X[:200]),
                                      g1.predict_raw(X[:200]))

    def test_auto_rule(self):
        from lightgbm_tpu.ops.autotune import tune_hist_tier
        kw = dict(nnz=100, F=10, B=64, W=0)
        assert tune_hist_tier(requested=1, density=0.5, quant=False,
                              **kw)
        assert not tune_hist_tier(requested=0, density=0.001,
                                  quant=True, **kw)
        # auto: exactness-first (quantized only) + density ceiling
        assert tune_hist_tier(requested=-1, density=0.01, quant=True,
                              **kw)
        assert not tune_hist_tier(requested=-1, density=0.01,
                                  quant=False, **kw)
        assert not tune_hist_tier(requested=-1, density=0.5,
                                  quant=True, **kw)

    def test_f32_forced_tier_trains_close(self):
        X, sm, y = _sparse_task(n=1500, f=12, seed=12)
        params = dict(TEST_PARAMS, objective="binary",
                      enable_bundle=False)
        g0 = fit_gbdt(sm, y, dict(params, tpu_sparse=0), num_round=6)
        g1 = fit_gbdt(sm, y, dict(params, tpu_sparse=1), num_round=6)
        assert g1._grower_cfg.sparse_hist
        np.testing.assert_allclose(g0.predict_raw(X[:300]),
                                   g1.predict_raw(X[:300]),
                                   rtol=1e-4, atol=1e-5)

    def test_step_cache_reuse_same_geometry(self):
        # the sparse planes ride the step as TRACED arguments: a second
        # same-geometry sparse booster is a registry hit serving ITS
        # OWN coordinates (the sliding-window pattern)
        from lightgbm_tpu.ops import step_cache
        r = np.random.default_rng(13)
        X1, sm1, y1 = _sparse_task(n=1500, f=12, seed=13)
        X2 = np.where(r.uniform(size=X1.shape) < 0.05,
                      r.normal(size=X1.shape), 0.0)
        sm2 = SparseMatrix.from_scipy(sp_sparse.csr_matrix(X2))
        y2 = (X2.sum(1) > 0).astype(np.float32)
        s0 = step_cache.stats()
        g1 = self._tier_pair(sm1, y1, 1, rounds=4)
        mid = step_cache.stats()
        g2 = self._tier_pair(sm2, y2, 1, rounds=4)
        s1 = step_cache.stats()
        assert g1._grower_cfg.sparse_hist and g2._grower_cfg.sparse_hist
        assert s1["hits"] > mid["hits"], \
            "same-geometry sparse booster missed the step registry"
        # the hit served booster 2's OWN data, not booster 1's
        assert _trees(g1) != _trees(g2)
        del s0

    def test_tier_geometry_key_distinguishes(self):
        # a sparse-tier booster and a dense-tier booster of the same
        # shape must NOT share a compiled step
        X, sm, y = _sparse_task(n=1500, f=12, seed=14)
        g0 = self._tier_pair(sm, y, 0, rounds=3)
        g1 = self._tier_pair(sm, y, 1, rounds=3)
        k0 = g0._step_geometry_key(False, g0.objective, None, None,
                                   g0._meta)
        k1 = g1._step_geometry_key(False, g1.objective, None, None,
                                   g1._meta)
        assert k0 != k1


# ---------------------------------------------------------------------------
# O(nnz) memory + route decision
# ---------------------------------------------------------------------------

class TestMemoryAndRoute:
    def test_o_nnz_no_dense_materialization(self, monkeypatch):
        """A ~1%-density workload trains end to end without EVER
        allocating a dense [N, F] matrix: to_dense is banned outright,
        and the python-side allocation peak during construct+train
        stays under even a UINT8 [N, F] (the float64 cliff is 8x
        that)."""
        import tracemalloc

        r = np.random.default_rng(15)
        n, f = 60_000, 100                   # float64 [N, F] = 48 MB
        k = max(1, int(f * 0.01))
        rows = np.repeat(np.arange(n, dtype=np.int64), k)
        cols = r.integers(0, f, size=n * k).astype(np.int64)
        key = rows * f + cols
        _, first = np.unique(key, return_index=True)
        rows, cols = rows[first], cols[first]
        vals = r.normal(size=len(rows)) + 2.0
        indptr = np.concatenate(
            [[0], np.cumsum(np.bincount(rows, minlength=n))])
        sm = SparseMatrix(vals, cols, indptr, (n, f))
        y = np.zeros(n, np.float32)
        np.add.at(y, rows, vals.astype(np.float32))
        y = (y > y.mean()).astype(np.float32)
        assert sm.density <= 0.0105

        def boom(*a, **kw):
            raise AssertionError("dense [N, F] materialized on the "
                                 "CSR-native route")

        monkeypatch.setattr(SparseMatrix, "to_dense", boom)
        from lightgbm_tpu.obs import registry as obs
        routed0 = obs.counter("sparse/route_sparse").value
        densified0 = obs.counter("sparse/route_dense").value
        params = dict(TEST_PARAMS, objective="binary",
                      enable_bundle=False, tpu_ingest=1)
        tracemalloc.start()
        base = tracemalloc.get_traced_memory()[0]
        g = fit_gbdt(sm, y, params, num_round=3)
        peak = tracemalloc.get_traced_memory()[1] - base
        tracemalloc.stop()
        assert obs.counter("sparse/route_sparse").value == routed0 + 1
        assert obs.counter("sparse/route_dense").value == densified0
        # numpy/python peak far below the float64 cliff (8 * n * f):
        # the bound leaves room for trace/compile bookkeeping but any
        # [N, F] float64 (or even float32) materialization blows it
        assert peak < n * f * 4, \
            f"python allocation peak {peak} suggests densification"
        assert g.current_iteration == 3

    def test_route_threshold_and_fallback(self):
        X, _, y = _sparse_task(n=800, f=8, density=0.6, seed=17)
        sm = SparseMatrix.from_scipy(sp_sparse.csr_matrix(X))
        from lightgbm_tpu.obs import registry as obs
        cfg = Config().set(dict(TEST_PARAMS))
        assert not route_sparse(cfg, sm)     # too dense for the route
        d0 = obs.counter("sparse/route_dense").value
        ds = TpuDataset(cfg).construct_from_matrix(sm, Metadata(label=y))
        assert obs.counter("sparse/route_dense").value == d0 + 1
        assert ds.sparse_coords is None
        # identical result to the explicitly-densified construction
        ds2 = TpuDataset(Config().set(dict(TEST_PARAMS))) \
            .construct_from_matrix(X, Metadata(label=y))
        np.testing.assert_array_equal(ds.bins, ds2.bins)
        # is_enable_sparse=false refuses the CSR route regardless
        cfg2 = Config().set(dict(TEST_PARAMS, is_enable_sparse=False))
        _, smn, _ = _sparse_task(n=500, f=8, density=0.02, seed=18)
        assert not route_sparse(cfg2, smn)

    def test_config_knob_validation(self):
        cfg = Config().set({"sparse_threshold": 1.7})
        assert cfg.sparse_threshold == 0.8
        cfg = Config().set({"tpu_sparse": 5})
        assert cfg.tpu_sparse == -1
        cfg = Config().set({"sparse_threshold": 0.5, "tpu_sparse": 1})
        assert cfg.sparse_threshold == 0.5 and cfg.tpu_sparse == 1

    def test_dense_cliff_warning_unified(self):
        from lightgbm_tpu import capi
        from lightgbm_tpu.utils import log as tlog
        seen = []
        old = tlog._callback
        old_level = tlog.get_level()
        tlog.set_callback(seen.append)
        tlog.set_level(tlog.LogLevel.INFO)   # a verbosity=-1 test may
        try:                                 # have lowered the level
            warn_dense_cliff(600_000_000, 2_000, 12_345)
            assert any("GiB" in m for m in seen), seen
            seen.clear()
            warn_dense_cliff(100, 10, 50)     # tiny: no warning
            assert not seen
        finally:
            tlog.set_callback(old)
            tlog.set_level(old_level)
        # both explicit densify helpers route through the one guard
        calls = []
        orig = capi.warn_dense_cliff
        try:
            capi.warn_dense_cliff = \
                lambda *a, **k: calls.append(a)
            capi._csr_to_dense([0, 1], [0], [1.0], 3)
            capi._csc_to_dense([0, 1, 1, 1], [0], [1.0], 2, 3)
        finally:
            capi.warn_dense_cliff = orig
        assert len(calls) == 2

    def test_predict_chunked_paths(self, monkeypatch):
        import lightgbm_tpu.models.gbdt as gbdt_mod
        from lightgbm_tpu.io import sparse as sparse_mod
        X, sm, y = _sparse_task(n=900, f=10, seed=19)
        params = dict(TEST_PARAMS, objective="binary")
        g = fit_gbdt(X.copy(), y, params, num_round=8)
        monkeypatch.setattr(sparse_mod, "PREDICT_CHUNK_ROWS", 128)
        np.testing.assert_array_equal(g.predict_raw(X), g.predict_raw(sm))
        np.testing.assert_array_equal(g.predict(X), g.predict(sm))
        np.testing.assert_array_equal(g.predict_leaf_index(X),
                                      g.predict_leaf_index(sm))
        np.testing.assert_array_equal(g.predict_contrib(X),
                                      g.predict_contrib(sm))

    def test_predict_during_construct_thread_safety(self):
        # cheap sanity: chunked sparse predict from a second thread
        # while the main thread trains another booster
        X, sm, y = _sparse_task(n=900, f=8, seed=20)
        params = dict(TEST_PARAMS, objective="binary")
        g = fit_gbdt(X.copy(), y, params, num_round=6)
        want = g.predict_raw(X[:256])
        errs = []

        def hammer():
            try:
                for _ in range(3):
                    got = g.predict_raw(
                        SparseMatrix.from_scipy(
                            sp_sparse.csr_matrix(X[:256])))
                    np.testing.assert_array_equal(got, want)
            except Exception as e:           # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=hammer)
        t.start()
        fit_gbdt(sm, y, params, num_round=3)
        t.join(timeout=60)
        assert not errs, errs
