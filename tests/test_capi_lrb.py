"""C-API shim + fork cache-admission driver tests.

Covers the LGBM_* surface (reference: src/c_api.cpp:47-1568) and the
windowed LRB retraining loop (reference: src/test.cpp:97-341).
"""
import numpy as np
import pytest

from lightgbm_tpu import capi
from lightgbm_tpu.lrb import LrbDriver, synthetic_trace


def _data(n=300, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


class TestCApi:
    def test_train_predict_save_cycle(self, tmp_path):
        X, y = _data()
        params = "objective=binary num_leaves=15 min_data_in_leaf=5 verbose=-1"
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        assert capi.LGBM_DatasetGetNumData(ds) == 300
        assert capi.LGBM_DatasetGetNumFeature(ds) == 6
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(10):
            if capi.LGBM_BoosterUpdateOneIter(bst):
                break
        assert capi.LGBM_BoosterGetCurrentIteration(bst) == 10
        pred = capi.LGBM_BoosterPredictForMat(bst, X)
        assert ((np.asarray(pred) > 0.5) == y).mean() > 0.9
        path = str(tmp_path / "m.txt")
        capi.LGBM_BoosterSaveModel(bst, filename=path)
        loaded = capi.LGBM_BoosterCreateFromModelfile(path)
        p2 = capi.LGBM_BoosterPredictForMat(loaded, X)
        np.testing.assert_allclose(p2, pred, atol=1e-5)
        imp = capi.LGBM_BoosterFeatureImportance(bst)
        assert imp.sum() > 0

    def test_csr_paths(self):
        X, y = _data(n=200)
        import scipy.sparse as sp
        S = sp.csr_matrix(X)
        params = "objective=binary num_leaves=7 min_data_in_leaf=5 verbose=-1"
        ds = capi.LGBM_DatasetCreateFromCSR(
            S.indptr, capi.C_API_DTYPE_INT32, S.indices, S.data,
            capi.C_API_DTYPE_FLOAT64, len(S.indptr), S.nnz, X.shape[1],
            parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(5):
            capi.LGBM_BoosterUpdateOneIter(bst)
        pred = capi.LGBM_BoosterPredictForCSR(
            bst, S.indptr, capi.C_API_DTYPE_INT32, S.indices, S.data,
            capi.C_API_DTYPE_FLOAT64, len(S.indptr), S.nnz, X.shape[1])
        dense_pred = capi.LGBM_BoosterPredictForMat(bst, X)
        np.testing.assert_allclose(pred, dense_pred, atol=1e-6)

    def test_custom_objective_and_eval(self):
        X, y = _data(n=200)
        params = ("objective=binary num_leaves=7 min_data_in_leaf=5 "
                  "verbose=-1 is_provide_training_metric=true "
                  "metric=binary_logloss")
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        capi.LGBM_BoosterUpdateOneIter(bst)
        evals = capi.LGBM_BoosterGetEval(bst, 0)
        assert evals and evals[0][0] == "binary_logloss"
        # custom gradients
        raw = np.asarray(capi.LGBM_BoosterPredictForMat(
            bst, X, predict_type=capi.C_API_PREDICT_RAW_SCORE))
        p = 1 / (1 + np.exp(-raw))
        capi.LGBM_BoosterUpdateOneIterCustom(bst, (p - y), p * (1 - p))
        assert capi.LGBM_BoosterGetCurrentIteration(bst) == 2
        capi.LGBM_BoosterRollbackOneIter(bst)
        assert capi.LGBM_BoosterGetCurrentIteration(bst) == 1


class TestLrbDriver:
    def test_windowed_retraining(self):
        """The fork's end-to-end loop on a synthetic zipf trace:
        per-window OPT labels, fresh boosters, FP/FN eval output
        (test.cpp:300-341)."""
        driver = LrbDriver(cache_size=1 << 16, window_size=500,
                           sample_size=400, cutoff=0.5, sampling=1,
                           result_file=open("/dev/null", "w"))
        for seq, oid, size, cost in synthetic_trace(1500):
            driver.process_request(seq, oid, size, cost)
        assert driver.window_index == 3
        assert driver.booster is not None
        r1, r2, r3 = driver.results
        # OPT labeled something cacheable in every window
        assert all(r["opt_obj_hit_ratio"] > 0 for r in driver.results)
        # windows after the first evaluate the previous model
        assert "fp_rate" in r2 and "fn_rate" in r2
        assert 0 <= r2["fp_rate"] <= 1 and 0 <= r2["fn_rate"] <= 1
        # the learned admission policy beats chance: error rates bounded
        assert r3["fp_rate"] + r3["fn_rate"] < 0.9


class TestCApiExtended:
    """The remaining c_api.h surface (59-function parity)."""

    def _csc(self, X):
        col_ptr = [0]
        indices, data = [], []
        for j in range(X.shape[1]):
            nz = np.nonzero(X[:, j])[0]
            indices.extend(nz.tolist())
            data.extend(X[nz, j].tolist())
            col_ptr.append(len(indices))
        return col_ptr, indices, data

    def test_csc_create_and_predict(self):
        X, y = _data()
        params = "objective=binary num_leaves=15 min_data_in_leaf=5"
        col_ptr, indices, data = self._csc(X)
        ds = capi.LGBM_DatasetCreateFromCSC(
            col_ptr, 3, indices, data, 1, len(col_ptr), len(data),
            X.shape[0], parameters=params)
        # CSR-native handle (io/sparse.py): the raw matrix stays O(nnz)
        np.testing.assert_allclose(ds.X.to_dense(), X)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(8):
            capi.LGBM_BoosterUpdateOneIter(bst)
        p_csc = capi.LGBM_BoosterPredictForCSC(
            bst, col_ptr, 3, indices, data, 1, len(col_ptr), len(data),
            X.shape[0])
        p_mat = capi.LGBM_BoosterPredictForMat(bst, X)
        np.testing.assert_allclose(p_csc, p_mat, atol=1e-6)

    def test_mats_subset_names_counts(self):
        X, y = _data(n=400)
        params = ("objective=binary num_leaves=15 min_data_in_leaf=5 "
                  "metric=auc is_provide_training_metric=true")
        ds = capi.LGBM_DatasetCreateFromMats(
            2, [X[:150], X[150:]], 1, [150, 250], X.shape[1], 1,
            parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        capi.LGBM_DatasetSetFeatureNames(
            ds, [f"f{i}" for i in range(X.shape[1])])
        assert capi.LGBM_DatasetGetFeatureNames(ds)[0] == "f0"
        sub = capi.LGBM_DatasetGetSubset(ds, np.arange(0, 400, 2))
        assert sub.X.shape == (200, X.shape[1])
        assert len(sub.fields["label"]) == 200

        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(6):
            capi.LGBM_BoosterUpdateOneIter(bst)
        assert capi.LGBM_BoosterGetEvalCounts(bst) >= 1
        assert capi.LGBM_BoosterNumModelPerIteration(bst) == 1
        assert capi.LGBM_BoosterNumberOfTotalModel(bst) == 6
        assert capi.LGBM_BoosterGetFeatureNames(bst)[0] == "f0"
        n_pred = capi.LGBM_BoosterGetNumPredict(bst, 0)
        assert n_pred == 400
        raw = capi.LGBM_BoosterGetPredict(bst, 0)
        assert raw.shape == (400,)
        p = capi.LGBM_BoosterPredictForMat(bst, X)
        np.testing.assert_allclose(raw, np.asarray(p).reshape(-1),
                                   atol=1e-5)

    def test_leaf_value_roundtrip_and_merge(self):
        X, y = _data()
        params = "objective=binary num_leaves=15 min_data_in_leaf=5"
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(4):
            capi.LGBM_BoosterUpdateOneIter(bst)
        v = capi.LGBM_BoosterGetLeafValue(bst, 0, 1)
        capi.LGBM_BoosterSetLeafValue(bst, 0, 1, v + 1.0)
        assert capi.LGBM_BoosterGetLeafValue(bst, 0, 1) ==             pytest.approx(v + 1.0)

        ds2 = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
        capi.LGBM_DatasetSetField(ds2, "label", y)
        bst2 = capi.LGBM_BoosterCreate(ds2, params)
        for _ in range(3):
            capi.LGBM_BoosterUpdateOneIter(bst2)
        capi.LGBM_BoosterMerge(bst, bst2)
        assert capi.LGBM_BoosterNumberOfTotalModel(bst) == 7

    def test_sampled_column_push_rows(self):
        X, y = _data(n=200)
        params = "objective=binary num_leaves=15 min_data_in_leaf=5"
        cols = [X[:, j] for j in range(X.shape[1])]
        idx = [np.arange(200)] * X.shape[1]
        ds = capi.LGBM_DatasetCreateFromSampledColumn(
            cols, idx, X.shape[1], [200] * X.shape[1], 200, 200,
            parameters=params)
        capi.LGBM_DatasetPushRows(ds, X[:120], 1, 120, X.shape[1], 0)
        capi.LGBM_DatasetPushRows(ds, X[120:], 1, 80, X.shape[1], 120)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(8):
            capi.LGBM_BoosterUpdateOneIter(bst)
        p = capi.LGBM_BoosterPredictForMat(bst, X)
        assert ((np.asarray(p) > 0.5) == y).mean() > 0.85

    def test_network_and_error_state(self):
        assert capi.LGBM_NetworkInit("127.0.0.1:12400", 12400, 120, 1) == 0
        assert capi.LGBM_NetworkFree() == 0
        # the external-collective seam (network.cpp:41-54) installs and
        # clears overrides (tests/test_parallel.py exercises them live)
        from lightgbm_tpu.parallel.learners import _collective_overrides
        assert capi.LGBM_NetworkInitWithFunctions(
            1, 2, reduce_scatter_fn=lambda x, d: d(x)) == 0
        assert "reduce_scatter" in _collective_overrides
        assert capi.LGBM_NetworkFree() == 0
        assert not _collective_overrides
        capi.LGBM_SetLastError("boom")
        assert capi.LGBM_GetLastError() == "boom"

    def test_refit_and_reset_training_data(self):
        X, y = _data(n=300)
        params = "objective=binary num_leaves=15 min_data_in_leaf=5"
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(5):
            capi.LGBM_BoosterUpdateOneIter(bst)
        assert capi.LGBM_BoosterRefit(bst) == 0
        p = capi.LGBM_BoosterPredictForMat(bst, X)
        assert ((np.asarray(p) > 0.5) == y).mean() > 0.85


def test_subset_multiclass_init_score():
    """init_score is stored flattened [K*N]; a row subset must slice
    per class, not by raw flat index (c_api.cpp:430 CopySubset)."""
    X, _ = _data(n=100)
    y3 = (np.arange(100) % 3).astype(np.float32)
    ds = capi.LGBM_DatasetCreateFromMat(
        X, "objective=multiclass num_class=3")
    capi.LGBM_DatasetSetField(ds, "label", y3)
    init = np.arange(300, dtype=np.float64)   # [K=3 * N=100] flattened
    capi.LGBM_DatasetSetField(ds, "init_score", init)
    idx = np.array([5, 17, 42, 99])
    sub = capi.LGBM_DatasetGetSubset(ds, idx)
    got = np.asarray(sub.fields["init_score"])
    want = init.reshape(3, 100)[:, idx].reshape(-1)
    np.testing.assert_array_equal(got, want)
    assert got.size == 3 * len(idx)
