"""C-API shim + fork cache-admission driver tests.

Covers the LGBM_* surface (reference: src/c_api.cpp:47-1568) and the
windowed LRB retraining loop (reference: src/test.cpp:97-341).
"""
import numpy as np
import pytest

from lightgbm_tpu import capi
from lightgbm_tpu.lrb import LrbDriver, synthetic_trace


def _data(n=300, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


class TestCApi:
    def test_train_predict_save_cycle(self, tmp_path):
        X, y = _data()
        params = "objective=binary num_leaves=15 min_data_in_leaf=5 verbose=-1"
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        assert capi.LGBM_DatasetGetNumData(ds) == 300
        assert capi.LGBM_DatasetGetNumFeature(ds) == 6
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(10):
            if capi.LGBM_BoosterUpdateOneIter(bst):
                break
        assert capi.LGBM_BoosterGetCurrentIteration(bst) == 10
        pred = capi.LGBM_BoosterPredictForMat(bst, X)
        assert ((np.asarray(pred) > 0.5) == y).mean() > 0.9
        path = str(tmp_path / "m.txt")
        capi.LGBM_BoosterSaveModel(bst, filename=path)
        loaded = capi.LGBM_BoosterCreateFromModelfile(path)
        p2 = capi.LGBM_BoosterPredictForMat(loaded, X)
        np.testing.assert_allclose(p2, pred, atol=1e-5)
        imp = capi.LGBM_BoosterFeatureImportance(bst)
        assert imp.sum() > 0

    def test_csr_paths(self):
        X, y = _data(n=200)
        import scipy.sparse as sp
        S = sp.csr_matrix(X)
        params = "objective=binary num_leaves=7 min_data_in_leaf=5 verbose=-1"
        ds = capi.LGBM_DatasetCreateFromCSR(
            S.indptr, capi.C_API_DTYPE_INT32, S.indices, S.data,
            capi.C_API_DTYPE_FLOAT64, len(S.indptr), S.nnz, X.shape[1],
            parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        for _ in range(5):
            capi.LGBM_BoosterUpdateOneIter(bst)
        pred = capi.LGBM_BoosterPredictForCSR(
            bst, S.indptr, capi.C_API_DTYPE_INT32, S.indices, S.data,
            capi.C_API_DTYPE_FLOAT64, len(S.indptr), S.nnz, X.shape[1])
        dense_pred = capi.LGBM_BoosterPredictForMat(bst, X)
        np.testing.assert_allclose(pred, dense_pred, atol=1e-6)

    def test_custom_objective_and_eval(self):
        X, y = _data(n=200)
        params = ("objective=binary num_leaves=7 min_data_in_leaf=5 "
                  "verbose=-1 is_provide_training_metric=true "
                  "metric=binary_logloss")
        ds = capi.LGBM_DatasetCreateFromMat(X, parameters=params)
        capi.LGBM_DatasetSetField(ds, "label", y)
        bst = capi.LGBM_BoosterCreate(ds, params)
        capi.LGBM_BoosterUpdateOneIter(bst)
        evals = capi.LGBM_BoosterGetEval(bst, 0)
        assert evals and evals[0][0] == "binary_logloss"
        # custom gradients
        raw = np.asarray(capi.LGBM_BoosterPredictForMat(
            bst, X, predict_type=capi.C_API_PREDICT_RAW_SCORE))
        p = 1 / (1 + np.exp(-raw))
        capi.LGBM_BoosterUpdateOneIterCustom(bst, (p - y), p * (1 - p))
        assert capi.LGBM_BoosterGetCurrentIteration(bst) == 2
        capi.LGBM_BoosterRollbackOneIter(bst)
        assert capi.LGBM_BoosterGetCurrentIteration(bst) == 1


class TestLrbDriver:
    def test_windowed_retraining(self):
        """The fork's end-to-end loop on a synthetic zipf trace:
        per-window OPT labels, fresh boosters, FP/FN eval output
        (test.cpp:300-341)."""
        driver = LrbDriver(cache_size=1 << 16, window_size=500,
                           sample_size=400, cutoff=0.5, sampling=1,
                           result_file=open("/dev/null", "w"))
        for seq, oid, size, cost in synthetic_trace(1500):
            driver.process_request(seq, oid, size, cost)
        assert driver.window_index == 3
        assert driver.booster is not None
        r1, r2, r3 = driver.results
        # OPT labeled something cacheable in every window
        assert all(r["opt_obj_hit_ratio"] > 0 for r in driver.results)
        # windows after the first evaluate the previous model
        assert "fp_rate" in r2 and "fn_rate" in r2
        assert 0 <= r2["fp_rate"] <= 1 and 0 <= r2["fn_rate"] <= 1
        # the learned admission policy beats chance: error rates bounded
        assert r3["fp_rate"] + r3["fn_rate"] < 0.9
