"""User-facing python package tests.

Port of the reference acceptance suite
(reference: tests/python_package_test/test_engine.py:28-square,
test_basic.py, test_sklearn.py) against lightgbm_tpu's
Dataset/Booster/train/cv surface. Datasets are scaled down so the CPU
test backend stays fast; thresholds scale accordingly.
"""
import os
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _binary_data(n=400, f=10, seed=42):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 1.5 + X[:, 1] - 0.5 * X[:, 2]
    y = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _regression_data(n=400, f=8, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    return X, y


def _logloss(y, p):
    p = np.clip(p, 1e-15, 1 - 1e-15)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


class TestEngine:
    """test_engine.py ports."""

    def test_binary(self):
        # test_engine.py:28-48 (num_iteration in params wins)
        X, y = _binary_data()
        Xt, yt = _binary_data(seed=43)
        params = {"objective": "binary", "metric": "binary_logloss",
                  "verbose": -1, "num_iteration": 30}
        lgb_train = lgb.Dataset(X, y)
        lgb_eval = lgb.Dataset(Xt, yt, reference=lgb_train)
        evals_result = {}
        gbm = lgb.train(params, lgb_train, num_boost_round=20,
                        valid_sets=lgb_eval, verbose_eval=False,
                        evals_result=evals_result)
        ret = _logloss(yt, gbm.predict(Xt))
        assert ret < 0.35
        assert len(evals_result["valid_0"]["binary_logloss"]) == 30
        assert evals_result["valid_0"]["binary_logloss"][-1] == \
            pytest.approx(ret, abs=1e-4)

    def test_regression(self):
        # test_engine.py:75-93
        X, y = _regression_data()
        Xt, yt = _regression_data(seed=8)
        params = {"metric": "l2", "verbose": -1}
        lgb_train = lgb.Dataset(X, y)
        lgb_eval = lgb.Dataset(Xt, yt, reference=lgb_train)
        evals_result = {}
        gbm = lgb.train(params, lgb_train, num_boost_round=30,
                        valid_sets=lgb_eval, verbose_eval=False,
                        evals_result=evals_result)
        ret = float(np.mean((yt - gbm.predict(Xt)) ** 2))
        assert ret < 1.0
        assert evals_result["valid_0"]["l2"][-1] == \
            pytest.approx(ret, abs=1e-4)

    def test_multiclass(self):
        # test_engine.py:290-310
        rng = np.random.default_rng(0)
        n = 300
        y = rng.integers(0, 3, n).astype(np.float64)
        X = rng.normal(size=(n, 6))
        X[:, 0] += 2 * y
        X[:, 1] -= 2 * y
        params = {"objective": "multiclass", "metric": "multi_logloss",
                  "num_class": 3, "verbose": -1}
        lgb_train = lgb.Dataset(X, y)
        evals_result = {}
        gbm = lgb.train(params, lgb_train, num_boost_round=20,
                        valid_sets=lgb.Dataset(X, y, reference=lgb_train),
                        verbose_eval=False, evals_result=evals_result)
        pred = gbm.predict(X)
        assert pred.shape == (n, 3)
        assert (pred.argmax(axis=1) == y).mean() > 0.9
        assert evals_result["valid_0"]["multi_logloss"][-1] < 0.6

    def test_missing_value_handle(self):
        # test_engine.py:94-118: NaN rows learn their own leaf
        X = np.zeros((500, 1))
        y = np.zeros(500)
        rng = np.random.default_rng(3)
        trues = rng.choice(500, 100, replace=False)
        X[trues, 0] = np.nan
        y[trues] = 1
        params = {"metric": "l2", "verbose": -1,
                  "boost_from_average": False,
                  "min_data_in_leaf": 1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=20,
                        verbose_eval=False)
        ret = float(np.mean((y - gbm.predict(X)) ** 2))
        assert ret < 0.005

    def test_early_stopping(self):
        # test_engine.py:364-394
        X, y = _binary_data()
        Xt, yt = _binary_data(seed=99)
        params = {"objective": "binary", "metric": "binary_logloss",
                  "verbose": -1}
        lgb_train = lgb.Dataset(X, y)
        lgb_eval = lgb.Dataset(Xt, yt, reference=lgb_train)
        valid_set_name = "valid_set"
        # no early stopping without improvement stop
        gbm = lgb.train(params, lgb_train, num_boost_round=10,
                        valid_sets=lgb_eval, valid_names=valid_set_name,
                        verbose_eval=False, early_stopping_rounds=5)
        assert gbm.best_iteration > 0
        assert valid_set_name in gbm.best_score
        assert "binary_logloss" in gbm.best_score[valid_set_name]
        # early stopping should trigger well before 400 rounds
        gbm = lgb.train(params, lgb_train, num_boost_round=400,
                        valid_sets=lgb_eval, valid_names=valid_set_name,
                        verbose_eval=False, early_stopping_rounds=5)
        assert gbm.best_iteration < 400

    def test_continue_train(self):
        # test_engine.py:395-423: init_model continuation via file
        X, y = _regression_data()
        Xt, yt = _regression_data(seed=8)
        params = {"objective": "regression", "metric": "l1",
                  "verbose": -1}
        lgb_train = lgb.Dataset(X, y, free_raw_data=False)
        lgb_eval = lgb.Dataset(Xt, yt, reference=lgb_train,
                               free_raw_data=False)
        init_gbm = lgb.train(params, lgb_train, num_boost_round=10,
                             verbose_eval=False)
        model_name = "model.txt"
        init_gbm.save_model(model_name)
        try:
            evals_result = {}
            gbm = lgb.train(params, lgb_train, num_boost_round=20,
                            valid_sets=lgb_eval, verbose_eval=False,
                            evals_result=evals_result,
                            init_model="model.txt")
            ret = float(np.mean(np.abs(yt - (
                init_gbm.predict(Xt) + gbm.predict(Xt)))))
            assert ret < 0.6
            assert evals_result["valid_0"]["l1"][-1] == \
                pytest.approx(ret, abs=1e-4)
            for l1 in evals_result["valid_0"]["l1"]:
                assert l1 < 2.0
        finally:
            os.remove(model_name)

    def test_cv(self):
        # test_engine.py:447-496 (subset)
        X, y = _regression_data()
        params = {"verbose": -1}
        lgb_train = lgb.Dataset(X, y, free_raw_data=False)
        # shuffle = False, override metric in params (2 folds / 5
        # rounds: every booster pays a full XLA compile on this
        # backend, so fold count sets the test's wall time — the fold
        # mechanics under test are fold-count-invariant)
        params_with_metric = {"metric": "l2", "verbose": -1}
        cv_res = lgb.cv(params_with_metric, lgb_train,
                        num_boost_round=5, nfold=2, stratified=False,
                        shuffle=False, metrics="l1", verbose_eval=False)
        assert "l1-mean" in cv_res
        assert "l2-mean" not in cv_res
        assert len(cv_res["l1-mean"]) == 5
        # shuffle = True, callbacks
        cv_res = lgb.cv(params, lgb_train, num_boost_round=5, nfold=2,
                        stratified=False, shuffle=True, metrics="l1",
                        verbose_eval=False,
                        callbacks=[lgb.reset_parameter(
                            learning_rate=lambda i: 0.1 - 0.001 * i)])
        assert "l1-mean" in cv_res
        assert len(cv_res["l1-mean"]) == 5
        # self defined folds
        from sklearn.model_selection import KFold
        folds = KFold(n_splits=2)
        cv_res = lgb.cv(params_with_metric, lgb_train, num_boost_round=5,
                        folds=folds, verbose_eval=False)
        assert "l2-mean" in cv_res
        # lambdarank (group-aware folds)
        rng = np.random.default_rng(1)
        q = np.full(20, 15)
        Xr = rng.normal(size=(300, 5))
        yr = rng.integers(0, 4, 300).astype(np.float64)
        params_rank = {"objective": "lambdarank", "verbose": -1,
                       "eval_at": [3]}
        lgb_rank = lgb.Dataset(Xr, yr, group=q, free_raw_data=False)
        cv_res = lgb.cv(params_rank, lgb_rank, num_boost_round=4,
                        nfold=2, metrics="ndcg", verbose_eval=False)
        assert "ndcg@3-mean" in cv_res
        assert len(cv_res["ndcg@3-mean"]) == 4

    def test_feature_name(self):
        # test_engine.py:497-509
        X, y = _regression_data()
        params = {"verbose": -1}
        lgb_train = lgb.Dataset(X, y)
        feature_names = [f"f_{i}" for i in range(X.shape[1])]
        gbm = lgb.train(params, lgb_train, num_boost_round=3,
                        feature_name=feature_names, verbose_eval=False)
        assert feature_names == gbm.feature_name()
        # no exception with non-ascii
        feature_names = ["F_零", "F_一", "F_二", "F_三", "F_四",
                         "F_五", "F_六", "F_七"]
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=3,
                        feature_name=feature_names, verbose_eval=False)
        assert feature_names == gbm.feature_name()

    def test_save_load_copy_pickle(self):
        # test_engine.py:510-541
        X, y = _regression_data()
        params = {"objective": "regression", "metric": "l2",
                  "verbose": -1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=10,
                        verbose_eval=False)
        ret_origin = float(np.mean((y - gbm.predict(X)) ** 2))

        gbm.save_model("model_pkl.txt")
        try:
            for option in range(4):
                if option == 0:
                    model = lgb.Booster(model_file="model_pkl.txt")
                elif option == 1:
                    model = lgb.Booster(
                        model_str=gbm.model_to_string())
                elif option == 2:
                    model = pickle.loads(pickle.dumps(gbm))
                else:
                    import copy
                    model = copy.deepcopy(gbm)
                ret = float(np.mean((y - model.predict(X)) ** 2))
                assert ret_origin == pytest.approx(ret, abs=1e-5)
        finally:
            os.remove("model_pkl.txt")

    def test_contribs(self):
        # test_engine.py:598-612: SHAP sums to raw prediction
        X, y = _binary_data(n=200)
        params = {"objective": "binary", "verbose": -1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=10,
                        verbose_eval=False)
        contribs = gbm.predict(X, pred_contrib=True)
        raw = gbm.predict(X, raw_score=True)
        assert contribs.shape == (X.shape[0], X.shape[1] + 1)
        np.testing.assert_allclose(contribs.sum(axis=1), raw,
                                   rtol=1e-5, atol=1e-5)

    def test_constant_features(self):
        # test_engine.py:753-804: all-constant features -> prior
        y = np.array([0.0, 10.0, 0.0, 10.0])
        X = np.zeros((4, 2))
        params = {"objective": "regression_l2", "min_data_in_leaf": 1,
                  "min_data_in_bin": 1, "boost_from_average": True,
                  "verbose": -1}
        gbm = lgb.train(params, lgb.Dataset(X, y), num_boost_round=2,
                        verbose_eval=False)
        np.testing.assert_allclose(gbm.predict(X), np.full(4, 5.0),
                                   atol=1e-5)

    def test_fobj_feval(self):
        # custom objective + custom metric (test_engine.py advanced)
        X, y = _regression_data()

        def loglikelihood(preds, train_data):
            labels = train_data.get_label()
            grad = preds - labels
            hess = np.ones_like(preds)
            return grad, hess

        def custom_l2(preds, train_data):
            labels = train_data.get_label()
            return "custom_l2", float(np.mean((preds - labels) ** 2)), \
                False

        params = {"objective": "none", "verbose": -1,
                  "boost_from_average": False}
        evals_result = {}
        lgb_train = lgb.Dataset(X, y, free_raw_data=False)
        gbm = lgb.train(params, lgb_train, num_boost_round=15,
                        valid_sets=[lgb_train], valid_names=["train"],
                        fobj=loglikelihood, feval=custom_l2,
                        verbose_eval=False, evals_result=evals_result)
        assert evals_result["train"]["custom_l2"][-1] < \
            evals_result["train"]["custom_l2"][0]

    def test_reset_parameter_callback(self):
        X, y = _regression_data()
        lrs = []

        def spy(env):
            lrs.append(env.params.get("learning_rate"))
        gbm = lgb.train({"verbose": -1, "metric": "l2"},
                        lgb.Dataset(X, y), num_boost_round=5,
                        learning_rates=lambda i: 0.2 * (0.5 ** i),
                        callbacks=[spy], verbose_eval=False)
        assert gbm.current_iteration() == 5


class TestBasic:
    """test_basic.py ports."""

    def test_dataset_fields(self):
        X, y = _binary_data(n=100)
        w = np.linspace(0.5, 1.5, 100)
        ds = lgb.Dataset(X, label=y, weight=w, free_raw_data=False)
        ds.construct()
        np.testing.assert_allclose(ds.get_label(), y, rtol=1e-6)
        np.testing.assert_allclose(ds.get_weight(), w, rtol=1e-6)
        assert ds.num_data() == 100
        assert ds.num_feature() == X.shape[1]
        assert ds.get_field("label") is ds.get_label()

    def test_save_binary_roundtrip(self, tmp_path):
        X, y = _binary_data(n=100)
        ds = lgb.Dataset(X, label=y)
        path = str(tmp_path / "ds.bin")
        ds.save_binary(path)
        ds2 = lgb.Dataset(path)
        ds2.construct()
        assert ds2.num_data() == 100
        np.testing.assert_allclose(ds2.get_label(), y.astype(np.float32))
        gbm = lgb.train({"objective": "binary", "verbose": -1}, ds2,
                        num_boost_round=3, verbose_eval=False)
        assert gbm.current_iteration() == 3

    def test_subset(self):
        X, y = _binary_data(n=200)
        ds = lgb.Dataset(X, label=y, free_raw_data=False)
        sub = ds.subset(np.arange(50))
        sub.construct()
        assert sub.num_data() == 50

    def test_pandas_dataframe(self):
        pd = pytest.importorskip("pandas")
        X, y = _binary_data(n=150)
        df = pd.DataFrame(X, columns=[f"c{i}" for i in range(X.shape[1])])
        df["cat"] = pd.Categorical(
            np.random.default_rng(0).integers(0, 3, 150))
        ds = lgb.Dataset(df, label=pd.Series(y))
        gbm = lgb.train({"objective": "binary", "verbose": -1}, ds,
                        num_boost_round=3, verbose_eval=False)
        assert gbm.feature_name()[:2] == ["c0", "c1"]
        pred = gbm.predict(df)
        assert pred.shape == (150,)


class TestSklearn:
    """test_sklearn.py ports."""

    def test_classifier(self):
        X, y = _binary_data()
        clf = lgb.LGBMClassifier(n_estimators=10, verbose=-1)
        clf.fit(X, y.astype(int), verbose=False)
        assert (clf.predict(X) == y).mean() > 0.9
        proba = clf.predict_proba(X)
        assert proba.shape == (len(y), 2)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
        assert len(clf.feature_importances_) == X.shape[1]

    def test_regressor(self):
        X, y = _regression_data()
        reg = lgb.LGBMRegressor(n_estimators=20, verbose=-1)
        reg.fit(X, y, verbose=False)
        assert reg.score(X, y) > 0.8

    def test_multiclass_sklearn(self):
        rng = np.random.default_rng(0)
        y = rng.integers(0, 3, 300)
        X = rng.normal(size=(300, 5))
        X[:, 0] += 2 * y
        clf = lgb.LGBMClassifier(n_estimators=10, verbose=-1)
        clf.fit(X, y, verbose=False)
        assert clf.n_classes_ == 3
        assert clf.predict_proba(X).shape == (300, 3)
        assert (clf.predict(X) == y).mean() > 0.8

    def test_ranker(self):
        rng = np.random.default_rng(5)
        X = rng.normal(size=(200, 5))
        y = rng.integers(0, 4, 200)
        group = np.full(10, 20)
        rk = lgb.LGBMRanker(n_estimators=5, verbose=-1)
        rk.fit(X, y, group=group, verbose=False)
        assert rk.predict(X).shape == (200,)

    def test_early_stopping_sklearn(self):
        X, y = _binary_data()
        Xt, yt = _binary_data(seed=11)
        clf = lgb.LGBMClassifier(n_estimators=200, verbose=-1)
        clf.fit(X, y.astype(int), eval_set=[(Xt, yt.astype(int))],
                eval_metric="binary_logloss", early_stopping_rounds=5,
                verbose=False)
        assert clf.best_iteration_ is not None
        assert clf.best_iteration_ < 200

    def test_sklearn_clone_and_grid(self):
        from sklearn.base import clone
        est = lgb.LGBMRegressor(n_estimators=5, num_leaves=7)
        est2 = clone(est)
        assert est2.get_params()["num_leaves"] == 7


class TestPlotting:
    """plotting.py ports (reference test_plotting.py)."""

    def test_plot_importance_and_metric(self, tmp_path):
        mpl = pytest.importorskip("matplotlib")
        mpl.use("Agg")
        X, y = _binary_data(n=200)
        ev = {}
        gbm = lgb.train({"objective": "binary",
                         "metric": "binary_logloss", "verbose": -1},
                        lgb.Dataset(X, y), 8,
                        valid_sets=lgb.Dataset(X, y, reference=None),
                        verbose_eval=False, evals_result=ev)
        ax = lgb.plot_importance(gbm)
        assert ax.get_title() == "Feature importance"
        assert len(ax.patches) > 0
        ax2 = lgb.plot_metric(ev)
        assert ax2.get_title() == "Metric during training"
        ax3 = lgb.plot_tree(gbm, tree_index=0)
        assert ax3.get_title() == "Tree 0"


def test_quantized_hist_training_quality():
    """tpu_quantized_hist through the user API: the int8 quantization
    path (XLA-fallback semantics identical to the TPU kernel) reaches
    the same quality as exact histograms."""
    import lightgbm_tpu as lgb
    from conftest import make_binary

    X, y = make_binary(n=2000, f=8, seed=41)
    out = {}
    for quant in (False, True):
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "metric": "auc",
                         "num_leaves": 15, "max_bin": 63,
                         "min_data_in_leaf": 5, "verbose": -1,
                         "tpu_quantized_hist": quant}, ds, 30)
        from conftest import rank_auc
        out[quant] = rank_auc(y, bst.predict(X))
    assert out[True] == pytest.approx(out[False], abs=0.01)
    assert out[True] > 0.97


def test_create_tree_digraph():
    """Reference plotting.py:311-381 — a graphviz Digraph with split
    and leaf nodes for one tree."""
    pytest.importorskip("graphviz")
    from conftest import make_binary

    X, y = make_binary(n=800, f=5, seed=61)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params), 5,
                    verbose_eval=False, keep_training_booster=True)
    g = lgb.create_tree_digraph(
        bst, tree_index=1,
        show_info=["split_gain", "leaf_count", "internal_count"])
    src = g.source
    assert "split" in src and "leaf" in src
    assert "gain:" in src and "count:" in src
    with pytest.raises(IndexError):
        lgb.create_tree_digraph(bst, tree_index=99)
