"""Extended golden corpus: BOTH interop directions against the real
reference engine, across 10 configs.

tests/data/golden2/* was produced by the reference engine itself
(lib_lightgbm.so rebuilt from /root/reference, driven through its C API
by a small harness — train from CSV, SaveModel, PredictForFile). For
each case:

  g2_<name>_model.txt        model TRAINED BY THE REFERENCE
  g2_<name>_pred.bin         reference predictions on X
  g2_<name>_ours_model.txt   model trained by THIS engine (frozen)
  g2_<name>_ours_refpred.bin REFERENCE predictions on OUR model file

Forward: we load the reference's model and must reproduce its
predictions. Reverse: the reference loaded OUR model file and
predicted; our predictions on the same frozen model must match what
the reference computed from it. Together these pin byte-level model
interop over binary, L2/L1 regression (leaf renewal), multiclass
softmax, categorical bitset splits, and DART/GOSS boosting (per-tree
shrinkage bookkeeping), lambdarank with .query sidecars, and
row-weighted training (.weight sidecar). The "contin" case goes further: OUR engine
CONTINUED training from a reference-trained model and the reference
engine then read the mixed-provenance file — its predictions must
match ours. This corpus caught a shape-dependent bf16
matmul-precision bug in the stacked predictor AND a CLI
continued-training semantics divergence (num_iterations counts
additional rounds, gbdt.cpp:248).
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

DATA = os.path.join(os.path.dirname(__file__), "data", "golden2")

CASES = ["binary", "regl2", "regl1", "multic", "catbin",
         "dart", "goss", "contin", "rank", "wbin"]
# reverse-only cases: models trained by THIS engine's approximation
# tiers (int8 count-proxy histograms; 4-bit packed bins) — the
# reference engine can't train these modes, but it must READ the
# model files and reproduce our predictions (it does, to ~1e-7)
REVERSE_ONLY = ["proxy", "pkd4"]


def _inputs(name):
    # the reverse-only tier cases share one dataset (single fixture,
    # stored under the "proxy" name)
    src = "proxy" if name in REVERSE_ONLY else name
    X = np.fromfile(os.path.join(DATA, f"g2_{src}_X.bin"),
                    np.float64).reshape(600, 8)
    y = np.fromfile(os.path.join(DATA, f"g2_{src}_y.bin"), np.float32)
    return X, y


def _pred_shape(pred, n):
    return pred.reshape(n, -1).squeeze()


@pytest.mark.parametrize("name", CASES)
def test_forward_reference_model_predicts_identically(name):
    X, _ = _inputs(name)
    ref = np.fromfile(os.path.join(DATA, f"g2_{name}_pred.bin"),
                      np.float64)
    bst = lgb.Booster(
        model_file=os.path.join(DATA, f"g2_{name}_model.txt"))
    ours = np.asarray(bst.predict(X))
    np.testing.assert_allclose(
        ours.reshape(-1), ref.reshape(-1), atol=1e-5,
        err_msg=f"{name}: reference-trained model predictions diverge")


@pytest.mark.parametrize("name", CASES + REVERSE_ONLY)
def test_reverse_reference_reads_our_model_identically(name):
    X, _ = _inputs(name)
    ref_on_ours = np.fromfile(
        os.path.join(DATA, f"g2_{name}_ours_refpred.bin"), np.float64)
    bst = lgb.Booster(
        model_file=os.path.join(DATA, f"g2_{name}_ours_model.txt"))
    ours = np.asarray(bst.predict(X))
    # reverse-only tier cases measured at ~9e-8 agreement when minted;
    # assert an order of magnitude of headroom
    atol = 1e-6 if name in REVERSE_ONLY else 1e-5
    np.testing.assert_allclose(
        ours.reshape(-1), ref_on_ours.reshape(-1), atol=atol,
        err_msg=f"{name}: the reference engine read our model file and "
                f"computed different predictions")
