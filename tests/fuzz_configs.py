"""Randomized config fuzz harness (NOT collected by pytest — run
directly): train/predict/save/load across random parameter
combinations, asserting no crash, finite predictions, and exact
save->load parity.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python tests/fuzz_configs.py

Covers objective x boosting x bagging x feature_fraction x depth x
regularization x EFB x quantized-hist x tree_learner interactions that
the targeted test suite samples only pointwise. ~1 min/case on one CPU
core (XLA compiles dominate).
"""
import os, sys, traceback
os.environ["JAX_PLATFORMS"] = "cpu"; os.environ["LGBM_TPU_PLATFORM"] = "cpu"
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
import numpy as np
import lightgbm_tpu as lgb

N_CASES = 70
fails = []

for case in range(N_CASES):
    r = np.random.default_rng(case)
    n = int(r.integers(300, 1200))
    f = int(r.integers(3, 10))
    X = r.normal(size=(n, f))
    has_cat = r.random() < 0.3
    if has_cat:
        X[:, 0] = r.integers(0, int(r.integers(3, 20)), n)
    obj = r.choice(["binary", "regression", "regression_l1", "huber",
                    "multiclass", "poisson", "quantile"])
    K = int(r.integers(2, 5)) if obj == "multiclass" else 1
    if obj == "binary":
        y = (X[:, 1] > 0).astype(np.float64)
    elif obj == "multiclass":
        y = np.clip(np.round(np.abs(X[:, 1]) * K / 2), 0, K - 1)
    elif obj == "poisson":
        y = np.round(np.abs(X[:, 1]) * 2)
    else:
        y = X[:, 1] * 1.5 + 0.3 * r.normal(size=n)
    params = {
        "objective": obj, "verbose": -1,
        "num_leaves": int(r.integers(3, 32)),
        "max_bin": int(r.choice([15, 63, 255])),
        "min_data_in_leaf": int(r.integers(1, 30)),
        "learning_rate": float(r.uniform(0.05, 0.4)),
        "max_depth": int(r.choice([-1, 3, 6])),
        "lambda_l1": float(r.choice([0.0, 0.5])),
        "lambda_l2": float(r.choice([0.0, 1.0])),
        "min_gain_to_split": float(r.choice([0.0, 0.1])),
        "boosting": str(r.choice(["gbdt", "gbdt", "dart", "goss"])),
        "bagging_fraction": float(r.choice([1.0, 0.7])),
        "bagging_freq": int(r.choice([0, 1, 3])),
        "feature_fraction": float(r.choice([1.0, 0.8])),
        "enable_bundle": bool(r.random() < 0.3),
        "tpu_quantized_hist": bool(r.random() < 0.3),
        # count-proxy / 4-bit packed tiers: auto vs forced-off (they
        # auto-engage under quant + serial/data + no-EFB/cat gates,
        # packed additionally at max_bin <= 16)
        "tpu_count_proxy": int(r.choice([-1, 0])),
        "tpu_packed_bins": int(r.choice([-1, 0])),
    }
    if obj == "multiclass":
        params["num_class"] = K
    if has_cat:
        params["categorical_feature"] = "0"
    if params["boosting"] == "goss":
        params["bagging_freq"] = 0
        params["bagging_fraction"] = 1.0
    if r.random() < 0.25:
        params["tree_learner"] = str(r.choice(["data", "voting"]))
    nrounds = int(r.integers(3, 12))
    tag = f"case{case} {obj} {params['boosting']} " \
          f"leaves={params['num_leaves']} bin={params['max_bin']} " \
          f"tl={params.get('tree_learner', 'serial')} " \
          f"efb={params['enable_bundle']} q={params['tpu_quantized_hist']}"
    try:
        ds = lgb.Dataset(X, label=y, params=params)
        bst = lgb.train(params, ds, nrounds, verbose_eval=False,
                        keep_training_booster=True)
        p = np.asarray(bst.predict(X))
        assert np.isfinite(p).all(), "non-finite predictions"
        s = bst.model_to_string()
        p2 = np.asarray(lgb.Booster(model_str=s).predict(X))
        assert np.abs(p - p2).max() < 1e-5, \
            f"save/load diff {np.abs(p - p2).max()}"
        lf = bst.predict(X[:64], pred_leaf=True)
        assert np.isfinite(lf).all()
    except Exception as e:
        fails.append((tag, repr(e)))
        print(f"FAIL {tag}: {e}", flush=True)
        traceback.print_exc()
    else:
        print(f"ok   {tag}", flush=True)

print(f"\n{N_CASES - len(fails)}/{N_CASES} passed", flush=True)
for t, e in fails:
    print("FAILED:", t, e)
sys.exit(1 if fails else 0)
