"""Real multi-process training + elastic resume (pytest -m multihost).

The multichip suite proves the sharded learners on a VIRTUAL mesh;
this suite proves the runtime that makes the same programs span real
OS processes (lightgbm_tpu/parallel/cluster.py + elastic.py):

- unit layer: rank-naming error mapping, the DeadlineGuard stall
  watchdog, world-invariant shard geometry (the property that makes
  elastic resume shape-preserving), host-block tiling, and the
  multihost ingest's bit-parity with the single-process sharded path;
- process layer: a 2-process ``jax.distributed`` smoke over localhost
  (both ranks must finish and agree on the trained model hash), and
  the no-hang drill — SIGKILL one rank mid-collective, the survivor
  must exit with a rank-naming error within the configured deadline;
- the full elastic drill (slow): train on 2 processes, kill one,
  resume the survivor on a 1-process mesh from the latest checkpoint,
  final model bit-identical to the uninterrupted run
  (parallel/elastic.py run_drill — the MULTICHIP_r06 artifact).
"""
import json
import os
import sys
import time

import numpy as np
import pytest

from conftest import TEST_PARAMS

from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel import cluster, elastic

pytestmark = pytest.mark.multihost

REPO = os.path.join(os.path.dirname(__file__), os.pardir)
sys.path.insert(0, os.path.join(REPO, "tools"))


def _make_cfg(**kw):
    full = dict(TEST_PARAMS)
    full.update({"objective": "binary"})
    full.update(kw)
    return Config().set(full)


# ---------------------------------------------------------------------------
# cluster units (in-process)
# ---------------------------------------------------------------------------

def test_explain_names_ranks_from_task_strings():
    e = RuntimeError(
        "DEADLINE_EXCEEDED: Barrier timed out. Id: x::0.\n"
        "The first task at the barrier: "
        "/job:jax_worker/replica:0/task:0. Some timed out task names:\n"
        "/job:jax_worker/replica:0/task:2\n")
    err = cluster.explain_collective_error(e, what="barrier 'sync'")
    assert isinstance(err, cluster.PeerLostError)
    assert err.ranks == [2]
    assert "rank 2" in str(err)
    assert "checkpoint" in str(err)          # actionable next step
    # one line: the promise is a rank-naming ERROR, not a traceback
    assert "\n" not in str(err).strip()


def test_explain_classifies_gloo_reset_without_task_names():
    err = cluster.explain_collective_error(RuntimeError(
        "FAILED_PRECONDITION: Buffer Definition Event: Gloo "
        "all-reduce failed: Read error [127.0.0.1]:30356: "
        "Connection reset by peer"), what="training")
    assert isinstance(err, cluster.PeerLostError)
    assert "resume" in str(err)


def test_explain_leaves_genuine_bugs_alone():
    assert cluster.explain_collective_error(
        ValueError("shapes (3,) and (4,) not aligned")) is None
    assert cluster.explain_collective_error(
        KeyError("feature_fraction")) is None


def test_deadline_guard_fires_names_rank_and_respects_progress():
    fired = []
    with cluster.DeadlineGuard(deadline=0.5, what="unit collective",
                               on_stall=fired.append,
                               probe=lambda: [1],
                               poll_s=0.05) as g:
        cluster.tick("iteration 3")
        time.sleep(1.1)
    assert g.fired
    err = fired[0]
    assert isinstance(err, cluster.PeerLostError)
    assert err.ranks == [1]
    assert "rank 1" in str(err) and "iteration 3" in str(err)
    assert "unit collective" in str(err)

    # a live tick stream keeps the guard quiet
    with cluster.DeadlineGuard(deadline=0.5, on_stall=fired.append,
                               probe=lambda: [0], poll_s=0.05) as g2:
        for _ in range(14):
            cluster.tick("hot loop")
            time.sleep(0.05)
    assert not g2.fired

    # coordinator-gone probe (None): suspect is rank 0
    dead = []
    with cluster.DeadlineGuard(deadline=0.3, on_stall=dead.append,
                               probe=lambda: None, poll_s=0.05):
        cluster.tick("x")
        time.sleep(0.8)
    assert dead and dead[0].ranks == [0]
    assert "coordinator" in str(dead[0])

    # all peers ALIVE (probe returns []): a slow step must NOT read
    # as a cluster death — the guard warns and keeps waiting
    alive = []
    with cluster.DeadlineGuard(deadline=0.2, on_stall=alive.append,
                               probe=lambda: [], poll_s=0.05) as g3:
        cluster.tick("slow compile")
        time.sleep(0.7)
    assert not g3.fired and alive == []


def test_barrier_is_noop_single_process():
    cluster.barrier("unit-barrier", timeout_s=0.05)   # must not block


def test_shard_geometry_world_invariance_and_rebucket():
    """At pow2-friendly shapes, bucket_rows over shard_align_unit
    yields the SAME score width for every world size — a world change
    is then purely a re-sharding, and resume is verbatim. At shapes
    where the alignment units do NOT divide the bucket the widths
    differ — exactly the case checkpoint restore's elastic re-shard
    path (utils/checkpoint.py) exists for."""
    from lightgbm_tpu.ops import step_cache as sc
    for n in (2048, 4096, 1 << 20, 11_010_048):
        widths = {sc.bucket_rows(n, sc.shard_align_unit(n, D, 16384),
                                 policy=-1)
                  for D in (1, 2, 4, 8)}
        assert len(widths) == 1, (n, widths)
    # a width-changing transition (the re-shard case): TPU-serial
    # chunk alignment vs a 2-chip mesh at an awkward n
    n = 100_000
    w1 = sc.bucket_rows(n, sc.shard_align_unit(n, 1, 16384), policy=-1)
    w2 = sc.bucket_rows(n, sc.shard_align_unit(n, 2, 16384), policy=-1)
    assert w1 != w2
    assert min(w1, w2) >= n      # both still cover every real row


def test_host_row_block_tiles_the_matrix():
    from lightgbm_tpu.io.ingest import host_row_block, shard_width
    from lightgbm_tpu.parallel.learners import make_mesh
    mesh = make_mesh(8)
    n = 1000
    lo, hi, S = host_row_block(n, mesh)
    # single process: this host owns every block
    assert (lo, hi) == (0, n)
    assert S == shard_width(n, 8, 0)
    assert 8 * S >= n


def test_bin_matrix_multihost_matches_sharded_single_process():
    """The multihost assembly maps the SAME device->row-block layout
    as bin_matrix_sharded — on one process the two must be bit-equal,
    which is what makes a W-process mesh reproduce the virtual mesh's
    (proven) layout."""
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.io.ingest import DeviceBinner
    from lightgbm_tpu.parallel.learners import make_mesh

    r = np.random.default_rng(5)
    X = r.normal(size=(1024, 6))
    X[::17, 2] = np.nan
    cfg = _make_cfg(tpu_ingest=1)
    ds = TpuDataset(cfg).construct_from_matrix(
        X, Metadata(label=(X[:, 0] > 0).astype(np.float32)))
    binner = DeviceBinner(ds.mappers, ds.used_feature_map, cfg,
                          X.dtype)
    mesh = make_mesh(8)
    a = binner.bin_matrix_sharded(X, mesh)
    b = binner.bin_matrix_multihost(X, mesh, X.shape[0], 0)
    assert a.shape == b.shape
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # a rank whose rows do not cover its devices' blocks is refused
    # with an actionable error, never mis-assembled
    with pytest.raises(ValueError, match="host_row_block"):
        binner.bin_matrix_multihost(X[:100], mesh, X.shape[0], 0)


def test_construct_multihost_single_process_matches_reference():
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.io.distributed import DistributedLoader
    from lightgbm_tpu.parallel.learners import make_mesh

    r = np.random.default_rng(9)
    X = r.normal(size=(600, 5))
    y = (X[:, 0] > 0).astype(np.float32)
    cfg = _make_cfg(tpu_ingest=1, tree_learner="data")
    mesh = make_mesh(8)
    ds = DistributedLoader(cfg).construct_multihost(
        X, Metadata(label=y), n_global=600, row_start=0, mesh=mesh)
    ref = TpuDataset(_make_cfg()).construct_from_matrix(
        X, Metadata(label=y))
    assert [m.feature_info() for m in ds.mappers] == \
        [m.feature_info() for m in ref.mappers]
    assert ds.num_data == 600
    got = np.asarray(ds.bins_t_dev)[:, :600].T
    np.testing.assert_array_equal(got, ref.host_bins().astype(got.dtype))


def test_strip_volatile_model_text():
    a = ("tree\nTree=0\nstuff\n\nparameters:\n"
         "[tpu_checkpoint_dir: /a/ckpt]\nend of parameters\ntail\n")
    b = a.replace("/a/ckpt", "/c/ckpt")
    assert a != b
    assert elastic._strip_volatile(a) == elastic._strip_volatile(b)
    # tree bytes still covered
    c = a.replace("Tree=0", "Tree=1")
    assert elastic._strip_volatile(a) != elastic._strip_volatile(c)


def test_retry_classifier_knows_dcn_strings():
    from lightgbm_tpu.utils import retry

    class E(Exception):
        pass

    for msg in (
            "failed to connect to all addresses; last error: "
            "UNKNOWN: Connection refused",
            "DEADLINE_EXCEEDED: Barrier timed out. Id: init::0",
            "UNAVAILABLE: Task /job:jax_worker/replica:0/task:1 "
            "heartbeat timeout",
            "INTERNAL: Coordination service has been shut down"):
        assert retry.is_transient(E(msg)), msg
    assert not retry.is_transient(E("Unknown parameter: learning_rat"))


# ---------------------------------------------------------------------------
# real processes over localhost
# ---------------------------------------------------------------------------

_SKIP_SPAWN = bool(os.environ.get("LGBM_TPU_SKIP_MULTIHOST"))


@pytest.mark.skipif(_SKIP_SPAWN, reason="LGBM_TPU_SKIP_MULTIHOST set")
def test_two_process_smoke(tmp_path):
    """2 REAL jax.distributed processes train one sharded model: both
    ranks finish, agree on the model hash bit-for-bit, and each
    ingested exactly its own contiguous host block."""
    out = elastic.run_two_process(str(tmp_path), n=768, iterations=3)
    r0, r1 = out["rank_results"]
    assert r0["model_sha"] == r1["model_sha"]
    assert [r0["host_row_block"], r1["host_row_block"]] == \
        [[0, 384], [384, 768]]
    assert r0["ingest_rows_local"] == r1["ingest_rows_local"] == 384
    assert r0["iterations"] == 3
    assert out["result"]["train_auc"] > 0.9


@pytest.mark.skipif(_SKIP_SPAWN, reason="LGBM_TPU_SKIP_MULTIHOST set")
def test_peer_kill_names_rank_and_never_hangs(tmp_path):
    """SIGKILL rank 1 mid-training: rank 0 must exit EXIT_PEER_LOST
    within the collective deadline, with ONE line naming rank 1 — the
    no-hang guarantee, measured on real processes."""
    deadline_s = 15.0
    spec = {
        "seed": 0, "n": 512, "f": 6,
        "params": {"num_iterations": 6,
                   "tpu_collective_timeout_s": deadline_s},
        "out": str(tmp_path / "result.json"),
        "checkpoint_dir": str(tmp_path / "ckpt"),
    }
    spec_path = str(tmp_path / "spec.json")
    with open(spec_path, "w") as fh:
        json.dump(spec, fh)
    procs = elastic.launch_workers(
        spec_path, 2, log_dir=str(tmp_path), fault_rank=1,
        faults="train.iter@3:kill")
    # the victim dies by SIGKILL
    rc1 = procs[1].wait(timeout=240)
    assert rc1 == -9, rc1
    t0 = time.monotonic()
    # the survivor must exit WITHIN the deadline (+ probe/IO slack) —
    # a hang here is exactly the failure mode this layer removes
    rc0 = procs[0].wait(timeout=deadline_s + 30)
    waited = time.monotonic() - t0
    assert rc0 == cluster.EXIT_PEER_LOST, rc0
    assert waited < deadline_s + 15, waited
    surv = json.loads((tmp_path / "result.json.rank0").read_text())
    assert surv["peer_lost"] is True
    assert surv["dead_ranks"] == [1]
    assert "rank 1" in surv["error"]
    assert "checkpoint" in surv["error"]
    # checkpoints survived for the resume that would follow
    from lightgbm_tpu.utils import checkpoint as ckpt
    assert ckpt.list_checkpoints(str(tmp_path / "ckpt"))


@pytest.mark.slow
@pytest.mark.skipif(_SKIP_SPAWN, reason="LGBM_TPU_SKIP_MULTIHOST set")
def test_elastic_drill_end_to_end(tmp_path):
    """The full preemption drill: uninterrupted 2-process run, killed
    2-process run, 1-process resume — final model bit-identical; the
    artifact passes the regression gate."""
    out = elastic.run_drill(str(tmp_path), n=2048, iterations=8,
                            kill_at=5, collective_timeout_s=20)
    assert out["model_parity"] is True
    assert out["kill"]["survivor_named_ranks"] == [1]
    assert out["kill"]["survivor_exit_code"] == cluster.EXIT_PEER_LOST
    assert out["resume"]["from_iteration"] == 4
    assert out["per_host_ingest_rows"] == [1024, 1024]

    import check_bench_regression as cbr
    schema, regressions, _ = cbr.check_multichip_drill(out)
    assert schema == [] and regressions == []


# ---------------------------------------------------------------------------
# (PR16) elastic autoscale at window boundaries
# ---------------------------------------------------------------------------

def test_scale_signal_roundtrip_and_garbage():
    """Single-process: the scale signal rides the env twin of the
    coordinator KV — post/poll/clear roundtrip, and unparsable or
    nonsensical targets read as 'no signal'."""
    cluster.clear_scale_signal()
    try:
        assert cluster.poll_scale_signal() is None
        cluster.post_scale_signal(4)
        assert cluster.poll_scale_signal() == 4
        cluster.clear_scale_signal()
        assert cluster.poll_scale_signal() is None
        os.environ[cluster.ENV_TARGET_WORLD] = "not-a-world"
        assert cluster.poll_scale_signal() is None
        os.environ[cluster.ENV_TARGET_WORLD] = "0"
        assert cluster.poll_scale_signal() is None
    finally:
        cluster.clear_scale_signal()


def test_autoscale_smoke_grows_at_window_boundary(tmp_path):
    """In-process autoscale smoke: one scheduled grow (virtual world
    2 -> 4 over the 8-device mesh) lands exactly at the window
    boundary via checkpoint + re-shard + resume, without leaving the
    process. Full parity is the slow drill's job."""
    from lightgbm_tpu.obs import registry as obs
    r0 = int(obs.counter("elastic/reshard_total").value)
    out = elastic.train_autoscale(str(tmp_path), n=512, iterations=4,
                                  window=2, start_world=2,
                                  schedule={2: 4})
    assert out["worlds"] == [2, 4]
    assert out["reshards"] == 1
    assert out["iterations"] == 4
    assert "tree" in out["model_text"]
    assert int(obs.counter("elastic/reshard_total").value) - r0 == 1


@pytest.mark.slow
def test_autoscale_grow_shrink_drill_bit_identical(tmp_path):
    """The acceptance drill: grow 2 -> 4 then shrink 4 -> 2 at window
    boundaries, final model BIT-identical to an uninterrupted
    fixed-world run — no process restart anywhere."""
    out = elastic.run_autoscale_drill(str(tmp_path), n=1024,
                                      iterations=9, window=3,
                                      worlds=(2, 4, 2))
    assert out["model_parity"] is True
    assert out["parity_kind"] == "bit_identical"
    assert out["reshard_total"] == 2
    assert out["worlds"] == [2, 4, 2]
