"""Out-of-core disk->device ingest (pytest -m ooc).

The promise: ``tpu_out_of_core=1`` routes a file load through the
two-round streaming pass (io/loader.py _load_two_round) so the [F, N]
bin matrix assembles from bounded row blocks — BIT-identical to the
in-memory loader on every route (host bins, single-device device
stream, row-sharded device stream, libsvm), with peak host memory
bounded by the block size instead of N. ``tpu_ooc_block_rows`` sizes
the blocks; ``tpu_out_of_core=0`` pins the host-bins fallback inside
two_round. ooc/* counters account for the streamed work.
"""
import numpy as np
import pytest

from conftest import TEST_PARAMS, make_binary

pytestmark = pytest.mark.ooc


def _cfg(**kw):
    from lightgbm_tpu.config import Config
    full = dict(TEST_PARAMS)
    full.update({"objective": "binary"})
    full.update(kw)
    return Config().set(full)


def _write_csv(path, X, y):
    np.savetxt(path, np.column_stack([y, X]), delimiter=",",
               fmt="%.7g")


def _trees(g):
    return g.model_to_string().split("parameters:")[0]


def _train(cfg, ds, rounds=5):
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, ())
    for _ in range(rounds):
        g.train_one_iter()
    g.finish_training()
    return g


def test_ooc_forced_routes_and_matches_in_memory(tmp_path):
    """tpu_out_of_core=1 takes the streaming path WITHOUT two_round
    set, and the binned dataset is bit-identical to the in-memory
    loader's."""
    from lightgbm_tpu.io.loader import DatasetLoader
    from lightgbm_tpu.obs import registry as obs

    X, y = make_binary(n=900, f=6, seed=41)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    ref = DatasetLoader(_cfg()).load_from_file(str(f))
    b0 = obs.counter("ooc/blocks").value
    ds = DatasetLoader(_cfg(tpu_out_of_core=1)).load_from_file(str(f))
    assert obs.counter("ooc/blocks").value > b0, \
        "forced OOC did not take the streaming path"
    assert ds.num_data == ref.num_data
    np.testing.assert_array_equal(ds.bins, ref.bins)
    np.testing.assert_array_equal(ds.metadata.label, ref.metadata.label)


def test_ooc_device_stream_bit_parity_and_counters(tmp_path):
    """With device ingest on, the OOC route assembles the bin matrix
    ON DEVICE (no host bin matrix at all) and matches the in-memory
    loader bit-for-bit; ooc/disk_bytes accounts the streamed text and
    the peak-RSS gauge is recorded."""
    from lightgbm_tpu.io.loader import DatasetLoader
    from lightgbm_tpu.obs import registry as obs

    X, y = make_binary(n=1100, f=6, seed=43)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    ref = DatasetLoader(_cfg()).load_from_file(str(f))
    d0 = obs.counter("ooc/disk_bytes").value
    ds = DatasetLoader(_cfg(tpu_out_of_core=1, tpu_ingest=1,
                            enable_bundle=False)).load_from_file(str(f))
    assert ds.bins is None and ds.bins_t_dev is not None
    got = np.asarray(ds.bins_t_dev)[:, :ds.num_data].T
    np.testing.assert_array_equal(got, ref.bins.astype(got.dtype))
    streamed = obs.counter("ooc/disk_bytes").value - d0
    import os
    assert streamed >= os.path.getsize(f) * 0.9, \
        "disk_bytes must account (approximately) the whole file"
    assert (obs.gauge("ooc/rss_peak_mb").value or 0) > 0


def test_ooc_off_pins_host_bins(tmp_path):
    """tpu_out_of_core=0 inside two_round disables the device stream:
    host bins, still bit-identical."""
    from lightgbm_tpu.io.loader import DatasetLoader

    X, y = make_binary(n=700, f=5, seed=45)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    ref = DatasetLoader(_cfg()).load_from_file(str(f))
    ds = DatasetLoader(_cfg(two_round=True, tpu_ingest=1,
                            tpu_out_of_core=0)).load_from_file(str(f))
    assert ds.bins_t_dev is None and ds.bins is not None
    np.testing.assert_array_equal(ds.bins, ref.bins)


def test_ooc_block_rows_knob(tmp_path):
    """tpu_ooc_block_rows sizes the pass-2 blocks: tiny blocks mean
    many flushes and an IDENTICAL matrix."""
    from lightgbm_tpu.io.loader import DatasetLoader
    from lightgbm_tpu.obs import registry as obs

    X, y = make_binary(n=640, f=5, seed=47)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    big = DatasetLoader(_cfg(tpu_out_of_core=1)).load_from_file(str(f))
    b0 = obs.counter("ooc/blocks").value
    small = DatasetLoader(_cfg(tpu_out_of_core=1,
                               tpu_ooc_block_rows=64)
                          ).load_from_file(str(f))
    assert obs.counter("ooc/blocks").value - b0 >= 640 // 64
    np.testing.assert_array_equal(small.bins, big.bins)


def test_sharded_stream_matches_in_memory_sharded():
    """ShardedIngestStream fed odd-sized sequential blocks assembles
    the SAME row-sharded [F, N_pad] array as bin_matrix_sharded on the
    whole matrix — identical chunk kernel, identical row->device map."""
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.io.ingest import DeviceBinner
    from lightgbm_tpu.parallel.learners import make_mesh

    r = np.random.default_rng(7)
    X = r.normal(size=(1030, 6))
    X[::13, 3] = np.nan
    cfg = _cfg(tpu_ingest=1)
    ds = TpuDataset(cfg).construct_from_matrix(
        X, Metadata(label=(X[:, 0] > 0).astype(np.float32)))
    binner = DeviceBinner(ds.mappers, ds.used_feature_map, cfg,
                          X.dtype)
    mesh = make_mesh(8)
    a = binner.bin_matrix_sharded(X, mesh)
    stream = binner.start_sharded_stream(mesh, X.shape[0])
    for r0 in range(0, X.shape[0], 97):          # parser-sized blocks
        stream.feed(X[r0:r0 + 97])
    b = stream.finish()
    assert a.shape == b.shape
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ooc_sharded_loader_trains_bit_identical(tmp_path):
    """File load under a row-sharding learner: the OOC route streams
    straight into the mesh layout (bins_t_dev + pad) and the trained
    model is bit-identical to the in-memory loader's."""
    from lightgbm_tpu.io.loader import DatasetLoader

    X, y = make_binary(n=1000, f=6, seed=49)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    kw = dict(tree_learner="data", tpu_ingest=1, enable_bundle=False)
    cfg_m = _cfg(**kw)
    ref = DatasetLoader(cfg_m).load_from_file(str(f))
    cfg_o = _cfg(tpu_out_of_core=1, **kw)
    ds = DatasetLoader(cfg_o).load_from_file(str(f))
    assert ds.bins_t_dev is not None
    assert ds.bins_t_dev.shape[1] >= ds.num_data
    g1 = _train(cfg_m, ref)
    g2 = _train(cfg_o, ds)
    assert _trees(g1) == _trees(g2)


def test_ooc_libsvm_parity(tmp_path):
    """Sparse-format (libsvm) files ride the same forced-OOC route
    bit-identically, device stream included."""
    from lightgbm_tpu.io.loader import DatasetLoader

    X, y = make_binary(n=500, f=5, seed=51)
    f = tmp_path / "t.svm"
    with open(f, "w") as fh:
        for i in range(500):
            feats = " ".join(f"{j}:{X[i, j]:.6g}" for j in range(5)
                             if abs(X[i, j]) > 0.05)
            fh.write(f"{y[i]:.0f} {feats}\n")
    ref = DatasetLoader(_cfg()).load_from_file(str(f))
    ds = DatasetLoader(_cfg(tpu_out_of_core=1, tpu_ooc_block_rows=128)
                       ).load_from_file(str(f))
    np.testing.assert_array_equal(ds.bins, ref.bins)
    dd = DatasetLoader(_cfg(tpu_out_of_core=1, tpu_ingest=1,
                            enable_bundle=False)).load_from_file(str(f))
    got = np.asarray(dd.bins_t_dev)[:, :dd.num_data].T
    np.testing.assert_array_equal(got, ref.bins.astype(got.dtype))


def test_ooc_train_bit_identical_serial(tmp_path):
    """End-to-end acceptance: a model trained from the OOC-loaded
    dataset is BIT-identical to one trained from the in-memory load."""
    from lightgbm_tpu.io.loader import DatasetLoader

    X, y = make_binary(n=800, f=6, seed=53)
    f = tmp_path / "t.csv"
    _write_csv(f, X, y)
    cfg_m = _cfg()
    cfg_o = _cfg(tpu_out_of_core=1, tpu_ooc_block_rows=100)
    g1 = _train(cfg_m, DatasetLoader(cfg_m).load_from_file(str(f)))
    g2 = _train(cfg_o, DatasetLoader(cfg_o).load_from_file(str(f)))
    assert _trees(g1) == _trees(g2)
