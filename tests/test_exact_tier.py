"""Exact-semantics histogram tier suite (pytest -m exact_tier).

Three layers lock the exact-tier overhaul down:

1. **Kernel bit-parity** — the reduced-channel hi/lo layouts ("hilo4",
   "hilo3") of both Pallas kernels (interpret mode) must reproduce the
   original 5-channel kernel BIT-FOR-BIT on the same inputs, and their
   integer channels must match the XLA oracle exactly, across a
   fixture grid (-0.0 gradients, zero hessians, out-of-bag rows,
   missing-type metadata, categorical bitsets).
2. **Fused-XLA route parity** — the off-TPU fused partition+histogram
   region (ops/hist_wave.py fused_partition_histogram_xla, the new
   CPU hot path) trains BIT-identical models to the legacy two-pass
   pipeline (cfg.fused=False) across bagging / NaN / -0.0 /
   categorical / multiclass / quantized-off-and-on, at the grower
   level AND end-to-end through GBDT (pinned wave size, so the only
   change is the route).
3. **Selection + caching** — tune_exact_tier unit tests with a fake
   timer (winner by measured time, cache hit on re-encounter, hilo3
   gated on constant-unit hessians), and the step-cache geometry key
   carrying the winning variant (different variants = different
   compiled steps; same variant re-trains are pure hits).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from conftest import fit_gbdt, make_binary, make_regression
from lightgbm_tpu.ops import autotune, step_cache
from lightgbm_tpu.ops.hist_wave import (
    TBL_ROWS, fused_partition_histogram_pallas,
    fused_partition_histogram_xla, wave_histogram_pallas,
    wave_histogram_xla)
from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                          apply_wave_splits,
                                          make_wave_grower)

pytestmark = pytest.mark.exact_tier


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def _kernel_problem(kind, N=777, F=6, B=63, n_leaves=5, seed=3):
    """(bins_t, g, h, leaf) with the grid's awkward numerics."""
    r = np.random.default_rng(seed)
    bins_t = r.integers(0, B, (F, N)).astype(np.uint8)
    g = r.normal(size=N).astype(np.float32)
    h = r.uniform(0.2, 1.0, N).astype(np.float32)
    leaf = r.integers(-1, n_leaves, N).astype(np.int32)
    if kind == "neg_zero":
        # -0.0 gradients: the bf16 hi/lo bit-truncation split must
        # carry the sign through both halves
        g[::7] = -0.0
        g[1::7] = 0.0
    elif kind == "zero_hess":
        h[::5] = 0.0
    elif kind == "bag_heavy":
        leaf[r.random(N) < 0.6] = -1
    return bins_t, g, h, leaf


KERNEL_KINDS = ["plain", "neg_zero", "zero_hess", "bag_heavy"]


def _jx(*arrs):
    return tuple(jnp.asarray(a) for a in arrs)


# ---------------------------------------------------------------------------
# 1. kernel bit-parity
# ---------------------------------------------------------------------------

class TestWaveKernelVariants:
    @pytest.mark.parametrize("kind", KERNEL_KINDS)
    def test_hilo4_bitwise_vs_hilo5_and_oracle(self, kind):
        bins_t, g, h, leaf = _kernel_problem(kind)
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = _jx(bins_t, g, h, leaf, wl)
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        h5 = np.asarray(wave_histogram_pallas(
            *args, num_bins=64, chunk=256, interpret=True,
            variant="hilo5"))
        h4 = np.asarray(wave_histogram_pallas(
            *args, num_bins=64, chunk=256, interpret=True,
            variant="hilo4"))
        np.testing.assert_array_equal(h4, h5)
        # the second (count) dot must be exact, not merely close
        np.testing.assert_array_equal(h4[..., 2], ref[..., 2])
        np.testing.assert_allclose(h4, ref, atol=1e-4)

    @pytest.mark.parametrize("kind", ["plain", "neg_zero", "bag_heavy"])
    def test_hilo3_bitwise_on_unit_hessians(self, kind):
        """hilo3's fused hess/count plane: with h == membership mask
        (the constant-unit-hessian contract) all three channels are
        bit-equal to the 5-channel kernel AND the oracle's integer
        channels."""
        bins_t, g, h, leaf = _kernel_problem(kind)
        m = (leaf >= 0).astype(np.float32)      # bag mask via leaf=-1
        gm, hm = g * m, m.copy()                # h = 1.0 * mask
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = _jx(bins_t, gm, hm, leaf, wl)
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        h5 = np.asarray(wave_histogram_pallas(
            *args, num_bins=64, chunk=256, interpret=True,
            variant="hilo5"))
        h3 = np.asarray(wave_histogram_pallas(
            *args, num_bins=64, chunk=256, interpret=True,
            variant="hilo3"))
        np.testing.assert_array_equal(h3, h5)
        np.testing.assert_array_equal(h3[..., 1], ref[..., 1])
        np.testing.assert_array_equal(h3[..., 2], ref[..., 2])

    def test_wide_waves_respect_new_lane_caps(self):
        """hilo4 admits W=32 and hilo3 W=40 — both beyond hilo5's 25 —
        while hilo5 still refuses them (the lane budget is the whole
        point of the reduced layouts)."""
        bins_t, g, h, leaf = _kernel_problem("plain", B=16, n_leaves=40)
        wl40 = np.arange(40, dtype=np.int32)
        args = _jx(bins_t, g, h, leaf, wl40)
        with pytest.raises(NotImplementedError, match="128 lanes"):
            wave_histogram_pallas(*args, num_bins=16, chunk=256,
                                  interpret=True, variant="hilo5")
        ref = np.asarray(wave_histogram_xla(*args, num_bins=16))
        h3 = np.asarray(wave_histogram_pallas(
            *args, num_bins=16, chunk=256, interpret=True,
            variant="hilo3"))
        np.testing.assert_array_equal(h3[..., 2], ref[..., 2])
        h4 = np.asarray(wave_histogram_pallas(
            *_jx(bins_t, g, h, leaf, np.arange(32, dtype=np.int32)),
            num_bins=16, chunk=256, interpret=True, variant="hilo4"))
        assert h4.shape == (32, 6, 16, 3)


class TestFusedKernelVariants:
    def _fused_case(self):
        r = np.random.default_rng(0)
        N, F, B, W = 999, 5, 64, 8
        bins_t = r.integers(0, 63, (F, N)).astype(np.uint8)
        g = r.normal(size=N).astype(np.float32)
        g[::9] = -0.0
        h = r.uniform(0.1, 1, N).astype(np.float32)
        mask = (r.uniform(size=N) > 0.3).astype(np.float32)
        leaf = r.integers(0, 4, N).astype(np.int32)
        wl = np.array([0, 1, 2, 3, -1, -1, -1, -1], np.int32)
        new_ids = np.array([4, 5, 6, 7, -1, -1, -1, -1], np.int32)
        feat = r.integers(0, F, W).astype(np.int32)
        tbin = r.integers(0, 60, W).astype(np.int32)
        dleft = r.integers(0, 2, W).astype(bool)
        meta = FeatureMeta(
            num_bin=np.full(F, 64, np.int32),
            missing_type=np.array([0, 1, 2, 0, 1], np.int32),
            default_bin=np.array([0, 3, 0, 0, 5], np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        tbl = np.zeros((18, W), np.int32)
        tbl[0], tbl[1], tbl[2], tbl[3] = wl, new_ids, feat, tbin
        tbl[4] = dleft.astype(np.int32)
        tbl[5] = meta.missing_type[feat]
        tbl[6] = meta.default_bin[feat]
        tbl[7] = meta.num_bin[feat]
        tbl[8] = new_ids            # small = right child
        return (bins_t, g, h, mask, leaf, wl, new_ids, feat, tbin,
                dleft, meta, tbl, B, W)

    @pytest.mark.parametrize("variant,unit_h", [("hilo4", False),
                                                ("hilo3", True)])
    def test_fused_variant_bitwise_vs_hilo5(self, variant, unit_h):
        (bins_t, g, h, mask, leaf, wl, new_ids, feat, tbin, dleft,
         meta, tbl, B, W) = self._fused_case()
        if unit_h:
            h = mask.copy()         # constant-unit-hessian contract
        gm, hm = g * mask, h * mask
        base = _jx(bins_t, gm, hm, mask, leaf, tbl)
        l5, h5 = fused_partition_histogram_pallas(
            *base, num_bins=B, chunk=256, interpret=True,
            variant="hilo5")
        lv, hv = fused_partition_histogram_pallas(
            *base, num_bins=B, chunk=256, interpret=True,
            variant=variant)
        np.testing.assert_array_equal(np.asarray(lv), np.asarray(l5))
        np.testing.assert_array_equal(np.asarray(hv), np.asarray(h5))

    def test_fused_xla_bitwise_vs_legacy_pipeline(self):
        """The XLA fused route == [apply_wave_splits ->
        wave_histogram_xla] bit-for-bit: partition ints AND histogram
        f32 bits (same membership, same combined-scatter order)."""
        (bins_t, g, h, mask, leaf, wl, new_ids, feat, tbin, dleft,
         meta, tbl, B, W) = self._fused_case()
        gm, hm = g * mask, h * mask
        iscat = np.zeros(W, bool)
        catw = np.zeros((W, 8), np.int32)
        lf, hf = fused_partition_histogram_xla(
            *_jx(bins_t, gm, hm, mask, leaf, wl, new_ids, feat, tbin,
                 dleft, iscat, catw, new_ids,
                 meta.missing_type[np.maximum(feat, 0)],
                 meta.default_bin[np.maximum(feat, 0)],
                 meta.num_bin[np.maximum(feat, 0)]),
            num_bins=B)
        meta_j = FeatureMeta(*[jnp.asarray(x) for x in meta])
        lu = apply_wave_splits(
            *_jx(bins_t, leaf, wl, new_ids, feat, tbin, dleft,
                 wl >= 0), meta_j)
        bag_leaf = jnp.where(jnp.asarray(mask) > 0, lu, -1)
        hu = wave_histogram_xla(
            *_jx(bins_t, gm, hm), bag_leaf, jnp.asarray(new_ids),
            num_bins=B)
        np.testing.assert_array_equal(np.asarray(lf), np.asarray(lu))
        np.testing.assert_array_equal(np.asarray(hf), np.asarray(hu))


# ---------------------------------------------------------------------------
# 2. fused-XLA route parity (grower + end-to-end)
# ---------------------------------------------------------------------------

def _grower_inputs(kind):
    r = np.random.default_rng(4)
    N, F, B = 3000, 8, 63
    bins = r.integers(0, B, (F, N)).astype(np.uint8)
    y = (bins[0].astype(float) / B + 0.3 * (bins[1] > 30)
         + 0.2 * r.normal(size=N) > 0.55).astype(np.float32)
    g = 0.5 - y
    h = np.full(N, 0.25, np.float32)
    mask = np.ones(N, np.float32)
    if kind == "bagging":
        mask = (r.random(N) < 0.7).astype(np.float32)
    meta = FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.array([0, 1, 2, 0, 1, 0, 2, 0], np.int32),
        default_bin=np.array([0, 3, 0, 0, 5, 0, 0, 0], np.int32),
        monotone=np.zeros(F, np.int32),
        penalty=np.ones(F, np.float32))
    return bins, g, h, mask, meta, B


@pytest.mark.parametrize("kind", ["plain", "bagging"])
@pytest.mark.parametrize("quant", [False, True])
def test_grower_fused_xla_route_bit_parity(kind, quant):
    """Whole-tree parity: the auto (fused-XLA) route and the forced
    legacy route grow IDENTICAL TreeRecords and leaf assignments."""
    bins, g, h, mask, meta, B = _grower_inputs(kind)
    F = bins.shape[0]
    kw = dict(num_leaves=31, num_bins=B, wave_size=8, hp=SplitParams(),
              precision="int8" if quant else "highest")
    ga = make_wave_grower(WaveGrowerConfig(**kw), meta)
    gl = make_wave_grower(WaveGrowerConfig(**kw, fused=False), meta)
    args = _jx(bins, g, h, mask) + (jnp.ones(F, bool),)
    ra, la = ga(*args)
    rl, ll = gl(*args)
    np.testing.assert_array_equal(np.asarray(la), np.asarray(ll))
    for a, b in zip(ra, rl):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _awkward_data(kind, n=900, f=8, seed=7):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, f))
    if kind == "nan":
        X[r.random((n, f)) < 0.1] = np.nan
    elif kind == "neg_zero":
        X[:, 0] = np.where(r.random(n) < 0.3, -0.0, X[:, 0])
    elif kind == "categorical":
        X[:, 1] = r.integers(0, 9, n).astype(float)
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 2])
         + 0.2 * r.normal(size=n) > 0).astype(np.float32)
    return X, y


END_TO_END_GRID = [
    ("nan", {"objective": "binary"}),
    ("neg_zero", {"objective": "binary"}),
    ("categorical", {"objective": "binary",
                     "categorical_feature": "1"}),
    ("plain", {"objective": "multiclass", "num_class": 3}),
    ("plain", {"objective": "binary", "bagging_freq": 2,
               "bagging_fraction": 0.7}),
    ("plain", {"objective": "binary", "tpu_quantized_hist": True}),
]


def _trees(g):
    return g.model_to_string().split("parameters:")[0]


@pytest.mark.parametrize("kind,params", END_TO_END_GRID)
def test_end_to_end_variant_bit_parity(kind, params):
    """Pinned wave size => the variant choice changes ONLY the kernel
    channel layout (off-TPU: nothing at all), so hilo4-pinned training
    must reproduce hilo5-pinned training model-text-identically across
    the awkward-data grid — no silent semantics downgrade."""
    X, y = _awkward_data(kind)
    if params["objective"] == "multiclass":
        y = (np.abs(X[:, 0]) * 2 % 3 // 1).astype(np.float32)
    base = dict(params, num_leaves=15, tpu_wave_size=8)
    g5 = fit_gbdt(X, y, dict(base, tpu_exact_tier="hilo5"), num_round=6)
    g4 = fit_gbdt(X, y, dict(base, tpu_exact_tier="hilo4"), num_round=6)
    assert _trees(g5) == _trees(g4)


def test_end_to_end_hilo3_bit_parity_on_l1():
    """hilo3 engages for the constant-unit-hessian family and trains
    the same trees as hilo5 at a pinned wave size."""
    X, y = make_regression(900)
    base = {"objective": "regression_l1", "num_leaves": 15,
            "tpu_wave_size": 8}
    g5 = fit_gbdt(X, y, dict(base, tpu_exact_tier="hilo5"), num_round=6)
    g3 = fit_gbdt(X, y, dict(base, tpu_exact_tier="hilo3"), num_round=6)
    assert g3._grower_cfg.exact_variant == "hilo3"
    assert _trees(g5) == _trees(g3)


def test_packed4_hilo_kernel_bitwise():
    """The nibble-packed HBM tier composes with the exact hi/lo
    layouts: packed bins through the interpret wave kernel ==
    unpacked bins, bit-for-bit, for every variant."""
    r = np.random.default_rng(5)
    N, F, B = 777, 6, 16
    bins = r.integers(0, B, (F, N)).astype(np.uint8)
    packed = (bins[0::2] | (bins[1::2] << 4)).astype(np.uint8)
    g = r.normal(size=N).astype(np.float32)
    h = r.uniform(0.2, 1.0, N).astype(np.float32)
    leaf = r.integers(-1, 5, N).astype(np.int32)
    wl = np.array([0, 2, -1, 4, 1], np.int32)
    for variant in ("hilo5", "hilo4", "hilo3"):
        hv = h if variant != "hilo3" else (leaf >= 0).astype(np.float32)
        ref = np.asarray(wave_histogram_pallas(
            *_jx(bins, g, hv, leaf, wl), num_bins=B, chunk=256,
            interpret=True, variant=variant))
        got = np.asarray(wave_histogram_pallas(
            *_jx(packed, g, hv, leaf, wl), num_bins=B, chunk=256,
            interpret=True, variant=variant, packed4=True,
            num_features=F))
        np.testing.assert_array_equal(got, ref)


def test_packed4_engages_on_exact_tier_end_to_end():
    """max_bin <= 16 non-quantized training rides the packed-bins
    HBM tier under exact semantics — and trains the SAME model as
    unpacked bins."""
    X, y = make_binary(900, seed=9)
    base = {"objective": "binary", "max_bin": 15, "num_leaves": 15}
    gp = fit_gbdt(X, y, base, num_round=5)
    assert gp._grower_cfg.packed4, \
        "packed bins must auto-engage on the exact tier at max_bin<=16"
    assert gp._grower_cfg.precision == "highest"
    gu = fit_gbdt(X, y, dict(base, tpu_packed_bins=0), num_round=5)
    assert not gu._grower_cfg.packed4
    assert _trees(gp) == _trees(gu)


def test_auto_variant_selection_per_objective():
    """Auto (off-TPU analytic) rule: widest feasible wave — hilo3 for
    constant-unit-hessian objectives, hilo4 otherwise; hilo3 requests
    on a varying-hessian objective demote to hilo4 with a warning."""
    Xb, yb = make_binary(640)
    gb = fit_gbdt(Xb, yb, {"objective": "binary", "num_leaves": 63},
                  num_round=2)
    assert gb._grower_cfg.exact_variant == "hilo4"
    assert gb._grower_cfg.wave_size == 32

    Xr, yr = make_regression(640)
    gr = fit_gbdt(Xr, yr, {"objective": "regression",
                           "num_leaves": 63}, num_round=2)
    assert gr._grower_cfg.exact_variant == "hilo3"
    assert gr._grower_cfg.wave_size == 40

    g_demoted = fit_gbdt(Xb, yb, {"objective": "binary",
                                  "tpu_exact_tier": "hilo3"},
                         num_round=2)
    assert g_demoted._grower_cfg.exact_variant == "hilo4"

    gq = fit_gbdt(Xb, yb, {"objective": "binary",
                           "tpu_quantized_hist": True}, num_round=2)
    assert gq._grower_cfg.precision == "int8"


def test_weighted_rows_exclude_hilo3():
    """Row weights make h == w, not the mask — the objective reports
    non-constant hessians and the auto rule must not pick hilo3."""
    X, y = make_regression(640)
    w = np.random.default_rng(0).uniform(0.5, 2.0, len(y)) \
        .astype(np.float32)
    g = fit_gbdt(X, y, {"objective": "regression"}, num_round=2,
                 weight=w)
    assert g._grower_cfg.exact_variant == "hilo4"


# ---------------------------------------------------------------------------
# 3. tune_exact_tier selection + step-cache keying
# ---------------------------------------------------------------------------

class TestTuneExactTier:
    @pytest.fixture
    def fresh_tuner(self, tmp_path):
        """Isolated tuning cache; restores the module tuner after."""
        autotune.configure("on", str(tmp_path / "tuning.json"))
        yield
        autotune.configure("on", None)

    def test_requested_variant_honored_and_gated(self, fresh_tuner):
        assert autotune.tune_exact_tier(
            F=8, B=64, requested="hilo5") == "hilo5"
        assert autotune.tune_exact_tier(
            F=8, B=64, constant_hessian=True,
            requested="hilo3") == "hilo3"
        # hilo3 without the constant-hessian contract demotes
        assert autotune.tune_exact_tier(
            F=8, B=64, constant_hessian=False,
            requested="hilo3") == "hilo4"

    def test_mode_off_pins_hilo5(self, tmp_path):
        autotune.configure("off", str(tmp_path / "t.json"))
        try:
            assert autotune.tune_exact_tier(
                F=8, B=64, constant_hessian=True) == "hilo5"
        finally:
            autotune.configure("on", None)

    def test_fake_timer_selection_and_cache(self, fresh_tuner):
        """Injected timer: the fastest candidate wins; the second
        encounter of the key is served from the cache without timing
        anything."""
        calls = []

        def fake(cand):
            calls.append(cand["variant"])
            return {"hilo3": 3.0, "hilo4": 0.5, "hilo5": 2.0}[
                cand["variant"]]

        got = autotune.tune_exact_tier(
            F=8, B=64, constant_hessian=True, _measure=fake)
        assert got == "hilo4"
        assert sorted(calls) == ["hilo3", "hilo4", "hilo5"]
        calls.clear()
        again = autotune.tune_exact_tier(
            F=8, B=64, constant_hessian=True, _measure=fake)
        assert again == "hilo4"
        assert calls == [], "second encounter must be a cache hit"

    def test_candidate_set_excludes_hilo3_without_contract(self):
        cands = [c["variant"] for c in autotune.exact_tier_candidates(
            constant_hessian=False)]
        assert "hilo3" not in cands
        assert cands[0] == "hilo4"
        cands_c = [c["variant"] for c in autotune.exact_tier_candidates(
            constant_hessian=True)]
        assert cands_c[0] == "hilo3"

    def test_failed_candidates_fall_back(self, fresh_tuner):
        def broken(cand):
            raise RuntimeError("mosaic says no")

        assert autotune.tune_exact_tier(
            F=9, B=64, constant_hessian=False,
            _measure=broken) == "hilo5"

    def test_vmem_pricing_accounts_hilo4_count_accumulator(self):
        geom = autotune.hist_geometry(F=28, B=64, W=32)
        base = autotune.hist_vmem_bytes(chunk=8192, geom=geom, W=32,
                                        fused=True, variant="hilo5")
        with_cnt = autotune.hist_vmem_bytes(chunk=8192, geom=geom,
                                            W=32, fused=True,
                                            variant="hilo4")
        assert with_cnt > base


class TestStepCacheKeying:
    def _delta(self, fn):
        s0 = step_cache.stats()
        out = fn()
        s1 = step_cache.stats()
        return out, {k: s1[k] - s0[k] for k in ("hits", "misses")}

    def test_variant_rides_geometry_key(self):
        """Different exact-tier variants are DIFFERENT compiled steps
        (no cross-variant contamination), and each variant's retrain
        is a pure registry hit — compiled-step reuse survives the
        tuner picking different variants for different geometries."""
        X, y = make_binary(640, seed=21)
        _, d5 = self._delta(lambda: fit_gbdt(
            X, y, {"objective": "binary", "tpu_wave_size": 8,
                   "tpu_exact_tier": "hilo5"}, num_round=3))
        assert d5["misses"] >= 1
        _, d4 = self._delta(lambda: fit_gbdt(
            X, y, {"objective": "binary", "tpu_wave_size": 8,
                   "tpu_exact_tier": "hilo4"}, num_round=3))
        assert d4["misses"] >= 1, \
            "a different variant must not hit the other's step"
        _, d5b = self._delta(lambda: fit_gbdt(
            X, y, {"objective": "binary", "tpu_wave_size": 8,
                   "tpu_exact_tier": "hilo5"}, num_round=3))
        assert d5b["misses"] == 0 and d5b["hits"] >= 1
        _, d4b = self._delta(lambda: fit_gbdt(
            X, y, {"objective": "binary", "tpu_wave_size": 8,
                   "tpu_exact_tier": "hilo4"}, num_round=3))
        assert d4b["misses"] == 0 and d4b["hits"] >= 1

    def test_auto_variant_reuse_across_boosters(self):
        """The auto-picked variant is deterministic per geometry, so
        the sliding-window pattern (fresh booster, same shape) stays a
        registry hit."""
        X, y = make_binary(640, seed=22)
        g1, _ = self._delta(lambda: fit_gbdt(
            X, y, {"objective": "binary"}, num_round=3))
        g2, d2 = self._delta(lambda: fit_gbdt(
            X, y, {"objective": "binary"}, num_round=3))
        assert d2["misses"] == 0 and d2["hits"] >= 1
        assert g1._grower_cfg.exact_variant \
            == g2._grower_cfg.exact_variant
        assert _trees(g1) == _trees(g2)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def test_config_validates_exact_tier_knob():
    from lightgbm_tpu.config import Config
    cfg = Config().set({"tpu_exact_tier": "hilo9"})
    assert cfg.tpu_exact_tier == ""          # warned + reset to auto
    cfg = Config().set({"tpu_exact_tier": "hilo4"})
    assert cfg.tpu_exact_tier == "hilo4"


def test_config_refuses_bad_tier_combos_at_param_time():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError, match="tpu_quantized_hist"):
        Config().set({"tpu_count_proxy": 1})
    with pytest.raises(LightGBMError, match="max_bin"):
        Config().set({"tpu_packed_bins": 1})     # default max_bin 255
    with pytest.raises(LightGBMError, match="count-proxy"):
        Config().set({"tpu_packed_bins": 1, "tpu_quantized_hist": True,
                      "tpu_count_proxy": 0, "max_bin": 15})
    with pytest.raises(LightGBMError, match="tpu_use_dp"):
        Config().set({"tpu_packed_bins": 1, "tpu_use_dp": False,
                      "max_bin": 15})
    # the valid combos still parse: count-proxy int8, and the hi/lo
    # exact tier (the packed-bins hilo tier this PR adds)
    cfg = Config().set({"tpu_packed_bins": 1,
                        "tpu_quantized_hist": True, "max_bin": 15})
    assert cfg.tpu_packed_bins == 1
    cfg = Config().set({"tpu_packed_bins": 1, "max_bin": 15})
    assert cfg.tpu_packed_bins == 1 and cfg.tpu_use_dp
