"""Compiled-step registry suite (ops/step_cache.py): cross-booster
reuse proven by registry counters, and bit-parity of bucket-padded
training against exact-shape training across the eligibility matrix
(bagging, valid sets, quantized histograms, weights, renew objectives,
data-parallel learner). Run with ``pytest -m stepcache``.

Parity here is between the SHARED-step programs (tpu_row_bucket=-1 vs
0): that is the invariant the registry relies on — a booster served
from the cache must produce exactly what it would have compiled for
itself. The legacy per-instance closure (tpu_step_cache=0) is checked
too where the suite historically guaranteed it (K=1 objectives); for
multiclass, XLA's whole-program fusion can flip an exactly-tied
zero-gain split between the two PROGRAM SHAPES (observed as an
output-neutral extra leaf), so the legacy check there is on
predictions, not model text.
"""
import numpy as np
import pytest

from conftest import (TEST_PARAMS, fit_gbdt, make_binary,
                      make_multiclass, make_regression)
from lightgbm_tpu.ops import step_cache

pytestmark = pytest.mark.stepcache


def trees(g):
    """Model text minus the parameters section (the tpu_step_cache /
    tpu_row_bucket knobs legitimately differ between parity runs)."""
    return g.model_to_string().split("parameters:")[0]


def stats_delta(fn):
    s0 = step_cache.stats()
    out = fn()
    s1 = step_cache.stats()
    return out, {k: s1[k] - s0[k] for k in ("hits", "misses")}


def test_cross_booster_reuse_exact_counters():
    """Two boosters with identical geometry compile the fused step
    exactly once — the second is a pure registry hit."""
    X, y = make_binary(640, seed=11)
    g1, d1 = stats_delta(
        lambda: fit_gbdt(X, y, {"objective": "binary"}, num_round=4))
    g2, d2 = stats_delta(
        lambda: fit_gbdt(X, y, {"objective": "binary"}, num_round=4))
    assert d2["misses"] == 0, "second booster must not recompile"
    assert d2["hits"] >= 1
    assert trees(g1) == trees(g2)


def test_same_bucket_different_n_shares_step():
    """Row counts landing in the same power-of-two bucket share one
    compiled step; the padded run is bit-exact vs its own exact-shape
    run."""
    X, y = make_binary(1280, seed=12)
    _, d1 = stats_delta(
        lambda: fit_gbdt(X, y, {"objective": "binary"}, num_round=4))
    gb, d2 = stats_delta(
        lambda: fit_gbdt(X[:1100], y[:1100], {"objective": "binary"},
                         num_round=4))
    assert d2["misses"] == 0, \
        "n=1100 and n=1280 land in the same 2048 bucket"
    ge = fit_gbdt(X[:1100], y[:1100],
                  {"objective": "binary", "tpu_row_bucket": 0},
                  num_round=4)
    assert trees(gb) == trees(ge)


@pytest.mark.parametrize("name,params,kwargs", [
    ("bagging", {"objective": "binary", "bagging_freq": 2,
                 "bagging_fraction": 0.7}, {}),
    ("valid", {"objective": "binary"}, {"valid": True}),
    ("quantized", {"objective": "binary",
                   "tpu_quantized_hist": True}, {}),
    ("weights", {"objective": "regression"}, {"weight": True}),
    ("l1_renew", {"objective": "regression_l1"}, {}),
])
def test_bucket_padding_bit_parity(name, params, kwargs):
    """Bucket-padded training (tpu_row_bucket=-1) is bit-exact vs
    exact shapes (tpu_row_bucket=0) AND vs the legacy per-instance
    closure (tpu_step_cache=0)."""
    if params["objective"].startswith("regression"):
        X, y = make_regression(1280, seed=13)
    else:
        X, y = make_binary(1280, seed=13)
    kw = {}
    if kwargs.get("valid"):
        kw["valid"] = (X[:320], y[:320])
    if kwargs.get("weight"):
        r = np.random.default_rng(5)
        kw["weight"] = (np.abs(r.normal(size=1280)) + 0.5).astype(
            np.float32)
    gb = fit_gbdt(X, y, params, num_round=5, **kw)
    ge = fit_gbdt(X, y, dict(params, tpu_row_bucket=0), num_round=5,
                  **kw)
    gl = fit_gbdt(X, y, dict(params, tpu_step_cache=0), num_round=5,
                  **kw)
    assert trees(gb) == trees(ge), f"{name}: bucket != exact"
    assert trees(gb) == trees(gl), f"{name}: cached != legacy"


def test_data_parallel_reuse_and_legacy_parity():
    """The sharded f32 data learner caches at exact shapes (bucketing
    would regroup the cross-shard f32 psums): same-N boosters share
    one step, and the shared step matches the legacy closure."""
    X, y = make_binary(1280, seed=14)
    params = {"objective": "binary", "tree_learner": "data"}
    gb, _ = stats_delta(lambda: fit_gbdt(X, y, params, num_round=4))
    _, d2 = stats_delta(lambda: fit_gbdt(X, y, params, num_round=4))
    assert d2["misses"] == 0
    assert d2["hits"] >= 1
    gl = fit_gbdt(X, y, dict(params, tpu_step_cache=0), num_round=4)
    assert trees(gb) == trees(gl)


def test_data_parallel_quantized_bucket_parity():
    """Quantized data-parallel training buckets: the int32 histogram
    wire and integer root sums are grouping-invariant, so the padded
    run is bit-exact vs exact shapes even though the shard boundaries
    moved."""
    X, y = make_binary(1280, seed=19)
    params = {"objective": "binary", "tree_learner": "data",
              "tpu_quantized_hist": True}
    gb = fit_gbdt(X, y, params, num_round=4)
    assert gb._n_score > gb._n, "quantized data mode must bucket"
    ge = fit_gbdt(X, y, dict(params, tpu_row_bucket=0), num_round=4)
    assert trees(gb) == trees(ge)


def test_multiclass_bucket_parity():
    """K>1: bucket-vs-exact stays bit-exact within the shared path;
    vs the legacy program shape, predictions (not borderline zero-gain
    splits) are the guarantee."""
    X, y = make_multiclass(1280, seed=15)
    params = {"objective": "multiclass", "num_class": 4}
    gb = fit_gbdt(X, y, params, num_round=4)
    ge = fit_gbdt(X, y, dict(params, tpu_row_bucket=0), num_round=4)
    assert trees(gb) == trees(ge)
    gl = fit_gbdt(X, y, dict(params, tpu_step_cache=0), num_round=4)
    np.testing.assert_array_equal(gb.predict(X[:256]),
                                  gl.predict(X[:256]))


def test_step_cache_off_knob():
    """tpu_step_cache=0 keeps the legacy closure: no registry
    traffic."""
    X, y = make_binary(512, seed=16)
    _, d = stats_delta(
        lambda: fit_gbdt(X, y, {"objective": "binary",
                                "tpu_step_cache": 0}, num_round=3))
    assert d["misses"] == 0 and d["hits"] == 0


def test_custom_gradients_cached():
    """Objective-less boosters (custom fobj gradients) ride the shared
    step with grad_fn=None; parity with the legacy closure holds."""
    X, y = make_regression(700, seed=17)

    def run(extra):
        def go():
            import conftest as _c
            from lightgbm_tpu.config import Config
            from lightgbm_tpu.io.dataset import Metadata, TpuDataset
            from lightgbm_tpu.models.gbdt import GBDT
            p = dict(TEST_PARAMS)
            p.update({"objective": "none"})
            p.update(extra)
            cfg = Config().set(p)
            ds = TpuDataset(cfg).construct_from_matrix(
                X, Metadata(label=y))
            g = GBDT()
            g.init(cfg, ds, None, ())
            for _ in range(3):
                s = np.asarray(g.train_scores())[0]
                g.train_one_iter(grad=(s - y).astype(np.float32),
                                 hess=np.ones_like(y, np.float32))
            g.finish_training()
            return g
        return go
    gb, _ = stats_delta(run({}))
    _, d2 = stats_delta(run({}))
    assert d2["misses"] == 0
    gl = fit_gbdt  # noqa: F841  (uniform style)
    ge, _ = stats_delta(run({"tpu_step_cache": 0}))
    assert trees(gb) == trees(ge)


def test_reset_parameter_cannot_flip_step_implementation():
    """A mid-life reset_parameter that flips a step-cache knob must
    NOT switch step implementations: the live buffers are frozen at
    the widths chosen at init (the legacy closure cannot consume a
    bucketed score width)."""
    import lightgbm_tpu as lgb
    X, y = make_binary(1000, seed=21)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "max_bin": 31, "verbosity": -1},
                    lgb.Dataset(X, y), num_boost_round=2,
                    verbose_eval=False, keep_training_booster=True)
    g = bst._gbdt
    assert g._cache_eligible and g._n_score > g._n
    bst.reset_parameter({"tpu_step_cache": 0, "learning_rate": 0.05})
    bst.update()                      # crashed before the freeze
    assert g._cache_eligible, "implementation flipped mid-life"
    assert g._n_score > g._n
    assert len(g.records) == 3


def _goss_booster(extra):
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.models.boosting import create_boosting
    from lightgbm_tpu.objectives import create_objective
    X, y = make_binary(640, seed=18)
    p = dict(TEST_PARAMS)
    p.update({"objective": "binary", "boosting": "goss",
              "top_rate": 0.3, "other_rate": 0.3})
    p.update(extra)
    cfg = Config().set(p)
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    g = create_boosting("goss")
    g.init(cfg, ds, obj, ())
    for _ in range(3):
        g.train_one_iter()
    return g


def test_ineligible_variants_keep_legacy():
    """Legacy GOSS (tpu_goss_hash=0) opts out — its in-jit sampler is
    positional in the row width — no registry traffic, and the booster
    still trains."""
    g, d = stats_delta(lambda: _goss_booster({"tpu_goss_hash": 0}))
    assert d["misses"] == 0 and d["hits"] == 0
    assert not g._cache_eligible
    assert g._n_score == g._n
    assert len(g.records) == 3


def test_goss_hash_rides_registry():
    """Default (hashed) GOSS is step-cache eligible: first booster
    misses once, a same-geometry retrain is a pure registry hit, and
    the two produce identical trees."""
    g1, d1 = stats_delta(lambda: _goss_booster({}))
    assert g1._cache_eligible
    assert d1["misses"] >= 1
    g2, d2 = stats_delta(lambda: _goss_booster({}))
    assert d2["misses"] == 0, "same-geometry GOSS retrain recompiled"
    assert d2["hits"] >= 1
    assert trees(g1) == trees(g2)
    assert len(g2.records) == 3


def test_lambdarank_rides_registry_and_retrain_hits():
    """lambdarank's query tables ride the aux pytree (replicated
    ``_``-keys, bucketed [nq, qmax] shapes): a same-geometry retrain is
    a pure registry hit and bit-identical — the windows-2+ zero-compile
    promise for the ranking workload."""
    rng = np.random.default_rng(31)
    n, qsize = 1200, 20
    X = rng.normal(size=(n, 8))
    y = np.clip((X[:, 0] * 2 + rng.normal(size=n)) // 1.0,
                0, 3).astype(np.float32)
    group = np.full(n // qsize, qsize, np.int64)
    params = {"objective": "lambdarank"}
    g1, d1 = stats_delta(
        lambda: fit_gbdt(X, y, params, num_round=4, group=group))
    assert g1._cache_eligible, "lambdarank must be registry-eligible"
    assert d1["misses"] >= 1
    g2, d2 = stats_delta(
        lambda: fit_gbdt(X, y, params, num_round=4, group=group))
    assert d2["misses"] == 0, "lambdarank retrain recompiled"
    assert d2["hits"] >= 1
    assert trees(g1) == trees(g2)
    # bucket-vs-exact parity: padded query tables are inert
    ge = fit_gbdt(X, y, dict(params, tpu_row_bucket=0), num_round=4,
                  group=group)
    assert trees(g1) == trees(ge)


def test_lrb_two_window_smoke():
    """Two sliding windows of the paper workload: fresh booster per
    window, ONE compile for the run — every window after the first is
    a registry hit with ~zero compile time (windows differ in observed
    bin counts AND surviving feature counts, so this exercises the B/F
    geometry bucketing, not just row bucketing)."""
    from lightgbm_tpu.lrb import LrbDriver, synthetic_trace
    import io
    out = io.StringIO()
    drv = LrbDriver(cache_size=1 << 16, window_size=512,
                    sample_size=256, cutoff=0.5, sampling=1,
                    result_file=out)
    for seq, oid, size, cost in synthetic_trace(1024, n_objects=60):
        drv.process_request(seq, oid, size, cost)
    assert len(drv.results) == 2
    assert drv.booster is not None
    trained = [r for r in drv.results if "train_s" in r]
    assert trained, "at least one window must have trained a model"
    assert all(r["compile_s"] >= 0 for r in trained)
    # amortization: windows after the first must NOT recompile
    for r in trained[1:]:
        assert r["step_cache_hits"] >= 1, \
            "later window re-compiled — geometry key drifted"
        assert r["compile_s"] < 1.0
    # the second window evaluates the first window's model
    assert "fp_rate" in drv.results[1]


def test_geometry_bucketing_shares_across_data_shapes():
    """The observed max bin count AND the surviving feature count are
    data-dependent (trivial columns are excluded) — the B/F axis
    buckets (pow2 bins, mult-of-8 features) make boosters trained on
    differently-shaped windows share ONE step, bit-exactly vs the
    legacy exact-shape closure."""
    rng = np.random.default_rng(11)
    n = 1280
    # 10 informative + 1 constant column -> F=10 after trivial
    # exclusion (pads to 16); ~40 distinct levels -> B!=pow2 (pads 64)
    X = np.round(rng.normal(size=(n, 11)) * 6).clip(-20, 19)
    X[:, 7] = 3.0
    w = rng.normal(size=11)
    w[7] = 0
    y = ((X @ w + rng.normal(size=n) * 0.5) > 0).astype(np.float32)
    params = {"objective": "binary", "bagging_freq": 2,
              "bagging_fraction": 0.8}
    gb, _ = stats_delta(lambda: fit_gbdt(X, y, params, num_round=5))
    assert gb._f_pad % 8 == 0 and gb._f_pad > gb.train_data.num_features
    assert gb._grower_cfg.num_bins == 64
    gl = fit_gbdt(X, y, dict(params, tpu_step_cache=0), num_round=5)
    assert gl._f_pad == gl.train_data.num_features
    assert trees(gb) == trees(gl), "padded F/B drifted vs legacy"
    # different observed bins (50 levels) AND features (12, no trivial
    # column): same (16, 64) bucket -> pure registry hit
    X2 = np.round(rng.normal(size=(n, 12)) * 8).clip(-25, 24)
    y2 = ((X2 @ rng.normal(size=12)) > 0).astype(np.float32)
    _, d2 = stats_delta(lambda: fit_gbdt(X2, y2, params, num_round=5))
    assert d2["misses"] == 0, "same-bucket shapes must share the step"
    assert d2["hits"] >= 1
