"""Wave histogram / fused kernel / wave grower regression tests.

Promotes the round-2 scratch parity checks into the collected suite
(VERDICT r2 weak #5) and adds coverage for the fused partition+histogram
kernel (hist_wave.py) now wired into the grower. The Pallas kernels run
in interpret mode on the CPU test backend — same code path as TPU, with
HIGHEST-precision dots standing in for the MXU's exact bf16 products.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.grower import GrowerConfig, make_tree_grower
from lightgbm_tpu.ops.hist_wave import (fused_partition_histogram_pallas,
                                        wave_histogram_pallas,
                                        wave_histogram_xla)
from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                          apply_wave_splits,
                                          make_wave_grower)


def _numpy_hist(bins_t, g, h, leaf, wl, B):
    """Per-slot histogram oracle (plain loops)."""
    W, F = len(wl), bins_t.shape[0]
    out = np.zeros((W, F, B, 3), np.float64)
    for k, l in enumerate(wl):
        if l < 0:
            continue
        m = leaf == l
        for f in range(F):
            out[k, f, :, 0] = np.bincount(
                bins_t[f, m], weights=g[m], minlength=B)[:B]
            out[k, f, :, 1] = np.bincount(
                bins_t[f, m], weights=h[m], minlength=B)[:B]
            out[k, f, :, 2] = np.bincount(bins_t[f, m], minlength=B)[:B]
    return out


def _problem(N=777, F=6, B=63, n_leaves=5, seed=3):
    r = np.random.default_rng(seed)
    bins_t = r.integers(0, B, (F, N)).astype(np.uint8)
    g = r.normal(size=N).astype(np.float32)
    h = r.uniform(0.2, 1.0, N).astype(np.float32)
    leaf = r.integers(-1, n_leaves, N).astype(np.int32)
    return bins_t, g, h, leaf


class TestWaveHistogram:
    def test_xla_matches_numpy_oracle(self):
        bins_t, g, h, leaf = _problem()
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        out = np.asarray(wave_histogram_xla(
            jnp.asarray(bins_t), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(leaf), jnp.asarray(wl), num_bins=64))
        ref = _numpy_hist(bins_t, g, h, leaf, wl, 64)
        np.testing.assert_allclose(out, ref, atol=2e-4)
        np.testing.assert_array_equal(out[..., 2], ref[..., 2])

    @pytest.mark.parametrize("precision", ["highest", "default"])
    def test_pallas_matches_xla(self, precision):
        bins_t, g, h, leaf = _problem()
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = (jnp.asarray(bins_t), jnp.asarray(g), jnp.asarray(h),
                jnp.asarray(leaf), jnp.asarray(wl))
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        out = np.asarray(wave_histogram_pallas(
            *args, num_bins=64, chunk=256, interpret=True,
            precision=precision))
        np.testing.assert_array_equal(out[..., 2], ref[..., 2])
        np.testing.assert_allclose(out, ref, atol=1e-4)


class TestFusedKernel:
    def test_fused_matches_unfused(self):
        """Partition bit-exact, histograms f32-grade vs the unfused
        (apply_wave_splits + wave_histogram_xla) pipeline."""
        r = np.random.default_rng(0)
        N, F, B, W = 999, 5, 64, 8
        bins_t = r.integers(0, 63, (F, N)).astype(np.uint8)
        g = r.normal(size=N).astype(np.float32)
        h = r.uniform(0.1, 1, N).astype(np.float32)
        mask = (r.uniform(size=N) > 0.3).astype(np.float32)
        leaf = r.integers(0, 4, N).astype(np.int32)
        meta_np = FeatureMeta(
            num_bin=np.full(F, 64, np.int32),
            missing_type=np.array([0, 1, 2, 0, 1], np.int32),
            default_bin=np.array([0, 3, 0, 0, 5], np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        meta = FeatureMeta(*[jnp.asarray(x) for x in meta_np])
        wl = np.array([0, 1, 2, 3, -1, -1, -1, -1], np.int32)
        new_ids = np.array([4, 5, 6, 7, -1, -1, -1, -1], np.int32)
        feat = r.integers(0, F, W).astype(np.int32)
        tbin = r.integers(0, 60, W).astype(np.int32)
        dleft = r.integers(0, 2, W).astype(bool)
        small = new_ids.copy()

        gm, hm = g * mask, h * mask
        tbl = jnp.stack([jnp.asarray(x) for x in [
            wl, new_ids, feat, tbin, dleft.astype(np.int32),
            meta_np.missing_type[feat], meta_np.default_bin[feat],
            meta_np.num_bin[feat], small]])
        leaf_f, hist_f = fused_partition_histogram_pallas(
            jnp.asarray(bins_t), jnp.asarray(gm),
            jnp.asarray(hm), jnp.asarray(mask), jnp.asarray(leaf), tbl,
            num_bins=B, chunk=256, interpret=True)

        leaf_u = apply_wave_splits(
            jnp.asarray(bins_t), jnp.asarray(leaf), jnp.asarray(wl),
            jnp.asarray(new_ids), jnp.asarray(feat), jnp.asarray(tbin),
            jnp.asarray(dleft), jnp.asarray(wl >= 0), meta)
        bag_leaf = jnp.where(jnp.asarray(mask) > 0, leaf_u, -1)
        hist_u = wave_histogram_xla(
            jnp.asarray(bins_t), jnp.asarray(gm), jnp.asarray(hm),
            bag_leaf, jnp.asarray(small), num_bins=B)

        np.testing.assert_array_equal(np.asarray(leaf_f),
                                      np.asarray(leaf_u))
        hf, hu = np.asarray(hist_f), np.asarray(hist_u)
        np.testing.assert_array_equal(hf[..., 2], hu[..., 2])
        np.testing.assert_allclose(hf, hu, atol=5e-5)


def _grower_problem():
    r = np.random.default_rng(0)
    N, F, B = 3000, 8, 63
    bins = r.integers(0, B, (N, F)).astype(np.uint8)
    logit = (bins[:, 0].astype(float) / B - 0.5
             + 0.3 * (bins[:, 1] > 30) - 0.2 * (bins[:, 2] < 10))
    y = (logit + 0.3 * r.normal(size=N) > 0).astype(np.float32)
    grad = jnp.asarray(0.5 - y)
    hess = jnp.full(N, 0.25, jnp.float32)
    mask = jnp.asarray((r.random(N) < 0.8).astype(np.float32))
    fmask = jnp.ones(F, bool)
    meta = FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.zeros(F, np.int32),
        default_bin=np.zeros(F, np.int32),
        monotone=np.zeros(F, np.int32),
        penalty=np.ones(F, np.float32))
    return bins, grad, hess, mask, fmask, meta, B


class TestWaveGrower:
    def test_wave1_matches_legacy_grower(self):
        """W=1 reproduces the round-1 strict leaf-wise grower exactly
        (the correctness oracle relationship from scratch/, promoted)."""
        bins, grad, hess, mask, fmask, meta, B = _grower_problem()
        L = 31
        hp = SplitParams(min_data_in_leaf=20)
        old = make_tree_grower(
            GrowerConfig(num_leaves=L, num_bins=B, chunk=bins.shape[0],
                         hp=hp), meta)
        rec_o, leaf_o = old(jnp.asarray(bins), grad, hess, mask, fmask)
        new = make_wave_grower(
            WaveGrowerConfig(num_leaves=L, num_bins=B, wave_size=1,
                             hp=hp), meta)
        rec_n, leaf_n = new(jnp.asarray(bins.T.copy()), grad, hess,
                            mask, fmask)
        assert int(rec_o.num_leaves) == int(rec_n.num_leaves)
        np.testing.assert_array_equal(np.asarray(rec_o.split_feature),
                                      np.asarray(rec_n.split_feature))
        np.testing.assert_array_equal(np.asarray(rec_o.split_bin),
                                      np.asarray(rec_n.split_bin))
        np.testing.assert_array_equal(np.asarray(leaf_o),
                                      np.asarray(leaf_n))
        np.testing.assert_allclose(np.asarray(rec_o.leaf_output),
                                   np.asarray(rec_n.leaf_output),
                                   atol=1e-5)

    def test_wave_batched_quality(self):
        """W>1 trees reach the same total gain grade as W=1 (waves split
        in gain order; only budget-boundary choices may differ)."""
        bins, grad, hess, mask, fmask, meta, B = _grower_problem()
        L = 31
        hp = SplitParams(min_data_in_leaf=20)
        gains = {}
        for W in (1, 8):
            gr = make_wave_grower(
                WaveGrowerConfig(num_leaves=L, num_bins=B, wave_size=W,
                                 hp=hp), meta)
            rec, _ = gr(jnp.asarray(bins.T.copy()), grad, hess, mask,
                        fmask)
            gains[W] = float(np.asarray(rec.split_gain).sum())
            assert int(rec.num_leaves) == L
        assert gains[8] >= 0.95 * gains[1]

    def test_fused_grower_matches_unfused(self):
        """The fused Pallas grower path (interpret mode) grows the same
        tree as the unfused path."""
        bins, grad, hess, mask, fmask, meta, B = _grower_problem()
        L = 15
        hp = SplitParams(min_data_in_leaf=20)
        base = make_wave_grower(
            WaveGrowerConfig(num_leaves=L, num_bins=B, wave_size=8,
                             hp=hp, fused=False), meta)
        rec_b, leaf_b = base(jnp.asarray(bins.T.copy()), grad, hess,
                             mask, fmask)
        fused = make_wave_grower(
            WaveGrowerConfig(num_leaves=L, num_bins=B, wave_size=8,
                             hp=hp, fused=True, chunk=1024), meta)
        rec_f, leaf_f = fused(jnp.asarray(bins.T.copy()), grad, hess,
                              mask, fmask)
        assert int(rec_b.num_leaves) == int(rec_f.num_leaves)
        np.testing.assert_array_equal(np.asarray(rec_b.split_feature),
                                      np.asarray(rec_f.split_feature))
        np.testing.assert_array_equal(np.asarray(rec_b.split_bin),
                                      np.asarray(rec_f.split_bin))
        np.testing.assert_array_equal(np.asarray(leaf_b),
                                      np.asarray(leaf_f))
        np.testing.assert_allclose(np.asarray(rec_f.leaf_output),
                                   np.asarray(rec_b.leaf_output),
                                   atol=1e-4)


class TestLeafGather:
    def test_pallas_matches_xla_gather(self):
        from lightgbm_tpu.ops.predict import leaf_gather_pallas
        r = np.random.default_rng(9)
        table = r.normal(size=255).astype(np.float32)
        ids = r.integers(0, 255, 100_001).astype(np.int32)
        out = np.asarray(leaf_gather_pallas(
            jnp.asarray(table), jnp.asarray(ids), interpret=True))
        np.testing.assert_array_equal(out, table[ids])

    def test_out_of_range_ids_zero(self):
        from lightgbm_tpu.ops.predict import leaf_gather_pallas
        table = jnp.asarray([1.0, 2.0, 3.0])
        ids = jnp.asarray([0, -1, 2, 7, 1], jnp.int32)
        out = np.asarray(leaf_gather_pallas(table, ids, interpret=True))
        np.testing.assert_array_equal(out, [1.0, 0.0, 3.0, 0.0, 2.0])


class TestInt8Histogram:
    """tpu_quantized_hist kernels: int8 MXU products must reproduce the
    exact integer sums of the XLA scatter oracle."""

    def _qproblem(self):
        r = np.random.default_rng(11)
        N, F = 777, 6
        bins_t = r.integers(0, 63, (F, N)).astype(np.uint8)
        gq = r.integers(-127, 128, N).astype(np.float32)
        hq = r.integers(0, 128, N).astype(np.float32)
        leaf = r.integers(-1, 5, N).astype(np.int32)
        mask = (leaf >= 0).astype(np.float32)
        return bins_t, gq, hq, leaf, mask

    def test_wave_int8_matches_xla(self):
        bins_t, gq, hq, leaf, _ = self._qproblem()
        wl = np.array([0, 2, -1, 4, 1], np.int32)
        args = (jnp.asarray(bins_t), jnp.asarray(gq), jnp.asarray(hq),
                jnp.asarray(leaf), jnp.asarray(wl))
        ref = np.asarray(wave_histogram_xla(*args, num_bins=64))
        sg, sh = 0.5, 0.25
        out = np.asarray(wave_histogram_pallas(
            *args, num_bins=64, chunk=256, interpret=True,
            precision="int8", gh_scale=(sg, sh)))
        np.testing.assert_array_equal(out[..., 2], ref[..., 2])
        np.testing.assert_allclose(out[..., 0], ref[..., 0] * sg,
                                   rtol=1e-6)
        np.testing.assert_allclose(out[..., 1], ref[..., 1] * sh,
                                   rtol=1e-6)

    def test_fused_int8_matches_xla(self):
        from lightgbm_tpu.ops.hist_wave import (
            fused_partition_histogram_pallas)
        from lightgbm_tpu.ops.wave_grower import apply_wave_splits
        bins_t, gq, hq, leaf, mask = self._qproblem()
        F = bins_t.shape[0]
        meta_np = FeatureMeta(
            num_bin=np.full(F, 64, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        meta = FeatureMeta(*[jnp.asarray(x) for x in meta_np])
        W = 8
        wl = np.array([0, 1, 2, 3, 4, -1, -1, -1], np.int32)
        new_ids = np.array([5, 6, 7, 8, 9, -1, -1, -1], np.int32)
        r = np.random.default_rng(12)
        feat = r.integers(0, F, W).astype(np.int32)
        tbin = r.integers(0, 60, W).astype(np.int32)
        dleft = np.zeros(W, bool)
        small = new_ids.copy()
        gm, hm = gq * mask, hq * mask
        tbl = jnp.stack([jnp.asarray(x) for x in [
            wl, new_ids, feat, tbin, dleft.astype(np.int32),
            meta_np.missing_type[feat], meta_np.default_bin[feat],
            meta_np.num_bin[feat], small,
            np.zeros(W, np.int32)]])
        leaf0 = np.where(mask > 0, leaf, 0).astype(np.int32)
        sg, sh = 0.125, 2.0
        leaf_f, hist_f = fused_partition_histogram_pallas(
            jnp.asarray(bins_t), jnp.asarray(gm), jnp.asarray(hm),
            jnp.asarray(mask), jnp.asarray(leaf0), tbl,
            num_bins=64, chunk=256, interpret=True,
            precision="int8", gh_scale=(sg, sh))
        leaf_u = apply_wave_splits(
            jnp.asarray(bins_t), jnp.asarray(leaf0), jnp.asarray(wl),
            jnp.asarray(new_ids), jnp.asarray(feat), jnp.asarray(tbin),
            jnp.asarray(dleft), jnp.asarray(wl >= 0), meta)
        bag_leaf = jnp.where(jnp.asarray(mask) > 0, leaf_u, -1)
        hist_u = np.asarray(wave_histogram_xla(
            jnp.asarray(bins_t), jnp.asarray(gm), jnp.asarray(hm),
            bag_leaf, jnp.asarray(small), num_bins=64))
        np.testing.assert_array_equal(np.asarray(leaf_f),
                                      np.asarray(leaf_u))
        hf = np.asarray(hist_f)
        np.testing.assert_array_equal(hf[..., 2], hist_u[..., 2])
        np.testing.assert_allclose(hf[..., 0], hist_u[..., 0] * sg,
                                   rtol=1e-6)
        np.testing.assert_allclose(hf[..., 1], hist_u[..., 1] * sh,
                                   rtol=1e-6)

    def test_quantized_grower_quality(self):
        """End-to-end: int8-precision wave grower reaches f32-grade
        split quality on a separable problem (XLA fallback path — the
        same quantization code the TPU kernel path runs)."""
        from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                                  make_wave_grower)
        bins, grad, hess, mask, fmask, meta, B = _grower_problem()
        bins_t = jnp.asarray(np.ascontiguousarray(bins.T))
        outs = {}
        for prec in ("highest", "int8"):
            cfg = WaveGrowerConfig(num_leaves=15, num_bins=B,
                                   wave_size=8, precision=prec)
            grow = make_wave_grower(cfg, meta)
            rec, leaf_ids = grow(bins_t, grad, hess, mask, fmask)
            outs[prec] = rec
        exact, quant = outs["highest"], outs["int8"]
        assert int(quant.num_leaves) >= 12
        # same dominant split structure: root feature agrees
        assert int(quant.split_feature[0]) == int(exact.split_feature[0])
        # leaf outputs close in aggregate
        np.testing.assert_allclose(
            np.sort(np.asarray(quant.leaf_output)[:12]),
            np.sort(np.asarray(exact.leaf_output)[:12]), atol=0.05)


class TestWideBins:
    def test_fused_wide_bin_tier_exact(self):
        """>256 bins: the partition must stay exact (the bf16 MXU
        row-gather only covers the uint8 tier)."""
        from lightgbm_tpu.ops.hist_wave import (
            fused_partition_histogram_pallas)
        from lightgbm_tpu.ops.wave_grower import apply_wave_splits
        r = np.random.default_rng(31)
        N, F, B, W = 700, 4, 320, 8
        bins_t = r.integers(0, B, (F, N)).astype(np.int32)
        g = r.normal(size=N).astype(np.float32)
        h = r.uniform(0.1, 1, N).astype(np.float32)
        mask = np.ones(N, np.float32)
        leaf = r.integers(0, 4, N).astype(np.int32)
        meta_np = FeatureMeta(
            num_bin=np.full(F, B, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        meta = FeatureMeta(*[jnp.asarray(x) for x in meta_np])
        wl = np.array([0, 1, 2, 3, -1, -1, -1, -1], np.int32)
        new_ids = np.array([4, 5, 6, 7, -1, -1, -1, -1], np.int32)
        feat = r.integers(0, F, W).astype(np.int32)
        # thresholds far above 256 exercise the wide tier
        tbin = r.integers(250, 310, W).astype(np.int32)
        dleft = np.zeros(W, bool)
        tbl = jnp.stack([jnp.asarray(x) for x in [
            wl, new_ids, feat, tbin, dleft.astype(np.int32),
            meta_np.missing_type[feat], meta_np.default_bin[feat],
            meta_np.num_bin[feat], new_ids,
            np.zeros(W, np.int32)]])
        leaf_f, _ = fused_partition_histogram_pallas(
            jnp.asarray(bins_t), jnp.asarray(g), jnp.asarray(h),
            jnp.asarray(mask), jnp.asarray(leaf), tbl,
            num_bins=B, chunk=256, interpret=True)
        leaf_u = apply_wave_splits(
            jnp.asarray(bins_t), jnp.asarray(leaf), jnp.asarray(wl),
            jnp.asarray(new_ids), jnp.asarray(feat), jnp.asarray(tbin),
            jnp.asarray(dleft), jnp.asarray(wl >= 0), meta)
        np.testing.assert_array_equal(np.asarray(leaf_f),
                                      np.asarray(leaf_u))


class TestCountProxy:
    """count-proxy int8 mode: the MXU dot carries only g/h (2 channels,
    waves up to 64); per-bin counts are hessian-proportional estimates
    and per-leaf counts stay exact via partition-mask counting."""

    def _qproblem(self, n=3000, F=5, seed=3):
        r = np.random.default_rng(seed)
        bins_t = r.integers(0, 64, (F, n), dtype=np.uint8)
        gq = r.integers(-127, 128, n).astype(np.float32)
        hq = r.integers(0, 128, n).astype(np.float32)
        leaf = r.integers(0, 5, n).astype(np.int32)
        mask = (r.random(n) < 0.8).astype(np.float32)
        return bins_t, gq, hq, leaf, mask

    def test_fused_proxy_kernel_matches_xla_gh_and_counts(self):
        from lightgbm_tpu.ops.hist_wave import (
            fused_partition_histogram_pallas, wave_histogram_xla)
        from lightgbm_tpu.ops.wave_grower import apply_wave_splits
        from lightgbm_tpu.ops.split import FeatureMeta
        bins_t, gq, hq, leaf, mask = self._qproblem()
        F = bins_t.shape[0]
        meta_np = FeatureMeta(
            num_bin=np.full(F, 64, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        meta = FeatureMeta(*[jnp.asarray(x) for x in meta_np])
        W = 16
        wl = np.full(W, -1, np.int32); wl[:5] = np.arange(5)
        new_ids = np.full(W, -1, np.int32)
        new_ids[:5] = np.arange(5, 10)
        r = np.random.default_rng(12)
        feat = r.integers(0, F, W).astype(np.int32)
        tbin = r.integers(0, 60, W).astype(np.int32)
        dleft = np.zeros(W, bool)
        gm, hm = gq * mask, hq * mask
        tbl = jnp.stack([jnp.asarray(x) for x in [
            wl, new_ids, feat, tbin, dleft.astype(np.int32),
            meta_np.missing_type[feat], meta_np.default_bin[feat],
            meta_np.num_bin[feat], new_ids,
            np.zeros(W, np.int32)]])
        leaf0 = np.where(mask > 0, leaf, 0).astype(np.int32)
        sg, sh = 0.125, 2.0
        leaf_f, hist_f, cnt_r = fused_partition_histogram_pallas(
            jnp.asarray(bins_t), jnp.asarray(gm), jnp.asarray(hm),
            jnp.asarray(mask), jnp.asarray(leaf0), tbl,
            num_bins=64, chunk=256, interpret=True,
            precision="int8", gh_scale=(sg, sh), count_proxy=True)
        assert hist_f.shape[-1] == 2
        leaf_u = apply_wave_splits(
            jnp.asarray(bins_t), jnp.asarray(leaf0), jnp.asarray(wl),
            jnp.asarray(new_ids), jnp.asarray(feat), jnp.asarray(tbin),
            jnp.asarray(dleft), jnp.asarray(wl >= 0), meta)
        np.testing.assert_array_equal(np.asarray(leaf_f),
                                      np.asarray(leaf_u))
        bag_leaf = jnp.where(jnp.asarray(mask) > 0, leaf_u, -1)
        hist_u = np.asarray(wave_histogram_xla(
            jnp.asarray(bins_t), jnp.asarray(gm), jnp.asarray(hm),
            bag_leaf, jnp.asarray(new_ids), num_bins=64))
        np.testing.assert_allclose(np.asarray(hist_f[..., 0]),
                                   hist_u[..., 0] * sg, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(hist_f[..., 1]),
                                   hist_u[..., 1] * sh, rtol=1e-6)
        # exact right-child counts = in-bag rows that landed on new ids
        lu = np.asarray(leaf_u)
        want = np.array([((lu == ni) & (mask > 0)).sum() if ni >= 0
                         else 0 for ni in new_ids], np.float32)
        np.testing.assert_array_equal(np.asarray(cnt_r), want)

    def _grow(self, count_proxy, W, n=4000, F=6, fused=True):
        from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
        from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                                  make_wave_grower)
        r = np.random.default_rng(9)
        bins = r.integers(0, 64, (n, F)).astype(np.uint8)
        x = bins[:, 0].astype(np.float32) / 64.0
        y = ((x + 0.3 * (bins[:, 1] > 40) + 0.1 * r.normal(size=n)) > 0.6)
        p = np.full(n, 0.5, np.float32)
        grad = (p - y).astype(np.float32)
        hess = (p * (1 - p)).astype(np.float32)
        # integer-quantize g/h exactly like the grower's quant step
        # does not matter here: feed pre-quantized integer g/h so the
        # proxy and exact paths see identical inputs
        gq = np.round(grad * 127).astype(np.float32)
        hq = np.maximum(np.round(hess * 127), 1).astype(np.float32)
        meta = FeatureMeta(
            num_bin=np.full(F, 64, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        hp = SplitParams(min_data_in_leaf=0, min_sum_hessian_in_leaf=0.0,
                         count_lb=count_proxy)
        cfg = WaveGrowerConfig(
            num_leaves=31, num_bins=64, wave_size=W, hp=hp,
            precision="int8", fused=fused, chunk=512,
            count_proxy=count_proxy)
        grow = make_wave_grower(cfg, meta)
        mask = np.ones(n, np.float32)
        rec, leaf_ids = grow(jnp.asarray(bins.T.copy()),
                             jnp.asarray(gq), jnp.asarray(hq),
                             jnp.asarray(mask),
                             jnp.ones(F, bool))
        return rec, np.asarray(leaf_ids)

    def test_proxy_grower_matches_exact_when_gates_idle(self):
        """With min_data_in_leaf=1 the count gate never binds, so the
        proxy grower must build the IDENTICAL tree to the exact int8
        grower — per-bin counts only ever feed that gate."""
        rec_e, leaf_e = self._grow(count_proxy=False, W=8)
        rec_p, leaf_p = self._grow(count_proxy=True, W=8)
        assert int(rec_p.num_leaves) == int(rec_e.num_leaves)
        np.testing.assert_array_equal(leaf_p, leaf_e)
        np.testing.assert_array_equal(np.asarray(rec_p.split_feature),
                                      np.asarray(rec_e.split_feature))
        np.testing.assert_array_equal(np.asarray(rec_p.split_bin),
                                      np.asarray(rec_e.split_bin))
        np.testing.assert_allclose(np.asarray(rec_p.leaf_output),
                                   np.asarray(rec_e.leaf_output),
                                   rtol=1e-5, atol=1e-7)

    def test_proxy_leaf_counts_exact(self):
        """leaf_count / internal_count come from partition-mask
        counting and must equal a host recount of leaf_ids."""
        rec, leaf_ids = self._grow(count_proxy=True, W=16)
        nl = int(rec.num_leaves)
        counts = np.asarray(rec.leaf_count)[:nl]
        recount = np.array([(leaf_ids == k).sum() for k in range(nl)],
                           np.float32)
        np.testing.assert_array_equal(counts, recount)

    def test_proxy_unfused_oracle_path(self):
        """The XLA-oracle (non-fused) proxy path agrees with the fused
        interpret path."""
        rec_f, leaf_f = self._grow(count_proxy=True, W=8, fused=True)
        rec_u, leaf_u = self._grow(count_proxy=True, W=8, fused=False)
        np.testing.assert_array_equal(leaf_f, leaf_u)
        np.testing.assert_array_equal(np.asarray(rec_f.split_feature),
                                      np.asarray(rec_u.split_feature))


class TestPacked4:
    """4-bit packed HBM bins (count-proxy tier): two features per byte,
    nibble-unpack in the kernel; must grow IDENTICAL trees to the
    unpacked uint8 tier."""

    def _grow(self, packed, W=8, n=3000, F=5, fused=True):
        from lightgbm_tpu.ops.split import FeatureMeta, SplitParams
        from lightgbm_tpu.ops.wave_grower import (WaveGrowerConfig,
                                                  make_wave_grower)
        r = np.random.default_rng(21)
        bins = r.integers(0, 16, (F, n)).astype(np.uint8)
        gq = r.integers(-127, 128, n).astype(np.float32)
        hq = r.integers(1, 128, n).astype(np.float32)
        meta = FeatureMeta(
            num_bin=np.full(F, 16, np.int32),
            missing_type=np.zeros(F, np.int32),
            default_bin=np.zeros(F, np.int32),
            monotone=np.zeros(F, np.int32),
            penalty=np.ones(F, np.float32))
        hp = SplitParams(min_data_in_leaf=0, min_sum_hessian_in_leaf=0.0,
                         count_lb=True)
        cfg = WaveGrowerConfig(
            num_leaves=15, num_bins=16, wave_size=W, hp=hp,
            precision="int8", fused=fused, chunk=512,
            count_proxy=True, packed4=packed)
        grow = make_wave_grower(cfg, meta)
        if packed:
            b = bins if F % 2 == 0 else np.concatenate(
                [bins, np.zeros((1, n), np.uint8)])
            dev_bins = jnp.asarray(b[0::2] | (b[1::2] << 4))
        else:
            dev_bins = jnp.asarray(bins)
        rec, leaf = grow(dev_bins, jnp.asarray(gq), jnp.asarray(hq),
                         jnp.ones(n, jnp.float32), jnp.ones(F, bool))
        return rec, np.asarray(leaf)

    def test_packed_fused_matches_unpacked(self):
        rec_u, leaf_u = self._grow(packed=False)
        rec_p, leaf_p = self._grow(packed=True)
        assert int(rec_p.num_leaves) == int(rec_u.num_leaves)
        np.testing.assert_array_equal(leaf_p, leaf_u)
        np.testing.assert_array_equal(np.asarray(rec_p.split_feature),
                                      np.asarray(rec_u.split_feature))
        np.testing.assert_array_equal(np.asarray(rec_p.split_bin),
                                      np.asarray(rec_u.split_bin))
        np.testing.assert_allclose(np.asarray(rec_p.leaf_output),
                                   np.asarray(rec_u.leaf_output),
                                   rtol=1e-5, atol=1e-7)

    def test_packed_unfused_fallback_matches(self):
        """The non-fused path unpacks up front and must agree too."""
        rec_p, leaf_p = self._grow(packed=True, fused=True)
        rec_q, leaf_q = self._grow(packed=True, fused=False)
        np.testing.assert_array_equal(leaf_p, leaf_q)
        np.testing.assert_array_equal(np.asarray(rec_p.split_feature),
                                      np.asarray(rec_q.split_feature))

    def test_gbdt_packs_and_matches_unpacked(self):
        """End-to-end: max_bin=15 + quantized training auto-packs the
        HBM bins (halved first axis) and trains the same model as
        tpu_packed_bins=0."""
        from conftest import fit_gbdt, make_binary
        X, y = make_binary(n=1500, f=6, seed=9)
        params = {"objective": "binary", "metric": "auc", "max_bin": 15,
                  "tpu_quantized_hist": True}
        gp = fit_gbdt(X, y, params, num_round=10)
        gu = fit_gbdt(X, y, dict(params, tpu_packed_bins=0),
                      num_round=10)
        assert gp._grower_cfg.packed4
        assert not gu._grower_cfg.packed4
        assert gp._bins_dev.shape[0] == (gu._bins_dev.shape[0] + 1) // 2
        np.testing.assert_allclose(
            np.asarray(gp.predict_raw(X[:200])),
            np.asarray(gu.predict_raw(X[:200])), atol=1e-6)

    def test_gbdt_packed_early_stop_trim_replays_correctly(self):
        """The early-stopping trim (and refit/continued training)
        replay the partition on the TRAINING bins — with the 4-bit tier
        those must be nibble-unpacked first (regression: reading packed
        bytes as [F, N] bin codes silently corrupted scores)."""
        from conftest import fit_gbdt, make_binary
        X, y = make_binary(n=1500, f=6, seed=15)
        params = {"objective": "binary", "metric": "auc", "max_bin": 15,
                  "tpu_quantized_hist": True}
        gp = fit_gbdt(X, y, params, num_round=10)
        gu = fit_gbdt(X, y, dict(params, tpu_packed_bins=0),
                      num_round=10)
        assert gp._grower_cfg.packed4
        gp._drop_last_iterations(3)     # replays partition on train bins
        gu._drop_last_iterations(3)
        np.testing.assert_allclose(
            np.asarray(gp.predict_raw(X[:200])),
            np.asarray(gu.predict_raw(X[:200])), atol=1e-6)
