"""Mosaic odd-shape sweep on REAL TPU hardware (NOT collected by
pytest — run directly where a TPU is attached):

    PYTHONPATH=. python tests/tpu_shape_sweep.py

The CPU suite runs the Pallas kernels in interpret mode, which cannot
vouch for per-shape MOSAIC legality (8-bit ops, sublane alignment,
lane paddings are backend decisions). This sweep compiles and trains
the quantized/count-proxy/4-bit-packed tiers across the shapes most
likely to hit lowering edges: single-feature, tiny bin counts,
odd/even feature counts under nibble packing, multiclass, sub-chunk
row counts, bagging and GOSS sampling, and the f32-grade hi/lo tier.
All cases ran clean on v5e (round 5)."""
import sys

import numpy as np

import jax

jax.config.update("jax_compilation_cache_dir",
                  "/tmp/lgbm_tpu_jax_cache_dev")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
sys.path.insert(0, ".")

from lightgbm_tpu.config import Config                    # noqa: E402
from lightgbm_tpu.io.dataset import TpuDataset, Metadata  # noqa: E402
from lightgbm_tpu.models.gbdt import GBDT                 # noqa: E402
from lightgbm_tpu.objectives import create_objective      # noqa: E402

r = np.random.default_rng(5)


def run(tag, n, f, max_bin, obj="binary", K=1, extra=None):
    X = r.normal(size=(n, f))
    if obj == "binary":
        y = (X[:, 0] > 0).astype(np.float32)
    else:
        y = np.clip(np.round(np.abs(X[:, 0]) * K / 2), 0, K - 1
                    ).astype(np.float32)
    p = {"objective": obj, "num_leaves": 15, "max_bin": max_bin,
         "min_data_in_leaf": 2, "tpu_stop_check_interval": 10_000,
         "tpu_quantized_hist": True}
    if K > 1:
        p["num_class"] = K
    p.update(extra or {})
    cfg = Config().set(p)
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    obj_ = create_objective(obj, cfg)
    obj_.init(ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj_, [])
    for _ in range(4):
        g.train_one_iter()
    pred = np.asarray(g.predict_raw(X[:64]))
    assert np.isfinite(pred).all(), tag
    print(f"ok {tag} (proxy={g._grower_cfg.count_proxy}, "
          f"packed={g._grower_cfg.packed4})", flush=True)


run("F=1", 5000, 1, 63)
run("F=3 small-N", 900, 3, 63)
run("B=4 packed", 5000, 6, 3)
run("B=4 unpacked", 5000, 6, 3, extra={"tpu_packed_bins": 0})
run("multiclass K=3", 4000, 5, 63, obj="multiclass", K=3)
run("F=29 odd + bin15 packed", 20000, 29, 15)
run("F=2 even packed", 8000, 2, 15)
run("F=3 odd packed", 8000, 3, 15)
run("n<chunk", 4000, 8, 63)
run("hilo no-quant", 20000, 8, 63,
    extra={"tpu_quantized_hist": False})
run("bagging+proxy", 20000, 8, 63,
    extra={"bagging_fraction": 0.6, "bagging_freq": 1})
run("goss+quant", 20000, 8, 63, extra={"boosting": "goss"})
print("SWEEP OK", flush=True)

# EFB bundled training (non-fused pallas wave kernel over bundle
# columns + member expansion) on real hardware, quantized and hi/lo
def run_efb(tag, quant):
    n, blocks = 20000, 30
    group = r.integers(0, blocks, n)
    X = np.zeros((n, blocks + 1))
    X[np.arange(n), group] = r.uniform(1, 5, n)
    X[:, blocks] = r.normal(size=n)
    y = ((group % 7 < 3) ^ (X[:, blocks] > 0)).astype(np.float32)
    p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
         "min_data_in_leaf": 2, "enable_bundle": True,
         "tpu_stop_check_interval": 10_000,
         "tpu_quantized_hist": quant}
    cfg = Config().set(p)
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    assert ds.bundles is not None and len(ds.bundles) < blocks
    obj_ = create_objective("binary", cfg)
    obj_.init(ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj_, [])
    assert g._use_bundles
    for _ in range(4):
        g.train_one_iter()
    pred = np.asarray(g.predict_raw(X[:64]))
    assert np.isfinite(pred).all(), tag
    print(f"ok {tag} (bundles={len(ds.bundles)})", flush=True)


run_efb("EFB quant", True)
run_efb("EFB hilo", False)
print("EFB SWEEP OK", flush=True)
