"""Native C++ parser parity tests.

The native tokenizer (native/fast_parser.cpp via io/native.py) must
agree with the pure-Python parser (io/parser.py), which remains the
semantic oracle.
"""
import numpy as np
import pytest

from lightgbm_tpu.io import native
from lightgbm_tpu.io.parser import (ParsedText, parse_delimited,
                                    parse_file, parse_libsvm)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="g++ toolchain unavailable")


def test_tsv_parity(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 5))
    y = rng.integers(0, 2, 300)
    p = str(tmp_path / "d.tsv")
    with open(p, "w") as fh:
        fh.write("# a comment line\n")
        for i in range(300):
            fh.write("\t".join([f"{y[i]:d}"]
                               + [f"{v:.6f}" for v in X[i]]) + "\n")
    out = native.parse_file_native(p, header=False, label_idx=0)
    assert out is not None
    values, labels, fmt = out
    lines = [ln.rstrip("\n") for ln in open(p) if ln.strip()
             and not ln.startswith("#")]
    ref = parse_delimited(lines, "\t", 0)
    np.testing.assert_array_equal(values, ref.values)
    np.testing.assert_array_equal(labels, ref.label)


def test_csv_header_and_missing(tmp_path):
    p = str(tmp_path / "d.csv")
    with open(p, "w") as fh:
        fh.write("y,a,b\n1,0.5,na\n0,NaN,2.25\n1,,3.5\n")
    parsed, names = parse_file(p, header=True, label_idx=0)
    assert names == ["a", "b"]
    np.testing.assert_array_equal(parsed.label, [1, 0, 1])
    assert parsed.values[0, 0] == 0.5 and np.isnan(parsed.values[0, 1])
    assert np.isnan(parsed.values[1, 0]) and np.isnan(parsed.values[2, 0])
    assert parsed.values[2, 1] == 3.5


def test_libsvm_parity(tmp_path):
    p = str(tmp_path / "d.svm")
    with open(p, "w") as fh:
        fh.write("1 0:0.5 2:1.5\n0 1:2.0\n1 0:1.0 1:1.0 2:1.0\n")
    out = native.parse_file_native(p, header=False, label_idx=0)
    assert out is not None
    values, labels, fmt = out
    lines = [ln.rstrip("\n") for ln in open(p)]
    ref = parse_libsvm(lines, 0)
    np.testing.assert_array_equal(values, ref.values)
    np.testing.assert_array_equal(labels, ref.label)


def test_reference_example_parity():
    """Byte-for-byte agreement with the python parser on a real
    reference data file."""
    import os
    path = "/root/reference/examples/binary_classification/binary.train"
    if not os.path.exists(path):
        pytest.skip("reference examples not mounted")
    out = native.parse_file_native(path, header=False, label_idx=0)
    assert out is not None
    values, labels, _ = out
    lines = [ln.rstrip("\n") for ln in open(path) if ln.strip()]
    ref = parse_delimited(lines, "\t", 0)
    assert values.shape == ref.values.shape == (7000, 28)
    np.testing.assert_array_equal(values, ref.values)
    np.testing.assert_array_equal(labels, ref.label)


def test_format_mismatch_falls_back(tmp_path):
    """A ':' inside a CSV field must not flip the file to libsvm: the
    native sniff is cross-checked against the python two-line detection
    and the python parser takes over — which raises a CLEAR error on
    the non-numeric token instead of silently returning a corrupted
    libsvm-shaped matrix."""
    p = str(tmp_path / "odd.csv")
    with open(p, "w") as fh:
        fh.write("1,12:30,2.5\n0,4.0,5.0\n")
    with pytest.raises(ValueError):
        parse_file(p, label_idx=0)


def test_ragged_rows_fall_back(tmp_path):
    p = str(tmp_path / "ragged.csv")
    with open(p, "w") as fh:
        fh.write("1,2.0\n0,3.0,4.0\n")
    parsed, _ = parse_file(p, label_idx=0)
    # python pad-and-warn semantics: longer row keeps its value
    assert parsed.values.shape == (2, 2)
    assert parsed.values[1, 1] == 4.0


def test_label_idx_out_of_range(tmp_path):
    p = str(tmp_path / "d.csv")
    with open(p, "w") as fh:
        fh.write("1,2\n3,4\n")
    parsed, _ = parse_file(p, label_idx=5)
    assert parsed.values.shape == (2, 2)
    assert parsed.label is None


class TestNativeBinner:
    def test_bin_matrix_native_matches_python(self):
        """The threaded C++ bulk binner must agree bit-for-bit with
        BinMapper.value_to_bin over every missing-type configuration."""
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.io.dataset import TpuDataset, Metadata
        from lightgbm_tpu.io.native import available
        if not available():
            import pytest
            pytest.skip("native toolchain unavailable")
        r = np.random.default_rng(5)
        n = 5000
        X = r.normal(size=(n, 6))
        X[:, 1] = np.where(r.uniform(size=n) < 0.2, np.nan, X[:, 1])
        X[:, 2] = np.where(r.uniform(size=n) < 0.5, 0.0, X[:, 2])
        X[:, 3] = r.integers(0, 4, n)          # few distinct values
        X[:, 4] = np.where(r.uniform(size=n) < 0.1, np.nan, 0.0)
        cfg = Config().set({"objective": "binary", "max_bin": 63,
                            "min_data_in_leaf": 5})
        ds = TpuDataset(cfg).construct_from_matrix(
            np.asarray(X, np.float64),
            Metadata(label=(r.uniform(size=n) > 0.5).astype(np.float32)))
        # python reference per column
        for i, real in enumerate(ds.used_feature_map):
            ref = ds.mappers[i].value_to_bin(X[:, real])
            np.testing.assert_array_equal(ds.bins[:, i], ref,
                                          err_msg=f"feature {i}")
        # f32 input path binds identically (double-domain compares)
        ds32 = TpuDataset(cfg).construct_from_matrix(
            np.asarray(X, np.float32),
            Metadata(label=(r.uniform(size=n) > 0.5).astype(np.float32)))
        for i, real in enumerate(ds32.used_feature_map):
            ref = ds32.mappers[i].value_to_bin(
                np.asarray(X[:, real], np.float32))
            np.testing.assert_array_equal(ds32.bins[:, i], ref)
