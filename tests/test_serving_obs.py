"""Serving-grade observability (obs/reqlog.py, obs/slo.py,
obs/flight.py + the lrb/export wiring): request-id issuance and
deterministic file sampling, SLO error-budget/burn-rate math, the
``/healthz``/``/slo`` endpoints under a concurrent-scrape hammer
during a live LRB run, and the flight recorder's trigger matrix —
watchdog, injected fault, degraded window (the PR-8 drill machinery),
SLO budget exhaustion, and a SIGTERM subprocess drill.

Run with ``pytest -m obs``.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from lightgbm_tpu import lrb
from lightgbm_tpu.obs import export as obs_export
from lightgbm_tpu.obs import flight, reqlog, slo
from lightgbm_tpu.obs import registry as obs_registry
from lightgbm_tpu.obs.recorder import RunRecorder
from lightgbm_tpu.utils import faults, log

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _isolated_serving_obs():
    """Every test here starts with no armed faults and a fresh (or
    absent) global flight recorder / SLO engine / request log — the
    three are process-global by design, and a previous test's dump
    rate-limit clock or latched budget must not leak in."""
    faults.clear()
    flight.shutdown()
    slo.shutdown()
    reqlog.shutdown()
    prev = log.get_level()
    log.set_level(log.LogLevel.INFO)
    yield
    log.set_level(prev)
    faults.clear()
    flight.shutdown()
    slo.shutdown()
    reqlog.shutdown()


# -- request ids + contexts --------------------------------------------------

def test_request_ids_monotonic_across_threads():
    got = []
    lock = threading.Lock()

    def worker():
        mine = [reqlog.next_request_id() for _ in range(200)]
        with lock:
            got.extend(mine)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(got) == len(set(got)) == 1600   # unique, none lost
    later = reqlog.next_request_id()
    assert later > max(got)                    # monotone issuance


def test_request_context_nesting_and_serve_bucket_seam():
    from lightgbm_tpu.ops import predict_cache
    assert reqlog.current() is None
    with reqlog.request(window=4) as outer:
        assert reqlog.current() is outer
        assert outer.window == 4 and outer.bucket is None
        # the serve-bucket seam notes the padded width on the ACTIVE
        # context (ops/predict_cache.py serve_bucket_rows)
        b = predict_cache.serve_bucket_rows(3, policy=-1)
        assert b == 16 and outer.bucket == 16
        with reqlog.request(req_id=999) as inner:
            assert reqlog.current() is inner
            predict_cache.serve_bucket_rows(100, policy=-1)
            assert inner.bucket == 128
        assert reqlog.current() is outer       # restored
        assert outer.bucket == 16              # inner never leaked
    assert reqlog.current() is None
    # without a context the seam is a pure function, no crash
    assert predict_cache.serve_bucket_rows(3, policy=-1) == 16


def test_reqlog_sampling_deterministic():
    a = reqlog.RequestLog(sample=0.25,
                          registry=obs_registry.MetricsRegistry())
    b = reqlog.RequestLog(sample=0.25,
                          registry=obs_registry.MetricsRegistry())
    decisions = [a.sampled(i) for i in range(8192)]
    # a pure function of (id, rate): a second instance agrees exactly
    assert decisions == [b.sampled(i) for i in range(8192)]
    frac = sum(decisions) / len(decisions)
    assert 0.2 < frac < 0.3                    # ~rate, hash-uniform
    full = reqlog.RequestLog(sample=1.0,
                             registry=obs_registry.MetricsRegistry())
    none = reqlog.RequestLog(sample=0.0,
                             registry=obs_registry.MetricsRegistry())
    assert all(full.sampled(i) for i in range(100))
    assert not any(none.sampled(i) for i in range(100))


def test_reqlog_file_ring_and_always_logged_kinds(tmp_path):
    path = str(tmp_path / "req.jsonl")
    reg = obs_registry.MetricsRegistry()
    rl = reqlog.RequestLog(path, sample=0.0, ring_records=64,
                           registry=reg)
    for i in range(5):
        rl.record("request", req_id=i + 1, rows=8, latency_ms=1.0)
    rl.record("window", window=1, fp_rate=0.1)
    rl.record("degraded_window", window=2, label="budget")
    rl.close()
    lines = [json.loads(ln) for ln in open(path)]
    # header + the two always-logged kinds; sample=0 drops every
    # request record from the FILE...
    assert [ln["kind"] for ln in lines] == ["header", "window",
                                            "degraded_window"]
    assert lines[0]["schema"] == reqlog.REQLOG_SCHEMA
    assert lines[0]["version"] == reqlog.REQLOG_VERSION
    # ...but the ring (the flight recorder's feed) kept everything
    kinds = [r["kind"] for r in rl.recent()]
    assert kinds.count("request") == 5
    assert reg.counter("reqlog/records").value == 7


def test_reqlog_write_failure_never_raises(tmp_path):
    reg = obs_registry.MetricsRegistry()
    bad = str(tmp_path / "dir-as-file")
    os.mkdir(bad)                              # open(bad, "a") fails
    rl = reqlog.RequestLog(bad, registry=reg)
    rl.record("window", window=1)              # must not raise
    assert reg.counter("reqlog/write_failures").value == 1
    assert rl.recent()                          # ring still records


# -- SLO engine: parsing + budget math ---------------------------------------

def test_slo_parse_named_generic_and_errors():
    specs = slo.parse_specs(
        "predict_p99_ms<50; serve_p999_ms <= 20;"
        "window_wall_p95_s<30;staleness_windows<=2;"
        "degraded_window_rate<0.05;hist:a/b:p90>0.1;"
        "gauge:x/y<=3;ratio:n/a|d/b<0.2")
    kinds = [(s.name, s.kind) for s in specs]
    assert ("predict_p99_ms", "quantile") in kinds
    assert ("staleness_windows", "gauge") in kinds
    assert ("degraded_window_rate", "ratio") in kinds
    by_name = {s.name: s for s in specs}
    assert by_name["predict_p99_ms"].objective == 0.99
    assert by_name["serve_p999_ms"].objective == 0.999
    assert by_name["predict_p99_ms"].threshold_s == pytest.approx(0.05)
    assert by_name["degraded_window_rate"].source_den == \
        "lrb/windows_total"
    for bad in ("predict_p99_ms=50",           # no operator
                "predict_p99_s<50",            # wrong unit
                "nonsense<1",                  # unknown indicator
                "degraded_window_rate<5",      # rate outside (0,1]
                "hist:x:q99<1",                # malformed quantile
                "ratio:only_num<0.1",          # no denominator
                "predict_p100_ms<50",          # p100 is not a quantile
                "hist:x:p500<1",               # p500 must not alias p50
                "predict_p99_ms<abc"):         # non-numeric threshold
        with pytest.raises(ValueError):
            slo.parse_specs(bad)
    assert slo.parse_specs("") == []


def test_slo_quantile_budget_and_burn_math():
    """The unit math: 2 bad of 100 events under a p99 objective means
    the 1%-of-events budget is 2x overspent (remaining -1.0) and the
    first interval burned at 2x; a later all-good interval burns 0 and
    refills the cumulative remaining to 0.5 at 400 events."""
    reg = obs_registry.MetricsRegistry()
    h = obs_registry.latency_histogram("t/lat", reg)
    for v in [0.001] * 98 + [1.0] * 2:
        h.observe(v)
    eng = slo.SloEngine.from_spec("hist:t/lat:p99<0.1", registry=reg)
    row = eng.evaluate()["specs"][0]
    assert row["events"] == 100 and row["bad_events"] == 2
    assert row["budget_remaining"] == pytest.approx(-1.0)
    assert row["burn_rate"] == pytest.approx(2.0)
    assert row["ok"] is False and row["exhausted"] is True
    for _ in range(300):
        h.observe(0.001)
    row = eng.evaluate()["specs"][0]
    assert row["events"] == 400 and row["bad_events"] == 2
    # delta interval was all-good: instantaneous burn 0
    assert row["burn_rate"] == pytest.approx(0.0)
    assert row["budget_remaining"] == pytest.approx(0.5)
    assert row["ok"] is True
    assert row["exhausted"] is True            # the latch holds
    # the budget state became first-class gauges
    assert reg.gauge("slo/t_lat_p99/budget_remaining").value == \
        pytest.approx(0.5)
    assert reg.gauge("slo/t_lat_p99/ok").value == 1.0


def test_slo_ratio_budget_math():
    reg = obs_registry.MetricsRegistry()
    reg.counter("lrb/windows_degraded").add(1)
    reg.counter("lrb/windows_total").add(10)
    eng = slo.SloEngine.from_spec("degraded_window_rate<0.5",
                                  registry=reg)
    row = eng.evaluate()["specs"][0]
    assert row["current"] == pytest.approx(0.1)
    assert row["ok"] is True
    # budget = thr * den = 5 degraded windows allowed; 1 spent
    assert row["budget_remaining"] == pytest.approx(0.8)
    assert row["burn_rate"] == pytest.approx(0.2)


def test_slo_gauge_ticks_and_empty_registry():
    reg = obs_registry.MetricsRegistry()
    eng = slo.SloEngine.from_spec(
        "staleness_windows<=2;hist:none:p99<1;"
        "degraded_window_rate<0.5", registry=reg)
    rep = eng.evaluate()
    # nothing observed anywhere: every budget intact, nothing violating
    assert rep["ok"] is True
    assert rep["budget_remaining_min"] == pytest.approx(1.0)
    reg.gauge("lrb/model_staleness_windows").set(5.0)
    rep = eng.evaluate()
    row = [r for r in rep["specs"]
           if r["name"] == "staleness_windows"][0]
    assert row["ok"] is False and row["current"] == 5.0
    assert row["bad_events"] == 1              # one bad tick
    assert rep["violating"] == 1


def test_slo_exhaustion_triggers_flight_once(tmp_path):
    fr = flight.configure(capacity=32, directory=str(tmp_path),
                          min_dump_interval_s=0.0)
    h = obs_registry.latency_histogram("t/exh")   # default registry
    h.observe(5.0)                                # 1 bad of 1 event
    eng = slo.configure("hist:t/exh:p99<0.1")
    eng.evaluate()
    dumps = fr.dump_paths()
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "slo_budget_exhausted"
    assert doc["context"]["slo"] == "t_exh_p99"
    eng.evaluate()                                # latched: no re-dump
    assert len(fr.dump_paths()) == 1


# -- /healthz + /slo ---------------------------------------------------------

def _get(url, timeout=10):
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type"), r.read()


def test_healthz_first_scrape_race_and_slo_endpoint(tmp_path):
    """/healthz answers 200 JSON BEFORE the first snapshot completes
    (last_snapshot_age_s null), and /slo distinguishes 'not armed'
    from 'down'."""
    from lightgbm_tpu.obs.export import MetricsExporter
    ex = MetricsExporter(base_path=str(tmp_path / "m"), interval_s=60,
                         port=0, registry=obs_registry.MetricsRegistry())
    ex._start_server()                 # server up, NO snapshot yet
    try:
        url = f"http://127.0.0.1:{ex.http_port}"
        status, ctype, body = _get(f"{url}/healthz")
        assert status == 200 and ctype == "application/json"
        h = json.loads(body)
        assert h["ok"] is True and h["alive"] is True
        assert h["last_snapshot_age_s"] is None
        assert h["snapshots_written"] == 0
        assert h["slo"] is None
        status, ctype, body = _get(f"{url}/slo")
        assert status == 200 and ctype == "application/json"
        assert json.loads(body) == {"enabled": False, "specs": []}
        # arm an SLO and scrape again: the report flows through
        obs_registry.gauge("t/healthz_g").set(0.0)
        slo.configure("gauge:t/healthz_g<=1")
        rep = json.loads(_get(f"{url}/slo")[2])
        assert rep["enabled"] is True and len(rep["specs"]) == 1
        assert rep["specs"][0]["ok"] is True
        h = json.loads(_get(f"{url}/healthz")[2])
        assert h["slo"]["specs"] == 1 and h["budget_ok"] is True
    finally:
        ex.stop(final_snapshot=False)


def test_exporter_snapshot_age_gauge_and_slo_evaluation(tmp_path):
    """The exporter thread IS the SLO clock: budgets are evaluated
    every interval (gauges land in the written snapshots) and the
    exporter's own staleness is a gauge."""
    from lightgbm_tpu.obs.export import MetricsExporter
    obs_registry.gauge("t/exp_g").set(0.0)
    eng = slo.configure("gauge:t/exp_g<=1")
    ex = MetricsExporter(base_path=str(tmp_path / "live"),
                         interval_s=0.05).start()
    try:
        deadline = time.monotonic() + 5.0
        while ex.snapshots_written < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ex.snapshots_written >= 3
        assert eng._evaluations >= 2           # the thread evaluated
        assert ex.last_snapshot_age_s() is not None
        rows = [json.loads(ln) for ln in open(ex.jsonl_path)]
        last = rows[-1]["gauges"]
        assert last["slo/t_exp_g/ok"] == 1.0
        assert last["slo/t_exp_g/budget_remaining"] == 1.0
        assert "exporter/last_snapshot_age_s" in last
        # /healthz through the running exporter reports the age too
    finally:
        ex.stop()


def test_concurrent_scrape_hammer_during_live_lrb_run(tmp_path,
                                                       lock_order):
    """N threads hammer /metrics, /metrics.json, /healthz and /slo
    while a real (pipelined) LRB loop trains/serves — every response
    must be 200 and parseable; no torn bodies, no 500s. Runs under
    the lock-order detector: exporter/slo/registry/driver locks must
    record an acyclic acquisition graph."""
    import io
    import urllib.request

    from lightgbm_tpu.obs.export import MetricsExporter
    slo.configure("serve_p99_ms<60000;degraded_window_rate<0.9;"
                  "staleness_windows<=8")
    ex = MetricsExporter(interval_s=0.05, port=0).start()
    url = f"http://127.0.0.1:{ex.http_port}"
    stop = threading.Event()
    failures: list = []
    hits = [0]

    def hammer():
        routes = ("/metrics", "/metrics.json", "/healthz", "/slo")
        i = 0
        while not stop.is_set():
            route = routes[i % len(routes)]
            i += 1
            try:
                with urllib.request.urlopen(url + route,
                                            timeout=10) as r:
                    body = r.read()
                    if r.status != 200:
                        failures.append((route, r.status))
                    elif route != "/metrics":
                        json.loads(body)
                    elif b"_total" not in body and b"# TYPE" not in body:
                        failures.append((route, "empty prom body"))
                hits[0] += 1
            except Exception as e:      # noqa: BLE001 — collected
                failures.append((route, repr(e)))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        drv = lrb.LrbDriver(1 << 16, 200, 100, 0.5, 1,
                            result_file=io.StringIO(),
                            extra_params={"num_iterations": 2,
                                          "verbose": "-1"})
        for req in lrb.synthetic_trace(400, 50):
            drv.process_request(*req)
        drv.drain()
        drv.close()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        ex.stop(final_snapshot=False)
    assert not failures, failures[:5]
    assert hits[0] >= 20                       # the hammer really ran


# -- flight recorder ---------------------------------------------------------

def test_flight_rings_bounded_and_dump_schema_round_trip(tmp_path):
    reg = obs_registry.MetricsRegistry()
    reg.counter("t/c").add(3)
    fr = flight.FlightRecorder(capacity=16, directory=str(tmp_path),
                               registry=reg, min_dump_interval_s=0.0)
    for i in range(50):                        # ring keeps newest 16
        fr.note_span({"name": f"s{i}", "ph": "X", "ts": i, "dur": 1,
                      "pid": 1, "tid": 1})
        fr.note_log(f"line {i}")
    fr.note_metrics({"ts": 1.0, "uptime_s": 2.0,
                     "counters": {"t/c": 3}, "gauges": {},
                     "histograms": {"dropped": {}}})
    path = fr.trigger("watchdog", {"it": 9})
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["schema"] == flight.FLIGHT_SCHEMA
    assert doc["version"] == flight.FLIGHT_VERSION
    assert doc["reason"] == "watchdog"
    assert doc["context"] == {"it": 9}
    assert len(doc["spans"]) == 16
    assert doc["spans"][-1]["name"] == "s49"   # newest kept
    assert len(doc["log_lines"]) == 16
    assert doc["metrics"]["current"]["counters"]["t/c"] == 3
    # compacted exporter snapshots: histograms dropped, counters kept
    assert doc["metrics"]["recent"][0]["counters"] == {"t/c": 3}
    assert "histograms" not in doc["metrics"]["recent"][0]
    assert doc["triggers"][-1]["reason"] == "watchdog"
    assert reg.counter("flight/dumps").value == 1


def test_flight_rate_limit_force_and_pending_sweep(tmp_path):
    reg = obs_registry.MetricsRegistry()
    fr = flight.FlightRecorder(capacity=16, directory=str(tmp_path),
                               registry=reg,
                               min_dump_interval_s=3600.0)
    assert fr.trigger("degraded_window") is not None
    # within the interval: coalesced, recorded, not dumped
    assert fr.trigger("degraded_window") is None
    assert reg.counter("flight/dumps_suppressed").value == 1
    assert len(fr.dump_paths()) == 1
    # force bypasses the interval (SIGTERM / kill faults / exhaustion)
    assert fr.trigger("sigterm", force=True) is not None
    # a coalesced trigger is swept at exit, not lost
    assert fr.trigger("watchdog") is None
    swept = fr.sweep_pending()
    assert swept is not None
    assert json.load(open(swept))["reason"] == "watchdog"
    assert fr.sweep_pending() is None          # nothing pending now
    # the cap stops a runaway non-forced trigger loop — but a forced
    # moment (SIGTERM, kill fault) still leaves its bundle: a capped
    # process must not die evidence-less
    fr2 = flight.FlightRecorder(capacity=16, directory=str(tmp_path),
                                registry=reg, min_dump_interval_s=0.0,
                                max_dumps=2)
    assert fr2.trigger("a") and fr2.trigger("b")
    assert fr2.trigger("c") is None
    assert fr2.trigger("kill_fault", force=True) is not None
    assert len(fr2.dump_paths()) == 3


def test_watchdog_firing_dumps_flight(tmp_path):
    fr = flight.configure(capacity=64, directory=str(tmp_path),
                          min_dump_interval_s=0.0)
    rec = RunRecorder(watchdog_factor=3.0,
                      registry=obs_registry.MetricsRegistry()).start()
    for i in range(8):
        rec.observe_iteration(i + 1, 0.01)
    rec.observe_iteration(9, 10.0)             # 1000x the median
    rec.finish()
    dumps = fr.dump_paths()
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "watchdog"
    assert doc["context"]["it"] == 9


def test_fault_injection_dumps_flight(tmp_path):
    fr = flight.configure(capacity=64, directory=str(tmp_path),
                          min_dump_interval_s=0.0)
    faults.configure("train.iter@1")
    with pytest.raises(faults.InjectedFault):
        faults.check("train.iter", context=1)
    dumps = fr.dump_paths()
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "fault"
    assert doc["context"]["point"] == "train.iter"
    assert doc["context"]["action"] == "raise"


def test_run_report_cross_links_flight_dumps(tmp_path):
    fr = flight.configure(capacity=64, directory=str(tmp_path),
                          min_dump_interval_s=0.0)
    p = fr.trigger("degraded_window", {"window": 2})
    rec = RunRecorder(path=str(tmp_path / "report.json"),
                      registry=obs_registry.MetricsRegistry()).start()
    report = rec.finish()
    assert report["meta"]["flight_dumps"] == [p]
    on_disk = json.load(open(tmp_path / "report.json"))
    assert on_disk["meta"]["flight_dumps"] == [p]


def test_degraded_lrb_window_drill(tmp_path):
    """The acceptance drill: a fault-injected lrb run (PR-8 machinery)
    degrades one window and the black box captures it — a dump with
    the failing window's spans, reqlog wide events and SLO budget
    state; the degraded reason lands as a labeled counter family AND
    a wide event in the reqlog file (never sampled out)."""
    import io
    reqpath = str(tmp_path / "req.jsonl")
    reg = obs_registry.default_registry()
    c0 = reg.counter("lrb/degraded_reason/injected_fault").value
    t0 = reg.counter("lrb/windows_total").value
    drv = lrb.LrbDriver(
        1 << 16, 200, 100, 0.5, 1, result_file=io.StringIO(),
        extra_params={
            "num_iterations": 2, "verbose": "-1",
            "tpu_reqlog": reqpath,
            "tpu_reqlog_sample": 0.0,          # windows still logged
            "tpu_slo": ("serve_p99_ms<60000;degraded_window_rate<0.9;"
                        "staleness_windows<=8"),
            "tpu_faults": "lrb.window_train@2",
            "tpu_lrb_pipeline": 0})
    for req in lrb.synthetic_trace(600, 50):
        drv.process_request(*req)
    drv.drain()
    drv.close()
    res = drv.results
    bad = [r for r in res if r.get("degraded")]
    assert len(bad) == 1 and bad[0]["window"] == 2
    assert bad[0]["degrade_label"] == "injected_fault"
    # labeled counter family: WHY, not just THAT
    assert reg.counter(
        "lrb/degraded_reason/injected_fault").value == c0 + 1
    assert reg.counter("lrb/windows_total").value == t0 + 3
    # the black box dumped (fault trigger and/or degraded-window
    # trigger — one incident coalesces to one bundle)
    assert drv.flight_dumps
    doc = json.load(open(drv.flight_dumps[0]))
    assert doc["reason"] in ("fault", "degraded_window")
    span_names = {e.get("name") for e in doc["spans"]}
    assert "lrb/train" in span_names
    assert "serve/request" in span_names       # the failing window's
    # requests with their ids are in the bundle
    reqs = [r for r in doc["reqlog"] if r["kind"] == "request"]
    assert reqs and all("req_id" in r for r in reqs)
    assert doc["slo"] is not None and doc["slo"]["specs"]
    # the reqlog FILE carries the degraded window despite sample=0
    kinds = [json.loads(ln)["kind"] for ln in open(reqpath)]
    assert "degraded_window" in kinds and "window" in kinds
    assert "request" not in kinds              # sampled out of file


def test_sigterm_subprocess_drill(tmp_path):
    """SIGTERM is a trigger: the dying process leaves a postmortem
    bundle (forced — the moment cannot recur) and still exits by
    signal."""
    child = (
        "import sys, time\n"
        "from lightgbm_tpu.obs import flight\n"
        "flight.configure(capacity=32, directory=sys.argv[1],\n"
        "                 min_dump_interval_s=0.0)\n"
        "print('READY', flush=True)\n"
        "time.sleep(60)\n")
    proc = subprocess.Popen(
        [sys.executable, "-c", child, str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.terminate()                       # SIGTERM
        proc.wait(timeout=30)
    finally:
        proc.kill()
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("flight_") and "sigterm" in f]
    assert len(dumps) == 1
    doc = json.load(open(tmp_path / dumps[0]))
    assert doc["reason"] == "sigterm"
    assert proc.returncode != 0                # died BY the signal


def test_flight_disabled_by_knob():
    assert flight.configure(capacity=0) is None
    assert flight.trigger("watchdog") is None  # no-op, no crash
    assert flight.ensure_from_config({"tpu_flight_buffer": "0"}) is None
    assert flight.get() is None


# -- registry satellite: p99.9 + count_le ------------------------------------

def test_histogram_p999_snapshot_and_prometheus():
    reg = obs_registry.MetricsRegistry()
    h = obs_registry.latency_histogram("t/p999", reg)
    for v in [0.001] * 995 + [2.0] * 5:
        h.observe(v)
    q = h.quantiles()
    assert set(q) == {"p50", "p95", "p99", "p999"}
    assert q["p99"] < 1.0 < q["p999"] <= 2.0   # the tail past p99
    snap = reg.snapshot()["histograms"]["t/p999"]
    assert snap["p999"] == pytest.approx(q["p999"])
    text = obs_export.prometheus_text(reg.snapshot())
    assert "lgbm_tpu_t_p999_p999" in text
    # percentile() semantics unchanged: p50 is still the bulk
    assert h.percentile(0.5) == pytest.approx(0.001, rel=0.3)


def test_histogram_count_le():
    reg = obs_registry.MetricsRegistry()
    h = obs_registry.latency_histogram("t/cle", reg)
    assert h.count_le(1.0) == 0                # empty
    for v in [0.001] * 90 + [1.0] * 10:
        h.observe(v)
    assert h.count_le(2.0) == 100              # >= max: everything
    assert h.count_le(1e-9) == 0               # < min: nothing
    assert h.count_le(0.1) == 90               # between the modes
    # monotone in v
    vals = [h.count_le(v) for v in (1e-4, 1e-3, 1e-2, 0.5, 1.0, 5.0)]
    assert vals == sorted(vals)
    # the one-lock pair the SLO engine reads (bad = total - le can
    # never go negative within one call)
    assert h.count_and_le(0.1) == (100, 90)
