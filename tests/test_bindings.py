"""R and Java binding tests.

The reference ships a 5.2k-LoC R package over C glue (R-package/R/,
src/lightgbm_R.cpp) and a SWIG JVM binding (swig/lightgbmlib.i). Here R
rides reticulate over the Python package and Java marshals through the
config-file CLI. Real interpreter smoke tests run when Rscript / a JDK
exist; the structural checks below always run and pin the binding
sources to the Python surface they call into.
"""
import re
import shutil
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
R_SRC = REPO / "R-package" / "R" / "lightgbm.R"
JAVA_SRC = REPO / "java" / "LightGbmTpu.java"


# --- structural checks (no R / JVM needed) --------------------------------

def test_r_binding_calls_real_python_surface():
    """Every python attribute the R glue dereferences must exist on the
    live Python objects — catches drift without an R interpreter."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.basic import Booster, Dataset

    src = R_SRC.read_text()
    # lgb$<name>( — module-level entry points
    for name in set(re.findall(r"lgb\$(\w+)\(", src)):
        assert hasattr(lgb, name), f"lightgbm_tpu.{name} missing (R glue)"
    # model$/booster$/bst$<name>( — Booster methods
    for name in set(re.findall(r"(?:model|booster|object|x|bst)\$(\w+)\(",
                               src)):
        assert hasattr(Booster, name), f"Booster.{name} missing (R glue)"
    # dataset$<name>( — Dataset methods
    for name in set(re.findall(r"dataset\$(\w+)\(", src)):
        assert hasattr(Dataset, name), f"Dataset.{name} missing (R glue)"


def test_r_binding_covers_reference_core_api():
    src = R_SRC.read_text()
    for fn in ("lgb.Dataset", "lgb.Dataset.create.valid", "lgb.train",
               "lgb.cv", "lightgbm", "predict.lgb.Booster", "lgb.save",
               "lgb.load", "lgb.dump", "lgb.importance",
               "lgb.model.dt.tree", "lgb.interprete",
               "lgb.plot.importance", "lgb.plot.interpretation",
               "lgb.Dataset.save", "lgb.slice.Dataset",
               "lgb.get.eval.result", "getinfo.lgb.Dataset",
               "setinfo.lgb.Dataset", "saveRDS.lgb.Booster",
               "readRDS.lgb.Booster"):
        assert re.search(rf"^{re.escape(fn)} <- function",
                         src, re.M), f"R function {fn} missing"


def test_java_binding_marshals_real_cli_keys():
    """The Java wrapper shells out to the config CLI; every k=v key it
    writes must be a real config key (alias table included)."""
    from lightgbm_tpu.config import Config

    src = JAVA_SRC.read_text()
    keys = set(re.findall(r'argv\.add\("(\w+)=', src))
    cfg = Config()
    for k in keys:
        resolved = Config.key_alias_transform(k)
        assert hasattr(cfg, resolved), f"Java passes unknown key {k}"
    assert "task" in keys and "data" in keys


# --- interpreter smoke tests (gated on toolchain presence) ----------------

@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="Rscript not installed")
def test_r_train_predict_save_load(tmp_path):
    X = np.random.default_rng(0).normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    np.savetxt(tmp_path / "X.csv", X, delimiter=",")
    np.savetxt(tmp_path / "y.csv", y, delimiter=",")
    script = f"""
library(reticulate)
use_python("{sys.executable}", required = TRUE)
source("{R_SRC}")
X <- as.matrix(read.csv("{tmp_path}/X.csv", header = FALSE))
y <- as.numeric(read.csv("{tmp_path}/y.csv", header = FALSE)[[1]])
ds <- lgb.Dataset(X, label = y, num_leaves = 7)
bst <- lgb.train(list(objective = "binary", num_leaves = 7), ds,
                 nrounds = 5, verbose = 0)
p <- predict.lgb.Booster(bst, X)
stopifnot(mean((p > 0.5) == (y > 0.5)) > 0.8)
lgb.save(bst, "{tmp_path}/model.txt")
bst2 <- lgb.load("{tmp_path}/model.txt")
p2 <- predict.lgb.Booster(bst2, X)
stopifnot(max(abs(p - p2)) < 1e-6)
imp <- lgb.importance(bst)
stopifnot(nrow(imp) >= 1)
ii <- lgb.interprete(bst, X, 1:2)
stopifnot(length(ii) == 2)
cat("R-BINDING-OK\\n")
"""
    r = subprocess.run(["Rscript", "-e", script], capture_output=True,
                       text=True, timeout=600)
    assert "R-BINDING-OK" in r.stdout, r.stderr


@pytest.mark.skipif(shutil.which("javac") is None
                    or shutil.which("java") is None,
                    reason="JDK not installed")
def test_java_train_predict(tmp_path):
    X = np.random.default_rng(0).normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    data = tmp_path / "train.csv"
    np.savetxt(data, np.column_stack([y, X]), delimiter=",")
    build = tmp_path / "classes"
    build.mkdir()
    subprocess.run(["javac", "-d", str(build), str(JAVA_SRC)],
                   check=True, timeout=300)
    driver = tmp_path / "Driver.java"
    driver.write_text(f"""
import java.nio.file.*;
import java.util.*;

public class Driver {{
  public static void main(String[] a) throws Exception {{
    LightGbmTpu lgb = new LightGbmTpu("{sys.executable}");
    Map<String, String> params = new HashMap<>();
    params.put("objective", "binary");
    params.put("num_leaves", "7");
    params.put("num_iterations", "5");
    Path model = lgb.train(Paths.get("{data}"), null, params,
                           Paths.get("{tmp_path}/model.txt"));
    double[] p = lgb.predict(model, Paths.get("{data}"), null);
    if (p.length != 200) throw new RuntimeException("bad length");
    System.out.println("JAVA-BINDING-OK");
  }}
}}
""")
    subprocess.run(["javac", "-cp", str(build), "-d", str(build),
                    str(driver)], check=True, timeout=300)
    r = subprocess.run(["java", "-cp", str(build), "Driver"],
                       capture_output=True, text=True, timeout=600)
    assert "JAVA-BINDING-OK" in r.stdout, r.stderr


JAVA_FFM_SRC = REPO / "java" / "LightGbmTpuNative.java"
C_ABI_SRC = REPO / "native" / "c_api_embed.cpp"


def test_java_ffm_binding_symbols_exist_in_c_abi():
    """Every native symbol the Panama-FFM binding downcalls must be an
    exported entry point of native/c_api_embed.cpp — pins the in-process
    surface (create/train/predict/save/load/eval/free) against the .so."""
    import re
    src = JAVA_FFM_SRC.read_text()
    syms = set(re.findall(r'down\("(LGBM_\w+)"', src))
    assert len(syms) >= 15, sorted(syms)
    required = {
        "LGBM_DatasetCreateFromMatC", "LGBM_DatasetCreateFromFile",
        "LGBM_DatasetSetField", "LGBM_DatasetFree",
        "LGBM_BoosterCreateC", "LGBM_BoosterCreateFromModelfile",
        "LGBM_BoosterUpdateOneIter", "LGBM_BoosterPredictForMatC",
        "LGBM_BoosterSaveModel", "LGBM_BoosterGetEval",
        "LGBM_BoosterFree",
    }
    assert required <= syms, required - syms
    cpp = C_ABI_SRC.read_text()
    exported = set(re.findall(
        r"LIGHTGBM_C_EXPORT[\w\s*]+?(LGBM_\w+)\s*\(", cpp))
    missing = syms - exported
    assert not missing, f"FFM binds symbols the .so does not export: " \
                        f"{sorted(missing)}"
    # per-row predict (the point of an in-process binding) is present
    assert "predictRow" in src


@pytest.mark.skipif(shutil.which("javac") is None
                    or shutil.which("java") is None,
                    reason="no JDK in image")
def test_java_ffm_train_predict_inprocess(tmp_path):
    """Compile the FFM binding and run its main(): in-process train,
    per-row predict, save, reload, re-predict through the embedded
    .so — no subprocess spawn per call."""
    import os
    import sysconfig
    so = tmp_path / "liblightgbm_tpu.so"
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    r = subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++14",
         str(REPO / "native" / "c_api_embed.cpp"), "-o", str(so),
         f"-I{inc}", f"-L{libdir}", f"-l{pyver}", "-ldl", "-lm",
         f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    build = tmp_path / "classes"
    subprocess.run(["javac", "-d", str(build), str(JAVA_FFM_SRC)],
                   check=True)
    env = dict(os.environ, PYTHONPATH=str(REPO))
    r = subprocess.run(
        ["java", "--enable-native-access=ALL-UNNAMED", "-cp",
         str(build), "LightGbmTpuNative", str(so),
         str(tmp_path / "model.txt")],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "JAVA_FFM_OK" in r.stdout
