"""Categorical split tests.

Covers the categorical pipeline end-to-end (reference:
src/treelearner/feature_histogram.hpp:112-234 split search,
src/io/tree.cpp SplitCategorical / CategoricalDecision, test_engine.py
test_categorical_handle): binning, device split search + partition,
host-tree bitsets, serialization round-trip, and quality vs treating
the same column as numerical.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cat_problem(n=1200, n_cat=12, seed=5):
    """Label depends on a scrambled category -> numerical split on the
    raw code cannot separate it, a categorical k-vs-rest can."""
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, n_cat, n)
    # scrambled "good" categories (non-contiguous codes)
    good = {1, 4, 7, 10}
    logit = np.where(np.isin(cat, list(good)), 2.0, -2.0)
    y = (logit + 0.5 * rng.normal(size=n) > 0).astype(np.float64)
    X = np.column_stack([cat.astype(np.float64),
                         rng.normal(size=n)])
    return X, y


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty(len(s)); ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


class TestCategoricalTraining:
    def test_categorical_beats_numerical(self):
        X, y = _cat_problem()
        params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
                  "min_data_in_leaf": 5, "min_data_per_group": 5,
                  "cat_smooth": 1.0}
        # ONE tree each: a single k-vs-rest categorical split separates
        # the scrambled good-set; a single numerical threshold cannot
        # (boosted numerical trees would eventually memorize the codes)
        cat = lgb.train(params, lgb.Dataset(X, y, categorical_feature=[0]),
                        num_boost_round=1, verbose_eval=False)
        num = lgb.train(params, lgb.Dataset(X, y),
                        num_boost_round=1, verbose_eval=False)
        auc_cat = _auc(y, cat.predict(X, raw_score=True))
        auc_num = _auc(y, num.predict(X, raw_score=True))
        assert auc_cat > 0.97
        assert auc_cat > auc_num + 0.02
        # a categorical split actually exists in the model
        cat._gbdt._ensure_host_trees()
        assert any(t.num_cat > 0 for t in cat._gbdt.models)

    def test_model_roundtrip_with_cats(self):
        X, y = _cat_problem()
        params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
                  "min_data_in_leaf": 5, "min_data_per_group": 5,
                  "cat_smooth": 1.0}
        gbm = lgb.train(params, lgb.Dataset(X, y, categorical_feature=[0]),
                        num_boost_round=8, verbose_eval=False)
        s = gbm.model_to_string()
        assert "num_cat=" in s
        loaded = lgb.Booster(model_str=s)
        np.testing.assert_allclose(loaded.predict(X), gbm.predict(X),
                                   atol=1e-5)
        # unseen category routes right like the reference (no bit set)
        X2 = X.copy()
        X2[:5, 0] = 99
        p = loaded.predict(X2)
        assert np.isfinite(p).all()

    def test_one_hot_mode(self):
        # cardinality <= max_cat_to_onehot uses single-category splits
        rng = np.random.default_rng(0)
        n = 800
        cat = rng.integers(0, 3, n)
        y = (cat == 1).astype(np.float64)
        X = np.column_stack([cat.astype(np.float64), rng.normal(size=n)])
        params = {"objective": "binary", "num_leaves": 5, "verbose": -1,
                  "max_cat_to_onehot": 4, "min_data_in_leaf": 5}
        gbm = lgb.train(params, lgb.Dataset(X, y, categorical_feature=[0]),
                        num_boost_round=10, verbose_eval=False)
        acc = ((gbm.predict(X) > 0.5) == y).mean()
        assert acc > 0.98

    def test_continue_training_with_cats(self):
        X, y = _cat_problem()
        params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
                  "min_data_in_leaf": 5, "min_data_per_group": 5,
                  "cat_smooth": 1.0}
        from lightgbm_tpu.models.gbdt import GBDT
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.io.dataset import TpuDataset, Metadata
        from lightgbm_tpu.objectives import create_objective
        gbm = lgb.train(params, lgb.Dataset(X, y, categorical_feature=[0]),
                        num_boost_round=5, verbose_eval=False)
        s = gbm.model_to_string()
        cfg = Config().set(params)
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=y), categorical=[0])
        obj = create_objective("binary", cfg)
        obj.init(ds.metadata, ds.num_data)
        g2 = GBDT()
        g2.load_model_from_string(s)
        g2.init_from_loaded(cfg, ds, obj, [])
        base = g2.predict_raw(X)
        np.testing.assert_allclose(base, gbm.predict(X, raw_score=True),
                                   atol=2e-4)
        for _ in range(3):
            g2.train_one_iter()
        assert g2.current_iteration == 8
