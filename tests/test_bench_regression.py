"""Bench-regression gate (tools/check_bench_regression.py): artifact
normalization (raw bench JSON + BENCH_r0x wrappers, tail-AUC
recovery), schema validation, trajectory comparison semantics, and a
slow-marked end-to-end run of ``bench.py --quick`` through the tool.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), os.pardir)
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_bench_regression as cbr  # noqa: E402

pytestmark = pytest.mark.obs


def _fresh(metric="M", value=50.0, test_auc=0.927, **kw):
    d = {"metric": metric, "value": value, "unit": "M row-iters/s",
         "test_auc": test_auc}
    d.update(kw)
    return d


# -- normalization -----------------------------------------------------------

def test_load_bench_raw_and_wrapper(tmp_path):
    raw = _fresh()
    p = tmp_path / "fresh.json"
    p.write_text(json.dumps(raw))
    assert cbr.load_bench(str(p))["value"] == 50.0
    # BENCH_r0x wrapper: numbers under "parsed", AUC only in the tail
    wrapper = {"rc": 0,
               "tail": "# 500 iters in 112.1s  train-AUC=0.93202  "
                       "test-AUC=0.92726  (holdout...)",
               "parsed": {"metric": "M", "value": 48.954,
                          "unit": "M row-iters/s"}}
    norm = cbr.load_bench(wrapper)
    assert norm["value"] == 48.954
    assert norm["test_auc"] == pytest.approx(0.92726)
    assert norm["train_auc"] == pytest.approx(0.93202)


def test_trajectory_orders_numerically(tmp_path):
    """r10 must sort AFTER r9 (lexicographic order would pin the gate
    to a stale baseline once the run index grows a digit)."""
    for name in ("BENCH_r9.json", "BENCH_r10.json", "BENCH_r2.json"):
        (tmp_path / name).write_text("{}")
    names = [os.path.basename(p) for p in cbr.trajectory(str(tmp_path))]
    assert names == ["BENCH_r2.json", "BENCH_r9.json", "BENCH_r10.json"]


def test_repo_trajectory_loads_and_self_passes():
    """The repo's own BENCH_r0x files normalize, and the latest point
    compared against itself passes (the tool's identity check)."""
    points = cbr.trajectory(REPO)
    assert len(points) >= 2, "BENCH_r0x trajectory missing from repo"
    latest = cbr.load_bench(points[-1])
    assert not cbr.check_schema(latest)
    assert latest.get("test_auc") is not None, \
        "tail AUC recovery failed on the real trajectory"
    assert cbr.compare(latest, latest) == []


# -- schema ------------------------------------------------------------------

def test_check_schema():
    assert cbr.check_schema(_fresh()) == []
    assert cbr.check_schema({"unit": "rows"})   # several problems
    bad_lat = _fresh(predict_latency={"p50_ms": 1.0, "p95_ms": None,
                                      "p99_ms": 2.0})
    assert any("p95" in p for p in cbr.check_schema(bad_lat))
    good_lat = _fresh(predict_latency={"p50_ms": 1.0, "p95_ms": 2.0,
                                       "p99_ms": 3.0})
    assert cbr.check_schema(good_lat) == []
    # malformed artifact must be REPORTED, not crash the validator
    assert any("not a dict" in p for p in
               cbr.check_schema(_fresh(predict_latency="n/a")))


# -- comparison semantics ----------------------------------------------------

def test_compare_throughput_and_auc():
    base = _fresh(value=49.0, test_auc=0.927)
    assert cbr.compare(_fresh(value=45.0, test_auc=0.9275), base) == []
    # throughput: 20% tolerance boundary
    probs = cbr.compare(_fresh(value=35.0), base)
    assert probs and "throughput regression" in probs[0]
    assert cbr.compare(_fresh(value=39.3), base) == []
    # quality: absolute AUC drop beyond tolerance
    probs = cbr.compare(_fresh(test_auc=0.920), base)
    assert probs and "quality regression" in probs[0]
    # a fresh run that LOST the AUC field cannot silently pass
    fresh = _fresh()
    del fresh["test_auc"]
    assert any("no test_auc" in p for p in cbr.compare(fresh, base))


def test_compare_latency_gate():
    """predict_latency p50/p99 within --latency-tol of a baseline that
    carries the quantiles; old baselines without the field gate
    nothing; a fresh run that lost the field cannot silently pass."""
    lat = {"p50_ms": 10.0, "p95_ms": 15.0, "p99_ms": 20.0}
    base = _fresh(predict_latency=dict(lat))
    # within 50%: pass
    ok = _fresh(predict_latency={"p50_ms": 14.0, "p95_ms": 21.0,
                                 "p99_ms": 29.0})
    assert cbr.compare(ok, base) == []
    # p50 beyond tolerance: regression names the quantile
    slow = _fresh(predict_latency={"p50_ms": 16.0, "p95_ms": 16.0,
                                   "p99_ms": 21.0})
    probs = cbr.compare(slow, base)
    assert probs and "latency regression" in probs[0] \
        and "p50_ms" in probs[0]
    # p99 tail regression caught independently of a healthy p50
    tail = _fresh(predict_latency={"p50_ms": 9.0, "p95_ms": 16.0,
                                   "p99_ms": 40.0})
    probs = cbr.compare(tail, base)
    assert len(probs) == 1 and "p99_ms" in probs[0]
    # tolerance flag respected
    assert cbr.compare(slow, base, latency_tol=1.0) == []
    # baseline predates the field: nothing to gate
    assert cbr.compare(_fresh(predict_latency={"p50_ms": 999.0,
                                               "p95_ms": 999.0,
                                               "p99_ms": 999.0}),
                       _fresh()) == []
    # fresh LOST the field vs a baseline that has it
    probs = cbr.compare(_fresh(), base)
    assert any("no predict_latency" in p for p in probs)
    # cross-workload refusal still wins over everything
    probs = cbr.compare(_fresh(metric="other", predict_latency=lat),
                        base)
    assert len(probs) == 1 and "not comparable" in probs[0]


def test_cli_latency_tol_flag(tmp_path):
    """--latency-tol reaches the comparison (exit 1 at the default,
    exit 0 when widened)."""
    base_dir = tmp_path / "repo"
    base_dir.mkdir()
    lat = {"p50_ms": 10.0, "p95_ms": 15.0, "p99_ms": 20.0}
    (base_dir / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": _fresh(value=49.0, predict_latency=lat)}))
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(_fresh(
        value=49.0, predict_latency={"p50_ms": 18.0, "p95_ms": 20.0,
                                     "p99_ms": 22.0})))
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir)]) == 1
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir),
                     "--latency-tol", "1.0"]) == 0


def test_compare_refuses_cross_workload():
    base = _fresh(metric="HIGGS 11000000 rows")
    probs = cbr.compare(_fresh(metric="quick 65536 rows", value=1.0),
                        base)
    assert len(probs) == 1 and "not comparable" in probs[0]


def test_compare_refuses_cross_backend():
    """bench.py stamps the device kind into the metric string
    (bench._metric_tag), so a CPU number is structurally incomparable
    with a GPU or TPU trajectory point — compare() refuses instead of
    ratioing across backends."""
    shape = "HIGGS-class GBDT training throughput (65536 rows)"
    base = _fresh(metric=shape + " [NVIDIA H100]")
    probs = cbr.compare(_fresh(metric=shape + " [cpu]", value=1.0),
                        base)
    assert len(probs) == 1 and "not comparable" in probs[0]
    # the refusal names both stamps so a sweep log is self-explaining
    assert "[cpu]" in probs[0] and "[NVIDIA H100]" in probs[0]


def test_metric_tag_matches_device_kind():
    """The stamp bench.py appends is exactly the autotuner's device
    kind in brackets — the same value the parity section records, so
    the metric-string gate and _parity_comparable agree on identity."""
    sys.path.insert(0, REPO)
    import bench
    from lightgbm_tpu.ops import autotune
    assert bench._metric_tag() == f" [{autotune.device_kind()}]"


def test_cli_cross_backend_exit_2_and_walkback(tmp_path):
    """A fresh CPU run against a trajectory whose NEWEST point was
    recorded on GPU: baseline selection filters on metric equality, so
    it walks back past the non-matching-backend point to the newest
    same-device one and gates there; a fresh run from a backend with
    no trajectory point at all is refused (exit 2), never ratioed
    against another device's numbers."""
    shape = "HIGGS-class GBDT training throughput (65536 rows)"
    base_dir = tmp_path / "repo"
    base_dir.mkdir()
    (base_dir / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": _fresh(metric=shape + " [cpu]", value=49.0)}))
    (base_dir / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": _fresh(metric=shape + " [NVIDIA H100]",
                          value=490.0)}))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_fresh(metric=shape + " [cpu]",
                                    value=48.0)))
    # walk-back past the newer GPU point: 48 passes the 49 CPU floor
    # (against the GPU point it would read as a 10x regression)
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(_fresh(metric=shape + " [cpu]",
                                      value=10.0)))
    # ...and the walked-back point still GATES (exit 1 = regression)
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir)]) == 1
    tpu = tmp_path / "tpu.json"
    tpu.write_text(json.dumps(_fresh(metric=shape + " [TPU v4]",
                                     value=700.0)))
    # no TPU point anywhere on the trajectory: refusal, exit 2
    assert cbr.main([str(tpu), "--baseline-dir", str(base_dir)]) == 2


def test_cli_pass_fail_and_exit_codes(tmp_path):
    base_dir = tmp_path / "repo"
    base_dir.mkdir()
    (base_dir / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": _fresh(value=49.0)}))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_fresh(value=48.0)))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_fresh(value=10.0)))
    garbled = tmp_path / "garbled.json"
    garbled.write_text(json.dumps({"unit": "bananas"}))
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0
    assert cbr.main([str(bad), "--baseline-dir", str(base_dir)]) == 1
    assert cbr.main([str(garbled), "--baseline-dir",
                     str(base_dir)]) == 2
    assert cbr.main([str(bad), "--schema-only"]) == 0


# -- lrb-stream (retrain-while-serve) gate -----------------------------------

def _stream(requests_per_s=230.0, staleness=0.0, p99d=45.0, **kw):
    d = {"windows": 8, "window_rows": 2048,
         "requests_per_s": requests_per_s,
         "staleness_p99_windows": staleness,
         "serve_p99_during_retrain_ms": p99d,
         "speedup": 2.5}
    d.update(kw)
    return d


def test_check_schema_lrb_stream():
    # the standalone --lrb-stream line: unit requests/s + stream block
    standalone = {"metric": "LRB streaming retrain-while-serve (8...)",
                  "value": 230.0, "unit": "requests/s",
                  "lrb_stream": _stream()}
    assert cbr.check_schema(standalone) == []
    # a training line CARRYING the appended stream section
    assert cbr.check_schema(_fresh(lrb_stream=_stream())) == []
    # requests/s without the block is a shape problem
    assert any("lrb_stream" in p for p in cbr.check_schema(
        {"metric": "m", "value": 1.0, "unit": "requests/s"}))
    # missing gate fields are named
    broken = _stream()
    del broken["requests_per_s"]
    assert any("requests_per_s" in p
               for p in cbr.check_schema(_fresh(lrb_stream=broken)))
    # during-retrain p99 may be null (fast trainer), not a wrong type
    assert cbr.check_schema(_fresh(lrb_stream=_stream(p99d=None))) == []
    assert any("serve_p99_during_retrain_ms" in p for p in
               cbr.check_schema(_fresh(lrb_stream=_stream(p99d="n/a"))))
    assert any("not a dict" in p
               for p in cbr.check_schema(_fresh(lrb_stream="n/a")))


def _sparse_block(**kw):
    d = {"rows": 200_000, "features": 256, "density": 0.0098,
         "nnz": 501_760, "iters": 30,
         "routes": {
             "dense": {"route": "dense", "ingest_s": 3.2,
                       "train_s": 41.0, "rows_per_s": 146341.0,
                       "peak_rss_mb": 1410.2,
                       "sparse_hist_tier": False,
                       "model_sha1": "aa"},
             "csr": {"route": "csr", "ingest_s": 0.8, "train_s": 39.5,
                     "rows_per_s": 151898.0, "peak_rss_mb": 620.4,
                     "sparse_hist_tier": True, "model_sha1": "aa"}},
         "peak_rss_ratio": 2.273, "model_parity": True}
    d.update(kw)
    return d


def test_check_schema_sparse():
    # the standalone --sparse line: unit rows/s + sparse block
    standalone = {"metric": "sparse CTR GBDT training (200000 rows x "
                            "256 feat, density 0.0098, 30 iters)",
                  "value": 151898.0, "unit": "rows/s",
                  "sparse": _sparse_block()}
    assert cbr.check_schema(standalone) == []
    # rows/s without the block is a shape problem
    assert any("sparse" in p for p in cbr.check_schema(
        {"metric": "m", "value": 1.0, "unit": "rows/s"}))
    # missing route metrics are named per route
    broken = _sparse_block()
    del broken["routes"]["csr"]["peak_rss_mb"]
    assert any("routes.csr.peak_rss_mb" in p for p in cbr.check_schema(
        dict(standalone, sparse=broken)))
    no_dense = _sparse_block()
    del no_dense["routes"]["dense"]
    assert any("routes.dense" in p for p in cbr.check_schema(
        dict(standalone, sparse=no_dense)))
    # diverged models across routes fail the artifact outright
    assert any("model_parity" in p for p in cbr.check_schema(
        dict(standalone, sparse=_sparse_block(model_parity=False))))
    # wrong container types are reported, not crashed on
    assert any("not a dict" in p for p in cbr.check_schema(
        dict(standalone, sparse="n/a")))
    assert any("sparse.routes" in p for p in cbr.check_schema(
        dict(standalone, sparse=_sparse_block(routes=7))))
    # cross-workload refusal still wins: a sparse line never compares
    # against a HIGGS training baseline
    assert cbr.compare(standalone, _fresh())[0].startswith(
        "not comparable")


def _rank_block(**kw):
    d = {"rows": 200_000, "features": 16, "qsize": 50, "iters": 30,
         "routes": {
             "memory": {"route": "memory", "queries": 4000,
                        "ingest_s": 5.1, "train_s": 62.0,
                        "rows_per_s": 96774.0, "peak_rss_mb": 1810.0,
                        "ndcg": {"ndcg@1": 0.91, "ndcg@5": 0.87},
                        "ndcg_goss": {"ndcg@1": 0.90, "ndcg@5": 0.86},
                        "retrain_step_cache": {"hits": 2, "misses": 0,
                                               "hit_rate": 1.0},
                        "model_sha1": "bb"},
             "ooc": {"route": "ooc", "queries": 4000, "ingest_s": 6.3,
                     "train_s": 63.0, "rows_per_s": 95238.0,
                     "peak_rss_mb": 705.0,
                     "ndcg": {"ndcg@1": 0.91, "ndcg@5": 0.87},
                     "ndcg_goss": {"ndcg@1": 0.90, "ndcg@5": 0.86},
                     "retrain_step_cache": {"hits": 2, "misses": 0,
                                            "hit_rate": 1.0},
                     "model_sha1": "bb"}},
         "peak_rss_ratio": 2.567, "step_cache_hit_rate": 1.0,
         "model_parity": True}
    d.update(kw)
    return d


def test_check_schema_rank():
    # the standalone --rank line: unit rows/s + rank block (the
    # section key disambiguates it from --sparse, which shares the
    # unit)
    standalone = {"metric": "lambdarank ranking training (200000 rows "
                            "x 16 feat, 50-row queries, 30 iters, "
                            "out-of-core)",
                  "value": 95238.0, "unit": "rows/s",
                  "rank": _rank_block()}
    assert cbr.check_schema(standalone) == []
    # missing route metrics are named per route
    broken = _rank_block()
    del broken["routes"]["ooc"]["peak_rss_mb"]
    assert any("rank.routes.ooc.peak_rss_mb" in p
               for p in cbr.check_schema(dict(standalone, rank=broken)))
    no_mem = _rank_block()
    del no_mem["routes"]["memory"]
    assert any("rank.routes.memory" in p for p in cbr.check_schema(
        dict(standalone, rank=no_mem)))
    # NDCG must survive as a non-empty numeric dict — the quality
    # ledger must not silently disappear
    no_ndcg = _rank_block()
    no_ndcg["routes"]["ooc"]["ndcg"] = {}
    assert any("rank.routes.ooc.ndcg" in p for p in cbr.check_schema(
        dict(standalone, rank=no_ndcg)))
    # the step-cache hit rate and RSS ratio are the PR's headline
    # observables — a line that lost them fails shape
    for k in ("peak_rss_ratio", "step_cache_hit_rate"):
        gone = _rank_block()
        del gone[k]
        assert any(f"rank.{k}" in p for p in cbr.check_schema(
            dict(standalone, rank=gone)))
    # OOC promises BIT parity: diverged models fail the artifact
    assert any("model_parity" in p for p in cbr.check_schema(
        dict(standalone, rank=_rank_block(model_parity=False))))
    # wrong container types are reported, not crashed on
    assert any("not a dict" in p for p in cbr.check_schema(
        dict(standalone, rank="n/a")))
    assert any("rank.routes" in p for p in cbr.check_schema(
        dict(standalone, rank=_rank_block(routes=7))))
    # cross-workload refusal still wins — a rank line never compares
    # against a sparse line even though they share the rows/s unit
    sparse_line = {"metric": "sparse CTR GBDT training (...)",
                   "value": 151898.0, "unit": "rows/s",
                   "sparse": _sparse_block()}
    assert cbr.compare(standalone, sparse_line)[0].startswith(
        "not comparable")


def test_compare_rank_gate():
    metric = ("lambdarank ranking training (200000 rows x 16 feat, "
              "50-row queries, 30 iters, out-of-core)")

    def line(**kw):
        return {"metric": metric, "value": 95238.0, "unit": "rows/s",
                "rank": _rank_block(**kw)}

    def with_ooc(**route_kw):
        blk = _rank_block()
        blk["routes"]["ooc"].update(route_kw)
        return {"metric": metric, "value": 95238.0, "unit": "rows/s",
                "rank": blk}

    base = line()
    # same numbers: pass
    assert cbr.compare(line(), base) == []
    # NDCG floor (--auc-tol): ranking quality must not silently decay
    probs = cbr.compare(
        with_ooc(ndcg={"ndcg@1": 0.80, "ndcg@5": 0.87}), base)
    assert probs and "ranking-quality regression" in probs[0]
    assert "ndcg@1" in probs[0]
    # within the tolerance: pass
    assert cbr.compare(
        with_ooc(ndcg={"ndcg@1": 0.9095, "ndcg@5": 0.87}), base) == []
    # OOC peak-RSS ceiling (--latency-tol slack): RSS creep back
    # toward the in-memory watermark is the regression OOC prevents
    probs = cbr.compare(with_ooc(peak_rss_mb=1500.0), base)
    assert probs and "out-of-core RSS regression" in probs[0]
    assert cbr.compare(with_ooc(peak_rss_mb=900.0), base) == []
    # a fresh run that LOST the section against a carrier is a problem
    lost = {"metric": metric, "value": 95238.0, "unit": "rows/s"}
    probs = cbr.compare(lost, base)
    assert probs and "no rank section" in probs[0]
    # a baseline without the section gates nothing
    assert cbr.compare(line(), lost) == []
    # headline rows/s still rides the generic value floor
    slow = line()
    slow["value"] = 10_000.0
    probs = cbr.compare(slow, base)
    assert probs and "throughput regression" in probs[0]


def test_compare_lrb_stream_gate():
    base = _fresh(lrb_stream=_stream(requests_per_s=200.0,
                                     staleness=0.0))
    # within tolerance: pass
    assert cbr.compare(_fresh(lrb_stream=_stream(
        requests_per_s=190.0, staleness=0.5)), base) == []
    # sustained requests/s floor (same 20% tolerance as throughput)
    probs = cbr.compare(_fresh(lrb_stream=_stream(
        requests_per_s=100.0)), base)
    assert probs and "serving-throughput regression" in probs[0]
    # staleness lag ceiling: absolute slack in windows
    probs = cbr.compare(_fresh(lrb_stream=_stream(staleness=2.0)),
                        base)
    assert probs and "staleness regression" in probs[0]
    assert cbr.compare(_fresh(lrb_stream=_stream(staleness=2.0)),
                       base, staleness_slack=3.0) == []
    # old baselines without the section gate nothing
    assert cbr.compare(_fresh(lrb_stream=_stream(
        requests_per_s=1.0, staleness=99.0)), _fresh()) == []
    # a fresh run that LOST the section cannot silently pass
    probs = cbr.compare(_fresh(), base)
    assert any("no lrb_stream.requests_per_s" in p for p in probs)
    # cross-workload refusal still wins
    probs = cbr.compare(_fresh(metric="other",
                               lrb_stream=_stream()), base)
    assert len(probs) == 1 and "not comparable" in probs[0]
    # a baseline with a DIFFERENT stream shape gates nothing: the
    # training metric string does not embed the stream geometry, so
    # requests/s from a 4x-larger window is not a comparable floor
    assert cbr.compare(
        _fresh(lrb_stream=_stream(requests_per_s=10.0,
                                  window_rows=512)), base) == []


def test_cli_lrb_stream_walks_back_to_latest_carrier(tmp_path):
    """When the newest trajectory point predates the stream bench,
    the lrb-stream fields gate against the LATEST same-workload point
    that carries them — old points gate nothing beyond that."""
    base_dir = tmp_path / "repo"
    base_dir.mkdir()
    (base_dir / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": _fresh(value=49.0,
                          lrb_stream=_stream(requests_per_s=200.0))}))
    (base_dir / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": _fresh(value=49.0)}))      # newest: no stream block
    slow_serve = tmp_path / "fresh.json"
    slow_serve.write_text(json.dumps(_fresh(
        value=49.0, lrb_stream=_stream(requests_per_s=50.0))))
    assert cbr.main([str(slow_serve), "--baseline-dir",
                     str(base_dir)]) == 1
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_fresh(
        value=49.0, lrb_stream=_stream(requests_per_s=195.0))))
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0
    # --staleness-slack reaches the comparison
    lagged = tmp_path / "lagged.json"
    lagged.write_text(json.dumps(_fresh(
        value=49.0, lrb_stream=_stream(requests_per_s=200.0,
                                       staleness=0.8))))
    assert cbr.main([str(lagged), "--baseline-dir",
                     str(base_dir)]) == 0
    assert cbr.main([str(lagged), "--baseline-dir", str(base_dir),
                     "--staleness-slack", "0.25"]) == 1
    # a newest point carrying a DIFFERENT stream shape must not
    # disable the gate either: walk back to the same-shape carrier
    (base_dir / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": _fresh(value=49.0,
                          lrb_stream=_stream(requests_per_s=5000.0,
                                             window_rows=256))}))
    assert cbr.main([str(slow_serve), "--baseline-dir",
                     str(base_dir)]) == 1
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0
    # a fresh run that LOST the section cannot hide behind a newest
    # point that also lacks it: the walk-back still finds the carrier
    lost = tmp_path / "lost.json"
    lost.write_text(json.dumps(_fresh(value=49.0)))
    assert cbr.main([str(lost), "--baseline-dir", str(base_dir)]) == 1


# -- fleet serving (bench.py --fleet) gate -----------------------------------

def _fleet_block(requests_per_s=130.0, worst_p99=70.0, **kw):
    d = {"tenants": 4, "requests_per_tenant": 300,
         "rows_per_request": 4, "streams_per_tenant": 2,
         "coalesce_us": 2000,
         "requests_per_s": requests_per_s,
         "requests_per_s_sequential": 78.0,
         "coalescing_speedup": 1.66,
         "per_tenant": {
             f"tenant_{i:02d}": {"requests": 300, "p50_ms": 30.0,
                                 "p99_ms": (worst_p99 if i == 0
                                            else 55.0),
                                 "shed": 0}
             for i in range(4)},
         "registry_hit_rate": 0.75, "registry_lookups": 8,
         "coalesced_batch_rows": {"batches": 505, "mean": 2.4,
                                  "p50": 2.0, "p99": 8.0},
         "shed_total": 0, "queue_rejects": 0,
         "requests_total": 1364, "client_retries": 0}
    d.update(kw)
    return d


def _fleet_doc(metric="fleet coalesced serving (4 tenants x 300 "
                      "requests, 4-row requests)", **kw):
    # top-level value pinned so these tests exercise the FLEET gates,
    # not the generic throughput floor (which reads ``value``)
    d = {"metric": metric, "unit": "requests/s", "value": 130.0,
         "fleet": _fleet_block()}
    d.update(kw)
    return d


def test_check_schema_fleet():
    # the standalone --fleet line: unit requests/s + fleet block, and
    # it must NOT be mistaken for an lrb_stream artifact
    assert cbr.check_schema(_fleet_doc()) == []
    # missing gate fields are named
    for k in ("requests_per_s", "requests_per_s_sequential",
              "shed_total", "queue_rejects", "tenants"):
        broken = _fleet_block()
        del broken[k]
        assert any(f"fleet.{k}" in p for p in
                   cbr.check_schema(_fleet_doc(fleet=broken)))
    # per-tenant quantiles must be numeric; null is a problem (a shed
    # count of 0 is fine, a MISSING quantile is lost evidence)
    broken = _fleet_block()
    broken["per_tenant"]["tenant_00"]["p99_ms"] = None
    assert any("per_tenant.tenant_00.p99_ms" in p for p in
               cbr.check_schema(_fleet_doc(fleet=broken)))
    assert any("per_tenant" in p for p in cbr.check_schema(
        _fleet_doc(fleet=_fleet_block(per_tenant={}))))
    assert any("per_tenant.t is" in p for p in cbr.check_schema(
        _fleet_doc(fleet=_fleet_block(per_tenant={"t": "n/a"}))))
    # registry hit rate: null only legitimate with zero lookups
    assert any("registry_hit_rate null" in p for p in cbr.check_schema(
        _fleet_doc(fleet=_fleet_block(registry_hit_rate=None))))
    assert cbr.check_schema(_fleet_doc(fleet=_fleet_block(
        registry_hit_rate=None, registry_lookups=0))) == []
    assert any("registry_hit_rate is" in p for p in cbr.check_schema(
        _fleet_doc(fleet=_fleet_block(registry_hit_rate="n/a"))))
    # batch-size histogram must exist (coalescing evidence)
    assert any("coalesced_batch_rows" in p for p in cbr.check_schema(
        _fleet_doc(fleet=_fleet_block(coalesced_batch_rows=None))))
    assert any("coalesced_batch_rows.batches" in p
               for p in cbr.check_schema(_fleet_doc(
                   fleet=_fleet_block(coalesced_batch_rows={}))))
    # wrong container type is reported, not crashed on
    assert any("not a dict" in p for p in
               cbr.check_schema(_fleet_doc(fleet="n/a")))


def test_compare_fleet_gate():
    base = _fleet_doc()
    # within tolerance: pass
    assert cbr.compare(_fleet_doc(fleet=_fleet_block(
        requests_per_s=110.0, worst_p99=90.0)), base) == []
    # aggregate requests/s floor (same 20% tolerance as throughput)
    probs = cbr.compare(_fleet_doc(fleet=_fleet_block(
        requests_per_s=60.0)), base)
    assert probs and "fleet-throughput regression" in probs[0]
    # worst-tenant p99 ceiling — no tenant's tail may quietly rot
    # behind a healthy aggregate
    probs = cbr.compare(_fleet_doc(fleet=_fleet_block(
        worst_p99=500.0)), base)
    assert probs and "fleet-latency regression" in probs[0] \
        and "worst-tenant p99" in probs[0]
    # tolerance knobs reach both gates
    assert cbr.compare(_fleet_doc(fleet=_fleet_block(
        requests_per_s=60.0)), base, throughput_tol=0.6) == []
    assert cbr.compare(_fleet_doc(fleet=_fleet_block(
        worst_p99=500.0)), base, latency_tol=9.0) == []
    # old baselines without the section gate nothing
    no_fleet = dict(_fleet_doc())
    del no_fleet["fleet"]
    assert cbr.compare(_fleet_doc(fleet=_fleet_block(
        requests_per_s=1.0, worst_p99=9999.0)), no_fleet) == []
    # a fresh run that LOST the section cannot silently pass
    probs = cbr.compare(no_fleet, base)
    assert any("no fleet.requests_per_s" in p for p in probs)
    assert any("no fleet per-tenant p99_ms" in p for p in probs)
    # a baseline with a DIFFERENT fleet shape gates nothing: 8-tenant
    # requests/s is not a comparable floor for a 4-tenant run
    assert cbr.compare(_fleet_doc(fleet=_fleet_block(
        requests_per_s=1.0, tenants=8)), base) == []
    assert cbr.compare(_fleet_doc(fleet=_fleet_block(
        requests_per_s=1.0, streams_per_tenant=8)), base) == []
    # cross-workload refusal still wins: a fleet line never compares
    # against a HIGGS training baseline
    probs = cbr.compare(_fleet_doc(), _fresh())
    assert len(probs) == 1 and "not comparable" in probs[0]


def test_cli_fleet_walks_back_to_latest_carrier(tmp_path):
    """When the newest trajectory point predates the fleet bench, the
    fleet fields gate against the LATEST same-workload point carrying
    a comparable shape — old points gate nothing beyond that."""
    base_dir = tmp_path / "repo"
    base_dir.mkdir()
    (base_dir / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": _fleet_doc()}))
    (base_dir / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": _fleet_doc(fleet=None)}))  # newest: no fleet block
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(_fleet_doc(fleet=_fleet_block(
        requests_per_s=40.0))))
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir)]) == 1
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_fleet_doc(fleet=_fleet_block(
        requests_per_s=125.0))))
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0
    # the tolerance flags reach the walked-back comparison
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir),
                     "--throughput-tol", "0.8"]) == 0
    tail = tmp_path / "tail.json"
    tail.write_text(json.dumps(_fleet_doc(fleet=_fleet_block(
        worst_p99=500.0))))
    assert cbr.main([str(tail), "--baseline-dir", str(base_dir)]) == 1
    assert cbr.main([str(tail), "--baseline-dir", str(base_dir),
                     "--latency-tol", "9.0"]) == 0
    # a newest point carrying a DIFFERENT fleet shape must not disable
    # the gate either: walk back to the same-shape carrier
    (base_dir / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": _fleet_doc(fleet=_fleet_block(
            requests_per_s=5000.0, tenants=16))}))
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir)]) == 1
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0


# -- the slo section (obs/slo.py budget report in bench JSON) ----------------

def _slo_block(**kw):
    d = {"spec": "predict_p99_ms<5000;degraded_window_rate<0.5",
         "ok": True, "violating": 0,
         "budget_remaining_min": 0.98, "burn_rate_max": 0.02,
         "predict_p999_ms": 41.5, "serve_p999_ms": None,
         "objectives": [
             {"name": "predict_p99_ms", "ok": True, "current": 12.0,
              "threshold": 5000.0, "budget_remaining": 0.98,
              "burn_rate": 0.02},
             {"name": "degraded_window_rate", "ok": True,
              "current": None, "threshold": 0.5,
              "budget_remaining": 1.0, "burn_rate": 0.0}]}
    d.update(kw)
    return d


# -- measured-parity section (bench.py --parity) -----------------------------

def _parity(ref_available=True, exact_rate=0.02, proxy_rate=0.034,
            auc_delta=1e-4, ok=True, **kw):
    tier = lambda rate: {  # noqa: E731
        "wall_s": 100.0, "row_iters_per_s": rate,
        "auc_tpu": 0.8626,
        "ref_wall_s": 120.0 if ref_available else None,
        "auc_ref": 0.8627 if ref_available else None,
        "auc_delta": auc_delta if ref_available else None,
    }
    d = {"rows": 65536, "iters": 20, "leaves": 63, "max_bin": 63,
         "device_kind": "cpu", "ref_available": ref_available,
         "skip_reason": None if ref_available else "no lightgbm here",
         "auc_tol": 4e-4, "ok": ok,
         "tiers": {"exact": tier(exact_rate), "proxy": tier(proxy_rate)}}
    d.update(kw)
    return d


def test_check_schema_parity_section():
    assert cbr.check_schema(_fresh(parity=_parity())) == []
    assert cbr.check_schema(
        _fresh(parity=_parity(ref_available=False))) == []
    # tier numbers missing
    bad = _parity()
    del bad["tiers"]["exact"]["row_iters_per_s"]
    assert any("exact.row_iters_per_s" in p
               for p in cbr.check_schema(_fresh(parity=bad)))
    # reference measured but its fields lost
    bad = _parity()
    bad["tiers"]["proxy"]["auc_ref"] = None
    assert any("proxy.auc_ref" in p
               for p in cbr.check_schema(_fresh(parity=bad)))
    # unavailable reference must record why
    bad = _parity(ref_available=False)
    bad["skip_reason"] = ""
    assert any("skip_reason" in p
               for p in cbr.check_schema(_fresh(parity=bad)))
    assert any("not a dict" in p
               for p in cbr.check_schema(_fresh(parity=[1])))


def test_parity_quality_problems_are_self_gates():
    """A measured AUC miss fails the fresh artifact with no baseline
    needed; skipped-reference runs assert nothing."""
    assert cbr.parity_quality_problems(_fresh(parity=_parity())) == []
    bad = cbr.parity_quality_problems(
        _fresh(parity=_parity(auc_delta=9e-4, ok=False)))
    assert any("AUC delta" in p for p in bad)
    assert any("parity.ok" in p for p in bad)
    assert cbr.parity_quality_problems(
        _fresh(parity=_parity(ref_available=False))) == []


def test_compare_parity_exact_tier_floor():
    base = _fresh(parity=_parity(exact_rate=0.02))
    # within tolerance: pass
    ok = _fresh(parity=_parity(exact_rate=0.0185))
    assert cbr._compare_parity(ok, base, 0.20) == []
    # exact tier regressed beyond the floor
    slow = _fresh(parity=_parity(exact_rate=0.01))
    got = cbr._compare_parity(slow, base, 0.20)
    assert any("exact-tier throughput regression" in p for p in got)
    # lost the section against a carrier
    got = cbr._compare_parity(_fresh(), base, 0.20)
    assert any("no parity section" in p for p in got)
    # different shape/device gates nothing
    other = _fresh(parity=_parity(exact_rate=0.001, rows=11_000_000))
    assert cbr._compare_parity(other, base, 0.20) == []
    # a baseline without the section gates nothing
    assert cbr._compare_parity(ok, _fresh(), 0.20) == []


def test_cli_parity_self_gate_and_floor(tmp_path):
    """End-to-end through main(): a failing measured-parity artifact
    exits 1 even against a trajectory that predates the section, and
    the exact-tier floor gates against a carrier point."""
    base_dir = tmp_path / "traj"
    base_dir.mkdir()
    (base_dir / "BENCH_r1.json").write_text(json.dumps(
        _fresh(parity=_parity(exact_rate=0.02))))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_fresh(parity=_parity(exact_rate=0.019))))
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0
    bad_q = tmp_path / "bad_quality.json"
    bad_q.write_text(json.dumps(
        _fresh(parity=_parity(auc_delta=9e-4, ok=False))))
    assert cbr.main([str(bad_q), "--baseline-dir",
                     str(base_dir)]) == 1
    # --schema-only must ALSO refuse a recorded quality miss: quick
    # parity runs are metric-refused against the full trajectory, so
    # schema-only is the mode that validates them
    assert cbr.main([str(bad_q), "--schema-only"]) == 1
    assert cbr.main([str(ok), "--schema-only"]) == 0
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(_fresh(parity=_parity(exact_rate=0.01))))
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir)]) == 1


def test_cli_parity_walks_back_to_latest_carrier(tmp_path):
    """A newer trajectory point that predates the parity section must
    not mask the exact-tier floor of an older carrier."""
    base_dir = tmp_path / "traj"
    base_dir.mkdir()
    (base_dir / "BENCH_r1.json").write_text(json.dumps(
        _fresh(parity=_parity(exact_rate=0.02))))
    (base_dir / "BENCH_r2.json").write_text(json.dumps(_fresh()))
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(_fresh(parity=_parity(exact_rate=0.01))))
    assert cbr.main([str(slow), "--baseline-dir", str(base_dir)]) == 1
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_fresh(parity=_parity(exact_rate=0.02))))
    assert cbr.main([str(ok), "--baseline-dir", str(base_dir)]) == 0


def test_check_schema_slo_section():
    # a valid section passes; absence is fine too (old artifacts)
    assert cbr.check_schema(_fresh(slo=_slo_block())) == []
    assert cbr.check_schema(_fresh()) == []
    # budget fields must be numeric-or-null, never a string
    bad = cbr.check_schema(_fresh(
        slo=_slo_block(budget_remaining_min="lots")))
    assert any("budget_remaining_min" in p for p in bad)
    bad = cbr.check_schema(_fresh(slo=_slo_block(burn_rate_max=True)))
    assert any("burn_rate_max" in p for p in bad)
    bad = cbr.check_schema(_fresh(slo=_slo_block(predict_p999_ms="x")))
    assert any("predict_p999_ms" in p for p in bad)
    # per-objective budget state is REQUIRED, not optional
    objs = _slo_block()["objectives"]
    del objs[0]["budget_remaining"]
    bad = cbr.check_schema(_fresh(slo=_slo_block(objectives=objs)))
    assert any("objectives[0].budget_remaining" in p for p in bad)
    bad = cbr.check_schema(_fresh(slo=_slo_block(objectives="none")))
    assert any("objectives" in p for p in bad)
    bad = cbr.check_schema(_fresh(slo=_slo_block(ok="yes")))
    assert any("slo.ok" in p for p in bad)
    bad = cbr.check_schema(_fresh(slo=[1, 2]))
    assert any("slo is list" in p for p in bad)
    # a section that lost its spec string is a shape problem
    blk = _slo_block()
    del blk["spec"]
    assert any("slo.spec" in p for p in cbr.check_schema(
        _fresh(slo=blk)))


def test_slo_violations_are_notes_not_gates():
    """A violated SLO is an operator signal: field_notes reports it,
    compare() does not fail on it, and cross-workload refusal still
    wins over everything."""
    blk = _slo_block(ok=False, violating=1,
                     budget_remaining_min=-2.0)
    blk["objectives"][0]["ok"] = False
    fresh = _fresh(slo=blk)
    assert cbr.check_schema(fresh) == []       # shape is still valid
    notes = cbr.field_notes(fresh)
    assert any("SLO violations" in n and "predict_p99_ms" in n
               for n in notes)
    # same-workload compare ignores the slo values entirely
    assert cbr.compare(fresh, _fresh(value=50.0)) == []
    # cross-workload refusal unchanged
    got = cbr.compare(fresh, _fresh(metric="OTHER"))
    assert len(got) == 1 and got[0].startswith("not comparable")


# -- multichip elastic-drill artifacts (MULTICHIP_r06+) ----------------------

def _drill_doc(**kw):
    d = {
        "schema": cbr.MULTICHIP_DRILL_SCHEMA, "version": 1,
        "drill": "elastic_resume",
        "workload": {"n": 2048, "f": 8, "iterations": 8},
        "world_sizes": {"train": 2, "resume": 1},
        "kill": {"rank": 1, "iteration": 5, "survivor_exit_code": 17,
                 "survivor_error": "rank 1 of 2 unresponsive",
                 "survivor_named_ranks": [1]},
        "resume": {"from_iteration": 4, "total_iterations": 8},
        "per_host_ingest_rows": [1024, 1024],
        "model_parity": True, "parity_kind": "bit_identical",
        "train_auc": 0.98, "resumed_auc": 0.98,
        "wall_s": {"uninterrupted": 20.0},
    }
    d.update(kw)
    return d


def test_multichip_drill_pass_and_cli(tmp_path):
    schema, regressions, notes = cbr.check_multichip_drill(_drill_doc())
    assert schema == [] and regressions == []
    assert any("ingest" in n for n in notes)
    p = tmp_path / "drill.json"
    p.write_text(json.dumps(_drill_doc()))
    assert cbr.main([str(p)]) == 0


def test_multichip_drill_parity_false_fails():
    _, regressions, _ = cbr.check_multichip_drill(
        _drill_doc(model_parity=False))
    assert any("model_parity=false" in r for r in regressions)


def test_multichip_drill_schema_refusals(tmp_path):
    schema, _, _ = cbr.check_multichip_drill(_drill_doc(version=2))
    assert any("version" in s for s in schema)
    schema, _, _ = cbr.check_multichip_drill(
        _drill_doc(world_sizes={"train": 1, "resume": 1}))
    assert any("SHRINKING" in s for s in schema)
    d = _drill_doc()
    del d["model_parity"]
    schema, _, _ = cbr.check_multichip_drill(d)
    assert any("model_parity" in s for s in schema)
    d = _drill_doc(per_host_ingest_rows=[1024])
    schema, _, _ = cbr.check_multichip_drill(d)
    assert any("per_host_ingest_rows" in s for s in schema)
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(_drill_doc(version=2)))
    assert cbr.main([str(p)]) == 2


def test_multichip_drill_survivor_and_rows_gates():
    d = _drill_doc()
    d["kill"]["survivor_named_ranks"] = []
    _, regressions, _ = cbr.check_multichip_drill(d)
    assert any("named" in r for r in regressions)
    # a survivor that HUNG (killed by the launcher timeout, -9) or
    # crashed (1) is a no-hang regression, not a pass
    for bad in (-9, 1):
        d = _drill_doc()
        d["kill"]["survivor_exit_code"] = bad
        _, regressions, _ = cbr.check_multichip_drill(d)
        assert any("EXIT_PEER_LOST" in r for r in regressions), bad
    _, regressions, _ = cbr.check_multichip_drill(
        _drill_doc(per_host_ingest_rows=[2048, 0]))
    assert any("without its data shard" in r for r in regressions)
    _, regressions, _ = cbr.check_multichip_drill(
        _drill_doc(per_host_ingest_rows=[512, 512]))
    assert any("dropped" in r for r in regressions)


def _scaling_doc(**kw):
    d = {
        "schema": cbr.MULTICHIP_SCALING_SCHEMA, "version": 1,
        "workload": {"n": 2048, "f": 8, "iterations": 6},
        "points": [
            {"world": 1, "throughput_rows_per_s": 1700.0,
             "comm_bytes_per_iter": None, "psum_stall_s": None,
             "ckpt_hidden_s": 0.03, "wire": "", "psum_slots": 1,
             "model_sha": "aa"},
            {"world": 2, "throughput_rows_per_s": 900.0,
             "comm_bytes_per_iter": 172032, "psum_stall_s": 0.02,
             "ckpt_hidden_s": 0.04, "wire": "int32", "psum_slots": 2,
             "model_sha": "aa"},
        ],
        "model_parity": True, "parity_kind": "bit_identical",
        "checkpoint": {"hidden_s": 0.04},
        "autoscale": {"drill": "autoscale_grow_shrink",
                      "worlds": [2, 4, 2], "window": 3,
                      "iterations": 9, "reshard_total": 2,
                      "model_parity": True,
                      "parity_kind": "bit_identical"},
    }
    d.update(kw)
    return d


def test_multichip_scaling_pass_and_cli(tmp_path):
    schema, regressions, notes = cbr.check_multichip_scaling(
        _scaling_doc())
    assert schema == [] and regressions == []
    assert any("hidden" in n for n in notes)
    p = tmp_path / "scaling.json"
    p.write_text(json.dumps(_scaling_doc()))
    assert cbr.main([str(p)]) == 0


def test_multichip_scaling_parity_and_reshard_regressions():
    _, regressions, _ = cbr.check_multichip_scaling(
        _scaling_doc(model_parity=False))
    assert any("mesh-size invariance" in r for r in regressions)
    doc = _scaling_doc()
    doc["autoscale"]["model_parity"] = False
    _, regressions, _ = cbr.check_multichip_scaling(doc)
    assert any("elastic autoscale is broken" in r for r in regressions)
    doc = _scaling_doc()
    doc["autoscale"]["reshard_total"] = 0
    _, regressions, _ = cbr.check_multichip_scaling(doc)
    assert any("never" in r for r in regressions)


def test_multichip_scaling_schema_refusals(tmp_path):
    assert cbr.check_multichip_scaling(
        _scaling_doc(version=2))[0]
    assert cbr.check_multichip_scaling(
        _scaling_doc(points=[]))[0]
    doc = _scaling_doc()
    doc["points"] = list(reversed(doc["points"]))     # worlds 2, 1
    assert any("strictly increasing" in s for s in
               cbr.check_multichip_scaling(doc)[0])
    doc = _scaling_doc()
    doc["points"][1]["psum_stall_s"] = "fast"
    assert any("psum_stall_s" in s for s in
               cbr.check_multichip_scaling(doc)[0])
    doc = _scaling_doc()
    del doc["autoscale"]
    assert any("autoscale" in s for s in
               cbr.check_multichip_scaling(doc)[0])
    # the CLI maps a schema refusal to exit 2
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(_scaling_doc(points=[])))
    assert cbr.main([str(p)]) == 2


def test_multichip_r07_artifact_passes_gate():
    """The committed MULTICHIP_r07 artifact (the real measured scaling
    curve + autoscale drill) must stay green through its own gate."""
    path = os.path.join(REPO, "MULTICHIP_r07.json")
    assert cbr.main([path]) == 0
    doc = json.loads(open(path).read())
    assert doc["model_parity"] is True
    assert doc["autoscale"]["model_parity"] is True
    assert doc["autoscale"]["reshard_total"] >= 1
    assert [p["world"] for p in doc["points"]] == [1, 2, 4]
    shas = {p["model_sha"] for p in doc["points"]}
    assert len(shas) == 1, "scaling points trained different models"


def test_baseline_flag_and_shape_aware_selection(tmp_path):
    """(PR16) trajectory baseline selection: a point flagged
    ``"baseline": false`` (the quick-shape r06 ledger entry) never
    becomes the comparison floor, and among eligible points the gate
    prefers the newest one whose metric string MATCHES the fresh
    run's workload shape."""
    full = "11M rows x 28 feat"
    quick = "65536 rows x 28 feat"
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        _fresh(metric=full, value=50.0)))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        _fresh(metric=quick, value=400.0, baseline=False)))
    fresh = tmp_path / "fresh.json"
    # full-size fresh: compares against r01 (r02 is flagged off), so a
    # value that would be a crash vs r02's 400 still passes vs 50
    fresh.write_text(json.dumps(_fresh(metric=full, value=49.0)))
    assert cbr.main([str(fresh), "--baseline-dir",
                     str(tmp_path)]) == 0
    # quick-shape fresh: no eligible matching-metric point -> the gate
    # refuses the cross-shape comparison instead of passing it
    fresh.write_text(json.dumps(_fresh(metric=quick, value=400.0)))
    assert cbr.main([str(fresh), "--baseline-dir",
                     str(tmp_path)]) == 2
    # un-flag r02: now the quick shape has a true baseline and passes
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        _fresh(metric=quick, value=400.0)))
    assert cbr.main([str(fresh), "--baseline-dir",
                     str(tmp_path)]) == 0
    # and the full shape still walks back to r01 over the newer r02
    fresh.write_text(json.dumps(_fresh(metric=full, value=49.0)))
    assert cbr.main([str(fresh), "--baseline-dir",
                     str(tmp_path)]) == 0


def test_all_baselines_flagged_off_is_a_refusal(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        _fresh(baseline=False)))
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(_fresh()))
    assert cbr.main([str(fresh), "--baseline-dir",
                     str(tmp_path)]) == 2


def test_multichip_r06_artifact_passes_gate():
    """The committed MULTICHIP_r06 artifact (the real drill run) must
    stay green through its own gate."""
    path = os.path.join(REPO, "MULTICHIP_r06.json")
    assert cbr.main([path]) == 0
    doc = json.loads(open(path).read())
    assert doc["model_parity"] is True
    assert doc["world_sizes"] == {"train": 2, "resume": 1}


# -- end-to-end (slow): a real quick bench through the gate ------------------

@pytest.mark.slow
def test_quick_bench_json_schema_end_to_end(tmp_path):
    """``bench.py --quick`` emits a JSON line whose predict-latency
    p50/p95/p99 come from the log-bucketed histogram, and the gate's
    schema check accepts it (a quick run is NOT comparable to the
    full-size trajectory — that is exactly what --schema-only is
    for)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    doc = json.loads(line)
    lat = doc["predict_latency"]
    assert lat["batches"] > 10
    for q in ("p50_ms", "p95_ms", "p99_ms"):
        assert lat[q] > 0
    assert lat["p50_ms"] <= lat["p95_ms"] <= lat["p99_ms"]
    assert 0.5 < doc["test_auc"] <= 1.0
    fresh = tmp_path / "fresh.json"
    fresh.write_text(line)
    assert cbr.main([str(fresh), "--schema-only"]) == 0
    # and the full-size gate refuses the shape mismatch instead of
    # comparing apples to oranges
    assert cbr.main([str(fresh), "--baseline-dir", REPO]) == 2
