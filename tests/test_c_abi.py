"""Linkable C ABI (native/c_api_embed.cpp) — the last unreproduced
interface from VERDICT r3: a real .so a foreign runtime can link, with
the fork driver's call pattern (reference src/test.cpp:243-298:
DatasetCreateFromCSR -> SetField -> BoosterCreate -> UpdateOneIter ->
PredictForCSR, plus Merge/SaveModel/CreateFromModelfile)."""
import shutil
import subprocess
import sys
import sysconfig
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="needs g++")

DRIVER = r"""
#include <cstdint>
#include <cstdio>
#include <string>
#include <unordered_map>
#include <vector>

typedef void* DatasetHandle;
typedef void* BoosterHandle;
extern "C" const char* LGBM_GetLastError();
extern "C" int LGBM_DatasetCreateFromCSR(
    const void*, int, const int32_t*, const void*, int, int64_t,
    int64_t, int64_t, const std::unordered_map<std::string, std::string>,
    const DatasetHandle, DatasetHandle*);
extern "C" int LGBM_DatasetSetField(DatasetHandle, const char*,
                                    const void*, int, int);
extern "C" int LGBM_DatasetGetNumData(DatasetHandle, int*);
extern "C" int LGBM_DatasetFree(DatasetHandle);
extern "C" int LGBM_BoosterCreate(
    const DatasetHandle, std::unordered_map<std::string, std::string>,
    BoosterHandle*);
extern "C" int LGBM_BoosterUpdateOneIter(BoosterHandle, int*);
extern "C" int LGBM_BoosterCalcNumPredict(BoosterHandle, int, int, int,
                                          int64_t*);
extern "C" int LGBM_BoosterPredictForCSR(
    BoosterHandle, const void*, int, const int32_t*, const void*, int,
    int64_t, int64_t, int64_t, int, int,
    std::unordered_map<std::string, std::string>, int64_t*, double*);
extern "C" int LGBM_BoosterSaveModel(BoosterHandle, int, int,
                                     const char*);
extern "C" int LGBM_BoosterCreateFromModelfile(const char*, int*,
                                               BoosterHandle*);
extern "C" int LGBM_BoosterMerge(BoosterHandle, BoosterHandle);
extern "C" int LGBM_BoosterFree(BoosterHandle);

#define CHECK(x) if ((x) != 0) { \
    printf("FAIL %s: %s\n", #x, LGBM_GetLastError()); return 1; }

int main(int argc, char** argv) {
  const int n = 600, f = 4;
  std::vector<int32_t> indptr(n + 1);
  std::vector<int32_t> indices;
  std::vector<double> data;
  std::vector<float> labels(n);
  unsigned s = 12345;
  for (int i = 0; i < n; i++) {
    indptr[i] = (int32_t)indices.size();
    double row0 = 0.0;
    for (int j = 0; j < f; j++) {
      s = s * 1103515245u + 12345u;
      double v = ((s >> 8) % 2000) / 1000.0 - 1.0;
      if (j == 0) row0 = v;
      indices.push_back(j);
      data.push_back(v);
    }
    labels[i] = row0 > 0.0 ? 1.0f : 0.0f;
  }
  indptr[n] = (int32_t)indices.size();

  std::unordered_map<std::string, std::string> params = {
      {"objective", "binary"}, {"num_leaves", "7"},
      {"min_data_in_leaf", "5"}, {"verbose", "-1"}};

  DatasetHandle ds = nullptr;
  CHECK(LGBM_DatasetCreateFromCSR(indptr.data(), 2, indices.data(),
                                  data.data(), 1, n + 1,
                                  (int64_t)data.size(), f, params,
                                  nullptr, &ds));
  CHECK(LGBM_DatasetSetField(ds, "label", labels.data(), n, 0));
  int nd = 0;
  CHECK(LGBM_DatasetGetNumData(ds, &nd));
  if (nd != n) { printf("FAIL num_data %d\n", nd); return 1; }

  BoosterHandle bst = nullptr;
  CHECK(LGBM_BoosterCreate(ds, params, &bst));
  int fin = 0;
  for (int it = 0; it < 8 && !fin; it++) {
    CHECK(LGBM_BoosterUpdateOneIter(bst, &fin));
  }

  int64_t len = 0;
  CHECK(LGBM_BoosterCalcNumPredict(bst, n, 0, -1, &len));
  std::vector<double> preds(len);
  CHECK(LGBM_BoosterPredictForCSR(bst, indptr.data(), 2, indices.data(),
                                  data.data(), 1, n + 1,
                                  (int64_t)data.size(), f, 0, -1,
                                  params, &len, preds.data()));
  int correct = 0;
  for (int i = 0; i < n; i++) {
    correct += ((preds[i] > 0.5) == (labels[i] > 0.5f)) ? 1 : 0;
  }
  if (correct < n * 0.9) { printf("FAIL acc %d/%d\n", correct, n); return 1; }

  std::string model = std::string(argv[1]) + "/model.txt";
  CHECK(LGBM_BoosterSaveModel(bst, 0, -1, model.c_str()));
  int iters = 0;
  BoosterHandle loaded = nullptr;
  CHECK(LGBM_BoosterCreateFromModelfile(model.c_str(), &iters, &loaded));
  std::vector<double> preds2(len);
  CHECK(LGBM_BoosterPredictForCSR(loaded, indptr.data(), 2,
                                  indices.data(), data.data(), 1, n + 1,
                                  (int64_t)data.size(), f, 0, -1,
                                  params, &len, preds2.data()));
  for (int i = 0; i < n; i++) {
    if (preds[i] - preds2[i] > 1e-6 || preds2[i] - preds[i] > 1e-6) {
      printf("FAIL roundtrip row %d: %f vs %f\n", i, preds[i], preds2[i]);
      return 1;
    }
  }
  CHECK(LGBM_BoosterMerge(bst, loaded));
  CHECK(LGBM_BoosterFree(loaded));
  CHECK(LGBM_BoosterFree(bst));
  CHECK(LGBM_DatasetFree(ds));
  printf("C-ABI-OK acc=%d/%d iters=%d\n", correct, n, iters);
  return 0;
}
"""


@pytest.fixture(scope="module")
def capi_so(tmp_path_factory):
    out = tmp_path_factory.mktemp("cabi") / "liblightgbm_tpu.so"
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    r = subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++14",
         str(REPO / "native" / "c_api_embed.cpp"), "-o", str(out),
         f"-I{inc}", f"-L{libdir}", f"-l{pyver}", "-ldl", "-lm",
         f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    return out


def test_fork_driver_flow_links_and_runs(capi_so, tmp_path):
    drv = tmp_path / "driver.cpp"
    drv.write_text(DRIVER)
    exe = tmp_path / "driver"
    r = subprocess.run(
        ["g++", "-O1", "-std=c++14", str(drv), "-o", str(exe),
         f"-L{capi_so.parent}", "-llightgbm_tpu",
         f"-Wl,-rpath,{capi_so.parent}"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    import site
    pypath = ":".join([str(REPO)] + site.getsitepackages())
    env = {"PYTHONPATH": pypath, "PATH": "/usr/bin:/bin",
           "JAX_PLATFORMS": "cpu", "LGBM_TPU_PLATFORM": "cpu",
           "HOME": "/tmp"}
    run = subprocess.run([str(exe), str(tmp_path)], env=env,
                         capture_output=True, text=True, timeout=560)
    assert "C-ABI-OK" in run.stdout, (run.stdout, run.stderr)


class TestEmbedGlue:
    """Drive lightgbm_tpu/c_embed.py directly with raw pointers (the
    same marshalling the .so performs) — covers the glue functions the
    C driver doesn't reach."""

    def _mk(self, n=300, f=4):
        r = np.random.default_rng(3)
        X = np.ascontiguousarray(r.normal(size=(n, f)))
        y = (X[:, 0] > 0).astype(np.float32)
        return X, np.ascontiguousarray(y)

    def test_mat_train_eval_refit_save(self, tmp_path):
        from lightgbm_tpu import c_embed as ce

        X, y = self._mk()
        n, f = X.shape
        ds = ce.dataset_from_mat(X.ctypes.data, 1, n, f, 1,
                                 "objective=binary num_leaves=7 "
                                 "metric=auc "
                                 "is_provide_training_metric=true", 0)
        ce.dataset_set_field(ds, "label", y.ctypes.data, n, 0)
        assert ce.dataset_num_data(ds) == n
        assert ce.dataset_num_feature(ds) == f
        bst = ce.booster_create(
            ds, "objective=binary num_leaves=7 metric=auc "
                "is_provide_training_metric=true")
        fin = np.zeros(1, np.int32)
        for _ in range(6):
            ce.booster_update(bst, fin.ctypes.data)
        evals = np.zeros(4, np.float64)
        ne = ce.booster_get_eval(bst, 0, evals.ctypes.data)
        assert ne >= 1 and 0.5 < evals[0] <= 1.0     # train AUC
        # leaf predictions feed refit like the reference's flow
        ln2 = ce.booster_calc_num_predict(bst, n, 2, -1)
        leaves = np.zeros(ln2, np.float64)
        ce.booster_predict_mat(bst, X.ctypes.data, 1, n, f, 1, 2, -1,
                               "", leaves.ctypes.data)
        lp = np.ascontiguousarray(
            leaves.reshape(n, -1).astype(np.int32))
        ce.booster_refit(bst, lp.ctypes.data, n, lp.shape[1])
        # predictions AFTER refit are what the saved model must carry
        ln = ce.booster_calc_num_predict(bst, n, 0, -1)
        out = np.zeros(ln, np.float64)
        got = ce.booster_predict_mat(bst, X.ctypes.data, 1, n, f, 1,
                                     0, -1, "", out.ctypes.data)
        assert got == n
        acc = ((out > 0.5) == y).mean()
        assert acc > 0.85
        mf = str(tmp_path / "m.txt")
        ce.booster_save_model(bst, 0, -1, mf)
        iters = np.zeros(1, np.int32)
        b2 = ce.booster_from_modelfile(mf, iters.ctypes.data)
        assert iters[0] == 6
        out2 = np.zeros(ln, np.float64)
        ce.booster_predict_mat(b2, X.ctypes.data, 1, n, f, 1, 0, -1,
                               "", out2.ctypes.data)
        np.testing.assert_allclose(out, out2, atol=1e-6)
        ce.booster_merge(bst, b2)
        for h in (bst, b2, ds):
            ce.free_handle(h)

    def test_dataset_from_file(self, tmp_path):
        from lightgbm_tpu import c_embed as ce
        X, y = self._mk(200)
        fpath = tmp_path / "d.csv"
        np.savetxt(fpath, np.column_stack([y, X]), delimiter=",")
        ds = ce.dataset_from_file(str(fpath), "objective=binary", 0)
        assert ce.dataset_num_data(ds) == 200
        ce.free_handle(ds)
