"""if-else codegen end-to-end: compile the generated C++ and compare
its predictions against the framework to 5 decimals — the reference's
cpp_test loop (reference: tests/cpp_test/test.py:5-6 + .ci/test.sh:55-60,
which rebuilds gbdt_prediction.cpp from convert_model output).
"""
import shutil
import subprocess

import numpy as np
import pytest

from conftest import make_binary


@pytest.mark.skipif(shutil.which("g++") is None,
                    reason="no C++ toolchain")
def test_generated_cpp_predicts_identically(tmp_path):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.models.codegen import model_to_if_else

    X, y = make_binary(n=600, f=6, seed=51)
    # missing values exercise the NaN/default-left decision paths
    X = X.copy()
    X[::7, 2] = np.nan
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "max_bin": 63, "min_data_in_leaf": 5,
                     "verbose": -1}, ds, 12)
    cpp = model_to_if_else(bst._gbdt)

    driver = r"""
#include <cstdio>
#include <cstdlib>
#include <vector>
namespace LightGBM { void PredictRaw(const double*, double*); }
int main(int argc, char** argv) {
  int n = atoi(argv[1]), f = atoi(argv[2]);
  std::vector<double> row(f);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < f; ++j) {
      if (scanf("%lf", &row[j]) != 1) return 1;
    }
    double out = 0.0;
    LightGBM::PredictRaw(row.data(), &out);
    printf("%.10f\n", out);
  }
  return 0;
}
"""
    src = tmp_path / "model.cpp"
    src.write_text(cpp + driver)
    exe = str(tmp_path / "predict")
    build = subprocess.run(["g++", "-O1", "-o", exe, str(src)],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr[-3000:]
    feed = "\n".join(" ".join(f"{v:.17g}" for v in row) for row in X)
    out = subprocess.run([exe, str(len(X)), str(X.shape[1])],
                         input=feed, capture_output=True, text=True,
                         check=True)
    got = np.array([float(t) for t in out.stdout.split()])
    want = np.asarray(bst.predict(X, raw_score=True)).ravel()
    # the reference's codegen test asserts 5-decimal equality
    np.testing.assert_allclose(got, want, atol=1e-5)
