"""Fault-tolerance suite (pytest -m faults).

Every recovery path is PROVEN with deterministic fault injection
(lightgbm_tpu/utils/faults.py), not hoped for:

- kill-and-resume bit-parity: a subprocess is SIGKILLed mid-train and
  resumed from its checkpoint bundle; the final model is byte-identical
  to the uninterrupted run's (serial here; the sharded-state path is
  the slow-marked twin);
- the degrade-don't-die lrb loop: an injected window-train failure
  leaves the loop serving the stale model with correct counters and a
  staleness gauge in the Prometheus export;
- injected transient ingest/transfer failures recover via the bounded
  backoff retry (utils/retry.py), bit-exact;
- a checkpoint-write failure warns and never corrupts training or the
  previous checkpoint;
- snapshots are atomic and pruned; truncated/corrupt model text and
  checkpoint bundles are refused with one-line errors.
"""
import glob
import io
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata, TpuDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.obs import registry as obs
from lightgbm_tpu.utils import checkpoint as ckpt
from lightgbm_tpu.utils import faults, retry
from lightgbm_tpu.utils.log import LightGBMError

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Fault plans are process-global: never leak one into the next
    test (or the rest of the suite)."""
    yield
    faults.clear()


def make_binary(seed=0, n=400, f=6):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
          "min_data_in_leaf": 5, "num_iterations": 12,
          "bagging_freq": 3, "bagging_fraction": 0.7,
          "feature_fraction": 0.8}


def build_booster(params):
    cfg = Config().set(dict(params))
    X, y = make_binary()
    ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
    obj = create_objective(cfg.objective, cfg)
    obj.init(ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, [])
    return g


def trees_only(model_str):
    """The model text minus the parameters block (the checkpoint knobs
    themselves land there and must not fail the comparison)."""
    return model_str.split("\nparameters:\n")[0]


def counter(name):
    return obs.default_registry().snapshot()["counters"].get(name, 0)


# ---------------------------------------------------------------------------
# faults.py / retry.py units
# ---------------------------------------------------------------------------

def test_fault_spec_occurrences_and_actions():
    faults.configure("p.a@2;p.b@1,3:transient;p.c@2+")
    faults.check("p.a")                      # occurrence 1: clean
    with pytest.raises(faults.InjectedFault) as ei:
        faults.check("p.a")                  # occurrence 2: fires
    assert not ei.value.transient
    faults.check("p.a")                      # 3: clean again
    with pytest.raises(faults.InjectedFault) as ei:
        faults.check("p.b")
    assert ei.value.transient
    faults.check("p.b")                      # 2: clean
    with pytest.raises(faults.InjectedFault):
        faults.check("p.b")                  # 3: fires
    faults.check("p.c")                      # 1: clean
    for _ in range(3):                       # 2+: every call fires
        with pytest.raises(faults.InjectedFault):
            faults.check("p.c")
    assert faults.counts()["p.a"] == 3


def test_fault_spec_probability_is_seeded():
    def fire_pattern(seed):
        faults.clear()      # same-spec re-arming is a no-op by design
        faults.configure("p.x@p0.5", seed=seed)
        out = []
        for _ in range(20):
            try:
                faults.check("p.x")
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    a, b = fire_pattern(7), fire_pattern(7)
    assert a == b and 0 < sum(a) < 20
    assert fire_pattern(8) != a


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError, match="unknown action"):
        faults.configure("p.a@1:explode")
    with pytest.raises(ValueError, match="point@N"):
        faults.configure("no-at-sign")
    faults.configure("")                     # empty disarms
    assert not faults.active()


def test_fault_sleep_action_stalls_without_raising():
    """The latency action (shed drills): the call stalls for the
    configured milliseconds and then proceeds normally — no exception,
    no flight dump, only the wall-clock damage."""
    import time as _t
    faults.configure("p.s@2+:sleep40")
    t0 = _t.perf_counter()
    faults.check("p.s")                      # occurrence 1: clean
    assert _t.perf_counter() - t0 < 0.030
    t0 = _t.perf_counter()
    faults.check("p.s")                      # 2+: stalls, returns
    assert _t.perf_counter() - t0 >= 0.030
    assert faults.counts()["p.s"] == 2
    for bad in ("p.s@1:sleepX", "p.s@1:sleep-5", "p.s@1:sleep"):
        with pytest.raises(ValueError, match="sleep<ms>"):
            faults.configure(bad)


def test_retry_recovers_transient_and_fails_fast():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise faults.InjectedFault("flaky", transient=True)
        return "ok"

    pol = retry.RetryPolicy(attempts=4, base_s=0.0, seed=1)
    r0 = counter("retry/retries")
    assert retry.call(flaky, what="unit", policy=pol) == "ok"
    assert calls["n"] == 3
    assert counter("retry/retries") - r0 == 2

    calls2 = {"n": 0}

    def count_broken():
        calls2["n"] += 1
        raise ValueError("a real bug")

    with pytest.raises(ValueError):
        retry.call(count_broken, what="unit", policy=pol)
    assert calls2["n"] == 1                  # non-transient: no retry

    g0 = counter("retry/giveups")
    with pytest.raises(faults.InjectedFault):
        retry.call(lambda: (_ for _ in ()).throw(
            faults.InjectedFault("always", transient=True)),
            what="unit", policy=retry.RetryPolicy(attempts=2, base_s=0.0))
    assert counter("retry/giveups") - g0 == 1


def test_retry_classifies_runtime_strings():
    assert retry.is_transient(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert retry.is_transient(TimeoutError())
    assert not retry.is_transient(ValueError("shape mismatch"))


# ---------------------------------------------------------------------------
# checkpoint bundle IO + refusals
# ---------------------------------------------------------------------------

def test_checkpoint_loader_one_line_refusals(tmp_path):
    p = tmp_path / "ckpt_iter_3.json"
    p.write_text('{"schema": "lightgbm-tpu/checkpoint", "version')
    with pytest.raises(ValueError, match="corrupt checkpoint"):
        ckpt.load_checkpoint(str(p))
    p.write_text('{"schema": "something-else"}')
    with pytest.raises(ValueError, match="not a checkpoint bundle"):
        ckpt.load_checkpoint(str(p))
    p.write_text(json.dumps({"schema": ckpt.CHECKPOINT_SCHEMA,
                             "version": 999}))
    with pytest.raises(ValueError, match="version 999"):
        ckpt.load_checkpoint(str(p))
    p.write_text(json.dumps({"schema": ckpt.CHECKPOINT_SCHEMA,
                             "version": ckpt.CHECKPOINT_VERSION}))
    with pytest.raises(ValueError, match="missing 'iteration'"):
        ckpt.load_checkpoint(str(p))
    d = tmp_path / "empty"
    d.mkdir()
    with pytest.raises(ValueError, match="no ckpt_iter_"):
        ckpt.resolve_resume(str(d))


def test_checkpoint_config_mismatch_is_actionable(tmp_path):
    g = build_booster(PARAMS)
    for _ in range(4):
        g.train_one_iter()
    ckpt.save_checkpoint(g, str(tmp_path))
    other = build_booster(dict(PARAMS, learning_rate=0.3))
    bundle = ckpt.resolve_resume(str(tmp_path))
    with pytest.raises(ValueError, match="different training config"):
        ckpt.restore(other, bundle)


def test_checkpoint_missing_sidecar_refused_and_dir_skips(tmp_path):
    g = build_booster(PARAMS)
    for _ in range(6):
        g.train_one_iter()
    ckpt.save_checkpoint(g, str(tmp_path))          # iter 6 (valid)
    # a newer bundle whose sidecar is gone (crash between writes /
    # partial copy): direct load refuses, dir resolve SKIPS to 6
    newer = tmp_path / "ckpt_iter_9.json"
    bundle = json.loads(
        (tmp_path / "ckpt_iter_6.json").read_text())
    bundle["iteration"] = 9
    bundle["scores_file"] = "ckpt_iter_9.scores.npz"
    newer.write_text(json.dumps(bundle))
    with pytest.raises(ValueError, match="sidecar"):
        ckpt.load_checkpoint(str(newer))
    resolved = ckpt.resolve_resume(str(tmp_path))
    assert resolved["iteration"] == 6


def test_checkpoint_volatile_knobs_do_not_change_fingerprint():
    a = Config().set(dict(PARAMS))
    b = Config().set(dict(PARAMS, tpu_checkpoint_dir="/tmp/x",
                          tpu_run_report="/tmp/r.json",
                          num_iterations=500))
    c = Config().set(dict(PARAMS, learning_rate=0.31))
    assert ckpt.config_fingerprint(a) == ckpt.config_fingerprint(b)
    assert ckpt.config_fingerprint(a) != ckpt.config_fingerprint(c)
    # cluster topology is volatile BY DESIGN: elastic resume means a
    # 2-process checkpoint restores under 1 process (different rank /
    # coordinator / world) without a fingerprint refusal
    d = Config().set(dict(PARAMS, tpu_num_machines=2,
                          tpu_machine_rank=1,
                          tpu_coordinator="host:123",
                          tpu_collective_timeout_s=7.5))
    assert ckpt.config_fingerprint(a) == ckpt.config_fingerprint(d)


def test_checkpoint_world_mismatch_named_in_refusal(tmp_path):
    """Resuming a sharded checkpoint under a mismatched world size
    over DIFFERENT data gets a dedicated one-line error naming both
    world sizes and pointing at the elastic re-shard path's
    requirement (same data) — not the generic shape message."""
    g = build_booster(PARAMS)
    for _ in range(4):
        g.train_one_iter()
    ckpt.save_checkpoint(g, str(tmp_path))
    bundle_path = ckpt.list_checkpoints(str(tmp_path))[0][1]
    bundle = json.loads(open(bundle_path).read())
    assert bundle["world"]["processes"] == 1          # written 1-proc
    # doctor the bundle into "written by a 2-process run over other
    # data": different world, different row count, wider score buffer
    bundle["world"].update(processes=2, devices=2, n_real=640,
                           n_score=768)
    open(bundle_path, "w").write(json.dumps(bundle))
    with np.load(ckpt.scores_path(bundle_path)) as z:
        k = z["scores"].shape[0]
    with open(ckpt.scores_path(bundle_path), "wb") as fh:
        np.savez_compressed(fh, scores=np.zeros((k, 768), np.float32))
    # drop the mapper record: this refusal matrix entry targets the
    # WORLD mismatch, not the (also different) binning
    bundle.pop("mappers")
    open(bundle_path, "w").write(json.dumps(bundle))
    fresh = build_booster(PARAMS)
    with pytest.raises(ValueError, match=r"2-process run.*1 process"):
        ckpt.restore(fresh, ckpt.resolve_resume(str(tmp_path)))


def test_checkpoint_elastic_reshard_same_data(tmp_path):
    """A world-size change over the SAME data re-shards instead of
    refusing: real rows carry verbatim into this run's (different-
    width) score buffer; the pad region keeps fresh-init values."""
    g = build_booster(PARAMS)
    for _ in range(4):
        g.train_one_iter()
    ckpt.save_checkpoint(g, str(tmp_path))
    bundle_path = ckpt.list_checkpoints(str(tmp_path))[0][1]
    bundle = json.loads(open(bundle_path).read())
    # provenance: every bundle stamps the writer's telemetry identity
    # (never part of the resume fingerprint)
    assert bundle["identity"]["machine_rank"] == 0
    n_real = bundle["world"]["n_real"]
    with np.load(ckpt.scores_path(bundle_path)) as z:
        saved = z["scores"]
    # pretend a 2-process run wrote it at a different aligned width;
    # the pad carries garbage the re-shard must ignore
    wider = np.pad(saved, ((0, 0), (0, 64)), constant_values=7.0)
    bundle["world"].update(processes=2, devices=2,
                           n_score=wider.shape[1])
    open(bundle_path, "w").write(json.dumps(bundle))
    with open(ckpt.scores_path(bundle_path), "wb") as fh:
        np.savez_compressed(fh, scores=wider)
    fresh = build_booster(PARAMS)
    from lightgbm_tpu.obs import identity
    inc0 = identity.incarnation()
    it = ckpt.restore(fresh, ckpt.resolve_resume(str(tmp_path)))
    assert it == 4
    # the re-shard starts a new incarnation of this process's
    # telemetry identity (obs/identity.py — Design.md §6e)
    assert identity.incarnation() == inc0 + 1
    got = np.asarray(fresh.train_scores())
    np.testing.assert_array_equal(got, saved[:, :n_real])
    # and the resumed booster keeps training
    fresh.train_one_iter()


def test_checkpoint_mapper_mismatch_refused(tmp_path):
    """A dataset binned differently from the checkpointed run is
    refused by fingerprint — restored thresholds would silently
    shift — and mappers_from_bundle reconstructs the original binning
    so an elastic resume can inject it."""
    g = build_booster(PARAMS)
    for _ in range(3):
        g.train_one_iter()
    ckpt.save_checkpoint(g, str(tmp_path))
    bundle = ckpt.resolve_resume(str(tmp_path))

    # same config, different data -> different mappers
    cfg = Config().set(dict(PARAMS))
    X2, y2 = make_binary(seed=99)
    ds2 = TpuDataset(cfg).construct_from_matrix(X2, Metadata(label=y2))
    obj = create_objective(cfg.objective, cfg)
    obj.init(ds2.metadata, ds2.num_data)
    other = GBDT()
    other.init(cfg, ds2, obj, [])
    with pytest.raises(ValueError, match="different bin mappers"):
        ckpt.restore(other, bundle)

    # the bundle's mappers reconstruct the ORIGINAL binning exactly
    full = ckpt.mappers_from_bundle(bundle)
    assert len(full) == g.train_data.num_total_features
    ds3 = TpuDataset(cfg).construct_from_matrix(
        *(lambda X, y: (X, Metadata(label=y)))(*make_binary()),
        mappers=full)
    assert [m.feature_info() for m in ds3.mappers] == \
        [m.feature_info() for m in g.train_data.mappers]
    assert ckpt.mapper_fingerprint(ds3.mappers) == \
        bundle["mappers"]["hash"]


# ---------------------------------------------------------------------------
# resume bit-parity (in-process; the subprocess kill drill is below)
# ---------------------------------------------------------------------------

def test_resume_bit_parity_in_process(tmp_path):
    g1 = build_booster(PARAMS)
    g1.train(-1, "")
    m1 = trees_only(g1.model_to_string())

    g2 = build_booster(dict(PARAMS, tpu_checkpoint_dir=str(tmp_path),
                            tpu_checkpoint_freq=4))
    g2.train(-1, "")
    assert trees_only(g2.model_to_string()) == m1, \
        "writing checkpoints perturbed training"

    g3 = build_booster(PARAMS)
    g3.train(-1, "", resume_from=str(tmp_path / "ckpt_iter_8.json"))
    assert trees_only(g3.model_to_string()) == m1, \
        "resumed run diverged from the uninterrupted one"


def test_resume_continued_training_counts_additional_rounds(tmp_path):
    """Resume of a CONTINUED-training run (input_model): the
    checkpoint stores TOTAL tree groups while the loop counts
    additional rounds — the resumed run must train exactly the
    remaining additional rounds, matching the unkilled continued run."""
    from lightgbm_tpu.metrics import create_metrics  # noqa: F401

    g0 = build_booster(dict(PARAMS, num_iterations=4))
    g0.train(-1, "")
    base_model = g0.model_to_string()

    def continued(extra):
        cfg = Config().set(dict(PARAMS, num_iterations=8, **extra))
        X, y = make_binary()
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=y))
        obj = create_objective(cfg.objective, cfg)
        obj.init(ds.metadata, ds.num_data)
        g = GBDT()
        g.load_model_from_string(base_model)
        g.init_from_loaded(cfg, ds, obj, [])
        return g

    g1 = continued({})
    g1.train(-1, "")
    assert g1.current_iteration == 12            # 4 base + 8 additional
    m1 = trees_only(g1.model_to_string())
    g2 = continued({"tpu_checkpoint_dir": str(tmp_path),
                    "tpu_checkpoint_freq": 3})
    g2.train(-1, "")
    assert trees_only(g2.model_to_string()) == m1
    # bundle at TOTAL iteration 7 == additional round 3
    g3 = continued({})
    g3.train(-1, "", resume_from=str(tmp_path / "ckpt_iter_7.json"))
    assert g3.current_iteration == 12, \
        "resume retrained the wrong number of additional rounds"
    assert trees_only(g3.model_to_string()) == m1


def test_resume_bit_parity_sharded_state(tmp_path):
    """Sharded-state path (tree_learner=data over the 8-device virtual
    CPU mesh): checkpoint at 6, resume, byte-identical final model."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device test platform")
    P = dict(PARAMS, tree_learner="data")
    P.pop("bagging_freq"), P.pop("bagging_fraction")
    P["num_iterations"] = 8
    g1 = build_booster(P)
    assert g1.learner_mode == "data" and g1.num_devices > 1
    g1.train(-1, "")
    m1 = trees_only(g1.model_to_string())
    g2 = build_booster(dict(P, tpu_checkpoint_dir=str(tmp_path),
                            tpu_checkpoint_freq=3))
    g2.train(-1, "")
    assert trees_only(g2.model_to_string()) == m1
    g3 = build_booster(P)
    g3.train(-1, "", resume_from=str(tmp_path / "ckpt_iter_6.json"))
    assert trees_only(g3.model_to_string()) == m1


# ---------------------------------------------------------------------------
# background checkpoint writer (tpu_ckpt_async)
# ---------------------------------------------------------------------------

def _writer_job(directory, it):
    """A minimal but schema-valid (bundle, sidecar) write job — enough
    for load_checkpoint to accept the result."""
    path = os.path.join(str(directory), f"ckpt_iter_{it}.json")
    arrays = {"train": np.zeros(3, np.float32)}
    bundle = {"schema": ckpt.CHECKPOINT_SCHEMA,
              "version": ckpt.CHECKPOINT_VERSION,
              "iteration": it, "model": "", "state": {},
              "config_hash": "x",
              "scores_file": os.path.basename(ckpt.scores_path(path))}
    return (str(directory), path, arrays, bundle, 10)


def test_async_writer_commits_in_order_and_drains(tmp_path):
    w = ckpt.AsyncCheckpointWriter()
    try:
        assert w.submit(*_writer_job(tmp_path, 3))
        assert w.submit(*_writer_job(tmp_path, 6))
        assert w.drain(timeout=30)
        for it in (3, 6):
            b = ckpt.load_checkpoint(
                str(tmp_path / f"ckpt_iter_{it}.json"))
            assert int(b["iteration"]) == it
        assert w.failures == 0
        assert w.write_seconds > 0
        assert obs.gauge("ckpt/queue_depth").value == 0
    finally:
        assert w.close(timeout=10)
    assert not w.submit(*_writer_job(tmp_path, 9))    # closed refuses


def test_async_writer_full_queue_drops_oldest(tmp_path, monkeypatch):
    import threading
    started, release, wrote = (threading.Event(), threading.Event(),
                               [])

    def stalling(directory, path, arrays, bundle, keep):
        wrote.append(int(bundle["iteration"]))
        started.set()
        release.wait(10)
        return path

    monkeypatch.setattr(ckpt, "_commit_bundle", stalling)
    w = ckpt.AsyncCheckpointWriter(maxsize=1)
    try:
        w.submit(*_writer_job(tmp_path, 1))       # in flight
        assert started.wait(10)
        w.submit(*_writer_job(tmp_path, 2))       # queued
        w.submit(*_writer_job(tmp_path, 3))       # full: 2 dropped
        release.set()
        assert w.drain(timeout=30)
        assert wrote == [1, 3]                    # superseded job gone
    finally:
        w.close(timeout=10)


def test_async_writer_failure_warns_and_training_continues(
        tmp_path, monkeypatch):
    real = ckpt._commit_bundle

    def broken(*a):
        raise RuntimeError("disk full")

    f0 = counter("checkpoint/write_failures")
    monkeypatch.setattr(ckpt, "_commit_bundle", broken)
    w = ckpt.AsyncCheckpointWriter()
    try:
        w.submit(*_writer_job(tmp_path, 3))
        assert w.drain(timeout=30)
        assert w.failures == 1
        assert counter("checkpoint/write_failures") - f0 == 1
        monkeypatch.setattr(ckpt, "_commit_bundle", real)
        w.submit(*_writer_job(tmp_path, 6))       # writer survives
        assert w.drain(timeout=30)
        assert ckpt.load_checkpoint(
            str(tmp_path / "ckpt_iter_6.json"))["iteration"] == 6
    finally:
        w.close(timeout=10)


def test_resolve_resume_drains_pending_background_writes(
        tmp_path, monkeypatch):
    import time as _time
    real = ckpt._commit_bundle

    def delayed(*a):
        _time.sleep(0.3)
        return real(*a)

    monkeypatch.setattr(ckpt, "_commit_bundle", delayed)
    w = ckpt.new_writer()                 # registered: resolve_resume
    try:                                  # must drain it itself
        w.submit(*_writer_job(tmp_path, 9))
        b = ckpt.resolve_resume(str(tmp_path))
        assert int(b["iteration"]) == 9
    finally:
        w.close(timeout=10)


# ---------------------------------------------------------------------------
# kill-and-resume subprocess drill
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys
os.environ["LGBM_TPU_PLATFORM"] = "cpu"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
mode, outdir, learner = sys.argv[1], sys.argv[2], sys.argv[3]
if learner == "data":
    # mirror tests/conftest.py's 8-device virtual CPU platform
    from importlib import metadata as _md
    legacy = tuple(int(x)
                   for x in _md.version("jax").split(".")[:2]) < (0, 5)
    if legacy:
        os.environ["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8 "
            + os.environ.get("XLA_FLAGS", ""))
    import jax
    if not legacy:
        jax.config.update("jax_num_cpu_devices", 8)
import numpy as np
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata, TpuDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.utils import log
log.set_level(0)

r = np.random.default_rng(0)
X = r.normal(size=(400, 6))
y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
params = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
          "min_data_in_leaf": 5, "num_iterations": 12,
          "bagging_freq": 3, "bagging_fraction": 0.7,
          "tree_learner": learner,
          "tpu_checkpoint_dir": outdir, "tpu_checkpoint_freq": 3}
import json
params.update(json.loads(os.environ.get("LGBM_TPU_TEST_EXTRA_PARAMS",
                                        "{}")))
cfg = Config().set(params)
ds = TpuDataset(cfg).construct_from_matrix(X, Metadata(label=y))
obj = create_objective(cfg.objective, cfg)
obj.init(ds.metadata, ds.num_data)
g = GBDT(); g.init(cfg, ds, obj, [])
g.train(-1, "", resume_from=outdir if mode == "resume" else "")
with open(os.path.join(outdir, f"model_{mode}.txt"), "w") as fh:
    fh.write(g.model_to_string().split("\nparameters:\n")[0])
"""


def _run_child(script, mode, outdir, learner="serial", extra_env=None):
    env = dict(os.environ)
    env.pop("LGBM_TPU_FAULTS", None)
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, script, mode, outdir, learner],
        capture_output=True, text=True, timeout=420, env=env)


@pytest.fixture(scope="module")
def child_script(tmp_path_factory):
    p = tmp_path_factory.mktemp("drill") / "child.py"
    p.write_text(_CHILD)
    return str(p)


def _kill_resume_drill(child_script, outdir, learner):
    os.makedirs(outdir, exist_ok=True)
    # 1) uninterrupted baseline
    r = _run_child(child_script, "plain", outdir, learner)
    assert r.returncode == 0, r.stderr[-2000:]
    # 2) killed mid-train: SIGKILL at the start of iteration 9 — the
    #    checkpoints at 3 and 6 are on disk, 9's never happens
    r = _run_child(child_script, "kill", outdir, learner,
                   extra_env={"LGBM_TPU_FAULTS": "train.iter@9:kill"})
    assert r.returncode == -signal.SIGKILL, \
        f"child was not SIGKILLed (rc={r.returncode}): {r.stderr[-500:]}"
    assert os.path.exists(os.path.join(outdir, "ckpt_iter_6.json"))
    # 3) resumed from the checkpoint dir (newest valid bundle)
    r = _run_child(child_script, "resume", outdir, learner)
    assert r.returncode == 0, r.stderr[-2000:]
    plain = open(os.path.join(outdir, "model_plain.txt")).read()
    resumed = open(os.path.join(outdir, "model_resume.txt")).read()
    assert resumed == plain, \
        "kill->resume did not reproduce the uninterrupted model"


def test_kill_and_resume_bit_parity_subprocess(child_script, tmp_path):
    _kill_resume_drill(child_script, str(tmp_path), "serial")


@pytest.mark.slow
def test_kill_and_resume_bit_parity_sharded(child_script, tmp_path):
    _kill_resume_drill(child_script, str(tmp_path), "data")


def test_kill_and_resume_async_writer_no_torn_bundle(child_script,
                                                     tmp_path):
    """(PR16) the kill drill with the BACKGROUND writer on: SIGKILL can
    land with a write still in the writer queue or mid-flight, but
    atomic_write + sidecar-then-bundle ordering hold on the writer
    thread too — every bundle on disk must load cleanly (no torn
    bundle) and the newest one must resume bit-identically. Separate
    dirs keep the killed run's checkpoints unpolluted by the
    baseline's."""
    plain_dir = str(tmp_path / "plain")
    kill_dir = str(tmp_path / "kill")
    os.makedirs(plain_dir)
    os.makedirs(kill_dir)
    async_env = {"LGBM_TPU_TEST_EXTRA_PARAMS": '{"tpu_ckpt_async": 1}'}
    r = _run_child(child_script, "plain", plain_dir,
                   extra_env=async_env)
    assert r.returncode == 0, r.stderr[-2000:]
    r = _run_child(child_script, "kill", kill_dir,
                   extra_env=dict(async_env,
                                  LGBM_TPU_FAULTS="train.iter@9:kill"))
    assert r.returncode == -signal.SIGKILL, \
        f"child was not SIGKILLed (rc={r.returncode}): {r.stderr[-500:]}"
    entries = ckpt.list_checkpoints(kill_dir)
    assert entries, "killed run left no checkpoints"
    for _, p in entries:
        ckpt.load_checkpoint(p)          # schema + sidecar intact
    r = _run_child(child_script, "resume", kill_dir,
                   extra_env=async_env)
    assert r.returncode == 0, r.stderr[-2000:]
    plain = open(os.path.join(plain_dir, "model_plain.txt")).read()
    resumed = open(os.path.join(kill_dir, "model_resume.txt")).read()
    assert resumed == plain, \
        "kill->resume with the async writer did not reproduce the " \
        "uninterrupted model"


# ---------------------------------------------------------------------------
# degrade-don't-die lrb loop
# ---------------------------------------------------------------------------

def _drive_lrb(n_requests=1200, window=300, faults_spec=None,
               budget=None):
    from lightgbm_tpu import lrb
    if faults_spec:
        faults.configure(faults_spec)
    out = io.StringIO()
    drv = lrb.LrbDriver(1 << 16, window, 120, 0.5, 1, result_file=out,
                        extra_params={"num_iterations": 4,
                                      "verbose": -1},
                        window_budget_s=budget)
    for seq, oid, size, cost in lrb.synthetic_trace(n_requests, 60):
        drv.process_request(seq, oid, size, cost)
    faults.clear()
    return drv


def test_lrb_injected_window_failure_serves_stale_model():
    f0 = counter("lrb/windows_failed")
    drv = _drive_lrb(faults_spec="lrb.window_train@2")
    res = drv.results
    assert len(res) == 4
    # window 2's training failed; it is marked degraded with the reason
    assert res[1]["degraded"] is True
    assert "InjectedFault" in res[1]["degrade_reason"]
    assert res[1]["staleness_windows"] == 1
    # window 3 retrained: staleness resets
    assert "degraded" not in res[2]
    assert res[2]["staleness_windows"] == 0
    # EVERY window after the first was evaluated — the loop kept
    # serving (window 3's eval ran against window 1's stale model)
    assert all(r.get("eval_rows", 0) > 0 for r in res[1:])
    assert drv.degraded_windows() == 1
    assert counter("lrb/windows_failed") - f0 == 1
    # ... and the whole story is visible in the Prometheus export
    from lightgbm_tpu.obs.export import prometheus_text
    txt = prometheus_text(obs.default_registry().snapshot())
    assert "lgbm_tpu_lrb_windows_failed_total" in txt
    assert "lgbm_tpu_lrb_windows_degraded_total" in txt
    assert "lgbm_tpu_lrb_model_staleness_windows" in txt


def test_lrb_transient_window_failure_retries_in_place():
    r0 = counter("retry/retries")
    drv = _drive_lrb(faults_spec="lrb.window_train@2:transient")
    assert drv.degraded_windows() == 0       # retry absorbed the fault
    assert counter("retry/retries") - r0 >= 1
    assert all(r["staleness_windows"] == 0 for r in drv.results)


def test_lrb_window_budget_degrades_not_dies():
    drv = _drive_lrb(budget=0.0)             # every window blows it
    assert len(drv.results) == 4
    assert drv.degraded_windows() == 4
    assert all("WindowBudgetExceeded" in r["degrade_reason"]
               for r in drv.results)
    # no model ever trained; the loop still completed the whole trace
    assert drv.booster is None


def test_lrb_malformed_trace_lines_skipped(tmp_path):
    from lightgbm_tpu import lrb
    trace_path = tmp_path / "trace.txt"
    lines = []
    for i, (seq, oid, size, cost) in enumerate(
            lrb.synthetic_trace(900, 60)):
        lines.append(f"{seq} {oid} {size} {cost}")
        if i == 100:
            lines.append("1 2 not-a-size 1.0")
        if i == 200:
            lines.append("only two")
    trace_path.write_text("\n".join(lines) + "\n")
    out = io.StringIO()
    drv = lrb.run_trace_file(str(trace_path), 1 << 16, 300, 120, 0.5, 1,
                             result_file=out,
                             extra_params={"num_iterations": 4,
                                           "verbose": -1})
    assert drv.trace_lines_skipped == 2
    assert len(drv.results) == 3             # 900 good lines / 300


# ---------------------------------------------------------------------------
# checkpoint/snapshot robustness in the training loop
# ---------------------------------------------------------------------------

def test_checkpoint_write_failure_warns_and_never_corrupts(tmp_path):
    g1 = build_booster(dict(PARAMS, num_iterations=8))
    g1.train(-1, "")
    m1 = trees_only(g1.model_to_string())
    w0 = counter("checkpoint/write_failures")
    faults.configure("checkpoint.write@1")
    g2 = build_booster(dict(PARAMS, num_iterations=8,
                            tpu_checkpoint_dir=str(tmp_path),
                            tpu_checkpoint_freq=4))
    g2.train(-1, "")
    faults.clear()
    assert trees_only(g2.model_to_string()) == m1
    assert counter("checkpoint/write_failures") - w0 == 1
    # iteration 4's write failed cleanly; iteration 8's succeeded and
    # resolves as a usable bundle
    assert ckpt.resolve_resume(str(tmp_path))["iteration"] == 8


def test_snapshots_atomic_and_pruned(tmp_path):
    base = str(tmp_path / "model.txt")
    g = build_booster(dict(PARAMS, num_iterations=10,
                           tpu_snapshot_keep=2))
    g.train(snapshot_freq=2, output_model=base)
    snaps = sorted(glob.glob(base + ".snapshot_iter_*"))
    assert [os.path.basename(p) for p in snaps] == [
        "model.txt.snapshot_iter_10", "model.txt.snapshot_iter_8"]
    # each surviving snapshot is complete, parseable model text
    for p in snaps:
        GBDT().load_model_from_string(open(p).read(), source=p)
    assert not glob.glob(base + "*.tmp*"), "torn tmp files left behind"


def test_load_model_one_line_errors():
    g = build_booster(dict(PARAMS, num_iterations=3))
    g.train(-1, "")
    good = g.model_to_string()
    with pytest.raises(LightGBMError, match="not a LightGBM model"):
        GBDT().load_model_from_string("garbage\nstuff\n", source="x.txt")
    truncated = good[: good.index("end of trees") - 40]
    with pytest.raises(LightGBMError, match="truncated model text"):
        GBDT().load_model_from_string(truncated, source="x.txt")
    broken = good.replace("left_child=", "left_child=zap ", 1)
    with pytest.raises(LightGBMError, match="malformed Tree="):
        GBDT().load_model_from_string(broken, source="x.txt")


def test_export_write_fault_does_not_crash(tmp_path):
    from lightgbm_tpu.obs.export import MetricsExporter
    faults.configure("export.write@1+")
    ex = MetricsExporter(base_path=str(tmp_path / "m"),
                         interval_s=60.0, port=-1)
    ex.start()
    ex.stop()
    faults.clear()
    assert not os.path.exists(str(tmp_path / "m.prom"))


def test_bench_regression_tolerates_new_fields():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "tools"))
    import check_bench_regression as cbr
    doc = {"metric": "m", "value": 1.0, "unit": "M row-iters/s",
           "degraded_windows": 2,
           "checkpoint": {"iteration": 40, "writes": 3}}
    notes = cbr.field_notes(doc)
    assert any("2 degraded window" in n for n in notes)
    assert any("checkpoint meta" in n for n in notes)
    # wrong-typed fields are reported, never a crash
    weird = dict(doc, degraded_windows="many", checkpoint=[1, 2])
    notes = cbr.field_notes(weird)
    assert any("not numeric" in n for n in notes)
    assert any("not an object" in n for n in notes)
    # and compare() ignores them entirely
    assert cbr.compare(doc, dict(doc)) == []
