"""Distributed data loading: per-rank shards, agreed bin mappers.

Covers the reference's distributed-loading semantics
(src/io/dataset_loader.cpp:163-167 round-robin / pre_partition row
assignment; :434-466 distributed bin-mapper agreement) in their TPU
redesign (lightgbm_tpu/io/distributed.py), emulated as S hosts in one
process.
"""
import numpy as np
import pytest

from conftest import TEST_PARAMS, make_binary


def _infos(ds):
    return [m.feature_info() for m in ds.mappers]


def _make_cfg(**kw):
    from lightgbm_tpu.config import Config
    full = dict(TEST_PARAMS)
    full.update({"objective": "binary", "metric": "auc"})
    full.update(kw)
    return Config().set(full)


def test_mapper_agreement_across_ranks():
    """All ranks end with byte-identical bin boundaries."""
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.io.distributed import DistributedLoader

    X, y = make_binary(n=2000, f=8, seed=3)
    cfg = _make_cfg()
    world = 4
    shards = [X[np.arange(r, X.shape[0], world)] for r in range(world)]
    datasets = []
    for r in range(world):
        ld = DistributedLoader(cfg, world=world, rank=r)
        ds = ld.load_rank_matrix(
            X, Metadata(label=y), all_shards=shards)
        datasets.append(ds)
    ref = _infos(datasets[0])
    for ds in datasets[1:]:
        assert _infos(ds) == ref
    # round-robin split partitions the rows
    assert sum(d.num_data for d in datasets) == X.shape[0]
    assert datasets[0].num_data == 500


def test_mapper_agreement_uneven_rows():
    """Row count not divisible by world: ranks still agree bit-exactly
    (the global total, not rank-local extrapolation, scales the bin
    sample and min_data filter)."""
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.io.distributed import DistributedLoader

    X, y = make_binary(n=2001, f=6, seed=17)
    cfg = _make_cfg()
    world = 4
    datasets = [
        DistributedLoader(cfg, world=world, rank=r).load_rank_matrix(
            X, Metadata(label=y)) for r in range(world)]
    ref = _infos(datasets[0])
    for ds in datasets[1:]:
        assert _infos(ds) == ref
    assert sum(d.num_data for d in datasets) == 2001
    assert datasets[0].num_data == 501


def test_local_vs_global_bins_close():
    """Owner-rule bins come from a quarter of the sample yet must stay
    usable: training with them matches global-bin training quality."""
    from conftest import fit_gbdt
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.io.distributed import (DistributedLoader,
                                             local_bin_mappers,
                                             shard_bin_mappers)
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.metrics import create_metrics

    X, y = make_binary(n=4000, f=8, seed=5)
    cfg = _make_cfg()
    world = 4
    shards = [X[np.arange(r, X.shape[0], world)] for r in range(world)]
    agreed = shard_bin_mappers(
        [local_bin_mappers(s, cfg, (), X.shape[0]) for s in shards])

    # train on the FULL data binned with the distributed-agreed mappers
    ds = TpuDataset(cfg).construct_from_matrix(
        X, Metadata(label=y), mappers=agreed)
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    mets = create_metrics(["auc"], cfg, ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, mets)
    for _ in range(30):
        g.train_one_iter()
    (_, auc_dist, _), = g.get_eval_at(0)

    g2 = fit_gbdt(X, y, {"objective": "binary", "metric": "auc"},
                  num_round=30)
    (_, auc_global, _), = g2.get_eval_at(0)
    assert auc_dist == pytest.approx(auc_global, abs=0.02)
    assert auc_dist > 0.9


def test_round_robin_file(tmp_path):
    """Shared-file round-robin: each rank keeps its slice; mappers agree
    because the emulation computes every rank's slice locally."""
    from lightgbm_tpu.io.distributed import DistributedLoader

    X, y = make_binary(n=600, f=5, seed=7)
    f = tmp_path / "train.csv"
    np.savetxt(f, np.column_stack([y, X]), delimiter=",", fmt="%.7g")
    cfg = _make_cfg()
    ds0 = DistributedLoader(cfg, world=2, rank=0).load_rank_file(str(f))
    ds1 = DistributedLoader(cfg, world=2, rank=1).load_rank_file(str(f))
    assert ds0.num_data == 300 and ds1.num_data == 300
    assert _infos(ds0) == _infos(ds1)
    # complementary rows: labels interleave back to the original
    lab = np.empty(600, np.float32)
    lab[0::2] = ds0.metadata.label
    lab[1::2] = ds1.metadata.label
    np.testing.assert_array_equal(lab, y.astype(np.float32))


def test_pre_partition_peer_files(tmp_path):
    """pre_partition=true: one file per host; the emulated mapper
    exchange (peer_files) yields identical bins on every rank."""
    from lightgbm_tpu.io.distributed import DistributedLoader

    X, y = make_binary(n=800, f=5, seed=11)
    files = []
    for r in range(2):
        sel = np.arange(r, 800, 2)
        fp = tmp_path / f"part{r}.csv"
        np.savetxt(fp, np.column_stack([y[sel], X[sel]]),
                   delimiter=",", fmt="%.7g")
        files.append(str(fp))
    cfg = _make_cfg(pre_partition=True)
    ds0 = DistributedLoader(cfg, world=2, rank=0).load_rank_file(
        files[0], peer_files=files)
    ds1 = DistributedLoader(cfg, world=2, rank=1).load_rank_file(
        files[1], peer_files=files)
    assert ds0.num_data == ds1.num_data == 400
    assert _infos(ds0) == _infos(ds1)


def test_distributed_shards_train_data_parallel():
    """End-to-end: shard-binned rows (agreed mappers) feed the
    data-parallel learner on the 8-device mesh and reach the same
    quality as single-machine training."""
    from conftest import fit_gbdt
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.io.distributed import DistributedLoader
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.metrics import create_metrics

    X, y = make_binary(n=2048, f=8, seed=13)
    cfg = _make_cfg(tree_learner="data", num_machines=8)
    world = 8
    shards = [X[np.arange(r, X.shape[0], world)] for r in range(world)]
    ranks = []
    for r in range(world):
        ld = DistributedLoader(cfg, world=world, rank=r)
        ranks.append(ld.load_rank_matrix(
            X, Metadata(label=y), all_shards=shards))
    # one process stands in for all hosts: device d holds rank d's rows,
    # which is exactly the round-robin interleave below
    order = np.concatenate(
        [np.arange(r, X.shape[0], world) for r in range(world)])
    Xg = np.concatenate([X[np.arange(r, X.shape[0], world)]
                         for r in range(world)])
    yg = y[order]
    ds = TpuDataset(cfg).construct_from_matrix(
        Xg, Metadata(label=yg),
        mappers=[ranks[0].mappers[ranks[0].real_to_inner[j]]
                 if j in ranks[0].real_to_inner else _trivial()
                 for j in range(X.shape[1])])
    obj = create_objective("binary", cfg)
    obj.init(ds.metadata, ds.num_data)
    mets = create_metrics(["auc"], cfg, ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, mets)
    for _ in range(20):
        g.train_one_iter()
    (_, auc_dp, _), = g.get_eval_at(0)

    g2 = fit_gbdt(X, y, {"objective": "binary", "metric": "auc"},
                  num_round=20)
    (_, auc_serial, _), = g2.get_eval_at(0)
    assert auc_dp == pytest.approx(auc_serial, abs=0.02)


def _trivial():
    from lightgbm_tpu.io.binning import BinMapper
    m = BinMapper()
    m.find_bin(np.zeros(0), 10, 63, 1, 0)
    return m


# ---------------------------------------------------------------------------
# _rank_rows / _slice_metadata boundary cases
# ---------------------------------------------------------------------------

def test_rank_rows_uneven_world_partitions_exactly():
    """n % world != 0: both assignment modes tile [0, n) with no
    overlap, no loss, and the documented per-rank counts."""
    from lightgbm_tpu.io.distributed import _rank_rows
    for n, world in ((2001, 4), (7, 3), (5, 8), (1024, 7)):
        for mode in ("round_robin", "contiguous"):
            parts = [_rank_rows(n, r, world, None, mode)
                     for r in range(world)]
            allr = np.concatenate(parts)
            assert len(allr) == n, (n, world, mode)
            np.testing.assert_array_equal(np.sort(allr), np.arange(n))
            if mode == "contiguous":
                # order-preserving blocks: concatenation IS the
                # original order (the elastic path's parity invariant)
                np.testing.assert_array_equal(allr, np.arange(n))
                sizes = [len(p) for p in parts]
                b = -(-n // world)
                assert all(s <= b for s in sizes)
            else:
                assert [len(p) for p in parts] == [
                    len(range(r, n, world)) for r in range(world)]


def test_rank_rows_world_larger_than_data():
    """More ranks than rows: trailing ranks legitimately hold zero
    rows — never a crash, never a duplicated row."""
    from lightgbm_tpu.io.distributed import _rank_rows
    for mode in ("round_robin", "contiguous"):
        parts = [_rank_rows(3, r, 5, None, mode) for r in range(5)]
        assert sum(len(p) for p in parts) == 3
        assert any(len(p) == 0 for p in parts)


def test_rank_rows_queries_never_split_across_ranks():
    """Query boundaries: whole queries ride one rank in BOTH modes,
    including queries that would straddle a naive row boundary (the
    7-row query sits exactly across n/2)."""
    from lightgbm_tpu.io.distributed import _rank_rows, _slice_metadata
    from lightgbm_tpu.io.dataset import Metadata

    sizes = [3, 5, 7, 2, 4, 6, 1, 8]          # 36 rows, uneven
    qb = np.concatenate([[0], np.cumsum(sizes)])
    n = int(qb[-1])
    y = np.arange(n, dtype=np.float32)
    for mode in ("round_robin", "contiguous"):
        world = 3
        seen = []
        for r in range(world):
            sel = _rank_rows(n, r, world, qb, mode)
            seen.append(sel)
            # every selected row's query is FULLY selected
            for q in range(len(sizes)):
                q_rows = set(range(qb[q], qb[q + 1]))
                inter = q_rows & set(sel.tolist())
                assert inter in (set(), q_rows), (mode, r, q)
            # metadata slices agree with the row assignment
            meta = Metadata(label=y, group=np.asarray(sizes))
            ml = _slice_metadata(meta, sel, n, r, world, mode)
            np.testing.assert_array_equal(ml.label, y[sel])
            assert int(ml.query_boundaries[-1]) == len(sel)
        allr = np.sort(np.concatenate(seen))
        np.testing.assert_array_equal(allr, np.arange(n))


def test_slice_metadata_multiclass_init_score_uneven():
    """init_score is the flattened [K*N] layout: per-class slicing
    must survive an uneven world split."""
    from lightgbm_tpu.io.distributed import _rank_rows, _slice_metadata
    from lightgbm_tpu.io.dataset import Metadata

    n, k, world = 10, 3, 4
    isc = np.arange(k * n, dtype=np.float64)
    meta = Metadata(label=np.arange(n, dtype=np.float32),
                    init_score=isc)
    for mode in ("round_robin", "contiguous"):
        for r in range(world):
            sel = _rank_rows(n, r, world, None, mode)
            ml = _slice_metadata(meta, sel, n, r, world, mode)
            want = isc.reshape(k, n)[:, sel].reshape(-1)
            np.testing.assert_array_equal(np.asarray(ml.init_score),
                                          want)


def test_single_rank_world_degenerates_to_serial_bit_identically():
    """world=1 must be EXACTLY the serial path: same rows, same
    mappers, same bins — resuming a 1-host cluster cannot differ from
    never having been distributed."""
    from lightgbm_tpu.io.dataset import Metadata, TpuDataset
    from lightgbm_tpu.io.distributed import DistributedLoader

    X, y = make_binary(n=777, f=6, seed=21)
    cfg = _make_cfg()
    ds = DistributedLoader(cfg, world=1, rank=0).load_rank_matrix(
        X, Metadata(label=y))
    ref = TpuDataset(_make_cfg()).construct_from_matrix(
        X, Metadata(label=y))
    assert ds.num_data == ref.num_data == 777
    assert _infos(ds) == _infos(ref)
    np.testing.assert_array_equal(ds.host_bins(), ref.host_bins())
    np.testing.assert_array_equal(ds.metadata.label, ref.metadata.label)


def test_contiguous_mode_rank_matrix_blocks():
    """contiguous=True hands each rank an order-preserving block and
    the usual agreed mappers."""
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.io.distributed import DistributedLoader

    X, y = make_binary(n=1001, f=5, seed=23)
    cfg = _make_cfg()
    world = 3
    dss = [DistributedLoader(cfg, world=world, rank=r).load_rank_matrix(
        X, Metadata(label=y), contiguous=True) for r in range(world)]
    assert [d.num_data for d in dss] == [334, 334, 333]
    ref = _infos(dss[0])
    for d in dss[1:]:
        assert _infos(d) == ref
    np.testing.assert_array_equal(
        np.concatenate([d.metadata.label for d in dss]),
        y.astype(np.float32))
