"""Device-side metric reductions (metrics/metric.py device_eval_builder).

The reference evaluates metrics on host scores (gbdt.cpp:432-534); here
scores live on device, so per-iteration eval (early stopping) runs as a
jitted reduction and downloads one scalar per metric. These tests pin
device values against the f64 host implementations.
"""
import numpy as np
import pytest

from conftest import TEST_PARAMS, fit_gbdt, make_binary


def _parity(metric_names, objective, y, scores, weights=None, num_class=1):
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import create_metrics
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.io.dataset import Metadata

    n = y.shape[0]
    cfg = Config().set({"objective": objective, "num_class": num_class})
    md = Metadata(label=y, weight=weights)
    mets = create_metrics(metric_names, cfg, md, n)
    obj = create_objective(objective, cfg)
    obj.init(md, n)
    raw = np.asarray(scores, np.float64)
    # padded scores: device path must ignore the pad columns
    pad = np.concatenate([scores, np.full((scores.shape[0], 7), 1e9,
                                          np.float32)], axis=1)
    for m in mets:
        b = m.device_eval_builder(obj)
        assert b is not None, m.name
        got = float(b(jnp.asarray(pad)))
        (_, want), = m.eval(raw, obj)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6,
                                   err_msg=m.name)


def test_binary_metrics_parity():
    r = np.random.default_rng(0)
    n = 5000
    y = (r.random(n) > 0.4).astype(np.float32)
    s = r.normal(size=(1, n)).astype(np.float32)
    s[0, :50] = s[0, 50:100]                 # score ties for AUC groups
    _parity(["auc", "binary_logloss", "binary_error"], "binary", y, s)
    w = r.uniform(0.5, 2.0, n).astype(np.float32)
    _parity(["auc", "binary_logloss", "binary_error"], "binary", y, s,
            weights=w)


def test_regression_metrics_parity():
    r = np.random.default_rng(1)
    n = 4000
    y = r.normal(size=n).astype(np.float32)
    s = (y + 0.3 * r.normal(size=n)).astype(np.float32)[None]
    _parity(["l2", "rmse", "l1"], "regression", y, s)
    w = r.uniform(0.1, 3.0, n).astype(np.float32)
    _parity(["l2", "rmse", "l1"], "regression", y, s, weights=w)


def test_multiclass_metrics_parity():
    r = np.random.default_rng(2)
    n, k = 3000, 4
    y = r.integers(0, k, n).astype(np.float32)
    s = r.normal(size=(k, n)).astype(np.float32)
    _parity(["multi_logloss", "multi_error"], "multiclass", y, s,
            num_class=k)


def test_training_uses_device_eval():
    """get_eval_at routes through the jitted device reduction when all
    metrics support it, and matches a host re-evaluation."""
    X, y = make_binary(n=1500, f=6, seed=31)
    g = fit_gbdt(X, y, dict(TEST_PARAMS, objective="binary",
                            metric="auc,binary_logloss"), num_round=8)
    assert g._device_eval_fn(0, g.training_metrics) is not None
    got = {n: v for n, v, _ in g.get_eval_at(0)}
    raw = np.asarray(g.train_scores())
    for m in g.training_metrics:
        for name, want in m.eval(raw, g.objective):
            np.testing.assert_allclose(got[name], want, rtol=2e-5,
                                       err_msg=name)


def test_unsupported_metric_falls_back_to_host():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.metrics import create_metrics
    from lightgbm_tpu.io.dataset import Metadata

    y = np.zeros(100, np.float32)
    cfg = Config().set({"objective": "regression"})
    (m,) = create_metrics(["huber"], cfg, Metadata(label=y), 100)
    assert m.device_eval_builder(None) is None


def test_pipelined_early_stopping_matches_sync():
    """The engine's pipelined (one-iteration-lookahead) evaluation must
    stop at the same best_iteration as the synchronous path, and trim
    the lookahead iteration from the model."""
    import lightgbm_tpu as lgb

    X, y = make_binary(n=1600, f=6, seed=41)
    Xv, yv = make_binary(n=500, f=6, seed=42)
    params = {"objective": "binary", "metric": "auc", "num_leaves": 15,
              "max_bin": 63, "learning_rate": 0.3, "verbose": -1}

    def run(force_sync):
        from lightgbm_tpu.basic import Booster
        ds = lgb.Dataset(X, label=y, params=params)
        dv = ds.create_valid(Xv, label=yv)
        orig = Booster.eval_dispatch_async
        if force_sync:
            Booster.eval_dispatch_async = lambda self, inc: None
        try:
            return lgb.train(params, ds, 80, valid_sets=[dv],
                             callbacks=[lgb.early_stopping(
                                 5, verbose=False)],
                             verbose_eval=False,
                             keep_training_booster=True)
        finally:
            Booster.eval_dispatch_async = orig

    fast = run(False)
    slow = run(True)
    assert fast.best_iteration == slow.best_iteration
    # lookahead iteration was rolled back: at most best + patience trees
    assert fast.num_trees() == slow.num_trees()
    np.testing.assert_allclose(
        fast.predict(Xv[:100]), slow.predict(Xv[:100]), atol=1e-6)


def test_logloss_confident_mispredictions_exact():
    """Device logloss is computed from RAW scores (softplus /
    logsumexp) — no probability clipping, so confident mispredictions
    (|raw| ~ 30) give the same value as the f64 host path instead of
    being capped at -log(1e-7)."""
    r = np.random.default_rng(3)
    n = 400
    y = (r.random(n) > 0.5).astype(np.float32)
    raw = np.where(y > 0, -30.0, 30.0).astype(np.float32)  # all wrong
    raw[: n // 4] *= -1                                    # some right
    _parity(["binary_logloss"], "binary", y, raw[None, :])
    y3 = r.integers(0, 3, n).astype(np.float32)
    raw3 = r.normal(size=(3, n)).astype(np.float32) * 20.0
    _parity(["multi_logloss"], "multiclass", y3, raw3, num_class=3)


def test_user_callback_sees_consistent_iteration():
    """A user-supplied after-iteration callback disables eval
    pipelining: CallbackEnv.iteration must match the number of trees
    the booster actually holds (no one-iteration lookahead skew)."""
    import lightgbm_tpu as lgb
    X, y = make_binary(n=800, f=5, seed=5)
    train = lgb.Dataset(X, label=y, params=dict(TEST_PARAMS))
    seen = []

    def spy(env):
        seen.append((env.iteration,
                     env.model.current_iteration()))

    lgb.train(dict(TEST_PARAMS, objective="binary", metric="auc",
                   verbose=-1),
              train, num_boost_round=6, valid_sets=[train],
              callbacks=[spy])
    assert len(seen) == 6
    for it, have in seen:
        assert have == it + 1, (it, have)
