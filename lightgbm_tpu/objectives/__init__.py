from .objective import (ObjectiveFunction, create_objective,
                        parse_objective_from_model_string)

__all__ = ["ObjectiveFunction", "create_objective",
           "parse_objective_from_model_string"]
