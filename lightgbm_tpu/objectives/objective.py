"""Objective functions (gradient/hessian providers).

TPU-native counterparts of the reference objectives
(reference: src/objective/objective_function.cpp:10-46 factory;
regression_objective.hpp, binary_objective.hpp, multiclass_objective.hpp,
rank_objective.hpp, xentropy_objective.hpp). Formulas follow the reference
exactly (file:line cited per class); evaluation is vectorized jax instead
of OpenMP loops. Scores are laid out [num_class, N] like the reference's
class-major score buffer.

The pairwise lambdarank loops (rank_objective.hpp:81-166) become padded
per-query dense [Q, Q] matrices under ``vmap`` — no data-dependent loops.
The reference's sigmoid lookup table (rank_objective.hpp:171-196) is a CPU
speed hack; we compute the exact sigmoid on the VPU.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log


def _wmul(x, w):
    return x if w is None else x * w


class ObjectiveFunction:
    """Base interface (include/LightGBM/objective_function.h:20-80)."""

    name = "base"
    is_constant_hessian = False
    num_positive_data = 0
    need_query = False

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data):
        self.label = np.asarray(metadata.label, np.float32)
        self.weights = (None if metadata.weights is None
                        else np.asarray(metadata.weights, np.float32))
        self.num_data = num_data

    # grad/hess for one model-per-iteration class slot
    def get_gradients(self, score):
        raise NotImplementedError

    # -- pure gradient seam (ops/step_cache.py) ---------------------------
    #
    # The process-wide compiled-step registry shares ONE jitted training
    # step between boosters, so the gradient computation cannot close
    # over this instance's label/weight arrays (they would embed as
    # trace constants). Eligible objectives expose:
    #   gradient_aux()      -> pytree of host arrays whose LAST axis is
    #                          the row axis (the caller pads it to the
    #                          step's bucketed width)
    #   gradient_builder()  -> pure fn(score, aux) -> (g, h) closing
    #                          only over config scalars
    #   static_key()        -> hashable tuple of everything the builder
    #                          closes over (part of the geometry key)
    # ``get_gradients`` delegates to the same pure fn, so the legacy
    # per-instance step and the shared step run IDENTICAL code — a
    # registry hit cannot change numerics. Aux dict keys starting with
    # ``_`` are NOT row-shaped (lambdarank's padded query tables) and
    # ride to the device unpadded/replicated. An objective without a
    # sound pure seam would return None and keep the legacy closure
    # (none remain in-tree — lambdarank, the last holdout, rides its
    # query tables as ``_``-keys).

    def gradient_aux(self):
        return None

    def gradient_builder(self):
        return None

    def static_key(self) -> tuple:
        return (self.name,)

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, raw):
        """Raw score -> output transform (identity by default)."""
        return raw

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    def is_renew_tree_output(self) -> bool:
        return False

    def renew_tree_output(self, pred, residual_fn, leaf_ids, num_leaves):
        raise NotImplementedError

    def to_string(self) -> str:
        return self.name


# --------------------------------------------------------------------------
# Regression family (src/objective/regression_objective.hpp)
# --------------------------------------------------------------------------

class RegressionL2Loss(ObjectiveFunction):
    """L2 (regression_objective.hpp:96-108): g = s - y, h = 1."""
    name = "regression"
    is_constant_hessian = True  # without weights

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.config.reg_sqrt:
            self.trans_label = np.sign(self.label) * np.sqrt(np.abs(self.label))
        else:
            self.trans_label = self.label
        self.is_constant_hessian = self.weights is None

    def gradient_aux(self):
        return {"y": self.trans_label, "w": self.weights}

    def gradient_builder(self):
        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            g = _wmul(score - y, w)
            h = jnp.ones_like(score) if w is None else w
            return g, h
        return fn

    def get_gradients(self, score):
        return self.gradient_builder()(score, self.gradient_aux())

    def boost_from_score(self, class_id):
        # weighted mean label (regression_objective.hpp:142-160)
        if self.weights is None:
            return float(np.mean(self.trans_label))
        return float(np.sum(self.trans_label * self.weights)
                     / np.sum(self.weights))

    def convert_output(self, raw):
        if self.config.reg_sqrt:
            return jnp.sign(raw) * raw * raw
        return raw


class RegressionL1Loss(RegressionL2Loss):
    """L1 (regression_objective.hpp:185-199): g = sign(s - y), h = 1;
    leaf outputs renewed to the residual median (hpp:219-258)."""
    name = "regression_l1"

    def gradient_builder(self):
        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            g = _wmul(jnp.sign(score - y), w)
            h = jnp.ones_like(score) if w is None else w
            return g, h
        return fn

    def boost_from_score(self, class_id):
        # weighted median (hpp:204-217)
        return _weighted_percentile(self.trans_label, self.weights, 0.5)

    def is_renew_tree_output(self):
        return True

    def renew_tree_output_percentile(self):
        return 0.5


class RegressionHuberLoss(RegressionL2Loss):
    """Huber (regression_objective.hpp:281-303)."""
    name = "huber"
    is_constant_hessian = False

    def gradient_builder(self):
        a = float(self.config.alpha)

        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            diff = score - y
            g = jnp.where(jnp.abs(diff) <= a, diff, jnp.sign(diff) * a)
            g = _wmul(g, w)
            h = jnp.ones_like(score) if w is None else w
            return g, h
        return fn

    def static_key(self):
        return (self.name, float(self.config.alpha))


class RegressionFairLoss(RegressionL2Loss):
    """Fair (regression_objective.hpp:335-349)."""
    name = "fair"
    is_constant_hessian = False

    def gradient_builder(self):
        c = float(self.config.fair_c)

        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            x = score - y
            g = _wmul(c * x / (jnp.abs(x) + c), w)
            h = _wmul(c * c / (jnp.abs(x) + c) ** 2, w)
            return g, h
        return fn

    def static_key(self):
        return (self.name, float(self.config.fair_c))


class RegressionPoissonLoss(RegressionL2Loss):
    """Poisson (regression_objective.hpp:414-426): score is log-mean."""
    name = "poisson"
    is_constant_hessian = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(self.label < 0):
            log.fatal("[poisson]: at least one target label is negative")

    def gradient_aux(self):
        return {"y": self.label, "w": self.weights}

    def gradient_builder(self):
        mds = float(self.config.poisson_max_delta_step)

        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            g = _wmul(jnp.exp(score) - y, w)
            h = _wmul(jnp.exp(score + mds), w)
            return g, h
        return fn

    def static_key(self):
        return (self.name, float(self.config.poisson_max_delta_step))

    def boost_from_score(self, class_id):
        return math.log(max(RegressionL2Loss.boost_from_score(self, class_id),
                            1e-20))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionQuantileLoss(RegressionL2Loss):
    """Quantile (regression_objective.hpp:465-487)."""
    name = "quantile"

    def gradient_aux(self):
        return {"y": self.label, "w": self.weights}

    def gradient_builder(self):
        a = float(self.config.alpha)

        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            g = _wmul(jnp.where(score > y, 1.0 - a, -a), w)
            h = jnp.ones_like(score) if w is None else w
            return g, h
        return fn

    def static_key(self):
        return (self.name, float(self.config.alpha))

    def boost_from_score(self, class_id):
        return _weighted_percentile(self.label, self.weights,
                                    self.config.alpha)

    def is_renew_tree_output(self):
        return True

    def renew_tree_output_percentile(self):
        return self.config.alpha


class RegressionMAPELoss(RegressionL2Loss):
    """MAPE (regression_objective.hpp:560-620)."""
    name = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label_weight = 1.0 / np.maximum(1.0, np.abs(self.label))
        if self.weights is not None:
            self.label_weight = self.label_weight * self.weights

    def gradient_aux(self):
        return {"y": self.label, "lw": self.label_weight,
                "w": self.weights}

    def gradient_builder(self):
        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            lw = jnp.asarray(aux["lw"])
            g = jnp.sign(score - y) * lw
            h = (jnp.ones_like(score) if aux["w"] is None
                 else jnp.asarray(aux["w"]))
            return g, h
        return fn

    def boost_from_score(self, class_id):
        return _weighted_percentile(self.label, self.label_weight, 0.5)

    def is_renew_tree_output(self):
        return True

    def renew_tree_output_percentile(self):
        return 0.5


class RegressionGammaLoss(RegressionPoissonLoss):
    """Gamma (regression_objective.hpp:663-675)."""
    name = "gamma"

    def gradient_builder(self):
        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            g = 1.0 - y / jnp.exp(score)
            h = y / jnp.exp(score)
            return _wmul(g, w), _wmul(h, w)
        return fn

    def static_key(self):
        return (self.name,)


class RegressionTweedieLoss(RegressionPoissonLoss):
    """Tweedie (regression_objective.hpp:701-722)."""
    name = "tweedie"

    def gradient_builder(self):
        rho = float(self.config.tweedie_variance_power)

        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            e1 = jnp.exp((1.0 - rho) * score)
            e2 = jnp.exp((2.0 - rho) * score)
            g = -y * e1 + e2
            h = -y * (1.0 - rho) * e1 + (2.0 - rho) * e2
            return _wmul(g, w), _wmul(h, w)
        return fn

    def static_key(self):
        return (self.name,
                float(self.config.tweedie_variance_power))


# --------------------------------------------------------------------------
# Binary (src/objective/binary_objective.hpp:13-170)
# --------------------------------------------------------------------------

class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        # needed by convert_output on loaded models (no init() there)
        self.sigmoid = config.sigmoid

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        is_pos = self.label > 0
        cnt_pos = int(is_pos.sum())
        cnt_neg = int(num_data - cnt_pos)
        self.num_positive_data = cnt_pos
        w_pos, w_neg = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.config.scale_pos_weight
        self.label_val = np.where(is_pos, 1.0, -1.0).astype(np.float32)
        self.label_weight = np.where(is_pos, w_pos, w_neg).astype(np.float32)
        self.sigmoid = self.config.sigmoid
        if cnt_pos == 0 or cnt_neg == 0:
            log.warning("Contains only one class")

    def gradient_aux(self):
        return {"lv": self.label_val, "lw": self.label_weight,
                "w": self.weights}

    def gradient_builder(self):
        sig = float(self.sigmoid)

        def fn(score, aux):
            lv = jnp.asarray(aux["lv"])
            lw = jnp.asarray(aux["lw"])
            if aux["w"] is not None:
                lw = lw * jnp.asarray(aux["w"])
            response = -lv * sig / (1.0 + jnp.exp(lv * sig * score))
            ar = jnp.abs(response)
            g = response * lw
            h = ar * (sig - ar) * lw
            return g, h
        return fn

    def static_key(self):
        return (self.name, float(self.sigmoid))

    def get_gradients(self, score):
        return self.gradient_builder()(score, self.gradient_aux())

    def boost_from_score(self, class_id):
        # binary_objective.hpp:124-142
        if self.weights is not None:
            suml = float(np.sum((self.label > 0) * self.weights))
            sumw = float(np.sum(self.weights))
        else:
            suml = float(np.sum(self.label > 0))
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, 1e-15), 1e-15), 1.0 - 1e-15)
        return math.log(pavg / (1.0 - pavg)) / self.sigmoid

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"binary sigmoid:{self.sigmoid:g}"


# --------------------------------------------------------------------------
# Multiclass (src/objective/multiclass_objective.hpp:16-220)
# --------------------------------------------------------------------------

class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        # needed by to_string/convert_output on loaded models
        self.num_class = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.num_class = self.config.num_class
        self.label_int = self.label.astype(np.int32)
        if np.any((self.label_int < 0) | (self.label_int >= self.num_class)):
            log.fatal("Label must be in [0, num_class)")

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def gradient_aux(self):
        return {"yi": self.label_int, "w": self.weights}

    def gradient_builder(self):
        K = int(self.num_class)

        def fn(score, aux):
            """score: [K, N] -> grads/hess [K, N]
            (multiclass_objective.hpp:68)."""
            y = jax.nn.one_hot(jnp.asarray(aux["yi"]), K, axis=0,
                               dtype=score.dtype)   # [K, N]
            p = jax.nn.softmax(score, axis=0)
            g = p - y
            h = 2.0 * p * (1.0 - p)
            if aux["w"] is not None:
                w = jnp.asarray(aux["w"])[None, :]
                g, h = g * w, h * w
            return g, h
        return fn

    def static_key(self):
        return (self.name, int(self.num_class))

    def get_gradients(self, score):
        return self.gradient_builder()(score, self.gradient_aux())

    def boost_from_score(self, class_id):
        return 0.0

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=0)

    def to_string(self):
        return f"multiclass num_class:{self.num_class}"


class MulticlassOVA(ObjectiveFunction):
    """One-vs-all (multiclass_objective.hpp:167-220): K independent
    binary objectives."""
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.binary = []
        for k in range(self.num_class):
            sub = _Shim(self.config)
            meta_k = _MetaShim((self.label == k).astype(np.float32),
                               self.weights)
            b = BinaryLogloss(self.config)
            b.init(meta_k, num_data)
            self.binary.append(b)

    @property
    def num_model_per_iteration(self):
        return self.num_class

    def gradient_aux(self):
        return {"lv": np.stack([b.label_val for b in self.binary]),
                "lw": np.stack([b.label_weight for b in self.binary]),
                "w": self.weights}

    def gradient_builder(self):
        # K independent binary objectives, vectorized over the class
        # axis — elementwise, so bit-identical to the per-class loop
        sig = float(self.config.sigmoid)

        def fn(score, aux):
            lv = jnp.asarray(aux["lv"])             # [K, N]
            lw = jnp.asarray(aux["lw"])
            if aux["w"] is not None:
                lw = lw * jnp.asarray(aux["w"])[None, :]
            response = -lv * sig / (1.0 + jnp.exp(lv * sig * score))
            ar = jnp.abs(response)
            return response * lw, ar * (sig - ar) * lw
        return fn

    def static_key(self):
        return (self.name, int(self.num_class),
                float(self.config.sigmoid))

    def get_gradients(self, score):
        return self.gradient_builder()(score, self.gradient_aux())

    def boost_from_score(self, class_id):
        return self.binary[class_id].boost_from_score(0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * raw))

    def to_string(self):
        return (f"multiclassova num_class:{self.num_class} "
                f"sigmoid:{self.config.sigmoid:g}")


class _Shim:
    def __init__(self, config):
        self.__dict__.update(config.__dict__)


class _MetaShim:
    def __init__(self, label, weights):
        self.label = label
        self.weights = weights


# --------------------------------------------------------------------------
# Cross entropy (src/objective/xentropy_objective.hpp)
# --------------------------------------------------------------------------

class CrossEntropy(ObjectiveFunction):
    """xentropy (hpp:77-86): labels in [0,1]; z = sigmoid(s)."""
    name = "cross_entropy"

    def gradient_aux(self):
        return {"y": self.label, "w": self.weights}

    def gradient_builder(self):
        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            w = aux["w"]
            w = None if w is None else jnp.asarray(w)
            z = 1.0 / (1.0 + jnp.exp(-score))
            g = _wmul(z - y, w)
            h = z * (1.0 - z)
            if w is not None:
                h = h * w
            return g, h
        return fn

    def get_gradients(self, score):
        return self.gradient_builder()(score, self.gradient_aux())

    def boost_from_score(self, class_id):
        # xentropy_objective.hpp:107-118: log(pavg / (1 - pavg))
        if self.weights is not None:
            pavg = float(np.sum(self.label * self.weights)
                         / np.sum(self.weights))
        else:
            pavg = float(np.mean(self.label))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-raw))

    def to_string(self):
        return "cross_entropy"


class CrossEntropyLambda(ObjectiveFunction):
    """xentlambda (hpp:150-240): intensity-weighted cross entropy."""
    name = "cross_entropy_lambda"

    def gradient_aux(self):
        return {"y": self.label, "w": self.weights}

    def gradient_builder(self):
        weighted = self.weights is not None

        def fn(score, aux):
            y = jnp.asarray(aux["y"])
            if not weighted:
                # unit weights: identical to CrossEntropy (hpp:184-189)
                z = 1.0 / (1.0 + jnp.exp(-score))
                return z - y, z * (1.0 - z)
            return _xentlambda_weighted(score, y,
                                        jnp.asarray(aux["w"]))
        return fn

    def get_gradients(self, score):
        return self.gradient_builder()(score, self.gradient_aux())

    def boost_from_score(self, class_id):
        pavg = float(np.mean(self.label))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))

    def to_string(self):
        return "cross_entropy_lambda"


def _xentlambda_weighted(score, y, w):
    """Weighted xentlambda grads (xentropy_objective.hpp:192-206)."""
    epf = jnp.exp(score)
    hhat = jnp.log1p(epf)
    z = 1.0 - jnp.exp(-w * hhat)
    enf = 1.0 / epf
    g = (1.0 - y / z) * w / (1.0 + enf)
    c = 1.0 / (1.0 - z)
    d = 1.0 + epf
    a = w * epf / (d * d)
    d = c - 1.0
    b = (c / (d * d)) * (1.0 + w * epf - c)
    h = a * (1.0 + y * b)
    return g, h


# --------------------------------------------------------------------------
# LambdaRank (src/objective/rank_objective.hpp:19-240)
# --------------------------------------------------------------------------

class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"
    need_query = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Lambdarank tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries,
                                           np.int64)
        self.sigmoid = self.config.sigmoid
        self.optimize_pos_at = self.config.max_position
        label_gain = self.config.label_gain
        if not label_gain:
            label_gain = [float(2 ** i - 1) for i in range(31)]
        self.label_gain = np.asarray(label_gain, np.float64)
        lab = self.label.astype(np.int32)
        if lab.max() >= len(self.label_gain):
            log.fatal("Label exceeds label_gain size")

        # pad queries to a fixed max length (TPU static shapes)
        nq = len(self.query_boundaries) - 1
        counts = np.diff(self.query_boundaries)
        qmax = int(counts.max())
        idx = np.zeros((nq, qmax), np.int32)
        valid = np.zeros((nq, qmax), bool)
        for q in range(nq):
            c = counts[q]
            idx[q, :c] = np.arange(self.query_boundaries[q],
                                   self.query_boundaries[q + 1])
            valid[q, :c] = True
        self.q_idx = idx
        self.q_valid = valid
        # inverse max DCG at k per query (rank_objective.hpp:55-68)
        self.inv_max_dcg = np.zeros(nq, np.float64)
        for q in range(nq):
            labels_q = lab[idx[q, :counts[q]]]
            top = np.sort(labels_q)[::-1][:self.optimize_pos_at]
            dcg = np.sum(self.label_gain[top]
                         / np.log2(np.arange(len(top)) + 2.0))
            self.inv_max_dcg[q] = 1.0 / dcg if dcg > 0 else 0.0

    def _bucketed_query_tables(self):
        """(q_idx, q_valid, inv_max_dcg) with the QUERY axis padded to
        its pow2 bucket under the booster's ``tpu_row_bucket`` policy
        (0 = exact), so ranking windows whose query counts land in the
        same bucket share ONE compiled step — the sliding-window
        retrain hits the registry instead of re-tracing per window.
        Pad queries are all-invalid: every pairwise term is masked by
        ``pair_ok`` and the scatter by ``flat_valid``, so they
        contribute exact +0.0 (bit-identical to the exact-shape run).
        ``qmax`` is deliberately NOT bucketed: the per-query pair sums
        reduce over that axis, and a wider axis regroups the reduction
        of the REAL values (ulp drift) even though the pad terms are
        exact zeros."""
        from ..ops.step_cache import pow2_bucket
        nq, qmax = self.q_idx.shape
        if getattr(self.config, "tpu_row_bucket", -1) == 0:
            return self.q_idx, self.q_valid, self.inv_max_dcg
        nq_p = pow2_bucket(nq, 16)
        if nq_p == nq:
            return self.q_idx, self.q_valid, self.inv_max_dcg
        idx = np.zeros((nq_p, qmax), np.int32)
        valid = np.zeros((nq_p, qmax), bool)
        imd = np.zeros(nq_p, np.float64)
        idx[:nq] = self.q_idx
        valid[:nq] = self.q_valid
        imd[:nq] = self.inv_max_dcg
        return idx, valid, imd

    def gradient_aux(self):
        idx, valid, imd = self._bucketed_query_tables()
        return {
            "y": self.label.astype(np.int32),
            "w": self.weights,
            # query tables are [nq, qmax]/[nq] — NOT row-shaped; the
            # ``_`` prefix tells the caller to place them unpadded
            "_q_idx": idx,
            "_q_valid": valid,
            "_inv_max_dcg": imd.astype(np.float32),
            "_label_gain": self.label_gain.astype(np.float32),
        }

    def gradient_builder(self):
        sigmoid = self.sigmoid
        weighted = self.weights is not None

        def fn(score, aux):
            lam, hes = _lambdarank_grads(
                score, jnp.asarray(aux["y"]),
                jnp.asarray(aux["_q_idx"]),
                jnp.asarray(aux["_q_valid"]),
                jnp.asarray(aux["_inv_max_dcg"]),
                jnp.asarray(aux["_label_gain"]), sigmoid)
            if weighted:
                w = jnp.asarray(aux["w"])
                lam, hes = lam * w, hes * w
            return lam, hes
        return fn

    def static_key(self):
        return ("lambdarank", float(self.sigmoid))

    def get_gradients(self, score):
        return self.gradient_builder()(score, self.gradient_aux())

    def to_string(self):
        return "lambdarank"


@jax.jit
def _lambdarank_grads(score, labels, q_idx, q_valid, inv_max_dcg,
                      label_gain, sigmoid):
    """Padded pairwise lambda computation, vmapped over queries
    (rank_objective.hpp:81-166)."""

    def one_query(idx, valid, imd):
        s = jnp.where(valid, score[idx], -jnp.inf)
        lab = jnp.where(valid, labels[idx], -1)
        q = idx.shape[0]
        # rank positions by score desc (stable)
        order = jnp.argsort(-s, stable=True)
        rank_of = jnp.zeros(q, jnp.int32).at[order].set(
            jnp.arange(q, dtype=jnp.int32))
        discount = 1.0 / jnp.log2(rank_of.astype(jnp.float32) + 2.0)
        valid_f = valid
        best = jnp.max(jnp.where(valid_f, s, -jnp.inf))
        worst = jnp.min(jnp.where(valid_f, s, jnp.inf))
        norm_on = best != worst

        gain = label_gain[jnp.clip(lab, 0)]
        # pair (i, j): i=high (larger label), j=low
        hi_l = lab[:, None]
        lo_l = lab[None, :]
        pair_ok = (hi_l > lo_l) & valid_f[:, None] & valid_f[None, :]
        ds = s[:, None] - s[None, :]
        dcg_gap = gain[:, None] - gain[None, :]
        paired_disc = jnp.abs(discount[:, None] - discount[None, :])
        delta = dcg_gap * paired_disc * imd
        delta = jnp.where(norm_on, delta / (0.01 + jnp.abs(ds)), delta)
        p_lambda = 2.0 / (1.0 + jnp.exp(2.0 * ds * sigmoid))
        p_hess = p_lambda * (2.0 - p_lambda)
        p_lambda = jnp.where(pair_ok, -p_lambda * delta, 0.0)
        p_hess = jnp.where(pair_ok, 2.0 * p_hess * delta, 0.0)
        lam = jnp.sum(p_lambda, axis=1) - jnp.sum(p_lambda, axis=0)
        hes = jnp.sum(p_hess, axis=1) + jnp.sum(p_hess, axis=0)
        return lam, hes

    lam_q, hes_q = jax.vmap(one_query)(q_idx, q_valid, inv_max_dcg)
    n = score.shape[0]
    flat_idx = q_idx.reshape(-1)
    flat_valid = q_valid.reshape(-1)
    lam = jnp.zeros(n, score.dtype).at[flat_idx].add(
        jnp.where(flat_valid, lam_q.reshape(-1), 0.0))
    hes = jnp.zeros(n, score.dtype).at[flat_idx].add(
        jnp.where(flat_valid, hes_q.reshape(-1), 0.0))
    return lam, hes


# --------------------------------------------------------------------------
# Factory (src/objective/objective_function.cpp:10-46)
# --------------------------------------------------------------------------

_OBJECTIVES = {
    "regression": RegressionL2Loss,
    "regression_l2": RegressionL2Loss,
    "l2": RegressionL2Loss,
    "mean_squared_error": RegressionL2Loss,
    "mse": RegressionL2Loss,
    "l2_root": RegressionL2Loss,
    "root_mean_squared_error": RegressionL2Loss,
    "rmse": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "l1": RegressionL1Loss,
    "mean_absolute_error": RegressionL1Loss,
    "mae": RegressionL1Loss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "quantile": RegressionQuantileLoss,
    "mape": RegressionMAPELoss,
    "mean_absolute_percentage_error": RegressionMAPELoss,
    "gamma": RegressionGammaLoss,
    "tweedie": RegressionTweedieLoss,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "softmax": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "multiclass_ova": MulticlassOVA,
    "ova": MulticlassOVA,
    "ovr": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "xentropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "xentlambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(name: str, config) -> Optional[ObjectiveFunction]:
    name = name.strip().lower()
    if name in ("none", "null", "custom", "na", ""):
        return None
    # l2_root/rmse use sqrt transform
    if name in ("l2_root", "root_mean_squared_error", "rmse"):
        config.reg_sqrt = True
    if name not in _OBJECTIVES:
        log.fatal(f"Unknown objective type name: {name}")
    return _OBJECTIVES[name](config)


def parse_objective_from_model_string(s: str, config):
    """Recreate an objective from its model-file string, e.g.
    'binary sigmoid:1' or 'multiclass num_class:3'
    (objective_function.cpp:49-84)."""
    parts = s.strip().split()
    if not parts:
        return None
    name = parts[0]
    for tok in parts[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            if k == "num_class":
                config.num_class = int(v)
            elif k == "sigmoid":
                config.sigmoid = float(v)
    return create_objective(name, config)


def _weighted_percentile(values, weights, alpha):
    """PercentileFun / WeightedPercentileFun
    (regression_objective.hpp:23-60)."""
    values = np.asarray(values, np.float64)
    if len(values) == 0:
        return 0.0
    if weights is None:
        sorted_v = np.sort(values)
        pos = alpha * len(values)
        k = int(np.ceil(pos)) - 1
        k = min(max(k, 0), len(values) - 1)
        if np.ceil(pos) == pos and k + 1 < len(values):
            return float((sorted_v[k] + sorted_v[k + 1]) / 2.0)
        return float(sorted_v[k])
    order = np.argsort(values)
    sv, sw = values[order], np.asarray(weights, np.float64)[order]
    cum = np.cumsum(sw) - sw * (1.0 - alpha)
    thresh = alpha * np.sum(sw)
    k = int(np.searchsorted(cum, thresh, side="left"))
    k = min(max(k, 0), len(values) - 1)
    return float(sv[k])
