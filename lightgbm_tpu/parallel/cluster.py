"""Cluster bootstrap and collective robustness for real multi-process
training.

The sharded learners (parallel/learners.py) were proven on a
single-process virtual mesh; this module is the missing runtime layer
that makes the SAME shard_map programs span real OS processes over
DCN — the TPU-native analog of the reference's socket linkers
(src/network/linkers_socket.cpp Construct/CheckLinker: TCP bootstrap,
rank/world handshake, ``time_out``-bounded waits that NAME the machine
that never answered).

Three responsibilities:

**Bootstrap** (``initialize_from_config``): wraps
``jax.distributed.initialize`` behind the ``tpu_num_machines`` /
``tpu_machine_rank`` / ``tpu_coordinator`` knobs (env twins
``LGBM_TPU_NUM_MACHINES`` / ``LGBM_TPU_MACHINE_RANK`` /
``LGBM_TPU_COORDINATOR`` for subprocess launchers). Connection is
retried through utils/retry.py — a coordinator that is still starting
(connect refused / UNAVAILABLE / barrier timeout) is a transient blip,
not a config error. On the CPU backend the gloo collective
implementation is selected so the drill harness runs the real
cross-process wire. After initialize, a KV **heartbeat** thread
publishes this rank's liveness into the coordination service every
``HEARTBEAT_S`` so peers can DIAGNOSE a dead rank by name (see below).

**Liveness and the no-hang guarantee**: every blocking sync point gets
a bounded deadline (``tpu_collective_timeout_s``). A dead peer must
produce ONE actionable line naming the rank — never an indefinite
hang:

- ``barrier(name)`` wraps the coordination-service barrier with the
  configured timeout and re-raises its DEADLINE_EXCEEDED as a
  ``PeerLostError`` naming the ranks that never arrived (parsed from
  the service's straggler list, cross-checked against heartbeats).
- ``explain_collective_error(exc)`` maps a raw in-collective failure
  (gloo "Connection reset by peer", NCCL aborts, coordination-service
  heartbeat errors) to a ``PeerLostError`` naming the unresponsive
  rank(s) found by ``probe_dead_ranks()`` — heartbeat-SEQUENCE
  progress across a short window, never wall-clock comparison, so
  cross-host clock skew cannot frame a healthy peer.
- ``DeadlineGuard`` covers backends whose collectives BLOCK instead of
  failing: a watchdog thread monitors ``tick()`` progress stamps; a
  stall past the deadline probes liveness, logs the one-line error,
  triggers a flight dump, and fail-fasts the process with
  ``EXIT_PEER_LOST`` (a hang is turned into a fast, named death an
  orchestrator can act on — the elastic resume path).

**SPMD placement seams**: under a multi-process mesh,
``jax.device_put`` cannot place host arrays onto non-addressable
devices. ``host_to_global`` builds a global array from a host-global
value via ``make_array_from_callback`` (every rank holds the same
value — the labels/masks/scores discipline models/gbdt.py keeps), and
``fetch`` gathers any global array back to a host numpy array
(replicated arrays read directly; sharded ones ride one all-gather
jit). Single-process callers fall straight through to the normal
paths, so nothing here costs anything on the virtual mesh.

Import of this module never touches jax (the harness arms env vars
before the first jax import); jax loads lazily inside the functions.
"""
from __future__ import annotations

import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils import log

ENV_COORDINATOR = "LGBM_TPU_COORDINATOR"
ENV_NUM_MACHINES = "LGBM_TPU_NUM_MACHINES"
ENV_MACHINE_RANK = "LGBM_TPU_MACHINE_RANK"

# process exit code for "peer lost, resume me elsewhere" — distinct
# from crash codes so launchers (parallel/elastic.py) can tell a
# preemption casualty from a bug
EXIT_PEER_LOST = 17

# KV namespace for rank heartbeats inside the coordination service
_HB_PREFIX = "lgbm_tpu/hb/"
HEARTBEAT_S = 0.5
# how long probe_dead_ranks waits between its two sequence snapshots:
# a live rank publishes every HEARTBEAT_S, so 2.5 intervals guarantee
# visible progress with a full cycle of slack. Progress-based (the
# seq in the key), NOT wall-stamp-based — cross-host clock skew must
# not make a healthy peer look dead.
_PROBE_WAIT_S = 2.5 * HEARTBEAT_S

# coordination-service task names look like
# /job:jax_worker/replica:0/task:3 — the task index IS the rank
_TASK_RE = re.compile(r"/job:[^/]+/replica:\d+/task:(\d+)")


class PeerLostError(RuntimeError):
    """A peer process is unresponsive/dead. ``ranks`` lists the
    suspects (empty = could not attribute — coordinator itself may be
    gone). The message is the one actionable line the no-hang
    guarantee promises."""

    def __init__(self, msg: str, ranks: List[int] = ()):  # noqa: B006
        super().__init__(msg)
        self.ranks = list(ranks)


_lock = threading.Lock()
_state: Dict = {
    "initialized": False,   # this module ran jax.distributed.initialize
    "world": 1,
    "rank": 0,
    "coordinator": "",
    "deadline_s": 60.0,
    "hb_thread": None,
    "hb_stop": None,
    "tick": None,           # (label, monotonic stamp) progress marker
}


def world() -> int:
    return _state["world"]


def rank() -> int:
    return _state["rank"]


def is_multiprocess() -> bool:
    """True when this process is one rank of a >1-process cluster."""
    return _state["world"] > 1


def deadline_s() -> float:
    return _state["deadline_s"]


def _client():
    """The coordination-service KV client, or None single-process."""
    if not is_multiprocess():
        return None
    try:
        from jax._src.distributed import global_state
        return global_state.client
    except Exception:           # pragma: no cover - jax internals moved
        return None


# KV key the autoscale controller (parallel/elastic.py) polls at LRB
# window boundaries. A pod scheduler (or the drill) posts the DESIRED
# world size here; workers see it at the next boundary and re-shard
# through the checkpoint/restore path instead of dying. Env twin for
# single-process/virtual-mesh runs where no coordination service
# exists.
_ELASTIC_PREFIX = "lgbm_tpu/elastic/"
_ELASTIC_KEY = _ELASTIC_PREFIX + "target_world"
ENV_TARGET_WORLD = "LGBM_TPU_TARGET_WORLD"


def post_scale_signal(target_world: int) -> None:
    """Publish the desired world size for elastic autoscaling. Under a
    real cluster this lands in the coordination-service KV (visible to
    every rank); single-process it sets the env twin so in-process
    virtual-mesh controllers observe the same signal."""
    client = _client()
    if client is not None:
        client.key_value_set(_ELASTIC_KEY, str(int(target_world)))
    else:
        os.environ[ENV_TARGET_WORLD] = str(int(target_world))


def poll_scale_signal() -> Optional[int]:
    """The posted target world size, or None when no signal (or an
    unparsable one) is present. Non-blocking: the KV read is a dir
    listing (the only non-blocking get the coordination client
    offers — blocking_key_value_get would stall on an absent key)."""
    client = _client()
    raw = None
    if client is not None:
        try:
            entries = client.key_value_dir_get(_ELASTIC_PREFIX)
        except Exception:
            entries = []
        for key, value in entries:
            if key == _ELASTIC_KEY or key.endswith("target_world"):
                raw = value
    if raw is None:
        raw = os.environ.get(ENV_TARGET_WORLD)
    try:
        target = int(str(raw))
    except (TypeError, ValueError):
        return None
    return target if target >= 1 else None


def clear_scale_signal() -> None:
    """Retire a consumed signal so the controller does not re-shard
    again at the next boundary."""
    client = _client()
    if client is not None:
        try:
            client.key_value_delete(_ELASTIC_KEY)
        except Exception:
            pass
    os.environ.pop(ENV_TARGET_WORLD, None)


def _resolve_topology(config) -> tuple:
    """(world, rank, coordinator) from config knobs with env twins
    (a set-and-non-empty env wins — the launcher sets per-process
    ranks that one shared config string cannot express; an EMPTY env
    value falls back to the knob instead of crashing int(''))."""
    world_n = int(os.environ.get(ENV_NUM_MACHINES)
                  or getattr(config, "tpu_num_machines", 0) or 0)
    rank_n = int(os.environ.get(ENV_MACHINE_RANK)
                 or getattr(config, "tpu_machine_rank", -1))
    coord = (os.environ.get(ENV_COORDINATOR)
             or str(getattr(config, "tpu_coordinator", "") or ""))
    return world_n, rank_n, coord


def initialize_from_config(config) -> bool:
    """Bootstrap the jax.distributed runtime when the config/env asks
    for >1 processes. Returns True when this process is (now) part of
    a multi-process cluster. Idempotent: a second call with the same
    topology is a no-op; calls after jax is already distributed adopt
    the live topology.

    MUST run before any other jax use in the process (the backend
    client binds at first device access — the same constraint
    ``dryrun_multichip`` documents for platform selection).
    """
    world_n, rank_n, coord = _resolve_topology(config)
    _state["deadline_s"] = float(
        getattr(config, "tpu_collective_timeout_s", 60.0) or 60.0)
    import jax
    # prior-initialization probe via the distributed global state —
    # NOT jax.process_count(), which would initialize the backend and
    # freeze an uninitialized process out of its cluster
    try:
        from jax._src.distributed import global_state
        already = getattr(global_state, "client", None) is not None
    except Exception:           # pragma: no cover - jax internals moved
        already = False
    if _state["initialized"] or already:
        # already distributed (this module or an embedding application)
        _adopt_live_topology()
        return is_multiprocess()
    if world_n <= 1:
        return False
    try:
        from jax._src import xla_bridge
        backends_up = bool(getattr(xla_bridge, "_backends", None))
    except Exception:           # pragma: no cover - jax internals moved
        backends_up = False
    if backends_up:
        log.fatal(f"tpu_num_machines={world_n} but the jax backend is "
                  f"already initialized — cluster bootstrap must be "
                  f"the process's FIRST jax use (run training through "
                  f"the elastic worker, parallel/elastic.py, or call "
                  f"cluster.initialize_from_config before touching "
                  f"data)")
    if rank_n < 0 or rank_n >= world_n:
        log.fatal(f"tpu_num_machines={world_n} needs tpu_machine_rank "
                  f"in [0, {world_n}) on every process (got {rank_n}); "
                  f"set it per-process or export {ENV_MACHINE_RANK}")
    if not coord:
        log.fatal(f"tpu_num_machines={world_n} needs a coordinator "
                  f"address: set tpu_coordinator=host:port (or export "
                  f"{ENV_COORDINATOR}) — rank 0's address, like the "
                  f"reference's machine_list first entry")
    # The CPU backend's cross-process collectives ride gloo; the knob
    # must be set before backend init — and NOTHING here may touch
    # devices (even utils/device.on_tpu would initialize the backend
    # and freeze the process out of the cluster). Setting it is
    # harmless on accelerator platforms: it only shapes the CPU
    # client.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        log.warning("jax has no jax_cpu_collectives_implementation "
                    "option; CPU cross-process collectives may be "
                    "unavailable")

    from ..utils import retry

    def _connect():
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=world_n,
            process_id=rank_n,
            initialization_timeout=max(int(_state["deadline_s"]), 10))

    # a coordinator that is still binding its port surfaces as connect
    # refused / UNAVAILABLE / barrier timeout — the retry classifier
    # knows these DCN strings (utils/retry.py TRANSIENT_MARKERS)
    retry.call(_connect, what=f"jax.distributed.initialize({coord})",
               policy=retry.RetryPolicy(
                   attempts=max(int(getattr(config, "tpu_retry_attempts",
                                            4) or 4), 1),
                   base_s=0.5, max_s=5.0))
    with _lock:
        _state.update(initialized=True, world=world_n, rank=rank_n,
                      coordinator=coord)
    _set_identity(rank_n, world_n)
    from ..obs import clusterobs
    clusterobs.configure_from_config(config)
    _start_heartbeat()
    log.info("cluster up: rank %d/%d, coordinator %s, %d global / %d "
             "local device(s)", rank_n, world_n, coord,
             jax.device_count(), jax.local_device_count())
    return True


def _set_identity(rank_n: int, world_n: int) -> None:
    """Propagate the resolved topology into the process identity
    record (obs/identity.py — every metrics snapshot / trace event /
    flight bundle stamps it) and the log prefix rank tag."""
    from ..obs import identity
    identity.set_topology(rank_n, world_n)
    log.set_rank_tag(identity.log_tag())


def _adopt_live_topology() -> None:
    """Record a jax.distributed runtime someone else initialized."""
    import jax
    if jax.process_count() > 1 and _state["world"] == 1:
        with _lock:
            if _state["world"] == 1:
                _state.update(world=jax.process_count(),
                              rank=jax.process_index())
        _set_identity(_state["rank"], _state["world"])
        _start_heartbeat()


# -- heartbeats and liveness -------------------------------------------------


def _start_heartbeat() -> None:
    """Publish this rank's liveness into the coordination-service KV
    store every HEARTBEAT_S: ``lgbm_tpu/hb/<rank>/<seq> = monotonic-ish
    wall stamp``, deleting the previous seq so the directory stays one
    entry per rank. Peers read the directory to name dead ranks."""
    if _state["hb_thread"] is not None or not is_multiprocess():
        return                          # fast path; re-checked under _lock
    client = _client()
    if client is None:
        return
    stop = threading.Event()

    def beat():
        from ..obs import clusterobs
        seq = 0
        while not stop.is_set():
            try:
                client.key_value_set(
                    f"{_HB_PREFIX}{rank()}/{seq}", repr(time.time()))
                if seq:
                    client.key_value_delete(
                        f"{_HB_PREFIX}{rank()}/{seq - 1}")
            except Exception:
                # coordinator gone: nothing to publish to — the main
                # thread's own collectives will surface the failure
                return
            # metrics digest rides the same clock at a slower multiple
            # (obs/clusterobs.py): ~kilobytes every DIGEST_EVERY_BEATS
            # beats against the heartbeat's bytes every beat. A digest
            # failure is NOT liveness-fatal: keep beating.
            if (seq % clusterobs.DIGEST_EVERY_BEATS == 0
                    and clusterobs.enabled()):
                try:
                    clusterobs.publish_digest(client, rank())
                except Exception:       # noqa: BLE001 — telemetry
                    pass                # must never kill the heartbeat
            seq += 1
            stop.wait(HEARTBEAT_S)

    t = threading.Thread(target=beat, name="lgbm-cluster-heartbeat",
                         daemon=True)
    with _lock:
        # check-then-act under the lock: two boosters initializing
        # concurrently (the retrain-while-serve pattern) must not
        # start TWO heartbeat threads racing on the same KV keys
        if _state["hb_thread"] is not None:
            return
        _state.update(hb_thread=t, hb_stop=stop)
    t.start()


def _hb_snapshot(client) -> Optional[Dict[int, int]]:
    """rank -> newest heartbeat SEQUENCE from the KV directory (the
    seq lives in the key, so no cross-host clock enters); None when
    the directory read itself failed."""
    try:
        entries = client.key_value_dir_get(_HB_PREFIX)
    except Exception:
        return None
    newest: Dict[int, int] = {}
    for key, _value in entries:
        m = re.search(r"hb/(\d+)/(\d+)", key)
        if not m:
            continue
        r = int(m.group(1))
        newest[r] = max(newest.get(r, -1), int(m.group(2)))
    return newest


def probe_dead_ranks(wait_s: Optional[float] = None) -> Optional[List[int]]:
    """Ranks (this one excluded) whose heartbeat sequence makes NO
    progress across a ``wait_s`` window (default ``_PROBE_WAIT_S``,
    2.5 publish intervals) — or that never published at all. Progress
    comparison is skew-immune: a healthy peer on a badly-NTP'd host
    still advances its sequence. None = the probe itself failed
    (coordinator unreachable — rank 0's process is the prime
    suspect)."""
    client = _client()
    if client is None:
        return []
    first = _hb_snapshot(client)
    if first is None:
        return None
    time.sleep(float(wait_s) if wait_s is not None else _PROBE_WAIT_S)
    second = _hb_snapshot(client)
    if second is None:
        return None
    return [r for r in range(world())
            if r != rank() and second.get(r, -1) <= first.get(r, -1)]


def _rank_list(ranks: List[int]) -> str:
    return ", ".join(f"rank {r}" for r in ranks) or "an unknown rank"


def explain_collective_error(exc: BaseException,
                             what: str = "collective") -> Optional[PeerLostError]:
    """Map a raw in-collective failure to a PeerLostError naming the
    dead rank(s), or None when ``exc`` does not look like a peer/DCN
    failure (a genuine bug must keep its own traceback)."""
    msg = str(exc)
    # barrier timeouts list BOTH "the first task at the barrier" (an
    # alive one) and the stragglers — only the section after "timed
    # out task names" may accuse anyone; other coordination errors
    # name the dead task inline, so the whole message is fair game
    scope = msg
    marker = "timed out task names"
    if marker in msg:
        scope = msg[msg.index(marker):]
    named = [int(r) for r in _TASK_RE.findall(scope)]
    peerish = named or any(s in msg for s in (
        "Connection reset", "Connection refused", "Socket closed",
        "Gloo", "gloo", "NCCL", "heartbeat timeout", "Heartbeat",
        "UNAVAILABLE", "DEADLINE_EXCEEDED", "coordination service",
        "Coordination service", "Barrier timed out"))
    if not peerish:
        return None
    suspects = sorted(set(named))
    if not suspects and _client() is not None:
        # attribute by heartbeat progress: the probe's two-snapshot
        # window (~2.5 publish intervals) is deterministic — a dead
        # peer's sequence cannot advance, however fast the socket
        # error beat its last heartbeat; a LIVE peer behind a
        # transient network blip keeps advancing and is never accused
        probed = probe_dead_ranks()
        if probed is None:
            return PeerLostError(
                f"{what} failed and the coordinator is unreachable — "
                f"rank 0 (coordinator {_state['coordinator'] or '?'}) "
                f"is likely dead; restart the cluster and resume from "
                f"the latest checkpoint (tpu_resume_from)", [0])
        suspects = probed
    return PeerLostError(
        f"{what} failed: {_rank_list(suspects)} of {world()} "
        f"unresponsive (peer died or was preempted); surviving ranks "
        f"should exit and resume from the latest checkpoint onto the "
        f"remaining hosts (tpu_resume_from; original error: "
        f"{msg.splitlines()[0][:200]})", suspects)


def barrier(name: str, timeout_s: Optional[float] = None) -> None:
    """Cross-process sync with a bounded deadline; a peer that never
    arrives raises PeerLostError naming it (the coordination service's
    straggler list) instead of blocking forever. No-op
    single-process."""
    client = _client()
    if client is None:
        return
    t = float(timeout_s if timeout_s is not None else deadline_s())
    try:
        client.wait_at_barrier(name, int(t * 1000))
    except Exception as e:  # noqa: BLE001 — classified below
        named = explain_collective_error(e, what=f"barrier {name!r}")
        if named is not None:
            raise named from e
        raise


# -- the stall watchdog (no-hang guarantee for blocking backends) ------------


def tick(label: str = "") -> None:
    """Progress stamp for DeadlineGuard — the training loop calls this
    at every iteration choke point (models/gbdt.py train_one_iter)."""
    _state["tick"] = (label, time.monotonic())


class DeadlineGuard:
    """Watchdog turning a silent collective hang into a fast, named
    death: while active, a daemon thread checks the time since the
    last ``tick``; a stall past ``deadline_s`` probes liveness — a
    DEAD peer (or unreachable coordinator) logs ONE actionable line
    naming the rank(s), dumps the flight recorder, and exits the
    process with EXIT_PEER_LOST; a stall with every peer's heartbeat
    still advancing only WARNS and keeps waiting (a slow compile must
    never read as a cluster death).

    ``on_stall`` (tests) replaces the exit with a callback; ``probe``
    (tests) replaces the KV liveness probe. The guard never fires
    single-process unless a probe override is injected."""

    def __init__(self, deadline: Optional[float] = None,
                 what: str = "training collective",
                 on_stall: Optional[Callable] = None,
                 probe: Optional[Callable] = None,
                 poll_s: float = 0.25):
        self.deadline = float(deadline if deadline is not None
                              else deadline_s())
        self.what = what
        self.on_stall = on_stall
        self.probe = probe
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    def __enter__(self):
        if not is_multiprocess() and self.probe is None:
            return self
        tick("guard-start")
        self._thread = threading.Thread(
            target=self._watch, name="lgbm-deadline-guard", daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        return False

    def _watch(self):
        while not self._stop.wait(self.poll_s):
            last = _state.get("tick")
            if last is None:
                continue
            stalled = time.monotonic() - last[1]
            if stalled < self.deadline:
                continue
            probe = self.probe or probe_dead_ranks
            dead = probe()
            if dead == []:
                # EVERY peer's heartbeat is still advancing: nobody is
                # dead, this is a slow step (first-compile, a long
                # eval, a busy host) — killing a healthy cluster would
                # be the false positive this guard must never produce.
                # Say so, push the baseline forward, keep watching.
                log.warning(
                    "%s stalled for %.1fs at %s but every peer is "
                    "alive (heartbeats advancing) — waiting on (slow "
                    "compile/step?)", self.what, stalled,
                    last[0] or "start")
                tick(last[0])
                continue
            self.fired = True
            if dead is None:
                who = (f"the coordinator "
                       f"({_state['coordinator'] or 'rank 0'})")
                ranks = [0]
            else:
                who = _rank_list(dead)
                ranks = dead
            err = PeerLostError(
                f"{self.what} stalled for {stalled:.1f}s (deadline "
                f"{self.deadline:.1f}s) at {last[0] or 'start'}: {who} "
                f"unresponsive — exiting so the orchestrator can "
                f"resume from the latest checkpoint (tpu_resume_from)",
                ranks)
            log.warning("%s", err)
            if self.on_stall is not None:
                self.on_stall(err)
                return
            try:
                from ..obs import flight
                flight.trigger("peer_lost", {"what": self.what,
                                             "ranks": ranks,
                                             "stalled_s": round(stalled,
                                                                2)},
                               force=True)
            except Exception:
                pass
            os._exit(EXIT_PEER_LOST)


# -- SPMD placement/gather seams ---------------------------------------------


def spans_processes(mesh) -> bool:
    """True when ``mesh`` contains devices of more than one process —
    the signal that device_put placement must give way to the global
    constructors below."""
    if mesh is None or not is_multiprocess():
        return False
    procs = {getattr(d, "process_index", 0)
             for d in mesh.devices.flat}
    return len(procs) > 1


def host_to_global(x, mesh, *spec):
    """Host-global array -> global device array under
    NamedSharding(mesh, P(*spec)). EVERY process must pass the same
    value (the SPMD host-data discipline); each builds only its
    addressable shards."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    x = np.asarray(x)
    sh = NamedSharding(mesh, P(*spec))
    return jax.make_array_from_callback(x.shape, sh,
                                        lambda idx: x[idx])


def local_shards_to_global(shards, global_shape, mesh, *spec):
    """Per-local-device shards -> one global array (the multihost
    ingest assembly; wraps make_array_from_single_device_arrays)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P(*spec))
    return jax.make_array_from_single_device_arrays(
        tuple(global_shape), sh, list(shards))


# per-(mesh, ndim) jitted identity-with-replication programs: jax's
# jit cache keys on function identity, so a fresh lambda per fetch()
# would retrace + recompile the all-gather on EVERY checkpoint
_gather_jits: Dict = {}


def fetch(arr):
    """Global device array -> host numpy on EVERY rank. Replicated
    arrays read directly; sharded ones pay one all-gather jit (the
    checkpoint gather — utils/checkpoint.py save under a multi-process
    mesh; compiled once per (mesh, rank-count) and reused). Single-
    process/plain arrays fall through to np.asarray."""
    import numpy as np
    if not hasattr(arr, "is_fully_addressable"):
        return np.asarray(arr)
    if arr.is_fully_addressable or getattr(arr, "is_fully_replicated",
                                           False):
        return np.asarray(arr)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = arr.sharding.mesh
    key = (mesh, arr.ndim)
    fn = _gather_jits.get(key)
    if fn is None:
        rep = NamedSharding(mesh, P(*([None] * arr.ndim)))
        fn = jax.jit(lambda x: x, out_shardings=rep)
        _gather_jits[key] = fn
    return np.asarray(fn(arr))


def shutdown() -> None:
    """Orderly teardown (successful runs only: the shutdown barrier
    aborts the process if a peer already died — casualties exit via
    os._exit on the EXIT_PEER_LOST path instead)."""
    stop = _state.get("hb_stop")
    if stop is not None:
        stop.set()
    if _state["initialized"]:
        import jax
        try:
            jax.distributed.shutdown()
        except Exception as e:
            log.warning("jax.distributed.shutdown: %s", e)
        _state.update(initialized=False, world=1, rank=0,
                      hb_thread=None, hb_stop=None)
