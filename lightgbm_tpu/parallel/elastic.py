"""Elastic multi-host training: the worker entry, the localhost
launcher, and the preemption drill.

Three layers, bottom-up:

**Worker** (``python -m lightgbm_tpu.parallel.elastic --spec s.json``):
one rank of a real ``jax.distributed`` cluster. Reads a drill spec
(synthetic workload + training params), bootstraps the cluster
(parallel/cluster.py — topology from the ``LGBM_TPU_NUM_MACHINES`` /
``LGBM_TPU_MACHINE_RANK`` / ``LGBM_TPU_COORDINATOR`` env the launcher
exports), builds its per-host shard of the dataset through the
multihost ingest (io/distributed.py construct_multihost), trains the
full GBDT engine under the no-hang DeadlineGuard, and writes a
per-rank result JSON (+ rank 0: the final model text). A peer death —
mid-collective failure or silent stall — exits with
``EXIT_PEER_LOST`` after ONE actionable line naming the dead rank;
the orchestrator (here: the drill) restarts survivors on a smaller
mesh with ``resume_from`` pointed at the checkpoint directory. A
resume spec reconstructs the ORIGINAL run's binning by injecting the
checkpoint bundle's serialized mappers
(utils/checkpoint.mappers_from_bundle) — restored tree thresholds
cannot shift, whatever the new world size.

**Launcher** (``launch_workers``): spawns W real OS processes over a
fresh localhost port with per-rank env (platform pinned to CPU, one
virtual device per process, fault spec armed on the designated victim
only) — the CI-sized stand-in for a pod scheduler.

**Drill** (``run_drill``): the elastic-resume proof. Phase A trains
uninterrupted on a 2-process mesh. Phase B reruns the identical
workload with a seed-keyed SIGKILL (utils/faults.py
``train.iter@K:kill``) on rank 1 and asserts the survivor exits
promptly with the rank-naming error. Phase C resumes from phase B's
latest checkpoint on a ONE-process mesh and trains to completion.
The verdict: phase C's final model must equal phase A's —
bit-identical under the quantized int32 histogram wire, whose
shard-invariant stochastic rounding and integer collectives make the
mesh size drop out of the math (PR 4; tests/test_multichip.py proved
it across virtual mesh sizes, this drill proves it across REAL
process boundaries plus a kill plus a world-size change). The result
dict is the MULTICHIP artifact shape tools/check_bench_regression.py
gates (``model_parity=false`` fails the artifact).

Workload data is synthesized deterministically from the spec seed on
every rank (CI-scale convenience); each rank still ONLY ingests its
own host block — production per-host files ride the same
construct_multihost path.
"""
from __future__ import annotations

import hashlib
import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..utils import log
from . import cluster

# default drill workload: big enough that every world size buckets to
# the same score width (4096 is a pow2 bucket for worlds 1 and 2 —
# see ops/step_cache.py shard_align_unit), small enough for CI
DRILL_N = 4096
DRILL_F = 8

DRILL_PARAMS: Dict = {
    "objective": "binary",
    "metric": "auc",
    "num_leaves": 15,
    "max_bin": 63,
    "min_data_in_leaf": 5,
    "learning_rate": 0.1,
    "tree_learner": "data",
    # the quantized tier's int32 wire + shard-invariant stochastic
    # rounding are what make the final model independent of the mesh
    # size — the property the whole drill rests on
    "tpu_quantized_hist": True,
    # exercise the real double-buffered device ingest off-TPU
    "tpu_ingest": 1,
    # drain the dispatch queue every iteration so a peer death
    # surfaces at the iteration that hit it (and the fault occurrence
    # count == the iteration number)
    "tpu_dispatch_sync_interval": 1,
    "tpu_stop_check_interval": 4,
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _synth_data(spec: Dict):
    import numpy as np
    r = np.random.default_rng(int(spec.get("seed", 0)))
    n = int(spec.get("n", DRILL_N))
    f = int(spec.get("f", DRILL_F))
    X = r.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


def _write_json(path: str, payload: Dict) -> None:
    from ..utils.fileio import atomic_write
    with atomic_write(path) as fh:
        json.dump(payload, fh, indent=1)


# -- the worker ---------------------------------------------------------------


def run_worker(spec: Dict) -> Dict:
    """One rank's whole life: bootstrap -> per-host ingest -> train
    (checkpointing per the spec's params) -> result JSON. Returns the
    result dict (also written to ``spec['out'] + '.rank<r>'``)."""
    from ..config import Config

    params = dict(DRILL_PARAMS)
    params.update(spec.get("params", {}))
    if spec.get("checkpoint_dir"):
        params.setdefault("tpu_checkpoint_dir", spec["checkpoint_dir"])
        params.setdefault("tpu_checkpoint_freq", 1)
    out_dir = os.path.dirname(str(spec.get("out", "") or ""))
    if out_dir:
        # every rank's flight recorder dumps into the SHARED workdir
        # so the survivor's incident sweep (obs/incident.py) reaches
        # the victim's pre-kill bundle too
        params.setdefault("tpu_flight_dir", out_dir)
    cfg = Config().set(params)
    multi = cluster.initialize_from_config(cfg)
    t0 = time.monotonic()

    import numpy as np

    from ..io.dataset import Metadata, TpuDataset
    from ..metrics import create_metrics
    from ..models.gbdt import GBDT
    from ..objectives import create_objective
    from ..obs import registry as obs

    X, y = _synth_data(spec)
    n = X.shape[0]

    resume_from = str(spec.get("resume_from", "") or "")
    inject = None
    if resume_from:
        from ..utils import checkpoint as ckpt
        bundle = ckpt.resolve_resume(resume_from)
        inject = ckpt.mappers_from_bundle(bundle)
        if inject is not None:
            log.info("elastic resume: constructing dataset with the "
                     "checkpoint's %d bin mappers",
                     sum(1 for m in inject if not m.is_trivial))
    if inject is None and spec.get("shared_binning"):
        # the scaling bench compares MODELS across world sizes; the
        # multihost bin finder samples per-host blocks, so its bin
        # boundaries legitimately depend on the world. Pin them: every
        # rank computes mappers from the full synthetic matrix it
        # already holds — deterministic, world-independent, exactly
        # what sharing a binning artifact does in production
        from ..io.dataset import find_column_mappers
        inject = find_column_mappers(X, cfg)
        log.info("shared binning: %d mappers from the full matrix",
                 sum(1 for m in inject if m is not None))

    if multi:
        from ..io.distributed import (DistributedLoader,
                                      allgather_row_slices)
        from ..io.ingest import host_row_block
        from .learners import training_mesh
        mesh = training_mesh(cfg)
        if mesh is None:
            log.fatal("multi-process bootstrap succeeded but no >1 "
                      "device mesh is available — tree_learner must "
                      "be data/voting for multihost training")
        lo, hi, _ = host_row_block(n, mesh,
                                   int(cfg.tpu_hist_chunk or 0))
        # metadata rides the real per-host wire: each rank contributes
        # only its block's labels and the global vector assembles over
        # the allgather (exactly what per-host label files would do —
        # here it must reproduce the synthesized y bit-for-bit)
        y_global = allgather_row_slices(
            np.asarray(y[lo:hi], np.float64), lo, n)
        np.testing.assert_array_equal(
            np.asarray(y_global, np.float32), y)
        ds = DistributedLoader(cfg).construct_multihost(
            X[lo:hi], Metadata(label=y_global), n_global=n,
            row_start=lo, mesh=mesh, mappers=inject)
        block = (lo, hi)
    else:
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=y), mappers=inject)
        block = (0, n)

    obj = create_objective(cfg.objective, cfg)
    obj.init(ds.metadata, ds.num_data)
    mets = create_metrics(["auc"], cfg, ds.metadata, ds.num_data)
    g = GBDT()
    g.init(cfg, ds, obj, mets)

    out_base = str(spec.get("out", "") or "")
    my_out = (f"{out_base}.rank{cluster.rank()}" if out_base else "")

    def survivor_exit(err: cluster.PeerLostError):
        # the one-line actionable error + machine-readable survivor
        # report, then a prompt controlled exit (jax's own shutdown
        # barrier would abort the process — see cluster.shutdown)
        log.warning("%s", err)
        # this rank's own black box first (the survivor's state AT the
        # loss), then the cross-rank incident: sweep every reachable
        # flight bundle — the victim's pre-kill dump landed in the
        # shared tpu_flight_dir before its SIGKILL — plus the final KV
        # digest snapshot into ONE document (obs/incident.py)
        incident_path = None
        try:
            from ..obs import flight as obs_flight
            from ..obs import incident as obs_incident
            obs_flight.trigger(
                "peer_lost",
                {"dead_ranks": list(err.ranks),
                 "error": str(err)[:400],
                 "iteration": int(g.current_iteration)}, force=True)
            sweep_dir = str(cfg.tpu_flight_dir or "") or (
                os.path.dirname(my_out) if my_out else "")
            if sweep_dir:
                incident_path = obs_incident.write_incident(
                    "peer_lost", sweep_dir, dead_ranks=err.ranks,
                    context={"error": str(err)[:400],
                             "iteration": int(g.current_iteration)})
        except Exception:       # noqa: BLE001 — the postmortem must
            pass                # never block the controlled exit
        if my_out:
            _write_json(my_out, {
                "rank": cluster.rank(), "world": cluster.world(),
                "peer_lost": True, "dead_ranks": err.ranks,
                "error": str(err),
                "iterations": int(g.current_iteration),
                "incident": incident_path,
                "wall_s": round(time.monotonic() - t0, 3)})
        os._exit(cluster.EXIT_PEER_LOST)

    try:
        with cluster.DeadlineGuard(what="multihost training step",
                                   on_stall=survivor_exit):
            g.train(resume_from=resume_from)
    except BaseException as e:  # noqa: BLE001 — classified below
        named = cluster.explain_collective_error(e, what="training")
        if named is not None:
            survivor_exit(named)
        raise

    g._ensure_host_trees()
    text = g.model_to_string()
    auc = None
    try:
        auc = float(dict((nm, v) for nm, v, _ in
                         g.get_eval_at(0)).get("auc"))
    except Exception:
        pass
    # DCN accounting for the scaling artifact: per-iteration psum
    # payload bytes + the measured stall estimate (both None off the
    # data-parallel path — e.g. the world-1 scaling point)
    comm_per_iter = psum_stall = None
    try:
        _, waves = g.leaves_and_waves(0)
        comm = g._comm_bytes_per_iteration(waves)
        if comm:
            comm_per_iter = int(round(sum(comm) / len(comm)))
            passes = (sum(waves)
                      + g.num_tree_per_iteration * len(waves))
            psum_stall = g.psum_stall_estimate_s(passes)
    except Exception as e:      # accounting never takes training down
        log.debug("comm accounting skipped: %s", e)
    result = {
        "rank": cluster.rank(),
        "world": cluster.world(),
        "peer_lost": False,
        "iterations": int(g.current_iteration),
        "model_sha": hashlib.sha256(text.encode()).hexdigest(),
        "train_auc": auc,
        "host_row_block": list(block),
        "ingest_rows_local": int(
            obs.counter("ingest/rows_device").value
            or obs.counter("ingest/rows_host").value),
        "wall_s": round(time.monotonic() - t0, 3),
        "wire": g.wire_encoding(),
        "psum_slots": int(getattr(getattr(g, "_grower_cfg", None),
                                  "psum_slots", 1) or 1),
        "comm_bytes_per_iter": comm_per_iter,
        "psum_stall_s": psum_stall,
        "ckpt_hidden_s": (float(obs.counter("ckpt/hidden_s").value)
                          or None),
    }
    if cluster.rank() == 0:
        if spec.get("model_out"):
            from ..utils.fileio import atomic_write
            with atomic_write(spec["model_out"]) as fh:
                fh.write(text)
        if out_base:
            _write_json(out_base, result)
    if my_out:
        _write_json(my_out, result)
    if multi:
        # deterministic end-of-run rollup: push THIS rank's final
        # digest now (the heartbeat ride-along may not have fired
        # since the last iteration), and after the barrier below
        # proves every rank published, rank 0 merges and writes the
        # cluster/* rollups into its export files — the summed
        # cluster counters equal the per-rank digests by construction
        from ..obs import clusterobs
        clusterobs.publish_now()
    # every rank's files are on disk before anyone tears down
    cluster.barrier("elastic-train-done")
    if multi and cluster.rank() == 0:
        from ..obs import clusterobs
        from ..obs import export as obs_export
        try:
            clusterobs.refresh_from_kv()
            exp = obs_export.global_exporter()
            if exp is not None:
                exp._write_once()
        except Exception as e:          # noqa: BLE001 — telemetry
            log.debug("final cluster rollup skipped: %s", e)
    cluster.shutdown()
    return result


def worker_main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="elastic multihost worker (one rank)")
    ap.add_argument("--spec", required=True,
                    help="drill spec JSON path")
    args = ap.parse_args(argv)
    with open(args.spec) as fh:
        spec = json.load(fh)
    try:
        run_worker(spec)
    except BaseException as e:  # noqa: BLE001 — classified below
        # the training loop's own survivor path handles in-train peer
        # deaths; this net catches a peer dying during ANY other
        # collective (mapper-agreement allgather, multihost ingest
        # assembly, checkpoint gather) — same one-line rank-naming
        # error, same controlled exit
        named = cluster.explain_collective_error(e, what="collective")
        if named is not None:
            log.warning("%s", named)
            out = str(spec.get("out", "") or "")
            if out:
                _write_json(f"{out}.rank{cluster.rank()}", {
                    "rank": cluster.rank(), "world": cluster.world(),
                    "peer_lost": True, "dead_ranks": named.ranks,
                    "error": str(named), "iterations": 0})
            os._exit(cluster.EXIT_PEER_LOST)
        raise
    return 0


# -- the launcher -------------------------------------------------------------


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def launch_workers(spec_path: str, world: int, *,
                   port: Optional[int] = None,
                   local_devices: int = 1,
                   fault_rank: Optional[int] = None,
                   faults: str = "",
                   log_dir: str = "") -> List[subprocess.Popen]:
    """Spawn ``world`` real worker processes over a fresh localhost
    coordinator port. Every child gets a CLEAN platform env (CPU
    backend, ``local_devices`` virtual devices — NOT the parent's
    8-device test flag) and the fault spec is armed ONLY on
    ``fault_rank`` (the drill's designated victim)."""
    port = port or _free_port()
    procs = []
    for r in range(world):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["LGBM_TPU_PLATFORM"] = "cpu"
        env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                            f"{local_devices}")
        env[cluster.ENV_COORDINATOR] = f"localhost:{port}"
        env[cluster.ENV_NUM_MACHINES] = str(world)
        env[cluster.ENV_MACHINE_RANK] = str(r)
        env["PYTHONPATH"] = _repo_root() + os.pathsep + \
            env.get("PYTHONPATH", "")
        # a fault plan inherited from the parent (pytest arming its
        # own drills) must not leak into every worker
        env.pop("LGBM_TPU_FAULTS", None)
        if faults and r == fault_rank:
            env["LGBM_TPU_FAULTS"] = faults
        stdout = None
        if log_dir:
            stdout = open(os.path.join(log_dir, f"worker{r}.log"),
                          "w")
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "lightgbm_tpu.parallel.elastic",
                 "--spec", spec_path],
                cwd=_repo_root(), env=env, stdout=stdout,
                stderr=subprocess.STDOUT if stdout else None))
        finally:
            if stdout is not None:
                # the child owns its inherited descriptor; holding the
                # parent's open handle would leak one fd per worker
                # per drill phase
                stdout.close()
    return procs


def wait_workers(procs: List[subprocess.Popen],
                 timeout_s: float = 600.0) -> List[int]:
    """Join every worker; returns return codes (negative = signal).
    A worker that outlives the timeout is killed and reported as
    -9."""
    deadline = time.monotonic() + timeout_s
    codes = []
    for p in procs:
        left = max(deadline - time.monotonic(), 1.0)
        try:
            codes.append(p.wait(timeout=left))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            codes.append(-9)
    return codes


def run_two_process(workdir: str, *, n: int = 1024, iterations: int = 4,
                    seed: int = 0, extra_params: Optional[Dict] = None,
                    timeout_s: float = 420.0) -> Dict:
    """The tier-1 smoke: train a small workload across 2 REAL
    processes, assert both ranks finish and agree on the model hash.
    Returns {result, rank_results}."""
    os.makedirs(workdir, exist_ok=True)
    spec = {
        "seed": seed, "n": n, "f": DRILL_F,
        "params": {**(extra_params or {}),
                   "num_iterations": iterations},
        "out": os.path.join(workdir, "result.json"),
        "model_out": os.path.join(workdir, "model.txt"),
    }
    spec_path = os.path.join(workdir, "spec.json")
    _write_json(spec_path, spec)
    procs = launch_workers(spec_path, 2, log_dir=workdir)
    codes = wait_workers(procs, timeout_s)
    if any(codes):
        tails = _worker_tails(workdir, 2)
        raise RuntimeError(f"two-process smoke failed: rc={codes}\n"
                           f"{tails}")
    ranks = [_read_json(spec["out"] + f".rank{r}") for r in range(2)]
    if ranks[0]["model_sha"] != ranks[1]["model_sha"]:
        raise RuntimeError(f"ranks disagree on the trained model: "
                           f"{ranks[0]['model_sha']} vs "
                           f"{ranks[1]['model_sha']}")
    return {"result": _read_json(spec["out"]), "rank_results": ranks}


def _read_json(path: str) -> Dict:
    with open(path) as fh:
        return json.load(fh)


def _worker_tails(workdir: str, world: int, nbytes: int = 2000) -> str:
    outs = []
    for r in range(world):
        p = os.path.join(workdir, f"worker{r}.log")
        try:
            with open(p) as fh:
                data = fh.read()
            outs.append(f"--- worker{r} tail ---\n{data[-nbytes:]}")
        except OSError:
            outs.append(f"--- worker{r}: no log ---")
    return "\n".join(outs)


def run_drill(workdir: str, *, n: int = DRILL_N, iterations: int = 10,
              kill_at: int = 6, seed: int = 0,
              collective_timeout_s: float = 30.0,
              timeout_s: float = 900.0) -> Dict:
    """The full elastic-resume drill (see module docstring). Returns
    the MULTICHIP artifact dict; raises on any phase failure EXCEPT
    parity, which is reported in the dict (``model_parity``) so the
    artifact gate — not an exception — is the arbiter."""
    os.makedirs(workdir, exist_ok=True)
    base = {
        "seed": seed, "n": n, "f": DRILL_F,
        "params": {"num_iterations": iterations,
                   "tpu_collective_timeout_s": collective_timeout_s},
    }

    # phase A: uninterrupted 2-process run
    dir_a = os.path.join(workdir, "a_uninterrupted")
    os.makedirs(dir_a, exist_ok=True)
    spec_a = dict(base)
    # phase A also exercises the cluster-scope rollup path: rank 0's
    # exporter merges both ranks' KV digests into cluster/* series and
    # the artifact carries the final rollup (obs/clusterobs.py)
    spec_a["params"] = {**base["params"],
                        "tpu_metrics_export":
                            os.path.join(dir_a, "metrics")}
    spec_a.update(out=os.path.join(dir_a, "result.json"),
                  model_out=os.path.join(dir_a, "model.txt"),
                  checkpoint_dir=os.path.join(dir_a, "ckpt"))
    p_a = os.path.join(dir_a, "spec.json")
    _write_json(p_a, spec_a)
    t_a = time.monotonic()
    codes = wait_workers(launch_workers(p_a, 2, log_dir=dir_a),
                         timeout_s / 2)
    if any(codes):
        raise RuntimeError(f"drill phase A (uninterrupted) failed: "
                           f"rc={codes}\n{_worker_tails(dir_a, 2)}")
    res_a = _read_json(spec_a["out"])
    ranks_a = [_read_json(spec_a["out"] + f".rank{r}")
               for r in range(2)]
    wall_a = time.monotonic() - t_a

    # phase B: identical run, rank 1 SIGKILLed at iteration kill_at
    dir_b = os.path.join(workdir, "b_killed")
    os.makedirs(dir_b, exist_ok=True)
    spec_b = dict(base)
    spec_b.update(out=os.path.join(dir_b, "result.json"),
                  checkpoint_dir=os.path.join(dir_b, "ckpt"))
    p_b = os.path.join(dir_b, "spec.json")
    _write_json(p_b, spec_b)
    t_b = time.monotonic()
    procs = launch_workers(p_b, 2, log_dir=dir_b, fault_rank=1,
                           faults=f"train.iter@{kill_at}:kill")
    codes_b = wait_workers(procs, timeout_s / 2)
    wall_b = time.monotonic() - t_b
    # rank 1 dies by SIGKILL; rank 0 must exit EXIT_PEER_LOST, fast
    if codes_b[1] != -9:
        raise RuntimeError(f"drill phase B: victim rank 1 exited "
                           f"rc={codes_b[1]}, expected SIGKILL (-9)\n"
                           f"{_worker_tails(dir_b, 2)}")
    if codes_b[0] != cluster.EXIT_PEER_LOST:
        raise RuntimeError(f"drill phase B: survivor rank 0 exited "
                           f"rc={codes_b[0]}, expected EXIT_PEER_LOST "
                           f"({cluster.EXIT_PEER_LOST})\n"
                           f"{_worker_tails(dir_b, 2)}")
    surv = _read_json(spec_b["out"] + ".rank0")
    if not surv.get("peer_lost") or 1 not in surv.get("dead_ranks", []):
        raise RuntimeError(f"drill phase B: survivor report does not "
                           f"name rank 1: {surv}")
    # the distributed incident: the survivor assembled one on its way
    # out (every rank's flight recorder dumped into the shared dir_b);
    # re-sweep now that BOTH processes have exited — the victim's
    # pre-kill bundle can hit the disk after the survivor's sweep
    from ..obs import incident as obs_incident
    incident_path = surv.get("incident") or os.path.join(
        dir_b, "incident_peer_lost.json")
    inc_doc = None
    if os.path.exists(incident_path):
        inc_doc = obs_incident.resweep(incident_path, dir_b)
    if inc_doc is None:
        incident_path = obs_incident.write_incident(
            "drill_peer_lost", dir_b, dead_ranks=[1],
            context={"kill_iteration": kill_at})
        inc_doc = (obs_incident.load_incident(incident_path)
                   if incident_path else None)

    # phase C: resume the survivor onto a ONE-process mesh
    dir_c = os.path.join(workdir, "c_resumed")
    os.makedirs(dir_c, exist_ok=True)
    spec_c = dict(base)
    spec_c.update(out=os.path.join(dir_c, "result.json"),
                  model_out=os.path.join(dir_c, "model.txt"),
                  checkpoint_dir=os.path.join(dir_c, "ckpt"),
                  resume_from=spec_b["checkpoint_dir"])
    p_c = os.path.join(dir_c, "spec.json")
    _write_json(p_c, spec_c)
    t_c = time.monotonic()
    codes_c = wait_workers(launch_workers(p_c, 1, log_dir=dir_c),
                           timeout_s / 2)
    if any(codes_c):
        raise RuntimeError(f"drill phase C (resume) failed: "
                           f"rc={codes_c}\n{_worker_tails(dir_c, 1)}")
    res_c = _read_json(spec_c["out"])
    wall_c = time.monotonic() - t_c

    from ..utils import checkpoint as ckpt_mod
    entries = ckpt_mod.list_checkpoints(spec_b["checkpoint_dir"])
    resumed_from = entries[0][0] if entries else None

    with open(spec_a["model_out"]) as fh:
        model_a = fh.read()
    with open(spec_c["model_out"]) as fh:
        model_c = fh.read()
    parity = _strip_volatile(model_a) == _strip_volatile(model_c)

    return {
        "cluster_obs": _cluster_obs_section(
            os.path.join(dir_a, "metrics.r0.jsonl"), world=2),
        "incident": _incident_section(incident_path, inc_doc),
        "schema": "lightgbm-tpu/multichip-drill",
        "version": 1,
        "drill": "elastic_resume",
        "workload": {"n": n, "f": DRILL_F, "seed": seed,
                     "iterations": iterations,
                     "params": dict(DRILL_PARAMS)},
        "world_sizes": {"train": 2, "resume": 1},
        "kill": {"rank": 1, "iteration": kill_at,
                 "survivor_exit_code": codes_b[0],
                 "survivor_error": surv.get("error", ""),
                 "survivor_named_ranks": surv.get("dead_ranks", [])},
        "resume": {"from_iteration": resumed_from,
                   "total_iterations": res_c["iterations"],
                   "collective_timeout_s": collective_timeout_s},
        "per_host_ingest_rows": [r.get("ingest_rows_local")
                                 for r in ranks_a],
        "model_parity": parity,
        "parity_kind": "bit_identical",
        "train_auc": res_a.get("train_auc"),
        "resumed_auc": res_c.get("train_auc"),
        "wall_s": {"uninterrupted": round(wall_a, 2),
                   "killed": round(wall_b, 2),
                   "resumed": round(wall_c, 2)},
    }


def _cluster_obs_section(jsonl_path: str, world: int) -> Optional[Dict]:
    """The final cluster/* rollup out of rank 0's JSONL export, shaped
    for the MULTICHIP artifact (tools/check_bench_regression.py
    validates the shape; it never perf-gates these numbers). None when
    the export is absent/unparseable — a missing rollup is a note, not
    a drill failure."""
    last = None
    try:
        with open(jsonl_path) as fh:
            for ln in fh:
                ln = ln.strip()
                if ln:
                    last = json.loads(ln)
    except (OSError, ValueError):
        return None
    if not isinstance(last, dict):
        return None
    counters = last.get("counters") or {}
    gauges = last.get("gauges") or {}
    if not any(k.startswith("cluster/") for k in counters):
        return None
    return {
        "export": jsonl_path,
        "world": gauges.get("cluster/world"),
        "ranks_reporting": gauges.get("cluster/ranks_reporting"),
        "counters": {k: v for k, v in counters.items()
                     if k.startswith("cluster/")},
        "per_rank_iter_wall_mean_s": {
            k.rsplit("/r", 1)[1]: v for k, v in gauges.items()
            if k.startswith("cluster/iter_wall_mean_s/r")},
        "straggler": {
            "psum_stall_max_rank":
                gauges.get("cluster/psum_stall_max_rank"),
            "slowest_iter_rank":
                gauges.get("cluster/slowest_iter_rank")},
    }


def _incident_section(path: Optional[str],
                      doc: Optional[Dict]) -> Optional[Dict]:
    """The incident bundle summarized for the MULTICHIP artifact —
    the full document stays on disk; the artifact carries what the
    gate checks (who died, whose evidence made it in)."""
    if not path or not isinstance(doc, dict):
        return None
    return {
        "path": path,
        "schema": doc.get("schema"),
        "version": doc.get("version"),
        "dead_ranks": doc.get("dead_ranks", []),
        "ranks_with_dumps": doc.get("ranks_with_dumps", []),
        "digest_ranks": sorted(int(k) for k in
                               (doc.get("digests") or {})),
    }


def _strip_volatile(model_text: str) -> str:
    """Model text minus the serialized ``parameters:`` block — the
    parity bar covers every TREE byte and the feature metadata; the
    parameters block embeds volatile run-artifact paths
    (tpu_checkpoint_dir differs between drill phases by construction,
    exactly like checkpoint.VOLATILE_KNOBS excludes them from the
    resume fingerprint)."""
    lo = model_text.find("\nparameters:")
    hi = model_text.find("end of parameters")
    if lo < 0 or hi < 0:
        return model_text
    return model_text[:lo] + model_text[hi:]


# -- elastic autoscale --------------------------------------------------------


def train_autoscale(workdir: str, *, n: int = DRILL_N, f: int = DRILL_F,
                    iterations: int = 12, window: int = 4,
                    start_world: int = 2, seed: int = 0,
                    schedule: Optional[Dict[int, int]] = None,
                    extra_params: Optional[Dict] = None) -> Dict:
    """The elastic autoscale controller: train in LRB window segments
    and consult the scale signal (cluster.poll_scale_signal — a pod
    scheduler's preemption notice or load target) at every window
    boundary. On a world change the controller relies on the
    checkpoints already on disk (the controller trains with
    tpu_checkpoint_freq=1), tears down the segment's booster, and
    resumes onto the NEW world size WITHOUT leaving the process: the
    PR-15 restore path (mappers_from_bundle injection + resume_from)
    turns the re-shard into a data-plane event instead of a job
    restart. World sizes here are the ``num_machines`` virtual-mesh
    cap (the in-process stand-in for real rank counts — a VOLATILE
    knob, utils/checkpoint.py, so the fingerprint admits the resume);
    the maneuver preserves the model bit-for-bit because the
    quantized tier's histograms are mesh-size invariant
    (tests/test_multichip.py).

    ``schedule`` maps a boundary iteration to a target world; entries
    are POSTED through cluster.post_scale_signal when that boundary is
    reached, standing in for the external scheduler — the controller
    itself only ever READS the signal.

    Returns {model_text, worlds, reshards, iterations}.
    """
    from ..config import Config
    from ..io.dataset import Metadata, TpuDataset
    from ..metrics import create_metrics
    from ..models.gbdt import GBDT
    from ..objectives import create_objective
    from ..obs import registry as obs
    from ..utils import checkpoint as ckpt

    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ckpt")
    X, y = _synth_data({"seed": seed, "n": n, "f": f})

    world = max(int(start_world), 1)
    worlds = [world]
    reshards = 0
    done = 0
    model_text = ""
    while done < iterations:
        if schedule and done in schedule:
            cluster.post_scale_signal(int(schedule[done]))
        target = cluster.poll_scale_signal()
        if target is not None:
            cluster.clear_scale_signal()
            if target != world:
                if done > 0:
                    reshards += 1
                    obs.counter("elastic/reshard_total").add(1)
                    # instant on the trace timeline (the restore path
                    # bumps the identity incarnation when it actually
                    # re-shards the score buffers, utils/checkpoint.py)
                    from ..obs import trace as obs_trace
                    obs_trace.instant(
                        "elastic/reshard", cat="cluster",
                        args={"from_world": world, "to_world": target,
                              "iteration": done})
                    log.info("elastic autoscale: re-sharding world "
                             "%d -> %d at iteration %d (resume from "
                             "%s)", world, target, done, ckpt_dir)
                world = target
                worlds.append(world)
        end = min(done + window, iterations)
        params = dict(DRILL_PARAMS)
        params.update(extra_params or {})
        params.update(
            num_machines=world,
            num_iterations=end,
            tpu_checkpoint_dir=ckpt_dir,
            tpu_checkpoint_freq=1)
        cfg = Config().set(params)
        inject = None
        resume = ""
        if done > 0:
            resume = ckpt_dir
            bundle = ckpt.resolve_resume(ckpt_dir)
            inject = ckpt.mappers_from_bundle(bundle)
        ds = TpuDataset(cfg).construct_from_matrix(
            X, Metadata(label=y), mappers=inject)
        obj = create_objective(cfg.objective, cfg)
        obj.init(ds.metadata, ds.num_data)
        mets = create_metrics(["auc"], cfg, ds.metadata, ds.num_data)
        g = GBDT()
        g.init(cfg, ds, obj, mets)
        g.train(resume_from=resume)
        got = int(g.current_iteration)
        g._ensure_host_trees()
        model_text = g.model_to_string()
        if got <= done:     # early stop / no progress: don't spin
            done = iterations
            break
        done = got
    return {"model_text": model_text, "worlds": worlds,
            "reshards": reshards, "iterations": done}


def run_autoscale_drill(workdir: str, *, n: int = DRILL_N,
                        iterations: int = 12, window: int = 4,
                        worlds=(2, 4, 2), seed: int = 0,
                        extra_params: Optional[Dict] = None) -> Dict:
    """The grow-then-shrink proof: one uninterrupted run at
    ``worlds[0]`` vs one autoscaled run that re-shards through every
    world in ``worlds`` at successive window boundaries — final models
    must match bit-for-bit (minus the volatile parameters block).
    Returns the ``autoscale`` section of the MULTICHIP scaling
    artifact; the artifact gate (tools/check_bench_regression.py) —
    not an exception — is the parity arbiter."""
    os.makedirs(workdir, exist_ok=True)
    schedule = {window * (i + 1): int(w)
                for i, w in enumerate(worlds[1:])}
    cluster.clear_scale_signal()
    try:
        base = train_autoscale(
            os.path.join(workdir, "baseline"), n=n,
            iterations=iterations, window=iterations,
            start_world=worlds[0], seed=seed,
            extra_params=extra_params)
        el = train_autoscale(
            os.path.join(workdir, "elastic"), n=n,
            iterations=iterations, window=window,
            start_world=worlds[0], seed=seed, schedule=schedule,
            extra_params=extra_params)
    finally:
        cluster.clear_scale_signal()
    parity = (_strip_volatile(base["model_text"])
              == _strip_volatile(el["model_text"]))
    return {
        "drill": "autoscale_grow_shrink",
        "worlds": el["worlds"],
        "window": window,
        "iterations": iterations,
        "reshard_total": el["reshards"],
        "model_parity": parity,
        "parity_kind": "bit_identical",
    }


def run_scaling_bench(workdir: str, *, world_sizes=(1, 2, 4),
                      n: int = DRILL_N, iterations: int = 8,
                      seed: int = 0,
                      extra_params: Optional[Dict] = None,
                      timeout_s: float = 900.0) -> List[Dict]:
    """The measured scaling curve: train the identical workload at
    each world size over REAL processes (launch_workers), collecting
    throughput, per-iteration DCN bytes, the measured psum stall and
    the checkpoint seconds hidden by the background writer. Model
    texts (minus the volatile parameters block — world size and
    artifact paths differ by construction) must agree across every
    point; each point carries the stripped-text sha so the artifact
    gate can arbitrate."""
    points = []
    for w in world_sizes:
        wd = os.path.join(workdir, f"w{w}")
        os.makedirs(wd, exist_ok=True)
        spec = {
            "seed": seed, "n": n, "f": DRILL_F,
            "shared_binning": True,
            "params": {**(extra_params or {}),
                       "num_iterations": iterations},
            "checkpoint_dir": os.path.join(wd, "ckpt"),
            "out": os.path.join(wd, "result.json"),
            "model_out": os.path.join(wd, "model.txt"),
        }
        spec_path = os.path.join(wd, "spec.json")
        _write_json(spec_path, spec)
        t0 = time.monotonic()
        codes = wait_workers(launch_workers(spec_path, w, log_dir=wd),
                             timeout_s)
        wall = time.monotonic() - t0
        if any(codes):
            raise RuntimeError(
                f"scaling bench world={w} failed: rc={codes}\n"
                f"{_worker_tails(wd, w)}")
        res = _read_json(spec["out"])
        with open(spec["model_out"]) as fh:
            sha = hashlib.sha256(
                _strip_volatile(fh.read()).encode()).hexdigest()
        train_wall = float(res.get("wall_s") or wall)
        points.append({
            "world": w,
            "wall_s": train_wall,
            "launch_wall_s": round(wall, 2),
            "throughput_rows_per_s": round(
                n * iterations / max(train_wall, 1e-9), 1),
            "comm_bytes_per_iter": res.get("comm_bytes_per_iter"),
            "psum_stall_s": res.get("psum_stall_s"),
            "ckpt_hidden_s": res.get("ckpt_hidden_s"),
            "wire": res.get("wire"),
            "psum_slots": res.get("psum_slots"),
            "model_sha": sha,
        })
    return points


def run_scaling_artifact(workdir: str, *, world_sizes=(1, 2, 4),
                         n: int = DRILL_N, iterations: int = 8,
                         autoscale_window: int = 4,
                         seed: int = 0,
                         extra_params: Optional[Dict] = None) -> Dict:
    """Assemble the full MULTICHIP scaling artifact
    (schema lightgbm-tpu/multichip-scaling): the measured curve over
    real process worlds plus the in-process grow-then-shrink autoscale
    drill. This is what generates ``benchmarks/MULTICHIP_rNN.json``."""
    points = run_scaling_bench(
        os.path.join(workdir, "curve"), world_sizes=world_sizes, n=n,
        iterations=iterations, seed=seed, extra_params=extra_params)
    auto = run_autoscale_drill(
        os.path.join(workdir, "autoscale"), n=n,
        iterations=max(iterations, 3 * autoscale_window),
        window=autoscale_window, seed=seed,
        extra_params=extra_params)
    shas = {p["model_sha"] for p in points}
    hidden = [p["ckpt_hidden_s"] for p in points
              if p.get("ckpt_hidden_s")]
    return {
        "schema": "lightgbm-tpu/multichip-scaling",
        "version": 1,
        "workload": {"n": n, "f": DRILL_F, "seed": seed,
                     "iterations": iterations,
                     "params": {**DRILL_PARAMS,
                                **(extra_params or {})}},
        "points": points,
        "model_parity": len(shas) == 1,
        "parity_kind": "bit_identical",
        "checkpoint": {"hidden_s": (round(max(hidden), 4)
                                    if hidden else None)},
        "autoscale": auto,
    }


if __name__ == "__main__":
    sys.exit(worker_main())
